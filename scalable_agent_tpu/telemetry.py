"""Fleet-wide telemetry plane (round 13): unified metrics registry,
per-unroll trace spans, and the incident flight recorder.

Nine PRs in, the stack could say how FAST each plane runs (fps meters,
per-lane counters, bench rows) but not WHERE a single unroll spends its
time or what the behaviour-vs-target policy-lag distribution — the
quantity V-trace actually corrects for (IMPALA, arXiv 1802.01561) —
looks like under load. Podracer (arXiv 2104.06272) makes the same
point for pods: the scheduling story is only as good as the cross-host
telemetry behind it. This module is that layer, in three pieces:

1. **Metrics registry** — `Counter` / `Gauge` / `Histogram` objects
   that every component registers into ONE process-wide
   `MetricsRegistry` instead of keeping module-local ints with
   per-module reporting paths. `snapshot()` is the single source of
   truth the driver's drain manifest, the health halt bundle, the
   flight recorder, and the remote `stats` control-lane request all
   read. Registration is by NAME with latest-wins replacement: a
   per-run component (an ingest server, a health monitor) re-registers
   its metrics on construction and the snapshot always reflects the
   live incarnation. EVERY registration in scalable_agent_tpu/ must
   use the literal-string module helpers
   (`telemetry.counter('<component>/<name>')`, same for gauge /
   histogram) —
   scripts/ci.sh lints that each registered name appears in
   docs/OBSERVABILITY.md's inventory (and that no documented name is
   orphaned), which only works because the names are greppable
   literals.

2. **Trace spans** — a compact per-unroll trace context (actor id,
   per-actor sequence number, session epoch, behaviour params version)
   stamped with wall-clock hop timestamps as the unroll moves through
   the pipeline: env-step completion → actor send → wire receipt →
   ingest validate/commit → staging → learner serve → train step. The
   context rides the unroll's wire frame on the remote lanes
   (protocol v8, negotiated at hello — older peers simply don't
   stamp) and a bounded identity-keyed sidecar (`tag_unroll` /
   `pop_unroll`) inside a process, because trajectory pytrees cannot
   carry extra leaves without breaking the wire contract. The
   learner-side `PipelineTracer` assembles completed spans into
   `traces.jsonl` — one line per trained batch, carrying every
   member unroll's hop list and the batch's policy-lag vector
   (published version at train time minus each unroll's behaviour
   version). `scripts/trace_report.py` reconstructs per-hop latency
   and the lag distribution from this stream.

   Hop timestamps are `time.time()` (wall clock), not monotonic:
   spans cross process (and host) boundaries, where monotonic clocks
   do not compare. Within a host the deltas are exact; across hosts
   they carry NTP skew — docs/OBSERVABILITY.md documents the caveat.

3. **Flight recorder** — a bounded in-memory ring of the most recent
   trace records plus periodic registry snapshots. A halt or rollback
   then ships the last N seconds of pipeline history (what was the
   lag doing? did installs stall?) with the diagnostic bundle instead
   of a point-in-time counter dump (health.write_halt_bundle /
   driver.train's rollback incident path).

Costs are measured, not assumed: bench.py's `telemetry` stage runs
the feed pipeline with tracing on vs off and the always-on default is
an accept/reject call recorded in docs/PERF.md.

No jax imports here — actor hosts and test helpers use this module
before (or without) jax initialization.
"""

import collections
import json
import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

NAN = float('nan')


# --------------------------------------------------------------------
# Metrics registry.
# --------------------------------------------------------------------


class Counter:
  """Monotone (well, add-only) counter. Thread-safe."""

  def __init__(self, name: str):
    self.name = name
    self._value = 0
    self._lock = threading.Lock()

  def inc(self, n: int = 1):
    with self._lock:
      self._value += n

  @property
  def value(self):
    with self._lock:
      return self._value

  def snapshot_value(self):
    return self.value


class Gauge:
  """Point-in-time value: either `set()` by its owner, or backed by a
  zero-argument callable (`fn=`) read lazily at snapshot time — the
  adoption path for existing stats surfaces (a component registers
  `telemetry.gauge('<component>/<name>', fn=lambda: self._n)` and its
  module-local bookkeeping becomes registry-visible without rewriting
  the bookkeeping). A callback that raises reads as NaN: a torn-down
  component must never break the snapshot that is trying to describe
  the teardown."""

  def __init__(self, name: str, fn: Optional[Callable] = None):
    self.name = name
    self._fn = fn
    self._value = 0.0
    self._lock = threading.Lock()

  def set(self, value):
    with self._lock:
      self._value = value

  @property
  def value(self):
    if self._fn is not None:
      try:
        return self._fn()
      except Exception:
        return NAN
    with self._lock:
      return self._value

  def snapshot_value(self):
    return self.value


class Histogram:
  """Bounded-reservoir histogram: cumulative count/sum plus sample
  percentiles over the most recent `maxlen` observations (the
  LatencyReservoir design, promoted to a registry citizen). Empty →
  NaN percentiles — reports render '-', nothing crashes."""

  def __init__(self, name: str, maxlen: int = 4096):
    self.name = name
    self._samples = collections.deque(maxlen=maxlen)
    self._lock = threading.Lock()
    self._count = 0
    self._sum = 0.0
    self._max = NAN

  def observe(self, value):
    v = float(value)
    with self._lock:
      self._samples.append(v)
      self._count += 1
      self._sum += v
      self._max = v if math.isnan(self._max) else max(self._max, v)

  @property
  def count(self) -> int:
    with self._lock:
      return self._count

  def percentiles(self, *qs: float) -> Tuple[float, ...]:
    with self._lock:
      snap = sorted(self._samples)
    if not snap:
      return tuple(NAN for _ in qs)
    last = len(snap) - 1
    return tuple(snap[min(last, int(round(q * last)))] for q in qs)

  def snapshot_value(self) -> Dict:
    p50, p99 = self.percentiles(0.5, 0.99)
    with self._lock:
      return {'count': self._count, 'sum': round(self._sum, 6),
              'max': self._max, 'p50': p50, 'p99': p99}


class MetricsRegistry:
  """Name → metric map with a thread-safe `snapshot()`.

  Registration replaces by name (latest instance wins): components are
  per-run objects and the registry is process-global, so the snapshot
  must describe the LIVE incarnation — a test constructing ten ingest
  servers leaves the last one's counters registered, which is exactly
  the production semantics (one live server per process)."""

  def __init__(self):
    self._metrics: Dict[str, object] = {}
    self._lock = threading.Lock()

  def register(self, metric):
    with self._lock:
      self._metrics[metric.name] = metric
    return metric

  def counter(self, name: str) -> Counter:
    return self.register(Counter(name))

  def gauge(self, name: str, fn: Optional[Callable] = None) -> Gauge:
    return self.register(Gauge(name, fn=fn))

  def histogram(self, name: str, maxlen: int = 4096) -> Histogram:
    return self.register(Histogram(name, maxlen=maxlen))

  def get(self, name: str):
    with self._lock:
      return self._metrics.get(name)

  def unregister(self, name: str, metric=None):
    """Remove `name` — but when `metric` is given, only if it is the
    REGISTERED instance (identity check): a closing component must
    not evict a newer incarnation that already replaced it under the
    same name. fn-gauges close over their owner, so unregistering at
    teardown is what lets a finished run's pipeline objects be
    collected instead of pinned by the registry for the process
    lifetime."""
    with self._lock:
      if metric is None or self._metrics.get(name) is metric:
        self._metrics.pop(name, None)

  def names(self) -> List[str]:
    with self._lock:
      return sorted(self._metrics)

  def snapshot(self) -> Dict:
    """One JSON-serializable dict of every registered metric's current
    value (counters/gauges → number, histograms → {count, sum, max,
    p50, p99}). The read is point-in-time per metric, not a global
    atomic cut — consumers (drain manifest, flight recorder, fleet
    stats request) want recency, not transactional consistency."""
    with self._lock:
      metrics = list(self._metrics.values())
    out = {}
    for m in metrics:
      v = m.snapshot_value()
      if isinstance(v, (np.integer, np.floating)):
        v = v.item()
      out[m.name] = v
    return out


# The process-wide default registry. Module helpers below are the ONLY
# registration spellings used inside scalable_agent_tpu/ — the ci.sh
# metric-name lint greps for them.
_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
  return _REGISTRY


def counter(name: str) -> Counter:
  return _REGISTRY.counter(name)


def gauge(name: str, fn: Optional[Callable] = None) -> Gauge:
  return _REGISTRY.gauge(name, fn=fn)


def histogram(name: str, maxlen: int = 4096) -> Histogram:
  return _REGISTRY.histogram(name, maxlen=maxlen)


# --------------------------------------------------------------------
# Trace spans.
# --------------------------------------------------------------------

# Hop names, in pipeline order. Spans may omit hops (a local-fleet
# unroll never crosses the wire; an old-protocol peer stamps nothing) —
# scripts/trace_report.py computes deltas between the hops that ARE
# present, in this order.
HOP_DONE = 'done'        # env-step loop completed the unroll (actor)
HOP_SEND = 'send'        # remote pump handed it to the socket
HOP_WIRE = 'wire'        # ingest reader finished receiving the frame
HOP_COMMIT = 'commit'    # validate/commit worker landed the buffer put
HOP_STAGED = 'staged'    # batch assembly picked it (host stack or
                         # per-unroll device staging)
HOP_SERVE = 'serve'      # the learner's get() took the staged batch
HOP_STEP = 'step'        # the train step consuming it was dispatched
HOP_ORDER = (HOP_DONE, HOP_SEND, HOP_WIRE, HOP_COMMIT, HOP_STAGED,
             HOP_SERVE, HOP_STEP)


def make_trace(actor, seq: int, epoch=None,
               behavior_version=None) -> Dict:
  """A fresh per-unroll trace context. Compact keys on purpose — this
  dict rides every v8 unroll frame: 'a' actor id, 's' per-actor unroll
  sequence, 'e' session epoch (the learner incarnation the actor
  believes it feeds), 'bv' the params version the actor ACTED with
  (the behaviour policy — policy lag is published-at-train minus
  this), 'h' the [hop, wall_time] stamp list."""
  trace = {'a': str(actor), 's': int(seq), 'h': []}
  if epoch is not None:
    trace['e'] = int(epoch)
  if behavior_version is not None:
    trace['bv'] = int(behavior_version)
  return trace


def stamp(trace: Optional[Dict], hop: str, t: Optional[float] = None):
  """Append one [hop, wall_time] stamp. None-tolerant (call sites
  stay unconditional on untraced old-peer unrolls) AND shape-tolerant:
  a malformed context from a buggy/skewed peer — a dict missing 'h',
  or carrying a non-list there — gets a fresh stamp list instead of
  raising into whoever stamps it (the ingest READER stamps wire
  frames; a KeyError there would drop the connection outside the
  quarantine accounting every other malformed-frame path gets)."""
  if trace is None:
    return trace
  hops = trace.get('h')
  if not isinstance(hops, list):
    hops = trace['h'] = []
  hops.append([hop, round(time.time() if t is None else t, 6)])
  return trace


class _TagStore:
  """Bounded identity-keyed sidecar: unroll pytree → trace context.

  Trajectory pytrees cannot carry extra leaves (the wire contract and
  the learner's tree_flatten would both see them), so inside a process
  the trace context travels NEXT TO the unroll, keyed by `id()`. The
  store holds NO reference to the unroll itself — a tagged unroll
  that never reaches consumption (a drain drop, a fleet-stop discard)
  must cost a stale ~200-byte trace entry, not a multi-MB pytree
  pinned for the rest of the run (the soak's slow-leak shape). The
  id-only key admits one benign hazard: a freed unroll's id can be
  reused, and a LATER untraced object at the same address could pop
  the stale trace — a mislabeled span in the telemetry stream, never
  a correctness issue (and a re-tag at the same address simply
  overwrites the stale entry). Bounded: oldest entries evicted,
  counted."""

  def __init__(self, capacity: int = 8192):
    self._capacity = capacity
    self._entries: 'collections.OrderedDict' = collections.OrderedDict()
    self._lock = threading.Lock()
    self.evicted = 0

  def tag(self, obj, trace: Dict):
    with self._lock:
      self._entries[id(obj)] = trace
      while len(self._entries) > self._capacity:
        self._entries.popitem(last=False)
        self.evicted += 1

  def pop(self, obj) -> Optional[Dict]:
    with self._lock:
      return self._entries.pop(id(obj), None)

  def __len__(self):
    with self._lock:
      return len(self._entries)


_UNROLL_TAGS = _TagStore()


def tag_unroll(unroll, trace: Optional[Dict]):
  if trace is not None:
    _UNROLL_TAGS.tag(unroll, trace)


def pop_unroll(unroll) -> Optional[Dict]:
  return _UNROLL_TAGS.pop(unroll)


# --- Actor-side stamping switch. The learner process enables it by
# installing a PipelineTracer (set_tracer); a REMOTE actor host — which
# has no tracer, its spans complete learner-side — enables it
# explicitly with configure_actor_tracing. `version_fn` supplies the
# behaviour params version stamped on each fresh trace (a mutable-cell
# closure at both call sites: reading a stats surface per unroll would
# put a lock on the env loop). ---
_actor_tracing_lock = threading.Lock()
_actor_tracing: Optional[Dict] = None


def configure_actor_tracing(version_fn: Optional[Callable] = None,
                            epoch=None):
  global _actor_tracing
  with _actor_tracing_lock:
    _actor_tracing = {'version_fn': version_fn, 'epoch': epoch}


def clear_actor_tracing():
  global _actor_tracing
  with _actor_tracing_lock:
    _actor_tracing = None


def begin_unroll_trace(actor, seq: int) -> Optional[Dict]:
  """A fresh trace for one just-completed unroll, or None when
  tracing is off in this process (the actor loop's one-line seam)."""
  with _actor_tracing_lock:
    cfg = _actor_tracing
  if cfg is None:
    tracer = get_tracer()
    if tracer is None:
      return None
    cfg = {'version_fn': tracer.behavior_version,
           'epoch': tracer.epoch}
  version = None
  if cfg.get('version_fn') is not None:
    try:
      version = cfg['version_fn']()
    except Exception:
      version = None
  return make_trace(actor, seq, epoch=cfg.get('epoch'),
                    behavior_version=version)


# --------------------------------------------------------------------
# Flight recorder.
# --------------------------------------------------------------------


class FlightRecorder:
  """Bounded ring of recent telemetry: the last `capacity` trace
  records (batches, publishes, installs) plus the last `snapshots`
  registry snapshots — dumped into the health halt bundle and the
  rollback diagnostics so an incident ships the preceding pipeline
  history, not a point-in-time counter read. Thread-safe."""

  def __init__(self, capacity: int = 512, snapshots: int = 16):
    self._records = collections.deque(maxlen=max(capacity, 8))
    self._snapshots = collections.deque(maxlen=max(snapshots, 2))
    self._lock = threading.Lock()

  def record(self, rec: Dict):
    with self._lock:
      self._records.append(rec)

  def note_registry(self, snapshot: Dict):
    """Stash one registry snapshot (call on the summary cadence)."""
    with self._lock:
      self._snapshots.append({'wall_time': round(time.time(), 3),
                              'metrics': snapshot})

  def __len__(self) -> int:
    """Trace records currently retained in the ring."""
    with self._lock:
      return len(self._records)

  @property
  def snapshots_held(self) -> int:
    with self._lock:
      return len(self._snapshots)

  def dump(self) -> Dict:
    with self._lock:
      return {'wall_time': round(time.time(), 3),
              'records': list(self._records),
              'registry_snapshots': list(self._snapshots)}

  def write(self, path: str) -> str:
    """Atomic JSON dump (tmp + rename — incident artifacts must be
    complete or absent)."""
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
      json.dump(self.dump(), f, indent=2, default=str)
    os.replace(tmp, path)
    return path


# --------------------------------------------------------------------
# The learner-side pipeline tracer.
# --------------------------------------------------------------------


# Writes that raced (or followed) close() and were dropped, across
# every JSONL appender in the process: the pre-round-13 behavior was
# a ValueError from the closed file object in whatever thread lost
# the race — a respawning actor logging one last episode could take
# its fleet slot down over a log line.
_DROPPED_WRITES = counter('observability/dropped_writes')


def dropped_writes_total() -> int:
  """Process-wide silently-dropped JSONL writes (the driver's summary
  export and the SLO engine's dropped_writes objective both read this
  instead of reaching for the private counter)."""
  return _DROPPED_WRITES.value


class JsonlAppender:
  """THE thread-safe line-buffered append-only JSONL plumbing — one
  implementation behind the scalar summaries, the incident stream
  (observability._JsonlAppender subclasses this; it lives here
  because telemetry must stay importable without the observability
  module's env-suite dependency chain, and observability already
  imports telemetry), and the tracer's traces.jsonl.

  Crash-safety contract: a write AFTER close() is a silent drop,
  counted on `dropped_writes` (+ the process-wide
  'observability/dropped_writes' registry counter) — never a raise
  into the writing thread. `durable=True` flushes + fsyncs before
  returning, so records that must survive a kill -9 (halt/rollback
  incidents) reach the disk instead of dying in the userspace buffer
  with the process."""

  def __init__(self, logdir: str, filename: str):
    os.makedirs(logdir, exist_ok=True)
    self._path = os.path.join(logdir, filename)
    self._file = open(self._path, 'a', buffering=1)
    self._lock = threading.Lock()
    self._closed = False
    self.dropped_writes = 0

  @property
  def path(self):
    return self._path

  def write(self, record: Dict, durable: bool = False,
            **dumps_kwargs):
    with self._lock:
      if self._closed:
        self.dropped_writes += 1
        _DROPPED_WRITES.inc()
        return
      self._file.write(json.dumps(record, **dumps_kwargs) + '\n')
      if durable:
        try:
          self._file.flush()
          os.fsync(self._file.fileno())
        except OSError:
          pass  # best effort: the record is written either way

  def close(self):
    with self._lock:
      if self._closed:
        return
      self._closed = True
      self._file.close()


class PipelineTracer:
  """Assembles per-unroll spans into `traces.jsonl` + the flight ring.

  One per training run, installed process-globally via `set_tracer`
  (the faults_lib.install pattern — threading a tracer through every
  constructor between the driver and the prefetcher would touch ten
  signatures for one optional observer). The staged/served FIFOs
  mirror the BatchPrefetcher's own FIFO semantics: batches are staged
  in order, served in order (re-serves skip `on_serve`), and trained
  in order — so `on_step` always completes the OLDEST served batch.
  Both FIFOs are bounded: a consumer that stops calling on_step (a
  bench loop, a halted learner) must cost dropped trace records, not
  unbounded memory.

  Emitted records (one JSON object per line in traces.jsonl):
    {'k': 'batch', 'step', 'pv' (published version at train time),
     't' (step wall time), 'n_fresh', 'lag' ([pv - bv per unroll with
     a known behaviour version]), 'spans' ([{a, s, e, bv, h}, ...])}
    {'k': 'publish', 'v', 't'}
    {'k': 'install', 'a', 'v', 't' (actor-side install time),
     't_seen' (when the notice reached the learner)}
  """

  def __init__(self, logdir: str, filename: str = 'traces.jsonl',
               flight_capacity: int = 512, epoch=None,
               version_fn: Optional[Callable] = None):
    self._writer = JsonlAppender(logdir, filename)
    self.flight = FlightRecorder(capacity=flight_capacity)
    self.epoch = epoch
    self.version_fn = version_fn
    # The local publish clock: policy lag is a PUBLISH-COUNT delta
    # (the unit V-trace's staleness story is written in), so the
    # tracer counts publishes itself for locally produced unrolls.
    # Remote unrolls arrive with a behaviour version in the ingest
    # lane's OWN publish counter — the ingest worker stamps the
    # commit-time counter value ('cv') into the trace so the delta is
    # computed within one clock; two clocks never mix.
    self._publish_count = 0
    self._lock = threading.Lock()
    self._staged = collections.deque(maxlen=64)
    self._served = collections.deque(maxlen=64)
    # Registry-backed telemetry about the telemetry (meta, but the
    # overhead/coverage questions are real: untagged unrolls mean a
    # peer isn't stamping; dropped batches mean the FIFOs overflowed).
    self._m_batches = counter('trace/batches')
    self._m_unrolls = counter('trace/unrolls')
    self._m_untagged = counter('trace/untagged_unrolls')
    self._m_installs = counter('trace/param_installs')
    self._m_dropped = counter('trace/dropped_records')
    self._h_lag = histogram('trace/policy_lag')
    self._h_e2e = histogram('trace/e2e_ms')
    # Flight-recorder occupancy (round 14): fn-gauges over the ring so
    # the registry snapshot (and the driver's summary export) can say
    # how much incident history a dump would ship. Unregistered at
    # close() — they close over this per-run tracer's flight ring.
    self._flight_gauges = [
        gauge('trace/flight_records', fn=lambda: len(self.flight)),
        gauge('trace/flight_snapshots',
              fn=lambda: self.flight.snapshots_held),
    ]

  @property
  def path(self) -> str:
    return self._writer.path

  @property
  def publish_count(self) -> int:
    return self._publish_count

  def behavior_version(self) -> Optional[int]:
    """The behaviour-policy version a locally produced unroll should
    stamp: the injected version_fn when one is set, else this
    tracer's own publish count (local actors install every publish
    synchronously, so count-at-act-time IS their behaviour version)."""
    if self.version_fn is not None:
      try:
        return self.version_fn()
      except Exception:
        return None
    return self._publish_count

  # --- ingest/commit side ---

  def tag(self, unroll, trace: Optional[Dict]):
    tag_unroll(unroll, trace)

  def on_install(self, actor, version, t_install):
    rec = {'k': 'install', 'a': str(actor), 'v': int(version),
           't': float(t_install), 't_seen': round(time.time(), 6)}
    self._m_installs.inc()
    self._writer.write(rec, default=str)
    self.flight.record(rec)

  # --- feed pipeline side (BatchPrefetcher hooks) ---

  def on_batch(self, unrolls, n_fresh: int):
    """A batch's unrolls were picked for staging (in slot order,
    fresh first). Pops their sidecar tags; replayed slots (consumed
    once already) legitimately have none."""
    now = round(time.time(), 6)
    spans = []
    for u in unrolls[:n_fresh]:
      trace = pop_unroll(u)
      if trace is None:
        self._m_untagged.inc()
      else:
        stamp(trace, HOP_STAGED, now)
        spans.append(trace)
    with self._lock:
      if len(self._staged) == self._staged.maxlen:
        self._m_dropped.inc()
      self._staged.append({'spans': spans, 'n_fresh': int(n_fresh)})

  def on_serve(self):
    """The learner's get() took a batch's FIRST serve (re-serves ride
    the same staged arena and are not new pipeline traversals)."""
    now = round(time.time(), 6)
    with self._lock:
      if not self._staged:
        return
      entry = self._staged.popleft()
      if len(self._served) == self._served.maxlen:
        self._m_dropped.inc()
      self._served.append(entry)
    for trace in entry['spans']:
      stamp(trace, HOP_SERVE, now)

  def on_step(self, step: int):
    """The train step consuming the oldest served batch was
    dispatched: complete its spans, compute the policy-lag vector
    (publish-count delta, each unroll judged within ITS clock — the
    commit-time 'cv' for remote unrolls, this tracer's publish count
    for local ones), emit the batch record."""
    now = round(time.time(), 6)
    with self._lock:
      if not self._served:
        return
      entry = self._served.popleft()
    lags = []
    for trace in entry['spans']:
      stamp(trace, HOP_STEP, now)
      bv = trace.get('bv')
      current = trace.get('cv')
      if current is None:
        current = self._publish_count
      if bv is not None:
        lag = max(int(current) - int(bv), 0)
        lags.append(lag)
        self._h_lag.observe(lag)
      if trace['h']:
        self._h_e2e.observe((trace['h'][-1][1] - trace['h'][0][1])
                            * 1e3)
    self._m_batches.inc()
    self._m_unrolls.inc(len(entry['spans']))
    rec = {'k': 'batch', 'step': int(step), 't': now,
           'pv': self._publish_count,
           'n_fresh': entry['n_fresh'], 'lag': lags,
           'spans': entry['spans']}
    self._writer.write(rec, default=str)
    self.flight.record(rec)

  def on_publish(self, version: int,
                 remote_version: Optional[int] = None):
    """A param publish landed (version is the caller's label — the
    driver publishes step-stamped snapshots); bumps the local publish
    clock the policy-lag arithmetic counts in.

    `remote_version` is the INGEST LANE's version for this snapshot
    when it was also published to the remote fleet — actors' install
    notices carry ingest-lane versions (a different sequence from the
    step-stamped label), so the publish→install join in trace_report
    must key on it ('rv'). Without it, installs at production publish
    cadences would join nothing (or the wrong publish)."""
    self._publish_count += 1
    rec = {'k': 'publish', 'v': int(version),
           'count': self._publish_count, 't': round(time.time(), 6)}
    if remote_version is not None:
      rec['rv'] = int(remote_version)
    self._writer.write(rec, default=str)
    self.flight.record(rec)

  def span_percentiles(self) -> Dict[str, float]:
    """The live policy-lag / end-to-end percentiles (the summary
    export's supported surface — keeps the driver off the tracer's
    internal histogram objects). NaN until traffic flows."""
    lag_p50, lag_p99 = self._h_lag.percentiles(0.5, 0.99)
    e2e_p50, e2e_p99 = self._h_e2e.percentiles(0.5, 0.99)
    return {'policy_lag_p50': lag_p50, 'policy_lag_p99': lag_p99,
            'unroll_e2e_p50_ms': e2e_p50, 'unroll_e2e_p99_ms': e2e_p99}

  def stats(self) -> Dict:
    return {'batches': self._m_batches.value,
            'unrolls': self._m_unrolls.value,
            'untagged_unrolls': self._m_untagged.value,
            'param_installs': self._m_installs.value,
            'dropped_records': self._m_dropped.value,
            'tag_store_size': len(_UNROLL_TAGS),
            'dropped_writes': self._writer.dropped_writes}

  def close(self):
    self._writer.close()
    for g in self._flight_gauges:
      _REGISTRY.unregister(g.name, g)


_tracer_lock = threading.Lock()
_tracer: Optional[PipelineTracer] = None


def set_tracer(tracer: Optional[PipelineTracer]):
  """Install (or clear, with None) the process-global tracer. The
  driver owns the lifecycle: set before the fleet starts, cleared —
  and closed — in its teardown finally."""
  global _tracer
  with _tracer_lock:
    _tracer = tracer


def get_tracer() -> Optional[PipelineTracer]:
  return _tracer
