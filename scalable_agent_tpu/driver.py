"""Experiment driver: wires the whole framework into train/test runs.

TPU-native counterpart of the reference's `train()` / `test()`
orchestration (reference: experiment.py ≈L430–630). The TF1 machinery
maps as:

  FIFOQueue + QueueRunner threads      → TrajectoryBuffer + ActorFleet
  StagingArea GPU prefetch             → BatchPrefetcher (device_put
                                         with data-axis shardings)
  dynamic_batching monkey-patch        → InferenceServer (C++ batcher
                                         in front of a jitted step)
  MonitoredTrainingSession checkpoints → Checkpointer (Orbax)
  tf.summary + manual Summary protos   → SummaryWriter (JSONL) +
                                         EpisodeStats
  gRPC weight fetch by actors          → host param snapshot publish
  PyProcessHook env lifecycle          → factory.build_environment +
                                         fleet-owned processes

`train()` runs until `total_environment_frames` (reference while-loop
≈L585); `evaluate()` restores the latest checkpoint and plays
`test_num_episodes` per level, with DMLab-30 human-normalized scoring
in multi-task mode (reference test() ≈L595–630).
"""

import collections
import dataclasses
import inspect
import json
import logging
import os
import shutil
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import multihost_utils

from scalable_agent_tpu import checkpoint as checkpoint_lib
from scalable_agent_tpu import controller as controller_lib
from scalable_agent_tpu import health as health_lib
from scalable_agent_tpu import learner as learner_lib
from scalable_agent_tpu import observability
from scalable_agent_tpu import population as population_lib
from scalable_agent_tpu import slo as slo_lib
from scalable_agent_tpu import telemetry
from scalable_agent_tpu.analysis import runtime as lock_check
from scalable_agent_tpu.config import (Config, validate_controller,
                                       validate_distributed,
                                       validate_integrity,
                                       validate_population,
                                       validate_replay,
                                       validate_runtime,
                                       validate_serving, validate_slo,
                                       validate_transport)
from scalable_agent_tpu.envs import factory, suites
from scalable_agent_tpu.models import ImpalaAgent, init_params
from scalable_agent_tpu.parallel import mesh as mesh_lib
from scalable_agent_tpu.parallel import sharding as sharding_lib
from scalable_agent_tpu.parallel import train_parallel
from scalable_agent_tpu.runtime import faults as faults_lib
from scalable_agent_tpu.runtime import inference as inference_lib
from scalable_agent_tpu.runtime import ring_buffer
from scalable_agent_tpu.runtime.actor import Actor
from scalable_agent_tpu.runtime.fleet import ActorFleet
from scalable_agent_tpu.runtime.inference import InferenceServer

log = logging.getLogger('scalable_agent_tpu')

# The preemption drain's on-disk handoff: written next to the
# checkpoints at drain time, consumed (renamed) by the resuming run.
RESUME_MANIFEST = 'resume_manifest.json'


def read_resume_manifest(logdir: str) -> Optional[Dict]:
  """The drain manifest of a previous preempted run, or None."""
  path = os.path.join(logdir, RESUME_MANIFEST)
  try:
    with open(path) as f:
      return json.load(f)
  except (OSError, ValueError):
    return None


def _write_resume_manifest(logdir: str, manifest: Dict) -> str:
  """Atomic write (tmp + rename): a manifest is either complete or
  absent — a resume must never act on a half-written one."""
  path = os.path.join(logdir, RESUME_MANIFEST)
  tmp = path + '.tmp'
  with open(tmp, 'w') as f:
    json.dump(manifest, f, indent=2, sort_keys=True)
  os.replace(tmp, path)
  return path


def _stats_only_view(level_name, info, done):
  """ActorOutput carrying ONLY what observability.extract_episodes
  reads ([T+1, B] done/info + [B] level ids) — the single place that
  encodes its input contract for both train() and evaluate()."""
  from scalable_agent_tpu.structs import ActorOutput, StepOutput
  return ActorOutput(
      level_name=level_name,
      agent_state=None,
      env_outputs=StepOutput(reward=None, info=info, done=done,
                             observation=None),
      agent_outputs=None)


def build_agent(config: Config, num_actions: int,
                num_tasks: int = 1) -> ImpalaAgent:
  dtype = (jnp.bfloat16 if config.compute_dtype == 'bfloat16'
           else jnp.float32)
  return ImpalaAgent(num_actions=num_actions, torso=config.torso,
                     use_instruction=config.resolved_use_instruction,
                     num_popart_tasks=(num_tasks if config.use_popart
                                       else 0),
                     use_pixel_control=config.pixel_control_cost > 0,
                     pixel_control_cell_size=config.pixel_control_cell_size,
                     pixel_control_head_impl=config.pixel_control_head_impl,
                     pixel_control_q_f32=config.pixel_control_q_f32,
                     scan_unroll=config.scan_unroll,
                     dtype=dtype)


def make_fleet(config: Config, agent, policy, buffer, levels,
               seed_base: int = 0, level_offset: int = 0,
               is_test: bool = False,
               num_actors: Optional[int] = None,
               initial_state_fn=None) -> ActorFleet:
  """The one env+actor+fleet construction, shared by train(),
  evaluate(), and the remote-actor role (they differ only in seeds,
  level assignment, and fleet size). Actor i plays
  levels[(level_offset + i) % len] with env seed `seed_base + i + 1`.

  Heterogeneous fleets (round 22): when config.fleet_tasks is set AND
  `levels` is exactly its task-name list (train() arranges this), the
  fleet mixes SUITES — actor i's task comes from the weighted
  largest-remainder plan (population.plan_actor_assignment), its env
  spec is built for THAT task's backend, and level_name_id is the
  task index (one PopArt slot + one EpisodeStats curve per task). The
  declared weights are the per-task frame budgets: actors produce at
  the same rate, so actor share == frame share. Callers that pass
  ordinary level lists (evaluate on one backend, remote actors) are
  untouched.

  `initial_state_fn` builds each actor's policy core state, called
  fresh at every (re)spawn — pass the InferenceServer's
  `initial_core_state` so state-cache mode hands each actor a zeroed
  arena slot (a respawned actor must never inherit a stale carry);
  None falls back to the plain numeric zero carry. A factory that
  accepts a `priority` keyword (initial_core_state does) gets the
  admission class: PRIORITY_LIVE for a slot's first spawn,
  PRIORITY_RESPAWN for respawns — so respawn churn under overload
  waits behind live traffic instead of starving it.
  """
  n = config.num_actors if num_actors is None else num_actors
  if initial_state_fn is None:
    initial_state_fn = lambda: agent.initial_state(1)  # noqa: E731
  try:
    accepts_priority = ('priority' in
                        inspect.signature(initial_state_fn).parameters)
  except (TypeError, ValueError):
    accepts_priority = False
  # Spawn count per slot (single-threaded: start() and check_health
  # respawns both run on the learner thread) — first spawn vs respawn
  # picks the admission priority class.
  spawns = collections.Counter()
  task_plan = None
  if config.fleet_tasks:
    tasks = population_lib.parse_fleet_tasks(config.fleet_tasks)
    if [name for name, _ in tasks] == list(levels):
      task_plan = population_lib.plan_actor_assignment(tasks, n)

  def make_actor(i):
    idx = level_offset + i
    if task_plan is not None:
      # Task identity is a function of the SLOT (idx), not the spawn:
      # a respawned actor rejoins its task's frame budget.
      idx = task_plan[idx % len(task_plan)]
    level = levels[idx % len(levels)]
    backend = level if task_plan is not None else None
    spec = factory.make_env_spec(config, level,
                                 seed=seed_base + i + 1,
                                 is_test=is_test, backend=backend)
    env, process = factory.build_environment(
        spec, use_py_process=config.use_py_process)
    # Fault-injection seam (runtime/faults.py): identity unless an
    # installed plan targets env_step.
    env = faults_lib.maybe_wrap_env(env)
    try:
      if accepts_priority:
        priority = (inference_lib.PRIORITY_RESPAWN if spawns[i]
                    else inference_lib.PRIORITY_LIVE)
        state = initial_state_fn(priority=priority)
      else:
        state = initial_state_fn()
    except BaseException:
      # A denied slot admission must not leak the env just built —
      # the fleet retries this spawn later with a FRESH env.
      try:
        if process is not None:
          process.close(timeout=1.0)
        else:
          env.close()
      except Exception:
        pass
      raise
    spawns[i] += 1
    actor = Actor(env, policy, state,
                  unroll_length=config.unroll_length,
                  num_action_repeats=config.num_action_repeats,
                  level_name_id=idx % len(levels))
    return env, process, actor

  return ActorFleet(make_actor, buffer, n,
                    quarantine_after=config.fleet_quarantine_after,
                    probation_secs=config.fleet_probation_secs)


def _choose_eval_mesh():
  """Inference mesh for evaluate(): LOCAL devices only (each host's
  dynamic batcher fires independently — a cross-process mesh would
  need lockstep invocation), pure data axis (inference replicates
  params; a model axis would only do redundant compute). Any
  multi-device host then runs eval inference across all its chips
  instead of leaving (n-1)/n idle (VERDICT r2 W6)."""
  devices = jax.local_devices()
  if len(devices) == 1:
    return None
  return mesh_lib.make_mesh(devices, model_parallelism=1)


def choose_mesh(config: Config):
  """Mesh over all local devices when the batch can shard; None means
  plain single-device jit (the reference's single-machine mode)."""
  devices = jax.devices()
  mp = config.model_parallelism
  if len(devices) == 1 and mp == 1:
    return None
  if mp > len(devices) or len(devices) % mp != 0:
    raise ValueError(
        f'model_parallelism={mp} does not divide the device count '
        f'{len(devices)}')
  # Multi-host TP shards the batch over BOTH mesh axes (see
  # sharding.batch_shardings), so the batch must divide the full
  # device count there; otherwise only the data width.
  if sharding_lib.shard_batch_over_model(config):
    batch_width = len(devices)
  else:
    batch_width = len(devices) // mp
  if config.batch_size % batch_width != 0:
    if jax.process_count() > 1:
      # Multi-host: the fallback would leave every host training an
      # independent, never-synchronized replica against a shared
      # logdir — silently wrong training. Refuse.
      raise ValueError(
          f'batch_size={config.batch_size} not divisible by '
          f'batch-sharding width {batch_width}; single-device '
          'fallback is only safe single-host')
    log.warning('batch_size %d not divisible by batch-sharding width '
                '%d; falling back to single-device training',
                config.batch_size, batch_width)
    return None
  return mesh_lib.make_mesh(devices, model_parallelism=mp)


class TrainRun:
  """All live objects of a training run (for inspection/tests)."""

  def __init__(self, config, agent, state, fleet, prefetcher, server,
               checkpointer, writer, stats, fps_meter, ingest=None,
               health=None):
    self.config = config
    self.agent = agent
    self.state = state
    self.fleet = fleet
    self.prefetcher = prefetcher
    self.server = server
    self.checkpointer = checkpointer
    self.writer = writer
    self.stats = stats
    self.fps_meter = fps_meter
    self.ingest = ingest
    self.health = health  # HealthMonitor (None when watchdog is off)
    self.controller = None  # controller.Controller (round 15), set
                            # by train() when --controller != off
    # Set by train() when sample reuse is on: a closure over the
    # prefetcher's serve-time fresh-slot counter, so `frames` reports
    # FRESH env frames (reuse makes update_steps × frames_per_step an
    # overcount).
    self._env_frames_fn = None

  @property
  def frames(self) -> int:
    if self._env_frames_fn is not None:
      return int(self._env_frames_fn())
    return int(jax.device_get(self.state.update_steps)) * \
        self.config.frames_per_step


def train(config: Config, max_steps: Optional[int] = None,
          stall_timeout_secs: Optional[float] = None,
          max_seconds: Optional[float] = None,
          fleet_factory=None,
          drain_event: Optional[threading.Event] = None) -> TrainRun:
  """Run IMPALA training until total_environment_frames (or max_steps
  / max_seconds — timed smoke and bench runs).

  `fleet_factory(config, agent, policy, buffer, levels)` replaces
  make_fleet when given — bench.py's fed-learner stage injects a
  synthetic producer fleet here so THIS loop (stats extraction,
  publish cadence, summaries, health checks) can be measured at full
  feed rate without env/inference cost (VERDICT r4 #3). Production
  always uses the default.

  `drain_event` is the preemption seam (experiment.py sets it from
  SIGTERM; the 'preempt_signal' fault site fires it deterministically
  for chaos): when set, the loop QUIESCES instead of dying mid-step —
  admissions stop, in-flight unrolls flush through the learner,
  a verified checkpoint lands through the integrity ladder, and
  `resume_manifest.json` (frames / update_steps / param version /
  buffer watermarks) is written next to the summaries; the next
  train() on the same logdir resumes from it. Single-host only: the
  drain checkpoint is not a collective (multi-host preemption keeps
  the periodic-checkpoint story).

  Returns the TrainRun with the final state (all machinery shut down).
  """
  # --- Runtime axis (round 16): --runtime=anakin runs the fused
  # on-device act+learn loop under the SAME lifecycle contract this
  # function provides the fleet (checkpoint ladder, health ladder,
  # SLO verdict, summaries/incidents). One entry point, two operating
  # points — callers never branch. ---
  # --- Multi-process spin-up (round 17): validate the DECLARED
  # topology first (a malformed coordinator or out-of-range
  # process_id must be a crisp ValueError, not a coordinator hanging
  # out its 300 s initialization window waiting for a process that
  # can never come), then join jax.distributed BEFORE the first
  # device op below (the backend is built with cross-process
  # collectives only if the runtime exists first). Launcher-
  # initialized topologies (the test-harness path: config fields
  # default, jax.distributed already up) get the cross-links
  # re-checked against the LIVE process count after the join. ---
  from scalable_agent_tpu.parallel import distributed
  dist_warnings = validate_distributed(config)
  distributed.maybe_initialize(config)
  live_processes = jax.process_count()
  if live_processes > max(config.num_processes, 1):
    dist_warnings = validate_distributed(
        config, live_process_count=live_processes)
  for warning in dist_warnings:
    log.warning('%s', warning)
  # Lock-order detection (round 18, analysis/runtime.py): arm BEFORE
  # any component constructs its locks — make_lock reads the armed
  # state at construction (this covers both runtimes; the anakin
  # dispatch below constructs its own checkpoint/SLO planes).
  # Arm-only (never disarm): tests/chaos arm via the LOCK_ORDER_CHECK
  # env var, and a False flag here must not silently strip their
  # instrumentation.
  if config.lock_order_check:
    lock_check.arm()
  if config.pbt_population >= 2:
    # PBT (round 22): the population loop owns the members' anakin
    # runs end to end — dispatch before any fleet machinery exists
    # (train_population validates the knob group itself, hard errors
    # included: a non-anakin runtime is rejected there).
    if fleet_factory is not None:
      raise ValueError('fleet_factory is a fleet-runtime seam; PBT '
                       'members are fused-loop anakin replicas')
    return train_population(config, max_steps=max_steps,
                            max_seconds=max_seconds,
                            drain_event=drain_event)
  if config.runtime == 'anakin':
    if fleet_factory is not None:
      raise ValueError('fleet_factory is a fleet-runtime seam; '
                       '--runtime=anakin has no fleet')
    return train_anakin(config, max_steps=max_steps,
                        max_seconds=max_seconds,
                        drain_event=drain_event)
  if max_seconds is not None and jax.process_count() > 1:
    # Wall clocks differ per host: a time-based exit is NOT a
    # deterministic function of the shared step count, so hosts would
    # leave the loop at different steps and deadlock the collective
    # final checkpoint (see the finally-block contract below).
    raise ValueError('max_seconds is single-host only; bound multi-host '
                     'runs by max_steps/total_environment_frames')
  levels = factory.level_names(config)
  fleet_tasks = population_lib.parse_fleet_tasks(config.fleet_tasks)
  if fleet_tasks:
    # Heterogeneous fleet (round 22): the task list REPLACES the level
    # list — one PopArt slot and one EpisodeStats curve per TASK, and
    # make_fleet recognizes this exact list and applies the weighted
    # actor plan. One policy head serves every task, so the per-task
    # action widths must agree (validate_population rejects the known
    # conflicts; this catches default-width drift, e.g. bandit's 3 vs
    # gridworld's 4 — pin --num_actions to resolve).
    levels = [name for name, _ in fleet_tasks]
    specs = [factory.make_env_spec(config, name, seed=1, backend=name)
             for name in levels]
    widths = sorted({s.num_actions for s in specs})
    if len(widths) > 1:
      raise ValueError(
          f'fleet_tasks suites disagree on action width {widths}: one '
          'shared policy head needs one width — set --num_actions')
    spec0 = specs[0]
  else:
    spec0 = factory.make_env_spec(config, levels[0], seed=1)
  num_actions = spec0.num_actions
  agent = build_agent(config, num_actions, num_tasks=len(levels))
  params = init_params(agent, jax.random.PRNGKey(config.seed),
                       spec0.obs_spec)
  num_popart_tasks = len(levels) if config.use_popart else 0

  # Multi-host: config.batch_size is GLOBAL; each host's fleet feeds
  # its process-local shard (SURVEY §5.8 — trajectory transport stays
  # host-local; only gradients ride ICI/DCN).
  num_processes = jax.process_count()
  if config.batch_size % num_processes != 0:
    raise ValueError(f'batch_size={config.batch_size} must divide by '
                     f'process count {num_processes}')
  local_batch_size = config.batch_size // num_processes

  if config.use_pallas_vtrace and config.use_associative_scan:
    # Fail before any env/checkpoint spin-up (vtrace re-checks at
    # trace time for library users).
    raise ValueError('use_pallas_vtrace and use_associative_scan are '
                     'mutually exclusive')
  if config.staging_mode not in ('batch', 'unroll'):
    raise ValueError(f'unknown staging_mode {config.staging_mode!r} '
                     '(batch | unroll)')
  # Sample-reuse knob group (round 10): fail on bad ranges before any
  # env/checkpoint spin-up; soft cross-link findings (vtrace-without-
  # anchor, mismatched staleness windows) are logged, not fatal.
  for warning in validate_replay(config):
    log.warning('%s', warning)
  # Transport-liveness knob group (round 11): same contract — hard
  # range errors raise, cross-links (reconnect window shorter than the
  # learner restart budget, heartbeat outside the reaping window) log.
  for warning in validate_transport(config):
    log.warning('%s', warning)
  # Data-plane integrity knob group (round 12): cross-link warnings
  # for a half-enabled integrity plane (SDC without the ladder, remote
  # ingest without wire CRC).
  for warning in validate_integrity(config):
    log.warning('%s', warning)
  # SLO knob group (round 14): hard range errors raise; cross-links
  # (engine without tracing, capture without the watchdog) log.
  for warning in validate_slo(config):
    log.warning('%s', warning)
  # Controller knob group (round 15): hard enum/range errors raise;
  # cross-links (controller without the SLO engine, act-mode replay
  # escalation without the IMPACT anchor) log.
  for warning in validate_controller(config):
    log.warning('%s', warning)
  # Runtime-axis knob group (round 16): a non-jittable filler backend
  # fails here before any env/checkpoint spin-up; cross-links (filler
  # without the IMPACT anchor, filler with the SLO engine off) log.
  for warning in validate_runtime(config):
    log.warning('%s', warning)
  # Serving-plane knob group (round 21): multi-tenant residency,
  # A/B + shadow fractions, routed-inference topology cross-links.
  for warning in validate_serving(config):
    log.warning('%s', warning)
  # Population knob group (round 22): curriculum ranges, mixed-fleet
  # composition, PBT topology — hard errors raise here (before the
  # mesh/fleet spin-up below); cross-links (curriculum on a backend
  # with no level space, multi-suite without PopArt) log.
  for warning in validate_population(config):
    log.warning('%s', warning)
  # NOTE round 8: the fused Pallas V-trace is no longer rejected under
  # a mesh — the sharded step runs it shard_map'ped over the data axis
  # (vtrace.py / ops/vtrace_pallas.sharded_from_importance_weights;
  # parity-gated on the 8-virtual-device mesh in tests/test_parallel).
  mesh = choose_mesh(config)
  # The ONE registry instance every sharding consumer of this run
  # queries (round 19, parallel/sharding.py): state placement, the
  # checkpoint manifest, and the publisher predicate all resolve from
  # the same declared rule set — private copies are a lint violation.
  registry = sharding_lib.from_config(config)
  if mesh is not None:
    from scalable_agent_tpu.testing import make_example_batch
    from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
    h, w, _ = spec0.frame_shape
    example_batch = make_example_batch(
        config.unroll_length + 1, config.batch_size, h, w, num_actions,
        MAX_INSTRUCTION_LEN)
    state = train_parallel.make_sharded_train_state(
        params, config, mesh, enable_tp=config.model_parallelism > 1,
        num_popart_tasks=num_popart_tasks, registry=registry)
    train_step, place_fn = train_parallel.make_sharded_train_step(
        agent, config, mesh, example_batch)
  else:
    state = learner_lib.make_train_state(params, config,
                                         num_popart_tasks)
    train_step = learner_lib.make_train_step(agent, config)
    # ONE tree-level async device_put (the per-leaf
    # device_put(np.asarray(x)) round trip dispatched leaf-at-a-time
    # and re-materialized already-host arrays); default-device
    # placement matches the unroll stager's steady-state slot
    # placement, so batch and unroll staging land identically.
    place_fn = jax.device_put

  # --- Checkpoint restore (reference: MonitoredTrainingSession auto-
  # restore from --logdir, ≈L570). ---
  checkpointer = checkpoint_lib.Checkpointer(
      config.logdir + '/checkpoints',
      save_interval_secs=config.checkpoint_secs,
      verify_digests=config.ckpt_digests,
      registry=registry, mesh=mesh)
  # Elastic restore gate (round 20, elastic membership): when the
  # newest step's sharding manifest records a DIFFERENT mesh than this
  # run's (a 2-process checkpoint under a 4-process restart, or vice
  # versa), route through the registry's explicit resharding path —
  # targets respecified for the LIVE mesh, with the strict layout
  # check refusing cuts the new topology cannot honor — instead of the
  # implicit same-topology pinning. Fixed-topology restores take the
  # unchanged restore_latest path (docs/MIGRATION.md).
  elastic_restore = None
  try:
    topo_delta = (distributed.topology_delta(
        checkpointer.saved_mesh_shape(), mesh)
                  if mesh is not None else None)
    if topo_delta is not None:
      log.warning(
          'cross-topology restore: checkpoint saved on mesh %s, this '
          'run is mesh %s (%d process(es)) — resharding onto registry '
          'targets for the live topology', topo_delta['saved_mesh'],
          topo_delta['live_mesh'], topo_delta['processes'])
      abstract = jax.tree_util.tree_map(
          lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state)
      restored = checkpointer.restore_resharded(abstract, registry,
                                                mesh)
      if restored is not None:
        elastic_restore = topo_delta
    else:
      restored = checkpointer.restore_latest(state)
  except BaseException:
    # A structure-mismatch raise must not leak the manager (its
    # background threads survive a same-process retry).
    checkpointer.close()
    raise
  if restored is not None:
    state = restored
    log.info('restored checkpoint at step %d',
             int(jax.device_get(state.update_steps)))
  # Host-side step/frame mirror: the loop must not device_get the
  # on-device counter every iteration (that would sync the async
  # dispatch pipeline each step).
  _initial_steps = int(jax.device_get(state.update_steps))

  # --- Preemption resume: a drain manifest from a preempted run is
  # the handoff record — validate the restored step against it, then
  # CONSUME it (renamed, process 0) so a later unrelated restart does
  # not re-announce the same preemption. ---
  resume_manifest = read_resume_manifest(config.logdir)
  if resume_manifest is not None:
    manifest_steps = int(resume_manifest.get('update_steps', -1))
    if _initial_steps == manifest_steps:
      log.info('resuming from preemption drain manifest: step %d, '
               '%d frames (drain latency %.2fs, %d unroll(s) were '
               'left in the buffer)', manifest_steps,
               resume_manifest.get('frames', -1),
               resume_manifest.get('drain_latency_secs', -1.0),
               resume_manifest.get('buffer', {}).get(
                   'leftover_unrolls', 0))
    else:
      # The drain's verified checkpoint and the manifest disagree
      # (drain save failed → the ladder restored an older LAST_GOOD).
      # Resume anyway — frames between the checkpoint and the drain
      # point replay, the same at-least-once story as any crash.
      log.warning(
          'resume manifest names step %d but the restored checkpoint '
          'is step %d — resuming from the checkpoint (frames between '
          'them replay)', manifest_steps, _initial_steps)
    if jax.process_index() == 0:
      try:
        os.replace(os.path.join(config.logdir, RESUME_MANIFEST),
                   os.path.join(config.logdir,
                                RESUME_MANIFEST + '.consumed'))
      except OSError:
        log.exception('could not consume the resume manifest')

  # --- SDC sentinel (round 12): per-replica param fingerprints,
  # cross-checked host-side one step delayed. Pure-DP meshes with
  # >= 2 data replicas only — single device has nothing to compare,
  # TP-sharded params legitimately differ per device. ---
  sdc_fp_fn = None
  sdc_replicas = 0
  if (config.sdc_check and config.health_watchdog
      and train_parallel.supports_sdc_check(config, mesh)):
    sdc_fp_fn, sdc_replicas = train_parallel.make_sdc_fingerprint_fn(
        mesh)
    log.info('SDC sentinel armed: param fingerprints cross-checked '
             'across %d data replicas', sdc_replicas)

  # Multi-host TP: state.params are sharded ACROSS processes, so a
  # jit over them (the inference step) is a collective SPMD program —
  # and the batcher's computation thread invokes inference at
  # unsynchronized times per host, which deadlocks in the collective
  # (measured: device_get never returns). Actors must run on a FULL
  # host-local copy instead. process_allgather is itself a
  # collective, so every call site must be on the lockstep path
  # (same step, every host) — which publish_params_every is. The
  # predicate is the registry's (round 19): the publisher codec asks
  # the same sharding authority as the learner.
  localize_actor_params = sharding_lib.needs_host_local_params(
      config, mesh)

  def actor_params(params):
    if localize_actor_params:
      return multihost_utils.process_allgather(params, tiled=True)
    return params

  # Setup from here to the main loop's try/finally can raise (port
  # binds, env construction, 20–40 s inference compiles, fleet.start's
  # make_actor spawning env processes on this thread): the
  # already-listening ingest must not outlive a failed train() — a
  # bound zombie port serving stale v1 params would break retries in
  # the same process — and neither must the inference server (batcher
  # thread + warmed params/executables resident on the chip), the
  # prefetcher thread, a half-started fleet's env processes, or the
  # checkpoint manager's background threads.
  buffer = None
  ingest = None
  server = None
  fleet = None
  prefetcher = None
  writer = None
  incidents = None
  tracer = None
  slo_engine = None
  ctrl = None
  filler = None
  # The remote-publish cadence as a mutable cell (round 15): the loop
  # below reads publish_cadence['secs'] instead of the frozen config
  # field, so the controller's publish_secs actuator can stretch it
  # live (a float store/load is GIL-atomic).
  publish_cadence = {'secs': float(config.remote_publish_secs)}
  try:
    # --- Trajectory buffer + remote ingest, BEFORE inference warmup:
    # remote actor hosts connect and fetch params while this host
    # spends its 20–40 s compiling, instead of timing out against a
    # closed port (reference's learner-hosted shared FIFOQueue that
    # remote actors enqueue into, ≈L470/SURVEY §3.4 — remote unrolls
    # land in the SAME buffer as the local fleet's, so downstream is
    # source-oblivious). ---
    capacity = max(config.queue_capacity_batches * config.batch_size,
                   config.batch_size)
    # Circular replay tier (round 10, IMPACT): retains consumed
    # unrolls behind the FIFO so get_unrolls can compose
    # fresh:replayed batches; staleness is measured in published
    # param-version deltas against the version fed by the publish
    # cadence below (the same unit --max_unroll_staleness gates
    # ingest admission with).
    replay_tier = None
    if config.replay_ratio > 0:
      replay_tier = ring_buffer.ReplayTier(
          config.resolved_replay_capacity,
          max_staleness=config.resolved_replay_max_staleness,
          verify_crc=config.replay_crc)
    buffer = ring_buffer.TrajectoryBuffer(
        capacity, replay=replay_tier, replay_ratio=config.replay_ratio)
    buffer.note_param_version(_initial_steps)
    frames_per_unroll = config.unroll_length * config.num_action_repeats
    # Serve-time fresh-frame accounting is ALSO armed whenever an
    # acting controller could raise replay_k mid-run (round 15): the
    # steps-derived arithmetic would overcount env frames the moment
    # the knob moves, and the serve-time counter is exact at
    # replay_k=1 too. The hybrid filler (round 16) arms it for the
    # same reason from the other side: filler steps are learner
    # updates that consume ZERO fresh env frames, so only the
    # serve-time counter keeps the frame budget / LR clock / fps on
    # the fleet's fresh-frame clock.
    reuse_on = (config.replay_k > 1 or config.replay_ratio > 0
                or config.controller == 'act' or config.anakin_filler)
    # ONE localization for both the ingest snapshot and the inference
    # server, UNCONDITIONALLY before the ingest branch: actor_params
    # is a cross-host collective in multi-host-TP mode, and
    # remote_actor_port legitimately differs per host (mixed
    # topologies enable ingest on some hosts only) — a collective
    # inside that branch would desync the hosts' collective sequences
    # and hang the job at startup.
    initial_pub = actor_params(state.params)
    if config.remote_actor_port:
      from scalable_agent_tpu.runtime import remote
      # device_get of the LOCALIZED copy (a raw device_get of
      # cross-process-sharded params would raise on non-addressable
      # shards; on the plain path this is the ordinary host copy).
      ingest = remote.TrajectoryIngestServer(
          buffer, jax.device_get(initial_pub),
          host=config.remote_actor_bind_host,
          port=config.remote_actor_port,
          contract=remote.trajectory_contract(config, agent,
                                              num_actions),
          wire_dtype=config.resolved_wire_dtype,
          ingest_workers=config.ingest_workers,
          max_unroll_staleness=config.max_unroll_staleness,
          heartbeat_secs=config.remote_heartbeat_secs,
          idle_timeout_secs=config.remote_conn_idle_timeout_secs,
          wire_crc=config.wire_crc,
          trace=config.telemetry_trace)
      log.info('remote-actor ingest listening on port %d '
               '(session epoch %d)', ingest.port, ingest.session_epoch)
    # --- Inference server (weights served host-side to actor
    # threads). Per-process seed offset: params/init use config.seed
    # IDENTICALLY on every host (multi-host device_put asserts
    # equality), while env and action-sampling streams must NOT repeat
    # across hosts. ---
    process_index = jax.process_index()
    process_seed_base = process_index * max(config.num_actors, 1000)
    server = InferenceServer(agent, initial_pub, config,
                             seed=config.seed + 1000 + process_seed_base,
                             fleet_size=config.num_actors)
    # update_params COPIES: the constructor stores its argument by
    # reference, and in the non-localized path that is state.params
    # itself — which the first train step DONATES. Without this copy,
    # actors would run inference on deleted buffers (real on TPU;
    # invisible on CPU tests, where jit ignores donation).
    server.update_params(initial_pub, version=_initial_steps)
    # Pre-compile inference buckets up to the fleet size: a bucket's
    # first appearance otherwise stalls every parked actor for the TPU
    # compile (the reference's TF graph had dynamic batch dims). With
    # no local fleet (remote-ingest-only learners, synthetic
    # fleet_factory benches) nothing calls local inference — skip the
    # 20–40 s compile.
    if config.num_actors > 0:
      server.warmup(spec0.obs_spec, max_size=config.num_actors)
    # v10 routed serving (round 21): the ingest listener answers
    # 'infer' requests with this host's InferenceServer — actor hosts
    # running a ServingRouter spread batches across learner replicas.
    # Attached AFTER warmup so a routed batch never pays first-call
    # compile for the warm buckets.
    if ingest is not None:
      ingest.attach_serving(server.serve_remote)

    if fleet_factory is None:
      fleet = make_fleet(config, agent, server.policy, buffer, levels,
                         seed_base=process_seed_base,
                         initial_state_fn=server.initial_core_state)
    else:
      fleet = fleet_factory(config, agent, server.policy, buffer,
                            levels)

    def stage(host_batch, n_fresh=None):
      """Prefetcher stage: peel off a tiny host-side stats view (done /
      info / level ids / action counts — the batch is host numpy right
      here) BEFORE the device transfer, so the train loop never
      device_gets frames just to read episode stats.

      `n_fresh` (passed by the prefetcher when a replay tier composes
      the batch) bounds the peel to the FRESH columns — replayed slots
      already recorded their episodes/actions on first consumption, so
      peeling them again would double-count env-plane stats."""
      nf = (np.asarray(host_batch.level_name).shape[0]
            if n_fresh is None else n_fresh)
      stats_view = _stats_only_view(
          np.asarray(host_batch.level_name)[:nf],
          jax.tree_util.tree_map(lambda x: np.asarray(x)[:, :nf],
                                 host_batch.env_outputs.info),
          np.asarray(host_batch.env_outputs.done)[:, :nf])
      # Action histogram source (reference build_learner's
      # tf.summary.histogram, ≈L395): bincount of the trained-on
      # actions ([1:] drops the overlap row, like the loss shift).
      action_counts = np.bincount(
          np.asarray(host_batch.agent_outputs.action)[1:, :nf].ravel(),
          minlength=num_actions)
      return stats_view, action_counts, place_fn(host_batch)

    # --- Per-unroll host stats peel + batch finalize: the unroll
    # staging plane's split of stage() — the tiny leaves (done / info
    # / level id / action bincount) peel per unroll while it is host
    # numpy; the frames never come back, and the per-batch host work
    # is a [T+1, B]-of-scalars stack instead of the 67.5 MB frame
    # stack (BENCH_r05 stack_ms 37.5). ---
    def unroll_view(unroll):
      return (
          np.asarray(unroll.level_name),
          jax.tree_util.tree_map(np.asarray, unroll.env_outputs.info),
          np.asarray(unroll.env_outputs.done),
          np.bincount(np.asarray(unroll.agent_outputs.action)[1:],
                      minlength=num_actions))

    def finalize_views(views, batch_device):
      stats_view = _stats_only_view(
          np.stack([v[0] for v in views]),
          jax.tree_util.tree_map(lambda *xs: np.stack(xs, axis=1),
                                 *[v[1] for v in views]),
          np.stack([v[2] for v in views], axis=1))
      action_counts = np.sum([v[3] for v in views], axis=0)
      return stats_view, action_counts, batch_device

    stager = None
    if config.staging_mode == 'unroll':
      if train_parallel.supports_unroll_staging(config, mesh):
        if mesh is None:
          slot_devices, assemble_fn = None, None
        else:
          slot_devices, assemble_fn = train_parallel.make_unroll_assembly(
              config, mesh, example_batch)
        stager = ring_buffer.UnrollBatchStager(
            local_batch_size, slot_devices=slot_devices,
            assemble_fn=assemble_fn, host_view_fn=unroll_view,
            finalize_fn=finalize_views)
      else:
        log.warning(
            'staging_mode=unroll unsupported on this topology '
            '(model-axis batch sharding or local batch %d not '
            'divisible by the local data width) — falling back to '
            'batch staging', local_batch_size)
    _reserve_counts = np.zeros((num_actions,), np.int64)

    def reserve_view(item):
      """Re-serve transform (replay_k > 1): the staged device batch
      rides again untouched; the env-plane view must NOT — a re-serve
      consumes zero new env frames, so its episode stats are None and
      its action counts zero (the loop skips both)."""
      return None, _reserve_counts, item[2]

    prefetcher = ring_buffer.BatchPrefetcher(
        buffer, local_batch_size, place_fn=stage,
        depth=config.staging_depth, stager=stager,
        replay_k=config.replay_k, reserve_fn=reserve_view)

    # Env-frame accounting under sample reuse (round 10): with
    # replay_k > 1 or replay_ratio > 0 a learner step no longer
    # consumes frames_per_step FRESH env frames, so the frame budget,
    # fps meter, TrainRun.frames, and the drain manifest count fresh
    # unroll slots at SERVE time instead — the prefetcher's
    # fresh_slots_served counter, credited at each batch's first
    # serve, so the figure is immune to prefetch lookahead. The
    # pre-resume base is still approximated as steps ×
    # frames_per_step — exact for histories trained without reuse
    # (the counter does not survive the process). With reuse off this
    # stays the old steps-derived arithmetic exactly.
    env_frames_fn = None
    if reuse_on:
      # Per-host counter → global frames (local_batch_size slots per
      # host-local batch; multi-host reuse keeps the same scale-up the
      # steps-derived arithmetic applies).
      hosts_scale = max(config.batch_size // max(local_batch_size, 1),
                        1)
      resumed_frames = _initial_steps * config.frames_per_step

      def env_frames_fn():
        return (resumed_frames +
                prefetcher.fresh_slots_served() *
                frames_per_unroll * hosts_scale)

    # Multi-host: every host logs its OWN fleet's stream; process 0
    # keeps the canonical filename (shared logdirs must not interleave
    # writers).
    summary_name = ('summaries.jsonl' if process_index == 0
                    else f'summaries_p{process_index}.jsonl')
    writer = observability.SummaryWriter(config.logdir,
                                         filename=summary_name)
    # Structured incident stream (observability.EventLog): bad-step
    # bursts, rollbacks, halts, fault injections — what the scalar
    # summaries can't narrate. chaos.py reads this for its SLOs.
    incidents = observability.EventLog(
        config.logdir,
        filename=('incidents.jsonl' if process_index == 0
                  else f'incidents_p{process_index}.jsonl'))
    # Lock-order detections land as DURABLE lock_order_inversion
    # incidents (round 18): a latent ABBA deadlock found by a storm
    # must survive whatever crash follows it. Armed or not, wiring
    # the sink is free; the finally clears it (the bound method keeps
    # this run's incident stream referenced).
    lock_check.set_incident_sink(incidents.event)
    # The elastic restore above predates this stream — announce it
    # here so the topology change is on the incident record, not just
    # in the log (round 20).
    if elastic_restore is not None:
      incidents.event('topology_resharded', step=_initial_steps,
                      **elastic_restore)
    # Telemetry plane (round 13, telemetry.py): the pipeline tracer
    # completes per-unroll trace spans (actor → wire → ingest →
    # staging → serve → step) into traces.jsonl and keeps the flight
    # recorder the halt/rollback diagnostics dump. Installed
    # process-globally BEFORE fleet.start() so the first unroll is
    # already stamped; the finally clears and closes it.
    if config.telemetry_trace:
      tracer = telemetry.PipelineTracer(
          config.logdir,
          filename=('traces.jsonl' if process_index == 0
                    else f'traces_p{process_index}.jsonl'),
          flight_capacity=config.telemetry_flight_len,
          epoch=(ingest.session_epoch if ingest is not None else None))
      telemetry.set_tracer(tracer)
    # Reproducibility: the exact config of every run lives next to its
    # checkpoints/summaries (the reference leaves flags only in shell
    # history).
    if process_index == 0:
      with open(os.path.join(config.logdir, 'config.json'), 'w') as f:
        json.dump(dataclasses.asdict(config), f, indent=2,
                  sort_keys=True)
    stats = observability.EpisodeStats(
        levels,
        benchmark=(config.level_name
                   if config.level_name in suites.SUITES else None),
        writer=writer)
    fps_meter = observability.FpsMeter()
    # Training-health watchdog (health.py): the device-side guard in
    # the train step already skips non-finite updates; this host
    # monitor escalates — skip-and-count → rollback → halt. Verdicts
    # are a deterministic function of the (replicated) step metrics,
    # so multi-host processes reach rollback/halt decisions in
    # lockstep — the rollback restore stays a valid collective.
    health = (health_lib.monitor_from_config(config)
              if config.health_watchdog else None)
    # SLO engine (round 14, slo.py): the declarative-objective judge
    # over the metrics registry. Its thread snapshots the registry on
    # a cadence (the summary block also evaluates, so detection is
    # step-synchronous whenever summaries are frequent), emits
    # structured slo_violation incidents + the slo_violations summary
    # scalar, feeds burns into health's external-incident ledger, and
    # on the first page-severity burn captures its own explanation
    # (flight dump + trace slice now; a bounded jax.profiler capture
    # via the loop below). The finally writes SLO_VERDICT.json —
    # the per-run go/no-go artifact chaos/soak/slo_report consume.
    if config.slo_engine:
      slo_objectives = slo_lib.load_objectives(
          config.slo_spec,
          fast_window_secs=config.slo_fast_window_secs,
          slow_window_secs=config.slo_slow_window_secs)
      # Derived cadence: summary-paced, but ALWAYS at least ~4
      # samples inside the fast burn window — value objectives need
      # min_samples (3) fast-window samples before they can burn, so
      # an interval as long as the window would leave the page
      # objectives structurally unable to fire (validate_slo warns
      # when an EXPLICIT interval does this).
      slo_interval = (config.slo_interval_secs
                      if config.slo_interval_secs > 0 else
                      min(max(float(config.summary_secs), 1.0), 30.0,
                          config.slo_fast_window_secs / 4.0))
      slo_engine = slo_lib.SloEngine(
          slo_objectives, config.logdir, writer=writer,
          incidents=incidents,
          flight=(tracer.flight if tracer is not None else None),
          health=health, capture=config.slo_capture,
          interval_secs=slo_interval,
          baseline=slo_lib.load_baseline(config.slo_fps_baseline))
      slo_engine.start()
    run = TrainRun(config, agent, state, fleet, prefetcher, server,
                   checkpointer, writer, stats, fps_meter,
                   ingest=ingest, health=health)
    run._env_frames_fn = env_frames_fn
    fleet.start()
    # --- Self-healing controller (round 15, controller.py): the
    # verdict-to-actuation half of the control loop. The policy table
    # maps the SLO engine's burning set + margins to bounded moves on
    # the actuators this topology exposes: the prefetcher's replay_k,
    # the inference server's admission mode, the remote publish
    # cadence (the mutable cell below — the loop reads it instead of
    # the frozen config field), and the fleet's elastic target size
    # (grow = unpark/rehabilitate quarantined slots via probation).
    # observe mode evaluates and logs every move without touching
    # anything; the finally writes CONTROLLER_LOG.json either way. ---
    if config.controller != 'off' and slo_engine is not None:
      ctrl_rules = controller_lib.load_rules(config.controller_policy)
      actuators = [
          controller_lib.Actuator(
              'replay_k', kind='int',
              get_fn=lambda: prefetcher.replay_k,
              set_fn=prefetcher.set_replay_k,
              minimum=1,
              maximum=max(config.controller_replay_k_max,
                          config.replay_k)),
          controller_lib.Actuator(
              'admission', kind='enum',
              get_fn=lambda: server.admission,
              set_fn=server.set_admission,
              values=inference_lib.ADMISSION_POLICIES),
      ]
      if ingest is not None:
        actuators.append(controller_lib.Actuator(
            'publish_secs', kind='float',
            get_fn=lambda: publish_cadence['secs'],
            set_fn=lambda v: publish_cadence.__setitem__(
                'secs', float(v)),
            minimum=float(config.remote_publish_secs),
            maximum=max(config.controller_publish_secs_max,
                        float(config.remote_publish_secs))))
      if config.num_actors > 0 and hasattr(fleet, 'set_target_size'):
        actuators.append(controller_lib.Actuator(
            'fleet_size', kind='int',
            get_fn=fleet.target_size,
            set_fn=fleet.set_target_size,
            minimum=1, maximum=config.num_actors))
      # Pod topology actuator (round 20, elastic membership): the
      # pod-level set_target_size. DECLARATIVE — the learner cannot
      # spawn hosts, so a move publishes the desired host count to
      # <logdir>/POD_TARGET.json (atomic replace) for the cluster
      # supervisor (chaos.py's elastic storm; an operator's
      # orchestration in production) to reconcile against. Process 0
      # only, per the per-actuator-ownership rule — one pod, one
      # declared target, exactly like the checkpoint manifests.
      if (ingest is not None and process_index == 0
          and config.pod_max_hosts > 0):
        pod_target = {'hosts': None}  # None = never moved: mirror live

        def _pod_target_get():
          if pod_target['hosts'] is not None:
            return pod_target['hosts']
          return max(ingest.live_hosts(), 1)

        def _pod_target_set(n):
          pod_target['hosts'] = int(n)
          payload = {'target_hosts': int(n),
                     'live_hosts': ingest.live_hosts(),
                     'membership': ingest.membership(),
                     'wall_time': round(time.time(), 3)}
          path = os.path.join(config.logdir, 'POD_TARGET.json')
          tmp = f'{path}.tmp'
          with open(tmp, 'w') as f:
            json.dump(payload, f, indent=2)
          os.replace(tmp, path)

        actuators.append(controller_lib.Actuator(
            'pod_size', kind='int',
            get_fn=_pod_target_get, set_fn=_pod_target_set,
            minimum=1, maximum=config.pod_max_hosts))
      ctrl_interval = (config.controller_interval_secs
                       if config.controller_interval_secs > 0
                       else slo_interval)
      ctrl = controller_lib.Controller(
          slo_engine, ctrl_rules, actuators, config.logdir,
          mode=config.controller, interval_secs=ctrl_interval,
          incidents=incidents, health=health,
          log_name=('CONTROLLER_LOG.json' if process_index == 0
                    else f'CONTROLLER_LOG_p{process_index}.json'))
      run.controller = ctrl
      ctrl.start()
      log.info('controller started in %r mode: %d rule(s) over %d '
               'actuator(s)', config.controller, len(ctrl._rules),
               len(actuators))
    elif config.controller != 'off':
      log.warning('controller=%s ignored: the SLO engine is off and '
                  'the controller has no other input',
                  config.controller)
    # --- Hybrid filler (round 16, anakin.HybridFiller): idle feed
    # slices run ONE bounded Anakin self-play step on the learner
    # chips instead of parking — the loop below consults
    # prefetcher.ready() (the ready-without-dequeue probe) so a
    # staged batch is never delayed by more than one filler step.
    # validate_runtime already rejected non-jittable backends; an
    # unsupported TOPOLOGY (model-axis mesh, indivisible filler
    # batch) degrades to plain parking with a warning like the
    # staging-mode fallback — but a genuinely bad knob combination
    # (e.g. a filler core that cannot honor the main task's
    # action-space width) RAISES here, at spin-up, like every other
    # validate_* error: an explicitly requested feature must never be
    # silently off for the whole run.
    if config.anakin_filler:
      from scalable_agent_tpu.parallel import anakin as anakin_lib
      filler_ok, filler_reason = anakin_lib.supports_filler(config,
                                                            mesh)
      if not filler_ok:
        log.warning('anakin_filler disabled on this topology: %s',
                    filler_reason)
      else:
        filler = anakin_lib.HybridFiller(agent, config, num_actions,
                                         mesh=mesh)
        log.info(
            'hybrid filler armed: %r self-play (B=%d, T=%d) fills '
            'idle learner slices; fresh-frame clocks unchanged',
            filler.backend, filler.stats()['batch_size'],
            filler.stats()['unroll_length'])
  except BaseException:
    # Best-effort bounded teardown, most-critical-first: the ingest
    # port release leads (a second interrupt landing mid-cleanup must
    # not leave the bound zombie port), slow thread joins go last, and
    # one failing step must not skip the rest.
    def _try(fn):
      try:
        fn()
      except Exception:
        log.exception('train() setup-failure cleanup step failed')
    if ingest is not None:
      # Setup failure = crash semantics: remote actors keep their
      # reconnect window for the supervisor's retry (graceful=True
      # would 'bye' them into permanent exit — see the main finally).
      _try(lambda: ingest.close(graceful=False))
    if buffer is not None:
      _try(buffer.close)
    if prefetcher is not None:
      _try(prefetcher.close)
    if server is not None:
      _try(server.close)
    if fleet is not None:
      _try(lambda: fleet.stop(timeout=2.0))
    if writer is not None:
      _try(writer.close)
    if incidents is not None:
      _try(lambda: lock_check.set_incident_sink(None))
      _try(incidents.close)
    if tracer is not None:
      _try(lambda: telemetry.set_tracer(None))
      _try(tracer.close)
    if ctrl is not None:
      _try(ctrl.stop)  # no log finalize: the run never started
    if slo_engine is not None:
      _try(slo_engine.stop)  # no verdict: the run never started
    if filler is not None:
      _try(filler.close)
    _try(checkpointer.close)
    raise

  steps_done = 0
  profiling = False
  # Operator-requested profile window state: `pending` until the
  # window actually starts (DEFERRED past any in-flight SLO capture,
  # never silently skipped), then the captured stop step.
  profile_dir_pending = bool(config.profile_dir)
  profile_stop_step = None
  # SLO-triggered profiler capture in flight: (objective name, the
  # steps_done value at which the bounded trace stops). jax.profiler
  # supports one trace at a time, so this and the config.profile_dir
  # window are mutually exclusive in the loop below.
  slo_profile = None
  errors: List[BaseException] = []
  # Unified-registry view of the loop itself (round 13): the step and
  # frame clocks every other counter is read against. Lazy closures
  # over the loop locals — the registry reads the live values; the
  # finally unregisters them (the env-frames closure reaches the
  # prefetcher, which must not stay registry-pinned after the run).
  _loop_gauges = [
      telemetry.gauge('driver/update_steps',
                      fn=lambda: steps_done + _initial_steps),
      telemetry.gauge(
          'driver/env_frames',
          fn=lambda: (env_frames_fn() if env_frames_fn is not None
                      else (_initial_steps + steps_done) *
                      config.frames_per_step)),
  ]
  # Plane-state gauges (round 14): the summary block's utilization
  # split and fleet quorum, registered into the unified registry so
  # the SLO engine (and the flight recorder / drain manifest) judge
  # the SAME numbers the summaries carry. Created lazily at the first
  # summary interval — a default 0.0 before any measurement would
  # read as a dead plane to the env_plane_utilization objective.
  _plane_gauges: Dict[str, telemetry.Gauge] = {}

  def _set_plane_gauge(name, value):
    gauge = _plane_gauges.get(name)
    if gauge is None:
      # Literal registration names (the ci.sh lint contract).
      if name == 'env':
        gauge = telemetry.gauge('driver/env_plane_utilization')
      elif name == 'learner':
        gauge = telemetry.gauge('driver/learner_plane_utilization')
      elif name == 'hosts':
        gauge = telemetry.gauge('driver/remote_live_hosts')
      else:
        gauge = telemetry.gauge('driver/fleet_healthy_fraction')
      _plane_gauges[name] = gauge
    gauge.set(value)
  # Preemption-drain state: set once the drain is requested (SIGTERM
  # via drain_event, or the deterministic 'preempt_signal' fault);
  # the loop then flushes the already-produced feed instead of
  # breaking mid-pipeline, and the post-loop finalize takes the
  # verified checkpoint + writes the resume manifest.
  draining = False
  drain_t0 = None
  drain_deadline = None
  drain_source = None
  # Watchdog loop state: the stashed (step, SentinelHandle) awaiting
  # its delayed read, and the bad-step count of the current burst
  # (driver-side: the monitor's consecutive counter resets on
  # rollback, so it cannot bracket bursts).
  pending_sentinel = None
  bad_count_in_burst = 0
  # Deferred metrics readback (round 8): (step, stacked-handle) pairs.
  # `pending_metrics` is the step just dispatched; `prev_metrics` is
  # one step older — its values are computed by now, so the summary
  # read is a single non-syncing transfer.
  pending_metrics = None
  prev_metrics = None
  action_counts_acc = np.zeros((num_actions,), np.int64)
  last_publish_step = _initial_steps   # resume-manifest param version
  last_quarantined_slots = 0
  last_remote_publish = float('-inf')
  last_pf_snap = {'gets': 0, 'wait_secs': 0.0}
  # Sample-reuse / plane-utilization snapshot (round 10): per-interval
  # deltas for learner_updates_per_env_frame and the env-vs-learner
  # utilization split.
  last_reuse_snap = {'steps': 0, 'fresh_unrolls': 0,
                     'put_wait_secs': 0.0, 'time': time.monotonic()}
  last_inference_snap = {'calls': 0, 'requests': 0}
  last_ingest_snap = {'unrolls': 0, 'per_conn_unrolls': {}}
  last_ingest_time = time.monotonic()
  loop_start = time.monotonic()
  last_summary = time.monotonic()
  last_batch_time = time.monotonic()
  # Hybrid-filler loop state (round 16): liveness-check gate for the
  # filling regime + the incident edge detector for withheld
  # (non-finite) filler updates.
  last_filler_check = time.monotonic()
  last_filler_skipped = 0
  poll_secs = 10.0 if stall_timeout_secs is None else min(
      10.0, stall_timeout_secs)
  try:
    while True:
      # --- Preemption drain request (SIGTERM via drain_event, or the
      # deterministic 'preempt_signal' fault site): quiesce instead of
      # dying mid-step. The fault site is consulted every loop
      # iteration (one event per step, like nan_burst). ---
      preempt_fault = faults_lib.fire('preempt_signal') is not None
      if not draining and (preempt_fault or (
          drain_event is not None and drain_event.is_set())):
        if num_processes > 1:
          # The drain checkpoint is NOT a collective save; a one-host
          # drain would deadlock the others. Exit the loop — the
          # periodic collective checkpoints cover the tail.
          log.warning('preemption requested on a multi-host run: '
                      'drain is single-host, exiting the loop')
          break
        draining = True
        drain_source = 'fault' if preempt_fault else 'signal'
        drain_t0 = time.monotonic()
        drain_deadline = drain_t0 + config.preempt_drain_timeout_secs
        incidents.event('preempt_drain_start',
                        step=steps_done + _initial_steps,
                        source=drain_source)
        log.warning(
            'preemption drain (%s): admissions stopped; flushing '
            'in-flight unrolls within %.1fs', drain_source,
            config.preempt_drain_timeout_secs)
        # Stop production WITHOUT closing the buffer: actors finish
        # their current unroll, put it, and exit — those unrolls are
        # exactly what the flush below trains on. (Custom fleet
        # factories without a stop seam still drain: the feed just
        # keeps producing until the deadline.)
        if hasattr(fleet, 'stop_event'):
          fleet.stop_event.set()
      if draining and time.monotonic() > drain_deadline:
        log.warning('preemption drain budget exhausted; finalizing')
        break
      frames = (env_frames_fn() if env_frames_fn is not None else
                (_initial_steps + steps_done) * config.frames_per_step)
      if frames >= config.total_environment_frames:
        break
      if max_steps is not None and steps_done >= max_steps:
        break
      if (max_seconds is not None and
          time.monotonic() - loop_start > max_seconds):
        break
      # --- Hybrid filler slice (round 16): nothing staged right now,
      # so the learner chips run ONE bounded Anakin self-play step
      # instead of parking in prefetcher.get. fill_one BLOCKS on the
      # step's completion, so a batch staged meanwhile waits at most
      # one filler step (the yield-determinism contract,
      # tests/test_filler.py); the next iteration re-probes. Filler
      # updates mutate params but never advance update_steps — the
      # frame budget, LR schedule, and fps meter stay on the fleet's
      # fresh-frame clock (serve-time accounting, armed above). ---
      if (filler is not None and not draining
          and not prefetcher.ready()):
        run.state = filler.fill_one(run.state)
        state = run.state
        now_fill = time.monotonic()
        if now_fill - last_filler_check > poll_secs:
          # The starved branch's liveness duties, time-gated so a
          # microsecond filler step doesn't health-check every slice:
          # a dead fleet must still surface through the filler regime
          # (filler frames must not mask a dead env plane — the
          # env_plane_utilization objective pages, and the stall raise
          # below still fires).
          last_filler_check = now_fill
          errors = fleet.errors() or errors
          fleet.check_health(stall_timeout_secs=stall_timeout_secs)
          if (stall_timeout_secs is not None and
              now_fill - last_batch_time >
              max(3 * stall_timeout_secs, 30.0)):
            raise errors[0] if errors else TimeoutError(
                'no trajectory batch despite healthy actors (hybrid '
                'filler kept the learner busy; the env plane is the '
                'incident)')
        continue
      try:
        stats_view, action_counts, batch_device = prefetcher.get(
            timeout=0.5 if draining else poll_secs)
      except TimeoutError:
        if draining:
          break  # the feed dried up: every drainable batch is trained
        # No data yet: surface actor failures instead of hanging (the
        # reference hangs silently here — SURVEY §5.3). Read errors
        # BEFORE check_health — a respawn clears the slot's error, and
        # a crash-looping actor's root cause must survive to the stall
        # raise below (same ordering as evaluate()).
        errors = fleet.errors() or errors
        fleet.check_health(stall_timeout_secs=stall_timeout_secs)
        if (stall_timeout_secs is not None and
            time.monotonic() - last_batch_time >
            max(3 * stall_timeout_secs, 30.0)):
          raise errors[0] if errors else TimeoutError(
              'no trajectory batch despite healthy actors')
        continue
      except ring_buffer.Closed:
        if draining:
          break
        errors = fleet.errors() or errors
        if errors:
          raise errors[0]
        raise
      last_batch_time = time.monotonic()
      # Fault site 'learner_crash' (round 11): one event per CONSUMED
      # batch — a scheduled event hard-kills this process (SIGKILL: no
      # unwind, no drain, no 'bye'). kill -9/OOM made deterministic
      # for chaos.py's run_partition_storm, which runs the learner as
      # a child, restarts it, and asserts the restore-from-LAST_GOOD +
      # fleet re-attach SLOs.
      crash = faults_lib.fire('learner_crash')
      if crash is not None:
        faults_lib.hard_crash(crash)
      # Data is flowing again: captured errors are from a recovered
      # incident; keeping them would misattribute a much later stall.
      errors = []
      # jax.profiler capture window (SURVEY §5.1 — the reference has
      # no tracing at all): [start, start+num) learner steps, placed
      # after warmup so compiles don't drown the timeline.
      if config.profile_dir:
        # The operator window DEFERS past an in-flight SLO capture
        # (>= start step + the pending flag) instead of silently
        # skipping it when the two collide on the one profiler.
        if (profile_dir_pending and not profiling
            and slo_profile is None
            and steps_done >= config.profile_start_step):
          jax.profiler.start_trace(config.profile_dir)
          profiling = True
          profile_dir_pending = False
          profile_stop_step = steps_done + config.profile_num_steps
        elif profiling and steps_done >= profile_stop_step:
          jax.profiler.stop_trace()
          profiling = False
          log.info('profiler trace written to %s', config.profile_dir)
      # SLO-triggered deep diagnostics (round 14): a page-severity
      # burn queued a bounded profiler capture — the next
      # slo_capture_steps learner steps trace into
      # diagnostics/slo_profile_<objective>/ (the flight dump and the
      # trace slice already landed from the engine thread). One
      # capture at a time; the operator-requested profile_dir window
      # wins when both want the profiler.
      if slo_engine is not None and not profiling:
        if slo_profile is not None:
          name, end_step = slo_profile
          if steps_done >= end_step:
            jax.profiler.stop_trace()
            slo_profile = None
            log.info('SLO diagnostic profile for %r complete', name)
        else:
          req = slo_engine.take_profile_request()
          if req is not None:
            slo_prof_dir = os.path.join(config.logdir, 'diagnostics',
                                        f'slo_profile_{req}')
            os.makedirs(slo_prof_dir, exist_ok=True)
            try:
              jax.profiler.start_trace(slo_prof_dir)
            except Exception:
              log.exception('SLO profiler capture failed to start')
              slo_engine.note_profile(req, None)
            else:
              slo_profile = (req,
                             steps_done + config.slo_capture_steps)
              slo_engine.note_profile(req, slo_prof_dir)
              log.warning(
                  'SLO page (%s): capturing a %d-step profiler trace '
                  'into %s', req, config.slo_capture_steps,
                  slo_prof_dir)
      # Fault-injection seam (runtime/faults.py 'nan_burst'): rewards
      # become NaN on the staged device batch, driving a non-finite
      # loss through the REAL loss/grad path — what organic divergence
      # looks like to the watchdog.
      batch_device, poisoned = faults_lib.maybe_poison_batch(
          batch_device)
      if poisoned:
        incidents.event('fault_nan_burst',
                        step=steps_done + _initial_steps + 1)
      # Fault site 'slow_learner': a stalled step (device contention,
      # preempted neighbors) — the buffer must fill and producer-side
      # backpressure engage, never unbounded queueing (the overload
      # storm's occupancy SLO).
      slow = faults_lib.fire('slow_learner')
      if slow is not None and slow.kind == 'hang':
        time.sleep(float(slow.param))
      state, metrics = train_step(run.state, batch_device)
      run.state = state
      steps_done += 1
      if env_frames_fn is None:
        fps_meter.update(config.frames_per_step)
      else:
        # `frames` is this iteration's pre-serve reading, so the delta
        # is exactly the fresh frames this batch's first serve
        # credited — 0 on a re-serve, keeping fps an ENV-frame rate.
        fps_meter.update(max(env_frames_fn() - frames, 0))
      action_counts_acc += action_counts

      # Episode stats ride in the trajectory; the prefetcher peeled a
      # host-side view before the device transfer — no device_get here.
      step_now = steps_done + _initial_steps
      # Trace spans (round 13): the step consuming the oldest served
      # batch was just dispatched — complete its spans and emit the
      # batch record with the policy-lag vector (traces.jsonl).
      if tracer is not None:
        tracer.on_step(step_now)
      # Stack this step's scalar metrics into ONE device array now —
      # BEFORE the next step is dispatched, so the tiny stack
      # computation precedes it on the device stream. The summary
      # block reads the PREVIOUS step's stack: already computed, one
      # transfer, no dispatch-pipeline sync (the health-sentinel
      # pattern applied to the whole metrics dict — round 8; the old
      # path device_get each key separately against just-dispatched
      # values).
      prev_metrics = pending_metrics
      pending_metrics = (step_now, observability.stack_metrics(metrics))
      # A re-served batch (replay_k > 1) carries no env-plane view —
      # its episodes/actions were recorded on the first serve.
      if stats_view is not None:
        for name, ep_return, ep_frames in stats.record_batch(
            stats_view, step_now):
          log.info('episode %s return=%.2f frames=%d', name, ep_return,
                   ep_frames)

      # --- Escalation ladder (health.py): skip-and-count (the device
      # guard already withheld a non-finite update) → roll back to the
      # last-known-good checkpoint after K consecutive bad steps →
      # halt with a diagnostic bundle instead of training through
      # divergence. The sentinel read is ONE-STEP DELAYED: step N's
      # stacked scalars are fetched after step N+1 was dispatched, so
      # the device_get reads already-computed values instead of
      # syncing the dispatch pipeline every step (per-step coverage at
      # zero sync cost; the in-graph skip protects params with no
      # latency either way). ---
      if health is not None:
        prev_sentinel = pending_sentinel
        pending_sentinel = None
        if steps_done % config.health_check_every_steps == 0:
          # SDC fingerprints ride the same delayed-read cadence: the
          # [replicas] uint32 array is dispatched NOW (before the
          # next step donates the state) and read one check later.
          # The 'replica_divergence' fault site fires here — one
          # event per health check — perturbing one replica's probe
          # lane so the real detection→rollback path executes.
          fp_handle = None
          if sdc_fp_fn is not None:
            probe = np.zeros((sdc_replicas,), np.uint32)
            div = faults_lib.fire('replica_divergence')
            if div is not None:
              victim = div.index % sdc_replicas
              probe[victim] = np.uint32(1 + (div.index % 1000))
              incidents.event('fault_replica_divergence',
                              step=step_now, replica=victim)
            fp_handle = sdc_fp_fn(state.params, probe)
          pending_sentinel = (step_now,
                              health_lib.stack_sentinels(metrics),
                              fp_handle)
      if health is not None and prev_sentinel is not None:
        obs_step, handle, fp_handle_prev = prev_sentinel
        values = health_lib.read_handle(handle)
        if fp_handle_prev is not None:
          fps = np.asarray(jax.device_get(fp_handle_prev))
          sdc_mismatch = bool((fps != fps[0]).any())
          values['sdc_replica_mismatch'] = (1.0 if sdc_mismatch
                                            else 0.0)
          if sdc_mismatch:
            incidents.event('sdc_replica_mismatch', step=obs_step,
                            fingerprints=[int(x) for x in fps])
            log.error(
                'SDC sentinel: per-replica param fingerprints '
                'DISAGREE at step %d: %s — deterministic compute '
                'violated (suspect chip/HBM; docs/RUNBOOK.md §9)',
                obs_step, [f'{int(x):08x}' for x in fps])
        verdict = health.observe_values(obs_step, values)
        # Burst bracketing is driver-side state: the monitor resets
        # its consecutive count on a ROLLBACK verdict, so 'burst
        # ended' must be judged by verdicts, not that counter (a
        # burst whose length is an exact multiple of K would
        # otherwise never emit health_recovered).
        bad_count_in_burst += (verdict != health_lib.OK)
        if verdict != health_lib.OK and bad_count_in_burst == 1:
          incidents.event('health_bad_burst_start', step=obs_step,
                          reason=health.last_reason)
          log.warning('unhealthy training step %d: %s', obs_step,
                      health.last_reason)
        elif verdict == health_lib.OK and bad_count_in_burst > 0:
          incidents.event('health_recovered', step=obs_step,
                          bad_steps=bad_count_in_burst)
          bad_count_in_burst = 0
        if verdict == health_lib.ROLLBACK:
          if num_processes == 1:
            rolled = checkpointer.restore_last_good(state)
          else:
            # Hosts must enter the (collective) restore with the SAME
            # step: the per-host ladder could diverge on host-local
            # I/O errors. Process 0 chooses; everyone follows — the
            # broadcast is safe here because verdicts are a
            # deterministic function of the replicated metrics, so
            # every host reaches this branch in lockstep.
            choice = int(multihost_utils.broadcast_one_to_all(
                jnp.asarray(checkpointer.rollback_step_choice(),
                            jnp.int32)))
            rolled = (checkpointer.restore_step(choice, state)
                      if choice >= 0 else None)
          if rolled is None:
            verdict = health_lib.HALT
            health.rollbacks -= 1  # granted but could not be honored
            health.last_reason = (f'{health.last_reason}; rollback '
                                  'requested but no restorable '
                                  'checkpoint exists')
          else:
            # Keep the CURRENT update counter: frames/steps count
            # consumed env data and must stay monotone through a
            # rollback (checkpoint step numbers and the LR schedule
            # never move backwards; only params/opt/popart revert).
            restored_step = int(jax.device_get(rolled.update_steps))
            state = rolled._replace(update_steps=state.update_steps)
            run.state = state
            published = actor_params(state.params)
            server.update_params(published)
            rolled_remote_version = None
            if ingest is not None:
              rolled_remote_version = ingest.publish_params(
                  jax.device_get(published))
            if tracer is not None:
              # The rollback republish is a real publish: the local
              # lag clock and the install join both see it.
              tracer.on_publish(step_now,
                                remote_version=rolled_remote_version)
            # Flight-recorder dump (round 13): the last N seconds of
            # pipeline history (trace records + registry snapshots)
            # next to the rollback incident — a rollback postmortem
            # starts from what the pipeline was DOING, not just a
            # counter total.
            flight_path = None
            if tracer is not None:
              try:
                out_dir = os.path.join(config.logdir, 'diagnostics')
                os.makedirs(out_dir, exist_ok=True)
                flight_path = tracer.flight.write(os.path.join(
                    out_dir, f'flight_rollback_step{step_now}.json'))
              except OSError:
                log.exception('flight-recorder dump failed')
            incidents.event('rollback', step=step_now,
                            restored_checkpoint_step=restored_step,
                            reason=health.last_reason,
                            flight=flight_path)
            log.warning(
                'health rollback at step %d: restored checkpoint '
                'step %d (params/optimizer/popart revert; step '
                'counter keeps running)', step_now, restored_step)
        if verdict == health_lib.HALT:
          bundle = health.write_halt_bundle(
              config.logdir, config, step_now,
              reason=health.last_reason,
              flight=(tracer.flight.dump() if tracer is not None
                      else None))
          incidents.event('health_halt', step=step_now,
                          reason=health.last_reason, bundle=bundle)
          raise health_lib.TrainingDivergence(
              f'training halted at step {step_now} after '
              f'{health.rollbacks} rollback escalation(s): '
              f'{health.last_reason}. Diagnostic bundle: {bundle}',
              bundle_path=bundle)

      if steps_done % config.publish_params_every == 0:
        # actor_params is a cross-host collective in multi-host-TP
        # mode: it must run UNCONDITIONALLY here (lockstep branch),
        # never inside the per-host time-gated ingest publish below.
        # version=step_now gates the server's whole-tree copy: a
        # republish of the same step's snapshot is a counted no-op.
        published = actor_params(state.params)
        server.update_params(published, version=step_now)
        last_publish_step = step_now
        # Replay staleness clock (round 10): retained unrolls age in
        # published param versions — the same unit the ingest
        # admission window uses.
        buffer.note_param_version(step_now)
        remote_version = None
        if (ingest is not None and
            time.monotonic() - last_remote_publish >=
            publish_cadence['secs'] and
            ingest.stats()['live'] > 0):
          # Remote hosts poll-on-ack: publishing bumps the version the
          # next ack reports (the reference's per-run gRPC weight
          # fetch, as an explicit snapshot). Unlike the local pointer
          # swap above, this is a blocking device_get of the whole
          # param tree — hence the wall-clock throttle and the
          # nobody-connected gate. (Already host numpy when the
          # multi-host-TP localization ran; device_get is then a
          # pass-through.)
          last_remote_publish = time.monotonic()
          remote_version = ingest.publish_params(
              jax.device_get(published))
        # Trace record + the local publish clock policy lag counts
        # in. The INGEST-LANE version rides along when this snapshot
        # also went to the remote fleet: actors' install notices
        # carry that sequence, and trace_report's publish→install
        # join keys on it.
        if tracer is not None:
          tracer.on_publish(step_now, remote_version=remote_version)

      now = time.monotonic()
      if now - last_summary >= config.summary_secs:
        last_summary = now
        # One-step-delayed stacked read (round 8): the previous step's
        # metrics land in a single transfer of already-computed values.
        # Written at step_now — one step stale, immaterial at summary
        # cadence, and it keeps the summary step sequence monotone
        # (episode events already wrote step_now; the chaos SLO and
        # downstream readers assert non-decreasing steps). Only the
        # very first step has no predecessor — that one read blocks on
        # the fresh dispatch, like the old path always did.
        _, handle = (prev_metrics if prev_metrics is not None
                     else pending_metrics)
        writer.scalars(observability.read_stacked_metrics(handle),
                       step_now)
        writer.scalar('env_frames_per_sec', fps_meter.fps(), step_now)
        # Telemetry plane (round 13): the live policy-lag and
        # end-to-end span percentiles (the trace stream's headline
        # numbers, exported on the summary cadence so a lag blow-up
        # shows without a trace_report run), and one registry
        # snapshot into the flight recorder — the "what were the
        # counters doing just before" half of an incident dump. NaN
        # until traffic flows (rendered '-', not a fake 0).
        if tracer is not None:
          for tag, value in tracer.span_percentiles().items():
            writer.scalar(tag, value, step_now)
          writer.scalar('trace_untagged_unrolls',
                        tracer.stats()['untagged_unrolls'], step_now)
          tracer.flight.note_registry(telemetry.registry().snapshot())
        fleet_stats = fleet.stats(
            healthy_horizon_secs=(stall_timeout_secs
                                  if stall_timeout_secs else 60.0))
        writer.scalar('actors_alive', fleet_stats['alive'], step_now)
        # alive vs healthy (round 7): a wedged actor is alive without
        # producing — the quorum fraction is the honest fleet signal.
        writer.scalar('actors_healthy', fleet_stats['healthy'],
                      step_now)
        # Alive-but-silent actors (blocked in env.step / parked on
        # backpressure past the horizon): the fleet-side member of
        # the zero-deadlocked-threads ledger (round 11).
        writer.scalar('actors_wedged', fleet_stats.get('wedged', 0),
                      step_now)
        writer.scalar('fleet_healthy_fraction',
                      fleet_stats['healthy_fraction'], step_now)
        writer.scalar('actor_respawns', fleet_stats['respawns'],
                      step_now)
        # Learner failure-domain counters (health.py / checkpoint.py).
        if health is not None:
          hs = health.stats()
          writer.scalar('skipped_steps', hs['skipped_steps'], step_now)
          writer.scalar('flagged_steps', hs['flagged_steps'], step_now)
          writer.scalar('rollbacks', hs['rollbacks'], step_now)
          # SDC sentinel (round 12): replica fingerprint mismatches,
          # counted separately from non-finite skips — hardware lying
          # vs math diverging are different operator responses.
          writer.scalar('sdc_replica_mismatches',
                        hs.get('sdc_mismatches', 0), step_now)
        writer.scalar('checkpoint_save_errors',
                      checkpointer.save_errors, step_now)
        writer.scalar('checkpoint_restore_fallbacks',
                      checkpointer.restore_fallbacks, step_now)
        # Restore rungs refused for CONTENT-digest mismatch (bit rot
        # on a committed step) — a strict subset of the fallbacks
        # above, split out so disk rot alarms on its own curve.
        writer.scalar('ckpt_digest_fallbacks',
                      checkpointer.digest_fallbacks, step_now)
        # Buffer occupancy: ~0 means the learner is starved (env/
        # inference bound); ~capacity means actors are throttled by
        # backpressure (learner bound).
        writer.scalar('buffer_unrolls', len(buffer), step_now)
        # Merge telemetry over THIS summary interval (a cumulative
        # mean would hide regressions late in a long run): ≈1 means
        # the batcher is not merging — the single-machine throughput
        # lever (paper Table 1).
        snap = server.stats()
        d_calls = snap['calls'] - last_inference_snap['calls']
        d_reqs = snap['requests'] - last_inference_snap['requests']
        last_inference_snap = snap
        writer.scalar('inference_mean_batch',
                      (d_reqs / d_calls) if d_calls else 0.0, step_now)
        # Staleness: how many snapshots actors have been served (the
        # reference's "actions within one unroll may span weight
        # versions" caveat, made observable).
        writer.scalar('params_version', snap['params_version'],
                      step_now)
        # Actor-plane service time (round 7): per-merged-call latency
        # percentiles over the recent window — the inference-plane
        # bench's unit, exported live so a production regression shows
        # in the same numbers the bench rows use. publishes_skipped
        # counts version-gated no-op publishes (copy avoided).
        writer.scalar('inference_latency_p50_ms',
                      snap['latency_p50_ms'], step_now)
        writer.scalar('inference_latency_p99_ms',
                      snap['latency_p99_ms'], step_now)
        writer.scalar('inference_publishes_skipped',
                      snap['publishes_skipped'], step_now)
        # Admission/overload counters (round 9): sheds are the serving
        # plane's load-shedding response; admission_waits says how
        # often acquires parked; quarantined slots are respawn's
        # give-up tally. All bounded-degradation signals — alert on
        # slope, not presence.
        writer.scalar('inference_sheds', snap.get('sheds', 0),
                      step_now)
        writer.scalar('inference_admission_waits',
                      snap.get('admission_waits', 0), step_now)
        writer.scalar('inference_arena_grows',
                      snap.get('arena_grows', 0), step_now)
        # Multi-tenant serving plane (round 21): how many policy
        # versions are resident, and — when shadow traffic is on —
        # the EWMA action-disagreement between live and shadow (0.0
        # means the candidate acts identically on real traffic).
        writer.scalar('inference_resident_versions',
                      snap.get('resident_versions', 1), step_now)
        writer.scalar('inference_shadow_divergence',
                      snap.get('shadow_divergence', 0.0), step_now)
        quarantined_slots = fleet_stats.get('slots_quarantined', 0)
        writer.scalar('slots_quarantined', quarantined_slots, step_now)
        if quarantined_slots > last_quarantined_slots:
          incidents.event('actor_slots_quarantined', step=step_now,
                          count=quarantined_slots)
          last_quarantined_slots = quarantined_slots
        # Buffer occupancy guard: high_water at capacity + put_waits
        # growing = producers throttled by backpressure (the bound
        # holding), not a failure.
        buf_stats = buffer.stats()
        writer.scalar('buffer_high_water', buf_stats['high_water'],
                      step_now)
        writer.scalar('buffer_put_waits', buf_stats['put_waits'],
                      step_now)
        # --- Sample-reuse + plane-split telemetry (round 10): the
        # measurement that motivates replay and later judges it. ---
        pf = prefetcher.stats()
        d_steps = steps_done - last_reuse_snap['steps']
        # Fresh counted at SERVE time (fresh_slots_served — credited
        # at each batch's first serve), matching bench_replay's
        # composition attribution: dequeue-time fresh_unrolls runs
        # ahead by the prefetch lookahead, reading the headline low.
        d_fresh = (pf['fresh_slots_served'] -
                   last_reuse_snap['fresh_unrolls'])
        d_fresh_frames = d_fresh * frames_per_unroll
        # Learner updates per FRESH env frame over this interval: the
        # IMPACT headline. 1/frames_per_step at replay off; scales
        # with replay_k and 1/(1-replay_ratio).
        writer.scalar('learner_updates_per_env_frame',
                      (d_steps / d_fresh_frames) if d_fresh_frames
                      else 0.0, step_now)
        interval = now - last_reuse_snap['time']
        writer.scalar('env_frames_fresh_per_sec',
                      d_fresh_frames / interval if interval > 0
                      else 0.0, step_now)
        # Utilization split: how much of the interval each plane was
        # actually working. Learner-plane = wall fraction NOT blocked
        # on the feed (prefetcher wait); env-plane = fraction its
        # producer threads were NOT parked on buffer backpressure
        # (put_wait_secs is summed across producers, hence the
        # fleet-size normalization). Learner low + env high = env
        # bound (the regime replay attacks); the reverse = learner
        # bound.
        d_feed_wait = pf['wait_secs'] - last_reuse_snap.get(
            'feed_wait_secs', 0.0)
        learner_util = (min(max(1.0 - d_feed_wait / interval, 0.0),
                            1.0) if interval > 0 else 0.0)
        writer.scalar('learner_plane_utilization', learner_util,
                      step_now)
        d_put_wait = (buf_stats['put_wait_secs'] -
                      last_reuse_snap['put_wait_secs'])
        # Producer-thread count for the normalization: local actors
        # PLUS live ingest connections — the remote topology runs
        # num_actors=0 with N connection threads summing their waits,
        # which would otherwise clamp the metric to 0.
        producers = config.num_actors
        if ingest is not None:
          producers += ingest.stats()['live']
        producers = max(producers, 1)
        env_util = (min(max(1.0 - d_put_wait / (interval * producers),
                            0.0), 1.0) if interval > 0 else 0.0)
        writer.scalar('env_plane_utilization', env_util, step_now)
        # Registry mirror of the plane split + fleet quorum (round
        # 14): the numbers the SLO engine's env_plane_utilization /
        # fleet_healthy_fraction objectives judge.
        _set_plane_gauge('env', env_util)
        _set_plane_gauge('learner', learner_util)
        _set_plane_gauge('fleet', fleet_stats['healthy_fraction'])
        # Fresh vs reused frame counters (cumulative): reused = tier
        # replays (re-staged) + whole-batch re-serves (zero-H2D).
        frames_fresh = pf['fresh_slots_served'] * frames_per_unroll
        frames_reused = (
            buf_stats.get('replay_reused_unrolls', 0) +
            pf.get('batch_reserves', 0) * local_batch_size
        ) * frames_per_unroll
        writer.scalar('frames_fresh', frames_fresh, step_now)
        writer.scalar('frames_reused', frames_reused, step_now)
        if replay_tier is not None:
          for key in ('replay_occupancy', 'replay_evictions_age',
                      'replay_evictions_version',
                      'replay_reused_unrolls',
                      'replay_mean_staleness'):
            writer.scalar(key, buf_stats[key], step_now)
        last_reuse_snap = {
            'steps': steps_done,
            'fresh_unrolls': pf['fresh_slots_served'],
            'put_wait_secs': buf_stats['put_wait_secs'],
            'feed_wait_secs': pf['wait_secs'],
            'time': now,
        }
        # Per-interval action distribution (cumulative would hide a
        # late policy collapse).
        writer.histogram('actions', action_counts_acc, step_now)
        action_counts_acc = np.zeros_like(action_counts_acc)
        # Staging overlap (round 6): fraction of steps that did NOT
        # block on the prefetcher — the H2D-hidden-behind-compute
        # gate (read with buffer_unrolls: ≈0 there means the wait is
        # starvation upstream of staging, not transfer).
        pf = prefetcher.stats()
        writer.scalar('h2d_overlap_fraction',
                      pf['h2d_overlap_fraction'], step_now)
        writer.scalar('staged_batches', pf['staged_batches'], step_now)
        # EXPOSED staging wait over this interval (round 8): ms/step
        # the learner actually blocked on the feed — the part of
        # H2D+stacking NOT hidden behind compute. The overlap fraction
        # says how often a step waited; this says how much. bench.py's
        # learner_plane / e2e_fed itemization reads it back out.
        d_gets = pf['gets'] - last_pf_snap['gets']
        d_wait = pf['wait_secs'] - last_pf_snap['wait_secs']
        writer.scalar('staging_exposed_ms_per_step',
                      (d_wait / d_gets * 1e3) if d_gets else 0.0,
                      step_now)
        last_pf_snap = pf
        # The mode ACTUALLY running (config may have asked for unroll
        # and been topology-fallback'd to batch — a bench row labeled
        # from config alone would corrupt the head-to-head record).
        writer.scalar('staging_unroll_active',
                      1.0 if pf['mode'] == 'unroll' else 0.0, step_now)
        if pf.get('donation_fallback'):
          writer.scalar('staging_donation_fallback', 1, step_now)
        if ingest is not None:
          ing = ingest.stats()
          writer.scalar('remote_unrolls', ing['unrolls'], step_now)
          writer.scalar('remote_connections', ing['connections'],
                        step_now)
          # Rejected unrolls keep their connection alive (the actor
          # decides severity), so without this counter a host whose
          # every unroll is being refused is invisible here.
          writer.scalar('remote_rejected', ing['rejected'], step_now)
          # Staleness-window refusals (round 9): benign per unroll
          # (the client refetches), but a steadily climbing count
          # means some host can't keep its params fresh.
          writer.scalar('remote_stale_rejected',
                        ing.get('stale_rejected', 0), step_now)
          # Connections dropped for unparseable/garbage frames — the
          # wire-level quarantine (a corrupting peer must not be able
          # to take the learner down, only itself).
          writer.scalar('quarantined', ing['quarantined'], step_now)
          # v7 payload integrity (round 12): unrolls refused before
          # the put for a mismatched CRC trailer; param publishes the
          # fleet refused to install (digest mismatch, reported back
          # on the retry fetch); bytes/frames the discard paths threw
          # away. Expected flat at zero — any slope is an incident.
          writer.scalar('wire_crc_rejected',
                        ing.get('wire_crc_rejected', 0), step_now)
          writer.scalar('publish_digest_rejected',
                        ing.get('publish_digest_rejected', 0),
                        step_now)
          writer.scalar('ingest_discarded_frames',
                        ing.get('discarded_frames', 0), step_now)
          writer.scalar('ingest_discarded_bytes',
                        ing.get('discarded_bytes', 0), step_now)
          if (ing.get('wire_crc_rejected', 0) >
              last_ingest_snap.get('wire_crc_rejected', 0)):
            incidents.event(
                'wire_crc_rejected', step=step_now,
                total=ing['wire_crc_rejected'],
                delta=(ing['wire_crc_rejected'] -
                       last_ingest_snap.get('wire_crc_rejected', 0)))
          if (ing.get('publish_digest_rejected', 0) >
              last_ingest_snap.get('publish_digest_rejected', 0)):
            incidents.event(
                'publish_digest_rejected', step=step_now,
                total=ing['publish_digest_rejected'])
            if health is not None:
              health.note_external('publish_digest_rejected')
          # Per-lane transport counters (round 6). Ack latency is the
          # end-to-end backpressure signal remote pumps feel; the
          # per-connection rate spread separates one starved host
          # from a uniformly slow fleet.
          writer.scalar('remote_ack_p50_ms', ing['ack_p50_ms'],
                        step_now)
          writer.scalar('remote_ack_p99_ms', ing['ack_p99_ms'],
                        step_now)
          writer.scalar('remote_param_blobs', ing['param_blobs'],
                        step_now)
          # Transport-liveness counters (round 11): reaped idle/
          # half-open connections and dropped param subscribers are
          # the fan-out shrinkage signals; heartbeat misses lead the
          # reaps; reattach count/latency is the restarted learner's
          # fleet-recovery ledger; wedged threads should be ZERO —
          # any nonzero is an incident, not a trend.
          writer.scalar('remote_conns_reaped',
                        ing.get('conns_reaped', 0), step_now)
          writer.scalar('remote_heartbeat_misses',
                        ing.get('heartbeat_misses', 0), step_now)
          writer.scalar('param_subs_dropped',
                        ing.get('param_subs_dropped', 0), step_now)
          writer.scalar('remote_stale_epoch_rejected',
                        ing.get('stale_epoch_rejected', 0), step_now)
          writer.scalar('remote_reattached',
                        ing.get('reattached', 0), step_now)
          writer.scalar('remote_reattach_latency_secs',
                        ing.get('reattach_latency_secs', 0.0),
                        step_now)
          wedged_now = ing.get('ingest_threads_wedged', 0)
          writer.scalar('ingest_threads_wedged', wedged_now, step_now)
          if (ing.get('conns_reaped', 0) >
              last_ingest_snap.get('conns_reaped', 0)):
            incidents.event(
                'remote_conn_reaped', step=step_now,
                total=ing['conns_reaped'],
                delta=(ing['conns_reaped'] -
                       last_ingest_snap.get('conns_reaped', 0)))
          if wedged_now > last_ingest_snap.get(
              'ingest_threads_wedged', 0):
            names = ing.get('wedged_thread_names', [])
            incidents.event('ingest_threads_wedged', step=step_now,
                            count=wedged_now, names=names)
            if health is not None:
              health.note_external('ingest_threads_wedged')
            log.error('ingest watchdog: %d wedged thread(s): %s',
                      wedged_now, ', '.join(names))
          # Elastic membership (round 20): the v9 host ledger. The
          # gauge is the pod-size ground truth the SLO engine and the
          # pod_size actuator read; join/leave events drain into
          # DURABLE incidents (the 'host_' marker) so survivors'
          # incident streams narrate every topology change — the
          # departure itself is benign (training continues at reduced
          # topology), which is exactly why it must be on the record.
          live_hosts = ing.get('live_hosts', 0)
          writer.scalar('remote_live_hosts', live_hosts, step_now)
          _set_plane_gauge('hosts', live_hosts)
          for member_ev in ingest.drain_membership_events():
            if member_ev.get('kind') == 'host_left':
              incidents.event('host_left', step=step_now,
                              host=member_ev.get('host'),
                              reason=member_ev.get('reason'))
              log.warning(
                  'pod membership: host %s left (%s); %d host(s) '
                  'remain — continuing at reduced topology',
                  member_ev.get('host'), member_ev.get('reason'),
                  live_hosts)
            else:
              incidents.event('host_joined', step=step_now,
                              host=member_ev.get('host'),
                              reattach=member_ev.get('reattach',
                                                     False))
              log.info('pod membership: host %s joined (%d live)',
                       member_ev.get('host'), live_hosts)
          dt_summary = now - last_ingest_time
          d_unrolls = ing['unrolls'] - last_ingest_snap['unrolls']
          writer.scalar('remote_unrolls_per_sec',
                        d_unrolls / dt_summary if dt_summary else 0.0,
                        step_now)
          per_conn = ing['per_conn_unrolls']
          prev_conn = last_ingest_snap['per_conn_unrolls']
          rates = [(per_conn[k] - prev_conn.get(k, 0)) / dt_summary
                   for k in per_conn] if dt_summary else []
          if rates:
            writer.scalar('remote_conn_unrolls_per_sec_min',
                          min(rates), step_now)
            writer.scalar('remote_conn_unrolls_per_sec_max',
                          max(rates), step_now)
          last_ingest_snap = ing
          last_ingest_time = now
        # Telemetry self-health (round 14 satellites): silently
        # dropped JSONL writes (any stream, process-wide) and the
        # flight recorder's occupancy — asserted to reach
        # summaries.jsonl by the e2e remote test alongside the trace
        # scalars.
        writer.scalar('dropped_writes',
                      telemetry.dropped_writes_total(), step_now)
        if tracer is not None:
          writer.scalar('trace_flight_records', len(tracer.flight),
                        step_now)
        # Hybrid-filler surface (round 16): filler work is a SEPARATE
        # ledger from the fresh-frame clock — updates/frames say how
        # much idle learner capacity the filler reclaimed (the
        # learner_plane_utilization lift is the headline), skipped
        # counts non-finite filler updates the in-graph guard
        # withheld (an incident on increase: a filler stream must
        # never be able to poison params silently, and a climbing
        # count means the self-play task itself is diverging).
        if filler is not None:
          fstats = filler.stats()
          writer.scalar('filler_updates', fstats['updates'], step_now)
          writer.scalar('filler_frames', fstats['frames'], step_now)
          writer.scalar('filler_skipped_updates', fstats['skipped'],
                        step_now)
          if fstats['skipped'] > last_filler_skipped:
            incidents.event('filler_skipped_updates', step=step_now,
                            total=fstats['skipped'],
                            delta=(fstats['skipped'] -
                                   last_filler_skipped))
            if health is not None:
              health.note_external('filler_skipped_updates')
            last_filler_skipped = fstats['skipped']
        # Controller surface (round 15): the action/revert counts and
        # the live actuator state, so a knob the controller moved is
        # visible in the same stream the objectives are judged from.
        if ctrl is not None:
          ctrl_counts = ctrl.counts()
          writer.scalar('controller_actions', ctrl_counts['actions'],
                        step_now)
          writer.scalar('controller_reverts', ctrl_counts['reverts'],
                        step_now)
          writer.scalar('controller_engaged', ctrl.engaged_rules(),
                        step_now)
          writer.scalar('controller_replay_k', prefetcher.replay_k,
                        step_now)
          writer.scalar('controller_publish_secs',
                        publish_cadence['secs'], step_now)
        # Step-synchronous SLO evaluation (round 14): the engine's
        # thread covers long summary gaps; this call makes detection
        # deterministic wherever summaries are frequent (chaos runs
        # at summary_secs=0 — the storm's violation is judged the
        # step it happens, and the triggered capture still has loop
        # steps left to profile).
        if slo_engine is not None:
          slo_engine.observe()
      # Checkpoint cadence: Orbax saves are collective across hosts;
      # clocks differ, so all hosts act on PROCESS 0's decision (a
      # host-local clock here would desync the barrier and deadlock).
      # The broadcast is a cross-host sync, so it runs only every
      # checkpoint_check_every_steps — the cadence check itself must
      # not tax the hot loop (at worst the save lands that many steps
      # late, noise against checkpoint_secs=600).
      # Saves are WITHHELD mid-burst: finite divergence (loss
      # explosion) mutates params every step, and saving them would
      # both advance LAST_GOOD onto the diverged state (making the
      # rollback a no-op) and evict the healthy retained steps the
      # rollback needs. The gate is lockstep across hosts (verdicts
      # are a function of the replicated metrics).
      healthy_now = health is None or (bad_count_in_burst == 0)
      if num_processes == 1:
        if healthy_now:
          checkpointer.maybe_save(state)
      elif steps_done % config.checkpoint_check_every_steps == 0:
        decision = bool(multihost_utils.broadcast_one_to_all(
            jnp.asarray(checkpointer.should_save()))) and healthy_now
        checkpointer.maybe_save(state, decision=decision)
      fleet.check_health(stall_timeout_secs=stall_timeout_secs)
    if draining:
      # --- Drain finalize: quiesce → flush already happened in the
      # loop; now join the fleet (bounded), close the prefetcher
      # (pushes any partial batch's unrolls back into the buffer),
      # take a VERIFIED checkpoint through the integrity ladder, and
      # write the resume manifest. ---
      remaining = max(1.0, drain_deadline - time.monotonic())
      quiesce_report = (fleet.quiesce(timeout=remaining)
                        if hasattr(fleet, 'quiesce')
                        else {'unjoined_actors': []})
      prefetcher.close()
      step_final = _initial_steps + steps_done
      buf_stats = buffer.stats()
      # Withhold the drain save mid-bad-burst, exactly like the
      # periodic and final saves: checkpointing diverged params would
      # advance LAST_GOOD onto the poison. The manifest then names
      # the retained last-good step as the resume point.
      healthy_now = health is None or bad_count_in_burst == 0
      if healthy_now:
        checkpointer.save(run.state, force=True)
      else:
        log.warning('drain checkpoint withheld: training was '
                    'unhealthy at preemption (the retained last-'
                    'known-good step covers the resume)')
      ckpt_step = checkpointer.last_good_step()
      drain_latency = time.monotonic() - drain_t0
      manifest = {
          'update_steps': step_final,
          'frames': (env_frames_fn() if env_frames_fn is not None
                     else step_final * config.frames_per_step),
          'params_version_step': last_publish_step,
          'params_publishes': server.stats()['params_version'],
          'checkpoint_step': ckpt_step,
          'checkpoint_verified': ckpt_step == step_final,
          'buffer': {
              'leftover_unrolls': buf_stats['occupancy'],
              'high_water': buf_stats['high_water'],
              'capacity': buf_stats['capacity'],
          },
          'unjoined_actors': quiesce_report['unjoined_actors'],
          # Health at preemption: consecutive_bad > 0 here explains a
          # withheld (unverified) drain checkpoint to the resume/
          # postmortem without a summaries.jsonl dig.
          'health': (health.drain_report()
                     if health is not None else None),
          # The unified telemetry snapshot (round 13): every
          # registry-backed counter at drain time, from the same
          # source of truth the flight recorder and the remote
          # 'stats' request read — the resume/postmortem gets the
          # full counter surface without a summaries.jsonl dig.
          'metrics': telemetry.registry().snapshot(),
          # SLO state at drain time (round 14): the preempted run's
          # verdict-so-far, so the resume/postmortem sees which
          # objectives were burning when the platform pulled the node.
          'slo': (slo_engine.verdict() if slo_engine is not None
                  else None),
          # Controller state at drain time (round 15): what the run
          # did to itself before the platform pulled the node —
          # alongside the health ledger's controller_<actuator>
          # entries.
          'controller': (dict(ctrl.counts(), mode=ctrl.mode)
                         if ctrl is not None else None),
          # Hybrid-filler ledger (round 16): how much idle learner
          # capacity self-play reclaimed — explicitly OUTSIDE the
          # 'frames' fresh-frame figure above.
          'filler': filler.stats() if filler is not None else None,
          'drain_source': drain_source,
          'drain_latency_secs': round(drain_latency, 3),
          'wall_time': round(time.time(), 3),
      }
      if process_index == 0:
        path = _write_resume_manifest(config.logdir, manifest)
        log.warning(
            'preemption drain complete in %.2fs: checkpoint step %s '
            '(verified=%s), %d unroll(s) left in the buffer, '
            'manifest %s', drain_latency, ckpt_step,
            manifest['checkpoint_verified'],
            buf_stats['occupancy'], path)
      incidents.event('preempt_drain_complete', step=step_final,
                      drain_latency_secs=round(drain_latency, 3),
                      checkpoint_step=ckpt_step,
                      leftover_unrolls=buf_stats['occupancy'],
                      unjoined_actors=quiesce_report['unjoined_actors'])
      writer.scalar('drain_latency_secs', round(drain_latency, 3),
                    step_final)
  finally:
    exiting_clean = sys.exc_info()[0] is None
    # One robustness roll-up while the fleet still runs (stats after
    # stop() would read an all-dead fleet): what the run's failure
    # domain absorbed, in the same counters the summaries carry.
    try:
      fleet_stats = fleet.stats(
          healthy_horizon_secs=(stall_timeout_secs
                                if stall_timeout_secs else 60.0))
      hs = health.stats() if health is not None else {}
      ing_q = ingest.stats()['quarantined'] if ingest is not None else 0
      log.info(
          'robustness summary: skipped_steps=%d rollbacks=%d '
          'quarantined=%d respawns=%d fleet_healthy_fraction=%.2f '
          'checkpoint_save_errors=%d restore_fallbacks=%d',
          hs.get('skipped_steps', 0), hs.get('rollbacks', 0), ing_q,
          fleet_stats['respawns'], fleet_stats['healthy_fraction'],
          checkpointer.save_errors, checkpointer.restore_fallbacks)
    except Exception:
      log.exception('robustness summary failed')
    # Controller (round 15): stop the actuation thread FIRST (it
    # reads the engine and moves component knobs — both about to be
    # torn down) and write CONTROLLER_LOG.json on every exit path;
    # the action log is the operator's record of what the run did to
    # itself.
    if ctrl is not None:
      try:
        ctrl.stop()
        ctrl_counts = ctrl.finalize()
        log.info('controller [%s]: %d action(s) (%d escalation(s), '
                 '%d revert(s), %d applied) -> CONTROLLER_LOG.json',
                 ctrl.mode, ctrl_counts['actions'],
                 ctrl_counts['escalations'], ctrl_counts['reverts'],
                 ctrl_counts['applied'])
      except Exception:
        log.exception('controller finalize failed')
    # SLO verdict (round 14): stop the evaluator thread and write the
    # per-run SLO_VERDICT.json — BEFORE component teardown, so the
    # final observation still sees every fn-gauge its objectives
    # judge. Written on every exit path (a crashed run's verdict is
    # exactly what the postmortem wants); chaos/soak/slo_report read
    # the file.
    if slo_engine is not None:
      try:
        slo_engine.stop()
        verdict_name = ('SLO_VERDICT.json' if process_index == 0
                        else f'SLO_VERDICT_p{process_index}.json')
        verdict = slo_engine.finalize(
            os.path.join(config.logdir, verdict_name),
            extra={'clean_exit': exiting_clean,
                   'update_steps': _initial_steps + steps_done})
        (log.info if verdict['pass'] else log.warning)(
            'SLO verdict: %s (%d objective(s), violations: %s) -> %s',
            'PASS' if verdict['pass'] else 'FAIL',
            len(verdict['objectives']),
            verdict['violations'] or 'none', verdict_name)
      except Exception:
        log.exception('SLO verdict write failed')
    if profiling or slo_profile is not None:
      jax.profiler.stop_trace()
    elif config.profile_dir and profile_dir_pending:
      log.warning(
          'profile_dir set but the run ended at step %d before the '
          'window could start (profile_start_step=%d, or an SLO '
          'capture held the profiler) — no operator trace was '
          'captured', steps_done, config.profile_start_step)
    if ingest is not None:
      # v10 routed serving: flip the draining notice FIRST — every
      # infer reply from here on tells routers to shift traffic away
      # while the rest of the teardown runs.
      try:
        ingest.set_draining()
      except Exception:
        log.exception('set_draining failed')
    fleet.stop()
    prefetcher.close()
    server.close()
    if filler is not None:
      # Unregister the filler's per-run counter (identity-checked, so
      # this can never evict a newer run's registration).
      try:
        filler.close()
      except Exception:
        log.exception('filler close failed')
    if ingest is not None:
      # Clean end → 'bye' frame (remote actors exit immediately);
      # exception unwind → crash semantics (actors keep their
      # reconnect window for the supervisor's restart).
      ingest.close(graceful=exiting_clean)
    try:
      # The final save is a COLLECTIVE. On a clean exit every host
      # reaches it in lockstep (termination is a deterministic
      # function of the shared step count). When unwinding from a
      # host-local exception, other hosts are still inside the
      # collective train step — entering the Orbax barrier here would
      # deadlock the job instead of surfacing the error; periodic
      # checkpoints cover the tail. An UNHEALTHY exit (divergence
      # halt, or any unwind mid-bad-burst) must not save either:
      # finite divergence mutates params, and checkpointing them here
      # would advance LAST_GOOD onto the diverged state and evict the
      # healthy steps — the restarted run would restore the poison
      # and halt again, a crash loop with no way back.
      unhealthy_exit = health is not None and bad_count_in_burst > 0
      if unhealthy_exit:
        log.warning('skipping final checkpoint: training was '
                    'unhealthy at exit (the retained last-known-good '
                    'checkpoint covers the resume)')
      elif num_processes == 1 or exiting_clean:
        checkpointer.save(run.state, force=True)
      else:
        log.warning('skipping final collective checkpoint on '
                    'exception unwind (multi-host)')
    finally:
      checkpointer.close()
      writer.close()
      # The lock-order sink closes over THIS run's incident stream —
      # clear it before the stream closes (a later detection in a
      # leaked daemon thread becomes a counted log line, not a write
      # into a closed file).
      lock_check.set_incident_sink(None)
      incidents.close()
      for gauge in _loop_gauges:
        telemetry.registry().unregister(gauge.name, gauge)
      for gauge in _plane_gauges.values():
        telemetry.registry().unregister(gauge.name, gauge)
      if tracer is not None:
        telemetry.set_tracer(None)
        tracer.close()
  return run


def train_anakin(config: Config, max_steps: Optional[int] = None,
                 max_seconds: Optional[float] = None,
                 drain_event: Optional[threading.Event] = None,
                 initial_state=None) -> TrainRun:
  """The Anakin runtime (round 16, ROADMAP item 3): act+learn fused
  into one jitted device step (parallel/anakin.py, Podracer
  arXiv:2104.06272), run as a PRODUCTION run — the full lifecycle the
  fleet runtime gets, not the bench curiosity the r4 artifact
  measured at 1,250,181 fps:

  - checkpoint ladder (PR 2/9): verified saves with content digests,
    restore_latest at spin-up, LAST_GOOD rollback on health
    escalation, structure-mismatch refusal without overwriting;
  - health watchdog (PR 2): the in-graph non-finite guard is already
    inside the fused step (learner.make_train_step_fn); here the host
    monitor reads the one-step-delayed sentinels and escalates
    skip → rollback → halt-with-bundle exactly like the fleet loop;
  - metrics registry + SLO engine + verdict (PRs 10–11): the same
    literal gauge names, the same default objective set, the same
    SLO_VERDICT.json on every exit path, slo_violation incidents, and
    the triggered jax.profiler capture served by this loop;
  - summaries/incidents JSONL, config.json, FpsMeter — the artifact
    contract every script (chaos/soak/slo_report) already reads.

  Sharding: the mesh path shards the env batch over the data axis per
  the `test_anakin_shards_over_the_mesh` discipline (params
  replicate; jit inserts the gradient psum). Data-parallel and
  single-host only — the fused loop has no cross-host batch
  transport.

  Pipeline-plane machinery (fleet, inference server, prefetcher,
  ingest, tracer, controller) intentionally absent: there are no hops
  to trace and no actuators to drive; the SLO objectives over those
  planes evaluate no_data, which never violates. `drain_event`
  (SIGTERM via experiment.py) stops the loop at the next fused-step
  boundary — the finally's tail checkpoint + verdict are the drain.

  `initial_state` (round 23): a TrainState to start from INSTEAD of
  restore_latest — the population loop's on-device exploit seam. An
  in-process PBT loser inherits the donor's weights as a device
  pytree; round-tripping that copy through the filesystem (the old
  rmtree+copytree) cost a serialize/deserialize per exploit and a
  window where the loser's checkpoint ladder didn't exist at all.
  The ladder still records the decision durably: the loop's next
  periodic save lands the inherited state in the loser's own dir.

  Returns a TrainRun whose fleet/prefetcher/server/stats are None.
  """
  from scalable_agent_tpu.parallel import anakin as anakin_lib
  if jax.process_count() > 1:
    raise ValueError('runtime=anakin is single-host: the fused loop '
                     'has no cross-host batch transport — each '
                     'process would train an unsynchronized replica')
  if config.model_parallelism > 1:
    raise ValueError('runtime=anakin is data-parallel only; drop '
                     '--model_parallelism')
  # Knob-group validation, same contract as train(): hard errors
  # raise before any spin-up cost; cross-links log.
  for validate in (validate_runtime, validate_slo,
                   validate_population):
    for warning in validate(config):
      log.warning('%s', warning)
  if config.controller != 'off':
    log.info('controller=%s is a fleet-runtime feature: the anakin '
             'runtime has no actuators (no prefetcher/admission/'
             'publish/fleet knobs) — running without it',
             config.controller)

  mesh = choose_mesh(config)
  env_core, agent, step, carry = anakin_lib.build_run(config,
                                                      mesh=mesh)
  del env_core
  os.makedirs(config.logdir, exist_ok=True)

  checkpointer = checkpoint_lib.Checkpointer(
      config.logdir + '/checkpoints',
      save_interval_secs=config.checkpoint_secs,
      verify_digests=config.ckpt_digests,
      registry=sharding_lib.from_config(config), mesh=mesh)
  restore_ok = False
  if initial_state is not None:
    # On-device inheritance: the caller hands the starting state
    # directly (already the right structure — it came from a sibling
    # member of the same population). No disk round trip; the ladder
    # below saves it durably at the normal cadence.
    carry = carry._replace(train_state=initial_state)
    restore_ok = True
    log.info('starting from caller-provided state at step %d',
             int(jax.device_get(initial_state.update_steps)))
  else:
    try:
      restored = checkpointer.restore_latest(carry.train_state)
      restore_ok = True
    except BaseException:
      # A structure-mismatch raise must not leak the manager (its
      # background threads survive a same-process retry) — and the
      # finally below must NOT tail-save a fresh state into a logdir
      # holding an incompatible checkpoint (restore_ok gates it).
      checkpointer.close()
      raise
    if restored is not None:
      carry = carry._replace(train_state=restored)
      log.info('restored checkpoint at step %d',
               int(jax.device_get(restored.update_steps)))
  _initial_steps = int(jax.device_get(carry.train_state.update_steps))

  writer = None
  incidents = None
  slo_engine = None
  health = None
  try:
    writer = observability.SummaryWriter(config.logdir)
    incidents = observability.EventLog(config.logdir)
    # Same contract as the fleet loop (round 18): a lock-order
    # detection among the anakin checkpoint/SLO/health locks must
    # land as a DURABLE lock_order_inversion incident, not just a
    # counted log line. Cleared in both teardown paths.
    lock_check.set_incident_sink(incidents.event)
    with open(os.path.join(config.logdir, 'config.json'), 'w') as f:
      json.dump(dataclasses.asdict(config), f, indent=2,
                sort_keys=True)
    fps_meter = observability.FpsMeter()
    health = (health_lib.monitor_from_config(config)
              if config.health_watchdog else None)
    if config.slo_engine:
      slo_objectives = slo_lib.load_objectives(
          config.slo_spec,
          fast_window_secs=config.slo_fast_window_secs,
          slow_window_secs=config.slo_slow_window_secs)
      slo_interval = (config.slo_interval_secs
                      if config.slo_interval_secs > 0 else
                      min(max(float(config.summary_secs), 1.0), 30.0,
                          config.slo_fast_window_secs / 4.0))
      slo_engine = slo_lib.SloEngine(
          slo_objectives, config.logdir, writer=writer,
          incidents=incidents, flight=None, health=health,
          capture=config.slo_capture, interval_secs=slo_interval,
          baseline=slo_lib.load_baseline(config.slo_fps_baseline))
      slo_engine.start()
  except BaseException:
    if writer is not None:
      writer.close()
    if incidents is not None:
      lock_check.set_incident_sink(None)
      incidents.close()
    if slo_engine is not None:
      slo_engine.stop()
    checkpointer.close()
    raise

  run = TrainRun(config, agent, carry.train_state, None, None, None,
                 checkpointer, writer, None, fps_meter, health=health)
  steps_done = 0
  # Registry view of the loop (the same literal names train()
  # registers — the SLO engine and the name lint see ONE inventory).
  # The plane split is a fleet concept; in the fused runtime env and
  # learner are the same XLA program, busy whenever the loop is, so
  # both gauges pin 1.0 — fps_floor is the objective that catches a
  # wedged loop. fleet_healthy_fraction stays unregistered (no fleet:
  # no_data, never a violation).
  _loop_gauges = [
      telemetry.gauge('driver/update_steps',
                      fn=lambda: steps_done + _initial_steps),
      telemetry.gauge('driver/env_frames',
                      fn=lambda: (steps_done + _initial_steps) *
                      config.frames_per_step),
      telemetry.gauge('driver/env_plane_utilization', fn=lambda: 1.0),
      telemetry.gauge('driver/learner_plane_utilization',
                      fn=lambda: 1.0),
  ]
  # Curriculum telemetry (round 22): the fused step already folds the
  # per-level score/visit tables and their scalar digests into the
  # stacked metrics; these registry gauges re-export the latest
  # summary-read values so the SLO engine and scripts see them under
  # registry names without an extra device sync (zero host round
  # trips stays true — the dict updates at the summary cadence from
  # the one-step-delayed read the loop does anyway).
  curriculum_latest: Dict[str, float] = {}
  if config.curriculum != 'uniform':
    _loop_gauges += [
        telemetry.gauge(
            'curriculum/entropy',
            fn=lambda: curriculum_latest.get('curriculum_entropy',
                                             0.0)),
        telemetry.gauge(
            'curriculum/levels_visited',
            fn=lambda: curriculum_latest.get(
                'curriculum_levels_visited', 0.0)),
        telemetry.gauge(
            'curriculum/score_max',
            fn=lambda: curriculum_latest.get('curriculum_score_max',
                                             0.0)),
    ]
  sync_every = anakin_lib._cpu_mesh_sync_every(mesh)
  pending_metrics = None
  prev_metrics = None
  pending_sentinel = None
  bad_count_in_burst = 0
  slo_profile = None
  loop_start = time.monotonic()
  last_summary = loop_start
  try:
    while True:
      if drain_event is not None and drain_event.is_set():
        # SIGTERM: the fused loop quiesces at a step boundary — the
        # finally's tail checkpoint + SLO verdict ARE the drain (no
        # buffers to flush, no fleet to join).
        incidents.event('anakin_stop_requested',
                        step=_initial_steps + steps_done)
        log.warning('stop requested (SIGTERM): finalizing at step %d',
                    _initial_steps + steps_done)
        break
      frames = (_initial_steps + steps_done) * config.frames_per_step
      if frames >= config.total_environment_frames:
        break
      if max_steps is not None and steps_done >= max_steps:
        break
      if (max_seconds is not None and
          time.monotonic() - loop_start > max_seconds):
        break
      carry, metrics = step(carry)
      run.state = carry.train_state
      steps_done += 1
      step_now = _initial_steps + steps_done
      fps_meter.update(config.frames_per_step)
      if sync_every is not None and steps_done % sync_every == 0:
        jax.block_until_ready(metrics['total_loss'])
      # One-step-delayed stacked metrics (the train() discipline): the
      # summary read transfers already-computed values, never syncing
      # the async dispatch chain.
      prev_metrics = pending_metrics
      pending_metrics = (step_now, observability.stack_metrics(metrics))

      # SLO-triggered profiler capture (round 14): the engine thread
      # already dumped what it could; the bounded jax.profiler window
      # must ride the loop that dispatches device work.
      if slo_engine is not None:
        if slo_profile is not None:
          name, end_step = slo_profile
          if steps_done >= end_step:
            jax.profiler.stop_trace()
            slo_profile = None
            log.info('SLO diagnostic profile for %r complete', name)
        else:
          req = slo_engine.take_profile_request()
          if req is not None:
            slo_prof_dir = os.path.join(config.logdir, 'diagnostics',
                                        f'slo_profile_{req}')
            os.makedirs(slo_prof_dir, exist_ok=True)
            try:
              jax.profiler.start_trace(slo_prof_dir)
            except Exception:
              log.exception('SLO profiler capture failed to start')
              slo_engine.note_profile(req, None)
            else:
              slo_profile = (req,
                             steps_done + config.slo_capture_steps)
              slo_engine.note_profile(req, slo_prof_dir)

      # --- Health ladder (PR 2), one-step delayed exactly like
      # train(): skip-and-count → rollback to LAST_GOOD after K
      # consecutive bad steps → halt with the diagnostic bundle. The
      # fused step's in-graph guard already withheld any non-finite
      # update on device. ---
      if health is not None:
        prev_sentinel = pending_sentinel
        pending_sentinel = None
        if steps_done % config.health_check_every_steps == 0:
          pending_sentinel = (step_now,
                              health_lib.stack_sentinels(metrics))
        if prev_sentinel is not None:
          obs_step, handle = prev_sentinel
          verdict = health.observe_values(
              obs_step, health_lib.read_handle(handle))
          bad_count_in_burst += (verdict != health_lib.OK)
          if verdict != health_lib.OK and bad_count_in_burst == 1:
            incidents.event('health_bad_burst_start', step=obs_step,
                            reason=health.last_reason)
            log.warning('unhealthy training step %d: %s', obs_step,
                        health.last_reason)
          elif verdict == health_lib.OK and bad_count_in_burst > 0:
            incidents.event('health_recovered', step=obs_step,
                            bad_steps=bad_count_in_burst)
            bad_count_in_burst = 0
          if verdict == health_lib.ROLLBACK:
            rolled = checkpointer.restore_last_good(carry.train_state)
            if rolled is None:
              verdict = health_lib.HALT
              health.rollbacks -= 1  # granted but not honorable
              health.last_reason = (f'{health.last_reason}; rollback '
                                    'requested but no restorable '
                                    'checkpoint exists')
            else:
              restored_step = int(jax.device_get(rolled.update_steps))
              # Step counter stays monotone through a rollback (only
              # params/opt/popart revert) — the train() contract.
              carry = carry._replace(train_state=rolled._replace(
                  update_steps=carry.train_state.update_steps))
              run.state = carry.train_state
              incidents.event('rollback', step=step_now,
                              restored_checkpoint_step=restored_step,
                              reason=health.last_reason, flight=None)
              log.warning(
                  'health rollback at step %d: restored checkpoint '
                  'step %d', step_now, restored_step)
          if verdict == health_lib.HALT:
            bundle = health.write_halt_bundle(
                config.logdir, config, step_now,
                reason=health.last_reason, flight=None)
            incidents.event('health_halt', step=step_now,
                            reason=health.last_reason, bundle=bundle)
            raise health_lib.TrainingDivergence(
                f'training halted at step {step_now} after '
                f'{health.rollbacks} rollback escalation(s): '
                f'{health.last_reason}. Diagnostic bundle: {bundle}',
                bundle_path=bundle)

      now = time.monotonic()
      if now - last_summary >= config.summary_secs:
        last_summary = now
        _, handle = (prev_metrics if prev_metrics is not None
                     else pending_metrics)
        vals = observability.read_stacked_metrics(handle)
        writer.scalars(vals, step_now)
        if config.curriculum != 'uniform':
          curriculum_latest.update(
              {k: v for k, v in vals.items()
               if k.startswith('curriculum_')})
        writer.scalar('env_frames_per_sec', fps_meter.fps(), step_now)
        if health is not None:
          hs = health.stats()
          writer.scalar('skipped_steps', hs['skipped_steps'],
                        step_now)
          writer.scalar('flagged_steps', hs['flagged_steps'],
                        step_now)
          writer.scalar('rollbacks', hs['rollbacks'], step_now)
        writer.scalar('checkpoint_save_errors',
                      checkpointer.save_errors, step_now)
        writer.scalar('checkpoint_restore_fallbacks',
                      checkpointer.restore_fallbacks, step_now)
        writer.scalar('ckpt_digest_fallbacks',
                      checkpointer.digest_fallbacks, step_now)
        # Step-synchronous SLO evaluation (the chaos/summary_secs=0
        # determinism contract, same as train()).
        if slo_engine is not None:
          slo_engine.observe()
      healthy_now = health is None or bad_count_in_burst == 0
      if healthy_now:
        checkpointer.maybe_save(carry.train_state)
  finally:
    exiting_clean = sys.exc_info()[0] is None
    if slo_engine is not None:
      try:
        slo_engine.stop()
        verdict = slo_engine.finalize(
            os.path.join(config.logdir, 'SLO_VERDICT.json'),
            extra={'clean_exit': exiting_clean,
                   'update_steps': _initial_steps + steps_done,
                   'runtime': 'anakin'})
        (log.info if verdict['pass'] else log.warning)(
            'SLO verdict: %s (%d objective(s), violations: %s)',
            'PASS' if verdict['pass'] else 'FAIL',
            len(verdict['objectives']),
            verdict['violations'] or 'none')
      except Exception:
        log.exception('SLO verdict write failed')
    if slo_profile is not None:
      jax.profiler.stop_trace()
    try:
      # Final summary flush: short runs end inside one window and
      # would otherwise ship empty curves (anakin.train's contract).
      if steps_done and pending_metrics is not None:
        step_final, handle = pending_metrics
        try:
          writer.scalars(observability.read_stacked_metrics(handle),
                         step_final)
          writer.scalar('env_frames_per_sec', fps_meter.fps(),
                        step_final)
        except Exception:
          log.exception('final summary flush failed')
      # Per-level curriculum artifact (round 22): the final score /
      # visit tables plus the live sampling distribution — the
      # machine-readable answer to "which levels got the frames"
      # (scripts and the CI population lane read this, not summaries).
      if (config.curriculum != 'uniform' and
          hasattr(carry.env_state, 'level_scores')):
        try:
          scores = np.asarray(
              jax.device_get(carry.env_state.level_scores))
          visits = np.asarray(
              jax.device_get(carry.env_state.level_visits))
          probs = np.asarray(population_lib.level_probs(
              scores, config.curriculum_temperature,
              config.curriculum_eps))
          curriculum_path = os.path.join(config.logdir,
                                         'CURRICULUM_LEVELS.json')
          with open(curriculum_path, 'w') as f:
            json.dump({'curriculum': config.curriculum,
                       'temperature': config.curriculum_temperature,
                       'eps': config.curriculum_eps,
                       'scores': [float(s) for s in scores],
                       'visits': [float(v) for v in visits],
                       'probs': [float(p) for p in probs]},
                      f, indent=2)
        except Exception:
          log.exception('curriculum artifact write failed')
      unhealthy_exit = health is not None and bad_count_in_burst > 0
      if unhealthy_exit:
        log.warning('skipping final checkpoint: training was '
                    'unhealthy at exit (the retained last-known-good '
                    'checkpoint covers the resume)')
      elif restore_ok:
        checkpointer.save(run.state, force=True)
    finally:
      checkpointer.close()
      writer.close()
      lock_check.set_incident_sink(None)
      incidents.close()
      for gauge in _loop_gauges:
        telemetry.registry().unregister(gauge.name, gauge)
  return run


def _member_return(member_dir: str, tag: str = 'mean_reward',
                   tail: int = 5) -> float:
  """A member's fitness: the mean of its last `tail` summary values
  for `tag` (step-ordered). Summaries append across rounds, so the
  tail reflects the round just finished. Missing/empty summaries
  score 0.0 — a member that produced nothing never wins a round."""
  vals = []
  try:
    with open(os.path.join(member_dir, 'summaries.jsonl')) as f:
      for line in f:
        try:
          rec = json.loads(line)
        except ValueError:
          continue
        if rec.get('tag') == tag and 'value' in rec:
          vals.append((int(rec.get('step', 0)), float(rec['value'])))
  except OSError:
    return 0.0
  if not vals:
    return 0.0
  vals.sort(key=lambda sv: sv[0])
  return float(np.mean([v for _, v in vals[-tail:]]))


def _inherit_member_dir(donor_dir: str, loser_dir: str) -> None:
  """Cross-process PBT weight inheritance: the loser's checkpoint
  ladder becomes a copy of the donor's — via copy-then-swap, so a
  failed copy NEVER deletes the loser's own ladder (the r22 code did
  rmtree-then-copytree, which left the loser with no restorable
  checkpoint at all if the copy died mid-way). The loser's next
  restore re-verifies the donor's content digests through the PR 2
  ladder — a torn copy is refused, not trained on.

  This is the cross-process fallback only: in-process exploits hand
  the donor's state over as a device pytree (train_anakin's
  initial_state seam) and never touch the filesystem."""
  tmp = loser_dir + '.inherit_tmp'
  old = loser_dir + '.inherit_old'
  for leftover in (tmp, old):
    if os.path.isdir(leftover):
      shutil.rmtree(leftover)
  try:
    shutil.copytree(donor_dir, tmp)
  except BaseException:
    # The loser's ladder was never touched; only the partial copy
    # goes.
    shutil.rmtree(tmp, ignore_errors=True)
    raise
  if os.path.isdir(loser_dir):
    os.rename(loser_dir, old)
  os.rename(tmp, loser_dir)
  shutil.rmtree(old, ignore_errors=True)


def _train_population_fused(config: Config,
                            max_steps: Optional[int] = None,
                            max_seconds: Optional[float] = None,
                            drain_event: Optional[threading.Event] = None
                            ) -> TrainRun:
  """The vectorized population (round 23, --pbt_vectorized): all N
  members advance in ONE compiled program per round.

  The serial loop (train_population below) spins train_anakin up N
  times per round — N jit traces the first round, N spin-up/teardown
  walls every round, and a device left idle while the host replays
  lifecycle code between members. Here the member axis is a vmap
  axis instead: one stacked carry, one fused act+learn step vmapped
  over members, one dispatch per lockstep step. The PBT hypers
  (learning_rate, entropy_cost) enter the program as TRACED
  per-member scalars, so explore perturbations between rounds NEVER
  retrigger compilation — round 2 reuses round 1's executable.

  What stays host-side, by design: the decide/explore logic runs
  BETWEEN rounds (pbt_decide on the members' summary returns), and
  weight inheritance is a device-to-device stacked-index copy
  (`train_state.at[loser].set(train_state[donor])`) — no rmtree, no
  copytree, no serialize round trip. Each member still owns a real
  checkpoint ladder: its slice is force-saved at every round
  boundary AFTER exploits land, so the decision history is durable
  and any member dir resumes (fused or serial) across processes.

  Single-suite, single-device members: one vmapped program can only
  train structurally identical members (validate_population rejects
  multi-suite vectorized populations and degrades model-axis meshes
  to the serial loop). Artifacts match the serial path:
  population_summaries.jsonl, PBT_LOG.json (vectorized=true),
  pbt_exploit/pbt_winner incidents, per-member summaries.jsonl and
  checkpoints/, and the parent-logdir SLO verdict."""
  from scalable_agent_tpu.parallel import anakin as anakin_lib
  suite_list = list(config.resolved_pbt_suites)
  suite = suite_list[0]
  n = config.pbt_population
  round_frames = config.resolved_pbt_round_frames
  num_rounds = max(
      1, -(-config.total_environment_frames // round_frames))
  os.makedirs(config.logdir, exist_ok=True)
  rng = np.random.default_rng(config.seed)

  # Same hyper-init recipe as the serial loop (member 0 is the
  # unperturbed control arm) — the two paths must be comparable.
  members = []
  for k in range(n):
    hypers = {'learning_rate': config.learning_rate,
              'entropy_cost': config.entropy_cost}
    if k:
      hypers = population_lib.pbt_explore(hypers, rng,
                                          config.pbt_perturb)
    members.append({'member': k, 'suite': suite, 'hypers': hypers})

  base_config = dataclasses.replace(
      config, env_backend=suite, pbt_population=0, fleet_tasks='',
      pbt_vectorized=False)
  env_core = anakin_lib.make_env_core(base_config)
  agent = build_agent(base_config, env_core.num_actions)
  vstep = anakin_lib.make_vectorized_anakin_step(agent, env_core,
                                                 base_config)

  member_dirs = []
  member_configs = []
  checkpointers = []
  member_writers = []
  writer = None
  incidents = None
  slo_engine = None
  try:
    for k in range(n):
      member_dir = os.path.join(config.logdir, f'member_{k:02d}')
      os.makedirs(member_dir, exist_ok=True)
      member_config = dataclasses.replace(
          base_config, logdir=member_dir,
          seed=config.seed + 101 * k + 1,
          learning_rate=members[k]['hypers']['learning_rate'],
          entropy_cost=members[k]['hypers']['entropy_cost'])
      with open(os.path.join(member_dir, 'config.json'), 'w') as f:
        json.dump(dataclasses.asdict(member_config), f, indent=2,
                  sort_keys=True)
      member_dirs.append(member_dir)
      member_configs.append(member_config)
      checkpointers.append(checkpoint_lib.Checkpointer(
          os.path.join(member_dir, 'checkpoints'),
          save_interval_secs=config.checkpoint_secs,
          verify_digests=config.ckpt_digests,
          registry=sharding_lib.from_config(member_config)))
      member_writers.append(observability.SummaryWriter(member_dir))

    # Per-member init (each member's own PRNG stream — same seed
    # recipe as the serial member spin-up), per-member restore
    # through its own ladder, then ONE stacked carry.
    carries = []
    for k in range(n):
      carry_k = anakin_lib.init_carry(
          agent, env_core, base_config,
          jax.random.PRNGKey(member_configs[k].seed))
      restored = checkpointers[k].restore_latest(carry_k.train_state)
      if restored is not None:
        carry_k = carry_k._replace(train_state=restored)
        log.info('member %d: restored checkpoint at step %d', k,
                 int(jax.device_get(restored.update_steps)))
      carries.append(carry_k)
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *carries)
    del carries

    writer = observability.SummaryWriter(config.logdir)
    incidents = observability.EventLog(config.logdir)
    lock_check.set_incident_sink(incidents.event)
    with open(os.path.join(config.logdir, 'config.json'), 'w') as f:
      json.dump(dataclasses.asdict(config), f, indent=2,
                sort_keys=True)
    fps_meter = observability.FpsMeter()
    if config.slo_engine:
      slo_objectives = slo_lib.load_objectives(
          config.slo_spec,
          fast_window_secs=config.slo_fast_window_secs,
          slow_window_secs=config.slo_slow_window_secs)
      slo_interval = (config.slo_interval_secs
                      if config.slo_interval_secs > 0 else
                      min(max(float(config.summary_secs), 1.0), 30.0,
                          config.slo_fast_window_secs / 4.0))
      slo_engine = slo_lib.SloEngine(
          slo_objectives, config.logdir, writer=writer,
          incidents=incidents, flight=None, health=None,
          capture=config.slo_capture, interval_secs=slo_interval,
          baseline=slo_lib.load_baseline(config.slo_fps_baseline))
      slo_engine.start()
  except BaseException:
    for w in member_writers:
      w.close()
    for c in checkpointers:
      c.close()
    if slo_engine is not None:
      slo_engine.stop()
    if writer is not None:
      writer.close()
    if incidents is not None:
      lock_check.set_incident_sink(None)
      incidents.close()
    raise

  pop_path = os.path.join(config.logdir, 'population_summaries.jsonl')
  pop_stats: Dict[str, float] = {'exploits': 0.0}
  pop_gauges: List = []

  def _ensure_gauges():
    if pop_gauges:
      return
    pop_gauges.extend([
        telemetry.gauge(
            'population/task_return_min',
            fn=lambda: pop_stats.get('task_return_min', 0.0)),
        telemetry.gauge(
            'population/best_return',
            fn=lambda: pop_stats.get('best_return', 0.0)),
        telemetry.gauge(
            'population/exploits_total',
            fn=lambda: pop_stats.get('exploits', 0.0)),
    ])

  pbt_log = {'population': n, 'suites': suite_list,
             'round_frames': round_frames, 'num_rounds': num_rounds,
             'quantile': config.pbt_quantile,
             'perturb': config.pbt_perturb, 'vectorized': True,
             'rounds': [], 'winner': None}

  def _write_pbt_log():
    path = os.path.join(config.logdir, 'PBT_LOG.json')
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
      json.dump(pbt_log, f, indent=2, sort_keys=True)
    os.replace(tmp, path)

  _initial_steps = int(np.max(np.asarray(
      jax.device_get(stacked.train_state.update_steps))))
  steps_done = 0
  frames_per_step = config.frames_per_step
  _loop_gauges = [
      telemetry.gauge('driver/update_steps',
                      fn=lambda: steps_done + _initial_steps),
      telemetry.gauge('driver/env_frames',
                      fn=lambda: (steps_done + _initial_steps) *
                      frames_per_step * n),
      telemetry.gauge('driver/env_plane_utilization', fn=lambda: 1.0),
      telemetry.gauge('driver/learner_plane_utilization',
                      fn=lambda: 1.0),
  ]

  def _hyp_arrays():
    return {
        'learning_rate': jnp.asarray(
            [m['hypers']['learning_rate'] for m in members],
            jnp.float32),
        'entropy_cost': jnp.asarray(
            [m['hypers']['entropy_cost'] for m in members],
            jnp.float32),
    }

  def _flush_members(pending):
    step_f, (keys, stacked_vals) = pending
    vals = np.asarray(jax.device_get(stacked_vals))  # [keys, N]
    for k in range(n):
      member_writers[k].scalars(
          {key: float(vals[i, k]) for i, key in enumerate(keys)},
          step_f)

  returns = [0.0] * n
  scored = False
  pending_metrics = None
  prev_metrics = None
  loop_start = time.monotonic()
  last_summary = loop_start
  try:
    for r in range(num_rounds):
      if drain_event is not None and drain_event.is_set():
        break
      target = min((r + 1) * round_frames,
                   config.total_environment_frames)
      hyp = _hyp_arrays()
      round_steps = 0
      while True:
        if drain_event is not None and drain_event.is_set():
          incidents.event('anakin_stop_requested',
                          step=_initial_steps + steps_done, round=r)
          break
        if (_initial_steps + steps_done) * frames_per_step >= target:
          break
        if max_steps is not None and round_steps >= max_steps:
          break
        if (max_seconds is not None and
            time.monotonic() - loop_start > max_seconds):
          break
        stacked, metrics = vstep(stacked, hyp)
        steps_done += 1
        round_steps += 1
        step_now = _initial_steps + steps_done
        fps_meter.update(frames_per_step * n)
        prev_metrics = pending_metrics
        pending_metrics = (step_now,
                           observability.stack_metrics(metrics))
        now = time.monotonic()
        if now - last_summary >= config.summary_secs:
          last_summary = now
          _flush_members(prev_metrics if prev_metrics is not None
                         else pending_metrics)
          writer.scalar('env_frames_per_sec', fps_meter.fps(),
                        step_now)
          if slo_engine is not None:
            slo_engine.observe()
      # Round boundary: flush the freshest metrics so the scoring
      # pass below reads THIS round's tail, then score/decide.
      if pending_metrics is not None:
        _flush_members(pending_metrics)
      for k in range(n):
        returns[k] = _member_return(member_dirs[k])
        row = {'wall_time': round(time.time(), 3), 'round': r,
               'member': k, 'suite': suite, 'frames': target,
               'mean_return': returns[k]}
        row.update({f'hyper_{h}': float(v)
                    for h, v in sorted(members[k]['hypers'].items())})
        with open(pop_path, 'a') as f:
          f.write(json.dumps(row, sort_keys=True) + '\n')
      scored = True
      pop_stats['task_return_min'] = min(returns)
      pop_stats['best_return'] = max(returns)
      _ensure_gauges()
      writer.scalar('population/task_return_min',
                    pop_stats['task_return_min'], target)
      writer.scalar('population/best_return',
                    pop_stats['best_return'], target)

      round_rec = {'round': r, 'target_frames': target,
                   'returns': list(returns),
                   'suites': [suite] * n,
                   'hypers': [dict(m['hypers']) for m in members],
                   'decisions': []}
      final_round = (r == num_rounds - 1 or
                     (drain_event is not None and
                      drain_event.is_set()))
      if not final_round:
        decisions = population_lib.pbt_decide(
            returns, [suite] * n, rng,
            quantile=config.pbt_quantile,
            perturb=config.pbt_perturb,
            hypers=[m['hypers'] for m in members])
        for k, decision in enumerate(decisions):
          if decision is None:
            continue
          donor = decision['donor']
          # On-device weight inheritance: a stacked-index copy of
          # the donor's train-state slice over the loser's — the
          # r22 rmtree+copytree became one device op. (Only the
          # train state transfers; the loser keeps its own env
          # stream, exactly like the serial path, where inheritance
          # never touched env state either.)
          stacked = stacked._replace(
              train_state=jax.tree_util.tree_map(
                  lambda x: x.at[k].set(x[donor]),
                  stacked.train_state))
          members[k]['hypers'] = dict(decision['hypers'])
          pop_stats['exploits'] += 1.0
          incidents.event(
              'pbt_exploit', step=target, round=r, member=k,
              donor=donor, suite=suite,
              member_return=returns[k], donor_return=returns[donor],
              hypers=decision['hypers'])
          log.info('pbt round %d: member %d (return %.3f) exploits '
                   'member %d (return %.3f), new hypers %s '
                   '[on-device]', r, k, returns[k], donor,
                   returns[donor], decision['hypers'])
          round_rec['decisions'].append(dict(decision, member=k))
      writer.scalar('population/exploits_total',
                    pop_stats['exploits'], target)
      # Durable decision record: every member's slice lands in its
      # OWN ladder after exploits, so the round's outcome (inherited
      # weights included) survives this process — any member dir
      # resumes, fused or serial.
      for k in range(n):
        checkpointers[k].save(
            jax.tree_util.tree_map(lambda x: x[k],
                                   stacked.train_state),
            force=True)
      pbt_log['rounds'].append(round_rec)
      _write_pbt_log()

    if scored:
      winner = int(np.argmax(returns))
      pbt_log['winner'] = {
          'member': winner, 'suite': suite,
          'return': returns[winner],
          'hypers': dict(members[winner]['hypers']),
          'logdir': member_dirs[winner]}
      _write_pbt_log()
      incidents.event('pbt_winner', member=winner, suite=suite,
                      final_return=returns[winner],
                      hypers=members[winner]['hypers'])
      log.info('pbt winner: member %d (%s) return %.3f hypers %s '
               '[vectorized]', winner, suite, returns[winner],
               members[winner]['hypers'])
      return TrainRun(
          member_configs[winner], agent,
          jax.tree_util.tree_map(lambda x: x[winner],
                                 stacked.train_state),
          None, None, None, checkpointers[winner],
          member_writers[winner], None, fps_meter)
    raise RuntimeError('population run trained no member (drained '
                       'before the first round scored?)')
  finally:
    exiting_clean = sys.exc_info()[0] is None
    if slo_engine is not None:
      try:
        slo_engine.stop()
        verdict = slo_engine.finalize(
            os.path.join(config.logdir, 'SLO_VERDICT.json'),
            extra={'clean_exit': exiting_clean,
                   'update_steps': _initial_steps + steps_done,
                   'runtime': 'anakin', 'vectorized': True,
                   'population': n})
        (log.info if verdict['pass'] else log.warning)(
            'SLO verdict: %s (%d objective(s), violations: %s)',
            'PASS' if verdict['pass'] else 'FAIL',
            len(verdict['objectives']),
            verdict['violations'] or 'none')
      except Exception:
        log.exception('SLO verdict write failed')
    for gauge in _loop_gauges + pop_gauges:
      telemetry.registry().unregister(gauge.name, gauge)
    for c in checkpointers:
      c.close()
    for w in member_writers:
      w.close()
    writer.close()
    lock_check.set_incident_sink(None)
    incidents.close()


def train_population(config: Config, max_steps: Optional[int] = None,
                     max_seconds: Optional[float] = None,
                     drain_event: Optional[threading.Event] = None
                     ) -> TrainRun:
  """Population-based training over Anakin learner replicas (round
  22, PBT arXiv 1711.09846): ONE driver invocation trains
  `pbt_population` members — each a full train_anakin run in
  `<logdir>/member_<k>` with its own checkpoint ladder, summaries,
  and SLO verdict — suites assigned round-robin from
  `resolved_pbt_suites`, hypers (learning_rate, entropy_cost)
  exploit/explored between rounds.

  The schedule is round-synchronous and sequential on this host: each
  round extends every member's frame budget by
  `resolved_pbt_round_frames` (members RESUME from their own verified
  checkpoints — the round boundary is just a host-side pause), then
  the process-0-owned decision loop ranks WITHIN each suite
  (cross-suite returns are not commensurable), and bottom-quantile
  members inherit a donor's weights by copying its `checkpoints/`
  directory through the PR 2 ladder — the loser's next restore
  re-verifies the donor's content digests, so a torn copy is refused,
  not trained on. Every exploit lands as a DURABLE `pbt_exploit`
  incident (donor, returns, explored hypers) — the provenance chain
  RUNBOOK.md's "which replica won and why" walks backwards.

  Artifacts in the parent logdir: `population_summaries.jsonl` (one
  row per member per round: suite, frames, mean return, live hypers —
  the per-task return curves), `PBT_LOG.json` (the full decision
  history + final winner), and `summaries.jsonl` population/* scalars
  feeding the `per_task_return_floor` SLO objective via the
  population/* gauges (registered after the first scoring pass; other
  runs see no_data, never a violation).

  `max_steps`/`max_seconds` bound each MEMBER run (the test seam);
  `drain_event` stops cleanly at the next member/round boundary.
  Returns the winning member's TrainRun.
  """
  for warning in validate_population(config):
    log.warning('%s', warning)
  if config.pbt_population < 2:
    raise ValueError(f'train_population needs pbt_population >= 2, '
                     f'got {config.pbt_population}')
  if config.pbt_vectorized:
    # Round 23: the fused path — one vmapped program advances every
    # member in lockstep. Single-device members only: a model-axis
    # mesh degrades to the serial loop (validate_population already
    # warned).
    if config.model_parallelism <= 1:
      return _train_population_fused(config, max_steps=max_steps,
                                     max_seconds=max_seconds,
                                     drain_event=drain_event)
    log.warning('pbt_vectorized ignored (model_parallelism=%d): '
                'running the serial member loop',
                config.model_parallelism)
  suite_list = list(config.resolved_pbt_suites)
  n = config.pbt_population
  round_frames = config.resolved_pbt_round_frames
  num_rounds = max(
      1, -(-config.total_environment_frames // round_frames))
  os.makedirs(config.logdir, exist_ok=True)
  incidents = observability.EventLog(config.logdir)
  writer = observability.SummaryWriter(config.logdir)
  pop_path = os.path.join(config.logdir, 'population_summaries.jsonl')
  rng = np.random.default_rng(config.seed)

  # Member 0 carries the configured hypers unperturbed (the "control"
  # arm); the rest start from an explored neighborhood so round 0
  # already has diversity to select over.
  members = []
  for k in range(n):
    hypers = {'learning_rate': config.learning_rate,
              'entropy_cost': config.entropy_cost}
    if k:
      hypers = population_lib.pbt_explore(hypers, rng,
                                          config.pbt_perturb)
    members.append({'member': k, 'suite': suite_list[k % len(suite_list)],
                    'hypers': hypers})

  pop_stats: Dict[str, float] = {'exploits': 0.0}
  pop_gauges: List = []

  def _ensure_gauges():
    # Registered lazily AFTER the first scoring pass: an objective
    # over an absent gauge evaluates no_data (never violates), while
    # a gauge registered before any member has a return would judge a
    # placeholder. Member SLO engines from round 1 on DO see these
    # (same process, same registry) — that is the point: the
    # per-task floor is judged while the population still trains.
    if pop_gauges:
      return
    pop_gauges.extend([
        telemetry.gauge(
            'population/task_return_min',
            fn=lambda: pop_stats.get('task_return_min', 0.0)),
        telemetry.gauge(
            'population/best_return',
            fn=lambda: pop_stats.get('best_return', 0.0)),
        telemetry.gauge(
            'population/exploits_total',
            fn=lambda: pop_stats.get('exploits', 0.0)),
    ])

  pbt_log = {'population': n, 'suites': suite_list,
             'round_frames': round_frames, 'num_rounds': num_rounds,
             'quantile': config.pbt_quantile,
             'perturb': config.pbt_perturb, 'vectorized': False,
             'rounds': [], 'winner': None}

  def _write_pbt_log():
    path = os.path.join(config.logdir, 'PBT_LOG.json')
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
      json.dump(pbt_log, f, indent=2, sort_keys=True)
    os.replace(tmp, path)

  runs: Dict[int, TrainRun] = {}
  # Round 23: in-process weight inheritance. An exploited loser's
  # next spin-up starts from this device pytree instead of its own
  # checkpoint — no filesystem round trip, no window where its
  # ladder is gone.
  inherit: Dict[int, object] = {}
  returns = [0.0] * n
  try:
    for r in range(num_rounds):
      if drain_event is not None and drain_event.is_set():
        break
      target = min((r + 1) * round_frames,
                   config.total_environment_frames)
      for m in members:
        if drain_event is not None and drain_event.is_set():
          break
        k = m['member']
        member_dir = os.path.join(config.logdir, f'member_{k:02d}')
        member_config = dataclasses.replace(
            config,
            logdir=member_dir,
            # Distinct, round-stable env/init seed per member; params
            # beyond round 0 come from the member's own checkpoint.
            seed=config.seed + 101 * k + 1,
            env_backend=m['suite'],
            total_environment_frames=target,
            learning_rate=m['hypers']['learning_rate'],
            entropy_cost=m['hypers']['entropy_cost'],
            # Members are plain anakin runs: no recursive population,
            # no fleet-runtime task mixing.
            pbt_population=0,
            fleet_tasks='')
        runs[k] = train_anakin(member_config, max_steps=max_steps,
                               max_seconds=max_seconds,
                               drain_event=drain_event,
                               initial_state=inherit.pop(k, None))
        returns[k] = _member_return(member_dir)
        row = {'wall_time': round(time.time(), 3), 'round': r,
               'member': k, 'suite': m['suite'], 'frames': target,
               'mean_return': returns[k]}
        row.update({f'hyper_{h}': float(v)
                    for h, v in sorted(m['hypers'].items())})
        with open(pop_path, 'a') as f:
          f.write(json.dumps(row, sort_keys=True) + '\n')

      group_labels = [m['suite'] for m in members]
      per_suite_best = {
          s: max(returns[i] for i in range(n)
                 if group_labels[i] == s)
          for s in suite_list}
      pop_stats['task_return_min'] = min(per_suite_best.values())
      pop_stats['best_return'] = max(returns)
      _ensure_gauges()
      writer.scalar('population/task_return_min',
                    pop_stats['task_return_min'], target)
      writer.scalar('population/best_return',
                    pop_stats['best_return'], target)

      round_rec = {'round': r, 'target_frames': target,
                   'returns': list(returns),
                   'suites': list(group_labels),
                   'hypers': [dict(m['hypers']) for m in members],
                   'decisions': []}
      final_round = (r == num_rounds - 1 or
                     (drain_event is not None and
                      drain_event.is_set()))
      if not final_round:
        # Exploit/explore only when another round will train on the
        # result — mutating weights after the last round would ship
        # an inherited-but-untrained population.
        decisions = population_lib.pbt_decide(
            returns, group_labels, rng,
            quantile=config.pbt_quantile,
            perturb=config.pbt_perturb,
            hypers=[m['hypers'] for m in members])
        for k, decision in enumerate(decisions):
          if decision is None:
            continue
          donor = decision['donor']
          if donor in runs:
            # On-device inheritance (round 23): the donor trained in
            # THIS process, so its final state is already a device
            # pytree — deep-copy it (the loser's fused step donates
            # its carry; an aliased buffer would invalidate the
            # donor's state and any sibling inheriting it too) and
            # hand it to the loser's next spin-up. The loser's own
            # ladder then records the inherited-and-trained state at
            # the normal save cadence — durable, without a
            # serialize/deserialize round trip per exploit.
            inherit[k] = jax.tree_util.tree_map(
                lambda x: jnp.array(x, copy=True), runs[donor].state)
          else:
            # Cross-process fallback: inherit through the checkpoint
            # ladder — the loser's next restore_latest re-verifies
            # the donor's content digests (a torn copy is refused,
            # not loaded), and the copy-then-swap helper never
            # leaves the loser without a ladder.
            src = os.path.join(config.logdir, f'member_{donor:02d}',
                               'checkpoints')
            dst = os.path.join(config.logdir, f'member_{k:02d}',
                               'checkpoints')
            if os.path.isdir(src):
              _inherit_member_dir(src, dst)
          members[k]['hypers'] = dict(decision['hypers'])
          pop_stats['exploits'] += 1.0
          incidents.event(
              'pbt_exploit', step=target, round=r, member=k,
              donor=donor, suite=members[k]['suite'],
              member_return=returns[k], donor_return=returns[donor],
              hypers=decision['hypers'])
          log.info('pbt round %d: member %d (return %.3f) exploits '
                   'member %d (return %.3f), new hypers %s', r, k,
                   returns[k], donor, returns[donor],
                   decision['hypers'])
          round_rec['decisions'].append(dict(decision, member=k))
      writer.scalar('population/exploits_total', pop_stats['exploits'],
                    target)
      pbt_log['rounds'].append(round_rec)
      _write_pbt_log()

    if runs:
      winner = max(runs, key=lambda k: returns[k])
      pbt_log['winner'] = {
          'member': winner, 'suite': members[winner]['suite'],
          'return': returns[winner],
          'hypers': dict(members[winner]['hypers']),
          'logdir': os.path.join(config.logdir,
                                 f'member_{winner:02d}')}
      _write_pbt_log()
      incidents.event('pbt_winner', member=winner,
                      suite=members[winner]['suite'],
                      final_return=returns[winner],
                      hypers=members[winner]['hypers'])
      log.info('pbt winner: member %d (%s) return %.3f hypers %s',
               winner, members[winner]['suite'], returns[winner],
               members[winner]['hypers'])
      return runs[winner]
    raise RuntimeError('population run trained no member (drained '
                       'before the first member run?)')
  finally:
    for gauge in pop_gauges:
      telemetry.registry().unregister(gauge.name, gauge)
    writer.close()
    incidents.close()


def evaluate(config: Config,
             stall_timeout_secs: Optional[float] = 300.0,
             eval_drought_secs: float = 600.0
             ) -> Dict[str, List[float]]:
  """Play test_num_episodes per level from the latest checkpoint.

  Returns {train_level_name: [episode returns]}; logs DMLab-30
  human-normalized scores in multi-task mode (reference test()
  ≈L595–630: SingularMonitoredSession restore + done[1:] extraction).

  TPU re-design over the reference: instead of stepping levels one by
  one at batch 1, ALL levels evaluate concurrently — one env+actor per
  test level feeding the same dynamic batcher, so the chip sees merged
  inference batches (30× fewer serialized device round trips on
  DMLab-30).

  Multi-host: test levels PARTITION across processes (contiguous
  slices — each host plays only its share through its local sharded
  batcher), per-level returns allgather at the end, and only process 0
  computes scores and writes the single `eval_summaries.jsonl`
  (VERDICT r3 W2: previously every process duplicated the entire
  benchmark and wrote divergent score files). Every process returns
  the same combined dict.

  Inference compiles exactly ONE padded bucket (`pad_batch_to`): all
  of this host's levels step concurrently, so merged batches converge
  to one size anyway, and warming every power-of-two bucket cost 6
  serial 20–40 s compiles on dmlab30 before the first episode
  (VERDICT r3 W5).
  """
  from scalable_agent_tpu.parallel import distributed
  # Same contract as train(): validate the declared topology BEFORE
  # the join (crisp ValueError, not a hung initialization window).
  for warning in validate_distributed(config):
    log.warning('%s', warning)
  # Every validate_* knob group runs on the eval path too (round 18,
  # the validate-coverage lint): a hard range/enum error must fail an
  # eval exactly like a train — before this, a bad replay/transport/
  # SLO knob passed eval spin-up silently and only exploded (or was
  # silently ignored) once the same config reached train.
  for group_warnings in (validate_replay(config),
                         validate_transport(config),
                         validate_integrity(config),
                         validate_slo(config),
                         validate_controller(config),
                         validate_runtime(config),
                         validate_serving(config),
                         validate_population(config)):
    for warning in group_warnings:
      log.warning('%s', warning)
  distributed.maybe_initialize(config)
  train_levels = factory.level_names(config)
  test_levels = factory.test_level_names(config)
  num_procs = jax.process_count()
  pidx = jax.process_index()
  num_test = len(test_levels)
  base_count, rem = divmod(num_test, num_procs)
  counts = [base_count + (i < rem) for i in range(num_procs)]
  start = sum(counts[:pidx])
  my_count = counts[pidx]
  my_ids = list(range(start, start + my_count))
  if num_procs > 1:
    log.info('eval process %d/%d plays levels [%d, %d) of %d', pidx,
             num_procs, start, start + my_count, num_test)
  spec0 = factory.make_env_spec(config, test_levels[0], seed=1,
                                is_test=True)
  agent = build_agent(config, spec0.num_actions,
                      num_tasks=len(train_levels))
  params = init_params(agent, jax.random.PRNGKey(config.seed),
                       spec0.obs_spec)

  checkpointer = checkpoint_lib.Checkpointer(
      config.logdir + '/checkpoints')
  # Params-only restore: eval never materializes the RMSProp moments
  # (≈2× params) — see Checkpointer.restore_latest_params. The manager
  # closes on the raise path too (structure-mismatch guidance lives in
  # checkpoint._wrap_structure_error).
  try:
    restored = checkpointer.restore_latest_params(
        params,
        lambda p: learner_lib.make_train_state(
            p, config, len(train_levels) if config.use_popart else 0))
  finally:
    checkpointer.close()
  if restored is None:
    raise FileNotFoundError(
        f'no checkpoint under {config.logdir}/checkpoints')
  params, restored_steps = restored
  if num_procs > 1:
    # Restored leaves carry the checkpoint's GLOBAL placements (train
    # meshes span hosts — and Orbax may fall back to the sharding
    # recorded in the file). Eval inference is host-local, so localize
    # to host values first: a direct device_put of globally placed
    # leaves onto the local eval mesh is a cross-host transfer, which
    # CPU/gloo backends reject outright. Collective — every process
    # passes through here before its play phase.
    params = multihost_utils.process_allgather(params, tiled=True)

  level_returns: Dict[str, List[float]] = {
      name: [] for name in train_levels}

  def stats_view(unroll):
    """Single-unroll [T+1, 1] view — no frame stacking."""
    expand = lambda x: np.asarray(x)[:, None]  # noqa: E731
    return _stats_only_view(
        np.asarray([unroll.level_name]),
        jax.tree_util.tree_map(expand, unroll.env_outputs.info),
        expand(unroll.env_outputs.done))

  # A process with no assigned levels (more hosts than test levels)
  # skips the play phase but still joins the allgather below.
  if my_count > 0:
    # Same setup-failure guard as train(): a make_fleet raise (env
    # construction) must not leak the warmed inference server.
    server = None
    fleet = None
    try:
      # No fleet_size here: the auto merge FLOOR (inference_min_batch
      # =0) must not apply to eval — levels retire as their episodes
      # finish, so the caller count shrinks PERMANENTLY below the
      # floor and the tail would step one timeout per batch
      # (reintroducing the W5 tail stalls pad_batch_to eliminated).
      # pad_batch_to keeps the single-compile property either way.
      server = InferenceServer(agent, params, config,
                               seed=config.seed + 2000,
                               mesh=_choose_eval_mesh(),
                               pad_batch_to=my_count)
      server.warmup(spec0.obs_spec, max_size=my_count)
      buffer = ring_buffer.TrajectoryBuffer(max(2 * my_count, 2))
      # level_offset keeps level ids GLOBAL (actor i plays
      # test_levels[start + i] and stamps that id on its unrolls);
      # seed_base offsets by start so env streams stay disjoint
      # across processes.
      # Eval acquisitions carry the EVAL admission class: on a shared
      # or constrained state arena, eval churn parks behind live
      # traffic instead of starving it (the fleet's priority kwarg is
      # accepted and overridden — every eval acquire is eval-class).
      fleet = make_fleet(
          config, agent, server.policy, buffer,
          test_levels,
          seed_base=config.seed - 1 + start,
          level_offset=start, is_test=True,
          num_actors=my_count,
          initial_state_fn=lambda priority=None:
              server.initial_core_state(
                  priority=inference_lib.PRIORITY_EVAL))
    except BaseException:
      if server is not None:
        server.close()
      raise

    try:
      fleet.start()
      last_unroll_time = time.monotonic()
      errors: List[BaseException] = []
      while any(len(level_returns[train_levels[i]])
                < config.test_num_episodes for i in my_ids):
        try:
          unroll = buffer.get(timeout=10)
        except TimeoutError:
          # Read errors BEFORE check_health — a respawn clears the
          # slot's error, and a crash-looping actor's root cause must
          # survive to the drought raise below.
          errors = fleet.errors() or errors
          # Detect dead AND stalled actors (a wedged env whose thread
          # is alive would otherwise spin this loop forever while
          # healthy levels keep producing).
          fleet.check_health(stall_timeout_secs=stall_timeout_secs)
          if time.monotonic() - last_unroll_time > eval_drought_secs:
            raise errors[0] if errors else TimeoutError(
                f'eval produced no unrolls for {eval_drought_secs}s')
          continue
        except ring_buffer.Closed:
          errors = fleet.errors() or errors
          raise errors[0] if errors else ring_buffer.Closed()
        last_unroll_time = time.monotonic()
        errors = []  # recovered; see train()
        for level_id, ep_return, _ in observability.extract_episodes(
            stats_view(unroll)):
          level_returns[train_levels[level_id]].append(ep_return)
        fleet.check_health(stall_timeout_secs=stall_timeout_secs)
    finally:
      fleet.stop()
      server.close()

  if num_procs > 1:
    # Aggregate per-level returns: a dense [L, E] matrix (NaN = not
    # played here) allgathers to [P, L, E]; each level's row is taken
    # from its OWNER process. Every process computes the same combined
    # dict; only process 0 writes/scoring below.
    episodes = config.test_num_episodes
    mat = np.full((num_test, episodes), np.nan, np.float32)
    for lid in my_ids:
      rets = level_returns[train_levels[lid]][:episodes]
      mat[lid, :len(rets)] = rets
    gathered = np.asarray(multihost_utils.process_allgather(mat))
    owner = np.repeat(np.arange(num_procs), counts)
    for lid in range(num_test):
      row = gathered[owner[lid], lid]
      level_returns[train_levels[lid]] = [
          float(x) for x in row if not np.isnan(x)]

  if pidx != 0:
    return {name: returns[:config.test_num_episodes]
            for name, returns in level_returns.items()}

  writer = observability.SummaryWriter(config.logdir,
                                       filename='eval_summaries.jsonl')
  step = restored_steps
  for train_name, test_name in zip(train_levels, test_levels):
    returns = level_returns[train_name][:config.test_num_episodes]
    level_returns[train_name] = returns
    mean_return = float(np.mean(returns)) if returns else float('nan')
    log.info('level %s: mean return %.2f over %d episodes', test_name,
             mean_return, len(returns))
    writer.scalar(f'{test_name}/test_episode_return', mean_return,
                  step)

  if config.level_name in suites.SUITES:
    scores = suites.SUITES[config.level_name].eval_scores(level_returns)
    log.info('%s human-normalized: %s', config.level_name,
             ' '.join(f'{t.split("/")[-1]}={v:.1f}'
                      for t, v in scores.items()))
    for tag, value in scores.items():
      writer.scalar(tag, value, step)
  writer.close()
  return level_returns
