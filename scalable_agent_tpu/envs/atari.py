"""Atari (ALE) environment adapter — the reference's "swap-in env"
workload (SURVEY §0: Atari-style via swap-in env; BASELINE.json config
ladder). Import-guarded: no ALE ROMs ship in this sandbox.

The adapter keeps the SAME observation contract as the DMLab path
(frame uint8 [H, W, 3] + instruction ids, here empty) so every other
layer — actor, batcher, learner, models — is env-agnostic. Standard
DQN/IMPALA-style preprocessing is done host-side in pure numpy
(testable without ALE):

- action repeat with max-pool over the last two raw frames (flicker
  removal),
- nearest-neighbor resize to (height, width) in uint8,
- random no-op starts (≤30) at episode begin,
- auto-reset on game over (done=True returns the next episode's first
  frame, matching envs/base.py).

Backends tried in order: `ale_py` (canonical), then gymnasium's
ALE registration.
"""

from typing import Optional, Tuple

import numpy as np

from scalable_agent_tpu.envs import base
from scalable_agent_tpu.models.instruction import (
    empty_instruction, MAX_INSTRUCTION_LEN)

DEFAULT_NUM_ACTIONS = 18  # full ALE action set
DEFAULT_NOOP_MAX = 30


def resize_uint8(frame: np.ndarray, height: int, width: int
                 ) -> np.ndarray:
  """Nearest-neighbor resize of an [H, W, C] uint8 frame (pure numpy —
  no cv2/PIL dependency on the actor hot path)."""
  in_h, in_w = frame.shape[:2]
  rows = (np.arange(height) * in_h // height).astype(np.intp)
  cols = (np.arange(width) * in_w // width).astype(np.intp)
  return frame[rows[:, None], cols[None, :]]


def pooled_frame(last_two: Tuple[np.ndarray, np.ndarray]
                 ) -> np.ndarray:
  """Pixel-wise max over the last two raw frames (flicker removal)."""
  a, b = last_two
  return np.maximum(a, b)


class AtariEnv(base.Environment):
  """One ALE game behind the host env protocol."""

  def __init__(self, game: str, seed: int, height: int = 72,
               width: int = 96, num_action_repeats: int = 4,
               noop_max: int = DEFAULT_NOOP_MAX,
               full_action_set: bool = True, is_test: bool = False,
               num_actions: Optional[int] = None,
               sticky_action_prob: float = 0.0,
               ale: Optional[object] = None):
    """`ale` injects a backend (testing); otherwise ale_py/gymnasium.

    sticky_action_prob: per-FRAME probability that the previous
    executed action repeats instead of the policy's (Machado et al.
    2018 evaluation protocol, ς = 0.25). Implemented host-side in the
    adapter — backends run with their own stochastic repeat disabled —
    so it is deterministic under the env seed and testable without
    ALE. 0.0 (default) matches the reference-era deterministic
    protocol.

    is_test does NOT disable no-op starts: the random-≤30-no-op
    regime is the ALE *evaluation* protocol (DQN/IMPALA-era scores
    are reported under it) — without it a deterministic ALE would
    replay near-identical eval episodes. It is accepted for API
    symmetry with the DMLab adapter (whose test mode switches
    holdout levels/mixerSeed).
    """
    self._h, self._w = height, width
    self._num_action_repeats = num_action_repeats
    self._noop_max = noop_max
    self._sticky_prob = float(sticky_action_prob)
    if not 0.0 <= self._sticky_prob <= 1.0:
      # Fail fast: e.g. 25 meant-as-percent would otherwise make
      # every frame repeat NOOP forever, silently degenerate training.
      raise ValueError(
          f'sticky_action_prob={sticky_action_prob} not in [0, 1]')
    self._prev_exec_action = 0  # NOOP until the first step
    self._rng = np.random.RandomState(seed)
    self._instr = empty_instruction()
    self._ale = ale if ale is not None else _make_ale(
        game, self._rng.randint(0, 2 ** 31 - 1), full_action_set)
    self._actions = self._ale.action_set()
    if num_actions is not None and num_actions != len(self._actions):
      # Fail fast: a policy head sized differently from the backend's
      # action set would silently alias actions (e.g. num_actions=18
      # against a minimal set) and corrupt the policy/env
      # correspondence.
      raise ValueError(
          f'num_actions={num_actions} but the {game!r} backend exposes '
          f'{len(self._actions)} actions '
          f'(full_action_set={full_action_set})')
    self._reset()

  def _reset(self):
    self._ale.reset()
    self._prev_exec_action = 0  # stickiness does not cross episodes
    for _ in range(self._rng.randint(self._noop_max + 1)
                   if self._noop_max else 0):
      self._ale.act(0)  # NOOP
      if self._ale.game_over():
        self._ale.reset()
    self._raw = self._ale.screen_rgb()
    self._prev_raw = self._raw

  def _observation(self):
    frame = resize_uint8(pooled_frame((self._prev_raw, self._raw)),
                         self._h, self._w)
    return (frame, self._instr.copy())

  def initial(self):
    return self._observation()

  def step(self, action):
    a = int(action)
    if not 0 <= a < len(self._actions):
      # Python negative indexing would silently alias to the end of
      # the action set; out-of-range must raise either way.
      raise IndexError(
          f'action {a} outside [0, {len(self._actions)})')
    raw_action = self._actions[a]
    reward = 0.0
    for _ in range(self._num_action_repeats):
      if (self._sticky_prob and
          self._rng.random_sample() < self._sticky_prob):
        exec_action = self._prev_exec_action
      else:
        exec_action = raw_action
      self._prev_exec_action = exec_action
      reward += self._ale.act(exec_action)
      self._prev_raw = self._raw
      self._raw = self._ale.screen_rgb()
      if self._ale.game_over():
        break
    done = self._ale.game_over()
    if done:
      self._reset()
    return (np.float32(reward), np.bool_(done), self._observation())

  def close(self):
    pass

  @staticmethod
  def _tensor_specs(method_name, unused_kwargs, constructor_kwargs):
    h = constructor_kwargs.get('height', 72)
    w = constructor_kwargs.get('width', 96)
    if method_name == 'initial':
      return base.observation_specs(h, w, MAX_INSTRUCTION_LEN)
    if method_name == 'step':
      return base.step_output_specs(h, w, MAX_INSTRUCTION_LEN)
    return None


class _AlePyBackend:
  """Thin uniform wrapper over ale_py.ALEInterface."""

  def __init__(self, game, seed, full_action_set):
    import ale_py
    self._ale = ale_py.ALEInterface()
    self._ale.setInt('random_seed', int(seed))
    self._ale.setFloat('repeat_action_probability', 0.0)
    self._ale.loadROM(ale_py.roms.get_rom_path(game))
    self._action_set = (self._ale.getLegalActionSet() if full_action_set
                        else self._ale.getMinimalActionSet())

  def action_set(self):
    return list(self._action_set)

  def reset(self):
    self._ale.reset_game()

  def act(self, action):
    return float(self._ale.act(action))

  def game_over(self):
    return bool(self._ale.game_over())

  def screen_rgb(self):
    return np.asarray(self._ale.getScreenRGB(), np.uint8)


# gymnasium registrations whose CamelCase is NOT capitalize-each-part
# (ADVICE r4: an irregular id would otherwise convert wrongly and only
# fail later inside gymnasium.make with a less obvious error). All 57
# suite ids are regular (verified); these are the known ALE extras.
_GYM_ID_OVERRIDES = {
    'tic_tac_toe_3d': 'TicTacToe3D',  # capitalize gives 'TicTacToe3d'
}


def gym_game_id(game: str) -> str:
  """Canonical snake_case rom id ('kung_fu_master', the envs/atari57.py
  convention) → gymnasium's CamelCase registration ('KungFuMaster').
  Already-CamelCase names pass through."""
  if game in _GYM_ID_OVERRIDES:
    return _GYM_ID_OVERRIDES[game]
  if '_' in game or game.islower():
    return ''.join(part.capitalize() for part in game.split('_'))
  return game


class _GymnasiumBackend:
  """Fallback over gymnasium's ALE envs (frameskip disabled — the
  adapter owns action repeat and pooling)."""

  def __init__(self, game, seed, full_action_set):
    import gymnasium
    self._env = gymnasium.make(
        f'ALE/{gym_game_id(game)}-v5', frameskip=1,
        repeat_action_probability=0.0,
        full_action_space=full_action_set, render_mode='rgb_array')
    self._seed = int(seed)
    self._frame = None
    self._over = True

  def action_set(self):
    return list(range(self._env.action_space.n))

  def reset(self):
    self._frame, _ = self._env.reset(seed=self._seed)
    self._seed = None  # seed only the first reset
    self._over = False

  def act(self, action):
    self._frame, reward, terminated, truncated, _ = self._env.step(
        action)
    self._over = bool(terminated or truncated)
    return float(reward)

  def game_over(self):
    return self._over

  def screen_rgb(self):
    return np.asarray(self._frame, np.uint8)


def _make_ale(game, seed, full_action_set):
  try:
    return _AlePyBackend(game, seed, full_action_set)
  except ImportError:
    pass
  try:
    return _GymnasiumBackend(game, seed, full_action_set)
  except Exception as e:  # gymnasium missing, or present without ROMs
    raise ImportError(
        f'no Atari backend available (ale_py missing, gymnasium ALE '
        f'failed: {e}); use --env_backend=fake/bandit in this sandbox'
    ) from e
