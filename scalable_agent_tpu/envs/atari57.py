"""Atari-57 benchmark metadata and human-normalized scoring.

Companion of `envs/dmlab30.py` for the Atari workload (SURVEY §0:
"Atari-style via swap-in env"; §6 cites the paper's Atari-57 headline,
median human-normalized score over the 57-game suite). The reference
repo itself ships only DMLab metadata (reference: dmlab30.py), so this
module is the Atari half of the same evaluation story: game list +
human/random anchor scores + the aggregate the papers report.

Conventions:
- Game names are ALE snake_case rom ids ('kung_fu_master'); the
  `envs/atari.py` adapter accepts them for both backends.
- The headline aggregate is the MEDIAN over games (DQN/IMPALA/Rainbow
  convention — the mean is dominated by a few games with huge
  human-relative ceilings); the mean is also provided.

Provenance caveat (same as dmlab30.py): the reference mount was empty
at build time and this sandbox has no network, so the anchor tables
below are reconstructed from the standard published table (Wang et al.
2016 "Dueling Network Architectures", Table 4 — the table IMPALA,
Rainbow, Ape-X and R2D2 all normalize against). Re-verify against the
published table before reporting any score from a real run
(docs/RUNBOOK.md makes this step mandatory).

Pure numpy; nothing here touches a device.
"""

from typing import Dict, Optional

import numpy as np

from scalable_agent_tpu.envs import anchors

# Provenance gate (see envs/anchors.py and the caveat above):
# 'reconstructed' until scripts/verify_anchors.py diffs the table
# against the published Wang et al. 2016 Table 4; scoring warns once
# per process while unverified and self-checks the pinned SHA-256.
ANCHOR_PROVENANCE = 'reconstructed'
ANCHOR_SHA256 = (
    'b57710f7f90fc73e5cd900d3c47278ac0bf9e4b1a70ae498de4eb8e374fa0987')

# game: (random_score, human_score) — Wang et al. 2016 Table 4 anchors.
_ANCHOR_SCORES = {
    'alien': (227.8, 7127.7),
    'amidar': (5.8, 1719.5),
    'assault': (222.4, 742.0),
    'asterix': (210.0, 8503.3),
    'asteroids': (719.1, 47388.7),
    'atlantis': (12850.0, 29028.1),
    'bank_heist': (14.2, 753.1),
    'battle_zone': (2360.0, 37187.5),
    'beam_rider': (363.9, 16926.5),
    'berzerk': (123.7, 2630.4),
    'bowling': (23.1, 160.7),
    'boxing': (0.1, 12.1),
    'breakout': (1.7, 30.5),
    'centipede': (2090.9, 12017.0),
    'chopper_command': (811.0, 7387.8),
    'crazy_climber': (10780.5, 35829.4),
    'defender': (2874.5, 18688.9),
    'demon_attack': (152.1, 1971.0),
    'double_dunk': (-18.6, -16.4),
    'enduro': (0.0, 860.5),
    'fishing_derby': (-91.7, -38.7),
    'freeway': (0.0, 29.6),
    'frostbite': (65.2, 4334.7),
    'gopher': (257.6, 2412.5),
    'gravitar': (173.0, 3351.4),
    'hero': (1027.0, 30826.4),
    'ice_hockey': (-11.2, 0.9),
    'jamesbond': (29.0, 302.8),
    'kangaroo': (52.0, 3035.0),
    'krull': (1598.0, 2665.5),
    'kung_fu_master': (258.5, 22736.3),
    'montezuma_revenge': (0.0, 4753.3),
    'ms_pacman': (307.3, 6951.6),
    'name_this_game': (2292.3, 8049.0),
    'phoenix': (761.4, 7242.6),
    'pitfall': (-229.4, 6463.7),
    'pong': (-20.7, 14.6),
    'private_eye': (24.9, 69571.3),
    'qbert': (163.9, 13455.0),
    'riverraid': (1338.5, 17118.0),
    'road_runner': (11.5, 7845.0),
    'robotank': (2.2, 11.9),
    'seaquest': (68.4, 42054.7),
    'skiing': (-17098.1, -4336.9),
    'solaris': (1236.3, 12326.7),
    'space_invaders': (148.0, 1668.7),
    'star_gunner': (664.0, 10250.0),
    'surround': (-10.0, 6.5),
    'tennis': (-23.8, -8.3),
    'time_pilot': (3568.0, 5229.2),
    'tutankham': (11.4, 167.6),
    'up_n_down': (533.4, 11693.2),
    'venture': (0.0, 1187.5),
    'video_pinball': (16256.9, 17667.9),
    'wizard_of_wor': (563.5, 4756.5),
    'yars_revenge': (3092.9, 54576.9),
    'zaxxon': (32.5, 9173.3),
}

ALL_GAMES = tuple(sorted(_ANCHOR_SCORES))

RANDOM_SCORES = {g: rh[0] for g, rh in _ANCHOR_SCORES.items()}
HUMAN_SCORES = {g: rh[1] for g, rh in _ANCHOR_SCORES.items()}


def per_game_human_normalized(game_returns: Dict[str, list],
                              per_game_cap: Optional[float] = None
                              ) -> Dict[str, float]:
  """Per-game `(mean_return - random) / (human - random) * 100`.

  Args:
    game_returns: game name -> list/array of episode returns. Every
      game in `ALL_GAMES` must be present and non-empty (same
      missing-levels contract as dmlab30.compute_human_normalized_score).
    per_game_cap: optional scalar clip applied above, per game.
  """
  anchors.check_provenance(
      'envs/atari57.py', ANCHOR_PROVENANCE, ANCHOR_SHA256,
      {'RANDOM_SCORES': RANDOM_SCORES, 'HUMAN_SCORES': HUMAN_SCORES})
  missing = [g for g in ALL_GAMES
             if g not in game_returns or len(game_returns[g]) == 0]
  if missing:
    raise ValueError(f'Missing returns for games: {missing}')
  scores = {}
  for game in ALL_GAMES:
    human, random = HUMAN_SCORES[game], RANDOM_SCORES[game]
    mean_return = float(np.mean(game_returns[game]))
    score = (mean_return - random) / (human - random) * 100.0
    if per_game_cap is not None:
      score = min(score, per_game_cap)
    scores[game] = score
  return scores


def compute_human_normalized_score(game_returns: Dict[str, list],
                                   per_game_cap: Optional[float] = None,
                                   aggregate: str = 'median') -> float:
  """Aggregate human-normalized score over the 57 games.

  `aggregate='median'` is the suite's headline number (the convention
  every Atari-57 paper reports); 'mean' is the dmlab30-style mean.
  """
  scores = np.asarray(
      list(per_game_human_normalized(game_returns, per_game_cap)
           .values()))
  if aggregate == 'median':
    return float(np.median(scores))
  if aggregate == 'mean':
    return float(np.mean(scores))
  raise ValueError(f'unknown aggregate {aggregate!r}')
