"""DMLab-30 benchmark metadata and human-normalized scoring.

TPU-native counterpart of the reference's task-metadata module
(reference: dmlab30.py — `LEVEL_MAPPING`, `HUMAN_SCORES`,
`RANDOM_SCORES`, `compute_human_normalized_score`). Pure numpy; nothing
here touches a device.

The 30-level table maps *training* level names to their *held-out test*
variants (only the two `rooms_*_train` levels differ). Human/random
anchor scores reproduce the published DMLab-30 calibration values
(IMPALA paper, arXiv:1802.01561, appendix). Provenance caveat: the
reference mount was empty at build time (see SURVEY.md) — the tables
below are reconstructed from the public benchmark definition; re-verify
against the reference if it becomes available.
"""

import collections

import numpy as np

from scalable_agent_tpu.envs import anchors

# Provenance of the anchor tables below (see module docstring and
# envs/anchors.py): 'reconstructed' until scripts/verify_anchors.py has
# diffed them against the upstream reference (dmlab30.py HUMAN_SCORES /
# RANDOM_SCORES / LEVEL_MAPPING) — it prints the edit that flips this
# to 'verified'. Scoring warns once per process while unverified.
ANCHOR_PROVENANCE = 'reconstructed'
# SHA-256 of the canonical table serialization (anchors.anchor_checksum)
# — pins the exact constants below against silent edits; scoring
# self-checks it (tests/test_anchors.py pins it too).
ANCHOR_SHA256 = (
    'fb874c63c1632dbd673b0ff0282805474fbffb627b9be7f8e5ca0f2edb393b7e')

LEVEL_MAPPING = collections.OrderedDict([
    ('rooms_collect_good_objects_train', 'rooms_collect_good_objects_test'),
    ('rooms_exploit_deferred_effects_train',
     'rooms_exploit_deferred_effects_test'),
    ('rooms_select_nonmatching_object', 'rooms_select_nonmatching_object'),
    ('rooms_watermaze', 'rooms_watermaze'),
    ('rooms_keys_doors_puzzle', 'rooms_keys_doors_puzzle'),
    ('language_select_described_object', 'language_select_described_object'),
    ('language_select_located_object', 'language_select_located_object'),
    ('language_execute_random_task', 'language_execute_random_task'),
    ('language_answer_quantitative_question',
     'language_answer_quantitative_question'),
    ('lasertag_one_opponent_small', 'lasertag_one_opponent_small'),
    ('lasertag_three_opponents_small', 'lasertag_three_opponents_small'),
    ('lasertag_one_opponent_large', 'lasertag_one_opponent_large'),
    ('lasertag_three_opponents_large', 'lasertag_three_opponents_large'),
    ('natlab_fixed_large_map', 'natlab_fixed_large_map'),
    ('natlab_varying_map_regrowth', 'natlab_varying_map_regrowth'),
    ('natlab_varying_map_randomized', 'natlab_varying_map_randomized'),
    ('skymaze_irreversible_path_hard', 'skymaze_irreversible_path_hard'),
    ('skymaze_irreversible_path_varied', 'skymaze_irreversible_path_varied'),
    ('psychlab_arbitrary_visuomotor_mapping',
     'psychlab_arbitrary_visuomotor_mapping'),
    ('psychlab_continuous_recognition', 'psychlab_continuous_recognition'),
    ('psychlab_sequential_comparison', 'psychlab_sequential_comparison'),
    ('psychlab_visual_search', 'psychlab_visual_search'),
    ('explore_object_locations_small', 'explore_object_locations_small'),
    ('explore_object_locations_large', 'explore_object_locations_large'),
    ('explore_obstructed_goals_small', 'explore_obstructed_goals_small'),
    ('explore_obstructed_goals_large', 'explore_obstructed_goals_large'),
    ('explore_goal_locations_small', 'explore_goal_locations_small'),
    ('explore_goal_locations_large', 'explore_goal_locations_large'),
    ('explore_object_rewards_few', 'explore_object_rewards_few'),
    ('explore_object_rewards_many', 'explore_object_rewards_many'),
])

ALL_LEVELS = tuple(LEVEL_MAPPING.keys())

HUMAN_SCORES = {
    'rooms_collect_good_objects_test': 10.0,
    'rooms_exploit_deferred_effects_test': 85.65,
    'rooms_select_nonmatching_object': 65.9,
    'rooms_watermaze': 54.0,
    'rooms_keys_doors_puzzle': 53.8,
    'language_select_described_object': 389.5,
    'language_select_located_object': 280.7,
    'language_execute_random_task': 254.05,
    'language_answer_quantitative_question': 184.5,
    'lasertag_one_opponent_small': 12.65,
    'lasertag_three_opponents_small': 18.55,
    'lasertag_one_opponent_large': 18.6,
    'lasertag_three_opponents_large': 31.5,
    'natlab_fixed_large_map': 36.9,
    'natlab_varying_map_regrowth': 24.45,
    'natlab_varying_map_randomized': 42.35,
    'skymaze_irreversible_path_hard': 100.0,
    'skymaze_irreversible_path_varied': 100.0,
    'psychlab_arbitrary_visuomotor_mapping': 58.75,
    'psychlab_continuous_recognition': 58.3,
    'psychlab_sequential_comparison': 39.5,
    'psychlab_visual_search': 78.5,
    'explore_object_locations_small': 74.45,
    'explore_object_locations_large': 65.65,
    'explore_obstructed_goals_small': 206.0,
    'explore_obstructed_goals_large': 119.5,
    'explore_goal_locations_small': 267.5,
    'explore_goal_locations_large': 194.5,
    'explore_object_rewards_few': 77.7,
    'explore_object_rewards_many': 106.7,
}

RANDOM_SCORES = {
    'rooms_collect_good_objects_test': 0.073,
    'rooms_exploit_deferred_effects_test': 8.501,
    'rooms_select_nonmatching_object': 0.312,
    'rooms_watermaze': 4.065,
    'rooms_keys_doors_puzzle': 4.135,
    'language_select_described_object': -0.07,
    'language_select_located_object': 1.929,
    'language_execute_random_task': -5.913,
    'language_answer_quantitative_question': -0.33,
    'lasertag_one_opponent_small': -0.224,
    'lasertag_three_opponents_small': -0.214,
    'lasertag_one_opponent_large': -0.083,
    'lasertag_three_opponents_large': -0.102,
    'natlab_fixed_large_map': 2.173,
    'natlab_varying_map_regrowth': 2.989,
    'natlab_varying_map_randomized': 7.346,
    'skymaze_irreversible_path_hard': 0.1,
    'skymaze_irreversible_path_varied': 14.4,
    'psychlab_arbitrary_visuomotor_mapping': 0.163,
    'psychlab_continuous_recognition': 0.224,
    'psychlab_sequential_comparison': 0.129,
    'psychlab_visual_search': 0.085,
    'explore_object_locations_small': 3.575,
    'explore_object_locations_large': 4.673,
    'explore_obstructed_goals_small': 6.76,
    'explore_obstructed_goals_large': 2.61,
    'explore_goal_locations_small': 7.66,
    'explore_goal_locations_large': 3.14,
    'explore_object_rewards_few': 2.073,
    'explore_object_rewards_many': 2.438,
}


def compute_human_normalized_score(level_returns, per_level_cap=None):
  """Mean human-normalized score over the 30 levels.

  Args:
    level_returns: dict mapping *training* level name -> list/array of
      episode returns for that level. Every level in `ALL_LEVELS` must be
      present and non-empty (reference: dmlab30.py
      compute_human_normalized_score raises on missing levels).
    per_level_cap: optional scalar; each level's normalized score is
      clipped above at this value (cap=100 gives the paper's "capped"
      metric).

  Returns:
    float: mean over levels of
      (mean_return - random) / (human - random) * 100, optionally capped.
  """
  anchors.check_provenance(
      'envs/dmlab30.py', ANCHOR_PROVENANCE, ANCHOR_SHA256,
      {'LEVEL_MAPPING': dict(LEVEL_MAPPING),
       'HUMAN_SCORES': HUMAN_SCORES, 'RANDOM_SCORES': RANDOM_SCORES})
  missing = [l for l in ALL_LEVELS
             if l not in level_returns or len(level_returns[l]) == 0]
  if missing:
    raise ValueError(f'Missing returns for levels: {missing}')
  scores = []
  for train_level in ALL_LEVELS:
    test_level = LEVEL_MAPPING[train_level]
    human, random = HUMAN_SCORES[test_level], RANDOM_SCORES[test_level]
    mean_return = float(np.mean(level_returns[train_level]))
    score = (mean_return - random) / (human - random) * 100.0
    if per_level_cap is not None:
      score = min(score, per_level_cap)
    scores.append(score)
  return float(np.mean(scores))
