"""Benchmark-suite registry: ONE place that knows which `level_name`
values expand to a multi-task suite and how each suite is scored.

Factory level expansion, training-time scoring (observability.
EpisodeStats) and eval-time scoring (driver.evaluate) all dispatch
through `SUITES` — adding a suite is one entry here, nothing else.
(Reference scope: dmlab30.py is the only suite upstream; atari57 is
this build's addition for the paper's Atari evaluation story.)
"""

from typing import Callable, Dict, List, NamedTuple, Tuple

from scalable_agent_tpu.envs import atari57, dmlab30


class Suite(NamedTuple):
  """A multi-task benchmark: its level lists and score summaries.

  The score functions take `{train_level_name: [episode returns]}`
  (every level present and non-empty — they raise otherwise) and
  return `{summary_tag: value}` ready for the JSONL writer.
  """
  train_levels: Tuple[str, ...]
  test_levels: Tuple[str, ...]
  training_scores: Callable[[Dict[str, List[float]]], Dict[str, float]]
  eval_scores: Callable[[Dict[str, List[float]]], Dict[str, float]]


def _dmlab30_scores(prefix):
  def scores(level_returns):
    return {
        f'dmlab30/{prefix}_no_cap': dmlab30.compute_human_normalized_score(
            level_returns, per_level_cap=None),
        f'dmlab30/{prefix}_cap_100': dmlab30.compute_human_normalized_score(
            level_returns, per_level_cap=100),
    }
  return scores


def _atari57_scores(prefix):
  def scores(game_returns):
    return {
        f'atari57/{prefix}_median': atari57.compute_human_normalized_score(
            game_returns, aggregate='median'),
        f'atari57/{prefix}_mean': atari57.compute_human_normalized_score(
            game_returns, aggregate='mean'),
    }
  return scores


SUITES: Dict[str, Suite] = {
    'dmlab30': Suite(
        train_levels=tuple(dmlab30.ALL_LEVELS),
        test_levels=tuple(dmlab30.LEVEL_MAPPING.values()),
        training_scores=_dmlab30_scores('training'),
        eval_scores=_dmlab30_scores('test'),
    ),
    # Atari has no held-out level variants: eval plays the training
    # games (episode diversity comes from the always-on random no-op
    # starts — the ALE eval protocol — policy sampling, and sticky
    # actions if configured).
    'atari57': Suite(
        train_levels=atari57.ALL_GAMES,
        test_levels=atari57.ALL_GAMES,
        training_scores=_atari57_scores('training'),
        eval_scores=_atari57_scores('test'),
    ),
}
