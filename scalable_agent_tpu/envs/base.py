"""Environment protocol (host-side).

The device never sees an environment — envs live on the host (possibly in
separate processes, see runtime/py_process.py) and speak numpy. The
contract mirrors the reference's `PyProcessDmLab` (reference:
environments.py ≈L60–115):

- `initial()` → observation
- `step(action)` → (reward f32[], done bool[], observation), with
  action-repeat and auto-reset inside (done=True ⇒ the returned
  observation is the *first* frame of the next episode)
- `close()`
- `_tensor_specs(method_name, kwargs, constructor_kwargs)` → dtype/shape
  declaration for process hosting (the reference's py_process protocol).

Observations are `(frame uint8 [H, W, 3], instruction_ids int32 [L])` —
strings are hashed host-side (models/instruction.py) so only fixed-shape
numerics cross process/device boundaries.
"""

from typing import NamedTuple, Tuple

import numpy as np


class ArraySpec(NamedTuple):
  shape: Tuple[int, ...]
  dtype: np.dtype


def observation_specs(height, width, instr_len):
  return (ArraySpec((height, width, 3), np.dtype(np.uint8)),
          ArraySpec((instr_len,), np.dtype(np.int32)))


def step_output_specs(height, width, instr_len):
  """Specs for the (reward, done, observation) tuple of `step`."""
  return (ArraySpec((), np.dtype(np.float32)),
          ArraySpec((), np.dtype(bool)),
          observation_specs(height, width, instr_len))


class Environment:
  """Base class; subclasses implement `initial`/`step` (action-repeat
  and auto-reset live inside `step`) and declare `_tensor_specs`."""

  def initial(self):
    raise NotImplementedError

  def step(self, action):
    raise NotImplementedError

  def close(self):
    pass

  @staticmethod
  def _tensor_specs(method_name, unused_kwargs, constructor_kwargs):
    raise NotImplementedError
