"""DeepMind Lab environment adapter (import-guarded — SURVEY §7: no
DMLab in this sandbox; the fake envs are the CI workhorse).

TPU-native counterpart of the reference's `PyProcessDmLab`
(reference: environments.py ≈L60–115) and `LocalLevelCache` (≈L20),
with the same contracts:

- `DEFAULT_ACTION_SET`: the 9 discrete 7-dim composite DMLab actions
  (reference: environments.py ≈L40) the agent's categorical policy
  indexes into.
- `step(action_index)` repeats the raw action `num_action_repeats`
  times, returns (reward f32[], done bool[], observation) and
  auto-resets on episode end — the returned observation is then the
  first frame of the next episode.
- per-env `np.random.RandomState(seed)` drives reset seeds.
- test mode: `allowHoldOutLevels=true` + fixed `mixerSeed=0x600D5EED`
  (reference: create_environment ≈L395–410).

Divergence from the reference (TPU dtype contract): the INSTR string is
hashed host-side into fixed-shape int32 ids (models/instruction.py) —
strings never cross the process or device boundary.
"""

import os
import shutil
from typing import Optional

import numpy as np

from scalable_agent_tpu.envs import base
from scalable_agent_tpu.models.instruction import (
    hash_instruction, MAX_INSTRUCTION_LEN)

try:  # pragma: no cover - not installed in CI
  import deepmind_lab
except ImportError:
  deepmind_lab = None

TEST_MIXER_SEED = 0x600D5EED

# Discrete composite actions over DMLab's 7 continuous/discrete axes
# (look_lr, look_ud, strafe_lr, move_bf, fire, jump, crouch); the
# reference's DEFAULT_ACTION_SET (environments.py ≈L40).
DEFAULT_ACTION_SET = (
    (0, 0, 0, 1, 0, 0, 0),     # Forward
    (0, 0, 0, -1, 0, 0, 0),    # Backward
    (0, 0, -1, 0, 0, 0, 0),    # Strafe Left
    (0, 0, 1, 0, 0, 0, 0),     # Strafe Right
    (-20, 0, 0, 0, 0, 0, 0),   # Look Left
    (20, 0, 0, 0, 0, 0, 0),    # Look Right
    (-20, 0, 0, 1, 0, 0, 0),   # Look Left + Forward
    (20, 0, 0, 1, 0, 0, 0),    # Look Right + Forward
    (0, 0, 0, 0, 1, 0, 0),     # Fire
)


class LocalLevelCache:
  """Level cache storing compiled DMLab maps on local disk
  (reference: environments.py ≈L20). DMLab calls `fetch` before
  compiling a level and `write` after."""

  def __init__(self, cache_dir: str = '/tmp/level_cache'):
    self._cache_dir = cache_dir
    os.makedirs(cache_dir, exist_ok=True)

  def fetch(self, key: str, pk3_path: str) -> bool:
    path = os.path.join(self._cache_dir, key)
    if os.path.isfile(path):
      shutil.copyfile(path, pk3_path)
      return True
    return False

  def write(self, key: str, pk3_path: str) -> None:
    path = os.path.join(self._cache_dir, key)
    if not os.path.isfile(path):
      shutil.copyfile(pk3_path, path)


def constructor_kwargs(level_name: str, seed: int, is_test: bool,
                       config) -> dict:
  """Kwargs for DmLabEnv from the experiment config (the reference's
  create_environment config block, experiment.py ≈L395–410)."""
  lab_config = {
      'width': str(config.width),
      'height': str(config.height),
      'logLevel': 'WARN',
  }
  if config.dataset_path:
    lab_config['datasetPath'] = config.dataset_path
  if is_test:
    lab_config['allowHoldOutLevels'] = 'true'
    lab_config['mixerSeed'] = str(TEST_MIXER_SEED)
  return dict(level=level_name, config=lab_config, seed=seed,
              num_action_repeats=config.num_action_repeats,
              level_cache_dir=config.level_cache_dir or None)


class DmLabEnv(base.Environment):
  """One DMLab level behind the host env protocol."""

  def __init__(self, level: str, config: dict, seed: int,
               num_action_repeats: int = 4,
               action_set=DEFAULT_ACTION_SET,
               level_cache: Optional[LocalLevelCache] = None,
               level_cache_dir: Optional[str] = None,
               runfiles_path: Optional[str] = None,
               lab_cls=None):
    # `lab_cls` injects a scripted Lab for tests (same pattern as
    # AtariEnv's `ale=` — VERDICT r4 #4: the step/auto-reset/INSTR
    # path must execute in CI even though deepmind_lab cannot be
    # installed here). Production always resolves the real module.
    if lab_cls is None:
      if deepmind_lab is None:
        raise ImportError(
            'deepmind_lab is not installed; use --env_backend=fake/'
            'bandit in this sandbox, or install DeepMind Lab (see its '
            'build docs) for real runs.')
      if runfiles_path:
        deepmind_lab.set_runfiles_path(runfiles_path)
      lab_cls = deepmind_lab.Lab
    self._num_action_repeats = num_action_repeats
    self._action_set = np.array(action_set, dtype=np.intc)
    self._random_state = np.random.RandomState(seed=seed)
    self._level_name = level
    if level_cache is None:
      level_cache = (LocalLevelCache(level_cache_dir)
                     if level_cache_dir else LocalLevelCache())
    self._env = lab_cls(
        level=level,
        observations=['RGB_INTERLEAVED', 'INSTR'],
        config={k: str(v) for k, v in config.items()},
        level_cache=level_cache)
    self._height = int(config['height'])
    self._width = int(config['width'])
    self._reset()

  def _reset(self):
    self._env.reset(seed=self._random_state.randint(0, 2 ** 31 - 1))

  def _observation(self):
    obs = self._env.observations()
    frame = np.asarray(obs['RGB_INTERLEAVED'], np.uint8)
    instr = hash_instruction(str(obs['INSTR']))
    return (frame, instr)

  def initial(self):
    return self._observation()

  def step(self, action):
    raw_action = self._action_set[int(action)]
    reward = self._env.step(raw_action,
                            num_steps=self._num_action_repeats)
    done = not self._env.is_running()
    if done:
      self._reset()
    return (np.float32(reward), np.bool_(done), self._observation())

  def close(self):
    self._env.close()

  @staticmethod
  def _tensor_specs(method_name, unused_kwargs, constructor_kwargs):
    config = constructor_kwargs['config']
    h, w = int(config['height']), int(config['width'])
    if method_name == 'initial':
      return base.observation_specs(h, w, MAX_INSTRUCTION_LEN)
    if method_name == 'step':
      return base.step_output_specs(h, w, MAX_INSTRUCTION_LEN)
    return None
