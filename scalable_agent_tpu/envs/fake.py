"""Fake environments — the CI workhorse (SURVEY §4: what upstream lacks).

Spec-compatible with the DMLab adapter so the whole actor→buffer→learner
pipeline runs without DeepMind Lab:

- `FakeEnv`: deterministic frames/rewards from a counter; fixed episode
  length; auto-reset. For plumbing and alignment tests.
- `ContextualBanditEnv`: the frame's dominant color channel encodes which
  action pays reward — the simplest task where the IMPALA loss must
  visibly learn (E2E smoke: return goes up).
"""

import numpy as np

from scalable_agent_tpu.envs import base
from scalable_agent_tpu.models.instruction import (
    hash_instruction, MAX_INSTRUCTION_LEN)


class FakeEnv(base.Environment):
  """Deterministic counter-driven env."""

  def __init__(self, height=24, width=32, num_actions=5,
               episode_length=10, seed=0, level_name='fake',
               num_action_repeats=1):
    self._h, self._w = height, width
    self._num_actions = num_actions
    self._episode_length = episode_length
    self._count = 0
    self._episode_step = 0
    self._seed = seed
    self._instr = hash_instruction(level_name)

  def _observation(self):
    frame = np.full((self._h, self._w, 3),
                    (self._count + self._seed) % 255, np.uint8)
    return (frame, self._instr.copy())

  def initial(self):
    return self._observation()

  def step(self, action):
    self._count += 1
    self._episode_step += 1
    reward = np.float32(0.1 * (int(action) % 2))
    done = self._episode_step >= self._episode_length
    if done:
      self._episode_step = 0
    return reward, np.bool_(done), self._observation()

  @staticmethod
  def _tensor_specs(method_name, unused_kwargs, constructor_kwargs):
    h = constructor_kwargs.get('height', 24)
    w = constructor_kwargs.get('width', 32)
    if method_name == 'initial':
      return base.observation_specs(h, w, MAX_INSTRUCTION_LEN)
    if method_name == 'step':
      return base.step_output_specs(h, w, MAX_INSTRUCTION_LEN)
    return None


class CueMemoryEnv(base.Environment):
  """Two-step memory task: the cue is visible ONLY on the first frame.

  initial()/post-reset observation shows the cue (dominant color
  channel 0..2); the next frame is blank; the action taken on the
  BLANK frame earns reward 1 iff it matches the cue.

  Relay-proof: because the agent's core input includes
  one_hot(prev_action), a memoryless policy could otherwise smuggle
  the cue through its own first action. So the FIRST action is paid
  2.0 iff it is the fixed action 0 — an information-free optimum.
  Expected returns per episode: memory policy 3.0 (2 + 1); relay
  policy 5/3 (the 2.0 pays only when the cue happens to be 0, + 1);
  best memoryless policy 2 + 1/3. Only a working recurrent carry
  clears ~2.6.
  """

  def __init__(self, height=16, width=16, num_actions=3,
               episode_length=2, seed=0, level_name='cue_memory',
               num_action_repeats=1):
    del episode_length  # fixed two-step episodes
    if num_actions != 3:
      raise ValueError('CueMemoryEnv is a 3-action task (one action '
                       'per RGB cue channel); got num_actions='
                       f'{num_actions}')
    self._h, self._w = height, width
    self._num_actions = num_actions
    self._rng = np.random.RandomState(seed)
    self._instr = hash_instruction(level_name)
    self._step_in_episode = 0
    self._cue = int(self._rng.randint(3))

  def _observation(self):
    frame = np.zeros((self._h, self._w, 3), np.uint8)
    if self._step_in_episode == 0:  # cue only on the first frame
      frame[:, :, self._cue] = 255
    return (frame, self._instr.copy())

  def initial(self):
    return self._observation()

  def step(self, action):
    if self._step_in_episode == 0:
      # First action: paid 2.0 for the FIXED action 0 (carries no cue
      # information; relaying the cue here forfeits this reward).
      self._step_in_episode = 1
      reward = np.float32(2.0 if int(action) == 0 else 0.0)
      return reward, np.bool_(False), self._observation()
    reward = np.float32(1.0 if int(action) == self._cue else 0.0)
    self._cue = int(self._rng.randint(3))
    self._step_in_episode = 0
    return reward, np.bool_(True), self._observation()

  @staticmethod
  def _tensor_specs(method_name, unused_kwargs, constructor_kwargs):
    h = constructor_kwargs.get('height', 16)
    w = constructor_kwargs.get('width', 16)
    if method_name == 'initial':
      return base.observation_specs(h, w, MAX_INSTRUCTION_LEN)
    if method_name == 'step':
      return base.step_output_specs(h, w, MAX_INSTRUCTION_LEN)
    return None


class ContextualBanditEnv(base.Environment):
  """One-step contextual bandit: act = argmax-channel ⇒ reward 1.

  Each "episode" is `episode_length` steps of the same context; the
  rewarded action is the dominant color channel (0..2) of the frame. A
  learning agent's mean return must rise well above the 1/num_actions
  random baseline within a few thousand frames.
  """

  def __init__(self, height=24, width=32, num_actions=3,
               episode_length=5, seed=0, level_name='bandit',
               num_action_repeats=1):
    self._h, self._w = height, width
    self._num_actions = num_actions
    self._episode_length = episode_length
    self._rng = np.random.RandomState(seed)
    self._instr = hash_instruction(level_name)
    self._episode_step = 0
    self._target = None
    self._reset_context()

  def _reset_context(self):
    self._target = int(self._rng.randint(self._num_actions)) % 3
    self._episode_step = 0

  def _observation(self):
    frame = np.zeros((self._h, self._w, 3), np.uint8)
    frame[:, :, self._target] = 255
    return (frame, self._instr.copy())

  def initial(self):
    return self._observation()

  def step(self, action):
    reward = np.float32(1.0 if int(action) == self._target else 0.0)
    self._episode_step += 1
    done = self._episode_step >= self._episode_length
    if done:
      self._reset_context()
    return reward, np.bool_(done), self._observation()

  _tensor_specs = FakeEnv.__dict__['_tensor_specs']
