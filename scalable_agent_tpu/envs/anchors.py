"""Anchor-table provenance machinery (VERDICT r4 #7 / ADVICE r4).

The human/random anchor tables in `envs/dmlab30.py` and
`envs/atari57.py` were reconstructed without access to their upstream
sources (reference mount empty, zero network egress — see each
module's caveat). A misremembered constant silently corrupts every
human-normalized score, so the tables carry three mechanical guards:

1. a pinned SHA-256 (`ANCHOR_SHA256`) of the canonical serialization —
   any accidental edit of a constant fails the self-check the next
   time scoring runs (and `tests/test_anchors.py`);
2. a once-per-process provenance warning when a score is computed from
   a table whose `ANCHOR_PROVENANCE` is still 'reconstructed', so no
   reported number can claim verified anchors by silence;
3. `scripts/verify_anchors.py`, which diffs the tables symbol-by-symbol
   against the upstream files once network/reference access exists and
   prints the one-line edit that flips provenance to 'verified'
   (docs/RUNBOOK.md §2 is the operator protocol).
"""

import hashlib
import logging
from typing import Dict

# Module names already warned this process (once-per-run semantics).
_warned = set()


def anchor_checksum(tables: Dict[str, Dict[str, float]]) -> str:
  """SHA-256 over a canonical serialization of named anchor tables.

  Keys sorted, floats via repr (exact — these are decimal literals,
  not computed values), so the checksum is stable across Python
  versions and dict orderings.
  """
  parts = []
  for table_name in sorted(tables):
    parts.append(table_name)
    table = tables[table_name]
    for key in sorted(table):
      parts.append(f'{key}={table[key]!r}')
  blob = '\n'.join(parts).encode('utf-8')
  return hashlib.sha256(blob).hexdigest()


def check_provenance(module_name: str, provenance: str,
                     pinned_sha256: str,
                     tables: Dict[str, Dict[str, float]]) -> None:
  """Scoring-time gate: self-check the table checksum, and warn once
  per process if the table is still unverified against upstream.

  Raises ValueError on checksum mismatch — a silently edited anchor
  is worse than no score at all.
  """
  actual = anchor_checksum(tables)
  if actual != pinned_sha256:
    raise ValueError(
        f'{module_name} anchor tables do not match their pinned '
        f'checksum (expected {pinned_sha256[:16]}…, got '
        f'{actual[:16]}…). A constant was edited without updating '
        f'ANCHOR_SHA256 — if the edit was a deliberate upstream '
        f'correction, rerun scripts/verify_anchors.py and update the '
        f'pinned value it prints.')
  if provenance != 'verified' and module_name not in _warned:
    _warned.add(module_name)
    logging.warning(
        '%s anchor tables are PROVENANCE=%r: reconstructed without '
        'access to the upstream source. Human-normalized scores '
        'computed from them are provisional until the tables are '
        'diffed against upstream (scripts/verify_anchors.py; '
        'docs/RUNBOOK.md section 2).', module_name, provenance)
