"""Environment factory: config → host environment instances.

The reference builds envs in `create_environment` (reference:
experiment.py ≈L395–410: PyProcess(PyProcessDmLab, ...) wrapped in
FlowEnvironment, test mode setting allowHoldOutLevels + fixed
mixerSeed). Here the factory is backend-dispatched so the same driver
runs the CI fake envs, DMLab, or Atari — real simulators are
import-guarded (not present in this sandbox; SURVEY §7 "hard parts").

Envs are host-side numpy objects (envs/base.py protocol). With
`config.use_py_process` the driver hosts each one in its own OS process
via runtime/py_process.py — the reference's PyProcess GIL-escape.
"""

from typing import List, Optional, Tuple

from scalable_agent_tpu.config import Config
from scalable_agent_tpu.envs import suites
from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN


class EnvSpec(object):
  """What the driver needs to know about a backend before building it."""

  def __init__(self, env_class, constructor_kwargs, num_actions,
               frame_shape):
    self.env_class = env_class
    self.constructor_kwargs = dict(constructor_kwargs)
    self.num_actions = num_actions
    self.frame_shape = tuple(frame_shape)

  @property
  def obs_spec(self):
    return {'frame': self.frame_shape, 'instr_len': MAX_INSTRUCTION_LEN}

  def build(self):
    return self.env_class(**self.constructor_kwargs)


def level_names(config: Config) -> List[str]:
  """Training level list; a suite name ('dmlab30', 'atari57') expands
  to its full level list (reference: experiment.py main ≈L630)."""
  if config.level_name in suites.SUITES:
    return list(suites.SUITES[config.level_name].train_levels)
  return [config.level_name]


def test_level_names(config: Config) -> List[str]:
  """Held-out eval variants (reference: dmlab30.LEVEL_MAPPING; see
  envs/suites.py for the per-suite eval-level story)."""
  if config.level_name in suites.SUITES:
    return list(suites.SUITES[config.level_name].test_levels)
  return [config.level_name]


def make_env_spec(config: Config, level_name: str, seed: int,
                  is_test: bool = False,
                  backend: Optional[str] = None) -> EnvSpec:
  """One environment spec for (backend, level, seed).

  `backend` overrides config.env_backend — the heterogeneous-fleet
  seam (round 22): a mixed fleet builds each actor's spec for ITS
  task's backend while every other knob (sizes, seeds, repeats) still
  comes from the one config."""
  backend = backend or config.env_backend
  if backend in ('fake', 'bandit', 'cue_memory'):
    from scalable_agent_tpu.envs import fake
    env_class = {'bandit': fake.ContextualBanditEnv,
                 'cue_memory': fake.CueMemoryEnv,
                 'fake': fake.FakeEnv}[backend]
    num_actions = config.num_actions or (
        5 if backend == 'fake' else 3)
    kwargs = dict(height=config.height, width=config.width,
                  num_actions=num_actions,
                  episode_length=config.episode_length,
                  seed=seed, level_name=level_name,
                  num_action_repeats=config.num_action_repeats)
    frame_shape = (config.height, config.width, 3)
  elif backend in ('gridworld', 'procgen'):
    # Pure-JAX env family (round 16, envs/jittable.py): the host
    # wrapper runs the SAME jittable core the Anakin runtime scans on
    # device at batch=1 — the dual registration the runtime-axis
    # parity gate rides on (one task definition, both runtimes).
    from scalable_agent_tpu.envs import jittable
    env_class = jittable.HOST_ENVS[backend]
    num_actions = (config.num_actions or
                   jittable.DEFAULT_NUM_ACTIONS[backend])
    kwargs = dict(height=config.height, width=config.width,
                  num_actions=num_actions,
                  episode_length=config.episode_length,
                  seed=seed, level_name=level_name,
                  num_action_repeats=config.num_action_repeats)
    if backend == 'procgen':
      # The finite level-id space the curriculum drives (round 22) —
      # host wrapper and Anakin core must agree on its size or the
      # dual-registration parity story breaks.
      kwargs.update(num_levels=config.procgen_num_levels,
                    wall_density=config.procgen_wall_density)
    frame_shape = (config.height, config.width, 3)
  elif backend == 'dmlab':
    from scalable_agent_tpu.envs import dmlab
    env_class = dmlab.DmLabEnv
    num_actions = len(dmlab.DEFAULT_ACTION_SET)
    kwargs = dmlab.constructor_kwargs(
        level_name=level_name, seed=seed, is_test=is_test, config=config)
    frame_shape = (config.height, config.width, 3)
  elif backend == 'atari':
    from scalable_agent_tpu.envs import atari
    env_class = atari.AtariEnv
    num_actions = config.num_actions or atari.DEFAULT_NUM_ACTIONS
    # The factory knows both the policy-head size and the backend; the
    # env validates they agree at construction (no silent aliasing).
    # A head smaller than the full 18-action ALE set means the user
    # wants the game's minimal action set — the env still verifies the
    # backend's set has exactly num_actions entries.
    kwargs = dict(game=level_name, seed=seed,
                  height=config.height, width=config.width,
                  num_action_repeats=config.num_action_repeats,
                  is_test=is_test, num_actions=num_actions,
                  sticky_action_prob=config.sticky_action_prob,
                  full_action_set=(
                      num_actions == atari.DEFAULT_NUM_ACTIONS))
    frame_shape = (config.height, config.width, 3)
  else:
    raise ValueError(f'unknown env backend: {backend!r}')
  return EnvSpec(env_class, kwargs, num_actions, frame_shape)


def build_environment(spec: EnvSpec, use_py_process: bool = False
                      ) -> Tuple[object, Optional[object]]:
  """Instantiate (env, process): in-process, or hosted in its own OS
  process behind the py_process proxy (returns the PyProcess so the
  caller controls its lifecycle)."""
  if not use_py_process:
    return spec.build(), None
  from scalable_agent_tpu.runtime import py_process
  process = py_process.PyProcess(spec.env_class,
                                 constructor_kwargs=spec.constructor_kwargs)
  process.start()
  return py_process.ProxyEnv(process), process
