"""Pure-JAX environment family: gridworld + procgen-style generator.

Round 16 (the `--runtime={fleet,anakin}` axis): the Anakin operating
point (parallel/anakin.py, Podracer arXiv:2104.06272) is only as wide
as the set of environments that can live INSIDE the jitted device step.
bandit/cue_memory proved the architecture; this module opens the
family:

- `GridworldCore`: a G×G navigation task — agent spawns at the origin,
  a goal cell is sampled per episode, four movement actions, sparse
  +1 at the goal. The simplest task whose optimal policy must READ the
  observation spatially (the bandit's is a 1-pixel color lookup).
- `ProcgenCore`: a procgen-style PARAMETERIZED generator — each
  episode draws a level id from a finite level set and derives the
  wall layout deterministically from it in-graph
  (`jax.random.fold_in`), so one config spans `num_levels` distinct
  layouts the way procgen's level sets do. Walls block movement;
  start/goal are fixed corners; generalization pressure comes from the
  layout distribution.

Both cores follow the ENV_CORES protocol (parallel/anakin.py): a
constructor over (height, width, episode_length, num_action_repeats,
num_actions), `init(rng, batch)` / `step(state, action)` over batched
functional state, flow-style episode stats, and a NamedTuple state
whose `rng` field is the one replicated-by-name leaf (every other leaf
is [B]-leading and shards over the data mesh axis — anakin.init_carry's
placement contract).

DUAL REGISTRATION is the point: `GridworldEnv`/`ProcgenEnv` wrap the
SAME cores at batch=1 as host `envs/base.Environment`s (pinned to the
CPU backend so fleet env threads never contend for the learner chip),
registered in envs/factory.py — so one task definition runs under both
runtimes, which is the substrate of the anakin-vs-fleet parity gate
(tests/test_anakin.py). Dynamics parity is by construction, not by a
twin implementation.
"""

import functools
from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from scalable_agent_tpu.envs import base
from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
from scalable_agent_tpu.structs import StepOutput, StepOutputInfo


def _zero_instr(batch):
  return jnp.zeros((batch, MAX_INSTRUCTION_LEN), jnp.int32)


def _cell_masks(height, width, grid):
  """Static [H, W] int32 maps pixel → cell row/col (rendering grid
  cells into the frame without gathers)."""
  rows = (np.arange(height) * grid) // max(height, 1)
  cols = (np.arange(width) * grid) // max(width, 1)
  return (jnp.asarray(rows[:, None].repeat(width, 1), jnp.int32),
          jnp.asarray(cols[None, :].repeat(height, 0), jnp.int32))


class GridworldState(NamedTuple):
  """Batched functional gridworld state ([B]-leading except rng)."""
  rng: Any              # PRNG key [] — replicated by name (anakin)
  agent_yx: Any         # i32 [B, 2]
  goal_yx: Any          # i32 [B, 2]
  step_in_episode: Any  # i32 [B]
  episode_return: Any   # f32 [B]
  episode_frames: Any   # i32 [B]


class GridworldCore:
  """Jittable G×G gridworld: reach the per-episode goal cell.

  Actions 0..3 move up/down/left/right (clamped at the borders);
  actions >= 4 are no-ops, so the policy head can be any width >= 4 —
  the hybrid filler runs this core under the MAIN task's action space
  (driver.py), mirroring how the host bandit accepts a wider head.
  Reaching the goal pays +1 and ends the episode; `episode_length`
  caps wandering. Observation: channel 0 = agent cell, channel 1 =
  goal cell at 255 (uint8 [B, H, W, 3]).
  """

  def __init__(self, height=24, width=32, episode_length=12,
               num_action_repeats=1, num_actions=4, grid_size=4):
    if num_actions < 4:
      raise ValueError('GridworldCore needs num_actions >= 4 (four '
                       f'movement actions), got {num_actions}')
    if grid_size < 2:
      raise ValueError(f'grid_size must be >= 2, got {grid_size}')
    self.height, self.width = height, width
    self.episode_length = episode_length
    self.num_action_repeats = num_action_repeats
    self.num_actions = num_actions
    self.grid = grid_size
    self._row_cell, self._col_cell = _cell_masks(height, width,
                                                 grid_size)

  # [dy, dx] per action; rows past 3 are no-ops.
  def _moves(self):
    moves = np.zeros((self.num_actions, 2), np.int32)
    moves[:4] = [(-1, 0), (1, 0), (0, -1), (0, 1)]
    return jnp.asarray(moves)

  def _sample_goal(self, rng, batch):
    """Uniform over cells != (0, 0) — the fixed spawn cell."""
    flat = jax.random.randint(rng, (batch,), 1,
                              self.grid * self.grid)
    return jnp.stack([flat // self.grid, flat % self.grid], axis=-1)

  def _cell_plane(self, yx):
    """[B, H, W] bool: pixels of each env's cell `yx`."""
    return ((self._row_cell[None] == yx[:, 0, None, None]) &
            (self._col_cell[None] == yx[:, 1, None, None]))

  def _observation(self, state):
    agent = self._cell_plane(state.agent_yx)
    goal = self._cell_plane(state.goal_yx)
    frame = jnp.stack(
        [agent.astype(jnp.uint8) * 255, goal.astype(jnp.uint8) * 255,
         jnp.zeros_like(agent, jnp.uint8)], axis=-1)
    return (frame, _zero_instr(state.agent_yx.shape[0]))

  def init(self, rng, batch) -> Tuple[GridworldState, StepOutput]:
    rng, sub = jax.random.split(rng)
    state = GridworldState(
        rng=rng,
        agent_yx=jnp.zeros((batch, 2), jnp.int32),
        goal_yx=self._sample_goal(sub, batch),
        step_in_episode=jnp.zeros((batch,), jnp.int32),
        episode_return=jnp.zeros((batch,), jnp.float32),
        episode_frames=jnp.zeros((batch,), jnp.int32))
    output = StepOutput(
        reward=jnp.zeros((batch,), jnp.float32),
        info=StepOutputInfo(jnp.zeros((batch,), jnp.float32),
                            jnp.zeros((batch,), jnp.int32)),
        done=jnp.ones((batch,), bool),
        observation=self._observation(state))
    return state, output

  def _blocked(self, state, proposed):
    """Movement veto hook (ProcgenCore overrides with its walls)."""
    del state
    return jnp.zeros(proposed.shape[:1], bool)

  def step(self, state: GridworldState, action
           ) -> Tuple[GridworldState, StepOutput]:
    delta = self._moves()[action]
    proposed = jnp.clip(state.agent_yx + delta, 0, self.grid - 1)
    blocked = self._blocked(state, proposed)
    agent = jnp.where(blocked[:, None], state.agent_yx, proposed)

    at_goal = jnp.all(agent == state.goal_yx, axis=-1)
    reward = at_goal.astype(jnp.float32)
    step_count = state.step_in_episode + 1
    done = at_goal | (step_count >= self.episode_length)

    ep_return = state.episode_return + reward
    ep_frames = state.episode_frames + self.num_action_repeats
    info = StepOutputInfo(ep_return, ep_frames)  # emitted: incl. done

    rng, sub = jax.random.split(state.rng)
    fresh_goal, fresh_extra = self._fresh_episode(sub, action.shape[0],
                                                  state)
    new_state = self._replace_episode(
        state, rng=rng,
        agent_yx=jnp.where(done[:, None], jnp.zeros_like(agent), agent),
        goal_yx=jnp.where(done[:, None], fresh_goal, state.goal_yx),
        step_in_episode=jnp.where(done, 0, step_count),
        episode_return=jnp.where(done, jnp.zeros_like(ep_return),
                                 ep_return),
        episode_frames=jnp.where(done, jnp.zeros_like(ep_frames),
                                 ep_frames),
        done=done, fresh_extra=fresh_extra)
    output = StepOutput(reward=reward, info=info, done=done,
                        observation=self._observation(new_state))
    return new_state, output

  def _fresh_episode(self, rng, batch, state):
    """New-episode draws: (goal, extra) — extra is subclass state.
    `state` is the pre-step batched state (ProcgenCore's curriculum
    sampler reads its per-level scores from it)."""
    del state
    return self._sample_goal(rng, batch), None

  def _replace_episode(self, state, rng, agent_yx, goal_yx,
                       step_in_episode, episode_return, episode_frames,
                       done, fresh_extra):
    del done, fresh_extra
    return GridworldState(rng, agent_yx, goal_yx, step_in_episode,
                          episode_return, episode_frames)


class ProcgenState(NamedTuple):
  """GridworldState + the per-env level id the layout derives from,
  plus the per-LEVEL curriculum accumulators (round 22). The two
  [num_levels] leaves are NOT batch-leading: like `rng`, they are
  replicated BY NAME under a mesh (anakin.init_env_carry — shape
  sniffing would mis-shard them whenever num_levels == batch)."""
  rng: Any
  agent_yx: Any
  goal_yx: Any
  step_in_episode: Any
  episode_return: Any
  episode_frames: Any
  level_id: Any      # i32 [B] — index into the finite level set
  level_scores: Any  # f32 [num_levels] — curriculum priority EMAs
  level_visits: Any  # f32 [num_levels] — cumulative transition counts


class ProcgenCore(GridworldCore):
  """Procgen-style parameterized gridworld: per-episode level ids
  index a finite level set; each level's wall layout is derived
  IN-GRAPH from its id (`fold_in(layout_key, level_id)` → bernoulli
  wall mask with start/goal corners cleared), so `num_levels` distinct
  layouts ride one compiled program — the procgen recipe (level-set
  generalization) with zero host involvement. Walls veto movement
  (the agent stays put); the goal is the far corner.
  """

  def __init__(self, height=24, width=32, episode_length=16,
               num_action_repeats=1, num_actions=4, grid_size=5,
               num_levels=8, wall_density=0.25, layout_seed=1234,
               curriculum='uniform', curriculum_temperature=1.0,
               curriculum_eps=0.1):
    super().__init__(height=height, width=width,
                     episode_length=episode_length,
                     num_action_repeats=num_action_repeats,
                     num_actions=num_actions, grid_size=grid_size)
    if num_levels < 1:
      raise ValueError(f'num_levels must be >= 1, got {num_levels}')
    from scalable_agent_tpu import population
    if curriculum not in population.CURRICULUM_MODES:
      raise ValueError(
          f'unknown curriculum {curriculum!r} '
          f'(modes: {", ".join(population.CURRICULUM_MODES)})')
    self.num_levels = num_levels
    self.wall_density = wall_density
    self.layout_seed = layout_seed
    self.curriculum = curriculum
    self.curriculum_temperature = curriculum_temperature
    self.curriculum_eps = curriculum_eps

  def _walls(self, level_id):
    """[B, G, G] bool wall mask, a pure function of the level id."""
    def one(lid):
      key = jax.random.fold_in(jax.random.PRNGKey(self.layout_seed),
                               lid)
      walls = jax.random.bernoulli(key, self.wall_density,
                                   (self.grid, self.grid))
      # Start and goal corners always open (every level is playable
      # at both ends; connectivity in between is the level's hazard).
      walls = walls.at[0, 0].set(False)
      walls = walls.at[self.grid - 1, self.grid - 1].set(False)
      return walls
    return jax.vmap(one)(level_id)

  def _goal_corner(self, batch):
    corner = jnp.asarray([self.grid - 1, self.grid - 1], jnp.int32)
    return jnp.broadcast_to(corner[None], (batch, 2))

  def _observation(self, state):
    agent = self._cell_plane(state.agent_yx)
    goal = self._cell_plane(state.goal_yx)
    walls = self._walls(state.level_id)  # [B, G, G]
    wall_plane = walls[jnp.arange(walls.shape[0])[:, None, None],
                       self._row_cell[None], self._col_cell[None]]
    frame = jnp.stack(
        [agent.astype(jnp.uint8) * 255, goal.astype(jnp.uint8) * 255,
         wall_plane.astype(jnp.uint8) * 255], axis=-1)
    return (frame, _zero_instr(state.agent_yx.shape[0]))

  def init(self, rng, batch) -> Tuple[ProcgenState, StepOutput]:
    rng, sub = jax.random.split(rng)
    state = ProcgenState(
        rng=rng,
        agent_yx=jnp.zeros((batch, 2), jnp.int32),
        goal_yx=self._goal_corner(batch),
        step_in_episode=jnp.zeros((batch,), jnp.int32),
        episode_return=jnp.zeros((batch,), jnp.float32),
        episode_frames=jnp.zeros((batch,), jnp.int32),
        level_id=jax.random.randint(sub, (batch,), 0,
                                    self.num_levels),
        level_scores=jnp.zeros((self.num_levels,), jnp.float32),
        level_visits=jnp.zeros((self.num_levels,), jnp.float32))
    output = StepOutput(
        reward=jnp.zeros((batch,), jnp.float32),
        info=StepOutputInfo(jnp.zeros((batch,), jnp.float32),
                            jnp.zeros((batch,), jnp.int32)),
        done=jnp.ones((batch,), bool),
        observation=self._observation(state))
    return state, output

  def _blocked(self, state, proposed):
    walls = self._walls(state.level_id)
    return walls[jnp.arange(proposed.shape[0]), proposed[:, 0],
                 proposed[:, 1]]

  def _fresh_episode(self, rng, batch, state):
    """New-episode level draw: uniform, or the round-22 prioritized
    curriculum sampler — one in-graph categorical over the per-level
    score EMAs carried in `state` (population.sample_levels), so a
    driven level distribution costs zero host round trips."""
    if self.curriculum == 'uniform':
      fresh = jax.random.randint(rng, (batch,), 0, self.num_levels)
    else:
      from scalable_agent_tpu import population
      fresh = population.sample_levels(
          rng, state.level_scores, batch,
          self.curriculum_temperature,
          self.curriculum_eps).astype(jnp.int32)
    return self._goal_corner(batch), fresh

  def _replace_episode(self, state, rng, agent_yx, goal_yx,
                       step_in_episode, episode_return, episode_frames,
                       done, fresh_extra):
    return ProcgenState(
        rng, agent_yx, goal_yx, step_in_episode, episode_return,
        episode_frames,
        level_id=jnp.where(done, fresh_extra, state.level_id),
        level_scores=state.level_scores,
        level_visits=state.level_visits)


# The jittable registry anakin.ENV_CORES extends — one name, two
# runtimes (the host wrappers below resolve through the same dict).
JITTABLE_CORES = {'gridworld': GridworldCore, 'procgen': ProcgenCore}


@functools.lru_cache(maxsize=None)
def _host_cpu_device():
  """The CPU device host wrappers pin their tiny batch=1 core steps
  to: on a TPU host, fleet env threads must never queue work on the
  learner chip (under JAX_PLATFORMS=cpu this is just the default)."""
  return jax.local_devices(backend='cpu')[0]


class _JittableHostEnv(base.Environment):
  """Host `envs/base.Environment` over a jittable core at batch=1.

  The fleet-runtime half of the dual registration: dynamics come from
  the SAME core the Anakin runtime scans on device (no twin
  implementation to drift), stepped eagerly on the CPU backend and
  squeezed to the host protocol's scalar shapes. Auto-reset and
  flow-style stats are already inside the core's step.
  """

  _CORE_NAME = None  # subclasses pin this (py_process pickles classes)

  def __init__(self, height, width, num_actions, episode_length,
               seed=0, level_name='', num_action_repeats=1,
               num_levels=None, wall_density=None):
    del level_name  # identity rides the factory's level id stamping
    core_cls = JITTABLE_CORES[self._CORE_NAME]
    extra = {} if num_levels is None else {'num_levels': num_levels}
    if wall_density is not None:
      extra['wall_density'] = wall_density
    self._core = core_cls(height=height, width=width,
                          episode_length=episode_length,
                          num_action_repeats=num_action_repeats,
                          num_actions=num_actions, **extra)
    with jax.default_device(_host_cpu_device()):
      self._state, out = self._core.init(jax.random.PRNGKey(seed), 1)
    self._obs = self._host_obs(out)

  def _host_obs(self, out):
    frame, instr = out.observation
    return (np.asarray(frame[0]), np.asarray(instr[0]))

  def initial(self):
    return self._obs

  def step(self, action):
    with jax.default_device(_host_cpu_device()):
      self._state, out = self._core.step(
          self._state, jnp.asarray([int(action)], jnp.int32))
    self._obs = self._host_obs(out)
    return (np.float32(np.asarray(out.reward)[0]),
            np.bool_(np.asarray(out.done)[0]), self._obs)

  @staticmethod
  def _tensor_specs(method_name, unused_kwargs, constructor_kwargs):
    h = constructor_kwargs.get('height', 24)
    w = constructor_kwargs.get('width', 32)
    if method_name == 'initial':
      return base.observation_specs(h, w, MAX_INSTRUCTION_LEN)
    if method_name == 'step':
      return base.step_output_specs(h, w, MAX_INSTRUCTION_LEN)
    return None


class GridworldEnv(_JittableHostEnv):
  _CORE_NAME = 'gridworld'


class ProcgenEnv(_JittableHostEnv):
  _CORE_NAME = 'procgen'


HOST_ENVS = {'gridworld': GridworldEnv, 'procgen': ProcgenEnv}

# The factory's head-size default per backend (config.num_actions=None).
DEFAULT_NUM_ACTIONS = {'gridworld': 4, 'procgen': 4}
