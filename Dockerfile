# Deployment image for scalable_agent_tpu (build/deploy parity with the
# reference's Dockerfile — reference: Dockerfile ≈L1–50, which builds
# DeepMind Lab + TF1; here: JAX TPU + the C++ host batcher).
#
# Build:  docker build -t scalable-agent-tpu .
# Train:  docker run --privileged scalable-agent-tpu \
#           python experiment.py --mode=train --level_name=dmlab30
#
# TPU access requires the libtpu runtime of the host VM (Cloud TPU VMs
# mount it automatically with --privileged); for CPU-only smoke runs no
# flags are needed (env_backend=fake/bandit).
#
# DeepMind Lab / ALE are NOT baked in (they are external native
# dependencies exactly as in the reference); install them in a derived
# image and the import-guarded adapters (envs/dmlab.py, envs/atari.py)
# pick them up.

FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
      g++ make && \
    rm -rf /var/lib/apt/lists/*

# TPU-enabled JAX + the framework's python dependencies.
RUN pip install --no-cache-dir \
      "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
      flax optax orbax-checkpoint chex einops numpy absl-py pytest

WORKDIR /app
COPY scalable_agent_tpu/ scalable_agent_tpu/
COPY tests/ tests/
COPY scripts/ scripts/
COPY docs/ docs/
COPY experiment.py bench.py __graft_entry__.py README.md LICENSE ./

# Native host batcher (ctypes; no TF/pybind dependency).
RUN make -C scalable_agent_tpu/ops/batcher

# Smoke-verify the image: unit tests on a virtual CPU mesh.
RUN python -m pytest tests/test_vtrace.py tests/test_dynamic_batching.py -q

ENTRYPOINT []
CMD ["python", "experiment.py", "--helpshort"]
