"""CLI entry point — flag-compatible with the reference's experiment.py.

Flag names mirror the reference (reference: experiment.py ≈L30–75,
tf.app.flags definitions) so an operator of the reference finds the
same knobs:

  python experiment.py --mode=train --level_name=explore_goal_locations_small \
      --num_actors=48 --batch_size=32 --total_environment_frames=1000000000
  python experiment.py --mode=test --level_name=dmlab30 --test_num_episodes=10

TPU-build additions are grouped at the bottom (env backend selection,
mesh width, dtype). The reference's --job_name/--task multi-process
topology is replaced by jax.distributed (see
scalable_agent_tpu/parallel/distributed.py): every host runs the same
command and the mesh spans them.
"""

import dataclasses
import logging

from absl import app, flags

from scalable_agent_tpu.config import Config

_DEFAULTS = Config()

flags.DEFINE_string('logdir', _DEFAULTS.logdir, 'Experiment directory.')
flags.DEFINE_enum('mode', 'train', ['train', 'test', 'anakin'],
                  'Run mode. mode=anakin is the LEGACY research loop '
                  '(parallel/anakin.train: summaries + checkpoint '
                  'only); production Anakin runs are '
                  '--mode=train --runtime=anakin, which adds the full '
                  'lifecycle (health ladder, SLO verdict, incidents).')
flags.DEFINE_integer('test_num_episodes', _DEFAULTS.test_num_episodes,
                     'Episodes per level in test mode.')
flags.DEFINE_integer('task', _DEFAULTS.task,
                     'Process index in multi-host mode (-1: single).')
flags.DEFINE_string('job_name', _DEFAULTS.job_name,
                    "Role: 'learner' (default) or 'actor'. An actor "
                    'job runs an env fleet with CPU inference and '
                    'streams unrolls to --learner_address (the '
                    "reference's --job_name=actor gRPC topology). "
                    'Learner-side multi-CHIP roles are derived from '
                    'jax.distributed, not this flag.')
flags.DEFINE_string('learner_address', _DEFAULTS.learner_address,
                    'host:port of the learner ingest server '
                    '(--job_name=actor).')
flags.DEFINE_integer('remote_actor_port', _DEFAULTS.remote_actor_port,
                     'Learner: listen for remote actor hosts on this '
                     'port (0 = disabled).')
flags.DEFINE_string('remote_actor_bind_host',
                    _DEFAULTS.remote_actor_bind_host,
                    'Learner: interface the ingest server binds '
                    '(default loopback-only). The wire is '
                    'unauthenticated pickle — for real actor hosts, '
                    'explicitly bind a cluster-internal interface; '
                    'never expose the port publicly.')
flags.DEFINE_string('remote_params_dtype',
                    _DEFAULTS.remote_params_dtype,
                    'LEGACY spelling of --publish_codec: \'\' defers '
                    "to the codec, 'bfloat16' forces the bf16 cast.")
flags.DEFINE_float('remote_publish_secs',
                   _DEFAULTS.remote_publish_secs,
                   'Min seconds between param snapshots published to '
                   'remote actor hosts; the main knob on learner '
                   'weight egress (hosts x blob_bytes / this) and '
                   'remote policy staleness (docs/PERF.md).')
flags.DEFINE_float('actor_reconnect_secs',
                   _DEFAULTS.actor_reconnect_secs,
                   'Actor: on disconnect, retry the learner for this '
                   'many seconds (survives a learner restart — size '
                   'it ABOVE the learner restart budget of restore + '
                   'recompile, ~90s; validate_transport warns '
                   'otherwise); 0 = exit on disconnect. Default '
                   'nonzero since round 11 (docs/RUNBOOK.md §8).')
flags.DEFINE_float('remote_heartbeat_secs',
                   _DEFAULTS.remote_heartbeat_secs,
                   'Transport heartbeat cadence (protocol v6, '
                   'negotiated off for v5 peers): idle actors ping '
                   'inside the reaping window, and the learner emits '
                   "'busy' keepalives while backpressure holds an "
                   'ack. 0 = no heartbeats (docs/TRANSPORT.md).')
flags.DEFINE_float('remote_conn_idle_timeout_secs',
                   _DEFAULTS.remote_conn_idle_timeout_secs,
                   'Reap ingest/param-lane connections that received '
                   'no bytes for this long (half-open peers used to '
                   'pin a reader forever); doubles as the mid-frame '
                   'stall + send no-progress deadline and the '
                   "actor's I/O deadline on a silent learner. "
                   '0 = never reap, no deadlines.')
flags.DEFINE_integer('num_actors', _DEFAULTS.num_actors,
                     'Actor (environment) count.')
flags.DEFINE_integer('total_environment_frames',
                     _DEFAULTS.total_environment_frames,
                     'Training length in env frames (after action '
                     'repeat).')
flags.DEFINE_integer('batch_size', _DEFAULTS.batch_size,
                     'Learner batch size (unrolls per SGD step).')
flags.DEFINE_integer('unroll_length', _DEFAULTS.unroll_length,
                     'Trajectory unroll length T (learner sees T+1).')
flags.DEFINE_integer('num_action_repeats', _DEFAULTS.num_action_repeats,
                     'Env frames per agent action.')
flags.DEFINE_integer('seed', _DEFAULTS.seed, 'Random seed.')
flags.DEFINE_float('entropy_cost', _DEFAULTS.entropy_cost,
                   'Entropy cost/multiplier.')
flags.DEFINE_float('baseline_cost', _DEFAULTS.baseline_cost,
                   'Baseline cost/multiplier.')
flags.DEFINE_float('discounting', _DEFAULTS.discounting,
                   'Discounting factor.')
flags.DEFINE_enum('reward_clipping', _DEFAULTS.reward_clipping,
                  ['abs_one', 'soft_asymmetric', 'none'],
                  'Reward clipping.')
flags.DEFINE_string('dataset_path', _DEFAULTS.dataset_path,
                    'Path to dataset needed for psychlab_*, see '
                    'DMLab docs.')
flags.DEFINE_string('level_cache_dir', _DEFAULTS.level_cache_dir,
                    'DMLab compiled-level cache directory override.')
flags.DEFINE_string('level_name', _DEFAULTS.level_name,
                    "Level name, or 'dmlab30' for the full benchmark.")
flags.DEFINE_integer('width', _DEFAULTS.width, 'Frame width.')
flags.DEFINE_integer('height', _DEFAULTS.height, 'Frame height.')
flags.DEFINE_float('learning_rate', _DEFAULTS.learning_rate,
                   'Learning rate.')
flags.DEFINE_float('decay', _DEFAULTS.decay, 'RMSProp decay.')
flags.DEFINE_float('momentum', _DEFAULTS.momentum, 'RMSProp momentum.')
flags.DEFINE_float('epsilon', _DEFAULTS.epsilon, 'RMSProp epsilon.')

# --- TPU-build additions (not in the reference). ---
flags.DEFINE_enum('env_backend', _DEFAULTS.env_backend,
                  ['dmlab', 'atari', 'fake', 'bandit', 'cue_memory',
                   'gridworld', 'procgen'],
                  'Environment backend (fake/bandit/cue_memory are '
                  'simulator-free smoke tasks; gridworld/procgen are '
                  'the pure-JAX family of envs/jittable.py — the same '
                  'task runs under both --runtime values).')
flags.DEFINE_enum('runtime', _DEFAULTS.runtime, ['fleet', 'anakin'],
                  'Training runtime: fleet (host envs -> inference -> '
                  'buffer -> learner, the production pipeline) or '
                  'anakin (act+learn fused into one jitted device '
                  'step for jittable env backends — Podracer '
                  'arXiv:2104.06272 — under the same run lifecycle: '
                  'checkpoints, health ladder, SLO verdict, JSONL '
                  'streams; docs/PARALLELISM.md, RUNBOOK §13).')
flags.DEFINE_bool('anakin_filler', _DEFAULTS.anakin_filler,
                  'Hybrid filler fleets (fleet runtime): run one '
                  'bounded Anakin self-play step on the learner chips '
                  'whenever the prefetcher has no staged batch ready '
                  '(a staged batch is never delayed by more than one '
                  'filler step); fresh-frame clocks unchanged, filler '
                  'work accounted separately. Default OFF pending the '
                  'docs/PERF.md r13 accept/reject call.')
flags.DEFINE_string('filler_backend', _DEFAULTS.filler_backend,
                    "Filler env core ('' = auto: the run's backend "
                    "when jittable, else 'bandit').")
flags.DEFINE_integer('filler_batch_size', _DEFAULTS.filler_batch_size,
                     'Filler rollout batch (0 = auto: batch_size).')
flags.DEFINE_integer('filler_unroll_length',
                     _DEFAULTS.filler_unroll_length,
                     'Filler rollout length (0 = auto: '
                     'min(unroll_length, 16) — short slices keep the '
                     'yield bound tight).')
flags.DEFINE_float('sticky_action_prob', _DEFAULTS.sticky_action_prob,
                   'Atari: per-frame previous-action repeat '
                   'probability (0.25 = Machado et al. evaluation '
                   'protocol).', lower_bound=0.0, upper_bound=1.0)
flags.DEFINE_enum('torso', _DEFAULTS.torso,
                  ['deep', 'deep_fast', 'shallow'],
                  'Agent torso: deep ResNet (reference), deep_fast '
                  '(stride-2 convs replace the max-pools — the HBM-'
                  'bandwidth operating point, docs/PERF.md; '
                  'THROUGHPUT VARIANT, UNVALIDATED RETURNS: a '
                  'different function whose learning evidence is '
                  'bandit-grade only — run '
                  'scripts/compare_torsos.py before trusting it on '
                  "a real task), or the paper's shallow CNN.")
flags.DEFINE_enum('compute_dtype', _DEFAULTS.compute_dtype,
                  ['float32', 'bfloat16'], 'On-device compute dtype.')
flags.DEFINE_integer('model_parallelism', _DEFAULTS.model_parallelism,
                     'TP width of the device mesh.')
flags.DEFINE_bool('use_py_process', _DEFAULTS.use_py_process,
                  'Host each env in its own OS process.')
flags.DEFINE_bool('use_instruction', _DEFAULTS.use_instruction,
                  'Enable the language/instruction channel. Default '
                  'auto: on for dmlab30 / language_* / psychlab_* '
                  'levels, off otherwise (the encoder costs ~6% step '
                  'time — docs/PERF.md).')
flags.DEFINE_bool('use_popart', _DEFAULTS.use_popart,
                  'PopArt per-task value normalization.')
flags.DEFINE_float('pixel_control_cost', _DEFAULTS.pixel_control_cost,
                   'UNREAL pixel-control aux loss weight (0 = off).')
flags.DEFINE_integer('episode_length', _DEFAULTS.episode_length,
                     'Episode length of the fake/bandit backends.')
flags.DEFINE_integer('publish_params_every',
                     _DEFAULTS.publish_params_every,
                     'Learner steps between actor weight snapshots.')
flags.DEFINE_integer('inference_min_batch', _DEFAULTS.inference_min_batch,
                     'Dynamic batcher minimum merge size. 0 = auto: '
                     'train-mode merges floor at the fleet size, '
                     'bounded by --inference_timeout_ms (the measured '
                     '+53% e2e merge lever, docs/PERF.md); eval '
                     'ignores the floor (its caller count shrinks as '
                     'levels finish).')
flags.DEFINE_integer('inference_max_batch', _DEFAULTS.inference_max_batch,
                     'Dynamic batcher maximum merge size.')
flags.DEFINE_integer('inference_timeout_ms',
                     _DEFAULTS.inference_timeout_ms,
                     'Dynamic batcher flush timeout.')
flags.DEFINE_bool('inference_state_cache',
                  _DEFAULTS.inference_state_cache,
                  'Keep each actor\'s LSTM carry in a device-resident '
                  'state arena (gather/scatter by slot id in-graph) '
                  'instead of shipping it host<->device every step. '
                  'Numerics-identical (parity-gated); measured per '
                  'round by bench.py inference_plane '
                  '(docs/INFERENCE.md).')
flags.DEFINE_integer('inference_pipeline_depth',
                     _DEFAULTS.inference_pipeline_depth,
                     'Merged inference batches in flight on device: '
                     '2 overlaps batch assembly/H2D with the previous '
                     'batch\'s compute; 1 = serial dispatch.')
flags.DEFINE_integer('inference_state_slots',
                     _DEFAULTS.inference_state_slots,
                     'State-arena capacity in slots (state-cache '
                     'mode). 0 = auto: 2x the fleet size (respawn '
                     'headroom).')
flags.DEFINE_enum('inference_admission', _DEFAULTS.inference_admission,
                  ['block', 'shed', 'grow'],
                  'Slot admission when the state arena is exhausted: '
                  'block = deadline-bounded priority waitlist '
                  '(default), shed = deadline rejection counted as '
                  'load shedding, grow = double the arena in place. '
                  'Exhaustion never raises into the learner loop '
                  '(docs/ROBUSTNESS.md actor-plane rows).')
flags.DEFINE_float('inference_admission_timeout_secs',
                   _DEFAULTS.inference_admission_timeout_secs,
                   'Deadline for parked slot acquisitions '
                   '(block/shed admission).')
flags.DEFINE_integer('max_unroll_staleness',
                     _DEFAULTS.max_unroll_staleness,
                     'Ingest admission window in published param '
                     'versions: remote unrolls generated more than '
                     'this many versions behind the current snapshot '
                     'are refused (benign; the actor refetches and '
                     'keeps feeding). 0 = no window.')
flags.DEFINE_integer('fleet_quarantine_after',
                     _DEFAULTS.fleet_quarantine_after,
                     'Consecutive respawns without one completed '
                     'unroll before an actor slot quarantines '
                     '(slots_quarantined in summaries); 0 = retry '
                     'forever (backoff-paced).')
flags.DEFINE_float('preempt_drain_timeout_secs',
                   _DEFAULTS.preempt_drain_timeout_secs,
                   'Preemption drain budget: SIGTERM stops '
                   'admissions, flushes in-flight unrolls, takes a '
                   'verified checkpoint and writes '
                   'resume_manifest.json within this many seconds '
                   '(docs/RUNBOOK.md drain/resume).')
flags.DEFINE_integer('num_actions', _DEFAULTS.num_actions,
                     'Policy head size override (None = backend '
                     'default; Atari: 18 full set, fewer = minimal '
                     'set, validated against the backend).')
flags.DEFINE_float('popart_beta', _DEFAULTS.popart_beta,
                   'PopArt statistics EMA step size.')
flags.DEFINE_float('pixel_control_discount',
                   _DEFAULTS.pixel_control_discount,
                   'UNREAL pixel-control n-step discount.')
flags.DEFINE_integer('pixel_control_cell_size',
                     _DEFAULTS.pixel_control_cell_size,
                     'UNREAL pixel-control spatial cell size.')
flags.DEFINE_bool('pixel_control_integer_rewards',
                  _DEFAULTS.pixel_control_integer_rewards,
                  'Integer-domain pixel-control pseudo-rewards '
                  '(uint8 diff + int32 cell sums; no full-resolution '
                  'float frame temporaries — parity-gated byte '
                  'lever, docs/PERF.md r6). Auto-falls back to the '
                  'f32 form for non-uint8 observations.')
flags.DEFINE_enum('pixel_control_head_impl',
                  _DEFAULTS.pixel_control_head_impl,
                  ['deconv', 'd2s'],
                  'Pixel-control Q-head deconv implementation: '
                  'deconv (nn.ConvTranspose reference form, default) '
                  'or d2s (depth-to-space recast — parameter-'
                  'identical, checkpoint-interchangeable, parity-'
                  'gated; measured per round by bench.py pc_levers).')
flags.DEFINE_bool('pixel_control_q_f32', _DEFAULTS.pixel_control_q_f32,
                  'Cast the pixel-control Q-map to float32 at the '
                  'head (default). False keeps it in the compute '
                  'dtype until the loss gather/max — a byte lever '
                  'that bf16-rounds the Q-values the loss sees.')
flags.DEFINE_float('grad_clip_norm', _DEFAULTS.grad_clip_norm,
                   'Global gradient-norm clip (None = off, the '
                   'reference behavior).')
flags.DEFINE_bool('use_associative_scan', _DEFAULTS.use_associative_scan,
                  'V-trace via lax.associative_scan (log-depth in T) '
                  'instead of the sequential scan.')
flags.DEFINE_bool('use_pallas_vtrace', _DEFAULTS.use_pallas_vtrace,
                  'V-trace via the fused Pallas TPU kernel '
                  '(single-device meshes only).')
flags.DEFINE_integer('scan_unroll', _DEFAULTS.scan_unroll,
                     'LSTM time-scan unroll factor (perf knob; see '
                     'config.py for the measured sweep).')
flags.DEFINE_integer('checkpoint_secs', _DEFAULTS.checkpoint_secs,
                     'Seconds between checkpoints (reference '
                     'save_checkpoint_secs=600).')
flags.DEFINE_integer('checkpoint_check_every_steps',
                     _DEFAULTS.checkpoint_check_every_steps,
                     'Learner steps between cross-host checkpoint-'
                     'cadence broadcasts (multi-host).')
flags.DEFINE_integer('summary_secs', _DEFAULTS.summary_secs,
                     'Seconds between summary flushes (reference '
                     'save_summaries_secs=30).')
flags.DEFINE_integer('queue_capacity_batches',
                     _DEFAULTS.queue_capacity_batches,
                     'Trajectory buffer capacity in batches '
                     '(reference FIFOQueue capacity=1; small keeps '
                     'policy lag bounded).')
flags.DEFINE_integer('staging_depth', _DEFAULTS.staging_depth,
                     'Staged device batches in flight (prefetcher '
                     'depth): 2 overlaps consecutive host-to-device '
                     'transfers with the step; each extra slot adds '
                     'one batch of policy lag.')
flags.DEFINE_enum('staging_mode', _DEFAULTS.staging_mode,
                  ['batch', 'unroll'],
                  'Learner feed staging: batch = host-stack + one '
                  'device_put burst per step (default); unroll = '
                  'per-unroll eager H2D + on-device batch assembly '
                  '(the step-boundary burst becomes a trickle '
                  'overlapped with compute — parity-gated, measured '
                  'per round by bench.py learner_plane; docs/PERF.md '
                  'r8).')
# --- Sample reuse (round 10; IMPACT arXiv 1912.00167 — docs/PERF.md
# r9, RUNBOOK §5 knob guidance). ---
flags.DEFINE_enum('surrogate', _DEFAULTS.surrogate,
                  ['vtrace', 'impact'],
                  'Loss surrogate: vtrace (reference IMPALA path, '
                  'default) or impact (clipped-target surrogate: '
                  'on-device target-network anchor for the V-trace IS '
                  'ratios plus a PPO-style clip of the current/target '
                  'ratio — the staleness-tolerant form sample reuse '
                  'needs; bit-identical to vtrace at replay_k=1, '
                  'replay_ratio=0, target_update_interval=1).')
flags.DEFINE_float('impact_epsilon', _DEFAULTS.impact_epsilon,
                   'Clip width of the impact surrogate\'s '
                   'current/target policy ratio.')
flags.DEFINE_integer('target_update_interval',
                     _DEFAULTS.target_update_interval,
                     'Learner steps between target-network refreshes '
                     '(impact surrogate; in-graph select, no host '
                     'round trip). Interacts with replay staleness: '
                     'the anchor must not refresh slower than the '
                     'replay window ages (RUNBOOK §5).')
flags.DEFINE_integer('replay_k', _DEFAULTS.replay_k,
                     'Times each staged device batch is served to the '
                     'learner before release (no re-stage, no added '
                     'H2D). Default 1 = no reuse, per the measured '
                     'accept/reject discipline — bench.py\'s replay '
                     'stage carries the flip call.')
flags.DEFINE_float('replay_ratio', _DEFAULTS.replay_ratio,
                   'Fraction of each batch\'s unroll slots drawn from '
                   'the circular replay tier ([0, 1); 0 = off). '
                   'Replayed unrolls re-stage (one H2D each), unlike '
                   'replay_k re-serves.')
flags.DEFINE_integer('replay_capacity_unrolls',
                     _DEFAULTS.replay_capacity_unrolls,
                     'Circular replay tier capacity in unrolls '
                     '(0 = auto: 4x batch). Oldest entries overwrite '
                     'IMPACT-style when full.')
flags.DEFINE_integer('replay_max_staleness',
                     _DEFAULTS.replay_max_staleness,
                     'Replay eviction window in PUBLISHED '
                     'PARAM-VERSION deltas — the same unit as '
                     '--max_unroll_staleness (which gates ingest '
                     'admission; this gates re-serving). 0 = defer '
                     'to max_unroll_staleness; both 0 = no bound.')
flags.DEFINE_enum('publish_codec', _DEFAULTS.publish_codec,
                  ['bf16', 'f32', 'int8'],
                  'Wire codec for served param snapshots: bf16 '
                  '(default) halves learner weight egress, actors '
                  'upcast on receipt; f32 ships exact float32; int8 '
                  'absmax-quantizes (runtime/codec.py, wire v10 — '
                  'v<=9 peers still get bf16) and stores resident '
                  'serving versions quantized. Parity-gated on '
                  'greedy action agreement (bench serving stage).')
flags.DEFINE_integer('ingest_workers', _DEFAULTS.ingest_workers,
                     'Validate/commit workers behind the remote-'
                     'ingest reader threads (0 = auto).')
flags.DEFINE_bool('wire_crc', _DEFAULTS.wire_crc,
                  'Protocol v7 per-frame CRC32C trailers on the '
                  'remote lanes (negotiated off for v5/v6 peers): a '
                  'corrupt unroll is refused before the buffer put, '
                  'a corrupt param blob before install '
                  '(docs/TRANSPORT.md v7).')
flags.DEFINE_bool('ckpt_digests', _DEFAULTS.ckpt_digests,
                  'Record per-file content digests on verified '
                  'checkpoint saves and re-verify them in the '
                  'restore ladder — bit rot on a committed step '
                  'falls back instead of restoring garbage.')
flags.DEFINE_bool('sdc_check', _DEFAULTS.sdc_check,
                  'Cross-replica param-fingerprint SDC sentinel '
                  '(pure-DP meshes with >= 2 data replicas): replica '
                  'disagreement escalates through the health ladder '
                  '(docs/ROBUSTNESS.md, docs/RUNBOOK.md §9).')
flags.DEFINE_bool('sdc_allgather', _DEFAULTS.sdc_allgather,
                  'All-gather the per-replica SDC fingerprints '
                  'in-graph so the sentinel runs on multi-process '
                  'meshes too (round 17); false restores the '
                  'single-controller gate.')
flags.DEFINE_string('tp_compute', _DEFAULTS.tp_compute,
                    'How TP matmuls execute: auto (sharded on '
                    'TPU/GPU, the gathered workaround on CPU — this '
                    'jaxlib mis-computes differentiated programs '
                    'over model-sharded leaves), sharded, or '
                    'gathered (docs/PARALLELISM.md).')
flags.DEFINE_string('sharding_rules', _DEFAULTS.sharding_rules,
                    'Partition-rule set the sharding registry '
                    'resolves every placement from (parallel/'
                    'sharding.py): auto (megatron when '
                    'model_parallelism > 1, else replicated), '
                    'replicated, or megatron '
                    '(docs/PARALLELISM.md).')
flags.DEFINE_bool('replay_crc', _DEFAULTS.replay_crc,
                  'Verify replay-tier entries against their '
                  'insert-time CRC at every serve; rot evicts '
                  'instead of re-serving.')
flags.DEFINE_bool('telemetry_trace', _DEFAULTS.telemetry_trace,
                  'Per-unroll trace spans (protocol v8) + the '
                  'traces.jsonl stream and policy-lag attribution '
                  '(scripts/trace_report.py; docs/OBSERVABILITY.md). '
                  'Measured overhead below noise — docs/PERF.md r11.')
flags.DEFINE_integer('telemetry_flight_len',
                     _DEFAULTS.telemetry_flight_len,
                     'Flight-recorder depth: recent trace records + '
                     'registry snapshots dumped with halt bundles '
                     'and rollback incidents.')
# --- SLO engine (round 14; slo.py, docs/OBSERVABILITY.md). ---
flags.DEFINE_bool('slo_engine', _DEFAULTS.slo_engine,
                  'Declarative SLO evaluation over the metrics '
                  'registry: burn-rate windows, slo_violation '
                  'incidents, the per-run SLO_VERDICT.json go/no-go '
                  'artifact, and triggered deep diagnostics '
                  '(docs/OBSERVABILITY.md SLO inventory; overhead '
                  'measured, docs/PERF.md r12).')
flags.DEFINE_string('slo_spec', _DEFAULTS.slo_spec,
                    'JSON objective-set file; empty = the shipped '
                    'default objectives (slo.DEFAULT_OBJECTIVES).')
flags.DEFINE_float('slo_fast_window_secs',
                   _DEFAULTS.slo_fast_window_secs,
                   'Fast burn window for objectives that do not pin '
                   'their own (must be fully violating to burn).')
flags.DEFINE_float('slo_slow_window_secs',
                   _DEFAULTS.slo_slow_window_secs,
                   'Slow burn window (>= half violating confirms a '
                   'sustained burn).')
flags.DEFINE_float('slo_interval_secs', _DEFAULTS.slo_interval_secs,
                   'Evaluator thread cadence (0 = derive from '
                   'summary_secs; the summary block also evaluates).')
flags.DEFINE_bool('slo_capture', _DEFAULTS.slo_capture,
                  'Triggered deep diagnostics on the first burn of a '
                  'page-severity objective: flight dump + trace '
                  'slice + a bounded jax.profiler capture into '
                  '<logdir>/diagnostics/ (one per objective per run).')
flags.DEFINE_integer('slo_capture_steps', _DEFAULTS.slo_capture_steps,
                     'Learner steps a triggered profiler capture '
                     'covers.')
flags.DEFINE_string('slo_fps_baseline', _DEFAULTS.slo_fps_baseline,
                    'Per-host fps baseline file for the fps_floor '
                    'objective (JSON {hostname: {"fps": value}}; '
                    'scripts/slo_report.py --update-fps-baseline '
                    'records one). Empty = objective reads '
                    'no_baseline.')
# --- Self-healing controller (round 15; controller.py,
# docs/RUNBOOK.md §12). ---
flags.DEFINE_enum('controller', _DEFAULTS.controller,
                  ['off', 'observe', 'act'],
                  'Verdict-to-actuation loop over the SLO engine: '
                  'observe (default) dry-runs the policy table into '
                  'CONTROLLER_LOG.json; act applies the bounded '
                  'moves (replay_k, admission mode, publish '
                  'cadence, fleet size); off removes the thread. '
                  'CHAOS_STORM=controller is the acceptance drill.')
flags.DEFINE_string('controller_policy', _DEFAULTS.controller_policy,
                    'JSON rule-list file; empty = the shipped '
                    'controller.DEFAULT_RULES table '
                    '(docs/OBSERVABILITY.md).')
flags.DEFINE_float('controller_interval_secs',
                   _DEFAULTS.controller_interval_secs,
                   'Controller tick cadence (0 = share the SLO '
                   "engine's derived interval).")
flags.DEFINE_integer('controller_replay_k_max',
                     _DEFAULTS.controller_replay_k_max,
                     'Hard upper bound for the replay_k actuator '
                     '(the bounded-move guarantee).')
flags.DEFINE_float('controller_publish_secs_max',
                   _DEFAULTS.controller_publish_secs_max,
                   'Hard upper bound for the publish-cadence '
                   'actuator, seconds.')
flags.DEFINE_float('fleet_probation_secs',
                   _DEFAULTS.fleet_probation_secs,
                   'Quarantine probation cool-down before a '
                   'rehabilitation attempt (fleet slots and the '
                   "remote client's CRC self-quarantine).")
flags.DEFINE_integer('pod_max_hosts', _DEFAULTS.pod_max_hosts,
                     'Upper bound for the pod_size actuator (elastic '
                     'pod membership): the controller publishes the '
                     'desired actor-host count to POD_TARGET.json '
                     'for the cluster supervisor to reconcile. '
                     '0 = actuator off.')
flags.DEFINE_bool('lock_order_check', _DEFAULTS.lock_order_check,
                  'Arm runtime lock-order detection for this run: '
                  'the threaded modules\' locks record the '
                  'process-wide acquisition graph and a cycle (a '
                  'latent ABBA deadlock) lands as a durable '
                  'lock_order_inversion incident + the '
                  'analysis/lock_cycles counter. Default off in '
                  'production; tests/chaos run armed '
                  '(docs/STATIC_ANALYSIS.md).')
flags.DEFINE_integer('serving_resident_versions',
                     _DEFAULTS.serving_resident_versions,
                     'Policy versions resident concurrently in the '
                     'inference version table (1 = the classic '
                     'single snapshot). Re-publishing a resident '
                     'version flips live without a tree copy; LRU '
                     'eviction spares pinned + live entries.')
flags.DEFINE_float('serving_hbm_budget_mb',
                   _DEFAULTS.serving_hbm_budget_mb,
                   'Optional byte budget (MB) over resident serving '
                   'versions; 0 = count cap only.')
flags.DEFINE_float('serving_ab_fraction',
                   _DEFAULTS.serving_ab_fraction,
                   'Fraction of merged inference calls served by the '
                   'A/B candidate version (newest non-live resident '
                   'unless set_ab pins one).')
flags.DEFINE_float('serving_shadow_fraction',
                   _DEFAULTS.serving_shadow_fraction,
                   'Fraction of merged calls also replayed against '
                   'the shadow version (pure step, no RNG/arena '
                   'effects) and scored on greedy agreement into '
                   'the serving/shadow_divergence gauge.')
flags.DEFINE_bool('serving_aot', _DEFAULTS.serving_aot,
                  'Pre-compile serving steps per (batch bucket, '
                  'params structure) at publish/warmup so a version '
                  'flip never pays first-call compile on the serve '
                  'path. Off pending chip rows (docs/PERF.md).')
flags.DEFINE_string('serving_replicas', _DEFAULTS.serving_replicas,
                    'Comma-separated learner replica addresses an '
                    'actor host routes inference over (wire v10 '
                    'health-weighted round-robin; drains on leave). '
                    "'' = host-local inference.")
flags.DEFINE_bool('health_watchdog', _DEFAULTS.health_watchdog,
                  'Learner failure domain (health.py): skip '
                  'non-finite updates on device, roll back to the '
                  'last-known-good checkpoint after K consecutive '
                  'bad steps, halt with a diagnostic bundle after '
                  'the rollback budget (docs/ROBUSTNESS.md).')
flags.DEFINE_integer('health_check_every_steps',
                     _DEFAULTS.health_check_every_steps,
                     'Host-side sentinel read cadence (each check is '
                     'one tiny device_get; the device-side skip '
                     'protects params regardless).')
flags.DEFINE_integer('health_window', _DEFAULTS.health_window,
                     'Recent health checks retained (sliding window '
                     'for the relative detectors + the halt '
                     "bundle's metrics tail).")
flags.DEFINE_integer('health_min_window', _DEFAULTS.health_min_window,
                     'Good samples required before the relative '
                     'detectors (loss explosion, sigma divergence) '
                     'arm.')
flags.DEFINE_integer('health_rollback_after',
                     _DEFAULTS.health_rollback_after,
                     'Consecutive bad steps before an automatic '
                     'checkpoint rollback.')
flags.DEFINE_integer('health_max_rollbacks',
                     _DEFAULTS.health_max_rollbacks,
                     'Rollbacks granted before the watchdog halts '
                     'the run with a diagnostic bundle.')
flags.DEFINE_float('health_loss_explosion_factor',
                   _DEFAULTS.health_loss_explosion_factor,
                   'Finite-loss explosion threshold: |loss| beyond '
                   'this multiple of the window median flags the '
                   'step bad.')
flags.DEFINE_float('health_sigma_divergence_factor',
                   _DEFAULTS.health_sigma_divergence_factor,
                   'PopArt sigma_max beyond this multiple of its '
                   'window median flags the step bad.')
flags.DEFINE_string('profile_dir', _DEFAULTS.profile_dir,
                    'Capture a jax.profiler trace of a few learner '
                    'steps into this directory.')
flags.DEFINE_integer('profile_start_step', _DEFAULTS.profile_start_step,
                     'Learner step at which the trace starts.')
flags.DEFINE_integer('profile_num_steps', _DEFAULTS.profile_num_steps,
                     'Learner steps the trace covers.')
flags.DEFINE_string('coordinator_address', _DEFAULTS.coordinator_address,
                    'jax.distributed coordinator (host:port); empty '
                    'for single-host.')
flags.DEFINE_integer('num_processes', _DEFAULTS.num_processes,
                     'Total process count for jax.distributed.')
flags.DEFINE_integer('process_id', _DEFAULTS.process_id,
                     "This process's index in [0, num_processes); -1 "
                     'defers to max(--task, 0) (the reference\'s '
                     '--task spelling).')
flags.DEFINE_enum('curriculum', _DEFAULTS.curriculum,
                  ['uniform', 'regret', 'td'],
                  'In-graph auto-curriculum over the procgen level '
                  'set (population.py): uniform keeps the reference '
                  'draw; regret prioritizes positive value loss per '
                  'level (the PLR proxy), td prioritizes |TD error|. '
                  'Sampler + score update ride INSIDE the fused '
                  'anakin step — zero host round trips per level '
                  'decision.')
flags.DEFINE_float('curriculum_temperature',
                   _DEFAULTS.curriculum_temperature,
                   'Softmax temperature over per-level scores.')
flags.DEFINE_float('curriculum_eps', _DEFAULTS.curriculum_eps,
                   'Uniform mixing floor of the curriculum sampler '
                   '(every level keeps nonzero visitation — the '
                   'staleness escape hatch).')
flags.DEFINE_float('curriculum_alpha', _DEFAULTS.curriculum_alpha,
                   'Per-level score EMA step size.')
flags.DEFINE_float('curriculum_decay', _DEFAULTS.curriculum_decay,
                   'Per-fused-step score decay for levels the batch '
                   'did not visit (stale scores lose authority).')
flags.DEFINE_integer('procgen_num_levels', _DEFAULTS.procgen_num_levels,
                     'Procgen level-set size (the curriculum\'s '
                     'support); honored by both runtimes.')
flags.DEFINE_float('procgen_wall_density', _DEFAULTS.procgen_wall_density,
                   'Bernoulli wall rate of each procgen layout; '
                   'raising it past ~0.35 makes some levels '
                   'goal-unreachable (the skewed-difficulty regime '
                   'the regret curriculum exploits).')
flags.DEFINE_string('fleet_tasks', _DEFAULTS.fleet_tasks,
                    "Heterogeneous fleet spec, e.g. "
                    "'bandit:2,gridworld:1': one fleet's actors "
                    'split across jittable suites by weight '
                    '(largest-remainder apportionment = the per-task '
                    "frame budget). '' = single-task (unchanged).")
flags.DEFINE_integer('pbt_population', _DEFAULTS.pbt_population,
                     'Minimal PBT (population.py): >= 2 trains that '
                     'many anakin learner replicas under one driver '
                     'invocation with within-suite exploit/explore '
                     'over (learning_rate, entropy_cost); 0 = off.')
flags.DEFINE_integer('pbt_round_frames', _DEFAULTS.pbt_round_frames,
                     'Frames each member trains between PBT decision '
                     'points (0 = auto: a quarter of the per-member '
                     'budget).')
flags.DEFINE_string('pbt_suites', _DEFAULTS.pbt_suites,
                    'Comma-separated jittable backends assigned '
                    "round-robin to population members; '' = the "
                    "run's own env_backend.")
flags.DEFINE_float('pbt_quantile', _DEFAULTS.pbt_quantile,
                   'Bottom/top fraction per suite for exploit '
                   'decisions (in (0, 0.5]).')
flags.DEFINE_float('pbt_perturb', _DEFAULTS.pbt_perturb,
                   'Explore step: each inherited hyper multiplies or '
                   'divides by this factor (fair coin).')
flags.DEFINE_bool('pbt_vectorized', _DEFAULTS.pbt_vectorized,
                  'Fuse the population: vmap the N members over a '
                  'leading member axis so each round trains ONE '
                  'compiled Anakin program (hypers become traced '
                  'per-member scalars; exploit is an on-device '
                  'stacked-slice copy). Single jittable suite only; '
                  'a model-axis mesh falls back to the serial loop.')
flags.DEFINE_string('compile_cache_dir', _DEFAULTS.compile_cache_dir,
                    'Persistent XLA compilation cache, armed before '
                    "backend spin-up. 'auto' = <logdir>/.jax_cache "
                    'on accelerator hosts (skipped on CPU-pinned '
                    'processes, where executable reload is '
                    "unreliable); '' disables; else the cache dir "
                    'itself (shareable across runs and processes, '
                    'armed on any backend).')

FLAGS = flags.FLAGS


def config_from_flags() -> Config:
  cfg = Config()
  overrides = {}
  for field in dataclasses.fields(Config):
    if field.name in FLAGS:
      overrides[field.name] = getattr(FLAGS, field.name)
  return dataclasses.replace(cfg, **overrides)


def main(argv):
  del argv
  logging.basicConfig(
      level=logging.INFO,
      format='%(asctime)s %(name)s %(levelname)s %(message)s')
  # Before the driver/JAX imports below: the one-time fork creating
  # the forkserver (default env-process start method) must happen
  # while this process is still quiet — see runtime/py_process.py.
  from scalable_agent_tpu.runtime.py_process import warm_forkserver
  warm_forkserver()
  # Preemption safety: SIGTERM (k8s eviction, TPU-VM maintenance)
  # must not kill the process mid-step. Round 9 upgrades the response
  # from "unwind through the finally block" to a GRACEFUL DRAIN: the
  # first SIGTERM sets the drain event — driver.train stops
  # admissions, flushes in-flight unrolls through the learner, takes
  # a verified checkpoint and writes resume_manifest.json, then
  # returns cleanly (docs/RUNBOOK.md §7). A second SIGTERM (the
  # platform's kill escalation arriving before the drain finished)
  # falls back to the old raise-through-finally path; a third is
  # ignored so it cannot abort the final save. Only the train loop
  # consumes the drain event — every other mode (actor host, anakin,
  # eval) keeps the old first-SIGTERM-raises behavior, or its one
  # graceful shot would be absorbed by an event nobody reads.
  import signal
  import threading
  drain_event = threading.Event()
  drain_supported = threading.Event()

  def _terminate(signum, frame):
    if drain_supported.is_set() and not drain_event.is_set():
      drain_event.set()
      return
    signal.signal(signal.SIGTERM, signal.SIG_IGN)
    raise KeyboardInterrupt(f'signal {signum}')

  signal.signal(signal.SIGTERM, _terminate)
  # Multi-process spin-up (round 17): driver.train/evaluate own the
  # join (distributed.maybe_initialize from the config's coordinator
  # fields — idempotent, enables CPU gloo collectives before the
  # backend exists). Actor hosts deliberately DON'T join: they feed
  # the learner over TCP ingest, and joining would put their devices
  # into the training mesh.
  cfg = config_from_flags()
  if cfg.coordinator_address and cfg.job_name == 'actor':
    raise app.UsageError(
        '--job_name=actor does not join jax.distributed (actor hosts '
        'feed over --learner_address TCP ingest); drop '
        '--coordinator_address on actor hosts')
  if cfg.coordinator_address and cfg.mode == 'anakin':
    # The legacy research loop never calls driver.train, so the
    # coordinator flags would be silently ignored and every host
    # would train an independent replica — the process_count guard
    # below can't catch it because nothing ever joins.
    raise app.UsageError(
        '--mode=anakin is the single-host legacy loop and does not '
        'join jax.distributed; drop the coordinator flags (multi-host '
        'runs use --mode=train)')
  if cfg.job_name == 'actor':
    # Actor-only host: no TPU, no learner — stream unrolls to the
    # learner's ingest server (reference ≈L625 actor loop).
    if not cfg.learner_address:
      raise app.UsageError('--job_name=actor needs --learner_address')
    if cfg.mode != 'train':
      raise app.UsageError('--job_name=actor only makes sense with '
                           '--mode=train (--mode=test runs its own '
                           'envs)')
    from scalable_agent_tpu.runtime import remote
    remote.run_remote_actor(cfg, cfg.learner_address,
                            task=max(cfg.task, 0))
    return
  from scalable_agent_tpu import driver
  if cfg.mode == 'train':
    # Both runtimes consume the drain event: the fleet loop drains
    # (flush + verified checkpoint + resume manifest); the anakin loop
    # stops cleanly at the next fused-step boundary with its tail
    # checkpoint + SLO verdict (driver.train dispatches on --runtime).
    drain_supported.set()
    run = driver.train(cfg, drain_event=drain_event)
    logging.info('training done at %d frames', run.frames)
  elif cfg.mode == 'anakin':
    import jax
    from scalable_agent_tpu.parallel import anakin
    if jax.process_count() > 1:
      # Anakin is single-host by design: there is no cross-host batch
      # transport in the fused loop, so each process would train an
      # independent, never-synchronized replica (the failure
      # driver.choose_mesh refuses for multi-host too).
      raise app.UsageError('--mode=anakin is single-host; use '
                           '--mode=train for the multi-host pipeline')
    if cfg.model_parallelism > 1:
      # Anakin shards only the data axis (init_carry); a TP mesh would
      # silently replicate identical compute across the model axis.
      raise app.UsageError('--mode=anakin is data-parallel only; drop '
                           '--model_parallelism')
    # Same mesh policy as driver.train (ADVICE r4: a v5e-8 pod slice
    # must not silently train on one chip): all local devices,
    # model_parallelism honored, warn-and-fallback to single-device
    # when the batch cannot shard.
    carry = anakin.train(cfg, mesh=driver.choose_mesh(cfg))
    logging.info('anakin training done at %d frames',
                 int(carry.train_state.update_steps) *
                 cfg.frames_per_step)
  else:
    driver.evaluate(cfg)


if __name__ == '__main__':
  app.run(main)
