"""Benchmark: learner env-frames/sec on one chip, flagship config.

Two measurements, one JSON line:

1. `value` (headline, reference unit): the jitted IMPALA train step on
   a synthetic resident batch (deep ResNet, T=100, B=32, DMLab 72x96
   frames, bfloat16) — the chip's ceiling, comparable across rounds.
2. `e2e`: the REAL pipeline sustained for ~1 min — process-hosted fake
   envs at 72x96 → C++ dynamic batcher → TrajectoryBuffer →
   BatchPrefetcher → learner on chip — reporting the learner
   consumption rate (the reference's unit, SURVEY §6), the batcher's
   mean merged batch, and buffer occupancy. The gap between (1) and
   (2) is the tuning target; in THIS sandbox (1 host core, TPU behind
   a ~2 ms/dispatch tunnel) the e2e number is host/tunnel-bound, not
   chip-bound.

vs_baseline: BASELINE.json's north star is >=200k env-frames/sec on a
v5e-16 ⇒ 12,500 frames/sec/chip. vs_baseline = value / 12500.

Prints ONE JSON line.
"""

import json
import os
import tempfile
import time


def _time_step(cfg, use_instruction, smoke, h, w):
  import jax
  import jax.numpy as jnp
  from scalable_agent_tpu import learner as learner_lib
  from scalable_agent_tpu.models import ImpalaAgent, init_params
  from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
  from scalable_agent_tpu.testing import make_example_batch

  num_actions = 9  # DMLab DEFAULT_ACTION_SET
  t1, b = cfg.unroll_length + 1, cfg.batch_size
  agent = ImpalaAgent(num_actions=num_actions, torso=cfg.torso,
                      use_instruction=use_instruction,
                      scan_unroll=cfg.scan_unroll, dtype=jnp.bfloat16)
  obs_spec = {'frame': (h, w, 3), 'instr_len': MAX_INSTRUCTION_LEN}
  params = init_params(agent, jax.random.PRNGKey(0), obs_spec)
  batch = make_example_batch(t1, b, h, w, num_actions,
                             MAX_INSTRUCTION_LEN, done_prob=0.01)
  state = learner_lib.make_train_state(params, cfg)
  train_step = learner_lib.make_train_step(agent, cfg)

  # Warmup / compile. The sync barrier is a HOST READBACK of the loss
  # (float(...)), not block_until_ready: through the axon TPU tunnel
  # block_until_ready can return before the remote compute finishes
  # (measured: 10 deep-ResNet steps "completing" in 9 ms, ~500x over
  # MXU peak — impossible); a value readback cannot lie.
  state, metrics = train_step(state, batch)
  float(metrics['total_loss'])

  # Timed: steps chain on the donated state; one readback at the end.
  n = 20 if not smoke else 3
  t0 = time.perf_counter()
  for _ in range(n):
    state, metrics = train_step(state, batch)
  float(metrics['total_loss'])
  dt = (time.perf_counter() - t0) / n
  return cfg.frames_per_step / dt


def bench_synthetic(smoke):
  from scalable_agent_tpu.config import Config

  cfg = Config(batch_size=32 if not smoke else 2,
               unroll_length=100 if not smoke else 4,
               num_action_repeats=4,
               total_environment_frames=int(1e9),
               torso='deep', compute_dtype='bfloat16')
  h, w = (72, 96) if not smoke else (24, 32)
  # Headline: the full flagship model (language encoder ON — dmlab30
  # parity, comparable across rounds).
  fps = _time_step(cfg, True, smoke, h, w)
  # Lever (docs/PERF.md): single-task levels auto-skip the encoder.
  fps_no_instr = None if smoke else _time_step(cfg, False, smoke, h, w)
  return cfg, fps, fps_no_instr


def bench_e2e(smoke):
  """Sustained FPS through the full real pipeline (driver.train on
  process-hosted fake envs), read back from the run's own summaries."""
  from scalable_agent_tpu import driver
  from scalable_agent_tpu.config import Config

  logdir = tempfile.mkdtemp(prefix='bench_e2e_')
  cfg = Config(
      logdir=logdir,
      env_backend='fake',
      num_actions=9,
      num_actors=4 if not smoke else 2,
      batch_size=4 if not smoke else 2,
      unroll_length=100 if not smoke else 5,
      num_action_repeats=4,
      episode_length=50,
      height=72 if not smoke else 24,
      width=96 if not smoke else 32,
      torso='deep' if not smoke else 'shallow',
      compute_dtype='bfloat16' if not smoke else 'float32',
      use_py_process=not smoke,     # smoke: in-process envs (CI speed)
      use_instruction=False,
      total_environment_frames=int(1e9),
      inference_timeout_ms=20,
      checkpoint_secs=10**6,       # no checkpoint traffic in the window
      summary_secs=5 if not smoke else 1,
      seed=1)
  run = driver.train(cfg, max_seconds=65 if not smoke else 8,
                     stall_timeout_secs=120)

  last = {}
  with open(os.path.join(logdir, 'summaries.jsonl')) as f:
    for line in f:
      e = json.loads(line)
      if 'value' in e:
        last[e['tag']] = e['value']  # keep the latest per tag
  return {
      'fps': round(last.get('env_frames_per_sec', 0.0), 1),
      'inference_mean_batch': round(
          last.get('inference_mean_batch', 0.0), 2),
      'buffer_unrolls': last.get('buffer_unrolls', 0.0),
      'actors': cfg.num_actors,
      'batch_size': cfg.batch_size,
      'frames': int(run.frames),
  }


def main():
  # BENCH_SMOKE=1: tiny shapes on CPU — validates bench mechanics in CI
  # without the chip. The driver runs the real thing (no env var, TPU).
  smoke = os.environ.get('BENCH_SMOKE') == '1'
  if smoke:
    import jax
    jax.config.update('jax_platforms', 'cpu')

  cfg, fps, fps_no_instr = bench_synthetic(smoke)
  e2e = None
  if os.environ.get('BENCH_SKIP_E2E') != '1':
    e2e = bench_e2e(smoke)

  baseline_per_chip = 200_000.0 / 16.0  # north star / v5e-16 chips
  out = {
      'metric': 'learner_env_frames_per_sec_per_chip',
      'value': round(fps, 1),
      'unit': ('env-frames/sec (deep ResNet, T=%d, B=%d, bf16, 1 chip%s)'
               % (cfg.unroll_length, cfg.batch_size,
                  ', SMOKE' if smoke else '')),
      'vs_baseline': round(fps / baseline_per_chip, 3),
  }
  if fps_no_instr is not None:
    # The auto-off instruction-encoder lever (single-task configs).
    out['no_instruction_fps'] = round(fps_no_instr, 1)
  if e2e is not None:
    out['e2e'] = e2e
  print(json.dumps(out))


if __name__ == '__main__':
  # Before any JAX initialization, but inside the main guard: the
  # forkserver preloads __main__, so a module-level call would
  # recursively spawn a second server (see runtime/py_process.py).
  from scalable_agent_tpu.runtime.py_process import warm_forkserver
  warm_forkserver()
  main()
