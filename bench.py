"""Benchmark: learner env-frames/sec on one chip, flagship config.

Measures the jitted IMPALA train step (deep ResNet, T=100, B=32,
DMLab 72x96 frames, bfloat16 compute) and reports env-frames/sec in the
reference's unit: batch * unroll * num_action_repeats frames per SGD
step (reference: experiment.py ≈L390; BASELINE.md unit convention).

vs_baseline: BASELINE.json's north star is >=200k env-frames/sec on a
v5e-16 ⇒ 12,500 frames/sec/chip. vs_baseline = value / 12500.

Prints ONE JSON line.
"""

import json
import os
import sys
import time

import numpy as np


def main():
  # BENCH_SMOKE=1: tiny shapes on CPU — validates bench mechanics in CI
  # without the chip. The driver runs the real thing (no env var, TPU).
  smoke = os.environ.get('BENCH_SMOKE') == '1'
  if smoke:
    import jax
    jax.config.update('jax_platforms', 'cpu')
  import jax
  import jax.numpy as jnp
  from scalable_agent_tpu import learner as learner_lib
  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.models import ImpalaAgent, init_params
  from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
  from scalable_agent_tpu.testing import make_example_batch

  num_actions = 9  # DMLab DEFAULT_ACTION_SET
  cfg = Config(batch_size=32 if not smoke else 2,
               unroll_length=100 if not smoke else 4,
               num_action_repeats=4,
               total_environment_frames=int(1e9),
               torso='deep', compute_dtype='bfloat16')
  t1, b = cfg.unroll_length + 1, cfg.batch_size
  h, w = (72, 96) if not smoke else (24, 32)

  agent = ImpalaAgent(num_actions=num_actions, torso=cfg.torso,
                      scan_unroll=cfg.scan_unroll, dtype=jnp.bfloat16)
  obs_spec = {'frame': (h, w, 3), 'instr_len': MAX_INSTRUCTION_LEN}
  params = init_params(agent, jax.random.PRNGKey(0), obs_spec)

  batch = make_example_batch(t1, b, h, w, num_actions,
                             MAX_INSTRUCTION_LEN, done_prob=0.01)

  state = learner_lib.make_train_state(params, cfg)
  train_step = learner_lib.make_train_step(agent, cfg)

  # Warmup / compile. The sync barrier is a HOST READBACK of the loss
  # (float(...)), not block_until_ready: through the axon TPU tunnel
  # block_until_ready can return before the remote compute finishes
  # (measured: 10 deep-ResNet steps "completing" in 9 ms, ~500x over
  # MXU peak — impossible); a value readback cannot lie.
  state, metrics = train_step(state, batch)
  float(metrics['total_loss'])

  # Timed: steps chain on the donated state; one readback at the end.
  n = 20 if not smoke else 3
  t0 = time.perf_counter()
  for _ in range(n):
    state, metrics = train_step(state, batch)
  float(metrics['total_loss'])
  dt = (time.perf_counter() - t0) / n

  frames_per_step = cfg.frames_per_step
  fps = frames_per_step / dt
  baseline_per_chip = 200_000.0 / 16.0  # north star / v5e-16 chips
  print(json.dumps({
      'metric': 'learner_env_frames_per_sec_per_chip',
      'value': round(fps, 1),
      'unit': ('env-frames/sec (deep ResNet, T=%d, B=%d, bf16, 1 chip%s)'
               % (cfg.unroll_length, b, ', SMOKE' if smoke else '')),
      'vs_baseline': round(fps / baseline_per_chip, 3),
  }))


if __name__ == '__main__':
  main()
