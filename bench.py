"""Benchmark: learner env-frames/sec on one chip, flagship config.

Two measurements, one JSON line:

1. `value` (headline, reference unit): the jitted IMPALA train step on
   a synthetic resident batch (deep ResNet, T=100, B=32, DMLab 72x96
   frames, bfloat16) — the chip's ceiling, comparable across rounds.
   Round 6 itemizes the feature matrix around it (no_instruction /
   popart_only / pc_only / full_feature, each with step_ms +
   cost_analysis bytes) and sweeps the pixel-control fast-path
   variants (`pc_levers`) so the full-feature 20% keeps named,
   re-measured owners (docs/PERF.md r6).
2. `e2e`: the REAL pipeline — process-hosted fake envs at 72x96 → C++
   dynamic batcher → TrajectoryBuffer → BatchPrefetcher → learner on
   chip — reporting the learner consumption rate (the reference's
   unit, SURVEY §6) as median/min/max over 3 independent ~45 s
   windows, with per-window pipeline telemetry. The gap between (1)
   and (2) is the tuning target; in THIS sandbox (1 host core, TPU
   behind a ~2 ms/dispatch tunnel) the e2e number is host/tunnel-
   bound, not chip-bound.

Plus two host-transport stages feeding docs/PERF.md's scaling
arithmetic: `transport` (buffer→prefetcher, C++ batcher, TCP unroll
ingest) and `param_fanout` (the learner's param-snapshot egress to
actor hosts — the reverse direction).

vs_baseline: BASELINE.json's north star is >=200k env-frames/sec on a
v5e-16 ⇒ 12,500 frames/sec/chip. vs_baseline = value / 12500.

Artifact protocol (round 6): the FULL result is written to
BENCH_OUT.json (self-contained — the driver's tail capture used to
clip the one giant JSON line mid-object, VERDICT r5 weak #1); stdout
gets the full JSON line for humans, then a compact headline line LAST
so a clipped tail still ends on one complete object.
"""

import json
import os
import tempfile
import threading
import time


def _time_step(cfg, use_instruction, smoke, h, w, num_tasks=1):
  """Median/min/max env-frames/sec of the jitted train step over ≥3
  independent timing windows (VERDICT r4 W1: a single-sample headline
  made the r1→r4 −6.4% drift unattributable). Each window is n steps
  async-chained on the donated state with ONE value readback as the
  barrier.

  Round 6: every row also carries the compiled step's
  `cost_analysis()` bytes/FLOPs and the median step time in ms — the
  per-feature itemization (VERDICT r5 weak #3) needs owners in BYTES,
  not just fps, because the step is ~72% HBM-bound."""
  import jax
  import jax.numpy as jnp
  from scalable_agent_tpu import learner as learner_lib
  from scalable_agent_tpu.models import ImpalaAgent, init_params
  from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
  from scalable_agent_tpu.testing import make_example_batch

  num_actions = 9  # DMLab DEFAULT_ACTION_SET
  t1, b = cfg.unroll_length + 1, cfg.batch_size
  agent = ImpalaAgent(num_actions=num_actions, torso=cfg.torso,
                      use_instruction=use_instruction,
                      num_popart_tasks=(num_tasks if cfg.use_popart
                                        else 0),
                      use_pixel_control=cfg.pixel_control_cost > 0,
                      pixel_control_cell_size=cfg.pixel_control_cell_size,
                      pixel_control_head_impl=cfg.pixel_control_head_impl,
                      pixel_control_q_f32=cfg.pixel_control_q_f32,
                      scan_unroll=cfg.scan_unroll, dtype=jnp.bfloat16)
  obs_spec = {'frame': (h, w, 3), 'instr_len': MAX_INSTRUCTION_LEN}
  params = init_params(agent, jax.random.PRNGKey(0), obs_spec)
  batch = make_example_batch(t1, b, h, w, num_actions,
                             MAX_INSTRUCTION_LEN, done_prob=0.01)
  state = learner_lib.make_train_state(
      params, cfg, num_popart_tasks=(num_tasks if cfg.use_popart
                                     else 0))
  train_step = learner_lib.make_train_step(agent, cfg)

  # One explicit AOT compile serves both the timing loop and the
  # cost/bytes attribution (compiling a second executable just for
  # cost_analysis would double every row's compile time on the chip).
  compiled = train_step.lower(state, batch).compile()
  cost = {}
  try:
    analysis = compiled.cost_analysis()
    if isinstance(analysis, list):  # some jax versions return [dict]
      analysis = analysis[0]
    cost = {
        'bytes_gb': round(analysis.get('bytes accessed', float('nan'))
                          / 1e9, 2),
        'tflops': round(analysis.get('flops', float('nan')) / 1e12, 3),
    }
  except Exception:  # noqa: BLE001 — cost analysis is best-effort
    pass            # (backend-dependent); the timing rows still land.

  # Warmup / compile. The sync barrier is a HOST READBACK of the loss
  # (float(...)), not block_until_ready: through the axon TPU tunnel
  # block_until_ready can return before the remote compute finishes
  # (measured: 10 deep-ResNet steps "completing" in 9 ms, ~500x over
  # MXU peak — impossible); a value readback cannot lie.
  state, metrics = compiled(state, batch)
  float(metrics['total_loss'])

  num_windows = 3 if not smoke else 1
  n = 20 if not smoke else 3
  window_fps = []
  for _ in range(num_windows):
    t0 = time.perf_counter()
    for _ in range(n):
      state, metrics = compiled(state, batch)
    float(metrics['total_loss'])
    dt = (time.perf_counter() - t0) / n
    window_fps.append(cfg.frames_per_step / dt)
  window_fps.sort()
  median = window_fps[len(window_fps) // 2]
  return {
      'median': round(median, 1),
      'min': round(window_fps[0], 1),
      'max': round(window_fps[-1], 1),
      'windows': [round(f, 1) for f in window_fps],
      'step_ms': round(cfg.frames_per_step / median * 1e3, 2),
      **({'cost': cost} if cost else {}),
  }


# The pixel-control lever grid (round 6, docs/PERF.md): each variant
# of the full-feature config is timed + cost-analyzed head-to-head so
# the accept/reject call is MEASURED every round on whatever backend
# runs the bench — config defaults stay at the r5 reference forms
# until the chip rows justify a flip (config.py rationale). Order:
# the reference forms, then each lever cumulatively, then the opt-in
# numerics-affecting bf16-Q lever.
PC_LEVER_GRID = (
    # == the config default (r5 reference forms):
    ('r5_reference', dict(pixel_control_integer_rewards=False,
                          pixel_control_head_impl='deconv',
                          pixel_control_q_f32=True)),
    ('int_rewards', dict(pixel_control_integer_rewards=True,
                         pixel_control_head_impl='deconv',
                         pixel_control_q_f32=True)),
    ('int_rewards_d2s', dict(pixel_control_integer_rewards=True,
                             pixel_control_head_impl='d2s',
                             pixel_control_q_f32=True)),
    ('int_rewards_d2s_bf16_q', dict(
        pixel_control_integer_rewards=True,
        pixel_control_head_impl='d2s',
        pixel_control_q_f32=False)),
)


def bench_synthetic(smoke):
  """Headline + the per-feature itemization (VERDICT r5 weak #3): the
  full-feature 20% gets named owners. Base = deep/no-features; each
  feature then rides the base ALONE (instruction via the headline row,
  popart_only, pc_only) so fps and cost_analysis bytes attribute the
  plain→full_feature gap term by term. In smoke mode the itemized
  rows run at tiny shapes (mechanics gate for CI); chip numbers come
  from the driver's run."""
  import dataclasses
  from scalable_agent_tpu.config import Config

  cfg = Config(batch_size=32 if not smoke else 2,
               unroll_length=100 if not smoke else 4,
               num_action_repeats=4,
               total_environment_frames=int(1e9),
               torso='deep', compute_dtype='bfloat16')
  h, w = (72, 96) if not smoke else (24, 32)
  rows = {'config': cfg}
  # Headline: the full flagship model (language encoder ON — dmlab30
  # parity, comparable across rounds). Against the no-instruction
  # base this IS the instruction-only itemized row.
  rows['synthetic'] = _time_step(cfg, True, smoke, h, w)
  # The plain base (docs/PERF.md): single-task levels auto-skip the
  # encoder.
  rows['no_instruction'] = _time_step(cfg, False, smoke, h, w)
  # Itemized rows: one feature at a time on the plain base.
  popart_cfg = dataclasses.replace(cfg, use_popart=True)
  rows['popart_only'] = _time_step(popart_cfg, False, smoke, h, w,
                                   num_tasks=30)
  pc_cfg = dataclasses.replace(cfg, pixel_control_cost=0.01)
  rows['pc_only'] = _time_step(pc_cfg, False, smoke, h, w)
  # North-star operating point (VERDICT r4 W5): the config
  # BASELINE.json's DMLab-30 target actually runs — PopArt + UNREAL
  # pixel control + instruction encoder, 30 tasks.
  ns_cfg = dataclasses.replace(cfg, use_popart=True,
                               pixel_control_cost=0.01)
  rows['full_feature'] = _time_step(ns_cfg, True, smoke, h, w,
                                    num_tasks=30)
  # The pixel-control lever grid at the full-feature operating point
  # (the surface being attacked): accept/reject stays measured.
  levers = {}
  for name, overrides in PC_LEVER_GRID:
    lcfg = dataclasses.replace(ns_cfg, **overrides)
    if lcfg == ns_cfg:
      # This variant IS the full_feature row's config (the current
      # defaults) — reuse its measurement instead of paying a second
      # flagship compile + timing windows for the same program.
      levers[name] = rows['full_feature']
      levers['default'] = name
      continue
    levers[name] = _time_step(lcfg, True, smoke, h, w, num_tasks=30)
  levers.setdefault('default', '(config defaults not in grid)')
  rows['pc_levers'] = levers
  # deep_fast operating point (docs/PERF.md round 5): stride-2 convs
  # replace the max-pools — the measured HBM-bandwidth lever (−37%
  # step bytes). Same param tree as deep, different function; reported
  # alongside the parity headline, not in its place. NOTE: throughput
  # variant with UNVALIDATED RETURNS beyond bandit grade (README §
  # Performance / scripts/compare_torsos.py).
  fast_cfg = dataclasses.replace(cfg, torso='deep_fast')
  rows['deep_fast'] = _time_step(fast_cfg, True, smoke, h, w)
  return rows


def _read_window_summaries(logdir, frames_per_step):
  """Steady-state fps + telemetry from a run's summaries.jsonl.

  fps = frames counted between the FIRST and LAST summary event / the
  wall time between them (VERDICT r4 W4: the old instrument read the
  last FpsMeter sample, which quantizes in whole unroll-batches per
  30 s window — ±33% resolution at the sandbox operating point;
  step-counter deltas resolve to one batch over the whole window).
  The first event lands one summary interval after the first
  completed train step, so the compile/ramp phase is excluded.
  """
  last = {}
  fps_events = []
  with open(os.path.join(logdir, 'summaries.jsonl')) as f:
    for line in f:
      e = json.loads(line)
      if 'value' in e:
        last[e['tag']] = e['value']  # keep the latest per tag
        if e['tag'] == 'env_frames_per_sec':
          fps_events.append((e['wall_time'], e['step']))
  if len(fps_events) >= 2:
    (t0, s0), (t1, s1) = fps_events[0], fps_events[-1]
    fps = (s1 - s0) * frames_per_step / (t1 - t0) if t1 > t0 else 0.0
    span = t1 - t0
  else:
    # One event: no counting window — fall back to its meter sample.
    fps = last.get('env_frames_per_sec', 0.0)
    span = 0.0
  return fps, span, last


def _e2e_window_config(smoke, seed, **overrides):
  from scalable_agent_tpu.config import Config
  cfg = Config(
      logdir=tempfile.mkdtemp(prefix='bench_e2e_'),
      env_backend='fake',
      num_actions=9,
      num_actors=4 if not smoke else 2,
      batch_size=4 if not smoke else 2,
      unroll_length=100 if not smoke else 5,
      num_action_repeats=4,
      episode_length=50,
      height=72 if not smoke else 24,
      width=96 if not smoke else 32,
      torso='deep' if not smoke else 'shallow',
      compute_dtype='bfloat16' if not smoke else 'float32',
      use_py_process=not smoke,   # smoke: in-process envs (CI speed)
      use_instruction=False,
      total_environment_frames=int(1e9),
      inference_timeout_ms=20,
      checkpoint_secs=10**6,     # no checkpoint traffic in the window
      summary_secs=5 if not smoke else 1,
      seed=seed)
  import dataclasses
  return dataclasses.replace(cfg, **overrides) if overrides else cfg


def _run_e2e_window(cfg, smoke, label):
  """One fresh driver.train window; returns the window telemetry dict.

  65 s per window: the first ~25 s are compile/ramp (excluded by the
  summaries-based instrument, but the steady span must still be long
  enough for ≥2 summary events). A fully cold process can spend the
  WHOLE first window compiling (observed once: window 1 = 0 frames);
  such a window measures compile time, not throughput, so it is
  retried once against the now-warm in-process jit cache."""
  import dataclasses
  from scalable_agent_tpu import driver
  for attempt in range(2):
    run = driver.train(cfg, max_seconds=65 if not smoke else 8,
                       stall_timeout_secs=120)
    if run.frames > 0:
      break
    if attempt == 1:
      raise RuntimeError(
          f'e2e window {label}: zero frames in both attempts — even '
          'the warm-cache retry spent the whole window before the '
          'first train step; the window would measure compile, not '
          'throughput')
    cfg = dataclasses.replace(
        cfg, logdir=tempfile.mkdtemp(prefix='bench_e2e_'))
  fps, span, last = _read_window_summaries(cfg.logdir,
                                           cfg.frames_per_step)
  return {
      'fps': round(fps, 1),
      'steady_secs': round(span, 1),
      'inference_mean_batch': round(
          last.get('inference_mean_batch', 0.0), 2),
      # Per-merged-call service latency (round 7 summaries): read with
      # mean_batch — merge going up while p99 explodes means the floor
      # is buying batch size with actor stall time.
      'inference_p99_ms': round(
          last.get('inference_latency_p99_ms', 0.0), 2),
      'buffer_unrolls': last.get('buffer_unrolls', 0.0),
      # Staging overlap (round 8 satellite): how often the step found
      # its batch already staged — read with buffer_unrolls (≈0 there
      # means starvation upstream of staging, not transfer).
      'h2d_overlap_fraction': round(
          last.get('h2d_overlap_fraction', 0.0), 3),
      'frames': int(run.frames),
  }


def bench_e2e(smoke):
  """Sustained FPS through the full real pipeline (driver.train on
  process-hosted fake envs): ≥3 independent windows (fresh envs per
  window) with median/min/max, fps counted over each window's whole
  steady span (see _read_window_summaries), plus a batcher-knob sweep
  at the same operating point (VERDICT r4 #6: inference_mean_batch
  sat at 2.65–2.72 of 4 with no tuning recorded)."""
  windows = []
  num_windows = 3 if not smoke else 1
  for i in range(num_windows):
    cfg = _e2e_window_config(smoke, seed=1 + i)
    windows.append(_run_e2e_window(cfg, smoke, str(i)))

  fps_sorted = sorted(w['fps'] for w in windows)
  result = {
      'fps_median': fps_sorted[len(fps_sorted) // 2],
      'fps_min': fps_sorted[0],
      'fps_max': fps_sorted[-1],
      'windows': windows,
      'actors': cfg.num_actors,
      'batch_size': cfg.batch_size,
  }
  if not smoke:
    # Batcher tuning sweep, one window per setting: can a floor under
    # the merge (min_batch) or a longer merge window (timeout) push
    # mean_batch toward 4/4 — and does fps follow or does the added
    # latency eat the gain? (paper Table 1's single-machine ~3×
    # lever; since round 6 the default row above runs min_batch=0 =
    # AUTO, i.e. the fleet-size floor this sweep motivated.)
    sweep = []
    for min_batch, timeout_ms in ((2, 20), (4, 60)):
      scfg = _e2e_window_config(
          smoke, seed=101 + min_batch,
          inference_min_batch=min_batch,
          inference_timeout_ms=timeout_ms)
      w = _run_e2e_window(scfg, smoke,
                          f'min{min_batch}/t{timeout_ms}')
      w['inference_min_batch'] = min_batch
      w['inference_timeout_ms'] = timeout_ms
      sweep.append(w)
    result['batcher_sweep'] = sweep
  return result


def bench_inference_plane(smoke):
  """The actor-plane instrument (round 7): drive the InferenceServer
  with a synthetic actor fleet — threads looping policy() on canned
  observations, NO env stepping — and itemize policy-calls/s plus
  per-call latency p50/p99 across {carry-passing vs state-cache} ×
  {pipeline depth 1 vs 2} × fleet size. The e2e bench showed the
  pipeline actor/inference-bound (`inference_mean_batch` the governing
  knob); these rows isolate the server itself so the cache and
  pipeline defaults are accepted/rejected on measurement, per the
  repo's discipline (config.py inference_state_cache rationale).

  Every cell runs pad_batch_to=fleet (ONE compiled bucket per server —
  the merge floor is AUTO'd to the fleet anyway, so steady merges land
  in that bucket) and the flagship inference shapes (deep torso, 72x96
  uint8 frames, bf16 compute; tiny shallow shapes in smoke).
  Latencies are client-side (submit → answer, batcher wait included);
  the server-side merged-call latency rides along from stats().
  """
  import threading
  import numpy as np
  import jax
  import jax.numpy as jnp
  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.models import ImpalaAgent, init_params
  from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
  from scalable_agent_tpu.ops.dynamic_batching import BatcherCancelled
  from scalable_agent_tpu.runtime.inference import (InferenceServer,
                                                    percentile_ms)
  from scalable_agent_tpu.structs import StepOutput, StepOutputInfo

  h, w = (72, 96) if not smoke else (24, 32)
  torso = 'deep' if not smoke else 'shallow'
  dtype = jnp.bfloat16 if not smoke else jnp.float32
  dur = 5.0 if not smoke else 0.6
  fleet_sizes = (8, 32) if not smoke else (3,)
  num_actions = 9
  obs_spec = {'frame': (h, w, 3), 'instr_len': MAX_INSTRUCTION_LEN}
  agent = ImpalaAgent(num_actions=num_actions, torso=torso,
                      use_instruction=False, dtype=dtype)
  params = init_params(agent, jax.random.PRNGKey(0), obs_spec)
  rng = np.random.RandomState(0)
  frame = rng.randint(0, 255, (h, w, 3)).astype(np.uint8)
  instr = np.zeros((MAX_INSTRUCTION_LEN,), np.int32)

  def run_cell(fleet, cache, depth):
    cfg = Config(inference_min_batch=0, inference_max_batch=max(64, fleet),
                 inference_timeout_ms=20, inference_state_cache=cache,
                 inference_pipeline_depth=depth)
    server = InferenceServer(agent, params, cfg, seed=7,
                             pad_batch_to=fleet, fleet_size=fleet)
    server.warmup(obs_spec, sizes=[fleet])
    counts = [0] * fleet
    lats = [[] for _ in range(fleet)]
    measuring = threading.Event()
    stop = threading.Event()

    def run(i):
      state = server.initial_core_state()
      prev = np.int32(i % num_actions)
      step = 0
      try:
        while not stop.is_set():
          env_out = StepOutput(
              reward=np.float32(0.1),
              info=StepOutputInfo(np.float32(0), np.int32(0)),
              done=np.bool_(step > 0 and step % 23 == 0),
              observation=(frame, instr))
          t0 = time.perf_counter()
          out, state = server.policy(prev, env_out, state)
          dt = time.perf_counter() - t0
          counts[i] += 1
          if measuring.is_set():
            lats[i].append(dt)
          prev = np.int32(out.action)
          step += 1
      except BatcherCancelled:
        pass
      finally:
        if hasattr(state, 'release'):
          state.release()

    threads = [threading.Thread(target=run, args=(i,), daemon=True)
               for i in range(fleet)]
    for t in threads:
      t.start()
    # Warm until every thread is feeding (startup must not eat the
    # window — same rule as the transport stages).
    deadline = time.perf_counter() + (60 if not smoke else 120)
    while (not all(c > 0 for c in counts)
           and time.perf_counter() < deadline):
      time.sleep(0.05)
    base = sum(counts)
    measuring.set()
    dt = _count_window(lambda: sum(counts), base, dur,
                       min_count=fleet * 4)
    got = sum(counts) - base
    measuring.clear()
    stop.set()
    for t in threads:
      t.join(timeout=15)
    stats = server.stats()
    server.close()
    for t in threads:
      t.join(timeout=5)
    if got == 0:
      raise RuntimeError(
          f'inference_plane moved no calls (cache={cache} depth='
          f'{depth} fleet={fleet})')
    window = sorted(x for lat in lats for x in lat)
    return {
        'policy_calls_per_sec': round(got / dt, 1),
        'lat_p50_ms': round(percentile_ms(window, 0.5, 1e3), 2),
        'lat_p99_ms': round(percentile_ms(window, 0.99, 1e3), 2),
        'mean_batch': round(stats['mean_batch'], 2),
        'merged_call_p50_ms': stats['latency_p50_ms'],
        'merged_call_p99_ms': stats['latency_p99_ms'],
        'inflight_peak': stats['inflight_peak'],
    }

  results = {'fleet_sizes': list(fleet_sizes)}
  for fleet in fleet_sizes:
    for cache in (False, True):
      for depth in (1, 2):
        name = f"{'cache' if cache else 'carry'}_d{depth}_f{fleet}"
        results[name] = run_cell(fleet, cache, depth)
  return results


def bench_overload(smoke):
  """The overload instrument (round 9, docs/ROBUSTNESS.md actor-plane
  rows): tail latency and shed rate of the serving plane when the
  actor population exceeds the state arena — the regime admission
  control exists for. Three rows run the fleet at {1x, 2x, 4x} slot
  capacity under the SHED policy (deadline rejection is the intended
  steady-state overload answer); each actor holds its slot for a
  burst of policy calls, releases, and re-acquires, so the admission
  seam churns continuously:

  - `policy_calls_per_sec` + client-side `lat_p50_ms`/`lat_p99_ms` of
    the calls that DID run — what overload does to the served tail;
  - `shed_fraction` (sheds / acquires, the SLO number the chaos storm
    bounds) with the raw acquire/shed/wait counters and the parked-
    wait p99 from stats() riding along.

  The 1x row is the control (shed_fraction ≈ 0 — admission must cost
  nothing when capacity suffices); 2x matches the chaos overload
  storm's pressure; 4x is the headroom probe.
  """
  import threading
  import numpy as np
  import jax
  import jax.numpy as jnp
  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.models import ImpalaAgent, init_params
  from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
  from scalable_agent_tpu.ops.dynamic_batching import BatcherCancelled
  from scalable_agent_tpu.runtime.inference import (
      InferenceClosed, InferenceServer, SlotUnavailable, percentile_ms)
  from scalable_agent_tpu.structs import StepOutput, StepOutputInfo

  h, w = (72, 96) if not smoke else (24, 32)
  torso = 'deep' if not smoke else 'shallow'
  dtype = jnp.bfloat16 if not smoke else jnp.float32
  dur = 4.0 if not smoke else 0.6
  slots = 8 if not smoke else 2
  pressures = (1, 2, 4)
  hold_calls = 25 if not smoke else 8
  num_actions = 9
  obs_spec = {'frame': (h, w, 3), 'instr_len': MAX_INSTRUCTION_LEN}
  agent = ImpalaAgent(num_actions=num_actions, torso=torso,
                      use_instruction=False, dtype=dtype)
  params = init_params(agent, jax.random.PRNGKey(0), obs_spec)
  rng = np.random.RandomState(0)
  frame = rng.randint(0, 255, (h, w, 3)).astype(np.uint8)
  instr = np.zeros((MAX_INSTRUCTION_LEN,), np.int32)

  def run_cell(pressure):
    fleet = pressure * slots
    cfg = Config(inference_min_batch=0,
                 inference_max_batch=max(64, slots),
                 inference_timeout_ms=20,
                 inference_state_cache=True,
                 inference_state_slots=slots,
                 inference_admission='shed',
                 # Short deadline: a shed row must measure steady-state
                 # rejection rate, not one long parked wait per actor.
                 inference_admission_timeout_secs=0.05)
    server = InferenceServer(agent, params, cfg, seed=7,
                             pad_batch_to=slots, fleet_size=slots)
    server.warmup(obs_spec, sizes=[slots])
    counts = [0] * fleet
    lats = [[] for _ in range(fleet)]
    measuring = threading.Event()
    stop = threading.Event()

    def run(i):
      prev = np.int32(i % num_actions)
      step = 0
      try:
        while not stop.is_set():
          try:
            state = server.initial_core_state()
          except SlotUnavailable:
            # Shed: the intended overload answer — back off briefly
            # and retry (server.stats() counts it).
            time.sleep(0.005)
            continue
          except InferenceClosed:
            return
          try:
            for _ in range(hold_calls):
              if stop.is_set():
                return
              env_out = StepOutput(
                  reward=np.float32(0.1),
                  info=StepOutputInfo(np.float32(0), np.int32(0)),
                  done=np.bool_(step > 0 and step % 23 == 0),
                  observation=(frame, instr))
              t0 = time.perf_counter()
              out, state = server.policy(prev, env_out, state)
              dt = time.perf_counter() - t0
              counts[i] += 1
              if measuring.is_set():
                lats[i].append(dt)
              prev = np.int32(out.action)
              step += 1
          finally:
            if hasattr(state, 'release'):
              state.release()
      except BatcherCancelled:
        pass

    threads = [threading.Thread(target=run, args=(i,), daemon=True)
               for i in range(fleet)]
    for t in threads:
      t.start()
    deadline = time.perf_counter() + (60 if not smoke else 120)
    # Under pressure > 1x a given actor may legitimately never get a
    # slot inside the warm window — warm until the FLEET moves, not
    # until every member does.
    while (sum(counts) < slots * 2
           and time.perf_counter() < deadline):
      time.sleep(0.05)
    base = sum(counts)
    measuring.set()
    dt = _count_window(lambda: sum(counts), base, dur,
                       min_count=slots * 4)
    got = sum(counts) - base
    measuring.clear()
    stop.set()
    stats = server.stats()
    server.close()
    for t in threads:
      t.join(timeout=15)
    if got == 0:
      raise RuntimeError(f'overload moved no calls (pressure='
                         f'{pressure}x, slots={slots})')
    acquires = stats['acquires']
    window = sorted(x for lat in lats for x in lat)
    return {
        'fleet': fleet,
        'slots': slots,
        'policy_calls_per_sec': round(got / dt, 1),
        'lat_p50_ms': round(percentile_ms(window, 0.5, 1e3), 2),
        'lat_p99_ms': round(percentile_ms(window, 0.99, 1e3), 2),
        'acquires': acquires,
        'sheds': stats['sheds'],
        'shed_fraction': round(stats['sheds'] / acquires, 4)
        if acquires else 0.0,
        'admission_waits': stats['admission_waits'],
        'admission_wait_p99_ms': stats['admission_wait_p99_ms'],
    }

  results = {'slots': slots, 'pressures': list(pressures)}
  for pressure in pressures:
    results[f'{pressure}x'] = run_cell(pressure)
  return results


def bench_learner_plane(smoke):
  """The learner-feed instrument (round 8): itemize the batch boundary
  the tentpole attacks. BENCH_r05 measured it as ONE burst per step —
  stack_ms 37.5 host-stacking a 67.5 MB batch, then h2d_ms 1430.5
  transferring it — while the compiled step is HBM-bound, so headline
  growth must come from removing exposed overheads. Four cells run
  the REAL feed machinery ({batch, unroll} staging × depth {1, 2}:
  synthetic producers → TrajectoryBuffer → BatchPrefetcher →
  the compiled flagship train step) and report, per cell:

  - `exposed_feed_ms_per_step`: time the step actually BLOCKED on the
    feed (prefetcher wait — H2D + assembly not hidden behind compute);
  - `step_gap_ms`: fed wall-clock per step minus the bare compiled
    step (everything the loop adds, overlapped or not);
  - `h2d_overlap_fraction` and `stack_ms` (the host stack is 0 by
    construction in unroll mode — it left the hot path).

  Plus two one-off rows: `vtrace_sharded` (the shard_map'ped Pallas
  kernel vs the lax.scan form over a mesh of ALL local devices — 1 on
  the bench chip, so the row exercises the shard_map path trivially
  there; the scripts/ci.sh smoke lane forces 8 virtual CPU devices so
  the multi-shard path runs too, and the numeric multi-device parity
  gates live in tests/) and `metrics_readback` (leaf-by-leaf
  device_get vs the round-8 stacked read, stack dispatch itemized
  separately — the driver pays it a step before the read).
  The cells share ONE compiled executable; the accept/reject call for
  `--staging_mode` rides these rows into BENCH_r08.
  """
  import threading
  import numpy as np
  import jax
  import jax.numpy as jnp
  from scalable_agent_tpu import learner as learner_lib
  from scalable_agent_tpu import observability, vtrace
  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.models import ImpalaAgent, init_params
  from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
  from scalable_agent_tpu.parallel import mesh as mesh_lib
  from scalable_agent_tpu.runtime import ring_buffer
  from scalable_agent_tpu.runtime.actor import batch_unrolls

  h, w = (72, 96) if not smoke else (24, 32)
  b = 32 if not smoke else 2
  t = 100 if not smoke else 4
  steps = 12 if not smoke else 3
  cfg = Config(batch_size=b, unroll_length=t, num_action_repeats=4,
               total_environment_frames=int(1e9),
               torso='deep' if not smoke else 'shallow',
               compute_dtype='bfloat16' if not smoke else 'float32',
               use_instruction=False)
  agent = ImpalaAgent(num_actions=9, torso=cfg.torso,
                      use_instruction=False,
                      scan_unroll=cfg.scan_unroll,
                      dtype=(jnp.bfloat16 if not smoke
                             else jnp.float32))
  obs_spec = {'frame': (h, w, 3), 'instr_len': MAX_INSTRUCTION_LEN}
  params = init_params(agent, jax.random.PRNGKey(0), obs_spec)
  state = learner_lib.make_train_state(params, cfg)
  train_step = learner_lib.make_train_step(agent, cfg)
  unroll = _transport_unroll(t + 1, h, w)
  rows = [unroll] * b
  host_batch = batch_unrolls(rows)
  placed = jax.device_put(host_batch)
  compiled = train_step.lower(state, placed).compile()
  # Warm + bare step (same value-readback barrier as _time_step).
  state, metrics = compiled(state, placed)
  float(metrics['total_loss'])
  t0 = time.perf_counter()
  for _ in range(steps):
    state, metrics = compiled(state, placed)
  float(metrics['total_loss'])
  bare_step_ms = (time.perf_counter() - t0) / steps * 1e3

  # Host stack cost (the batch-mode term unroll mode deletes).
  t0 = time.perf_counter()
  n_stack = 3 if not smoke else 1
  for _ in range(n_stack):
    batch_unrolls(rows)
  stack_ms = (time.perf_counter() - t0) / n_stack * 1e3

  def run_cell(mode, depth):
    nonlocal state
    buffer = ring_buffer.TrajectoryBuffer(2 * b)
    stop = threading.Event()

    def produce():
      while not stop.is_set():
        try:
          buffer.put(unroll, timeout=0.2)
        except (TimeoutError, ring_buffer.Closed):
          continue

    producers = [threading.Thread(target=produce, daemon=True)
                 for _ in range(4)]
    for p in producers:
      p.start()
    stager = (ring_buffer.UnrollBatchStager(b) if mode == 'unroll'
              else None)
    pf = ring_buffer.BatchPrefetcher(buffer, b,
                                     place_fn=jax.device_put,
                                     depth=depth, stager=stager)
    try:
      # Prime: the first get covers the insert-jit compile (unroll
      # mode) and the pipeline fill; excluded from the window.
      batch = pf.get(timeout=300)
      state, m = compiled(state, batch)
      float(m['total_loss'])
      base = pf.stats()
      t0 = time.perf_counter()
      for _ in range(steps):
        batch = pf.get(timeout=300)
        state, m = compiled(state, batch)
      float(m['total_loss'])
      fed_ms = (time.perf_counter() - t0) / steps * 1e3
      stats = pf.stats()
    finally:
      stop.set()
      pf.close()
      for p in producers:
        p.join(timeout=2)
    d_gets = stats['gets'] - base['gets']
    d_wait = stats['wait_secs'] - base['wait_secs']
    d_blocked = stats['blocked_gets'] - base['blocked_gets']
    return {
        'mode': mode,
        'depth': depth,
        'fed_step_ms': round(fed_ms, 2),
        'step_gap_ms': round(fed_ms - bare_step_ms, 2),
        'exposed_feed_ms_per_step': round(
            d_wait / d_gets * 1e3 if d_gets else 0.0, 2),
        'h2d_overlap_fraction': round(
            1.0 - d_blocked / d_gets if d_gets else 0.0, 3),
        'stack_ms': round(stack_ms, 1) if mode == 'batch' else 0.0,
        'donation_fallback': stats.get('donation_fallback', False),
    }

  results = {
      'batch_size': b,
      'unroll_length': t,
      'bare_step_ms': round(bare_step_ms, 2),
      'batch_mb': round(sum(x.nbytes for x in
                            jax.tree_util.tree_leaves(host_batch))
                        / 1e6, 1),
  }
  for mode in ('batch', 'unroll'):
    for depth in (1, 2):
      results[f'{mode}_d{depth}'] = run_cell(mode, depth)

  # --- Sharded Pallas-vs-scan V-trace (the lifted mesh restriction,
  # timed standalone over a mesh of ALL local devices — 1 on the
  # bench chip, 8 virtual in the CI smoke; both exercise the
  # shard_map path the flagship sharded step now takes). ---
  mesh = mesh_lib.make_mesh(jax.local_devices(), model_parallelism=1)
  tb, bb = (100, 32) if not smoke else (6, 8)
  bb = max(bb, len(jax.local_devices()))
  rng = np.random.RandomState(0)
  vkw = dict(
      log_rhos=jnp.asarray(rng.randn(tb, bb) * 0.5, jnp.float32),
      discounts=jnp.full((tb, bb), 0.9, jnp.float32),
      rewards=jnp.asarray(rng.randn(tb, bb), jnp.float32),
      values=jnp.asarray(rng.randn(tb, bb), jnp.float32),
      bootstrap_value=jnp.asarray(rng.randn(bb), jnp.float32))

  def time_vtrace(fn):
    out = fn(**vkw)
    float(np.asarray(out[0, 0]))  # readback barrier
    n = 20 if not smoke else 3
    t0 = time.perf_counter()
    for _ in range(n):
      out = fn(**vkw)
    float(np.asarray(out[0, 0]))
    return round((time.perf_counter() - t0) / n * 1e3, 3)

  results['vtrace_sharded'] = {
      'devices': len(jax.local_devices()),
      'pallas_ms': time_vtrace(jax.jit(
          lambda **k: vtrace.from_importance_weights(
              use_pallas=True, mesh=mesh, **k).vs)),
      'scan_ms': time_vtrace(jax.jit(
          lambda **k: vtrace.from_importance_weights(**k).vs)),
  }

  # --- Metrics readback, measured as the DRIVER actually pays it.
  # The round-8 path splits into two independently-timed pieces:
  # the per-step stack DISPATCH (async, returns immediately — rides
  # alongside the next step's dispatch) and the summary-time READ of
  # an already-computed stack (one transfer). Timing
  # read(stack(metrics)) as one unit would charge the deferred path a
  # serialize-on-fresh-dispatch sync it never pays in the driver,
  # where the stack was dispatched a whole step earlier. The per-leaf
  # row is the pre-round-8 summary path: one device_get per key
  # (computed values here too, so both rows measure transfer/dispatch
  # cost, not step-completion waits). ---
  n = 10 if not smoke else 2
  t0 = time.perf_counter()
  for _ in range(n):
    _ = {k: float(jax.device_get(v)) for k, v in metrics.items()}
  per_leaf_ms = (time.perf_counter() - t0) / n * 1e3
  t0 = time.perf_counter()
  handles = [observability.stack_metrics(metrics) for _ in range(n)]
  stack_dispatch_ms = (time.perf_counter() - t0) / n * 1e3
  observability.read_stacked_metrics(handles[-1])  # all computed now
  t0 = time.perf_counter()
  for h in handles:
    _ = observability.read_stacked_metrics(h)
  stacked_read_ms = (time.perf_counter() - t0) / n * 1e3
  results['metrics_readback'] = {
      'keys': len(metrics),
      'per_leaf_ms': round(per_leaf_ms, 2),
      'stacked_read_ms': round(stacked_read_ms, 2),
      'stack_dispatch_ms': round(stack_dispatch_ms, 2),
  }
  return results


def bench_replay(smoke):
  """Sample-reuse instrument (round 10, IMPACT arXiv 1912.00167):
  step_ms and learner-updates/env-frame across replay_k x replay_ratio
  through the REAL feed machinery (synthetic producers →
  TrajectoryBuffer + ReplayTier → BatchPrefetcher with staged-arena
  re-serve → ONE compiled impact-surrogate step), plus the
  driver-level return-vs-wallclock run on cue_memory that the
  accept/reject call is made on (PERF.md discipline: defaults stay at
  replay_k=1 until the curves justify a flip).

  Per cell:
  - `fed_step_ms`: fed wall-clock per learner update;
  - `fresh_unrolls_per_batch`: measured batch composition, attributed
    at SERVE time (`fresh_slots_served` / first serves — a batch the
    prefetcher staged ahead but never served counts nothing, so the
    ratio is immune to prefetch lookahead);
  - `reuse_factor`: learner updates per env frame relative to the
    no-reuse baseline (= replay_k * B / fresh_unrolls_per_batch;
    steady-state exact). The k2_r0 cell's >= 1.8x is the acceptance
    gate;
  - `h2d_unrolls_per_update`: device transfers per update — re-serves
    add NONE (the staged arena rides again), so this halves at
    replay_k=2.
  """
  import threading
  import numpy as np
  import jax
  import jax.numpy as jnp
  from scalable_agent_tpu import learner as learner_lib
  from scalable_agent_tpu.config import Config, validate_replay
  from scalable_agent_tpu.models import ImpalaAgent, init_params
  from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
  from scalable_agent_tpu.runtime import ring_buffer
  from scalable_agent_tpu.runtime.actor import batch_unrolls

  h, w = (72, 96) if not smoke else (24, 32)
  b = 32 if not smoke else 2
  t = 100 if not smoke else 4
  steps = 12 if not smoke else 4
  cfg = Config(batch_size=b, unroll_length=t, num_action_repeats=4,
               total_environment_frames=int(1e9),
               torso='deep' if not smoke else 'shallow',
               compute_dtype='bfloat16' if not smoke else 'float32',
               use_instruction=False, surrogate='impact',
               target_update_interval=2)
  validate_replay(cfg)
  agent = ImpalaAgent(num_actions=9, torso=cfg.torso,
                      use_instruction=False,
                      scan_unroll=cfg.scan_unroll,
                      dtype=(jnp.bfloat16 if not smoke
                             else jnp.float32))
  obs_spec = {'frame': (h, w, 3), 'instr_len': MAX_INSTRUCTION_LEN}
  params = init_params(agent, jax.random.PRNGKey(0), obs_spec)

  def fresh_state():
    return learner_lib.make_train_state(
        jax.tree_util.tree_map(lambda x: jnp.array(x, copy=True),
                               params), cfg)

  train_step = learner_lib.make_train_step(agent, cfg)
  unroll = _transport_unroll(t + 1, h, w)
  placed = jax.device_put(batch_unrolls([unroll] * b))
  state = fresh_state()
  compiled = train_step.lower(state, placed).compile()
  state, metrics = compiled(state, placed)  # warm (impact compile)
  float(metrics['total_loss'])

  def run_cell(k, ratio):
    state = fresh_state()
    tier = (ring_buffer.ReplayTier(4 * b) if ratio > 0 else None)
    buffer = ring_buffer.TrajectoryBuffer(2 * b, replay=tier,
                                          replay_ratio=ratio)
    stop = threading.Event()

    def produce():
      while not stop.is_set():
        try:
          buffer.put(unroll, timeout=0.2)
        except (TimeoutError, ring_buffer.Closed):
          continue

    producers = [threading.Thread(target=produce, daemon=True)
                 for _ in range(4)]
    for p in producers:
      p.start()
    stager = ring_buffer.UnrollBatchStager(b)
    pf = ring_buffer.BatchPrefetcher(buffer, b, depth=2,
                                     stager=stager, replay_k=k)
    try:
      # Prime: pipeline fill + the insert-jit compile; excluded.
      batch = pf.get(timeout=300)
      state, m = compiled(state, batch)
      float(m['total_loss'])
      base_pf = pf.stats()
      t0 = time.perf_counter()
      for _ in range(steps):
        batch = pf.get(timeout=300)
        state, m = compiled(state, batch)
      float(m['total_loss'])
      fed_ms = (time.perf_counter() - t0) / steps * 1e3
      pf_stats = pf.stats()
    finally:
      stop.set()
      pf.close()
      for p in producers:
        p.join(timeout=2)
    # Serve-attributed composition (lookahead-free): fresh slots and
    # first serves are both credited when a batch is SERVED, so
    # batches the prefetcher staged ahead of the measured window (or
    # left half-served at its edge) cancel out exactly.
    d_serves = pf_stats['serves'] - base_pf['serves']
    d_reserves = (pf_stats['batch_reserves'] -
                  base_pf['batch_reserves'])
    d_first = d_serves - d_reserves
    d_fresh_served = (pf_stats['fresh_slots_served'] -
                      base_pf['fresh_slots_served'])
    fresh_per_batch = (d_fresh_served / d_first if d_first
                       else float(b))
    reuse = k * b / fresh_per_batch if fresh_per_batch else 0.0
    frames_per_batch = fresh_per_batch * t * cfg.num_action_repeats
    return {
        'replay_k': k,
        'replay_ratio': ratio,
        'fed_step_ms': round(fed_ms, 2),
        'fresh_unrolls_per_batch': round(fresh_per_batch, 2),
        'reuse_factor': round(reuse, 3),
        'updates_per_env_frame': round(
            k / frames_per_batch if frames_per_batch else 0.0, 6),
        # Unroll mode device_puts every slot of a first-served batch
        # (replayed slots re-stage too); re-serves transfer nothing.
        'h2d_unrolls_per_update': round(
            b * d_first / d_serves if d_serves else 0.0, 2),
        'batch_reserves': d_reserves,
    }

  results = {
      'batch_size': b,
      'unroll_length': t,
      'surrogate': 'impact',
  }
  for k in (1, 2, 4):
    for ratio in (0.0, 0.5, 0.75):
      results[f'k{k}_r{int(ratio * 100)}'] = run_cell(k, ratio)

  results['return_vs_wallclock'] = _bench_replay_return_curves(smoke)
  return results


def _bench_replay_return_curves(smoke):
  """The accept/reject instrument: driver.train on cue_memory (the CI
  task with a known learnability gap — memory policy 3.0 vs best
  memoryless 2.33), baseline vs reuse config, episode returns against
  WALLCLOCK (reuse buys updates per env second; only a wallclock axis
  can show whether they convert to faster learning or to staleness
  churn). Written into the artifact so the PERF.md r9 accept/reject
  record cites curves, not vibes."""
  import dataclasses
  from scalable_agent_tpu import driver
  from scalable_agent_tpu.config import Config

  def base_config(name, **kw):
    cfg = Config(
        logdir=tempfile.mkdtemp(prefix=f'bench_replay_{name}_'),
        env_backend='cue_memory', num_actions=3,
        num_actors=4 if not smoke else 2,
        batch_size=4 if not smoke else 2,
        unroll_length=16 if not smoke else 8,
        num_action_repeats=1,
        height=72 if not smoke else 24,
        width=96 if not smoke else 32,
        torso='shallow', compute_dtype='float32',
        use_py_process=False, use_instruction=False,
        learning_rate=0.003, entropy_cost=0.01, discounting=0.9,
        total_environment_frames=10**8,
        checkpoint_secs=10**6, summary_secs=2 if not smoke else 1,
        seed=17)
    return dataclasses.replace(cfg, **kw)

  variants = [
      ('baseline_k1', base_config('k1')),
      ('reuse_k2', base_config(
          'k2', surrogate='impact', replay_k=2, replay_ratio=0.5,
          target_update_interval=5, replay_max_staleness=100)),
  ]
  out = {'task': 'cue_memory'}
  for name, cfg in variants:
    run = driver.train(cfg, max_seconds=60 if not smoke else 6,
                       stall_timeout_secs=120)
    points = []
    t0 = None
    with open(os.path.join(cfg.logdir, 'summaries.jsonl')) as f:
      for line in f:
        e = json.loads(line)
        if e.get('tag', '').endswith('/episode_return'):
          if t0 is None:
            t0 = e['wall_time']
          points.append((round(e['wall_time'] - t0, 2), e['value']))
    # Downsample to <= 20 curve points (mean per wallclock bucket).
    curve = []
    if points:
      span = max(points[-1][0], 1e-9)
      buckets = {}
      for wt, v in points:
        buckets.setdefault(min(int(wt / span * 20), 19),
                           []).append(v)
      curve = [{'t_secs': round(i / 20 * span, 1),
                'mean_return': round(sum(vs) / len(vs), 3)}
               for i, vs in sorted(buckets.items())]
    _, _, last = _read_window_summaries(cfg.logdir,
                                        cfg.frames_per_step)
    out[name] = {
        'steps': int(run.state.update_steps),
        'episodes': len(points),
        'curve': curve,
        'updates_per_env_frame': last.get(
            'learner_updates_per_env_frame', 0.0),
    }
  return out


class _SyntheticFleet:
  """Producer 'fleet' for the fed-learner stage: threads put canned
  unrolls into the trajectory buffer as fast as it accepts them —
  actors/inference/envs out of the loop, driver.train's own machinery
  (stats peel, publish cadence, summaries, health checks, checkpoint
  decisions) fully in it. Implements the ActorFleet surface train()
  touches."""

  def __init__(self, buffer, unroll, num_threads=2):
    import threading
    self._buffer = buffer
    self._unroll = unroll
    self._stop = threading.Event()
    self._threads = [
        threading.Thread(target=self._produce, daemon=True)
        for _ in range(num_threads)]

  def _produce(self):
    from scalable_agent_tpu.runtime import ring_buffer
    while not self._stop.is_set():
      try:
        self._buffer.put(self._unroll, timeout=0.2)
      except (TimeoutError, ring_buffer.Closed):
        continue

  def start(self):
    for t in self._threads:
      t.start()

  def errors(self):
    return []

  def check_health(self, stall_timeout_secs=None):
    pass

  def stats(self, healthy_horizon_secs: float = 60.0):
    # Synthetic producers never wedge: healthy == alive by definition.
    alive = len(self._threads)
    return {'alive': alive, 'respawns': 0, 'healthy': alive,
            'healthy_fraction': 1.0, 'unrolls': 0}

  def stop(self, timeout=10.0):
    self._stop.set()
    for t in self._threads:
      t.join(timeout=timeout)


def bench_e2e_fed(smoke):
  """Fed-learner measurement (VERDICT r4 Missing #2): driver.train's
  REAL loop — per-step stats extraction, publish-every-step cadence,
  summary writes, health checks, prefetcher staging + H2D — consuming
  synthetic unrolls at full rate, at the flagship learner shape
  (B=32, T=100, deep, bf16). 'The learner loop sustains ~NNNk fps
  when fed' becomes a measurement; the remaining gap to the synthetic
  headline is the loop+transfer overhead, itemized by the window
  telemetry."""
  import dataclasses
  from scalable_agent_tpu import driver

  cfg = _e2e_window_config(
      smoke, seed=7,
      num_actors=0,            # no env fleet; feed is synthetic
      batch_size=32 if not smoke else 2,
      use_py_process=False)
  t1 = cfg.unroll_length + 1
  unroll = _transport_unroll(t1, cfg.height, cfg.width)

  def fleet_factory(config, agent, policy, buffer, levels):
    return _SyntheticFleet(buffer, unroll)

  for attempt in range(2):
    run = driver.train(cfg, max_seconds=65 if not smoke else 8,
                       stall_timeout_secs=120,
                       fleet_factory=fleet_factory)
    if run.frames > 0:
      break
    if attempt == 1:
      raise RuntimeError('e2e_fed: zero frames in both attempts')
    cfg = dataclasses.replace(
        cfg, logdir=tempfile.mkdtemp(prefix='bench_fed_'))
  fps, span, last = _read_window_summaries(cfg.logdir,
                                           cfg.frames_per_step)

  # Gap itemization (the VERDICT r4 #3 contract: fed fps within ~10%
  # of synthetic OR the gap itemized): measure the two stage costs the
  # fed loop adds over the bare step — host-side batch stacking and
  # the host→device transfer of the stacked batch, barriered by a
  # value readback. In THIS sandbox the tunnel H2D dominates (tens of
  # MB/s); on a co-located TPU host it is PCIe/DMA and the loop
  # overhead shrinks to the stacking + summary costs.
  import jax
  import numpy as np
  from scalable_agent_tpu.runtime.actor import batch_unrolls
  rows = [unroll] * cfg.batch_size
  t0 = time.perf_counter()
  n_itemize = 3 if not smoke else 1
  for _ in range(n_itemize):
    stacked = batch_unrolls(rows)
  stack_ms = (time.perf_counter() - t0) / n_itemize * 1e3
  batch_mb = sum(x.nbytes for x in
                 jax.tree_util.tree_leaves(stacked)) / 1e6
  # Barrier discipline: readback ONE element of the LARGEST leaf (the
  # 66 MB frame stack) — transfers are not ordered across arrays, so a
  # small-leaf readback could stop the clock before the dominant
  # transfer lands; a full-leaf np.asarray would add its own 66 MB D2H
  # to the timing. Residual error is bounded by the small leaves.
  def place_and_barrier(batch):
    placed = jax.tree_util.tree_map(jax.device_put, batch)
    biggest = max(jax.tree_util.tree_leaves(placed),
                  key=lambda x: x.nbytes)
    return lambda: float(biggest.ravel()[0].astype(np.float32))

  def h2d_once():
    place_and_barrier(stacked)()
  h2d_once()  # warm path
  t0 = time.perf_counter()
  for _ in range(n_itemize):
    h2d_once()
  h2d_ms = (time.perf_counter() - t0) / n_itemize * 1e3
  # Pipelined variant (round 6, staging_depth>=2): TWO transfers in
  # flight, barriered together, amortized per batch. This is what the
  # prefetcher's double-buffering actually issues; serial-vs-
  # pipelined is the measured overlap win of the transfers
  # themselves, independent of compute overlap (which the run's
  # h2d_overlap_fraction summary below reports).
  stacked2 = batch_unrolls(rows)  # distinct host buffers
  t0 = time.perf_counter()
  for _ in range(n_itemize):
    barriers = [place_and_barrier(stacked), place_and_barrier(stacked2)]
    for b in barriers:
      b()
  h2d_pipelined_ms = ((time.perf_counter() - t0) / n_itemize / 2) * 1e3
  # Exposed vs overlapped H2D (round 8 satellite): the run's own
  # telemetry says how much of the transfer the step actually WAITED
  # on (`staging_exposed_ms_per_step`, last steady interval); the
  # remainder of the serially-measured burst was hidden behind
  # compute/pipelining. The window-total h2d_ms alone could not tell
  # a fully-hidden transfer from a fully-exposed one.
  exposed_ms = round(last.get('staging_exposed_ms_per_step', 0.0), 1)
  return {
      'fps': round(fps, 1),
      'steady_secs': round(span, 1),
      'buffer_unrolls': last.get('buffer_unrolls', 0.0),
      # Fraction of steps that never blocked on staging (driver
      # summary; the ISSUE-1 acceptance counter).
      'h2d_overlap_fraction': last.get('h2d_overlap_fraction', 0.0),
      'staging_depth': cfg.staging_depth,
      # The mode the run ACTUALLY used (driver echo) — config alone
      # would mislabel a topology fallback to batch staging.
      'staging_mode': ('unroll'
                       if last.get('staging_unroll_active') else
                       'batch'),
      'frames': int(run.frames),
      'batch_size': cfg.batch_size,
      # Sample-reuse motivation split (round 10): updates per fresh
      # env frame (1/frames_per_step with replay off) and how busy
      # each plane actually was — learner low + env high is the
      # env-bound regime the replay knobs attack (driver summaries;
      # the same numbers judge the flip later).
      'learner_updates_per_env_frame': last.get(
          'learner_updates_per_env_frame', 0.0),
      'env_plane_utilization': round(
          last.get('env_plane_utilization', 0.0), 3),
      'learner_plane_utilization': round(
          last.get('learner_plane_utilization', 0.0), 3),
      'gap_itemization': {
          'batch_mb': round(batch_mb, 1),
          'stack_ms': round(stack_ms, 1),
          'h2d_ms': round(h2d_ms, 1),
          'h2d_pipelined_ms': round(h2d_pipelined_ms, 1),
          'h2d_exposed_ms': exposed_ms,
          'h2d_overlapped_ms': round(max(h2d_ms - exposed_ms, 0.0), 1),
      },
  }


def _transport_unroll(t1, h, w, num_actions=9):
  """One realistic host-side unroll (numpy, flagship shapes)."""
  from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
  from scalable_agent_tpu.testing import make_example_unroll
  return make_example_unroll(t1, h, w, num_actions,
                             MAX_INSTRUCTION_LEN)


def _ingest_pump_child(port, smoke, validate, duration):
  """Ingest-bench pump, run in a CHILD process (spawn): one actor
  host's connection at full tilt. Exits 0 when the duration lapses or
  the learner goes away (the parent tears the server down mid-pump)."""
  import os as _os
  _os.environ.setdefault('JAX_PLATFORMS', 'cpu')
  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.models import ImpalaAgent
  from scalable_agent_tpu.runtime import remote
  t1 = 101 if not smoke else 6
  h, w = (72, 96) if not smoke else (24, 32)
  unroll = _transport_unroll(t1, h, w)
  client = remote.RemoteActorClient(f'127.0.0.1:{port}',
                                    connect_timeout_secs=30)
  try:
    if validate:
      cfg = Config(env_backend='fake', num_actions=9,
                   unroll_length=t1 - 1, height=h, width=w,
                   use_instruction=False)
      agent = ImpalaAgent(num_actions=9, use_instruction=False)
      client.handshake(remote.trajectory_contract(cfg, agent, 9))
    end = time.monotonic() + duration
    while time.monotonic() < end:
      client.send_unroll(unroll)
  except (OSError, remote.LearnerShutdown):
    pass  # parent closed the server: clean end of the window
  finally:
    client.close()


def _fanout_fetch_child(port, duration, counter):
  """Fan-out bench fetcher, run in a CHILD process: one actor host
  polling get_params at full tilt (worst case — production clients
  are version-gated), decoding each blob like a real host would."""
  import os as _os
  _os.environ.setdefault('JAX_PLATFORMS', 'cpu')
  from scalable_agent_tpu.runtime import remote
  client = remote.RemoteActorClient(f'127.0.0.1:{port}',
                                    connect_timeout_secs=30)
  try:
    end = time.monotonic() + duration
    while time.monotonic() < end:
      client.fetch_params()
      counter.value += 1
  except (OSError, RuntimeError, remote.LearnerShutdown):
    pass  # parent closed the server: end of the window
  finally:
    client.close()


def _fanout_pump_child(port, smoke, duration, counter, lat_queue):
  """Fan-out bench unroll pump (the hot ingest path), run in a CHILD
  process; ships per-send ack latencies back for the p50/p99 rows."""
  import os as _os
  _os.environ.setdefault('JAX_PLATFORMS', 'cpu')
  from scalable_agent_tpu.runtime import remote
  t1 = 101 if not smoke else 6
  h, w = (72, 96) if not smoke else (24, 32)
  unroll = _transport_unroll(t1, h, w)
  client = remote.RemoteActorClient(f'127.0.0.1:{port}',
                                    connect_timeout_secs=30)
  try:
    end = time.monotonic() + duration
    while time.monotonic() < end:
      t0 = time.perf_counter()
      client.send_unroll(unroll)
      lat_queue.put(time.perf_counter() - t0)
      counter.value += 1
  except (OSError, RuntimeError, remote.LearnerShutdown):
    pass  # parent closed the server: end of the window
  finally:
    client.close()


def _count_window(count_fn, base, min_dur, min_count=8, max_dur=30.0):
  """Measure a completion-counter window robustly.

  Sleeps at least `min_dur`, then keeps extending (in 50 ms slices, up
  to `max_dur`) until at least `min_count` completions landed. A loaded
  1-core CI host can legitimately finish zero requests inside a 0.4 s
  smoke window — that starvation is scheduling noise, not a pipeline
  rate, and publishing 0.0 into the scaling arithmetic (or a smoke
  assert) is wrong. A genuinely dead stage still terminates: after
  `max_dur` we return whatever was counted (possibly 0) and the
  caller's zero-checks fire with their diagnostics.
  """
  t0 = time.perf_counter()
  time.sleep(min_dur)
  while (count_fn() - base < min_count
         and time.perf_counter() - t0 < max_dur):
    time.sleep(0.05)
  return time.perf_counter() - t0


def bench_transport(smoke):
  """Host-transport ceiling with the TPU tunnel and the envs OUT of
  the loop (VERDICT r2 Missing #1 / W4): what the host-side pipeline
  pieces can sustain by themselves, at flagship row sizes (72x96x3
  frames, T+1=101). Three stages, measured independently:

  a) synthetic producer threads → TrajectoryBuffer → BatchPrefetcher
     with a no-op place_fn (batch assembly/stacking included);
  b) the C++ dynamic batcher standalone: concurrent batch-1 callers
     through merge/split with a no-op computation, vs thread count;
  c) TrajectoryIngestServer loopback: pickle TCP ingest, 1 and 4
     connections.

  All numbers are for THIS host (the docs' scaling arithmetic divides
  by them); on the 1-core sandbox GIL contention is part of the
  measurement, deliberately — that is the per-core constant.
  """
  import threading
  import numpy as np
  from scalable_agent_tpu.ops import dynamic_batching
  from scalable_agent_tpu.runtime import remote, ring_buffer

  t1 = 101 if not smoke else 6
  h, w = (72, 96) if not smoke else (24, 32)
  dur = 6.0 if not smoke else 0.8
  unroll = _transport_unroll(t1, h, w)
  import jax
  unroll_mb = sum(x.nbytes for x in jax.tree_util.tree_leaves(unroll)
                  ) / 1e6
  results = {'unroll_mb': round(unroll_mb, 2)}

  # --- (a) buffer → prefetcher (batch assembly + staging thread). ---
  batch_size = 4
  buffer = ring_buffer.TrajectoryBuffer(2 * batch_size)
  stop = threading.Event()

  def produce():
    while not stop.is_set():
      try:
        buffer.put(unroll, timeout=0.2)
      except (TimeoutError, ring_buffer.Closed):
        continue

  producers = [threading.Thread(target=produce, daemon=True)
               for _ in range(4)]
  for p in producers:
    p.start()
  prefetcher = ring_buffer.BatchPrefetcher(buffer, batch_size,
                                           place_fn=lambda b: b)
  prefetcher.get(timeout=30)  # warm
  n = 0
  t0 = time.perf_counter()
  while time.perf_counter() - t0 < dur:
    prefetcher.get(timeout=30)
    n += 1
  dt = time.perf_counter() - t0
  stop.set()
  prefetcher.close()
  for p in producers:
    p.join(timeout=2)
  results['buffer_prefetcher'] = {
      'batches_per_sec': round(n / dt, 1),
      'unrolls_per_sec': round(n * batch_size / dt, 1),
      'mb_per_sec': round(n * batch_size * unroll_mb / dt, 1),
  }

  # --- (b) C++ batcher standalone (merge/split machinery only). ---
  frame_row = np.zeros((1, h, w, 3), np.uint8)
  action_row = np.zeros((1,), np.int32)
  batcher_results = {}
  for nthreads in ((4, 16, 48) if not smoke else (4,)):
    fn = dynamic_batching.batch_fn_with_options(
        maximum_batch_size=1024, timeout_ms=2)(
            lambda frame, action: action)
    counts = [0] * nthreads
    stop_b = threading.Event()

    def worker(i):
      while not stop_b.is_set():
        fn(frame_row, action_row)
        counts[i] += 1

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(nthreads)]
    for t in threads:
      t.start()
    time.sleep(0.3)  # warm
    base = sum(counts)
    dt = _count_window(lambda: sum(counts), base, dur / 2)
    got = sum(counts) - base
    # Join BEFORE close: close() cancels in-flight requests, which
    # raises BatcherCancelled out of any worker still inside fn().
    stop_b.set()
    for t in threads:
      t.join(timeout=2)
    fn.close()
    batcher_results[f'threads_{nthreads}'] = round(got / dt, 1)
  results['batcher_requests_per_sec'] = batcher_results

  # --- (c) ingest loopback (tagged TCP wire), with the production
  # contract: the measured constant must include the handshake and the
  # per-unroll signature/action-range validation every real ingest
  # pays (driver.train always passes a contract). Pumps run in CHILD
  # PROCESSES (round 6): the real topology is actor HOSTS feeding the
  # learner, so the measured quantity must be the learner-side ingest
  # capacity — in-process pump threads shared the server's GIL and
  # measured the bench's own client cost as much as the server (the
  # r5 "4 connections lose to 1" was partly that artifact, partly the
  # reader-thread critical path the worker-pool handoff removed). ---
  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.models import ImpalaAgent
  ingest_cfg = Config(env_backend='fake', num_actions=9,
                      unroll_length=t1 - 1, height=h, width=w,
                      use_instruction=False)
  ingest_agent = ImpalaAgent(num_actions=9, use_instruction=False)
  contract = remote.trajectory_contract(ingest_cfg, ingest_agent, 9)

  def run_ingest(nclients, validate, wire_crc=True):
    import multiprocessing
    ctx = multiprocessing.get_context('spawn')
    buf = ring_buffer.TrajectoryBuffer(16)
    server = remote.TrajectoryIngestServer(
        buf, {'w': np.zeros(1)}, host='127.0.0.1',
        contract=contract if validate else None,
        wire_crc=wire_crc)
    stop_c = threading.Event()

    def drain():
      while not stop_c.is_set():
        try:
          buf.get(timeout=0.2)
        except (TimeoutError, ring_buffer.Closed):
          continue

    drainer = threading.Thread(target=drain, daemon=True)
    drainer.start()
    # Children pump for a fixed wall budget that comfortably covers
    # their own startup plus the measuring window; the count is read
    # on the SERVER side.
    child_secs = dur * 2 + (30.0 if not smoke else 20.0)
    pumps = [ctx.Process(target=_ingest_pump_child,
                         args=(server.port, smoke, validate,
                               child_secs), daemon=True)
             for _ in range(nclients)]
    for p in pumps:
      p.start()
    # Warm until every connection is live and feeding (child startup
    # pays a jax import; do not let it eat the window).
    deadline = time.perf_counter() + (60 if not smoke else 120)
    while (server.stats()['unrolls'] < nclients
           and time.perf_counter() < deadline):
      if any(p.exitcode not in (None, 0) for p in pumps):
        break
      time.sleep(0.1)
    base = server.stats()['unrolls']
    dt = _count_window(lambda: server.stats()['unrolls'], base,
                       dur / 2)
    got = server.stats()['unrolls'] - base
    server_stats = server.stats()
    stop_c.set()
    for p in pumps:
      p.terminate()
      p.join(timeout=10)
    server.close()
    buf.close()
    drainer.join(timeout=2)
    if got == 0:
      raise RuntimeError(
          f'ingest bench moved no unrolls ({nclients} conns); child '
          f'exitcodes: {[p.exitcode for p in pumps]}')
    return {
        'unrolls_per_sec': round(got / dt, 1),
        'mb_per_sec': round(got * unroll_mb / dt, 1),
        # Server-side ack service time (recv-complete → ack-sent):
        # the per-lane counter the driver also exports.
        'ack_p50_ms': round(server_stats['ack_p50_ms'], 2),
        'ack_p99_ms': round(server_stats['ack_p99_ms'], 2),
    }

  for nclients in ((1, 4) if not smoke else (1,)):
    results[f'ingest_{nclients}conn'] = run_ingest(nclients, True)
  # The validation-cost delta (VERDICT r3 W4): production always
  # validates, so the headline ingest numbers above include it; this
  # pair quantifies what the precompiled fast path left on the table.
  results['ingest_1conn_novalidate'] = run_ingest(1, False)
  # The v7 CRC-cost delta (round 12): the headline rows run the
  # production default (CRC negotiated ON — the clients handshake, so
  # every unroll pays sender CRC + receiver verify); this row
  # negotiates it OFF server-side, making the trailer overhead a
  # measured fact (docs/PERF.md r10 records the accept call — the
  # gate is <5% frames/s).
  results['ingest_1conn_crc_off'] = run_ingest(1, True,
                                               wire_crc=False)
  on = results['ingest_1conn']['unrolls_per_sec']
  off = results['ingest_1conn_crc_off']['unrolls_per_sec']
  results['crc_overhead_fraction'] = (round(1.0 - on / off, 4)
                                      if off else None)
  return results


def bench_param_fanout(smoke):
  """Learner param-snapshot EGRESS ceiling (VERDICT r3 Missing #1).

  The other half of the reference's scaling story: weights served to
  150–500 actor machines (reference: experiment.py ≈L415–455
  `pin_global_variables` — variables pinned to the learner CPU because
  serving them is a real cost; SURVEY §5.8). Every connected actor
  host refetches the snapshot once per version bump, so worst-case
  learner egress is hosts × blob_bytes / remote_publish_secs — this
  stage measures the serving side of that term with the REAL flagship
  blob (deep ResNet + instruction encoder, the tree every dmlab30
  actor host fetches):

  a) serving ceiling: N loopback CHILD-PROCESS clients looping
     get_params over the PARAM LANE (round 6: one selector thread,
     chunked non-blocking sends, bf16 codec default, out-of-band
     blob frames) — aggregate blobs/s and MB/s vs N. Clients decode
     on their own processes, matching the actor-host topology; the
     serving side is the per-core constant the PERF.md arithmetic
     divides by, same methodology as the ingest stage.
  b) ack-latency impact: one unroll pump (the hot ingest path) alone
     vs sharing the server with 8 param fetchers. r5 measured the
     shared-thread design collapsing the pump 838.6 → 29.9 unrolls/s
     (ack p99 95.8 ms); the lane isolation is accepted or rejected on
     this row.
  c) wire-shrink levers, measured one-off on the real blob: zlib-1
     compression (ratio + CPU cost) and a bfloat16 cast (exactly
     halves the float32 payload) — the bf16 numbers justify the
     publish_codec='bf16' default (docs/TRANSPORT.md).
  """
  import pickle
  import threading
  import zlib
  import numpy as np
  import jax
  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.models import ImpalaAgent, init_params
  from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
  from scalable_agent_tpu.runtime import remote, ring_buffer

  h, w = (72, 96) if not smoke else (24, 32)
  dur = 6.0 if not smoke else 0.8
  agent = ImpalaAgent(num_actions=9,
                      torso='deep' if not smoke else 'shallow',
                      use_instruction=not smoke)
  params = jax.device_get(init_params(
      agent, jax.random.PRNGKey(0),
      {'frame': (h, w, 3), 'instr_len': MAX_INSTRUCTION_LEN}))
  blob = pickle.dumps(('params', 1, params),
                      protocol=pickle.HIGHEST_PROTOCOL)
  blob_mb = len(blob) / 1e6
  # The production default codec (config.publish_codec='bf16') is the
  # measured configuration; the f32 blob size is kept for the ratio.
  wire_dtype = Config().resolved_wire_dtype
  results = {
      'blob_mb': round(blob_mb, 2),
      'wire_dtype': wire_dtype or 'float32',
      'num_params': int(sum(
          x.size for x in jax.tree_util.tree_leaves(params))),
  }

  def run_fanout(nfetchers, with_pump):
    """nfetchers get_params loops (+ optionally one unroll pump)
    against one server; returns (blobs/s, pump stats or None). Like
    the ingest stage, the clients run in CHILD processes (round 6):
    real actor hosts fetch and decode on their own CPUs, so the
    measured quantity must be the learner-side serving/ack capacity,
    not the bench's own in-process client decode sharing the server's
    GIL."""
    import multiprocessing
    ctx = multiprocessing.get_context('spawn')
    buf = ring_buffer.TrajectoryBuffer(16)
    server = remote.TrajectoryIngestServer(buf, params,
                                           host='127.0.0.1',
                                           wire_dtype=wire_dtype)
    stop = threading.Event()

    def drain():
      while not stop.is_set():
        try:
          buf.get(timeout=0.2)
        except (TimeoutError, ring_buffer.Closed):
          continue

    drainer = threading.Thread(target=drain, daemon=True)
    drainer.start()
    child_secs = dur * 2 + (30.0 if not smoke else 20.0)
    # One counter PER child (each Value has a single writer — a shared
    # lock-free Value across N processes would lose increments to the
    # non-atomic read-modify-write and understate the ceiling).
    fetch_counts = [ctx.Value('q', 0, lock=False)
                    for _ in range(nfetchers)]
    pump_count = ctx.Value('q', 0, lock=False)
    lat_queue = ctx.Queue()
    procs = [ctx.Process(target=_fanout_fetch_child,
                         args=(server.port, child_secs,
                               fetch_counts[i]), daemon=True)
             for i in range(nfetchers)]
    if with_pump:
      procs.append(ctx.Process(
          target=_fanout_pump_child,
          args=(server.port, smoke, child_secs, pump_count,
                lat_queue), daemon=True))
    for p in procs:
      p.start()

    pump_latencies = []

    def drain_latencies():
      while True:
        try:
          pump_latencies.append(lat_queue.get(timeout=0.1))
        except Exception:
          if stop.is_set():
            return

    lat_drainer = threading.Thread(target=drain_latencies, daemon=True)
    lat_drainer.start()

    def total_fetched():
      return sum(c.value for c in fetch_counts)

    def progress():
      vals = []
      if nfetchers:
        vals.append(total_fetched() - fetch_base)
      if with_pump:
        vals.append(pump_count.value - pump_base)
      return min(vals) if vals else 1 << 30

    # Warm until every role is live (children pay a jax import).
    deadline = time.perf_counter() + (60 if not smoke else 120)
    while time.perf_counter() < deadline:
      if ((not nfetchers or total_fetched() > 0)
          and (not with_pump or pump_count.value > 0)):
        break
      if any(p.exitcode not in (None, 0) for p in procs):
        break
      time.sleep(0.1)
    fetch_base, pump_base = total_fetched(), pump_count.value
    lat_base = len(pump_latencies)
    dt = _count_window(progress, 0, dur / 2)
    fetched = total_fetched() - fetch_base
    pumped = pump_count.value - pump_base
    window_lat = sorted(pump_latencies[lat_base:])
    # MB/s must count the bytes actually on the wire (bf16 codec
    # halves the f32 pickle this stage used to multiply by).
    wire_mb = server.snapshot_nbytes() / 1e6
    results.setdefault('wire_blob_mb', round(wire_mb, 2))
    stop.set()
    for p in procs:
      p.terminate()
      p.join(timeout=10)
    server.close()
    buf.close()
    drainer.join(timeout=2)
    lat_drainer.join(timeout=2)
    if nfetchers and fetched == 0:
      raise RuntimeError(
          f'param fan-out moved no blobs ({nfetchers} fetchers); '
          f'child exitcodes: {[p.exitcode for p in procs]}')
    if with_pump and pumped == 0:
      # Same no-silent-zero rule as the ingest stage: a dead pump must
      # fail the bench, not publish a null latency row.
      raise RuntimeError(
          f'fan-out pump moved no unrolls; child exitcodes: '
          f'{[p.exitcode for p in procs]}')
    fanout = {'blobs_per_sec': round(fetched / dt, 1),
              'mb_per_sec': round(fetched * wire_mb / dt, 1)}
    pump_stats = None
    if with_pump and window_lat:
      # The shared nearest-rank percentile (runtime.inference): the
      # bench rows and the live stats() must compute identically.
      from scalable_agent_tpu.runtime.inference import percentile_ms
      pump_stats = {
          'unrolls_per_sec': round(pumped / dt, 1),
          'ack_p50_ms': round(percentile_ms(window_lat, 0.5, 1e3), 2),
          'ack_p99_ms': round(percentile_ms(window_lat, 0.99, 1e3), 2),
      }
    return fanout, pump_stats

  for nfetchers in ((1, 8, 32) if not smoke else (1,)):
    fanout, _ = run_fanout(nfetchers, with_pump=False)
    results[f'fanout_{nfetchers}host'] = fanout
  _, pump_alone = run_fanout(0, with_pump=True)
  contenders = 8 if not smoke else 1
  _, pump_contended = run_fanout(contenders, with_pump=True)
  results['pump_alone'] = pump_alone
  results[f'pump_with_{contenders}_fetchers'] = pump_contended

  # --- (c) wire-shrink levers, one-off on the real blob. ---
  t0 = time.perf_counter()
  z = zlib.compress(blob, 1)
  z_secs = time.perf_counter() - t0
  results['zlib1'] = {'ratio': round(len(z) / len(blob), 3),
                      'compress_ms': round(z_secs * 1e3, 1)}
  import ml_dtypes
  t0 = time.perf_counter()
  cast = jax.tree_util.tree_map(
      lambda x: x.astype(ml_dtypes.bfloat16)
      if x.dtype == np.float32 else x, params)
  bblob = pickle.dumps(('params', 1, cast),
                       protocol=pickle.HIGHEST_PROTOCOL)
  b_secs = time.perf_counter() - t0
  results['bf16_cast'] = {'ratio': round(len(bblob) / len(blob), 3),
                          'cast_ms': round(b_secs * 1e3, 1)}
  return results


class _ThrottledFleet(_SyntheticFleet):
  """Rate-limited synthetic producer: one unroll per `period` seconds
  across the fleet — the ENV-BOUND regime (BENCH r9: ~150 fps feed vs
  ~300k fps learner capacity) the hybrid filler exists for. Single
  producer thread so the offered rate is the period, not its
  multiple."""

  def __init__(self, buffer, unroll, period):
    super().__init__(buffer, unroll, num_threads=1)
    self._period = period

  def _produce(self):
    import time as _time
    from scalable_agent_tpu.runtime import ring_buffer
    while not self._stop.is_set():
      _time.sleep(self._period)
      try:
        self._buffer.put(self._unroll, timeout=0.2)
      except (TimeoutError, ring_buffer.Closed):
        continue


def bench_anakin(smoke):
  """The Anakin runtime axis (round 16; parallel/anakin.py,
  driver.train_anakin, docs/PARALLELISM.md):

  1. Fused-loop fps rows over the jittable env family ({bandit,
     cue_memory, gridworld} × {1 device, all local devices}) — the
     all-device rows shard the env batch over the data mesh axis per
     the `test_anakin_shards_over_the_mesh` discipline.
  2. TWO references at the SAME model/shape, batch size, and device
     set as the anakin bandit row (driver.choose_mesh shards the fed
     learner over all local devices exactly like the all-device
     anakin row):
     - `fleet_reference` — the REAL fleet path (actors -> inference
       server -> buffer -> learner), acting cost included:
       `anakin_vs_fleet` is the end-to-end fusion win the >=3x
       acceptance gate reads (the r4 chip artifact: 1.25M fused vs
       the fed flagship's ~300k).
     - `fed_reference` — a full-rate SYNTHETIC feed through the same
       driver loop: the learner-loop ceiling with acting excluded.
       `anakin_vs_fed` can legitimately read < 1 on a CPU build host
       (synthetic data is free there and the fused loop still pays
       its T sequential acting passes); on the chip the fed path's
       transport/H2D terms return and the ratio shows the fusion win.
       Reported so the two effects (acting amortization vs transport
       deletion) stay separable.
  3. The HYBRID row: driver.train under an env-THROTTLED synthetic
     feed with --anakin_filler off vs on — learner-plane utilization
     must be strictly higher with the filler ON while fleet
     fresh-frame accounting (frame budget, fps) is unchanged at
     filler-OFF parity. This is the accept/reject evidence for the
     filler default (docs/PERF.md r13)."""
  import dataclasses
  import numpy as np
  import jax
  from scalable_agent_tpu import driver
  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.parallel import anakin
  from scalable_agent_tpu.parallel import mesh as mesh_lib

  n_dev = len(jax.devices())
  steps = 200 if not smoke else 3
  t = 20 if not smoke else 3
  base = dict(
      unroll_length=t, num_action_repeats=1,
      height=24, width=32, torso='shallow',
      compute_dtype='bfloat16' if not smoke else 'float32',
      use_instruction=False, use_py_process=False,
      learning_rate=2e-3, entropy_cost=3e-3,
      total_environment_frames=10**9, seed=0)
  episode_lengths = {'bandit': 5, 'cue_memory': 2, 'gridworld': 12}

  out = {'devices': n_dev}
  for backend in ('bandit', 'cue_memory', 'gridworld'):
    for devices in sorted({1, n_dev}):
      b = 256 if not smoke else 8
      b = max(b - b % devices, devices)  # shardable batch
      cfg = Config(env_backend=backend, batch_size=b,
                   episode_length=episode_lengths[backend],
                   discounting=0.0 if backend == 'bandit' else 0.9,
                   **base)
      mesh = (mesh_lib.make_mesh() if devices > 1 else None)
      _, history, fps = anakin.run(cfg, steps, mesh=mesh)
      rewards = [float(h['mean_reward']) for h in history]
      tail = max(len(rewards) // 10, 1)
      out[f'{backend}_{devices}dev'] = {
          'env_frames_per_sec': round(fps, 1),
          'batch_size': b,
          'mean_reward_first': round(float(np.mean(rewards[:tail])),
                                     3),
          'mean_reward_last': round(float(np.mean(rewards[-tail:])),
                                    3),
      }
  out['config'] = ('shallow, 24x32, T=%d, %d step(s)' % (t, steps))

  # --- Fed-fleet reference + hybrid filler rows: driver.train's REAL
  # loop at the SAME model/shape, fed synthetically. ---
  unroll = _transport_unroll(t + 1, 24, 32, num_actions=3)

  def run_fed(tag, filler, period, seconds, batch_size,
              real_fleet=False):
    cfg = Config(env_backend='bandit', level_name='bandit',
                 num_actors=4 if real_fleet else 0,
                 batch_size=batch_size,
                 episode_length=5, discounting=0.0,
                 logdir=tempfile.mkdtemp(prefix=f'bench_anakin_{tag}_'),
                 anakin_filler=filler,
                 inference_timeout_ms=5,
                 queue_capacity_batches=2, summary_secs=0,
                 checkpoint_secs=10**6, slo_engine=False,
                 controller='off',
                 **{k: v for k, v in base.items()
                    if k not in ('seed',)}, seed=13)

    def fleet_factory(config, agent, policy, buffer, levels):
      if period is None:
        return _SyntheticFleet(buffer, unroll)
      return _ThrottledFleet(buffer, unroll, period)

    run = driver.train(cfg, max_seconds=seconds,
                       stall_timeout_secs=120,
                       fleet_factory=(None if real_fleet
                                      else fleet_factory))
    fps, _, last = _read_window_summaries(cfg.logdir,
                                          cfg.frames_per_step)
    return {
        'fps': round(fps, 1),
        'frames': int(run.frames),
        'learner_plane_utilization': round(
            last.get('learner_plane_utilization', 0.0), 3),
        'filler_updates': int(last.get('filler_updates', 0)),
        'filler_frames': int(last.get('filler_frames', 0)),
    }

  seconds = 20 if not smoke else 6
  # Apples to apples: both references run the SAME batch as the
  # anakin bandit rows, and choose_mesh shards them over all local
  # devices — so the ratios' numerator is the matching-device anakin
  # row, never a B-or-device artifact. The fleet reference uses the
  # 4-actor CI-scale local fleet (acting through the real batcher).
  anakin_ref = (out.get(f'bandit_{n_dev}dev')
                or out['bandit_1dev'])
  fleet_ref = run_fed('fleet', filler=False, period=None,
                      seconds=seconds,
                      batch_size=anakin_ref['batch_size'],
                      real_fleet=True)
  out['fleet_reference'] = dict(fleet_ref,
                                batch_size=anakin_ref['batch_size'],
                                num_actors=4)
  if fleet_ref['fps'] > 0:
    out['anakin_vs_fleet'] = round(
        anakin_ref['env_frames_per_sec'] / fleet_ref['fps'], 2)
  fed = run_fed('fed', filler=False, period=None, seconds=seconds,
                batch_size=anakin_ref['batch_size'])
  out['fed_reference'] = dict(fed, batch_size=anakin_ref['batch_size'])
  if fed['fps'] > 0:
    out['anakin_vs_fed'] = round(
        anakin_ref['env_frames_per_sec'] / fed['fps'], 2)

  # Hybrid: the SAME throttled env-bound feed, filler off vs on. The
  # off row is the parity baseline (fresh-frame fps/frames must match
  # the on row's fresh accounting — filler frames ride a separate
  # ledger). Small batch on purpose: the rows measure utilization
  # under a trickle feed, not throughput.
  period = 0.25 if not smoke else 0.4
  hybrid_b = 8 if not smoke else 2
  hybrid_off = run_fed('off', filler=False, period=period,
                       seconds=seconds, batch_size=hybrid_b)
  hybrid_on = run_fed('on', filler=True, period=period,
                      seconds=seconds, batch_size=hybrid_b)
  out['hybrid'] = {
      'feed_period_secs': period,
      'filler_off': hybrid_off,
      'filler_on': hybrid_on,
      'utilization_lift': round(
          hybrid_on['learner_plane_utilization'] -
          hybrid_off['learner_plane_utilization'], 3),
  }
  return out


def bench_telemetry(smoke):
  """Tracing/registry overhead (round 13; docs/PERF.md r11): the cost
  of the always-on telemetry plane, measured so the default is an
  accept/reject call with numbers. Three rows:

  a) registry micro: Counter.inc + Histogram.observe, ns/op — the
     per-event cost every converted module counter now pays;
  b) span micro: the full per-unroll trace lifecycle (make + 4 hop
     stamps + sidecar tag + pop), ns/span;
  c) feed pipeline head-to-head: synthetic producer threads →
     TrajectoryBuffer → BatchPrefetcher at flagship unroll sizes,
     tracer ON (spans stamped + tagged by producers, batch records
     written to a real traces.jsonl) vs OFF — unrolls/s both ways and
     the headline overhead fraction.
  """
  import shutil
  import tempfile
  import threading
  from scalable_agent_tpu import telemetry
  from scalable_agent_tpu.runtime import ring_buffer

  t1 = 101 if not smoke else 6
  h, w = (72, 96) if not smoke else (24, 32)
  dur = 4.0 if not smoke else 0.8
  unroll = _transport_unroll(t1, h, w)
  results = {}

  # --- (a) registry micro. ---
  n = 200_000 if not smoke else 20_000
  c = telemetry.counter('bench/telemetry_counter')
  hist = telemetry.histogram('bench/telemetry_hist')
  t0 = time.perf_counter()
  for i in range(n):
    c.inc()
    hist.observe(i)
  dt = time.perf_counter() - t0
  results['registry_ns_per_op'] = round(dt / (2 * n) * 1e9, 1)

  # --- (b) span micro. ---
  n = 50_000 if not smoke else 5_000
  t0 = time.perf_counter()
  for i in range(n):
    tr = telemetry.make_trace('bench', i, behavior_version=i)
    for hop in (telemetry.HOP_DONE, telemetry.HOP_WIRE,
                telemetry.HOP_STAGED, telemetry.HOP_STEP):
      telemetry.stamp(tr, hop)
    telemetry.tag_unroll(unroll, tr)
    telemetry.pop_unroll(unroll)
  dt = time.perf_counter() - t0
  results['span_ns'] = round(dt / n * 1e9, 1)

  # --- (c) feed pipeline, tracing on vs off. ---
  def run_feed(tracing):
    batch_size = 4
    tmpdir = tempfile.mkdtemp(prefix='bench_telemetry_')
    tracer = None
    if tracing:
      tracer = telemetry.PipelineTracer(tmpdir)
      telemetry.set_tracer(tracer)
    buffer = ring_buffer.TrajectoryBuffer(2 * batch_size)
    stop = threading.Event()

    def produce(name):
      seq = 0
      while not stop.is_set():
        # _replace: a fresh pytree object per put — the sidecar tag
        # store keys by identity, so re-putting ONE object would
        # alias every in-flight tag (production unrolls are always
        # distinct objects).
        item = unroll._replace()
        trace = telemetry.begin_unroll_trace(name, seq)
        if trace is not None:
          telemetry.stamp(trace, telemetry.HOP_DONE)
          telemetry.tag_unroll(item, trace)
        seq += 1
        try:
          buffer.put(item, timeout=0.2)
        except (TimeoutError, ring_buffer.Closed):
          continue

    producers = [threading.Thread(target=produce, args=(f'p{i}',),
                                  daemon=True) for i in range(4)]
    for p in producers:
      p.start()
    prefetcher = ring_buffer.BatchPrefetcher(buffer, batch_size,
                                             place_fn=lambda b: b)
    prefetcher.get(timeout=30)
    if tracer is not None:
      tracer.on_step(0)
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < dur:
      prefetcher.get(timeout=30)
      n += 1
      if tracer is not None:
        # The driver's per-step completion call — batch record +
        # policy-lag arithmetic + the traces.jsonl write.
        tracer.on_step(n)
    dt = time.perf_counter() - t0
    stop.set()
    prefetcher.close()
    for p in producers:
      p.join(timeout=2)
    row = {'unrolls_per_sec': round(n * batch_size / dt, 1)}
    if tracer is not None:
      row['tracer'] = tracer.stats()
      telemetry.set_tracer(None)
      tracer.close()
    shutil.rmtree(tmpdir, ignore_errors=True)
    return row

  results['feed_trace_off'] = run_feed(False)
  results['feed_trace_on'] = run_feed(True)
  on = results['feed_trace_on']['unrolls_per_sec']
  off = results['feed_trace_off']['unrolls_per_sec']
  results['overhead_fraction'] = (round(1.0 - on / off, 4)
                                  if off else None)
  return results


def bench_slo(smoke):
  """SLO-engine overhead (round 14; docs/PERF.md r12): the cost of
  judging every run continuously, measured so the default is an
  accept/reject call with numbers. Three rows:

  a) evaluator tick: one SloEvaluator.observe over a registry-scale
     snapshot (default objective set + ~50 synthetic metric names),
     µs/tick — the per-cadence cost the engine thread pays;
  b) verdict: SloEvaluator.verdict() µs (the finalize-path cost);
  c) profiler-capture overhead: a tiny jitted step loop timed bare vs
     wrapped in a bounded jax.profiler trace (what a triggered
     page-capture costs the K steps it covers), plus the trace write
     wall time.
  """
  import shutil
  import tempfile
  import jax
  import jax.numpy as jnp
  from scalable_agent_tpu import slo as slo_lib
  from scalable_agent_tpu import telemetry

  results = {}
  objectives = slo_lib.load_objectives()
  results['objectives'] = len(objectives)

  # --- (a) evaluator tick over a registry-scale snapshot. ---
  reg = telemetry.MetricsRegistry()
  for i in range(40):
    c = reg.counter(f'bench/slo_c{i}')
    c.inc(i)
  h = reg.histogram('trace/policy_lag')
  h2 = reg.histogram('trace/e2e_ms')
  for i in range(512):
    h.observe(i % 7)
    h2.observe(50.0 + i % 31)
  g = reg.gauge('driver/env_plane_utilization')
  g.set(0.8)
  crc = reg.counter('ingest/wire_crc_rejected')
  evaluator = slo_lib.SloEvaluator(objectives, min_samples=2)
  n = 2_000 if not smoke else 200
  t_base = time.time()
  t0 = time.perf_counter()
  for i in range(n):
    crc.inc(0)  # snapshot stays cheap-but-live
    evaluator.observe(reg.snapshot(), now=t_base + i * 0.5)
  dt = time.perf_counter() - t0
  results['evaluator_tick_us'] = round(dt / n * 1e6, 2)

  # --- (b) verdict cost. ---
  n = 2_000 if not smoke else 200
  t0 = time.perf_counter()
  for _ in range(n):
    evaluator.verdict()
  dt = time.perf_counter() - t0
  results['verdict_us'] = round(dt / n * 1e6, 2)

  # --- (c) profiler-capture overhead around a tiny jitted loop. ---
  steps = 20 if not smoke else 6
  x = jnp.ones((256, 256), jnp.float32)

  @jax.jit
  def step(x):
    return jnp.tanh(x @ x) * 0.5

  def run_steps():
    y = x
    t0 = time.perf_counter()
    for _ in range(steps):
      y = step(y)
    jax.block_until_ready(y)
    return time.perf_counter() - t0

  run_steps()  # compile
  bare = min(run_steps() for _ in range(3))
  tmpdir = tempfile.mkdtemp(prefix='bench_slo_prof_')
  t0 = time.perf_counter()
  jax.profiler.start_trace(tmpdir)
  traced = run_steps()
  jax.profiler.stop_trace()
  capture_wall = time.perf_counter() - t0
  shutil.rmtree(tmpdir, ignore_errors=True)
  results['profiled_steps'] = steps
  results['bare_steps_ms'] = round(bare * 1e3, 3)
  results['traced_steps_ms'] = round(traced * 1e3, 3)
  results['capture_wall_ms'] = round(capture_wall * 1e3, 3)
  results['capture_overhead_fraction'] = (
      round(traced / bare - 1.0, 4) if bare > 0 else None)
  return results


def bench_controller(smoke):
  """Self-healing-controller overhead (round 15; controller.py): the
  cost of the verdict-to-actuation loop, measured so the default
  observe-mode thread is an accept/reject call with numbers. Rows:

  a) idle tick: one Controller.tick over a healthy snapshot with the
     default rule set — the steady-state cost the controller thread
     pays every interval (nothing burning, nothing engaged);
  b) acting tick: the same tick while a rule is escalating — includes
     the actuator set, the CONTROLLER_LOG.json rewrite, and the
     incident emission (paid only when a knob actually moves);
  c) full escalate->revert cycle wall time through a real SloEngine
     snapshot path (the engine lock + deep copy included).
  """
  import shutil
  from scalable_agent_tpu import controller as controller_lib
  from scalable_agent_tpu import slo as slo_lib
  from scalable_agent_tpu import telemetry

  results = {}
  tmpdir = tempfile.mkdtemp(prefix='bench_ctrl_')

  class _Engine:
    def __init__(self, snap):
      self.snap = snap

    def control_snapshot(self):
      return {n: dict(e) for n, e in self.snap.items()}

  def _entry(state, margin):
    return {'state': state, 'margin': margin, 'value': margin,
            'severity': 'page', 'target': 1.0, 'burns': 0}

  rules = controller_lib.load_rules()
  results['rules'] = len(rules)
  healthy = {r.objective: _entry(slo_lib.OK, 10.0) for r in rules}
  knobs = {'replay_k': 1, 'admission': 'block', 'publish_secs': 2.0,
           'fleet_size': 4}

  def _actuators():
    acts = []
    for name, lo, hi in (('replay_k', 1, 4), ('publish_secs', 2.0,
                                              30.0),
                         ('fleet_size', 1, 64)):
      acts.append(controller_lib.Actuator(
          name, kind='float' if name == 'publish_secs' else 'int',
          get_fn=lambda n=name: knobs[n],
          set_fn=lambda v, n=name: knobs.__setitem__(n, v),
          minimum=lo, maximum=hi))
    acts.append(controller_lib.Actuator(
        'admission', kind='enum',
        get_fn=lambda: knobs['admission'],
        set_fn=lambda v: knobs.__setitem__('admission', v),
        values=('block', 'shed', 'grow')))
    return acts

  # --- (a) idle tick over the default table. ---
  engine = _Engine(healthy)
  ctrl = controller_lib.Controller(engine, rules, _actuators(),
                                   tmpdir, mode='act',
                                   interval_secs=3600.0)
  n = 20_000 if not smoke else 1_000
  t0 = time.perf_counter()
  for i in range(n):
    ctrl.tick(now=float(i))
  dt = time.perf_counter() - t0
  results['idle_tick_us'] = round(dt / n * 1e6, 2)
  ctrl.stop()

  # --- (b) acting tick: one rule escalating every tick (cooldown 0,
  # bounded knob reset each round so a set really happens). ---
  burning = dict(healthy)
  burning['fleet_healthy_fraction'] = _entry(slo_lib.BURNING, -0.5)
  hot_rule = controller_lib.Rule(
      objective='fleet_healthy_fraction', actuator='fleet_size',
      direction='up', step=1, cooldown_secs=0.0, clear_margin=0.5)
  ctrl = controller_lib.Controller(_Engine(burning), [hot_rule],
                                   _actuators(), tmpdir, mode='act',
                                   interval_secs=3600.0)
  n = 300 if not smoke else 50
  t0 = time.perf_counter()
  for i in range(n):
    knobs['fleet_size'] = 4
    ctrl.tick(now=float(i))
  dt = time.perf_counter() - t0
  results['acting_tick_us'] = round(dt / n * 1e6, 2)
  ctrl.stop()

  # --- (c) escalate->revert cycle through a REAL SloEngine. ---
  reg = telemetry.MetricsRegistry()
  gauge = reg.gauge('driver/fleet_healthy_fraction')
  gauge.set(1.0)
  objective = slo_lib.Objective(
      name='fleet_healthy_fraction',
      metric='driver/fleet_healthy_fraction', comparison='>=',
      target=0.6, severity='page', fast_window_secs=2.0,
      slow_window_secs=8.0)
  engine2 = slo_lib.SloEngine([objective], tmpdir, registry=reg,
                              capture=False, min_samples=2)
  cycle_rule = controller_lib.Rule(
      objective='fleet_healthy_fraction', actuator='fleet_size',
      direction='up', step=1, trigger_margin=0.2, clear_margin=0.3,
      cooldown_secs=0.0)
  knobs['fleet_size'] = 4
  ctrl = controller_lib.Controller(engine2, [cycle_rule],
                                   _actuators(), tmpdir, mode='act',
                                   interval_secs=3600.0)
  t0 = time.perf_counter()
  now = 1000.0
  gauge.set(0.5)
  for _ in range(4):
    now += 1.0
    engine2.observe(now=now)
  actions = ctrl.tick(now=now)
  gauge.set(1.0)
  for _ in range(4):
    now += 1.0
    engine2.observe(now=now)
  actions += ctrl.tick(now=now)
  results['cycle_wall_ms'] = round((time.perf_counter() - t0) * 1e3,
                                   3)
  results['cycle_actions'] = len(actions)
  ctrl.stop()
  engine2.stop()
  shutil.rmtree(tmpdir, ignore_errors=True)
  return results


def _multihost_child_main():
  """Child body of the multihost stage: one process of the 2-process
  jax.distributed drill (or the 1-process reference when
  BENCH_MH_NPROCS=1 — then no distributed runtime at all, the true
  single-controller baseline). Runs the REAL driver.train and reports
  the steady-state env-frames/sec (median of the back half of the
  summary stream's fps curve, so compile time and ramp-up don't
  pollute the row)."""
  proc = int(os.environ['BENCH_MH_PROC'])
  nprocs = int(os.environ['BENCH_MH_NPROCS'])
  steps = int(os.environ['BENCH_MH_STEPS'])
  batch_per = int(os.environ['BENCH_MH_BATCH_PER'])
  logdir = os.environ['BENCH_MH_DIR']
  os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=1'
  import jax
  jax.config.update('jax_platforms', 'cpu')
  if nprocs > 1:
    from scalable_agent_tpu.parallel import distributed
    distributed.initialize(
        f"localhost:{os.environ['BENCH_MH_PORT']}",
        num_processes=nprocs, process_id=proc,
        heartbeat_interval_secs=1, max_missing_heartbeats=8)
  from scalable_agent_tpu import driver
  from scalable_agent_tpu.config import Config
  cfg = Config(
      logdir=logdir, env_backend='bandit', level_name='bandit',
      num_actors=2, batch_size=batch_per * nprocs,
      unroll_length=10, num_action_repeats=1, episode_length=8,
      height=24, width=32, torso='shallow', use_py_process=False,
      use_instruction=False, total_environment_frames=10**9,
      inference_timeout_ms=5, checkpoint_secs=600, summary_secs=0,
      seed=5)
  run = driver.train(cfg, max_steps=steps, stall_timeout_secs=120)
  assert int(run.state.update_steps) == steps
  fname = ('summaries.jsonl' if proc == 0
           else f'summaries_p{proc}.jsonl')
  fps = []
  with open(os.path.join(logdir, fname)) as f:
    for line in f:
      event = json.loads(line)
      if event['tag'] == 'env_frames_per_sec' and event['value'] > 0:
        fps.append(event['value'])
  back = fps[len(fps) // 2:] or [0.0]
  back.sort()
  print(f'BENCH_MH proc={proc} fps={back[len(back) // 2]:.1f}',
        flush=True)


def bench_multihost(smoke):
  """The multi-process runtime (round 17): per-process fps through the
  REAL spin-up path (distributed.initialize with gloo collectives,
  per-host fleets feeding process-local shards, the cross-process
  gradient psum) vs the single-process row at the SAME per-process
  shape — `scaling_fraction` = multihost global fps / (nprocs x the
  single-process fps), the weak-scaling headline ROADMAP item 1 asks
  for as "a recorded number instead of a hope".

  This host runs the drill as 2 OS processes x 1 virtual CPU device
  (the mechanism and its overheads: gloo collectives, coordination
  heartbeats, per-host summary streams). Real pod rows come from
  running bench on the pod itself with the coordinator flags —
  recorded in docs/PERF.md when chip artifacts land."""
  import socket
  import subprocess
  import sys
  nprocs = 2
  steps = 20 if smoke else 120
  batch_per = 4

  def run_topology(n):
    tmpdir = tempfile.mkdtemp(prefix=f'bench_mh_{n}proc_')
    sock = socket.socket()
    sock.bind(('localhost', 0))
    port = sock.getsockname()[1]
    sock.close()
    env = {k: v for k, v in os.environ.items()
           if k not in ('XLA_FLAGS', 'JAX_PLATFORMS')}
    env.update(BENCH_MH_CHILD='1', BENCH_MH_NPROCS=str(n),
               BENCH_MH_PORT=str(port), BENCH_MH_DIR=tmpdir,
               BENCH_MH_STEPS=str(steps),
               BENCH_MH_BATCH_PER=str(batch_per))
    import shutil
    procs = []
    fps = {}
    try:
      for i in range(n):
        env_i = dict(env, BENCH_MH_PROC=str(i))
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env_i, text=True))
      for i, p in enumerate(procs):
        out, _ = p.communicate(timeout=600)
        assert p.returncode == 0, (
            f'multihost bench child {i}/{n} failed:\n{out[-2000:]}')
        for line in out.splitlines():
          if line.startswith('BENCH_MH '):
            parts = dict(kv.split('=') for kv in line.split()[1:])
            fps[int(parts['proc'])] = float(parts['fps'])
    finally:
      # One child failing (or timing out) must not orphan its
      # siblings holding CPU and the coordinator port, nor leak the
      # scratch dir.
      for p in procs:
        if p.poll() is None:
          p.kill()
        p.communicate()
      shutil.rmtree(tmpdir, ignore_errors=True)
    return fps

  single = run_topology(1)[0]
  multi = run_topology(nprocs)
  # Every process reports the GLOBAL frame rate (frames_per_step is
  # the global batch); the honest aggregate is the minimum — the
  # slowest host paces the collective step.
  mh_fps = min(multi.values())
  results = {
      'nprocs': nprocs,
      'steps': steps,
      'global_batch': batch_per * nprocs,
      'single_1proc': {'env_frames_per_sec': round(single, 1)},
      f'multihost_{nprocs}proc': {
          'env_frames_per_sec': round(mh_fps, 1),
          'per_process': round(mh_fps / nprocs, 1),
          'per_process_fps': {str(k): round(v, 1)
                              for k, v in multi.items()},
      },
      # Weak scaling: n processes each carry the single-process
      # per-host load; 1.0 = the runtime added zero overhead.
      'scaling_fraction': (round(mh_fps / (nprocs * single), 3)
                           if single > 0 else None),
  }
  return results


def bench_mesh2d(smoke):
  """The 2D {data, model} mesh vs pure-DP at the SAME global batch
  (round 19, parallel/sharding.py): what does cutting the params over
  the model axis buy, and what does it cost?

  Two rows through the PRODUCTION sharded path (registry-resolved
  placements, make_sharded_train_step — the exact code the driver
  runs):

  - `dp` — mesh {data: N}, `sharding_rules` resolves to 'replicated';
  - `mesh2d` — mesh {data: N/2, model: 2}, rules 'megatron' (TP on
    Dense/LSTM-gate/Conv kernels).

  Per row: measured `step_ms` (value-readback barrier), and the
  per-device memory split the registry's placements actually produce —
  `state_bytes_per_device` (params + optimizer moments, summed from
  the live state's addressable shards: the at-rest HBM story TP
  exists for) and `batch_bytes_per_device` — plus XLA's static
  `live_bytes_per_device` from the AOT memory analysis of the same
  step under the same shardings (parallel/fit.py's instrument) when
  the backend exposes it.

  Headline: `state_bytes_ratio` (mesh2d/dp, ≈0.5 + replicated-head
  remainder when the cut engages) and both step_ms. CPU rows carry
  the gathered-TP caveat: tp_compute=auto resolves 'gathered' there
  (docs/PARALLELISM.md), so mesh2d step_ms prices gather → replicated
  compute → scatter, NOT true sharded TP compute — per-device step
  time is a TPU question, the memory split is exact everywhere."""
  import numpy as np  # noqa: F401  (parity with sibling stages)
  import jax
  from scalable_agent_tpu import learner as learner_lib
  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.models import ImpalaAgent, init_params
  from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
  from scalable_agent_tpu.parallel import mesh as mesh_lib
  from scalable_agent_tpu.parallel import sharding as sharding_lib
  from scalable_agent_tpu.parallel import train_parallel
  from scalable_agent_tpu.testing import make_example_batch

  h, w = (72, 96) if not smoke else (24, 32)
  b = 32 if not smoke else 8
  t = 20 if not smoke else 4
  steps = 10 if not smoke else 2
  torso = 'deep' if not smoke else 'shallow'
  obs_spec = {'frame': (h, w, 3), 'instr_len': MAX_INSTRUCTION_LEN}

  def run_variant(mp):
    cfg = Config(batch_size=b, unroll_length=t, num_action_repeats=1,
                 total_environment_frames=int(1e9),
                 model_parallelism=mp, sharding_rules='auto',
                 torso=torso, use_instruction=False)
    agent = ImpalaAgent(num_actions=9, torso=torso,
                        use_instruction=False)
    params = init_params(agent, jax.random.PRNGKey(0), obs_spec)
    mesh = mesh_lib.make_mesh(model_parallelism=mp)
    registry = sharding_lib.from_config(cfg)
    state = train_parallel.make_sharded_train_state(
        params, cfg, mesh, registry=registry)
    batch = make_example_batch(t + 1, b, h, w, 9, obs_spec['instr_len'],
                               seed=0, done_prob=0.05)
    step, place = train_parallel.make_sharded_train_step(
        agent, cfg, mesh, batch)
    placed = place(batch)

    def bytes_per_device(tree):
      return int(sum(
          x.addressable_shards[0].data.nbytes
          for x in jax.tree_util.tree_leaves(tree)
          if isinstance(x, jax.Array)))

    state_bytes = bytes_per_device(state)
    batch_bytes = bytes_per_device(placed)

    # Static per-device live bytes of the SAME step under the SAME
    # registry shardings (the fit.py instrument; donation off — the
    # jaxlib TP donation defect xfail'd in tests/test_parallel.py).
    live_bytes = None
    try:
      raw_step = learner_lib.make_train_step_fn(agent, cfg, mesh=mesh)
      state_sh = registry.state_shardings(state, mesh)
      batch_sh = registry.batch_shardings(batch, mesh)
      ma = jax.jit(
          raw_step, in_shardings=(state_sh, batch_sh),
          out_shardings=(state_sh, sharding_lib.replicated(mesh)),
      ).lower(state, placed).compile().memory_analysis()
      live_bytes = int(ma.argument_size_in_bytes +
                       ma.output_size_in_bytes +
                       ma.temp_size_in_bytes - ma.alias_size_in_bytes)
    except Exception as e:  # backend without memory_analysis
      log_note = f'memory_analysis unavailable: {e}'
      live_bytes = None
      del log_note

    state, metrics = step(state, placed)  # warm/compile
    float(metrics['total_loss'])
    t0 = time.perf_counter()
    for _ in range(steps):
      state, metrics = step(state, placed)
    float(metrics['total_loss'])
    step_ms = (time.perf_counter() - t0) / steps * 1e3

    model_cut = any(
        sharding_lib.MODEL_AXIS in str(x.sharding.spec)
        for x in jax.tree_util.tree_leaves(state.params))
    return {
        'mesh': {k: int(v) for k, v in dict(mesh.shape).items()},
        'rule_set': registry.rule_set,
        'global_batch': b,
        'step_ms': round(step_ms, 2),
        'state_bytes_per_device': state_bytes,
        'batch_bytes_per_device': batch_bytes,
        'live_bytes_per_device': live_bytes,
        'model_sharded': bool(model_cut),
        'tp_gathered': bool(getattr(step, 'tp_gathered', False)),
    }

  dp = run_variant(1)
  mesh2d = run_variant(2)
  ratio = (round(mesh2d['state_bytes_per_device'] /
                 dp['state_bytes_per_device'], 3)
           if dp['state_bytes_per_device'] else None)
  return {
      'dp': dp,
      'mesh2d': mesh2d,
      # The memory headline: TP's reason to exist at IMPALA scale.
      'state_bytes_ratio': ratio,
      'step_ms_ratio': (round(mesh2d['step_ms'] / dp['step_ms'], 3)
                        if dp['step_ms'] else None),
  }


def bench_serving(smoke):
  """The multi-tenant serving-plane instrument (round 21): price every
  lever the serving PR added, so its defaults are accepted/rejected on
  measurement (the repo's discipline).

  Rows:
  - codec: wire bytes f32/bf16/int8 (the publish fan-out payload),
    int8 quantize/dequantize round-trip error, and the PARITY GATE —
    greedy action agreement between fp32 serving and int8-resident
    serving on identical inputs + identical RNG (the gate the int8
    default flip will be judged by).
  - publish blackout: update_params wall time per codec — int8 pays
    an on-device quantize per publish; the row says what that costs.
  - resident versions: an N=3-resident server under A/B traffic —
    per-version serve counters prove ≥2 versions SERVED (not merely
    stored).
  - shadow: divergence gauge ~0.0 when the shadow IS the live params,
    > 0 when the shadow is a different network (sanity both ways — a
    gauge that can't move is not a gauge).
  - version-flip blackout: first policy call after an int8 publish,
    AOT-cold vs AOT-warm. The quantized tree changes leaf dtypes, so
    the cold flip pays a full retrace ON the serve path; serving_aot
    pre-compiles at publish time and the flip serves warm.
  - routed: ServingRouter over two in-process replicas (channel =
    serve_remote, no sockets — prices the ROUTER, not the wire), plus
    a kill-one failover check.
  """
  import numpy as np
  import jax
  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.models import ImpalaAgent, init_params
  from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
  from scalable_agent_tpu.runtime import codec
  from scalable_agent_tpu.runtime.inference import (InferenceServer,
                                                    percentile_ms)
  from scalable_agent_tpu.runtime.routing import ServingRouter
  from scalable_agent_tpu.structs import StepOutput, StepOutputInfo

  h, w = (72, 96) if not smoke else (24, 32)
  torso = 'deep' if not smoke else 'shallow'
  reps = 200 if not smoke else 30
  batch = 8 if not smoke else 4
  num_actions = 9
  obs_spec = {'frame': (h, w, 3), 'instr_len': MAX_INSTRUCTION_LEN}
  agent = ImpalaAgent(num_actions=num_actions, torso=torso,
                      use_instruction=False)
  params = init_params(agent, jax.random.PRNGKey(0), obs_spec)
  params_b = init_params(agent, jax.random.PRNGKey(1), obs_spec)
  rng = np.random.RandomState(0)

  def payload(server, b=batch):
    sizes = [int(np.shape(c)[-1])
             for c in server.initial_core_state()]
    return {
        'prev_action': rng.randint(0, num_actions, (b,)).astype(np.int32),
        'reward': np.zeros((b,), np.float32),
        'done': np.zeros((b,), np.bool_),
        'frame': rng.randint(0, 255, (b, h, w, 3)).astype(np.uint8),
        'instr': np.zeros((b, MAX_INSTRUCTION_LEN), np.int32),
        'core_c': np.zeros((b, sizes[0]), np.float32),
        'core_h': np.zeros((b, sizes[1]), np.float32),
    }

  def make_server(**over):
    cfg = Config(inference_min_batch=0, inference_max_batch=max(16, batch),
                 inference_timeout_ms=5, inference_state_cache=False,
                 **over)
    return InferenceServer(agent, params, cfg, seed=7, pad_batch_to=1,
                           fleet_size=1)

  results = {}

  # --- codec rows: wire bytes, round-trip error, publish blackout.
  f32_b, bf16_b, int8_b = codec.wire_sizes(jax.device_get(params))
  q = codec.quantize_np(jax.device_get(params))
  results['wire_bytes'] = {
      'f32': f32_b, 'bf16': bf16_b, 'int8': int8_b,
      'int8_vs_f32': round(int8_b / f32_b, 3),
      'int8_vs_bf16': round(int8_b / bf16_b, 3),
      'roundtrip_max_abs_err': float(codec.max_abs_error(q)),
  }

  def publish_blackout(codec_name):
    server = make_server(publish_codec=codec_name)
    times = []
    for k in range(8 if smoke else 32):
      fresh = jax.tree_util.tree_map(lambda a: a + 0, params)
      t0 = time.perf_counter()
      server.update_params(fresh, version=k + 1)
      times.append(time.perf_counter() - t0)
    server.close()
    return {'p50_ms': round(percentile_ms(sorted(times), 0.5, 1e3), 2),
            'p99_ms': round(percentile_ms(sorted(times), 0.99, 1e3), 2)}

  results['publish_blackout'] = {name: publish_blackout(name)
                                 for name in ('f32', 'int8')}

  # --- parity gate: fp32 vs int8-resident serving, same inputs, same
  # per-call RNG (both servers fold the same dedicated base key).
  s_f32 = make_server()
  s_int8 = make_server(publish_codec='int8')
  s_f32.update_params(params, version=1)
  s_int8.update_params(params, version=1)
  pay = payload(s_f32)
  out_a = s_f32.serve_remote(pay)
  out_b = s_int8.serve_remote(pay)
  results['int8_parity'] = {
      'greedy_agreement': round(float(codec.greedy_agreement(
          out_a['logits'], out_b['logits'])), 4),
      'logits_max_abs_err': float(np.max(np.abs(
          out_a['logits'] - out_b['logits']))),
  }

  # --- routed: two in-process replicas; price the router itself.
  class _LocalChannel:
    def __init__(self, server):
      self._server = server
      self.dead = False

    def supports_infer(self):
      return True

    def remote_infer(self, req):
      if self.dead:
        raise ConnectionError('replica killed')
      return self._server.serve_remote(req), {}

    def close(self):
      pass

  channels = {'a:0': _LocalChannel(s_f32), 'b:0': _LocalChannel(s_int8)}
  router = ServingRouter(['a:0', 'b:0'],
                         connect_fn=lambda addr: channels[addr])
  direct = []
  for _ in range(reps):
    t0 = time.perf_counter()
    s_f32.serve_remote(pay)
    direct.append(time.perf_counter() - t0)
  routed = []
  for _ in range(reps):
    t0 = time.perf_counter()
    router.infer(pay)
    routed.append(time.perf_counter() - t0)
  channels['a:0'].dead = True
  # Several requests so the rotation is GUARANTEED to pick the dead
  # replica at least once — the row must price the failover path, not
  # a lucky pick of the survivor.
  survived = all(router.infer(pay) is not None for _ in range(4))
  rstats = router.stats()
  router.close()
  results['routed'] = {
      'direct_p50_ms': round(percentile_ms(sorted(direct), 0.5, 1e3), 3),
      'routed_p50_ms': round(percentile_ms(sorted(routed), 0.5, 1e3), 3),
      'failover_survived': bool(survived),
      'route_failovers': rstats['route_failovers'],
      'serves': {r['address']: r['serves'] for r in rstats['replicas']},
  }
  s_f32.close()
  s_int8.close()

  # --- resident versions under A/B + shadow traffic.
  server = make_server(serving_resident_versions=3,
                       serving_ab_fraction=0.25,
                       serving_shadow_fraction=1.0)
  server.update_params(params, version=1)
  server.update_params(jax.tree_util.tree_map(lambda a: a + 0, params),
                       version=2)  # live v2, shadow auto = v1 (equal)
  frame = rng.randint(0, 255, (h, w, 3)).astype(np.uint8)
  instr = np.zeros((MAX_INSTRUCTION_LEN,), np.int32)

  def drive_policy(n):
    state = server.initial_core_state()
    prev = np.int32(0)
    for step in range(n):
      env_out = StepOutput(
          reward=np.float32(0.0),
          info=StepOutputInfo(np.float32(0), np.int32(0)),
          done=np.bool_(False),
          observation=(frame, instr))
      out, state = server.policy(prev, env_out, state)
      prev = np.int32(out.action)

  drive_policy(reps)
  div_equal = server.stats()['shadow_divergence']
  server.update_params(params_b, version=3)  # live v3, shadow = v2
  drive_policy(reps)
  snap = server.stats()
  results['resident'] = {
      'resident_versions': snap['resident_versions'],
      'live_version': snap['live_version'],
      'serve_counts': snap['serve_counts'],
      'ab_calls': snap['ab_calls'],
      'shadow_calls': snap['shadow_calls'],
      'shadow_divergence_equal': div_equal,
      'shadow_divergence_different': snap['shadow_divergence'],
  }
  server.close()

  # --- version-flip blackout: int8 publish flips the resident leaf
  # dtypes; cold pays the retrace on the first serve, warm (AOT
  # pre-compile at publish) does not.
  def flip_blackout(aot):
    server = make_server(publish_codec='int8', serving_aot=aot)
    server.warmup(obs_spec, sizes=[1])
    times = []
    for k in range(3):
      server.update_params(
          jax.tree_util.tree_map(lambda a: a + 0, params_b),
          version=k + 1)
      t0 = time.perf_counter()
      state = server.initial_core_state()
      env_out = StepOutput(
          reward=np.float32(0.0),
          info=StepOutputInfo(np.float32(0), np.int32(0)),
          done=np.bool_(False),
          observation=(frame, instr))
      server.policy(np.int32(0), env_out, state)
      times.append((time.perf_counter() - t0) * 1e3)
    stats = server.stats()
    server.close()
    return {'first_flip_ms': round(times[0], 2),
            'steady_p99_ms': round(max(times[1:]), 2),
            'aot_misses': stats['aot_misses'],
            'aot_compiled': stats['aot_compiled']}

  results['flip_blackout'] = {'cold': flip_blackout(False),
                              'warm': flip_blackout(True)}
  return results


def bench_population(smoke):
  """The population engine (round 22; population.py, docs/PERF.md
  r22). Two measured claims:

  1. Curriculum tax: the SAME fused Anakin procgen run with
     --curriculum=uniform vs --curriculum=regret — the prioritized
     sampler, per-level EMA fold, and score-table carry all live
     INSIDE the jitted step (zero host round trips per level
     decision), so the acceptance gate is fps within 5% of uniform.
     The regret row also reports the per-level telemetry (entropy,
     levels visited) so the row shows the curriculum actually DROVE
     the distribution, not just cost nothing.
  2. Padding waste: a mixed-suite request stream (16x16 cue-scale
     frames + 24x32 gridworld-scale frames, 2:1) through the REAL
     C++ batcher behind ops/dynamic_batching.FamilyBatcher —
     per-obs-spec-family queues merge rows at their exact shape, so
     padded bytes == useful bytes; the reported waste_ratio is what
     the SAME stream would have paid under naive pad-to-fleet-max
     (the measured elimination claim).
  """
  import numpy as np
  import jax
  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.ops import dynamic_batching
  from scalable_agent_tpu.parallel import anakin
  from scalable_agent_tpu.parallel import mesh as mesh_lib

  n_dev = len(jax.devices())
  steps = 200 if not smoke else 3
  t = 20 if not smoke else 3
  b = 256 if not smoke else 8
  b = max(b - b % n_dev, n_dev)
  mesh = mesh_lib.make_mesh() if n_dev > 1 else None
  out = {'devices': n_dev,
         'config': 'procgen, shallow, 24x32, T=%d, B=%d, %d step(s)'
                   % (t, b, steps)}

  for mode in ('uniform', 'regret'):
    cfg = Config(env_backend='procgen', batch_size=b,
                 unroll_length=t, num_action_repeats=1,
                 episode_length=12, height=24, width=32,
                 torso='shallow',
                 compute_dtype='bfloat16' if not smoke else 'float32',
                 use_instruction=False, use_py_process=False,
                 learning_rate=2e-3, entropy_cost=3e-3,
                 discounting=0.9, total_environment_frames=10**9,
                 curriculum=mode, procgen_num_levels=8, seed=0)
    _, history, fps = anakin.run(cfg, steps, mesh=mesh)
    row = {'env_frames_per_sec': round(fps, 1), 'batch_size': b}
    if mode != 'uniform':
      last = history[-1]
      row.update({
          'curriculum_entropy': round(
              float(last['curriculum_entropy']), 3),
          'levels_visited': int(last['curriculum_levels_visited']),
          'score_max': round(float(last['curriculum_score_max']), 4),
      })
    out[mode] = row
  overhead = 1.0 - (out['regret']['env_frames_per_sec'] /
                    max(out['uniform']['env_frames_per_sec'], 1e-9))
  out['curriculum_overhead_fraction'] = round(overhead, 4)
  out['curriculum_gate'] = {'threshold': 0.05,
                            'pass': bool(overhead <= 0.05)}

  # --- Mixed-suite padding waste through the real batcher. ---
  def make_fn(key):
    def handler(*arrays):
      # Row-wise reduce: enough work to exercise the padded staging
      # without turning the row into a compute bench.
      return [np.ascontiguousarray(
          arrays[0].reshape(arrays[0].shape[0], -1).sum(-1))]
    return handler

  fb = dynamic_batching.FamilyBatcher(
      make_fn, minimum_batch_size=1, maximum_batch_size=256,
      timeout_ms=2)
  small = np.zeros((1, 16, 16, 3), np.uint8)
  large = np.zeros((1, 24, 32, 3), np.uint8)
  requests = 600 if not smoke else 60
  workers = 6
  errors = []

  def pump(worker):
    try:
      for i in range(requests // workers):
        # 2:1 small:large — the heterogeneous composition a mixed
        # cue+gridworld fleet produces.
        fb(small if (worker + i) % 3 else large)
    except Exception as exc:  # pragma: no cover - surfaced below
      errors.append(exc)

  threads = [threading.Thread(target=pump, args=(w,))
             for w in range(workers)]
  start = time.perf_counter()
  for th in threads:
    th.start()
  for th in threads:
    th.join()
  elapsed = time.perf_counter() - start
  stats = fb.padding_stats()
  fb.close()
  if errors:
    raise errors[0]
  out['padding'] = {
      'requests': requests,
      'families': int(stats['families']),
      'rows_per_sec': round(stats['rows'] / max(elapsed, 1e-9), 1),
      'useful_bytes': stats['useful_bytes'],
      'bucketed_bytes': stats['bucketed_bytes'],
      'max_shape_bytes': stats['max_shape_bytes'],
      'waste_ratio': round(stats['waste_ratio'], 4),
  }

  # --- Round 23: fused (vmapped) population vs serial round-robin.
  # N single-device members at IDENTICAL per-member shapes. The
  # serial side pays what the r22 population loop pays every round:
  # a fresh make_anakin_step trace + spin-up per member, members
  # stepped one after another. The fused side builds ONE vmapped
  # program and advances all members in lockstep. Wall INCLUDES
  # trace/compile on both sides — amortizing N traces into one IS
  # the claim (docs/PERF.md r23; gate: >= 2x aggregate fps). ---
  import dataclasses as _dc
  import jax.numpy as jnp
  from scalable_agent_tpu import driver as driver_lib

  n_members = 4
  psteps = 40 if not smoke else 3
  pcfg = Config(env_backend='bandit',
                batch_size=16 if not smoke else 4,
                unroll_length=10 if not smoke else 5,
                num_action_repeats=1, episode_length=5,
                torso='shallow', use_instruction=False,
                use_py_process=False, learning_rate=2e-3,
                entropy_cost=3e-3, discounting=0.9,
                total_environment_frames=10**9, seed=0)
  member_frames = psteps * pcfg.frames_per_step

  start = time.perf_counter()
  for k in range(n_members):
    anakin.run(_dc.replace(pcfg, seed=pcfg.seed + 101 * k + 1),
               psteps)
  serial_wall = time.perf_counter() - start
  serial_fps = n_members * member_frames / max(serial_wall, 1e-9)

  start = time.perf_counter()
  env_core = anakin.make_env_core(pcfg)
  agent = driver_lib.build_agent(pcfg, env_core.num_actions)
  vstep = anakin.make_vectorized_anakin_step(agent, env_core, pcfg)
  stacked = anakin.init_stacked_carry(
      agent, env_core, pcfg,
      [pcfg.seed + 101 * k + 1 for k in range(n_members)])
  hyp = {'learning_rate': jnp.full((n_members,), pcfg.learning_rate,
                                   jnp.float32),
         'entropy_cost': jnp.full((n_members,), pcfg.entropy_cost,
                                  jnp.float32)}
  metrics = None
  for _ in range(psteps):
    stacked, metrics = vstep(stacked, hyp)
  jax.block_until_ready(metrics['total_loss'])
  fused_wall = time.perf_counter() - start
  fused_fps = n_members * member_frames / max(fused_wall, 1e-9)
  speedup = fused_fps / max(serial_fps, 1e-9)
  out['fused_population'] = {
      'members': n_members, 'steps_per_member': psteps,
      'member_config': 'bandit, shallow, T=%d, B=%d'
                       % (pcfg.unroll_length, pcfg.batch_size),
      'serial_wall_secs': round(serial_wall, 3),
      'serial_env_frames_per_sec': round(serial_fps, 1),
      'fused_wall_secs': round(fused_wall, 3),
      'fused_env_frames_per_sec': round(fused_fps, 1),
      'speedup': round(speedup, 2),
      'gate': {'threshold': 2.0, 'pass': bool(speedup >= 2.0)},
  }
  return out


def main():
  # Child half of the multihost stage: a fresh interpreter dispatched
  # by bench_multihost — must run before any jax/backend setup below.
  if os.environ.get('BENCH_MH_CHILD'):
    _multihost_child_main()
    return
  # BENCH_SMOKE=1: tiny shapes on CPU — validates bench mechanics in CI
  # without the chip. The driver runs the real thing (no env var, TPU).
  smoke = os.environ.get('BENCH_SMOKE') == '1'
  if smoke:
    import jax
    jax.config.update('jax_platforms', 'cpu')

  # BENCH_ONLY=learner_plane: run just the learner-feed stage (the
  # scripts/ci.sh smoke — same rationale as the inference_plane lane).
  if os.environ.get('BENCH_ONLY') == 'learner_plane':
    plane = bench_learner_plane(smoke)
    _emit({
        'metric': 'learner_plane_exposed_feed_ms_per_step',
        'value': min(row['exposed_feed_ms_per_step']
                     for row in plane.values()
                     if isinstance(row, dict)
                     and 'exposed_feed_ms_per_step' in row),
        'unit': ('exposed feed ms/step, best staging variant%s'
                 % (' (SMOKE)' if smoke else '')),
        'learner_plane': plane,
    })
    return

  # BENCH_ONLY=inference_plane: run just the actor-plane stage (the
  # scripts/ci.sh smoke — the full bench's compile budget doesn't fit
  # a CI lane; the stage's mechanics must still be exercised there).
  if os.environ.get('BENCH_ONLY') == 'inference_plane':
    infer = bench_inference_plane(smoke)
    best = max((row['policy_calls_per_sec']
                for row in infer.values() if isinstance(row, dict)),
               default=0.0)
    _emit({
        'metric': 'inference_plane_policy_calls_per_sec',
        'value': best,
        'unit': ('policy calls/sec, best variant%s'
                 % (' (SMOKE)' if smoke else '')),
        'inference_plane': infer,
    })
    return

  # BENCH_ONLY=replay: just the sample-reuse rows (the scripts/ci.sh
  # smoke — replay_k x ratio mechanics + the cue_memory curve run).
  if os.environ.get('BENCH_ONLY') == 'replay':
    replay = bench_replay(smoke)
    k2 = replay.get('k2_r0') or {}
    _emit({
        'metric': 'replay_k2_reuse_factor',
        'value': k2.get('reuse_factor', 0.0),
        'unit': ('learner updates per env frame vs no-reuse baseline '
                 'at replay_k=2%s' % (' (SMOKE)' if smoke else '')),
        'replay': replay,
    })
    return

  # BENCH_ONLY=anakin: just the runtime-axis rows (the scripts/ci.sh
  # anakin lane — fused-loop fps over the jittable env family, the
  # fed-fleet reference ratio, and the hybrid filler off/on
  # utilization rows).
  if os.environ.get('BENCH_ONLY') == 'anakin':
    anakin_rows = bench_anakin(smoke)
    _emit({
        'metric': 'anakin_env_frames_per_sec',
        'value': (anakin_rows.get('bandit_1dev') or {}).get(
            'env_frames_per_sec'),
        'unit': ('env-frames/sec, fused act+learn, bandit, 1 device%s'
                 % (' (SMOKE)' if smoke else '')),
        'anakin': anakin_rows,
    })
    return

  # BENCH_ONLY=telemetry: just the tracing/registry overhead rows
  # (the scripts/ci.sh telemetry smoke — the on/off accept gate).
  if os.environ.get('BENCH_ONLY') == 'telemetry':
    tele = bench_telemetry(smoke)
    _emit({
        'metric': 'telemetry_overhead_fraction',
        'value': tele.get('overhead_fraction'),
        'unit': ('feed-throughput fraction lost with tracing on%s'
                 % (' (SMOKE)' if smoke else '')),
        'telemetry': tele,
    })
    return

  # BENCH_ONLY=slo: just the SLO-engine overhead rows (the
  # scripts/ci.sh slo lane — evaluator tick + triggered-capture cost).
  if os.environ.get('BENCH_ONLY') == 'slo':
    slo_rows = bench_slo(smoke)
    _emit({
        'metric': 'slo_evaluator_tick_us',
        'value': slo_rows.get('evaluator_tick_us'),
        'unit': ('microseconds per SLO evaluator tick, default '
                 'objective set%s' % (' (SMOKE)' if smoke else '')),
        'slo': slo_rows,
    })
    return

  # BENCH_ONLY=multihost: just the 2-process runtime rows (the
  # scripts/ci.sh multihost lane — per-process fps + the scaling
  # fraction vs the single-process row).
  if os.environ.get('BENCH_ONLY') == 'multihost':
    mh = bench_multihost(smoke)
    _emit({
        'metric': 'multihost_scaling_fraction',
        'value': mh.get('scaling_fraction'),
        'unit': ('multihost global fps / (nprocs x single-process '
                 'fps), 2 procs x 1 CPU device%s'
                 % (' (SMOKE)' if smoke else '')),
        'multihost': mh,
    })
    return

  # BENCH_ONLY=controller: just the controller-loop rows (the
  # scripts/ci.sh controller lane — idle/acting tick + cycle cost).
  if os.environ.get('BENCH_ONLY') == 'controller':
    ctrl_rows = bench_controller(smoke)
    _emit({
        'metric': 'controller_idle_tick_us',
        'value': ctrl_rows.get('idle_tick_us'),
        'unit': ('microseconds per idle controller tick, default '
                 'rule table%s' % (' (SMOKE)' if smoke else '')),
        'controller': ctrl_rows,
    })
    return

  # BENCH_ONLY=mesh2d: just the 2D {data, model} mesh rows (the
  # scripts/ci.sh sharding-lane smoke — registry-resolved DP vs
  # DP+TP at the same global batch, step time + per-device bytes).
  if os.environ.get('BENCH_ONLY') == 'mesh2d':
    mesh2d = bench_mesh2d(smoke)
    _emit({
        'metric': 'mesh2d_state_bytes_ratio',
        'value': mesh2d['state_bytes_ratio'],
        'unit': ('per-device state bytes, {data,model} mesh / pure-DP '
                 'mesh, same global batch%s'
                 % (' (SMOKE)' if smoke else '')),
        'mesh2d': mesh2d,
    })
    return

  # BENCH_ONLY=overload: just the overload rows (the scripts/ci.sh
  # chaos-adjacent smoke — shed-rate/tail-latency mechanics on CPU).
  if os.environ.get('BENCH_ONLY') == 'overload':
    overload = bench_overload(smoke)
    worst = max((row['shed_fraction']
                 for row in overload.values() if isinstance(row, dict)),
                default=0.0)
    _emit({
        'metric': 'overload_worst_shed_fraction',
        'value': worst,
        'unit': ('sheds/acquires at 4x slot pressure, shed admission%s'
                 % (' (SMOKE)' if smoke else '')),
        'overload': overload,
    })
    return

  # BENCH_ONLY=population: just the population-engine rows (the
  # scripts/ci.sh population lane — curriculum on/off fused fps with
  # the <=5% gate, and the mixed-suite padding-waste row).
  if os.environ.get('BENCH_ONLY') == 'population':
    pop = bench_population(smoke)
    _emit({
        'metric': 'curriculum_overhead_fraction',
        'value': pop.get('curriculum_overhead_fraction'),
        'unit': ('fused-loop fps fraction lost with the in-graph '
                 'regret curriculum on, gate <= 0.05%s'
                 % (' (SMOKE)' if smoke else '')),
        'population': pop,
    })
    return

  # BENCH_ONLY=serving: just the multi-tenant serving-plane rows (the
  # scripts/ci.sh serving lane — resident versions, int8 parity +
  # wire bytes, flip blackout AOT warm/cold, router overhead).
  if os.environ.get('BENCH_ONLY') == 'serving':
    serving = bench_serving(smoke)
    _emit({
        'metric': 'serving_int8_greedy_agreement',
        'value': serving['int8_parity']['greedy_agreement'],
        'unit': ('argmax action agreement, int8-resident vs fp32 '
                 'serving, identical inputs+RNG%s'
                 % (' (SMOKE)' if smoke else '')),
        'serving': serving,
    })
    return

  rows = bench_synthetic(smoke)
  cfg = rows['config']
  stats = rows['synthetic']
  e2e = None
  e2e_fed = None
  if os.environ.get('BENCH_SKIP_E2E') != '1':
    e2e = bench_e2e(smoke)
    e2e_fed = bench_e2e_fed(smoke)
  transport = None
  if os.environ.get('BENCH_SKIP_TRANSPORT') != '1':
    transport = bench_transport(smoke)
  fanout = None
  if os.environ.get('BENCH_SKIP_FANOUT') != '1':
    fanout = bench_param_fanout(smoke)
  anakin = None
  if os.environ.get('BENCH_SKIP_ANAKIN') != '1':
    anakin = bench_anakin(smoke)
  infer = None
  if os.environ.get('BENCH_SKIP_INFERENCE') != '1':
    infer = bench_inference_plane(smoke)
  overload = None
  if os.environ.get('BENCH_SKIP_OVERLOAD') != '1':
    overload = bench_overload(smoke)
  plane = None
  if os.environ.get('BENCH_SKIP_LEARNER_PLANE') != '1':
    plane = bench_learner_plane(smoke)
  replay = None
  if os.environ.get('BENCH_SKIP_REPLAY') != '1':
    replay = bench_replay(smoke)
  tele = None
  if os.environ.get('BENCH_SKIP_TELEMETRY') != '1':
    tele = bench_telemetry(smoke)
  slo_rows = None
  if os.environ.get('BENCH_SKIP_SLO') != '1':
    slo_rows = bench_slo(smoke)
  ctrl_rows = None
  if os.environ.get('BENCH_SKIP_CONTROLLER') != '1':
    ctrl_rows = bench_controller(smoke)
  mh_rows = None
  if os.environ.get('BENCH_SKIP_MULTIHOST') != '1':
    mh_rows = bench_multihost(smoke)
  mesh2d_rows = None
  if os.environ.get('BENCH_SKIP_MESH2D') != '1':
    mesh2d_rows = bench_mesh2d(smoke)
  serving_rows = None
  if os.environ.get('BENCH_SKIP_SERVING') != '1':
    serving_rows = bench_serving(smoke)
  pop_rows = None
  if os.environ.get('BENCH_SKIP_POPULATION') != '1':
    pop_rows = bench_population(smoke)

  baseline_per_chip = 200_000.0 / 16.0  # north star / v5e-16 chips
  out = {
      'metric': 'learner_env_frames_per_sec_per_chip',
      'value': stats['median'],  # median of ≥3 windows (VERDICT r4 W1)
      'unit': ('env-frames/sec (deep ResNet, T=%d, B=%d, bf16, 1 chip%s)'
               % (cfg.unroll_length, cfg.batch_size,
                  ', SMOKE' if smoke else '')),
      'vs_baseline': round(stats['median'] / baseline_per_chip, 3),
      'synthetic': stats,
  }
  # The per-feature itemization + lever grid (round 6, VERDICT r5
  # weak #3): no_instruction is the plain base; popart_only/pc_only
  # ride it one feature at a time; the headline row doubles as the
  # instruction-only row; pc_levers re-measures the pixel-control
  # fast-path variants head-to-head at the full-feature point.
  for key in ('no_instruction', 'popart_only', 'pc_only',
              'full_feature', 'deep_fast'):
    if rows.get(key) is not None:
      out[key] = rows[key]
      out[f'{key}_fps'] = rows[key]['median']
  if rows.get('pc_levers') is not None:
    out['pc_levers'] = rows['pc_levers']
  if e2e is not None:
    out['e2e'] = e2e
  if e2e_fed is not None:
    out['e2e_fed'] = e2e_fed
  if transport is not None:
    out['transport'] = transport
  if fanout is not None:
    out['param_fanout'] = fanout
  if anakin is not None:
    out['anakin'] = anakin
  if infer is not None:
    out['inference_plane'] = infer
  if overload is not None:
    out['overload'] = overload
  if plane is not None:
    out['learner_plane'] = plane
  if replay is not None:
    out['replay'] = replay
  if tele is not None:
    out['telemetry'] = tele
  if slo_rows is not None:
    out['slo'] = slo_rows
  if ctrl_rows is not None:
    out['controller'] = ctrl_rows
  if mh_rows is not None:
    out['multihost'] = mh_rows
  if mesh2d_rows is not None:
    out['mesh2d'] = mesh2d_rows
  if serving_rows is not None:
    out['serving'] = serving_rows
  if pop_rows is not None:
    out['population'] = pop_rows
  _emit(out)


def _headline(out):
  """The compact last line: the handful of gate numbers a clipped tail
  must still carry (VERDICT r5 weak #1 — the full JSON line got cut
  mid-object by the driver's tail capture)."""
  head = {
      'metric': out['metric'],
      'value': out['value'],
      'vs_baseline': out.get('vs_baseline'),
      'artifact': 'BENCH_OUT.json',
  }
  # The full-feature itemization (round 6): the popart/pc/instruction
  # split must ride the clip-safe last line — BENCH_rN's tail is the
  # round's record and must carry the 20%'s named owners by itself.
  for key in ('no_instruction_fps', 'popart_only_fps', 'pc_only_fps',
              'full_feature_fps', 'deep_fast_fps'):
    if out.get(key) is not None:
      head[key] = out[key]
  levers = out.get('pc_levers')
  if levers:
    head['pc_levers'] = {
        name: stats['median'] for name, stats in levers.items()
        if isinstance(stats, dict) and 'median' in stats}
  fed = out.get('e2e_fed')
  if fed:
    head['e2e_fed_fps'] = fed['fps']
    head['h2d_overlap_fraction'] = fed.get('h2d_overlap_fraction')
    gap = fed.get('gap_itemization') or {}
    head['h2d_exposed_ms'] = gap.get('h2d_exposed_ms')
    # Sample-reuse motivation row (round 10): the measurement that
    # justifies replay (learner idling on an env-bound pipeline) and
    # later judges it — must survive a clipped tail.
    head['learner_updates_per_env_frame'] = fed.get(
        'learner_updates_per_env_frame')
    head['plane_utilization'] = {
        'env': fed.get('env_plane_utilization'),
        'learner': fed.get('learner_plane_utilization')}
  transport = out.get('transport')
  if transport:
    head['ingest_1conn'] = transport['ingest_1conn']['unrolls_per_sec']
    if 'ingest_4conn' in transport:
      head['ingest_4conn'] = (
          transport['ingest_4conn']['unrolls_per_sec'])
  fanout = out.get('param_fanout')
  if fanout:
    for key, value in fanout.items():
      if key.startswith('pump_with_') and value:
        head['pump_contended_unrolls_per_sec'] = (
            value['unrolls_per_sec'])
        head['pump_contended_ack_p99_ms'] = value['ack_p99_ms']
    if fanout.get('pump_alone'):
      head['pump_alone_unrolls_per_sec'] = (
          fanout['pump_alone']['unrolls_per_sec'])
  # The actor-plane itemization (round 7): the cache×pipeline call
  # — calls/s + latency p50/p99 at the largest fleet — must ride the
  # clip-safe last line (any state-cache / pipeline-depth default flip
  # is justified by exactly these rows).
  infer = out.get('inference_plane')
  if infer:
    fmax = max(infer.get('fleet_sizes') or [0])
    head['inference_plane'] = {
        name: {'cps': row['policy_calls_per_sec'],
               'p50': row['lat_p50_ms'], 'p99': row['lat_p99_ms']}
        for name, row in infer.items()
        if isinstance(row, dict) and name.endswith(f'_f{fmax}')}
  # The overload rows (round 9): shed fraction + served tail latency
  # at 1x/2x/4x slot pressure — the clip-safe record of what the
  # admission policy does under the load the chaos storm drills.
  overload = out.get('overload')
  if overload:
    head['overload'] = {
        name: {'p99': row['lat_p99_ms'],
               'shed_fraction': row['shed_fraction']}
        for name, row in overload.items() if isinstance(row, dict)}
  # The learner-feed itemization (round 8): the {batch, unroll} ×
  # depth rows plus the sharded pallas-vs-scan call must ride the
  # clip-safe last line — BENCH_r08 carries the --staging_mode and
  # Pallas-under-mesh accept/reject on exactly these numbers.
  plane = out.get('learner_plane')
  if plane:
    head['learner_plane'] = {
        name: {'exposed': row['exposed_feed_ms_per_step'],
               'gap': row['step_gap_ms'],
               'overlap': row['h2d_overlap_fraction']}
        for name, row in plane.items()
        if isinstance(row, dict) and 'exposed_feed_ms_per_step' in row}
    head['learner_plane']['bare_step_ms'] = plane['bare_step_ms']
    if plane.get('vtrace_sharded'):
      head['learner_plane']['vtrace_sharded'] = plane['vtrace_sharded']
  # The sample-reuse rows (round 10): reuse factor + step cost per
  # replay_k x ratio cell — the clip-safe record the replay_k default
  # flip is judged on (k2_r0 >= 1.8x is the acceptance gate).
  replay = out.get('replay')
  if replay:
    head['replay'] = {
        name: {'reuse': row['reuse_factor'],
               'step_ms': row['fed_step_ms'],
               'h2d_per_update': row['h2d_unrolls_per_update']}
        for name, row in replay.items()
        if isinstance(row, dict) and 'reuse_factor' in row}
    curves = replay.get('return_vs_wallclock') or {}
    if curves.get('reuse_k2'):
      head['replay']['cue_memory_updates_per_env_frame'] = (
          curves['reuse_k2'].get('updates_per_env_frame'))
  # The runtime-axis rows (round 16): single-device fused fps, the
  # real-fleet ratio the >=3x acceptance gate reads (vs_fed is the
  # acting-free learner ceiling, documented in docs/PERF.md r13), and
  # the hybrid filler's utilization lift — the clip-safe record the
  # --anakin_filler default flip is judged on.
  anakin_rows = out.get('anakin')
  if anakin_rows:
    hybrid = anakin_rows.get('hybrid') or {}
    head['anakin'] = {
        'fps_1dev': (anakin_rows.get('bandit_1dev') or {}).get(
            'env_frames_per_sec'),
        'vs_fleet': anakin_rows.get('anakin_vs_fleet'),
        'vs_fed': anakin_rows.get('anakin_vs_fed'),
        'hybrid_utilization': {
            'off': (hybrid.get('filler_off') or {}).get(
                'learner_plane_utilization'),
            'on': (hybrid.get('filler_on') or {}).get(
                'learner_plane_utilization'),
            'lift': hybrid.get('utilization_lift')},
    }
  # The telemetry-plane cost (round 13): the on/off feed overhead the
  # always-on tracing default is accepted/rejected on (docs/PERF.md
  # r11) — clip-safe like every other default-flip record.
  tele = out.get('telemetry')
  if tele:
    head['telemetry'] = {
        'overhead_fraction': tele.get('overhead_fraction'),
        'span_ns': tele.get('span_ns'),
        'registry_ns_per_op': tele.get('registry_ns_per_op')}
  # The SLO-engine cost (round 14): evaluator tick + triggered-
  # capture overhead — the numbers the always-on judging default is
  # accepted/rejected on (docs/PERF.md r12), clip-safe like every
  # other default-flip record.
  slo_rows = out.get('slo')
  if slo_rows:
    head['slo'] = {
        'evaluator_tick_us': slo_rows.get('evaluator_tick_us'),
        'verdict_us': slo_rows.get('verdict_us'),
        'capture_overhead_fraction':
            slo_rows.get('capture_overhead_fraction')}
  # The controller-loop cost (round 15): idle/acting tick + the full
  # escalate->revert cycle — the numbers the default observe-mode
  # thread is accepted/rejected on, clip-safe like every other
  # default-flip record.
  ctrl_rows = out.get('controller')
  if ctrl_rows:
    head['controller'] = {
        'idle_tick_us': ctrl_rows.get('idle_tick_us'),
        'acting_tick_us': ctrl_rows.get('acting_tick_us'),
        'cycle_wall_ms': ctrl_rows.get('cycle_wall_ms')}
  # The multi-process runtime (round 17): per-process fps + the weak-
  # scaling fraction vs the single-process row — ROADMAP item 1's
  # "recorded number instead of a hope", clip-safe.
  mh = out.get('multihost')
  if mh:
    nprocs = mh.get('nprocs')
    mh_row = mh.get(f'multihost_{nprocs}proc') or {}
    head['multihost'] = {
        'scaling_fraction': mh.get('scaling_fraction'),
        'fps': mh_row.get('env_frames_per_sec'),
        'fps_per_process': mh_row.get('per_process'),
        'single_fps': (mh.get('single_1proc') or {}).get(
            'env_frames_per_sec')}
  # The 2D {data, model} mesh rows (round 19): the per-device memory
  # split the registry's TP rules buy + both step times — the numbers
  # the mesh shape is accepted/rejected on (docs/PERF.md), clip-safe.
  m2d = out.get('mesh2d')
  if m2d:
    head['mesh2d'] = {
        'state_bytes_ratio': m2d.get('state_bytes_ratio'),
        'step_ms_ratio': m2d.get('step_ms_ratio'),
        'dp_step_ms': (m2d.get('dp') or {}).get('step_ms'),
        'mesh2d_step_ms': (m2d.get('mesh2d') or {}).get('step_ms')}
  # The population-engine rows (round 22): curriculum tax vs the <=5%
  # gate + the mixed-suite padding-waste elimination — the clip-safe
  # record the --curriculum default flip is judged on.
  pop = out.get('population')
  if pop:
    head['population'] = {
        'curriculum_overhead_fraction':
            pop.get('curriculum_overhead_fraction'),
        'curriculum_gate_pass': (pop.get('curriculum_gate')
                                 or {}).get('pass'),
        'uniform_fps': (pop.get('uniform') or {}).get(
            'env_frames_per_sec'),
        'regret_fps': (pop.get('regret') or {}).get(
            'env_frames_per_sec'),
        'padding_waste_ratio': (pop.get('padding') or {}).get(
            'waste_ratio'),
        'fused_speedup': (pop.get('fused_population')
                          or {}).get('speedup'),
        'fused_gate_pass': ((pop.get('fused_population')
                             or {}).get('gate') or {}).get('pass')}
  return head


def _emit(out, path=None):
  """Self-contained artifact protocol: write the FULL result to
  BENCH_OUT.json, print the full JSON line (for humans tailing the
  log), then print the compact headline LAST so the driver's tail
  capture always ends on one complete, parseable object."""
  if path is None:
    path = os.environ.get('BENCH_OUT', os.path.join(
        os.path.dirname(os.path.abspath(__file__)), 'BENCH_OUT.json'))
  with open(path, 'w') as f:
    json.dump(out, f, indent=1, sort_keys=True)
  print(json.dumps(out))
  print(json.dumps(_headline(out)), flush=True)


if __name__ == '__main__':
  # Before any JAX initialization, but inside the main guard: the
  # forkserver preloads __main__, so a module-level call would
  # recursively spawn a second server (see runtime/py_process.py).
  from scalable_agent_tpu.runtime.py_process import warm_forkserver
  warm_forkserver()
  main()
