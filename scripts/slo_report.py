"""SLO verdict report + perf-regression gate (round 14).

    python scripts/slo_report.py LOGDIR [--bench BENCH_OUT.json]
                                 [--history docs/BENCH_HISTORY.md]
                                 [--tolerance 0.08] [--json OUT.json]
                                 [--update-fps-baseline BASELINE.json]

The single go/no-go artifact for CI and chip runs:

1. **SLO verdict gate** — reads the run's `SLO_VERDICT.json`
   (written by driver.train's SLO engine, scalable_agent_tpu/slo.py)
   and renders the per-objective table (state, value, target, margin,
   burns, triggered captures). A failing verdict exits nonzero naming
   the violated objectives.

2. **Bench regression gate** (`--bench`) — diffs the bench headline
   (`BENCH_OUT.json`'s `value`, the synthetic env-frames/s number)
   against the baseline derived from docs/BENCH_HISTORY.md's recorded
   rounds (the max of the per-round headline column). A drop beyond
   `--tolerance` (default 8% — 2x the documented ±4% capture noise
   band, docs/BENCH_HISTORY.md) exits nonzero. SMOKE-unit bench
   artifacts skip the gate with a note (CPU smoke numbers are
   mechanics checks, not perf records).

3. **Baseline maintenance** (`--update-fps-baseline`) — records the
   run's measured env-frames/s into the per-host baseline file the
   `fps_floor` objective judges future runs against (slo.py
   update_baseline; only do this from a run you would accept as the
   floor).

Exit codes: 0 all gates pass, 1 any gate failed, 2 missing artifacts.
"""

import argparse
import json
import math
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _fmt(v, digits=4):
  if v is None:
    return '-'
  try:
    f = float(v)
  except (TypeError, ValueError):
    return str(v)
  if math.isnan(f):
    return '-'
  return f'{f:.{digits}g}'


def load_history_baseline(history_path):
  """The bench-headline baseline from docs/BENCH_HISTORY.md: the max
  of the per-round synthetic headline column (`| rN | 313,838 fps
  ...`). Returns (baseline_fps or None, rows_parsed)."""
  try:
    with open(history_path) as f:
      text = f.read()
  except OSError:
    return None, 0
  rows = re.findall(r'^\|\s*r\d+\s*\|\s*([\d,]+)\s*fps', text,
                    re.MULTILINE)
  values = [float(r.replace(',', '')) for r in rows]
  return (max(values) if values else None), len(values)


def verdict_rows(verdict):
  rows = []
  for name, e in sorted(verdict.get('objectives', {}).items()):
    rows.append({
        'objective': name,
        'severity': e.get('severity'),
        'state': e.get('state'),
        'value': e.get('value'),
        'target': e.get('target'),
        'margin': e.get('margin'),
        'burns': e.get('burns', 0),
        'metric': e.get('metric'),
    })
  return rows


def render_verdict(verdict):
  out = []
  w = out.append
  ok = verdict.get('pass')
  w('== SLO verdict: %s ==' % ('PASS' if ok else 'FAIL'))
  w(f"{'objective':>28} {'sev':>7} {'state':>12} {'value':>12} "
    f"{'target':>12} {'margin':>12} {'burns':>6}")
  for row in verdict_rows(verdict):
    w(f"{row['objective']:>28} {row['severity']:>7} "
      f"{row['state']:>12} {_fmt(row['value']):>12} "
      f"{_fmt(row['target']):>12} {_fmt(row['margin']):>12} "
      f"{row['burns']:>6}")
  captures = verdict.get('captures') or {}
  if captures:
    w('-- triggered captures --')
    for name, cap in sorted(captures.items()):
      w(f'  {name}:')
      for kind in ('flight', 'trace_slice', 'profile'):
        w(f'    {kind}: {cap.get(kind) or "-"}')
  violations = verdict.get('violations') or []
  if violations:
    w('violated objectives: ' + ', '.join(violations))
  return '\n'.join(out)


def bench_gate(bench_path, history_path, tolerance):
  """(gate dict, failed bool). SMOKE artifacts and missing baselines
  report 'skipped' and never fail — the gate only judges numbers that
  are actually comparable."""
  gate = {'bench': bench_path, 'history': history_path,
          'tolerance': tolerance, 'status': 'skipped', 'reason': None}
  try:
    with open(bench_path) as f:
      bench = json.load(f)
  except (OSError, ValueError) as e:
    gate['reason'] = f'unreadable bench artifact: {e}'
    return gate, False
  unit = str(bench.get('unit', ''))
  value = bench.get('value')
  gate['value'] = value
  gate['unit'] = unit
  if 'SMOKE' in unit:
    gate['reason'] = ('SMOKE bench artifact: mechanics check, not a '
                      'perf record — gate skipped')
    return gate, False
  baseline, rows = load_history_baseline(history_path)
  gate['baseline'] = baseline
  gate['history_rows'] = rows
  if baseline is None:
    gate['reason'] = 'no parseable headline rows in the history'
    return gate, False
  if value is None:
    gate['reason'] = 'bench artifact carries no headline value'
    return gate, False
  floor = baseline * (1.0 - tolerance)
  gate['floor'] = floor
  gate['ratio'] = float(value) / baseline
  if float(value) < floor:
    gate['status'] = 'fail'
    gate['reason'] = (
        f'headline {value:,.0f} fps is below the regression floor '
        f'{floor:,.0f} ({(1 - tolerance) * 100:.0f}% of the recorded '
        f'best {baseline:,.0f}, docs/BENCH_HISTORY.md)')
    return gate, True
  gate['status'] = 'pass'
  gate['reason'] = (f'headline {value:,.0f} fps >= floor '
                    f'{floor:,.0f}')
  return gate, False


def main(argv=None):
  parser = argparse.ArgumentParser(
      description='SLO verdict report + bench regression gate')
  parser.add_argument('logdir',
                      help='run directory (has SLO_VERDICT.json)')
  parser.add_argument('--bench', default=None,
                      help='BENCH_OUT.json to gate against the '
                           'history baseline')
  parser.add_argument('--history',
                      default=os.path.join(REPO, 'docs',
                                           'BENCH_HISTORY.md'),
                      help='baseline source (docs/BENCH_HISTORY.md)')
  parser.add_argument('--tolerance', type=float, default=0.08,
                      help='allowed headline drop vs the history '
                           'baseline (default 0.08 = 2x the '
                           'documented capture-noise band)')
  parser.add_argument('--json', default=None,
                      help='also write the combined report here')
  parser.add_argument('--update-fps-baseline', default=None,
                      help='record this run\'s measured env frames/s '
                           'into the per-host baseline file the '
                           'fps_floor objective reads')
  args = parser.parse_args(argv)

  from scalable_agent_tpu import slo as slo_lib

  verdict = slo_lib.read_verdict(args.logdir)
  if verdict is None:
    print(f'no SLO_VERDICT.json under {args.logdir!r} — was the run '
          'started with --slo_engine=false?', file=sys.stderr)
    return 2
  print(render_verdict(verdict))
  failed = not verdict.get('pass', False)

  report = {'logdir': args.logdir, 'slo_pass': verdict.get('pass'),
            'violations': verdict.get('violations') or [],
            'objectives': verdict_rows(verdict)}

  if args.bench:
    gate, bench_failed = bench_gate(args.bench, args.history,
                                    args.tolerance)
    report['bench_gate'] = gate
    print(f"\n== bench regression gate: {gate['status']} ==")
    print(f"   {gate['reason']}")
    failed = failed or bench_failed

  if args.update_fps_baseline:
    fps = _measured_fps(args.logdir)
    if fps is None:
      print('\nno env_frames_per_sec summaries to record as a '
            'baseline', file=sys.stderr)
    else:
      path = slo_lib.update_baseline(args.update_fps_baseline,
                                     {'fps': fps})
      report['fps_baseline'] = {'fps': fps, 'path': path}
      print(f'\nrecorded fps baseline {fps:,.1f} for this host into '
            f'{path}')

  if args.json:
    with open(args.json, 'w') as f:
      json.dump(report, f, indent=2, default=str)
    print(f'\nreport JSON: {args.json}')
  return 1 if failed else 0


def _measured_fps(logdir):
  """The run's steady-state env frames/s: the median of the second
  half of its env_frames_per_sec summary samples (skips warmup)."""
  path = os.path.join(logdir, 'summaries.jsonl')
  values = []
  try:
    with open(path) as f:
      for line in f:
        line = line.strip()
        if not line:
          continue
        try:
          e = json.loads(line)
        except ValueError:
          continue
        if e.get('tag') == 'env_frames_per_sec':
          values.append(float(e['value']))
  except OSError:
    return None
  if not values:
    return None
  tail = sorted(values[len(values) // 2:])
  return tail[len(tail) // 2]


if __name__ == '__main__':
  sys.exit(main())
