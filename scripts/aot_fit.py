"""CI smoke for the compiled v5e-16 HBM fit check (parallel/fit.py).

The real fit gate runs inside `__graft_entry__.dryrun_multichip` (the
MULTICHIP_rN artifact records the flagship B=32/B=16 figures); this
script keeps the AOT path green in CI without the flagship compile
cost:

    SMOKE=1 JAX_PLATFORMS=cpu python scripts/aot_fit.py   # <60 s, CPU
    python scripts/aot_fit.py                             # flagship

SMOKE compiles the same full-feature step (deep torso, PopArt + pixel
control + instruction) at tiny shapes over 8 virtual devices and
asserts the memory analysis is sane; the no-SMOKE path is the
flagship `{'data': 16}` check the dryrun runs.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
  smoke = os.environ.get('SMOKE') == '1'
  n_devices = 8 if smoke else 16
  flags = os.environ.get('XLA_FLAGS', '')
  if '--xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags +
        f' --xla_force_host_platform_device_count={n_devices}').strip()
  os.environ.setdefault('JAX_PLATFORMS', 'cpu')
  import jax
  from scalable_agent_tpu.parallel import fit

  # Prefer the default platform only when it actually has the width;
  # on a single-chip accelerator host (ambient JAX_PLATFORMS=axon —
  # the setdefault above no-ops there) fall back to the virtual CPU
  # platform the device-count flag provisioned, like
  # __graft_entry__._provision_devices does.
  devices = jax.devices()
  if len(devices) < n_devices:
    devices = jax.devices('cpu')
  if len(devices) < n_devices:
    raise RuntimeError(
        f'aot_fit needs {n_devices} devices but found {len(devices)}; '
        'JAX was initialized before the device-count flag could take '
        'effect — set XLA_FLAGS=--xla_force_host_platform_device_'
        f'count={n_devices} in the environment.')
  devices = devices[:n_devices]
  if smoke:
    results = [fit.aot_memory_fit(devices=devices, batch_size=8,
                                  unroll_length=4, height=24, width=32,
                                  num_tasks=3)]
  else:
    results = [fit.aot_memory_fit(devices=devices, batch_size=b)
               for b in (32, 16)]
  for result in results:
    print(fit.format_fit(result), flush=True)
    assert result['live_bytes'] > 0, result
    assert result['mesh'] == {'data': n_devices}, result
    if smoke:
      # Tiny shapes must fit by an enormous margin — a failure here
      # is an analysis-plumbing bug, not a capacity finding.
      assert result['fits'], result
    else:
      assert result['fits'], (
          'flagship full-feature shapes no longer fit the v5e HBM '
          f'budget: {result}')
  print('aot_fit OK')


if __name__ == '__main__':
  main()
