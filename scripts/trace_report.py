"""Reconstruct per-unroll pipeline latency + policy-lag attribution
from a run's traces.jsonl (the round-13 telemetry plane).

    python scripts/trace_report.py LOGDIR [--json OUT.json]

Reads every `traces*.jsonl` under LOGDIR (multi-host: one stream per
process) plus `incidents.jsonl` when present, and reports:

- **per-hop latency**: p50/p99/max milliseconds for each adjacent hop
  transition actually observed (done→send→wire→commit→staged→serve→
  step; spans legitimately omit hops — a local-fleet unroll never
  crosses the wire), plus the end-to-end span;
- **policy lag**: the per-batch publish-version-delta distribution —
  the number V-trace actually corrects for (IMPALA arXiv 1802.01561)
  — as a histogram plus per-batch mean/max percentiles;
- **param propagation**: publish→installed-at-actor latency per
  version, joined from the 'publish' and 'install' records;
- **timeline**: batches per second-bucket with incident markers
  (rollbacks, partitions, reattaches) interleaved, so a chaos fault's
  window is visible as the gap/lag excursion it caused.

Missing values render '-' (the NaN-on-empty contract of the round-13
observability satellites). Cross-host hop deltas carry NTP skew —
within a host they are exact (docs/OBSERVABILITY.md).
"""

import argparse
import collections
import glob
import json
import math
import os
import sys

# Mirrors telemetry.HOP_ORDER (kept literal here so the report runs
# on operator machines without the package's numpy dependency chain;
# tests pin the two in sync).
HOP_ORDER = ('done', 'send', 'wire', 'commit', 'staged', 'serve',
             'step')


def span_hop_deltas(span):
  """One span's `[hop, wall_time]` list → (adjacent-hop deltas, e2e):
  `([((hop_from, hop_to), ms_or_None), ...], e2e_ms_or_None)`. Keeps
  the FIRST stamp per hop name in pipeline order — a resend re-stamps
  send/wire, and the first traversal is the latency story. A NEGATIVE
  raw delta (cross-host wall clocks skew past each other — NTP,
  docs/OBSERVABILITY.md) yields ms=None: the report renders '-'
  instead of laundering skew into a fake 0-ms latency, and consumers
  (summarize, to_tensorboard) skip None rows. Malformed stamp entries
  (wrong arity, non-numeric time) are ignored, never a crash — this
  runs over streams written by crashed/buggy peers. The ONE
  implementation behind summarize() and to_tensorboard's trace
  conversion, so the two views can never disagree on a hop."""
  seen = {}
  for entry in span.get('h') or []:
    try:
      name, t = entry
      t = float(t)
    except (TypeError, ValueError):
      continue
    seen.setdefault(name, t)
  ordered = [(n, seen[n]) for n in HOP_ORDER if n in seen]
  deltas = [((n0, n1), (t1 - t0) * 1e3 if t1 >= t0 else None)
            for (n0, t0), (n1, t1) in zip(ordered, ordered[1:])]
  e2e = None
  if len(ordered) >= 2 and ordered[-1][1] >= ordered[0][1]:
    e2e = (ordered[-1][1] - ordered[0][1]) * 1e3
  return deltas, e2e


def _fmt(v, digits=2):
  """Numbers → fixed-point; None/NaN → '-' (never crash a report)."""
  if v is None:
    return '-'
  try:
    f = float(v)
  except (TypeError, ValueError):
    return str(v)
  if math.isnan(f):
    return '-'
  return f'{f:.{digits}f}'


def _percentiles(values, *qs):
  if not values:
    return tuple(float('nan') for _ in qs)
  snap = sorted(values)
  last = len(snap) - 1
  return tuple(snap[min(last, int(round(q * last)))] for q in qs)


def load_traces(logdir):
  """Every record from every traces*.jsonl under `logdir`, sorted by
  record wall time. Truncated final lines (crashed writer) skip."""
  records = []
  for path in sorted(glob.glob(os.path.join(logdir, 'traces*.jsonl'))):
    with open(path) as f:
      for line in f:
        line = line.strip()
        if not line:
          continue
        try:
          records.append(json.loads(line))
        except json.JSONDecodeError:
          continue
  records.sort(key=lambda r: r.get('t', 0.0))
  return records


def load_incidents(logdir):
  events = []
  for path in sorted(glob.glob(os.path.join(logdir,
                                            'incidents*.jsonl'))):
    with open(path) as f:
      for line in f:
        line = line.strip()
        if not line:
          continue
        try:
          events.append(json.loads(line))
        except json.JSONDecodeError:
          continue
  events.sort(key=lambda e: e.get('wall_time', 0.0))
  return events


def summarize(records, incidents=()):
  """The report's data model: hop-transition latencies, end-to-end
  spans, the policy-lag histogram, publish→install propagation, and
  the per-second batch timeline. Pure function of the parsed records
  (scripts/soak.py and the tests consume this; main() renders it)."""
  hop_deltas = collections.defaultdict(list)   # (from, to) -> [ms]
  e2e_ms = []
  lag_hist = collections.Counter()
  batch_lag_mean = []
  batch_lag_max = []
  batches = 0
  unrolls = 0
  actors = set()
  publishes = {}                              # version -> wall time
  install_lat = []                            # publish -> install secs
  timeline = collections.Counter()            # int(second) -> batches
  steps = []
  for rec in records:
    kind = rec.get('k')
    if kind == 'publish':
      # Install notices carry the INGEST LANE's version sequence
      # ('rv' on publish records that also went to the remote fleet)
      # — the step-stamped label 'v' is a different clock and joins
      # nothing at production publish cadences.
      publishes[rec['rv'] if 'rv' in rec else rec.get('v')] = \
          rec.get('t')
    elif kind == 'install':
      t_pub = publishes.get(rec.get('v'))
      if t_pub is not None and rec.get('t') is not None:
        install_lat.append(max(rec['t'] - t_pub, 0.0))
    elif kind == 'batch':
      batches += 1
      steps.append(rec.get('step'))
      if rec.get('t') is not None:
        timeline[int(rec['t'])] += 1
      lags = rec.get('lag') or []
      for lag in lags:
        lag_hist[int(lag)] += 1
      if lags:
        batch_lag_mean.append(sum(lags) / len(lags))
        batch_lag_max.append(max(lags))
      for span in rec.get('spans') or []:
        unrolls += 1
        actors.add(span.get('a'))
        deltas, e2e = span_hop_deltas(span)
        for pair, ms in deltas:
          if ms is not None:  # clock-skewed hops render '-', not 0
            hop_deltas[pair].append(ms)
        if e2e is not None:
          e2e_ms.append(e2e)
  hop_rows = []
  for (n0, n1), values in sorted(
      hop_deltas.items(),
      key=lambda kv: (HOP_ORDER.index(kv[0][0]),
                      HOP_ORDER.index(kv[0][1]))):
    p50, p99 = _percentiles(values, 0.5, 0.99)
    hop_rows.append({'hop': f'{n0}->{n1}', 'count': len(values),
                     'p50_ms': p50, 'p99_ms': p99,
                     'max_ms': max(values)})
  e2e_p50, e2e_p99 = _percentiles(e2e_ms, 0.5, 0.99)
  lag_p50, lag_p99 = _percentiles(
      [lag for lag, n in lag_hist.items() for _ in range(n)],
      0.5, 0.99)
  inst_p50, inst_p99 = _percentiles(install_lat, 0.5, 0.99)
  incident_rows = [
      {'wall_time': e.get('wall_time'), 'kind': e.get('kind'),
       'step': e.get('step')}
      for e in incidents]
  return {
      'batches': batches,
      'unrolls': unrolls,
      'actors': len(actors),
      'steps': [s for s in (min(steps or [None]),
                            max(steps or [None])) if s is not None],
      'hops': hop_rows,
      'e2e_ms': {'count': len(e2e_ms), 'p50': e2e_p50,
                 'p99': e2e_p99,
                 'max': max(e2e_ms) if e2e_ms else float('nan')},
      'policy_lag': {
          'histogram': dict(sorted(lag_hist.items())),
          'p50': lag_p50, 'p99': lag_p99,
          'batch_mean_p99': _percentiles(batch_lag_mean, 0.99)[0],
          'batch_max_p99': _percentiles(batch_lag_max, 0.99)[0],
      },
      'publish_to_install_secs': {'count': len(install_lat),
                                  'p50': inst_p50, 'p99': inst_p99},
      'timeline': {str(k): v for k, v in sorted(timeline.items())},
      'incidents': incident_rows,
  }


def render(summary):
  out = []
  w = out.append
  lo_hi = summary['steps']
  w('== trace report ==')
  w(f"batches {summary['batches']}  unrolls {summary['unrolls']}  "
    f"actors {summary['actors']}  steps "
    f"{lo_hi[0] if lo_hi else '-'}..{lo_hi[-1] if lo_hi else '-'}")
  w('')
  w('-- per-hop latency (ms) --')
  w(f"{'hop':>14} {'count':>8} {'p50':>10} {'p99':>10} {'max':>10}")
  for row in summary['hops']:
    w(f"{row['hop']:>14} {row['count']:>8} {_fmt(row['p50_ms']):>10} "
      f"{_fmt(row['p99_ms']):>10} {_fmt(row['max_ms']):>10}")
  e2e = summary['e2e_ms']
  w(f"{'end-to-end':>14} {e2e['count']:>8} {_fmt(e2e['p50']):>10} "
    f"{_fmt(e2e['p99']):>10} {_fmt(e2e['max']):>10}")
  w('')
  w('-- policy lag (publish-version delta at train time) --')
  lag = summary['policy_lag']
  if lag['histogram']:
    total = sum(lag['histogram'].values())
    for value, count in lag['histogram'].items():
      bar = '#' * max(1, int(40 * count / total))
      w(f'  lag {value:>4}: {count:>8}  {bar}')
  else:
    w('  (no behaviour-version data: old-protocol peers, or tracing '
      'off)')
  w(f"  p50 {_fmt(lag['p50'])}  p99 {_fmt(lag['p99'])}  "
    f"batch-mean p99 {_fmt(lag['batch_mean_p99'])}  "
    f"batch-max p99 {_fmt(lag['batch_max_p99'])}")
  w('')
  pi = summary['publish_to_install_secs']
  w('-- param propagation (publish -> installed-at-actor) --')
  w(f"  joins {pi['count']}  p50 {_fmt(pi['p50'], 3)}s  "
    f"p99 {_fmt(pi['p99'], 3)}s")
  w('')
  w('-- timeline (batches/sec, * = incident) --')
  incident_secs = collections.defaultdict(list)
  for e in summary['incidents']:
    if e.get('wall_time') is not None:
      incident_secs[int(e['wall_time'])].append(e.get('kind'))
  seconds = sorted(set(int(s) for s in summary['timeline']) |
                   set(incident_secs))
  t0 = seconds[0] if seconds else 0
  for sec in seconds:
    n = summary['timeline'].get(str(sec), 0)
    marks = ','.join(incident_secs.get(sec, []))
    bar = '#' * min(n, 60)
    w(f'  +{sec - t0:>4}s {n:>5} {bar}{"  *" + marks if marks else ""}')
  return '\n'.join(out)


def main(argv=None):
  parser = argparse.ArgumentParser(
      description='per-unroll trace + policy-lag report from '
                  'traces.jsonl')
  parser.add_argument('logdir', help='run directory (has traces.jsonl)')
  parser.add_argument('--json', default=None,
                      help='also write the summary as JSON here')
  args = parser.parse_args(argv)
  records = load_traces(args.logdir)
  if not records:
    print(f'no traces*.jsonl records under {args.logdir!r} — was the '
          'run started with --telemetry_trace=false?', file=sys.stderr)
    return 1
  summary = summarize(records, load_incidents(args.logdir))
  print(render(summary))
  if args.json:
    with open(args.json, 'w') as f:
      json.dump(summary, f, indent=2, default=str)
    print(f'\nsummary JSON: {args.json}')
  return 0


if __name__ == '__main__':
  sys.exit(main())
