"""CI smoke for the persistent compilation cache (round 23).

Two-process protocol, driven by scripts/ci.sh:

  CI_CACHE_PHASE=fill  — arm the cache at CI_CACHE_DIR through the
      production seam (distributed.maybe_initialize), compile a small
      program, and assert the cache dir gained entries.
  CI_CACHE_PHASE=hit   — a FRESH interpreter arms the same dir,
      compiles the identical program, and proves the executable came
      from the cache via jax's monitoring events (entry-count
      equality proves nothing: a miss rewrites the same key).

This is the cross-process claim the unit tests cannot make: the
second *process* skips XLA compilation entirely — the mechanism that
turns a population spin-up from N cold compiles into 1 cold + N-1
reads, and a restart of the same config into a warm start.
"""

import os
import sys

sys.path.insert(0, os.getcwd())


def main():
  cache_dir = os.environ['CI_CACHE_DIR']
  phase = os.environ['CI_CACHE_PHASE']

  import jax
  # Cache tiny programs too — the smoke's matmul compiles in well
  # under the 1 s production write floor.
  jax.config.update('jax_persistent_cache_min_compile_time_secs', 0.0)
  import jax.numpy as jnp

  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.parallel import distributed

  cfg = Config(compile_cache_dir=cache_dir)
  distributed.maybe_initialize(cfg)
  assert jax.config.jax_compilation_cache_dir == cache_dir, (
      jax.config.jax_compilation_cache_dir)

  events = []

  def listener(event, **kwargs):
    events.append(event)

  from jax._src import monitoring
  monitoring.register_event_listener(listener)

  @jax.jit
  def program(x):
    return jnp.tanh(x @ x.T).sum()

  out = program(jnp.arange(64.0, dtype=jnp.float32).reshape(8, 8))
  jax.block_until_ready(out)

  entries = os.listdir(cache_dir) if os.path.isdir(cache_dir) else []
  if phase == 'fill':
    assert entries, 'fill phase wrote no cache entries'
    print('compile-cache smoke (fill): %d entr%s under %s'
          % (len(entries), 'y' if len(entries) == 1 else 'ies',
             cache_dir))
    return
  assert phase == 'hit', phase
  hits = [e for e in events
          if 'compilation_cache' in e and 'hit' in e]
  assert hits, ('hit phase compiled from scratch — no cache-hit '
                'monitoring event (saw: %r)' % sorted(set(events)))
  print('compile-cache smoke (hit): fresh process reused the cached '
        'executable (%s)' % hits[0])


if __name__ == '__main__':
  main()
