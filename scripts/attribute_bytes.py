"""Per-component byte attribution of the flagship train step.

docs/PERF.md's roofline says the step is HBM-bound (23.6 GB accessed);
this script breaks that aggregate down by op class via XLA
`cost_analysis()` on independently jitted sub-functions (VERDICT r2
W5: "memory-bound, accept it" is only a conclusion once we know WHICH
tensors account for the bytes). Run on the real chip:

    python scripts/attribute_bytes.py            # flagship shapes
    SMOKE=1 python scripts/attribute_bytes.py    # mechanics check, CPU

Sub-functions overlap (the full step contains all of them); the point
is attribution, not a partition: fwd-vs-bwd splits, the conv torso's
share, and the sizes of the V-trace/optimizer/host-visible pieces.

Round 6 adds the FEATURE itemization (VERDICT r5 weak #3 — the
full-feature 20% had no named owners): full-step cost rows for the
plain base, each feature alone (+instruction, +popart, +pixel
control), and the full stack, plus micro rows for the two
pixel-control fast-path levers (integer-domain pseudo-rewards vs the
f32 reference; the d2s Q-head vs the stride-2 deconv). Only compiles
are involved — the feature rows work at flagship shapes on any host
(the bytes are the compiled program's, so CPU-backend figures are the
CPU emitter's fusion choices; chip rows come from running on the
chip, same command).
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def cost(fn, *args):
  import jax
  compiled = jax.jit(fn).lower(*args).compile()
  analysis = compiled.cost_analysis()
  if isinstance(analysis, list):  # some jax versions return [dict]
    analysis = analysis[0]
  return (analysis.get('bytes accessed', float('nan')),
          analysis.get('flops', float('nan')))


def main():
  smoke = os.environ.get('SMOKE') == '1'
  if smoke:
    import jax
    jax.config.update('jax_platforms', 'cpu')
  import jax
  import jax.numpy as jnp
  from scalable_agent_tpu import learner as learner_lib
  from scalable_agent_tpu import vtrace
  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.models import ImpalaAgent, init_params
  from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
  from scalable_agent_tpu.models.torsos import DeepResNetTorso
  from scalable_agent_tpu.testing import make_example_batch

  t, b = (100, 32) if not smoke else (4, 2)
  h, w = (72, 96) if not smoke else (24, 32)
  num_actions = 9
  cfg = Config(batch_size=b, unroll_length=t, num_action_repeats=4,
               torso='deep', compute_dtype='bfloat16',
               total_environment_frames=int(1e9))
  agent = ImpalaAgent(num_actions=num_actions, torso='deep',
                      use_instruction=True, scan_unroll=cfg.scan_unroll,
                      dtype=jnp.bfloat16)
  obs = {'frame': (h, w, 3), 'instr_len': MAX_INSTRUCTION_LEN}
  params = init_params(agent, jax.random.PRNGKey(0), obs)
  batch = make_example_batch(t + 1, b, h, w, num_actions,
                             MAX_INSTRUCTION_LEN, done_prob=0.01)
  state = learner_lib.make_train_state(params, cfg)

  rows = []

  # Full step (the aggregate being attributed).
  step = learner_lib.make_train_step_fn(agent, cfg)
  rows.append(('full train step (fwd+bwd+V-trace+RMSProp)',
               *cost(step, state, batch)))

  # --- Feature itemization (round 6): full-step cost, one feature at
  # a time on the plain deep base. The popart/pc/instruction split of
  # the full-feature 20% in BYTES. ---
  import dataclasses
  from scalable_agent_tpu import driver as driver_lib

  def feature_row(label, feature_cfg, use_instruction, num_tasks=1):
    fcfg = dataclasses.replace(
        feature_cfg,
        use_instruction=use_instruction)
    fagent = driver_lib.build_agent(fcfg, num_actions,
                                    num_tasks=num_tasks)
    fparams = init_params(fagent, jax.random.PRNGKey(0), obs)
    fstate = learner_lib.make_train_state(
        fparams, fcfg,
        num_popart_tasks=(num_tasks if fcfg.use_popart else 0))
    fstep = learner_lib.make_train_step_fn(fagent, fcfg)
    rows.append((label, *cost(fstep, fstate, batch)))

  feature_row('step: plain base (deep, no features)', cfg, False)
  feature_row('step: +instruction only', cfg, True)
  feature_row('step: +popart only',
              dataclasses.replace(cfg, use_popart=True), False,
              num_tasks=30)
  feature_row('step: +pixel control only',
              dataclasses.replace(cfg, pixel_control_cost=0.01), False)
  full_cfg = dataclasses.replace(cfg, use_popart=True,
                                 pixel_control_cost=0.01)
  feature_row('step: full feature (popart+pc+instruction)', full_cfg,
              True, num_tasks=30)
  # The pc fast-path levers at the full-feature point (the full-
  # feature row above IS the r5 reference forms — the config
  # defaults; this row is the opt-in fast paths for the delta).
  feature_row('step: full feature, r6 fast paths (int rewards, d2s)',
              dataclasses.replace(
                  full_cfg, pixel_control_integer_rewards=True,
                  pixel_control_head_impl='d2s'), True,
              num_tasks=30)
  feature_row('step: full feature, bf16 Q lever on',
              dataclasses.replace(full_cfg, pixel_control_q_f32=False),
              True, num_tasks=30)

  # --- Pixel-control micro rows: the two levers in isolation. ---
  from scalable_agent_tpu import unreal
  frames_u8 = batch.env_outputs.observation[0]

  rows.append(('pixel_control_rewards f32 reference [T+1,B,H,W,C]',
               *cost(lambda f: unreal.pixel_control_rewards(
                   f, cfg.pixel_control_cell_size, integer_path=False),
                   frames_u8)))
  rows.append(('pixel_control_rewards integer path',
               *cost(lambda f: unreal.pixel_control_rewards(
                   f, cfg.pixel_control_cell_size, integer_path=True),
                   frames_u8)))

  cell = cfg.pixel_control_cell_size
  hc, wc = h // cell, w // cell
  merged = (t + 1) * b
  core_feats = jnp.zeros((merged, 256), jnp.bfloat16)
  for impl in ('deconv', 'd2s'):
    head = unreal.PixelControlHead(num_actions, (hc, wc),
                                   dtype=jnp.bfloat16, head_impl=impl)
    head_params = head.init(jax.random.PRNGKey(0),
                            np.zeros((2, 256), np.float32))

    def head_loss(p, x, head=head):
      return jnp.sum(head.apply(p, x))

    rows.append((f'pc head fwd+bwd [{merged} merged], impl={impl}',
                 *cost(jax.value_and_grad(head_loss), head_params,
                       core_feats)))

  # Forward only (loss_fn without grad): unroll + V-trace + losses.
  def fwd(params, batch):
    return learner_lib.loss_fn(params, agent, batch, cfg)[0]

  rows.append(('forward loss (unroll+V-trace+losses)',
               *cost(fwd, params, batch)))

  # Forward + backward (no optimizer).
  rows.append(('forward+backward (value_and_grad, no opt)',
               *cost(jax.value_and_grad(fwd), params, batch)))

  # Optimizer update alone (RMSProp moments + apply): param-sized.
  import optax
  optimizer = learner_lib.make_optimizer(cfg)
  grads = jax.tree_util.tree_map(jnp.zeros_like, params)

  def opt_update(grads, opt_state, params):
    updates, new_opt = optimizer.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), new_opt

  rows.append(('RMSProp update (moments + apply)',
               *cost(opt_update, grads, state.opt_state, params)))

  # V-trace alone at [T, B, A].
  rng = np.random.RandomState(0)
  logits = jnp.asarray(rng.randn(t, b, num_actions), jnp.float32)
  actions = jnp.asarray(rng.randint(0, num_actions, (t, b)), jnp.int32)
  scalars = jnp.asarray(rng.rand(t, b), jnp.float32)

  def vtrace_only(bl, tl, a, d, r, v, bv):
    return vtrace.from_logits(
        behaviour_policy_logits=bl, target_policy_logits=tl, actions=a,
        discounts=d, rewards=r, values=v, bootstrap_value=bv)

  rows.append(('V-trace standalone [T,B]',
               *cost(vtrace_only, logits, logits, actions,
                     scalars * 0.99, scalars, scalars, scalars[0])))

  # Conv torso alone on the merged [T+1 * B] frame batch (the MXU-heavy
  # slice; frames normalized exactly as the agent does).
  torso = DeepResNetTorso(dtype=jnp.bfloat16)
  torso_params = {'params': params['params']['DeepResNetTorso_0']}
  frames = jnp.asarray(
      np.asarray(batch.env_outputs.observation[0]).reshape(
          (t + 1) * b, h, w, 3))

  def torso_fwd(p, frames):
    x = frames.astype(jnp.bfloat16) / 255.0
    return torso.apply(p, x)

  rows.append(('conv torso forward [T+1*B merged]',
               *cost(torso_fwd, torso_params, frames)))

  def torso_loss(p, frames):
    return jnp.sum(torso_fwd(p, frames).astype(jnp.float32))

  rows.append(('conv torso forward+backward',
               *cost(jax.value_and_grad(torso_loss), torso_params,
                     frames)))

  print('| component | bytes accessed | GB | TFLOP |')
  print('|---|---|---|---|')
  for name, bytes_, flops in rows:
    print(f'| {name} | {bytes_:.3e} | {bytes_ / 1e9:.2f} | '
          f'{flops / 1e12:.3f} |')


if __name__ == '__main__':
  main()
