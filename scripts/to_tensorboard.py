"""Convert a run's JSONL summaries into TensorBoard event files.

The reference writes `tf.summary` event files an operator watches in
TensorBoard (experiment.py ≈L570 MonitoredTrainingSession
save_summaries_secs + the manual per-episode Summary protos ≈L590).
This build logs JSONL (observability.py — grep/jq-able, no TF
dependency on the hot path); this offline converter gives reference
operators their TensorBoard view back:

    python scripts/to_tensorboard.py LOGDIR [--out OUT]
    tensorboard --logdir OUT   # default: LOGDIR/tb

Each summary stream becomes a TB run: `summaries.jsonl` -> train,
`summaries_p3.jsonl` -> train_p3 (multi-host: one stream per process),
`eval_summaries.jsonl` -> eval. Scalars convert exactly (tag, value,
step, wall time). Histograms (kind=histogram: integer `counts`,
optional bin `edges`) convert via add_histogram_raw; min/max/sum/
sum_sq are reconstructed from bin centers — fine for the shape-of-
distribution reading these are for.

Import-guarded: requires the `tensorboard` package (ships with torch
in this image); the training path never imports it.
"""

import argparse
import glob
import json
import os
import shutil
import sys


def _run_name(filename):
  base = os.path.basename(filename)
  if base == 'summaries.jsonl':
    return 'train'
  if base == 'eval_summaries.jsonl':
    return 'eval'
  for prefix, run in (('summaries_', 'train_'),
                      ('eval_summaries_', 'eval_')):
    if base.startswith(prefix):
      return run + base[len(prefix):].removesuffix('.jsonl')
  return base.removesuffix('.jsonl')


def _histogram_raw_args(event):
  """JSONL histogram -> add_histogram_raw kwargs. Without edges the
  counts are per-integer bins (e.g. action ids 0..n-1)."""
  counts = event['counts']
  edges = event.get('edges')
  if edges is None:
    edges = [i - 0.5 for i in range(len(counts) + 1)]
  centers = [(edges[i] + edges[i + 1]) / 2 for i in range(len(counts))]
  num = float(sum(counts))
  total = sum(c * x for c, x in zip(counts, centers))
  total_sq = sum(c * x * x for c, x in zip(counts, centers))
  nonzero = [i for i, c in enumerate(counts) if c]
  lo = edges[nonzero[0]] if nonzero else 0.0
  hi = edges[nonzero[-1] + 1] if nonzero else 0.0
  return dict(min=lo, max=hi, num=num, sum=total, sum_squares=total_sq,
              bucket_limits=list(edges[1:]), bucket_counts=list(counts))


def _trace_report_module():
  """scripts/trace_report — the one owner of the hop-order/delta
  algorithm (its `span_hop_deltas`). Script-run resolution: when this
  file runs as `python scripts/to_tensorboard.py`, sys.path[0] is
  scripts/ itself, so `trace_report` imports flat; as a package
  member (`from scripts import to_tensorboard`, the tests) the
  relative-package spelling resolves."""
  try:
    from scripts import trace_report
  except ImportError:
    import trace_report
  return trace_report


def _trace_events(event):
  """One traces.jsonl 'batch' record → [(tag, value, step)] scalars:
  the per-batch policy-lag mean/max (the V-trace staleness curve an
  operator actually watches) and the mean per-hop latency across the
  batch's spans (round 13 — the trace stream's TensorBoard view;
  hop deltas computed by trace_report.span_hop_deltas so the two
  views can never disagree)."""
  if event.get('k') != 'batch':
    return []
  span_hop_deltas = _trace_report_module().span_hop_deltas
  step = int(event.get('step', 0))
  rows = []
  lags = event.get('lag') or []
  if lags:
    rows.append(('trace/policy_lag_mean', sum(lags) / len(lags), step))
    rows.append(('trace/policy_lag_max', float(max(lags)), step))
  deltas = {}
  for span in event.get('spans') or []:
    span_deltas, e2e = span_hop_deltas(span)
    for (n0, n1), ms in span_deltas:
      if ms is None:  # clock-skewed cross-host hop: no fake 0 point
        continue
      deltas.setdefault(f'trace/hop_{n0}_{n1}_ms', []).append(ms)
    if e2e is not None:
      deltas.setdefault('trace/e2e_ms', []).append(e2e)
  for tag, values in deltas.items():
    rows.append((tag, sum(values) / len(values), step))
  return rows


def convert(logdir, out=None):
  """Convert every summary AND trace stream under `logdir`; returns
  {run_name: events_written}. Trace streams (traces.jsonl, round 13)
  become a `trace`/`trace_pN` run of hop-latency and policy-lag
  scalars so TensorBoard operators keep their view of the new
  telemetry plane."""
  try:
    from torch.utils.tensorboard import SummaryWriter
  except ImportError as e:
    raise ImportError(
        'scripts/to_tensorboard.py writes events via '
        'torch.utils.tensorboard (`pip install torch tensorboard`); '
        'the training path itself never requires either') from e

  out = out or os.path.join(logdir, 'tb')
  streams = sorted(glob.glob(os.path.join(logdir, '*summaries*.jsonl')))
  trace_streams = sorted(glob.glob(os.path.join(logdir,
                                                'traces*.jsonl')))
  if not streams and not trace_streams:
    raise FileNotFoundError(
        f'no *summaries*.jsonl or traces*.jsonl under {logdir!r}')
  written = {}
  for path in trace_streams:
    base = os.path.basename(path)
    run = ('trace' if base == 'traces.jsonl'
           else 'trace_' + base[len('traces_'):].removesuffix('.jsonl'))
    run_dir = os.path.join(out, run)
    if os.path.isdir(run_dir):
      shutil.rmtree(run_dir)
    writer = SummaryWriter(run_dir)
    n = 0
    with open(path) as f:
      for line in f:
        line = line.strip()
        if not line:
          continue
        try:
          event = json.loads(line)
        except json.JSONDecodeError:
          continue
        for tag, value, step in _trace_events(event):
          writer.add_scalar(tag, value, global_step=step,
                            walltime=event.get('t'))
          n += 1
    writer.close()
    written[run] = n
  for path in streams:
    run = _run_name(path)
    run_dir = os.path.join(out, run)
    # Re-converting must replace, not append: a second event file in
    # the same run dir would make TensorBoard merge both conversions
    # and show every point twice.
    if os.path.isdir(run_dir):
      shutil.rmtree(run_dir)
    writer = SummaryWriter(run_dir)
    n = 0
    skipped = 0
    with open(path) as f:
      for line in f:
        line = line.strip()
        if not line:
          continue
        try:
          event = json.loads(line)
        except json.JSONDecodeError:
          # A crashed trainer can leave a truncated final line; the
          # thousands of valid events before it must still convert.
          skipped += 1
          continue
        step = int(event.get('step', 0))
        wall = event.get('wall_time')
        if event.get('kind') == 'histogram':
          writer.add_histogram_raw(
              event['tag'], global_step=step, walltime=wall,
              **_histogram_raw_args(event))
        else:
          writer.add_scalar(event['tag'], float(event['value']),
                            global_step=step, walltime=wall)
        n += 1
    writer.close()
    if skipped:
      print(f'warning: {run}: skipped {skipped} undecodable line(s) '
            f'in {path}', file=sys.stderr)
    written[run] = n
  return written


def main(argv=None):
  parser = argparse.ArgumentParser(
      description='JSONL summaries -> TensorBoard event files')
  parser.add_argument('logdir', help='run directory (has summaries.jsonl)')
  parser.add_argument('--out', default=None,
                      help='TB output dir (default: LOGDIR/tb)')
  args = parser.parse_args(argv)
  written = convert(args.logdir, args.out)
  for run, n in sorted(written.items()):
    print(f'{run}: {n} events')
  return 0


if __name__ == '__main__':
  sys.exit(main())
