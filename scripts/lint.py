#!/usr/bin/env python
"""Invariant analyzer CLI — the repo's static contract lint.

    python scripts/lint.py                 # full checker suite
    python scripts/lint.py --check metric-names --check guarded-by
    python scripts/lint.py --json          # machine-readable findings
    python scripts/lint.py --list          # checker inventory
    python scripts/lint.py --fix-docs      # regenerate generated doc
                                           # inventory blocks, then
                                           # re-lint

Exit status: 0 = clean, 1 = findings, 2 = usage error. The framework
lives in scalable_agent_tpu/analysis/ (stdlib-ast only); the checker
inventory printed by --list is itself contract-linted against
docs/STATIC_ANALYSIS.md (the `checker-inventory` check), so docs and
code cannot drift.
"""

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from scalable_agent_tpu import analysis  # noqa: E402
from scalable_agent_tpu.analysis import CheckContext, contracts  # noqa: E402


def main(argv=None) -> int:
  parser = argparse.ArgumentParser(
      description='AST-based contract lint (docs/STATIC_ANALYSIS.md)')
  parser.add_argument('--check', action='append', default=[],
                      metavar='NAME',
                      help='run only this checker (repeatable)')
  parser.add_argument('--json', action='store_true',
                      help='emit findings as a JSON list')
  parser.add_argument('--list', action='store_true',
                      help='print the checker inventory and exit')
  parser.add_argument('--fix-docs', action='store_true',
                      help='regenerate generated doc inventory blocks '
                           '(summary scalars) before linting')
  parser.add_argument('--root', default=_ROOT, help=argparse.SUPPRESS)
  args = parser.parse_args(argv)

  if args.list:
    for name, description, _ in analysis.all_checkers():
      print(f'{name}: {description}')
    return 0

  if args.fix_docs:
    changed = contracts.fix_summary_scalar_docs(CheckContext(args.root))
    print('docs/OBSERVABILITY.md summary-scalar block '
          + ('REGENERATED' if changed else 'already current'),
          file=sys.stderr)

  try:
    findings = analysis.run_checks(args.root, only=args.check or None)
  except ValueError as e:
    print(f'lint: {e}', file=sys.stderr)
    return 2

  if args.json:
    print(json.dumps([vars(f) for f in findings], indent=2))
  else:
    for f in findings:
      print(f.render())
    n_checks = len(args.check) if args.check else len(
        analysis.all_checkers())
    if findings:
      print(f'lint: {len(findings)} finding(s) across {n_checks} '
            'checker(s)', file=sys.stderr)
    else:
      print(f'lint OK: {n_checks} checker(s), no findings',
            file=sys.stderr)
  return 1 if findings else 0


if __name__ == '__main__':
  sys.exit(main())
