"""Head-to-head RETURN-quality harness: `deep` vs `deep_fast`.

`deep_fast` ships a 34–46% throughput carrot (docs/PERF.md r5) but is
a DIFFERENT FUNCTION (receptive field 3 vs 5 per section, no max
nonlinearity), and its only learning evidence is the trivial bandit —
VERDICT r5 weak #5: "an operator has a 46% carrot and no
return-quality data". Until this harness has been run on real
hardware and the curves recorded, README and `--torso` advertise
deep_fast as *throughput variant, unvalidated returns*.

This script is the one-command way to earn (or revoke) the demotion:
both torsos train head-to-head on `cue_memory` — the CI task built to
require vision + MEMORY (the cue is only visible on the first frame;
see envs/fake.py CueMemoryEnv) — through the PRODUCTION pipeline
(driver.train: batcher → buffer → prefetcher → learner), same seed
and frame budget, and the per-episode return curves land in
TORSO_COMPARE.json.

    python scripts/compare_torsos.py             # real run (chip)
    SMOKE=1 python scripts/compare_torsos.py     # mechanics, CPU <60 s

The artifact records curves and final means; it asserts only
mechanics (episodes finished, curves non-empty) — the accept/reject
call on return parity is a human judgment documented in docs/PERF.md
and README, with this JSON as the evidence.
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _return_curve(logdir, buckets=10):
  """[(step, ep_return)] from summaries.jsonl, bucketed into step
  deciles (mean per bucket) — the curve shape without per-episode
  noise."""
  events = []
  with open(os.path.join(logdir, 'summaries.jsonl')) as f:
    for line in f:
      e = json.loads(line)
      if e.get('tag', '').endswith('/episode_return'):
        events.append((e['step'], e['value']))
  if not events:
    return [], 0.0
  events.sort()
  max_step = max(s for s, _ in events) or 1
  sums = [[0.0, 0] for _ in range(buckets)]
  for step, value in events:
    i = min(step * buckets // (max_step + 1), buckets - 1)
    sums[i][0] += value
    sums[i][1] += 1
  curve = [round(s / n, 3) if n else None for s, n in sums]
  tail = [v for v in curve[-3:] if v is not None]
  return curve, round(sum(tail) / max(len(tail), 1), 3)


def run_one(torso, smoke, seed=11):
  from scalable_agent_tpu import driver
  from scalable_agent_tpu.config import Config

  cfg = Config(
      logdir=tempfile.mkdtemp(prefix=f'torso_cmp_{torso}_'),
      env_backend='cue_memory',
      num_actions=3,
      num_actors=4 if not smoke else 2,
      batch_size=4 if not smoke else 2,
      unroll_length=16 if not smoke else 8,
      num_action_repeats=1,
      height=72 if not smoke else 24,
      width=96 if not smoke else 32,
      torso=torso,
      compute_dtype='bfloat16' if not smoke else 'float32',
      use_py_process=False,     # in-process envs; the driver path
                                # (batcher/buffer/prefetcher) is the
                                # pipeline under test, not the IPC
      use_instruction=False,
      learning_rate=0.003, entropy_cost=0.01, discounting=0.9,
      total_environment_frames=10**8,
      checkpoint_secs=10**6, summary_secs=2 if not smoke else 1,
      seed=seed)
  max_steps = 400 if not smoke else 8
  run = driver.train(cfg, max_steps=max_steps, stall_timeout_secs=180)
  curve, tail_mean = _return_curve(cfg.logdir)
  return {
      'torso': torso,
      'steps': int(run.state.update_steps),
      'frames': int(run.frames),
      'return_curve_deciles': curve,
      'tail_mean_return': tail_mean,
  }


def main():
  smoke = os.environ.get('SMOKE') == '1'
  if smoke:
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
  results = {'task': 'cue_memory',
             'note': ('memory policy 3.0, best memoryless 2.33 — a '
                      'torso that cannot feed the LSTM usable '
                      'features plateaus below 2.6 '
                      '(tests/test_e2e_smoke.py)'),
             'runs': [run_one(t, smoke) for t in ('deep', 'deep_fast')]}
  for run in results['runs']:
    assert run['steps'] > 0, run
    if not smoke:
      assert run['return_curve_deciles'], (
          f"no episodes finished for {run['torso']} — window too "
          'short for the curve to exist')
  out = os.environ.get('TORSO_COMPARE_OUT', os.path.join(
      os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
      'TORSO_COMPARE.json'))
  with open(out, 'w') as f:
    json.dump(results, f, indent=1)
  print(json.dumps(results))
  print('compare_torsos OK ->', out)


if __name__ == '__main__':
  from scalable_agent_tpu.runtime.py_process import warm_forkserver
  warm_forkserver()
  main()
