"""Pallas fused conv3x3(3->16)+maxpool3x3/2 prototype — measured
accept/reject for the section-1 kernel (docs/PERF.md round 5 rejected
it at DESIGN time on im2col arithmetic; this prototype tests the one
formulation that beats the arithmetic: a banded matmul).

Key idea: flatten W and C (x: [N, H, W*C]) and pre-pad one pixel of
halo, so the 3x3 conv becomes, for each of 3 row shifts dy, a matmul
of overlapping 30-column windows against ONE banded weight block
  Wb[dy] : [30, 128]   (30 = (8+2) cols x 3 ch, 128 = 8 cols x 16 ch)
whose band structure repeats with period 24 — every column chunk uses
the same Wb, so the MXU streams [rows, 30] @ [30, 128] with a 128-wide
output (vs the 27x16 output-starved im2col form). Max-pool (3x3/2,
XLA's asymmetric SAME: window i covers rows 2i..2i+2) fuses in VMEM —
the 715 MB pre-pool tensor never reaches HBM.

Usage: python scripts/pallas_conv_pool.py          # real chip
       SMOKE=1 python scripts/pallas_conv_pool.py  # CPU interpreter
Prints timing + parity JSON lines.
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SMOKE = os.environ.get('SMOKE') == '1'

import jax  # noqa: E402

if SMOKE:
  jax.config.update('jax_platforms', 'cpu')

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402
from jax.experimental import pallas as pl  # noqa: E402
from jax.experimental.pallas import tpu as pltpu  # noqa: E402

CIN, COUT = 3, 16
JCHUNK = 8                      # output cols per matmul (x16 ch = 128)
KWIN = (JCHUNK + 2) * CIN       # 30 input cols per window


def build_banded_weights(w):
  """w [3, 3, 3, 16] -> Wb [3, KWIN, JCHUNK*COUT].

  Wb[dy, (j'+1)*CIN + ci, j*COUT + co] = w[dy, j'-j+1, ci, co]
  for j in [0, JCHUNK), j' in [j-1, j+1] — the chunk's input window
  is columns (j-1)..(j+JCHUNK) of the (1-padded) frame row."""
  wb = np.zeros((3, KWIN, JCHUNK * COUT), np.float32)
  w = np.asarray(w, np.float32)
  for dy in range(3):
    for j in range(JCHUNK):
      for dx in range(3):           # j' = j + dx - 1, window-relative
        jp = j + dx - 1
        for ci in range(CIN):
          wb[dy, (jp + 1) * CIN + ci, j * COUT:(j + 1) * COUT] = \
              w[dy, dx, ci]
  return jnp.asarray(wb, jnp.bfloat16)


def _kernel(x_ref, wb_ref, sel_ref, out_ref, *, bh, h, wd):
  """One block of BH samples.

  x [BH, h+2, (wd+2)*CIN] bf16 (halo pre-padded, already /255),
  wb [3, KWIN, 128], sel [wd*COUT, (wd//2)*COUT] (0/1 compaction) ->
  out [BH*h, (wd//2)*COUT]: column-pooled, ROW-pooled-but-uncompacted
  (every row r holds max over conv rows r..r+2; the stride-2 row
  selection happens outside — Mosaic has no stride-2 vector ops).
  Everything stays in the flat [rows, wd*COUT] layout: lane-splitting
  reshapes and strided slices don't lower."""
  nchunks = wd // JCHUNK
  x = x_ref[:]                                  # [BH, h+2, (wd+2)*3]
  rows = bh * h

  # Conv as banded matmuls. Output column chunks are disjoint (only
  # the dy row-shifts accumulate) — no scatter needed.
  slabs = [x[:, dy:dy + h, :].reshape(rows, (wd + 2) * CIN)
           for dy in range(3)]
  chunks = []
  for c in range(nchunks):
    lo, hi = c * JCHUNK * CIN, (c * JCHUNK + JCHUNK + 2) * CIN
    acc = jnp.dot(slabs[0][:, lo:hi], wb_ref[0],
                  preferred_element_type=jnp.float32)
    for dy in (1, 2):
      acc += jnp.dot(slabs[dy][:, lo:hi], wb_ref[dy],
                     preferred_element_type=jnp.float32)
    chunks.append(acc)
  y = jnp.concatenate(chunks, axis=1)           # [rows, wd*COUT] f32

  neg = jnp.float32(-np.inf)
  # --- Row pooling (window rows r..r+2) via sublane rolls + sample-
  # boundary masks: roll -k brings row r+k to row r; rows past the
  # sample's last conv row contribute -inf (XLA SAME pads below).
  # pltpu.roll wants non-negative shifts; roll by size-k == roll -k.
  row_in_sample = lax.broadcasted_iota(jnp.int32, y.shape, 0) % h
  r1 = pltpu.roll(y, rows - 1, 0)
  r2 = pltpu.roll(y, rows - 2, 0)
  y = jnp.maximum(y, jnp.where(row_in_sample + 1 < h, r1, neg))
  y = jnp.maximum(y, jnp.where(row_in_sample + 2 < h, r2, neg))

  # --- Column pooling (cols 2j..2j+2) via lane rolls. Lane layout is
  # [w, ch] interleaved (period COUT): col +1 = roll -COUT, col +2 =
  # roll -2*COUT. The -2*COUT roll wraps for the last column block;
  # mask those lanes (their col 2j+2 = wd is XLA's SAME pad).
  lane = lax.broadcasted_iota(jnp.int32, y.shape, 1)
  nlanes = wd * COUT
  c1 = pltpu.roll(y, nlanes - COUT, 1)
  c2 = pltpu.roll(y, nlanes - 2 * COUT, 1)
  y = jnp.maximum(y, c1)   # valid for every SELECTED (even) column
  y = jnp.maximum(y, jnp.where(lane < wd * COUT - 2 * COUT, c2, neg))

  # --- Column compaction (keep blocks at even columns): one MXU pass
  # against the 0/1 selection matrix — exact (one term per output).
  out_ref[:] = jnp.dot(y.astype(jnp.bfloat16), sel_ref[:],
                       preferred_element_type=jnp.float32).astype(
                           jnp.bfloat16)


def build_selection(wd):
  """0/1 compaction matrix [wd*COUT, (wd//2)*COUT]: keep the COUT-lane
  block of every EVEN column."""
  wo = wd // 2
  s = np.zeros((wd * COUT, wo * COUT), np.float32)
  for k in range(wo):
    for r in range(COUT):
      s[2 * k * COUT + r, k * COUT + r] = 1.0
  return jnp.asarray(s, jnp.bfloat16)


def fused_conv_pool(frames, w, b, block=8):
  """frames uint8 [N, H, W, 3] -> pooled bf16 [N, H/2, W/2, 16]."""
  n, h, wd, _ = frames.shape
  assert n % block == 0 and wd % JCHUNK == 0
  # Host-side prep (XLA ops, fused/cheap): scale + halo pad + flatten.
  x = frames.astype(jnp.bfloat16) / 255.0
  x = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
  x = x.reshape(n, h + 2, (wd + 2) * CIN)
  wb = build_banded_weights(np.asarray(w, np.float32))
  sel = build_selection(wd)
  ho, wo = h // 2, wd // 2
  out = pl.pallas_call(
      functools.partial(_kernel, bh=block, h=h, wd=wd),
      grid=(n // block,),
      in_specs=[
          pl.BlockSpec((block, h + 2, (wd + 2) * CIN),
                       lambda i: (i, 0, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((3, KWIN, JCHUNK * COUT), lambda i: (0, 0, 0),
                       memory_space=pltpu.VMEM),
          pl.BlockSpec((wd * COUT, wo * COUT), lambda i: (0, 0),
                       memory_space=pltpu.VMEM),
      ],
      out_specs=pl.BlockSpec((block * h, wo * COUT), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
      out_shape=jax.ShapeDtypeStruct((n * h, wo * COUT), jnp.bfloat16),
      interpret=SMOKE,
  )(x, wb, sel)
  # Row compaction (stride-2) outside the kernel: 2x-pooled-rows out,
  # keep the even ones (Mosaic has no stride-2 vector ops in-kernel).
  out = out.reshape(n, h, wo * COUT)[:, 0::2]
  return out.reshape(n, ho, wo, COUT) + b.astype(jnp.bfloat16)


def xla_conv_pool(frames, w, b):
  x = frames.astype(jnp.bfloat16) / 255.0
  y = lax.conv_general_dilated(
      x, w, (1, 1), 'SAME', dimension_numbers=('NHWC', 'HWIO', 'NHWC'))
  y = y + b
  return lax.reduce_window(y, -jnp.inf, lax.max, (1, 3, 3, 1),
                           (1, 2, 2, 1), 'SAME')


def main():
  n = 64 if SMOKE else 3232
  h, wd = (24, 32) if SMOKE else (72, 96)
  rng = np.random.RandomState(0)
  frames = jnp.asarray(rng.randint(0, 255, (n, h, wd, 3)), jnp.uint8)
  w = jax.random.normal(jax.random.PRNGKey(0), (3, 3, CIN, COUT),
                        jnp.bfloat16) * 0.3
  b = jnp.zeros((COUT,), jnp.bfloat16)

  ref = xla_conv_pool(frames, w, b)
  got = fused_conv_pool(frames, w, b)
  err = float(jnp.max(jnp.abs(ref.astype(jnp.float32) -
                              got.astype(jnp.float32))))
  scale = float(jnp.max(jnp.abs(ref.astype(jnp.float32))))
  print(json.dumps({'parity_max_abs_err': err, 'scale': scale}),
        flush=True)
  # Gate, not just telemetry (CI runs the SMOKE path): interpret mode
  # reproduces XLA bit-for-bit; on the chip the f32-accumulate-then-
  # round matmul differs from XLA's conv by bf16 rounding only
  # (measured 0.004 relative), so a few bf16 ulps is the budget.
  tol = 1e-6 if SMOKE else 0.02 * scale
  assert err <= tol, f'fused conv+pool parity broke: {err} > {tol}'

  if SMOKE:
    return

  def bench(fn, label):
    jf = jax.jit(lambda f: fn(f, w, b))
    out = jf(frames)
    float(out.ravel()[0].astype(jnp.float32))
    t0 = time.perf_counter()
    for _ in range(20):
      out = jf(frames)
    float(out.ravel()[0].astype(jnp.float32))
    dt = (time.perf_counter() - t0) / 20
    c = jf.lower(frames).compile().cost_analysis()
    if isinstance(c, list):
      c = c[0]
    print(json.dumps({label: {'ms': round(dt * 1e3, 2),
                              'gb': round(c.get('bytes accessed', 0)
                                          / 1e9, 2)}}), flush=True)

  bench(xla_conv_pool, 'xla_fwd')
  bench(fused_conv_pool, 'pallas_fwd')


if __name__ == '__main__':
  main()
