"""Chaos harness: scripted fault storms against the full local pipeline.

Runs REAL training (driver.train on the contextual-bandit backend:
actor fleet → inference batcher → buffer → prefetcher → train step →
checkpoints, plus a live remote-actor child feeding over TCP) under a
seeded `runtime.faults.FaultPlan` storm covering every injection
layer —

  env hang            a wedged simulator (stall detection → respawn)
  env raise           a crashing env (fleet respawn)
  socket garbage      a corrupting remote peer (ingest quarantines the
                      connection; the actor child reconnects with
                      jittered backoff)
  NaN burst           non-finite loss/grads (device-side skip →
                      watchdog rollback to the last-known-good
                      checkpoint)
  interrupted save    a checkpoint save killed mid-write (the newest
                      step is corrupt; restore must ladder past it)

— and asserts the recovery SLOs on the way out:

  * ZERO learner crashes (train() returns),
  * >= 1 automatic checkpoint rollback,
  * a monotone, fully-accounted frame counter,
  * bounded rollback loss (params revert at most to the last
    checkpoint; step/frame counters never move backwards),
  * bounded time-to-recover (first bad step → next healthy step),
  * the corrupt remote connection quarantined while remote unrolls
    keep flowing afterwards,
  * health/fault counters present in summaries.jsonl + incidents.jsonl.

Round 9 adds the OVERLOAD storm (`run_overload_storm`): real training
with the actor fleet at 2× the inference state-arena capacity
(admission=shed), a slow-learner burst forcing buffer backpressure,
and a REAL mid-storm SIGTERM driving the preemption drain — asserting
the actor-plane SLOs:

  * zero learner crashes with the fleet at 2× slot capacity,
  * sheds counted and the shed fraction bounded (the slotless actors
    quarantine after their respawn budget instead of shedding
    forever),
  * bounded trajectory-buffer occupancy under the slow learner
    (high-water ≤ capacity + batch push-back bound),
  * the SIGTERM drain lands a VERIFIED checkpoint + resume manifest
    within the drain budget,
  * `driver.train` resumes from the manifest with the parity gate
    green (contiguous, monotone learner step sequence across the
    preemption).

Round 11 adds the PARTITION storm (`run_partition_storm`): the
learner runs as a CHILD process (so it can be hard-killed) with a
remote-actor child feeding it over TCP under partition
(`conn_partition` — blackhole silence the idle reaper must catch) and
latency (`conn_delay`) faults, then the `learner_crash` fault SIGKILLs
the learner mid-storm — no drain, no 'bye'. The harness restarts the
learner on the same logdir/port and asserts the transport/restart
SLOs:

  * learner #1 died by SIGKILL exactly as scheduled (no unwind),
  * learner #2 restores from the PR 2 LAST_GOOD ladder and trains its
    full step budget (frames monotone within each incarnation; at
    most the one crash-replay dip at the boundary),
  * the actor child RE-ATTACHES (cross-epoch hello counted + timed,
    reattach TTR bounded) and keeps feeding — then exits cleanly on
    the final 'bye',
  * ZERO stale-epoch unrolls accepted across the restart,
  * a half-open peer (the harness's own silent partial-frame socket)
    is reaped within the idle budget,
  * zero wedged ingest threads and zero unjoined threads at exit,
  * the liveness counters present in summaries.jsonl.

Round 12 adds the CORRUPTION storm (`run_corruption_storm`): real
training (2 virtual devices — the SDC sentinel needs data replicas to
cross-check) on a remote-only feed, under all four silent-corruption
fault sites:

  wire_bitflip        one flipped bit in an unroll frame that still
                      PARSES (the CRC-not-garbage shape) — the v7
                      trailer check must refuse it before the buffer
                      put ('corrupt' reply), the client re-sends
  publish_corrupt     a param blob corrupted between digest and wire
                      (frame CRC self-consistent) — the client's
                      digest check must refuse the install, report it
                      back, keep its prior params, and refetch clean
  replica_divergence  one replica's fingerprint lane perturbed — the
                      SDC sentinel must flag, escalate through the
                      health ladder, and roll back within budget
  ckpt_bitrot         one byte flipped in the NEWEST committed step
                      (under LAST_GOOD) — the resuming run's ladder
                      must refuse the step on digests and restore the
                      prior verified one

and asserts the integrity SLOs: zero corrupt unrolls committed
(wire_crc_rejected == scheduled flips, every refused frame re-sent
clean), zero corrupt publishes installed (client digest rejections
reported server-side, no self-quarantine, fleet kept feeding), the
divergent replica detected + rolled back within the TTR budget, the
bit-rotted checkpoint skipped via digest fallback with training
resuming from the prior verified step, and every integrity counter
present in summaries.jsonl.

Round 14: the storms are additionally judged by the SHIPPED default
SLO set (scalable_agent_tpu/slo.py — the same objectives every
production run is evaluated under): a storm's injected damage must
produce a failing SLO_VERDICT.json naming the violated objectives,
benign-path objectives must stay clean, and the page-severity burns
must have triggered their deep-diagnostics captures (flight dump +
trace slice + bounded profiler trace under diagnostics/). The
overload storm's SIGTERM is gated on the quarantine incident ledger
(with a hard deadline) instead of a wall-clock guess — the
slots_quarantined SLO used to race the full-jitter respawn backoff.

Round 15 adds the CONTROLLER storm (`run_controller_storm`): the
load-surge drill for the self-healing control plane
(scalable_agent_tpu/controller.py). Real training starts with half
its actor fleet parked; mid-run the harness DOUBLES the offered load
(unparks the other half, whose first spawn is a scripted env flake so
the new slots deterministically quarantine — a surge arriving on a
flaky plane). Under `--controller=act` the controller must heal it
with zero human knob-turning:

  * the tightened fleet-quorum SLO's margin thins → the controller
    escalates (admission flip + grow-fleet moves that REHABILITATE
    the quarantined slots through the probation ladder),
  * the objective never burns → SLO_VERDICT.json stays GREEN,
  * recovery clears the hysteresis band → every move is REVERTED,
  * CONTROLLER_LOG.json shows the escalations and the later reverts,
    all applied, with `controller_action` incidents + counters,
  * `slots_rehabilitated` counts the reclaimed slots.

The SAME storm re-runs under `--controller=observe` (the dry run):
the controller logs the moves it WOULD have made (applied: false)
and touches nothing — the quorum objective burns and the verdict
FAILS, recording exactly the violation the actuated run avoided.

Round 20 adds the ELASTIC storm (`run_elastic_storm`,
CHAOS_STORM=elastic — its own invocation, not part of 'all'): a
learner fed ENTIRELY by two remote actor hosts has one SIGKILLed
mid-run. The membership ledger must record host_left(lost) as a
durable incident, the pod-hosts SLO margin must thin without burning,
the controller's pod_size actuator must raise the declared target
(POD_TARGET.json), the harness's grow-only cluster supervisor must
spawn the replacement, and the replacement must JOIN a live learner —
no restart, no pause, verdict green, zero human knob-turning. The
full run adds a SIGTERM drain cycle (host_left reason='drain' via the
v9 'leave' announcement) and heals that too.

Round 21 adds the ROUTED storm (`run_routed_storm`,
CHAOS_STORM=routed — its own invocation, not part of 'all'): an
actor-side ServingRouter spreads v10 routed-inference traffic over
two serving replicas (ingest listener + InferenceServer each, real
sockets). Mid-run one replica is SIGKILLed: the router must fail the
request over, put the corpse on probation, and keep every subsequent
batch served — zero starvation (NoReplicasAvailable never raised
after warm-up) and the routed-latency SLO verdict green. The full
run adds a drain cycle: a replacement replica joins the rotation,
the old one is SIGTERM'd and its 'draining' notice must pull it out
of the rotation BEFORE it exits (drain is an advisory handoff, not
an error).

Writes CHAOS_OUT (default CHAOS.json at the repo root). Invocation:

    python scripts/chaos.py               # all storms, ~4-6 min CPU
    CHAOS_SMOKE=1 python scripts/chaos.py # CI smoke (all), < 240 s
    CHAOS_STORM=fault     python scripts/chaos.py  # just the r7 storm
    CHAOS_STORM=overload  python scripts/chaos.py  # just the overload
    CHAOS_STORM=partition python scripts/chaos.py  # just the partition
    CHAOS_STORM=corruption python scripts/chaos.py # just the integrity
    CHAOS_STORM=controller python scripts/chaos.py # just the controller
    CHAOS_STORM=elastic   python scripts/chaos.py  # pod membership
                                                   # (not part of 'all')
    CHAOS_STORM=routed    python scripts/chaos.py  # serving router
                                                   # (not part of 'all')
    CHAOS_SEED=7 python scripts/chaos.py  # different garbage bytes

The fault schedule is a pure function of the arguments (the seed only
perturbs garbage payload content), so a failure reproduces exactly;
the overload storm's SIGTERM is wall-clock-timed (the drain must be
correct WHENEVER it lands — that is the point of the drill).
"""

import json
import os
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SMOKE = bool(os.environ.get('CHAOS_SMOKE'))
SEED = int(os.environ.get('CHAOS_SEED', '1'))
OUT_PATH = os.environ.get('CHAOS_OUT',
                          os.path.join(REPO, 'CHAOS.json'))

os.environ.setdefault('JAX_PLATFORMS', 'cpu')

# The corruption storm's SDC leg cross-checks param fingerprints
# ACROSS data replicas, so its learner needs >= 2 devices — forced
# BEFORE any jax import, and only for the dedicated invocation (the
# other storms keep their single-device shapes; CHAOS_STORM=all runs
# the corruption storm in a subprocess for the same reason).
if os.environ.get('CHAOS_STORM') == 'corruption':
  _flags = os.environ.get('XLA_FLAGS', '')
  if 'xla_force_host_platform_device_count' not in _flags:
    os.environ['XLA_FLAGS'] = (
        _flags + ' --xla_force_host_platform_device_count=2').strip()


def _free_port() -> int:
  with socket.create_server(('127.0.0.1', 0)) as s:
    return s.getsockname()[1]


def _read_jsonl(path):
  if not os.path.exists(path):
    return []
  with open(path) as f:
    return [json.loads(line) for line in f if line.strip()]


def _spawn_actor_child(address, overrides, plan_json):
  """The production remote-actor role as a child process, with a
  client-side transport-fault plan shipped via SA_FAULT_PLAN (plans
  are process-local; the child installs its own)."""
  from scalable_agent_tpu.runtime import faults as faults_lib
  env = {k: v for k, v in os.environ.items()
         if k not in ('XLA_FLAGS', 'JAX_PLATFORMS')}
  existing = env.get('PYTHONPATH', '')
  env['PYTHONPATH'] = (REPO + os.pathsep + existing if existing
                       else REPO)
  env[faults_lib.PLAN_ENV_VAR] = plan_json
  body = (
      'import json, os, sys\n'
      'from scalable_agent_tpu.config import Config\n'
      'from scalable_agent_tpu.runtime import faults, remote\n'
      'faults.install_from_env()\n'
      'cfg = Config(**json.loads(sys.argv[2]))\n'
      'sent = remote.run_remote_actor(cfg, sys.argv[1], task=0,\n'
      '                               platform="cpu")\n'
      'print("CHILD_OK", sent, flush=True)\n')
  return subprocess.Popen(
      [sys.executable, '-c', body, address, json.dumps(overrides)],
      cwd=REPO, env=env, stdout=subprocess.PIPE,
      stderr=subprocess.STDOUT, text=True)


def run_storm(logdir: str, smoke: bool = SMOKE, seed: int = SEED):
  """Run the storm; returns (results dict, hard-assert errors list)."""
  from scalable_agent_tpu import driver
  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.runtime import faults as faults_lib

  max_steps = 30 if smoke else 80
  burst_len = 5
  cfg_kwargs = dict(
      logdir=logdir,
      env_backend='bandit',
      num_actors=2,
      batch_size=2,
      unroll_length=5,
      num_action_repeats=1,
      episode_length=4,
      height=24, width=32,
      torso='shallow',
      use_py_process=False,
      use_instruction=False,
      total_environment_frames=10 ** 9,
      inference_timeout_ms=5,
      checkpoint_secs=0,        # a save every maybe_save window: the
                                # burst always has a rollback target
      summary_secs=0,
      remote_actor_port=_free_port(),
      actor_reconnect_secs=120.0,
      health_rollback_after=3,  # K: the burst (5) must cross it
      health_min_window=8,
      # Round 18 (analysis/runtime.py): run the storm with lock-order
      # detection ARMED — every lock the threaded planes take under
      # fault pressure feeds the acquisition graph, so the storm
      # doubles as a race hunt. The zero-cycles assert is below.
      lock_order_check=True,
      seed=seed)
  cfg = Config(**cfg_kwargs)

  # Learner-side plan: env faults early (respawn machinery), the NaN
  # burst mid-run (after checkpoints exist), one interrupted save
  # after that (the next restore must ladder past it).
  plan = faults_lib.FaultPlan.storm(
      seed,
      env_raise_at=40,          # ~unroll 8 of the fleet's env steps
      env_hang_at=200,
      env_hang_secs=8.0,        # > stall timeout: must trigger respawn
      nan_burst_at=10, nan_burst_len=burst_len,
      checkpoint_interrupt_at=16)
  # Child-side plan: transport damage on the unroll pump (garbage →
  # learner quarantine; truncate/drop → reconnect-with-backoff).
  child_plan = faults_lib.FaultPlan(
      [faults_lib.Fault('transport_send', 4, 'garbage'),
       faults_lib.Fault('transport_send', 9, 'truncate'),
       faults_lib.Fault('transport_send', 14, 'drop')],
      seed=seed)

  child_overrides = {k: v for k, v in cfg_kwargs.items()
                     if k not in ('logdir', 'remote_actor_port')}
  child_overrides['logdir'] = logdir + '/actor_child'
  child = _spawn_actor_child(
      f'127.0.0.1:{cfg.remote_actor_port}', child_overrides,
      child_plan.to_json())

  faults_lib.install(plan)
  t0 = time.monotonic()
  crash = None
  run = None
  try:
    run = driver.train(cfg, max_steps=max_steps,
                       stall_timeout_secs=5.0)
  except BaseException as e:  # SLO: zero learner crashes
    crash = f'{type(e).__name__}: {e}'
  finally:
    faults_lib.clear()
  wall_secs = time.monotonic() - t0
  child.terminate()
  try:
    child_out = child.communicate(timeout=20)[0]
  except subprocess.TimeoutExpired:
    child.kill()
    child_out = child.communicate()[0]

  summaries = _read_jsonl(os.path.join(logdir, 'summaries.jsonl'))
  incidents = _read_jsonl(os.path.join(logdir, 'incidents.jsonl'))
  tags = {e['tag'] for e in summaries if 'tag' in e}
  plan_stats = plan.stats()

  errors = []
  results = {
      'smoke': smoke,
      'seed': seed,
      'max_steps': max_steps,
      'wall_secs': round(wall_secs, 2),
      'crash': crash,
      'fault_plan': plan_stats,
      'child_tail': child_out[-600:] if child_out else '',
  }
  if crash is not None:
    errors.append(f'learner crashed: {crash}')
    return results, errors

  health = run.health
  ing = run.ingest.stats() if run.ingest is not None else {}
  # train() has already stopped the fleet, so the liveness fields
  # would read an all-dead fleet — keep only the cumulative counters
  # (the live healthy_fraction is asserted via the summaries tag).
  fleet_raw = run.fleet.stats()
  fleet_stats = {k: fleet_raw[k] for k in ('respawns', 'unrolls')}

  # --- SLO: monotone, fully-accounted frame counter. The device
  # counter must equal steps consumed (skips included — a skipped
  # step still consumed its batch), and the summaries' step fields
  # must never decrease.
  import jax
  device_steps = int(jax.device_get(run.state.update_steps))
  if device_steps != max_steps:
    errors.append(f'frame counter not monotone/complete: device '
                  f'update_steps={device_steps}, expected {max_steps}')
  steps_seq = [e['step'] for e in summaries if 'step' in e]
  if any(b < a for a, b in zip(steps_seq, steps_seq[1:])):
    errors.append('summary step sequence decreased')

  # --- SLO: the watchdog skipped the burst and rolled back >= once.
  hs = health.stats()
  if hs['skipped_steps'] < burst_len:
    errors.append(f"skipped_steps={hs['skipped_steps']} < burst "
                  f'{burst_len}')
  if hs['rollbacks'] < 1:
    errors.append('no automatic checkpoint rollback happened')

  # --- SLO: bounded time-to-recover (first bad step -> next healthy
  # step), from the incident stream.
  ttr = None
  t_bad = None
  for ev in incidents:
    # First burst start only: a rollback mid-burst must not restart
    # the clock — TTR is first-bad-step → first healthy step.
    if ev['kind'] == 'health_bad_burst_start' and t_bad is None:
      t_bad = ev['wall_time']
    if (ev['kind'] == 'health_recovered' and ttr is None
        and t_bad is not None):
      ttr = round(ev['wall_time'] - t_bad, 3)
  recover_slo = 60.0
  if ttr is None:
    errors.append('no health_recovered incident (burst never ended?)')
  elif ttr > recover_slo:
    errors.append(f'time-to-recover {ttr}s > SLO {recover_slo}s')

  # --- SLO: the garbage connection was quarantined, and remote
  # unrolls kept flowing (the child reconnected and resumed). Round
  # 14: the quarantine and the rollback are judged by the SAME
  # shipped SLO objectives production runs under — the storm asserts
  # the verdict NAMES them (scalable_agent_tpu/slo.py defaults),
  # instead of re-deriving thresholds from raw counters here.
  from scalable_agent_tpu import slo as slo_lib
  verdict = slo_lib.read_verdict(logdir)
  if verdict is None:
    errors.append('no SLO_VERDICT.json from the fault storm '
                  '(slo_engine is default-on)')
  else:
    violated = set(verdict.get('violations') or [])
    results['slo_verdict'] = {'pass': verdict.get('pass'),
                              'violations': sorted(violated)}
    if 'ingest_quarantine_zero' not in violated:
      errors.append('SLO objective ingest_quarantine_zero not '
                    'violated despite the garbage connection')
    if 'rollbacks_zero' not in violated:
      errors.append('SLO objective rollbacks_zero not violated '
                    'despite the NaN-burst rollback')
  if ing.get('unrolls', 0) < 1:
    errors.append('no remote unrolls landed')

  # --- SLO: the interrupted save left a corrupt newest step the
  # integrity ladder can see (save_errors recorded), without killing
  # the run; counters surfaced in summaries.
  if run.checkpointer.save_errors < 1:
    errors.append('interrupted save not recorded in save_errors')
  for tag in ('skipped_steps', 'rollbacks', 'quarantined',
              'fleet_healthy_fraction'):
    if tag not in tags:
      errors.append(f'summary tag {tag!r} missing')

  # --- SLO (round 18): zero lock-order inversions over the armed
  # storm — the detector recorded every acquisition the threaded
  # planes made under fault pressure, and a cycle anywhere in the
  # run is a latent deadlock (it would also have landed as a durable
  # lock_order_inversion incident; assert both surfaces).
  from scalable_agent_tpu.analysis import runtime as lock_check
  cycles = lock_check.cycles_detected()
  results['lock_order'] = {'armed': lock_check.is_armed(),
                           'cycles': cycles}
  if not lock_check.is_armed():
    errors.append('lock-order detection was not armed for the storm')
  if cycles:
    errors.append(f'{cycles} lock-order inversion(s) detected: '
                  f'{lock_check.cycle_reports()}')
  inversion_incidents = [e for e in incidents
                         if e['kind'] == 'lock_order_inversion']
  if inversion_incidents:
    errors.append(f'lock_order_inversion incidents in the stream: '
                  f'{inversion_incidents}')

  results.update({
      'health': hs,
      'ingest': {k: ing.get(k) for k in
                 ('unrolls', 'quarantined', 'rejected', 'connections')},
      'fleet': fleet_stats,
      'checkpoint': {'save_errors': run.checkpointer.save_errors,
                     'restore_fallbacks':
                         run.checkpointer.restore_fallbacks,
                     'last_good_step':
                         run.checkpointer.last_good_step()},
      'device_update_steps': device_steps,
      'time_to_recover_secs': ttr,
      'incident_kinds': sorted({e['kind'] for e in incidents}),
  })
  return results, errors


def run_overload_storm(logdir: str, smoke: bool = SMOKE,
                       seed: int = SEED):
  """The actor-plane overload + preemption drill; returns (results,
  hard-assert errors). Fleet at 2× slot capacity, shed admission, a
  slow-learner backpressure burst, a REAL mid-storm SIGTERM → drain →
  resume with the parity gate."""
  import signal
  import threading

  import jax

  from scalable_agent_tpu import driver
  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.runtime import faults as faults_lib

  slots = 2
  fleet_size = 2 * slots                  # 2x slot pressure
  resume_steps = 3
  sigterm_after = 8.0 if smoke else 18.0  # MINIMUM storm wall time
  sigterm_deadline = 90.0                 # hard fallback (see below)
  drain_budget = 20.0
  cfg_kwargs = dict(
      logdir=logdir,
      env_backend='bandit',
      num_actors=fleet_size,
      batch_size=2,
      unroll_length=5,
      num_action_repeats=1,
      episode_length=4,
      height=24, width=32,
      torso='shallow',
      use_py_process=False,
      use_instruction=False,
      total_environment_frames=10 ** 9,
      inference_timeout_ms=5,
      inference_state_cache=True,         # the slot arena under test
      inference_state_slots=slots,
      inference_admission='shed',
      inference_admission_timeout_secs=0.3,
      fleet_quarantine_after=2,
      preempt_drain_timeout_secs=drain_budget,
      checkpoint_secs=0,
      summary_secs=0,
      seed=seed)
  cfg = Config(**cfg_kwargs)

  # Slow-learner burst early: the buffer must fill and producer
  # backpressure engage (bounded occupancy), never unbounded queueing.
  plan = faults_lib.FaultPlan.storm(
      seed, slow_learner_at=4, slow_learner_len=3,
      slow_learner_secs=0.3 if smoke else 0.6)

  # The REAL preemption path: SIGTERM (from a watcher thread) →
  # handler sets the drain event — exactly experiment.py's wiring.
  #
  # The trigger is CONDITIONED on the quarantine ledger, not a
  # wall-clock guess (the round-14 flake root cause): the
  # slots_quarantined SLO below needs the two slotless actors to have
  # exhausted their respawn budget, and that ladder is paced by
  # full-jitter backoff (Backoff base 0.5/cap 30 per attempt) PLUS a
  # 0.3 s admission wait per denied spawn, all gated behind the first
  # post-compile check_health — a fixed 8 s timer lost that race
  # more often than not (measured 7/12 seeds). The watcher waits for the
  # actor_slots_quarantined incident to reach the expected count
  # (but at least `sigterm_after`, so the slow-learner burst stays
  # inside the storm window), then fires; a hard deadline keeps a
  # real quarantine regression a loud assert instead of a hang.
  expected_quarantined = fleet_size - slots
  drain_event = threading.Event()
  old_handler = signal.signal(signal.SIGTERM,
                              lambda s, f: drain_event.set())
  watcher_stop = threading.Event()
  sigterm_wall = [None]

  def _quarantined_count():
    try:
      events = _read_jsonl(os.path.join(logdir, 'incidents.jsonl'))
    except ValueError:
      return 0  # a partially-written line mid-poll: retry next tick
    counts = [int(e.get('count', 0)) for e in events
              if e.get('kind') == 'actor_slots_quarantined']
    return max(counts, default=0)

  def _sigterm_when_quarantined(t_start):
    deadline = t_start + sigterm_deadline
    while not watcher_stop.is_set():
      now = time.monotonic()
      if now >= deadline:
        break
      if (now - t_start >= sigterm_after and
          _quarantined_count() >= expected_quarantined):
        break
      watcher_stop.wait(0.25)
    if not watcher_stop.is_set():
      sigterm_wall[0] = round(time.monotonic() - t_start, 2)
      os.kill(os.getpid(), signal.SIGTERM)

  faults_lib.install(plan)
  t0 = time.monotonic()
  watcher = threading.Thread(target=_sigterm_when_quarantined,
                             args=(t0,), daemon=True)
  crash = None
  run = None
  try:
    watcher.start()
    run = driver.train(cfg, stall_timeout_secs=5.0,
                       drain_event=drain_event)
  except BaseException as e:  # SLO: zero learner crashes at 2x load
    crash = f'{type(e).__name__}: {e}'
  finally:
    faults_lib.clear()
    watcher_stop.set()
    watcher.join(timeout=5.0)
    signal.signal(signal.SIGTERM, old_handler)
  wall_secs = time.monotonic() - t0

  errors = []
  results = {
      'smoke': smoke,
      'seed': seed,
      'slots': slots,
      'fleet_size': fleet_size,
      'sigterm_min_secs': sigterm_after,
      'sigterm_trigger': 'quarantine_ledger',
      'sigterm_wall_secs': sigterm_wall[0],
      'wall_secs': round(wall_secs, 2),
      'crash': crash,
      'fault_plan': plan.stats(),
  }
  if crash is not None:
    errors.append(f'learner crashed under overload: {crash}')
    return results, errors

  # --- SLO: sheds counted, fraction bounded, slotless slots
  # quarantined instead of shedding forever.
  snap = run.server.stats()
  fleet_stats = run.fleet.stats()
  sheds = snap['sheds']
  acquires = snap['acquires']
  shed_fraction = sheds / acquires if acquires else 0.0
  if sheds < 1:
    errors.append('no sheds despite fleet at 2x slot capacity')
  if shed_fraction > 0.9:
    errors.append(f'shed fraction {shed_fraction:.2f} > 0.9 — '
                  'admission never converged')
  if fleet_stats['slots_quarantined'] != fleet_size - slots:
    errors.append(
        f"slots_quarantined={fleet_stats['slots_quarantined']}, "
        f'expected {fleet_size - slots} (the slotless actors must '
        'give up, not retry forever)')

  # --- SLO: bounded buffer occupancy under the slow-learner burst.
  buf_stats = run.prefetcher._buffer.stats() if hasattr(
      run.prefetcher, '_buffer') else None
  capacity = max(cfg.queue_capacity_batches * cfg.batch_size,
                 cfg.batch_size)
  if buf_stats is not None:
    bound = capacity + cfg.batch_size - 1  # get_batch push-back bound
    if buf_stats['high_water'] > bound:
      errors.append(f"buffer high_water {buf_stats['high_water']} > "
                    f'bound {bound} — occupancy not bounded')
    if buf_stats['put_waits'] < 1:
      errors.append('no producer put ever blocked — the slow-learner '
                    'burst exercised no backpressure')

  # --- SLO: the drain landed a verified checkpoint + manifest within
  # the budget.
  manifest = driver.read_resume_manifest(logdir)
  device_steps = int(jax.device_get(run.state.update_steps))
  if manifest is None:
    errors.append('no resume_manifest.json after the SIGTERM drain')
  else:
    if manifest['update_steps'] != device_steps:
      errors.append(f"manifest update_steps {manifest['update_steps']}"
                    f' != device {device_steps}')
    if not manifest['checkpoint_verified']:
      errors.append('drain checkpoint not verified '
                    f"(checkpoint_step={manifest['checkpoint_step']})")
    if manifest['drain_latency_secs'] > drain_budget + 10.0:
      errors.append(f"drain latency {manifest['drain_latency_secs']}s "
                    f'> budget {drain_budget}s (+10s grace)')
    results['drain_latency_secs'] = manifest['drain_latency_secs']

  # --- SLO: resume from the manifest; parity gate — the combined
  # learner step sequence is contiguous and monotone across the
  # preemption, no frames lost or double-counted.
  resume_crash = None
  try:
    run2 = driver.train(cfg, max_steps=resume_steps,
                        stall_timeout_secs=5.0)
  except BaseException as e:
    resume_crash = f'{type(e).__name__}: {e}'
  if resume_crash is not None:
    errors.append(f'resume from manifest crashed: {resume_crash}')
    final_steps = None
  else:
    final_steps = int(jax.device_get(run2.state.update_steps))
    if final_steps != device_steps + resume_steps:
      errors.append(f'resume step accounting broken: {final_steps} != '
                    f'{device_steps} + {resume_steps}')
    if driver.read_resume_manifest(logdir) is not None:
      errors.append('resume manifest not consumed by the resuming run')
  summaries = _read_jsonl(os.path.join(logdir, 'summaries.jsonl'))
  frame_steps = [e['step'] for e in summaries
                 if e.get('tag') == 'env_frames_per_sec']
  if final_steps is not None and frame_steps != list(
      range(1, final_steps + 1)):
    errors.append('parity gate: combined step sequence is not the '
                  f'contiguous 1..{final_steps} (got {frame_steps})')

  # --- SLO: counters present in the summary/incident streams.
  tags = {e['tag'] for e in summaries if 'tag' in e}
  for tag in ('inference_sheds', 'slots_quarantined',
              'buffer_high_water', 'drain_latency_secs'):
    if tag not in tags:
      errors.append(f'summary tag {tag!r} missing')
  incidents = _read_jsonl(os.path.join(logdir, 'incidents.jsonl'))
  kinds = {e['kind'] for e in incidents}
  for kind in ('preempt_drain_start', 'preempt_drain_complete',
               'actor_slots_quarantined'):
    if kind not in kinds:
      errors.append(f'incident kind {kind!r} missing')

  results.update({
      'inference': {k: snap[k] for k in
                    ('acquires', 'sheds', 'admission_waits',
                     'admission_timeouts', 'waitlist_depth')},
      'shed_fraction': round(shed_fraction, 3),
      'slots_quarantined': fleet_stats['slots_quarantined'],
      'buffer': buf_stats,
      'device_update_steps': device_steps,
      'final_update_steps': final_steps,
      'incident_kinds': sorted(kinds),
  })
  return results, errors


def _spawn_learner_child(overrides, max_steps, plan_json):
  """driver.train as a child process — the only way a hard-kill
  (learner_crash -> SIGKILL) can be both injected and survived. On a
  clean finish the child prints 'LEARNER_OK <json>' with the final
  step count and the ingest liveness/restart counters (read
  post-close: the counters outlive the sockets)."""
  env = dict(os.environ)
  env['JAX_PLATFORMS'] = 'cpu'
  existing = env.get('PYTHONPATH', '')
  env['PYTHONPATH'] = (REPO + os.pathsep + existing if existing
                       else REPO)
  if plan_json:
    from scalable_agent_tpu.runtime import faults as faults_lib
    env[faults_lib.PLAN_ENV_VAR] = plan_json
  body = (
      'import json, sys\n'
      'from scalable_agent_tpu.config import Config\n'
      'from scalable_agent_tpu.runtime import faults\n'
      'faults.install_from_env()\n'
      'from scalable_agent_tpu import driver\n'
      'cfg = Config(**json.loads(sys.argv[1]))\n'
      'run = driver.train(cfg, max_steps=int(sys.argv[2]),\n'
      '                   stall_timeout_secs=10.0)\n'
      'import jax\n'
      'ing = run.ingest.stats()\n'
      'keys = ("unrolls", "conns_reaped", "heartbeat_misses",\n'
      '        "stale_epoch_rejected", "reattached", "reconnected",\n'
      '        "reattach_latency_secs", "ingest_threads_wedged",\n'
      '        "unjoined_threads", "param_subs_dropped",\n'
      '        "quarantined")\n'
      'out = {"final_steps":\n'
      '           int(jax.device_get(run.state.update_steps)),\n'
      '       "last_good": run.checkpointer.last_good_step(),\n'
      '       "ingest": {k: ing[k] for k in keys}}\n'
      'print("LEARNER_OK " + json.dumps(out), flush=True)\n')
  return subprocess.Popen(
      [sys.executable, '-c', body, json.dumps(overrides),
       str(max_steps)],
      cwd=REPO, env=env, stdout=subprocess.PIPE,
      stderr=subprocess.STDOUT, text=True)


def run_partition_storm(logdir: str, smoke: bool = SMOKE,
                        seed: int = SEED):
  """The transport-plane partition + hard-crash drill; returns
  (results, hard-assert errors). Learner as a hard-killable child,
  remote-actor child under partition/delay faults, a harness-owned
  half-open socket, learner kill -9 mid-storm, restart, re-attach."""
  from scalable_agent_tpu.runtime import faults as faults_lib
  from scalable_agent_tpu.runtime import remote as remote_lib

  port = _free_port()
  crash_at = 6 if smoke else 10        # consumed batches before kill -9
  resume_steps = 5 if smoke else 10    # learner #2's budget
  idle_timeout = 2.0
  reattach_slo = 45.0
  reap_slo = idle_timeout + 6.0        # idle window + poll/sched grace
  cfg_kwargs = dict(
      logdir=logdir,
      env_backend='bandit',
      num_actors=0,                    # remote-fed only: the wire IS
                                       # the feed under test
      batch_size=2,
      unroll_length=5,
      num_action_repeats=1,
      episode_length=4,
      height=24, width=32,
      torso='shallow',
      use_py_process=False,
      use_instruction=False,
      total_environment_frames=10 ** 9,
      inference_timeout_ms=5,
      checkpoint_secs=0,               # a save every window: LAST_GOOD
                                       # always trails the crash point
      summary_secs=0,
      remote_actor_port=port,
      remote_heartbeat_secs=0.5,
      remote_conn_idle_timeout_secs=idle_timeout,
      actor_reconnect_secs=240.0,      # must cover the restart gap
      seed=seed)

  learner_plan = faults_lib.FaultPlan.storm(
      seed, learner_crash_at=crash_at)
  # Actor-side transport chaos: latency early, then a blackhole longer
  # than the idle window (the learner must reap the silent conn; the
  # client discovers the reaped socket when the partition heals and
  # reconnects). Indices are _rpc events (handshake + unrolls + pings).
  actor_plan = faults_lib.FaultPlan.storm(
      seed, conn_delay=[3, 5], conn_delay_secs=0.15,
      conn_partition_at=8,
      conn_partition_secs=idle_timeout + 2.0)

  child_overrides = {k: v for k, v in cfg_kwargs.items()
                     if k not in ('logdir', 'remote_actor_port')}
  child_overrides.update(logdir=logdir + '/actor_child', num_actors=2)
  learner_overrides = dict(cfg_kwargs)

  actor = _spawn_actor_child(f'127.0.0.1:{port}', child_overrides,
                             actor_plan.to_json())
  t0 = time.monotonic()
  errors = []
  results = {
      'smoke': smoke,
      'seed': seed,
      'crash_at': crash_at,
      'resume_steps': resume_steps,
      'fault_plan': learner_plan.stats(),
  }
  learner2_out = ''
  actor_out = ''
  try:
    # --- Phase 1: learner #1 trains on the remote feed under the
    # delay/partition faults until the scheduled kill -9. ---
    learner1 = _spawn_learner_child(learner_overrides, max_steps=200,
                                    plan_json=learner_plan.to_json())
    try:
      out1, _ = learner1.communicate(timeout=60 if smoke else 120)
    except subprocess.TimeoutExpired:
      learner1.kill()
      out1 = learner1.communicate()[0]
      errors.append('learner #1 never hit its scheduled kill -9')
    results['learner1_returncode'] = learner1.returncode
    results['learner1_tail'] = (out1 or '')[-600:]
    if learner1.returncode != -9:
      errors.append(
          f'learner #1 exited {learner1.returncode}, expected SIGKILL '
          '(-9) from the learner_crash fault')
    if 'LEARNER_OK' in (out1 or ''):
      errors.append('learner #1 finished cleanly — the hard-kill '
                    'never fired')

    # --- Phase 2: restart the learner on the SAME logdir/port. The
    # actor child is mid-reconnect-window; it must re-attach. ---
    learner2 = _spawn_learner_child(learner_overrides,
                                    max_steps=resume_steps,
                                    plan_json='')
    # While learner #2 runs: a harness-owned HALF-OPEN peer (partial
    # frame, then silence) — the reap-within-budget SLO, measured
    # end to end: the reaper closes the socket, so our recv returns.
    half_open_reaped_secs = None
    try:
      deadline = time.monotonic() + (90 if smoke else 150)
      probe = None
      probe_t0 = None
      while learner2.poll() is None and time.monotonic() < deadline:
        if probe is None:
          try:
            probe = socket.create_connection(('127.0.0.1', port),
                                             timeout=2.0)
            probe.sendall(remote_lib._LEN.pack(1000) + b'\x00'
                          + b'half-open partial frame')
            probe.settimeout(max(reap_slo, 5.0))
            probe_t0 = time.monotonic()
          except OSError:
            probe = None
            time.sleep(0.5)
            continue
        if half_open_reaped_secs is None:
          try:
            if probe.recv(1) == b'':
              half_open_reaped_secs = time.monotonic() - probe_t0
          except socket.timeout:
            pass
          except OSError:
            half_open_reaped_secs = time.monotonic() - probe_t0
        else:
          time.sleep(0.2)
      try:
        learner2_out, _ = learner2.communicate(timeout=60)
      except subprocess.TimeoutExpired:
        learner2.kill()
        learner2_out = learner2.communicate()[0]
        errors.append('learner #2 (restart) hung')
      if probe is not None:
        probe.close()
    finally:
      if learner2.poll() is None:
        learner2.kill()
        learner2.communicate()
    results['learner2_tail'] = (learner2_out or '')[-600:]
    results['half_open_reaped_secs'] = (
        round(half_open_reaped_secs, 2)
        if half_open_reaped_secs is not None else None)

    # --- Actor child: the final graceful close 'bye's it out. ---
    try:
      actor_out, _ = actor.communicate(timeout=30)
    except subprocess.TimeoutExpired:
      actor.terminate()
      try:
        actor_out, _ = actor.communicate(timeout=10)
      except subprocess.TimeoutExpired:
        actor.kill()
        actor_out = actor.communicate()[0]
      errors.append('actor child did not exit on the learner\'s '
                    'final bye (possible deadlocked pump)')
    results['actor_tail'] = (actor_out or '')[-600:]
    if 'CHILD_OK' not in (actor_out or ''):
      errors.append('actor child did not report CHILD_OK')
  finally:
    if actor.poll() is None:
      actor.kill()
      actor.communicate()

  # --- SLOs from learner #2's report. ---
  report = None
  for line in (learner2_out or '').splitlines():
    if line.startswith('LEARNER_OK '):
      report = json.loads(line[len('LEARNER_OK '):])
  if report is None:
    errors.append('learner #2 produced no LEARNER_OK report')
    results['wall_secs'] = round(time.monotonic() - t0, 2)
    return results, errors
  ing = report['ingest']
  restored = report['final_steps'] - resume_steps
  results.update({
      'learner2': report,
      'restored_step': restored,
  })
  # Restore came from the LAST_GOOD ladder: a real step short of the
  # crash point, and the resumed run trained its FULL budget on top.
  if not 1 <= restored <= crash_at:
    errors.append(f'restored step {restored} outside [1, {crash_at}] '
                  '— restore-from-LAST_GOOD broken')
  if report['last_good'] != report['final_steps']:
    errors.append(
        f"learner #2's final save not LAST_GOOD: {report['last_good']}"
        f" != {report['final_steps']}")
  # Fleet re-attach: counted, timed, bounded.
  if ing['reattached'] < 1:
    errors.append('actor child never counted as reattached (no '
                  'cross-epoch hello at learner #2)')
  elif ing['reattach_latency_secs'] > reattach_slo:
    errors.append(f"fleet re-attach took {ing['reattach_latency_secs']}"
                  f's > SLO {reattach_slo}s')
  if ing['unrolls'] < resume_steps * cfg_kwargs['batch_size']:
    errors.append(f"learner #2 ingested only {ing['unrolls']} unrolls "
                  f'for {resume_steps} steps — the re-attached fleet '
                  'did not feed it')
  # Zero stale-incarnation unrolls crossed the restart.
  if ing['stale_epoch_rejected'] != 0:
    errors.append(f"stale_epoch_rejected={ing['stale_epoch_rejected']}"
                  ' != 0 across the restart')
  # The half-open peer was reaped within budget.
  if half_open_reaped_secs is None:
    errors.append('harness half-open connection never reaped')
  elif half_open_reaped_secs > reap_slo:
    errors.append(f'half-open reap took {half_open_reaped_secs:.1f}s '
                  f'> budget {reap_slo}s')
  if ing['conns_reaped'] < 1:
    errors.append('learner #2 counted no reaped connections')
  # Zero deadlocked/leaked threads at exit.
  if ing['ingest_threads_wedged'] != 0:
    errors.append(f"ingest_threads_wedged="
                  f"{ing['ingest_threads_wedged']} != 0 at exit")
  if ing['unjoined_threads'] != 0:
    errors.append(f"unjoined_threads={ing['unjoined_threads']} != 0 "
                  'at close')

  # Frames monotone: each incarnation's summary step sequence is
  # non-decreasing; the only allowed dip is the single crash-replay
  # boundary (restore < crash point).
  summaries = _read_jsonl(os.path.join(logdir, 'summaries.jsonl'))
  steps_seq = [e['step'] for e in summaries if 'step' in e]
  dips = sum(1 for a, b in zip(steps_seq, steps_seq[1:]) if b < a)
  if dips > 1:
    errors.append(f'summary step sequence dipped {dips} times — only '
                  'the crash-replay boundary may dip once')
  tags = {e['tag'] for e in summaries if 'tag' in e}
  for tag in ('remote_conns_reaped', 'remote_heartbeat_misses',
              'param_subs_dropped', 'ingest_threads_wedged',
              'remote_reattached', 'remote_reattach_latency_secs',
              'remote_stale_epoch_rejected'):
    if tag not in tags:
      errors.append(f'summary tag {tag!r} missing')

  # Round 14: learner #2 (the restarted incarnation) judged itself
  # under the shipped default SLO set — its verdict must FAIL naming
  # the transport-plane objective the partition violated (the
  # half-open probe it reaped), while the stale-epoch objective stays
  # clean (zero foreign-incarnation unrolls accepted OR refused in
  # learner #2's run: the re-attach was a clean re-handshake). Same
  # code judging the storm and production.
  from scalable_agent_tpu import slo as slo_lib
  verdict = slo_lib.read_verdict(logdir)
  if verdict is None:
    errors.append('learner #2 wrote no SLO_VERDICT.json')
  else:
    violated = set(verdict.get('violations') or [])
    results['slo_verdict'] = {'pass': verdict.get('pass'),
                              'violations': sorted(violated)}
    if 'conns_reaped_zero' not in violated:
      errors.append('SLO objective conns_reaped_zero not violated '
                    'despite the reaped half-open peer')
    if 'stale_epoch_zero' in violated:
      errors.append('SLO objective stale_epoch_zero violated — '
                    'stale-incarnation unrolls crossed the restart')

  # Trace-plane view of the storm (round 13): the learner children
  # ran with tracing on (default), so traces.jsonl spans BOTH
  # incarnations — the report's timeline shows the kill -9 window as
  # the batch gap it caused, with the incident markers interleaved.
  # Soft telemetry (recorded, not a hard SLO): both learner
  # incarnations must have produced spans with the full remote hop
  # chain, or the telemetry plane regressed under faults.
  try:
    sys.path.insert(0, REPO)
    from scripts import trace_report
    trace_summary = trace_report.summarize(
        trace_report.load_traces(logdir),
        trace_report.load_incidents(logdir))
    results['trace'] = {
        'batches': trace_summary['batches'],
        'unrolls': trace_summary['unrolls'],
        'hops': [row['hop'] for row in trace_summary['hops']],
        'policy_lag_p99': trace_summary['policy_lag']['p99'],
        'timeline_seconds': len(trace_summary['timeline']),
    }
    if trace_summary['batches'] == 0:
      errors.append('telemetry: zero trace batch records across the '
                    'partition storm (tracing is default-on)')
    hop_set = set(results['trace']['hops'])
    for hop in ('send->wire', 'wire->commit', 'serve->step'):
      if hop not in hop_set:
        errors.append(f'telemetry: remote hop {hop!r} missing from '
                      'the storm trace — spans not crossing the wire')
  except Exception as e:  # pragma: no cover - diagnostics only
    errors.append(f'trace report over the storm logdir failed: {e!r}')

  results['wall_secs'] = round(time.monotonic() - t0, 2)
  return results, errors


def run_corruption_storm(logdir: str, smoke: bool = SMOKE,
                         seed: int = SEED):
  """The data-plane integrity drill (round 12); returns (results,
  hard-assert errors). Requires >= 2 jax devices (module-top
  XLA_FLAGS handles the dedicated invocation). Phase 1: in-process
  learner on a 2-replica mesh, remote-only feed, under wire_bitflip +
  publish_corrupt + replica_divergence. Phase 2: the newest committed
  step is bit-rotted on disk; a resuming run must refuse it on
  digests and restore the prior verified step."""
  import jax

  from scalable_agent_tpu import driver
  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.runtime import faults as faults_lib

  errors = []
  results = {'smoke': smoke, 'seed': seed}
  if jax.device_count() < 2:
    errors.append(f'corruption storm needs >= 2 devices for the SDC '
                  f'leg, got {jax.device_count()} (XLA_FLAGS not '
                  'applied before jax import?)')
    return results, errors

  port = _free_port()
  phase1_steps = 14 if smoke else 30
  resume_steps = 3
  sdc_burst = 3                 # == health_rollback_after: one rollback
  sdc_at = 6                    # after checkpoints exist
  bitflips = [4, 9]             # 5th and 10th unroll sends
  recover_slo = 60.0            # detection -> healthy, seconds
  cfg_kwargs = dict(
      logdir=logdir,
      env_backend='bandit',
      num_actors=0,             # remote-fed: the wire IS the feed
      batch_size=2,             # one slot per data replica
      unroll_length=5,
      num_action_repeats=1,
      episode_length=4,
      height=24, width=32,
      torso='shallow',
      use_py_process=False,
      use_instruction=False,
      total_environment_frames=10 ** 9,
      inference_timeout_ms=5,
      checkpoint_secs=0,        # a save every window: LAST_GOOD fresh
      summary_secs=0,
      remote_actor_port=port,
      remote_heartbeat_secs=0.5,
      remote_conn_idle_timeout_secs=10.0,
      remote_publish_secs=0.1,  # publishes flow: the corrupt-blob leg
      actor_reconnect_secs=120.0,
      health_rollback_after=sdc_burst,
      health_min_window=4,
      seed=seed)
  cfg = Config(**cfg_kwargs)

  # Learner-side plan: blobs 2..7 (index: init blob 0, then the
  # cadence publishes) ship with a post-digest bit flip — a RUN, so
  # the child's poll-on-ack refetch is guaranteed to meet a corrupt
  # one before a clean publish supersedes it; the SDC probe perturbs
  # replica fingerprints for `sdc_burst` consecutive health checks
  # starting at step sdc_at+1.
  learner_plan = faults_lib.FaultPlan.storm(
      seed, publish_corrupt_at=2, publish_corrupt_len=6,
      replica_divergence_at=sdc_at, replica_divergence_len=sdc_burst)
  # Child-side plan: single-bit flips that still parse, AFTER the CRC
  # trailer was computed — distinct from the r7 storm's 'garbage'
  # (unparseable -> quarantine); these MUST take the benign
  # ('corrupt', crc) -> re-send path instead.
  child_plan = faults_lib.FaultPlan(
      [faults_lib.Fault('wire_bitflip', i, 'flip') for i in bitflips],
      seed=seed)

  child_overrides = {k: v for k, v in cfg_kwargs.items()
                     if k not in ('logdir', 'remote_actor_port')}
  child_overrides.update(logdir=logdir + '/actor_child', num_actors=2)
  child = _spawn_actor_child(f'127.0.0.1:{port}', child_overrides,
                             child_plan.to_json())

  faults_lib.install(learner_plan)
  t0 = time.monotonic()
  crash = None
  run = None
  try:
    run = driver.train(cfg, max_steps=phase1_steps,
                       stall_timeout_secs=10.0)
  except BaseException as e:  # SLO: zero learner crashes
    crash = f'{type(e).__name__}: {e}'
  finally:
    faults_lib.clear()
  try:
    child_out = child.communicate(timeout=60)[0]
  except subprocess.TimeoutExpired:
    child.kill()
    child_out = child.communicate()[0]
    errors.append('actor child did not exit on the final bye')
  results.update({
      'phase1_steps': phase1_steps,
      'fault_plan': learner_plan.stats(),
      'child_plan': child_plan.stats(),
      'child_tail': (child_out or '')[-800:],
  })
  if crash is not None:
    errors.append(f'learner crashed under corruption: {crash}')
    return results, errors

  import jax as _jax
  ing = run.ingest.stats()
  hs = run.health.stats()
  device_steps = int(_jax.device_get(run.state.update_steps))
  results.update({
      'ingest': {k: ing.get(k) for k in
                 ('unrolls', 'wire_crc_rejected',
                  'publish_digest_rejected', 'quarantined',
                  'discarded_frames', 'discarded_bytes')},
      'health': hs,
      'device_update_steps': device_steps,
  })

  # --- SLO: zero corrupt unrolls committed. Every scheduled flip was
  # refused BEFORE the buffer put (the refusal-before-put ordering is
  # structural; the counter proves each flip was actually caught) and
  # every refused unroll was re-sent clean (training completed its
  # full step budget on the remote feed).
  if ing.get('wire_crc_rejected', 0) != len(bitflips):
    errors.append(f"wire_crc_rejected={ing.get('wire_crc_rejected')}"
                  f' != scheduled bit flips {len(bitflips)}')
  if device_steps != phase1_steps:
    errors.append(f'learner trained {device_steps} steps, expected '
                  f'{phase1_steps} — the re-sent unrolls did not land')
  if 'self-quarantin' in (child_out or '').lower():
    errors.append('actor child self-quarantined — a re-sent unroll '
                  'failed its CRC twice (injection leaked into the '
                  'retry?)')
  if 'CHILD_OK' not in (child_out or ''):
    errors.append('actor child did not report CHILD_OK')

  # --- SLO: zero corrupt publishes installed. The client refused the
  # digest-mismatched blob (reported back on its retry fetch), kept
  # feeding, and refetched a clean publish.
  if ing.get('publish_digest_rejected', 0) < 1:
    errors.append('no publish_digest_rejected recorded — the corrupt '
                  'blob was never refused (or never fetched)')
  if 'digest_rejections=0' in (child_out or ''):
    errors.append('child INTEGRITY_REPORT shows zero digest '
                  'rejections')

  # --- SLO: the divergent replica was detected, escalated, and
  # rolled back within budget.
  if hs.get('sdc_mismatches', 0) < sdc_burst:
    errors.append(f"sdc_mismatches={hs.get('sdc_mismatches')} < "
                  f'burst {sdc_burst}')
  if hs.get('rollbacks', 0) < 1:
    errors.append('no rollback despite the SDC burst crossing K')
  incidents = _read_jsonl(os.path.join(logdir, 'incidents.jsonl'))
  kinds = {e['kind'] for e in incidents}
  for kind in ('fault_replica_divergence', 'sdc_replica_mismatch',
               'rollback'):
    if kind not in kinds:
      errors.append(f'incident kind {kind!r} missing')
  ttr = None
  t_bad = None
  for ev in incidents:
    if ev['kind'] == 'health_bad_burst_start' and t_bad is None:
      t_bad = ev['wall_time']
    if (ev['kind'] == 'health_recovered' and ttr is None
        and t_bad is not None):
      ttr = round(ev['wall_time'] - t_bad, 3)
  if ttr is None:
    errors.append('no health_recovered after the SDC burst')
  elif ttr > recover_slo:
    errors.append(f'SDC time-to-recover {ttr}s > SLO {recover_slo}s')
  results['time_to_recover_secs'] = ttr

  # --- SLO: integrity counters reach summaries.jsonl.
  summaries = _read_jsonl(os.path.join(logdir, 'summaries.jsonl'))
  tags = {e['tag'] for e in summaries if 'tag' in e}
  for tag in ('wire_crc_rejected', 'publish_digest_rejected',
              'sdc_replica_mismatches', 'ckpt_digest_fallbacks'):
    if tag not in tags:
      errors.append(f'summary tag {tag!r} missing')

  # --- Round 14: the storm is judged by the SAME shipped SLO specs
  # production runs under (scalable_agent_tpu/slo.py defaults): the
  # injected damage must produce a FAILING SLO_VERDICT.json naming
  # the violated objectives, the benign-path objectives must stay
  # clean (a parseable bit flip takes the corrupt-reply path, never
  # the quarantine — judged by the ingest_quarantine_zero objective
  # instead of a hand-rolled counter assert), and the page-severity
  # burns must have shipped their own explanation: flight dump +
  # trace_report slice + a bounded profiler capture under
  # diagnostics/. Read BEFORE phase 2 — the resuming run writes its
  # own verdict over the file.
  from scalable_agent_tpu import slo as slo_lib
  verdict = slo_lib.read_verdict(logdir)
  if verdict is None:
    errors.append('phase 1 wrote no SLO_VERDICT.json (slo_engine is '
                  'default-on)')
  else:
    results['slo_verdict'] = {
        'pass': verdict.get('pass'),
        'violations': verdict.get('violations'),
        'captures': sorted((verdict.get('captures') or {})),
    }
    if verdict.get('pass'):
      errors.append('SLO verdict PASSED a corruption storm — the '
                    'default objective set judged injected damage '
                    'as healthy')
    violated = set(verdict.get('violations') or [])
    for objective in ('wire_crc_rejected_zero', 'sdc_mismatch_zero'):
      if objective not in violated:
        errors.append(f'SLO objective {objective!r} not violated '
                      'despite the injected damage')
    if 'ingest_quarantine_zero' in violated:
      errors.append('ingest_quarantine_zero violated — a parseable '
                    'bit flip must take the benign corrupt path, '
                    'not the quarantine')
    captures = verdict.get('captures') or {}
    for objective in ('wire_crc_rejected_zero', 'sdc_mismatch_zero'):
      cap = captures.get(objective)
      if cap is None:
        errors.append(f'no triggered capture for page objective '
                      f'{objective!r}')
        continue
      for kind in ('flight', 'trace_slice', 'profile'):
        path = cap.get(kind)
        if not path or not os.path.exists(path):
          errors.append(f'capture artifact {kind!r} for '
                        f'{objective!r} missing ({path!r})')
      prof = cap.get('profile')
      if prof and os.path.isdir(prof) and not any(os.scandir(prof)):
        errors.append(f'profiler capture dir for {objective!r} is '
                      'empty — jax.profiler never wrote a trace')
    if 'slo_violation' not in kinds:
      errors.append('no slo_violation incident recorded')

  # --- Phase 2: bit-rot the NEWEST committed step (it carries the
  # LAST_GOOD marker — restore verifies structure fine, only the
  # digest ladder can refuse it), then resume: training must come
  # back from the PRIOR verified step, not the rot.
  rotted_step = run.checkpointer.last_good_step()
  if rotted_step is None:
    errors.append('phase 1 left no LAST_GOOD step to rot')
    return results, errors
  faults_lib.bitrot_checkpoint_step(
      os.path.join(logdir, 'checkpoints'), rotted_step, seed=seed)
  resume_cfg = Config(**dict(
      cfg_kwargs, num_actors=2, remote_actor_port=0))
  resume_crash = None
  run2 = None
  try:
    run2 = driver.train(resume_cfg, max_steps=resume_steps,
                        stall_timeout_secs=10.0)
  except BaseException as e:
    resume_crash = f'{type(e).__name__}: {e}'
  if resume_crash is not None:
    errors.append(f'resume past the bit-rotted step crashed: '
                  f'{resume_crash}')
  else:
    final_steps = int(_jax.device_get(run2.state.update_steps))
    restored = final_steps - resume_steps
    results.update({
        'rotted_step': rotted_step,
        'restored_step': restored,
        'digest_fallbacks': run2.checkpointer.digest_fallbacks,
    })
    if run2.checkpointer.digest_fallbacks < 1:
      errors.append('resume recorded no digest fallback — the '
                    'bit-rotted step was restored as if clean')
    if not 0 <= restored < rotted_step:
      errors.append(f'resume restored step {restored}, expected a '
                    f'verified step BELOW the rotted {rotted_step}')
  results['wall_secs'] = round(time.monotonic() - t0, 2)
  return results, errors


def _run_controller_phase(logdir, mode, spec_path, policy_path,
                          smoke, seed, max_seconds):
  """One controller-storm run (mode = 'act' | 'observe'): fleet of 4
  starts with 2 slots parked; a watcher doubles the offered load
  mid-run by unparking them — their first spawn is a scripted env
  flake, so both new slots deterministically quarantine and only a
  rehabilitation path can reclaim them. Returns (results, errors)."""
  import threading

  from scalable_agent_tpu import controller as controller_lib
  from scalable_agent_tpu import driver
  from scalable_agent_tpu import slo as slo_lib
  from scalable_agent_tpu.config import Config

  fleet_size = 4
  initial_size = 2
  surge_warm_secs = 3.0
  cfg = Config(
      logdir=logdir,
      env_backend='bandit',
      num_actors=fleet_size,
      batch_size=2,
      unroll_length=5,
      num_action_repeats=1,
      episode_length=4,
      height=24, width=32,
      torso='shallow',
      use_py_process=False,
      use_instruction=False,
      total_environment_frames=10 ** 9,
      inference_timeout_ms=5,
      checkpoint_secs=0,
      summary_secs=0,
      # The surge slots must give up FAST (first respawn is the
      # scripted flake; the second attempt quarantines) so the
      # controller's rehabilitation move is the only way back.
      fleet_quarantine_after=1,
      fleet_probation_secs=0.2,
      controller=mode,
      controller_policy=policy_path,
      controller_interval_secs=0.25,
      slo_spec=spec_path,
      slo_capture=False,        # the verdict is the record here
      seed=seed)

  fleet_holder = []
  flakes = {i: 1 for i in range(initial_size, fleet_size)}

  def fleet_factory(cfg2, agent, policy, buffer, levels):
    fleet = driver.make_fleet(cfg2, agent, policy, buffer, levels)
    orig_make = fleet._make_actor

    def flaky_make(i):
      if flakes.get(i, 0) > 0:
        flakes[i] -= 1
        raise RuntimeError(f'storm surge: scripted env flake on '
                           f'slot {i}')
      return orig_make(i)

    fleet._make_actor = flaky_make
    fleet.set_target_size(initial_size)  # spin up at half load
    fleet_holder.append(fleet)
    return fleet

  watcher_stop = threading.Event()
  surge_wall = [None]

  def _surge(t_start):
    # Wait for real training traffic (first summary row) so the slow
    # burn window holds healthy samples before the surge lands, then
    # double the offered load.
    deadline = t_start + 60.0
    while not watcher_stop.is_set() and time.monotonic() < deadline:
      try:
        rows = _read_jsonl(os.path.join(logdir, 'summaries.jsonl'))
      except ValueError:
        rows = []
      if fleet_holder and any(r.get('tag') == 'env_frames_per_sec'
                              for r in rows):
        break
      watcher_stop.wait(0.2)
    if watcher_stop.is_set() or not fleet_holder:
      return
    watcher_stop.wait(surge_warm_secs)
    if watcher_stop.is_set():
      return
    surge_wall[0] = round(time.monotonic() - t_start, 2)
    fleet_holder[0].set_target_size(fleet_size)

  t0 = time.monotonic()
  watcher = threading.Thread(target=_surge, args=(t0,), daemon=True)
  crash = None
  run = None
  try:
    watcher.start()
    run = driver.train(cfg, max_seconds=max_seconds,
                       stall_timeout_secs=5.0,
                       fleet_factory=fleet_factory)
  except BaseException as e:  # SLO: zero learner crashes
    crash = f'{type(e).__name__}: {e}'
  finally:
    watcher_stop.set()
    watcher.join(timeout=5.0)

  errors = []
  results = {
      'mode': mode,
      'fleet_size': fleet_size,
      'initial_size': initial_size,
      'surge_wall_secs': surge_wall[0],
      'wall_secs': round(time.monotonic() - t0, 2),
      'crash': crash,
  }
  if crash is not None:
    errors.append(f'[{mode}] learner crashed under the load surge: '
                  f'{crash}')
    return results, errors
  if surge_wall[0] is None:
    errors.append(f'[{mode}] the load surge never fired (no training '
                  'traffic within 60s?)')
    return results, errors

  verdict = slo_lib.read_verdict(logdir)
  clog = controller_lib.read_log(logdir)
  fleet_stats = run.fleet.stats()
  results.update({
      'slo_verdict': None if verdict is None else {
          'pass': verdict.get('pass'),
          'violations': verdict.get('violations')},
      'controller_counts': None if clog is None else clog['counts'],
      'slots_quarantined': fleet_stats['slots_quarantined'],
      'slots_rehabilitated': fleet_stats['slots_rehabilitated'],
      'admission': run.server.admission,
  })
  if verdict is None:
    errors.append(f'[{mode}] no SLO_VERDICT.json')
    return results, errors
  if clog is None:
    errors.append(f'[{mode}] no CONTROLLER_LOG.json')
    return results, errors
  actions = clog.get('actions') or []
  escalations = [a for a in actions if a['kind'] == 'escalate']
  reverts = [a for a in actions if a['kind'] == 'revert']
  results['actions'] = [
      {k: a.get(k) for k in ('kind', 'actuator', 'from', 'to',
                             'applied')} for a in actions]

  if mode == 'act':
    # --- The headline SLO: the verdict stays GREEN with zero human
    # knob-turning — the quorum objective's margin triggered the
    # controller BEFORE it could burn.
    if not verdict.get('pass'):
      errors.append(f"[act] SLO verdict FAILED despite the "
                    f"controller: {verdict.get('violations')}")
    quorum = (verdict.get('objectives') or {}).get(
        'fleet_healthy_fraction') or {}
    if quorum.get('burns', 0) != 0:
      errors.append(f"[act] fleet_healthy_fraction burned "
                    f"{quorum.get('burns')}x — the controller acted "
                    'too late (the storm gives it the slow-window '
                    'confirmation as its reaction budget)')
    # --- Escalation and the later revert, all applied.
    if len(escalations) < 2:
      errors.append(f'[act] expected >= 2 escalations (admission + '
                    f'fleet grow), got {len(escalations)}')
    if len(reverts) < 2:
      errors.append(f'[act] expected >= 2 reverts, got '
                    f'{len(reverts)}')
    if not all(a['applied'] for a in actions):
      errors.append('[act] an action failed to apply: '
                    f'{[a for a in actions if not a["applied"]]}')
    if escalations and reverts:
      if min(a['wall_time'] for a in reverts) <= \
         min(a['wall_time'] for a in escalations):
        errors.append('[act] a revert preceded the first escalation')
    grew = [a for a in escalations if a['actuator'] == 'fleet_size']
    if not grew:
      errors.append('[act] no fleet_size escalation — the '
                    'quarantined surge slots were never reclaimed')
    # --- The grow move reclaimed the quarantined slots through
    # probation, and the reverts put every knob back.
    if fleet_stats['slots_rehabilitated'] != fleet_size - initial_size:
      errors.append(
          f"[act] slots_rehabilitated="
          f"{fleet_stats['slots_rehabilitated']}, expected "
          f'{fleet_size - initial_size}')
    if fleet_stats['slots_quarantined'] != 0:
      errors.append(f"[act] slots_quarantined="
                    f"{fleet_stats['slots_quarantined']} at exit — "
                    'rehabilitation did not reclaim the surge slots')
    if run.server.admission != 'block':
      errors.append(f'[act] admission not reverted to block '
                    f'(got {run.server.admission!r})')
    # --- The audit trail: fsync'd incidents + summary scalars.
    incidents = _read_jsonl(os.path.join(logdir, 'incidents.jsonl'))
    kinds = {e['kind'] for e in incidents}
    if 'controller_action' not in kinds:
      errors.append('[act] no controller_action incident recorded')
    summaries = _read_jsonl(os.path.join(logdir, 'summaries.jsonl'))
    tags = {e['tag'] for e in summaries if 'tag' in e}
    for tag in ('controller_actions', 'controller_reverts'):
      if tag not in tags:
        errors.append(f'[act] summary tag {tag!r} missing')
  else:
    # --- The dry run records the violation the actuated run avoided:
    # same surge, nothing actuated, the quorum objective burns and
    # fails the verdict; the intended moves are logged unapplied.
    if verdict.get('pass'):
      errors.append('[observe] SLO verdict PASSED — the surge did '
                    'not produce the violation the actuated run is '
                    'credited with avoiding')
    if 'fleet_healthy_fraction' not in (
        verdict.get('violations') or []):
      errors.append('[observe] fleet_healthy_fraction not among the '
                    f"violations: {verdict.get('violations')}")
    if not actions:
      errors.append('[observe] the dry-run controller logged no '
                    'intended actions')
    if any(a['applied'] for a in actions):
      errors.append('[observe] an observe-mode action was APPLIED: '
                    f'{[a for a in actions if a["applied"]]}')
    if fleet_stats['slots_rehabilitated'] != 0:
      errors.append('[observe] slots were rehabilitated in observe '
                    'mode')
    if fleet_stats['slots_quarantined'] != fleet_size - initial_size:
      errors.append(
          f"[observe] slots_quarantined="
          f"{fleet_stats['slots_quarantined']}, expected the surge's "
          f'{fleet_size - initial_size} to stay quarantined')
  return results, errors


def run_controller_storm(logdir: str, smoke: bool = SMOKE,
                         seed: int = SEED):
  """The self-healing control-plane drill (round 15); returns
  (results, hard-assert errors). Two phases on sibling logdirs: the
  ACTUATED run (controller=act — the verdict must stay green, the
  action log must show the escalation and the later revert, the
  quarantined surge slots must be rehabilitated) and the OBSERVE run
  (same storm, dry-run controller — the verdict must FAIL on the
  quorum objective, recording the violation the actuated run
  avoided)."""
  # The storm's tightened objective set: the shipped
  # fleet_healthy_fraction objective with a per-deployment target
  # (the --slo_spec mechanism — this 4-slot toy fleet's quorum floor
  # is 0.6, where the production default 0.25 fits thousand-slot
  # fleets), plus the rollbacks pin. Windows sized so the multi-window
  # burn semantics give the controller its documented reaction budget:
  # the fast window catches the surge in ~1.5 s; the slow window
  # confirms only after seconds of sustained violation — the
  # controller must heal inside that confirmation window or the
  # verdict goes red exactly like the observe run's.
  spec = [
      # Target 0.7 on a 4-slot fleet: the quorum steps through 0.5
      # (surge) -> 0.75 (first rehabilitation) -> 1.0 (healed), and
      # the trigger band must COVER the 0.75 intermediate — a
      # trigger_margin smaller than the largest single-step recovery
      # increment wedges the escalation inside the hysteresis band
      # with one slot still quarantined (docs/RUNBOOK.md §12 sizing
      # rule, learned the hard way by this storm's first cut).
      dict(name='fleet_healthy_fraction',
           metric='driver/fleet_healthy_fraction',
           comparison='>=', target=0.7, severity='page',
           fast_window_secs=1.5, slow_window_secs=30.0,
           description='storm-tightened fleet quorum'),
      dict(name='rollbacks_zero', metric='health/rollbacks',
           kind='rate', comparison='==', target=0.0,
           severity='ticket', fast_window_secs=1.5,
           slow_window_secs=30.0,
           description='no rollbacks under the surge'),
  ]
  policy = [
      dict(objective='fleet_healthy_fraction', actuator='admission',
           to='shed', revert_to='block', trigger_margin=0.1,
           clear_margin=0.25, cooldown_secs=1.0,
           description='quorum thinning under surge: stop parking '
                       'admissions'),
      dict(objective='fleet_healthy_fraction', actuator='fleet_size',
           direction='up', step=1, trigger_margin=0.1,
           clear_margin=0.25, cooldown_secs=0.4,
           description='quorum thinning: grow the fleet '
                       '(rehabilitate quarantined slots)'),
  ]
  os.makedirs(logdir, exist_ok=True)
  spec_path = os.path.join(logdir, 'storm_slo_spec.json')
  policy_path = os.path.join(logdir, 'storm_controller_policy.json')
  with open(spec_path, 'w') as f:
    json.dump(spec, f, indent=2)
  with open(policy_path, 'w') as f:
    json.dump(policy, f, indent=2)

  t0 = time.monotonic()
  errors = []
  results = {'smoke': smoke, 'seed': seed}
  act_dir = os.path.join(logdir, 'act')
  obs_dir = os.path.join(logdir, 'observe')
  os.makedirs(act_dir)
  os.makedirs(obs_dir)
  results['act'], act_errors = _run_controller_phase(
      act_dir, 'act', spec_path, policy_path, smoke, seed,
      max_seconds=14.0 if smoke else 22.0)
  errors += act_errors
  results['observe'], obs_errors = _run_controller_phase(
      obs_dir, 'observe', spec_path, policy_path, smoke, seed,
      max_seconds=12.0 if smoke else 18.0)
  errors += obs_errors
  results['wall_secs'] = round(time.monotonic() - t0, 2)
  return results, errors


def run_elastic_storm(logdir: str, smoke: bool = SMOKE,
                      seed: int = SEED):
  """The elastic pod-membership drill (round 20); returns (results,
  hard-assert errors).

  A single-process learner (no local actors) trains entirely from two
  remote actor hosts. Mid-run the harness SIGKILLs one host. The
  survivors must observe the departure (host_left reason='lost'
  durable incident), the pod-hosts SLO margin must thin WITHOUT
  burning, the controller's pod_size actuator must raise the declared
  target (POD_TARGET.json), and the harness's grow-only cluster
  supervisor — the reconciliation role a real deployment's cluster
  manager plays — must spawn the replacement, which joins WITHOUT the
  learner pausing. Zero human knob-turning; the verdict stays green.
  The full (non-smoke) run adds a second cycle: SIGTERM-draining a
  host (the deliberate 'leave' announcement → host_left
  reason='drain') and healing again."""
  import signal as signal_lib
  import threading

  from scalable_agent_tpu import controller as controller_lib
  from scalable_agent_tpu import driver
  from scalable_agent_tpu import slo as slo_lib
  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.runtime import faults as faults_lib

  port = _free_port()
  # The pod-hosts objective: fractional target so the margin is never
  # exactly zero at quorum (2 hosts -> margin +0.5, 1 host -> -0.5).
  # Slow window sized so the healthy warm-up always outweighs the
  # violation dip (burn needs >= half the slow-window samples bad).
  spec = [
      dict(name='pod_hosts', metric='driver/remote_live_hosts',
           comparison='>=', target=1.5, severity='page',
           fast_window_secs=2.0, slow_window_secs=90.0,
           description='elastic drill: the pod must hold 2 actor '
                       'hosts'),
  ]
  # clear_margin 10 is unreachable (pod_max_hosts bounds the gauge):
  # the grow decision is never reverted — shrinking the pod is the
  # utilization rule's job in production, and the drill's supervisor
  # is grow-only by design.
  policy = [
      dict(objective='pod_hosts', actuator='pod_size',
           direction='up', step=1, trigger_margin=0.25,
           clear_margin=10.0, cooldown_secs=15.0,
           description='a host left: raise the declared pod target '
                       'so the cluster supervisor replaces it'),
  ]
  os.makedirs(logdir, exist_ok=True)
  spec_path = os.path.join(logdir, 'elastic_slo_spec.json')
  policy_path = os.path.join(logdir, 'elastic_policy.json')
  with open(spec_path, 'w') as f:
    json.dump(spec, f, indent=2)
  with open(policy_path, 'w') as f:
    json.dump(policy, f, indent=2)

  cfg_kwargs = dict(
      logdir=logdir,
      env_backend='bandit',
      num_actors=0,             # every row arrives over TCP
      batch_size=2,
      unroll_length=5,
      num_action_repeats=1,
      episode_length=4,
      height=24, width=32,
      torso='shallow',
      use_py_process=False,
      use_instruction=False,
      total_environment_frames=10 ** 9,
      inference_timeout_ms=5,
      checkpoint_secs=0,
      summary_secs=0,
      remote_actor_port=port,
      # A SIGKILLed host must be reaped (and the ledger must record
      # the loss) in seconds, not the production minute — but the
      # window must still cover a fresh host's first-compile silence
      # (~5 s before its ping thread is up), or a HEALTHY joiner gets
      # reaped as half-open.
      remote_heartbeat_secs=0.5,
      remote_conn_idle_timeout_secs=8.0,
      controller='act',
      controller_policy=policy_path,
      controller_interval_secs=0.25,
      pod_max_hosts=3,
      slo_spec=spec_path,
      slo_capture=False,
      seed=seed)
  cfg = Config(**cfg_kwargs)

  child_overrides = {k: v for k, v in cfg_kwargs.items()
                     if k in ('env_backend', 'batch_size',
                              'unroll_length', 'num_action_repeats',
                              'episode_length', 'height', 'width',
                              'torso', 'use_py_process',
                              'use_instruction',
                              'total_environment_frames',
                              'inference_timeout_ms', 'seed')}
  child_overrides['num_actors'] = 2
  no_faults = faults_lib.FaultPlan([], seed=seed).to_json()

  def _spawn_host(idx):
    ov = dict(child_overrides, logdir=os.path.join(logdir,
                                                   f'host{idx}'))
    return _spawn_actor_child(f'127.0.0.1:{port}', ov, no_faults)

  children = {0: _spawn_host(0), 1: _spawn_host(1)}
  next_idx = [2]
  stop = threading.Event()
  timeline = []           # the supervisor's own audit trail

  # Burn math (slow window 90 s, burn needs >= half the samples bad):
  # a dip lasts reap (8 s) + controller (<1 s) + replacement spawn-to-
  # handshake (~8-10 s) ~= 17 s, so every dip must start with > 17 s
  # of healthy samples banked since the previous one.
  warm_secs = 22.0
  heal_wait_secs = 25.0
  max_seconds = 95.0 if smoke else 140.0
  pod_path = os.path.join(logdir, 'POD_TARGET.json')

  def _live_rows():
    rows = _read_jsonl(os.path.join(logdir, 'summaries.jsonl'))
    return [r['value'] for r in rows
            if r.get('tag') == 'remote_live_hosts']

  def _alive():
    return [i for i, p in children.items() if p.poll() is None]

  def _wait_live(n, deadline):
    while not stop.is_set() and time.monotonic() < deadline:
      vals = _live_rows()
      if vals and vals[-1] >= n:
        return True
      stop.wait(0.3)
    return False

  def _reconcile_until_live(n, deadline):
    """The grow-only cluster supervisor: spawn a replacement host
    whenever the controller's declared target exceeds the live pod,
    until the gauge has DIPPED below `n` and recovered — a pre-dip
    reading of n must not count as healed (the reap takes seconds;
    the gauge still shows the dead host until then)."""
    start = len(_live_rows())
    while not stop.is_set() and time.monotonic() < deadline:
      try:
        with open(pod_path) as f:
          target = int(json.load(f)['target_hosts'])
      except (OSError, ValueError, KeyError):
        target = None
      if (target is not None and target > len(_alive())
          and next_idx[0] < 5):
        idx = next_idx[0]
        next_idx[0] += 1
        children[idx] = _spawn_host(idx)
        timeline.append(
            {'event': 'replacement_spawned', 'host': idx,
             'target': target,
             'wall': round(time.monotonic() - t0, 2)})
      since = _live_rows()[start:]
      dip = next((i for i, v in enumerate(since) if v < n), None)
      if dip is not None and any(v >= n for v in since[dip:]):
        return True
      stop.wait(0.3)
    return False

  def _harness(t0):
    deadline = t0 + max_seconds - 10.0
    if not _wait_live(2, deadline):
      timeline.append({'event': 'no_initial_quorum'})
      return
    timeline.append({'event': 'quorum', 'wall': round(
        time.monotonic() - t0, 2)})
    # Healthy warm-up: the slow window must hold more good samples
    # than the coming violation dip will add bad ones.
    if stop.wait(warm_secs):
      return
    victim = children[0]
    victim.kill()                       # SIGKILL: no goodbye
    timeline.append({'event': 'sigkill', 'host': 0,
                     'wall': round(time.monotonic() - t0, 2)})
    if not _reconcile_until_live(2, deadline):
      timeline.append({'event': 'no_heal_after_kill'})
      return
    timeline.append({'event': 'healed', 'wall': round(
        time.monotonic() - t0, 2)})
    if smoke:
      return
    # Cycle 2: the DELIBERATE exit. Let the window re-fill with
    # healthy samples, then drain a host via SIGTERM (the PR 6
    # quiesce path ends in the v9 'leave' announcement).
    if stop.wait(heal_wait_secs):
      return
    drain_idx = next(i for i in sorted(_alive()) if i != 0)
    children[drain_idx].send_signal(signal_lib.SIGTERM)
    timeline.append({'event': 'sigterm_drain', 'host': drain_idx,
                     'wall': round(time.monotonic() - t0, 2)})
    if _reconcile_until_live(2, deadline):
      timeline.append({'event': 'healed_after_drain', 'wall': round(
          time.monotonic() - t0, 2)})
    else:
      timeline.append({'event': 'no_heal_after_drain'})

  t0 = time.monotonic()
  harness = threading.Thread(target=_harness, args=(t0,), daemon=True)
  crash = None
  run = None
  try:
    harness.start()
    run = driver.train(cfg, max_seconds=max_seconds,
                       stall_timeout_secs=30.0)
  except BaseException as e:  # SLO: zero learner crashes
    crash = f'{type(e).__name__}: {e}'
  finally:
    stop.set()
    harness.join(timeout=10.0)
    for p in children.values():
      if p.poll() is None:
        p.terminate()
    for p in children.values():
      try:
        p.communicate(timeout=20)
      except subprocess.TimeoutExpired:
        p.kill()
        p.communicate()

  errors = []
  events = {t['event'] for t in timeline}
  results = {
      'smoke': smoke,
      'wall_secs': round(time.monotonic() - t0, 2),
      'crash': crash,
      'timeline': timeline,
  }
  if crash is not None:
    errors.append(f'learner crashed during the elastic drill: {crash}')
    return results, errors
  if 'sigkill' not in events:
    errors.append(f'the harness never reached the SIGKILL ({timeline})')
    return results, errors

  ing = run.ingest.stats()
  verdict = slo_lib.read_verdict(logdir)
  clog = controller_lib.read_log(logdir)
  incidents = _read_jsonl(os.path.join(logdir, 'incidents.jsonl'))
  left = [e for e in incidents if e['kind'] == 'host_left']
  joined = [e for e in incidents if e['kind'] == 'host_joined']
  results.update({
      'slo_verdict': None if verdict is None else {
          'pass': verdict.get('pass'),
          'violations': verdict.get('violations')},
      'controller_counts': None if clog is None else clog['counts'],
      'hosts_joined': ing.get('hosts_joined'),
      'hosts_left': ing.get('hosts_left'),
      'live_hosts_at_exit': ing.get('live_hosts'),
      'stale_epoch_rejected': ing.get('stale_epoch_rejected'),
      'host_left_reasons': sorted({e.get('reason') for e in left}),
  })

  # --- The headline: a host died, the replacement joined, the verdict
  # stayed green with zero human knob-turning.
  if 'healed' not in events:
    errors.append(f'the pod never healed after the SIGKILL: {timeline}')
  if verdict is None:
    errors.append('no SLO_VERDICT.json')
  else:
    if not verdict.get('pass'):
      errors.append(f"SLO verdict FAILED: {verdict.get('violations')}")
    pod = (verdict.get('objectives') or {}).get('pod_hosts') or {}
    if pod.get('burns', 0) != 0:
      errors.append(f"pod_hosts burned {pod.get('burns')}x — the "
                    'pod was down a host longer than the healthy '
                    'warm-up covered')
  # --- The controller moved the pod_size actuator, applied.
  if clog is None:
    errors.append('no CONTROLLER_LOG.json')
  else:
    pod_moves = [a for a in (clog.get('actions') or [])
                 if a['actuator'] == 'pod_size' and a['applied']]
    if not pod_moves:
      errors.append('the controller never applied a pod_size move')
  if not os.path.exists(pod_path):
    errors.append('no POD_TARGET.json — the actuator never declared '
                  'a target')
  else:
    with open(pod_path) as f:
      pod_target = json.load(f)
    results['pod_target'] = pod_target
    if pod_target.get('target_hosts', 0) < 2:
      errors.append(f'POD_TARGET.json target_hosts='
                    f"{pod_target.get('target_hosts')} < 2")
  if 'replacement_spawned' not in events:
    errors.append('the supervisor never spawned a replacement host')
  # --- The membership ledger's durable audit trail.
  if not any(e.get('reason') == 'lost' for e in left):
    errors.append(f'no host_left(lost) incident: {left}')
  if len(joined) < 3:
    errors.append(f'expected >= 3 host_joined incidents (2 initial + '
                  f'replacement), got {len(joined)}')
  if not smoke:
    if 'healed_after_drain' not in events:
      errors.append(f'the pod never healed after the drain: '
                    f'{timeline}')
    if not any(e.get('reason') == 'drain' for e in left):
      errors.append(f"no host_left(drain) incident — the SIGTERM'd "
                    f'host left without its leave announcement: '
                    f'{left}')
  # --- No epoch confusion: joins are fresh hellos, not stale traffic.
  if ing.get('stale_epoch_rejected', 0) != 0:
    errors.append(f"stale_epoch_rejected="
                  f"{ing.get('stale_epoch_rejected')} during a "
                  'membership-only drill')
  return results, errors


def _spawn_replica_child(overrides, port):
  """A serving replica as a child process: ingest listener + local
  InferenceServer with the v10 serving seam attached — the
  learner-host role minus the train loop (the storm prices routing
  and failover, not learning). Prints 'REPLICA_READY <json>' (bound
  port + core-state sizes) once serving; SIGTERM flips the draining
  notice and exits ~6 s later (the drain handoff window)."""
  env = dict(os.environ)
  env['JAX_PLATFORMS'] = 'cpu'
  existing = env.get('PYTHONPATH', '')
  env['PYTHONPATH'] = (REPO + os.pathsep + existing if existing
                       else REPO)
  body = (
      'import json, os, signal, sys, threading, time\n'
      'import numpy as np\n'
      'import jax\n'
      'from scalable_agent_tpu.config import Config\n'
      'from scalable_agent_tpu.models import ImpalaAgent, init_params\n'
      'from scalable_agent_tpu.models.instruction import '
      'MAX_INSTRUCTION_LEN\n'
      'from scalable_agent_tpu.runtime import remote, ring_buffer\n'
      'from scalable_agent_tpu.runtime.inference import '
      'InferenceServer\n'
      'cfg = Config(**json.loads(sys.argv[1]))\n'
      'num_actions = 9\n'
      'agent = ImpalaAgent(num_actions=num_actions, torso=cfg.torso,\n'
      '                    use_instruction=False)\n'
      "obs_spec = {'frame': (cfg.height, cfg.width, 3),\n"
      "            'instr_len': MAX_INSTRUCTION_LEN}\n"
      'params = init_params(agent, jax.random.PRNGKey(0), obs_spec)\n'
      'server = InferenceServer(agent, params, cfg, seed=7,\n'
      '                         fleet_size=1, pad_batch_to=1)\n'
      'server.update_params(params, version=1)\n'
      'ingest = remote.TrajectoryIngestServer(\n'
      '    ring_buffer.TrajectoryBuffer(2), jax.device_get(params),\n'
      "    host='127.0.0.1', port=int(sys.argv[2]),\n"
      '    contract=remote.trajectory_contract(cfg, agent,\n'
      '                                        num_actions),\n'
      '    wire_dtype=cfg.resolved_wire_dtype)\n'
      'ingest.attach_serving(server.serve_remote)\n'
      'core = [int(np.shape(c)[-1])\n'
      '        for c in server.initial_core_state()]\n'
      "print('REPLICA_READY ' + json.dumps(\n"
      "    {'port': ingest.port, 'core': core}), flush=True)\n"
      'def _term(signum, frame):\n'
      '  ingest.set_draining()\n'
      '  threading.Timer(6.0, lambda: os._exit(0)).start()\n'
      'signal.signal(signal.SIGTERM, _term)\n'
      'while True:\n'
      '  time.sleep(0.5)\n')
  return subprocess.Popen(
      [sys.executable, '-c', body, json.dumps(overrides), str(port)],
      cwd=REPO, env=env, stdout=subprocess.PIPE,
      stderr=subprocess.STDOUT, text=True)


def run_routed_storm(logdir: str, smoke: bool = SMOKE,
                     seed: int = SEED):
  """The routed-serving drill (round 21); returns (results, errors).

  Two serving replicas (real sockets, wire v10), one actor-side
  ServingRouter pumping inference batches through them. Mid-run one
  replica is SIGKILLed. Asserts: the router failed over (probation,
  not a crash), every post-kill batch was still served (zero
  NoReplicasAvailable), both replicas had served before the kill (the
  rotation was real), and the routed-latency SLO objective — judged
  by the SAME evaluator production uses — never burned. The full run
  adds the drain handoff: a replacement joins, the survivor is
  SIGTERM'd, and its 'draining' notice must pull it from the rotation
  while its in-flight traffic completes."""
  import signal as signal_lib
  import threading

  import numpy as np

  from scalable_agent_tpu import slo as slo_lib
  from scalable_agent_tpu import telemetry
  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.models import ImpalaAgent
  from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
  from scalable_agent_tpu.runtime import remote
  from scalable_agent_tpu.runtime import routing

  os.makedirs(logdir, exist_ok=True)
  cfg_kwargs = dict(
      height=24, width=32, torso='shallow', use_instruction=False,
      inference_min_batch=0, inference_max_batch=8,
      inference_timeout_ms=5, inference_state_cache=False,
      unroll_length=5, batch_size=2, seed=seed)
  cfg = Config(**cfg_kwargs)
  num_actions = 9
  agent = ImpalaAgent(num_actions=num_actions, torso=cfg.torso,
                      use_instruction=False)
  contract = remote.trajectory_contract(cfg, agent, num_actions)

  ports = [_free_port(), _free_port()]
  children = {i: _spawn_replica_child(cfg_kwargs, p)
              for i, p in enumerate(ports)}
  sinks = {i: [] for i in children}

  def _tail(proc, sink):
    for line in proc.stdout:
      sink.append(line)

  def _watch(idx):
    threading.Thread(target=_tail, args=(children[idx], sinks[idx]),
                     daemon=True).start()

  for i in children:
    _watch(i)

  def _ready_info(idx, deadline):
    while time.monotonic() < deadline:
      for line in list(sinks[idx]):
        if line.startswith('REPLICA_READY '):
          return json.loads(line[len('REPLICA_READY '):])
      if children[idx].poll() is not None:
        return None
      time.sleep(0.2)
    return None

  t0 = time.monotonic()
  errors = []
  timeline = []
  results = {'smoke': smoke, 'timeline': timeline}
  router = None
  try:
    # CPU jit compile dominates replica startup; be generous.
    deadline = time.monotonic() + 180.0
    infos = {i: _ready_info(i, deadline) for i in children}
    if any(v is None for v in infos.values()):
      dead = [i for i, v in infos.items() if v is None]
      errors.append(
          f'replica(s) {dead} never became ready: '
          + ' | '.join(''.join(sinks[i])[-500:] for i in dead))
      return results, errors
    core = infos[0]['core']
    addrs = {i: f"127.0.0.1:{infos[i]['port']}" for i in infos}
    timeline.append({'event': 'replicas_ready',
                     'wall': round(time.monotonic() - t0, 2)})

    # Short dial timeout: post-probation redials of a SIGKILLed
    # replica must fail fast (connection refused), not eat the
    # production 60 s backoff window inside the router's io_lock.
    def connect_fn(addr):
      return routing.connect_serving(addr, contract,
                                     connect_timeout_secs=1.5)

    router = routing.ServingRouter(list(addrs.values()), connect_fn,
                                   probation_secs=3.0)
    rng = np.random.RandomState(seed)
    b = 2
    payload = {
        'prev_action': np.zeros((b,), np.int32),
        'reward': np.zeros((b,), np.float32),
        'done': np.zeros((b,), np.bool_),
        'frame': rng.randint(0, 255,
                             (b, 24, 32, 3)).astype(np.uint8),
        'instr': np.zeros((b, MAX_INSTRUCTION_LEN), np.int32),
        'core_c': np.zeros((b, core[0]), np.float32),
        'core_h': np.zeros((b, core[1]), np.float32),
    }

    # Warm-up: every replica must serve at least once (each pays its
    # serve_remote first-call compile here, OFF the judged window).
    warm_deadline = time.monotonic() + 120.0
    while time.monotonic() < warm_deadline:
      router.infer(payload)
      serves = {r['address']: r['serves']
                for r in router.stats()['replicas']}
      if all(v > 0 for v in serves.values()):
        break
    else:
      errors.append(f'warm-up starved a replica: {router.stats()}')
      return results, errors
    timeline.append({'event': 'warm',
                     'wall': round(time.monotonic() - t0, 2)})

    # The judged pump: the routed-latency objective production ships
    # (slo.py serving_latency_p99_ms is the server-side half; this is
    # the actor-side route view) over THIS process's registry.
    objective = slo_lib.Objective(
        name='routed_latency_p99_ms', metric='serving/route_ms',
        field='p99', comparison='<=', target=5000.0,
        severity='ticket', fast_window_secs=2.0,
        slow_window_secs=30.0,
        description='actor-side routed inference latency p99 (ms)')
    evaluator = slo_lib.SloEvaluator([objective])
    starvation = 0
    served = {'pre_kill': 0, 'post_kill': 0, 'post_drain': 0}
    phase = ['pre_kill']
    last_obs = [0.0]

    def _pump(secs):
      end = time.monotonic() + secs
      while time.monotonic() < end:
        try:
          router.infer(payload)
          served[phase[0]] += 1
        except routing.NoReplicasAvailable:
          nonlocal_starvation[0] += 1
        now = time.time()
        if now - last_obs[0] >= 0.25:
          last_obs[0] = now
          evaluator.observe(telemetry.registry().snapshot(), now)
        time.sleep(0.02)

    nonlocal_starvation = [0]
    _pump(4.0)
    victim = children[0]
    victim.kill()                      # SIGKILL: no draining notice
    timeline.append({'event': 'sigkill', 'replica': addrs[0],
                     'wall': round(time.monotonic() - t0, 2)})
    phase[0] = 'post_kill'
    _pump(8.0)

    if not smoke:
      # Drain handoff: replacement joins, survivor drains out.
      new_port = _free_port()
      children[2] = _spawn_replica_child(cfg_kwargs, new_port)
      sinks[2] = []
      _watch(2)
      info = _ready_info(2, time.monotonic() + 180.0)
      if info is None:
        errors.append('replacement replica never became ready')
        return results, errors
      addrs[2] = f"127.0.0.1:{info['port']}"
      router.add_replica(addrs[2])
      timeline.append({'event': 'replacement_joined',
                       'wall': round(time.monotonic() - t0, 2)})
      children[1].send_signal(signal_lib.SIGTERM)
      timeline.append({'event': 'sigterm_drain', 'replica': addrs[1],
                       'wall': round(time.monotonic() - t0, 2)})
      phase[0] = 'post_drain'
      _pump(8.0)

    starvation = nonlocal_starvation[0]
    rstats = router.stats()
    verdict = evaluator.verdict()
    with open(os.path.join(logdir, 'SLO_VERDICT.json'), 'w') as f:
      json.dump(verdict, f, indent=2, sort_keys=True)
    results.update({
        'wall_secs': round(time.monotonic() - t0, 2),
        'served': dict(served),
        'starvation': starvation,
        'router': rstats,
        'slo_verdict': {'pass': verdict.get('pass'),
                        'violations': verdict.get('violations')},
    })

    # --- The headline: the kill cost its in-flight request at most;
    # everything after was served, and the verdict stayed green.
    if served['post_kill'] == 0:
      errors.append('no traffic served after the SIGKILL')
    if starvation:
      errors.append(f'router starved {starvation}x '
                    '(NoReplicasAvailable after warm-up)')
    if rstats['route_failovers'] < 1:
      errors.append('the kill never exercised the failover path '
                    f'(failovers={rstats["route_failovers"]})')
    if not verdict.get('pass'):
      errors.append(f"routed SLO verdict FAILED: "
                    f"{verdict.get('violations')}")
    by_addr = {r['address']: r for r in rstats['replicas']}
    if not smoke:
      if served['post_drain'] == 0:
        errors.append('no traffic served after the drain')
      drained = by_addr.get(addrs[1], {})
      if not drained.get('draining'):
        errors.append(f'the SIGTERM\'d replica never advertised '
                      f'draining: {drained}')
      if by_addr.get(addrs[2], {}).get('serves', 0) == 0:
        errors.append('the replacement replica never served')
    return results, errors
  finally:
    if router is not None:
      router.close()
    for p in children.values():
      if p.poll() is None:
        p.terminate()
    for p in children.values():
      try:
        p.communicate(timeout=20)
      except subprocess.TimeoutExpired:
        p.kill()
        p.communicate()


def _run_corruption_subprocess():
  """CHAOS_STORM=all path: the corruption storm needs its own process
  (XLA device-count flags must precede the jax import, and the other
  storms' shapes must stay single-device)."""
  out_path = os.path.join(tempfile.mkdtemp(prefix='chaos_corr_'),
                          'CHAOS_CORR.json')
  env = dict(os.environ)
  env['CHAOS_STORM'] = 'corruption'
  env['CHAOS_OUT'] = out_path
  proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                        cwd=REPO, env=env, stdout=subprocess.PIPE,
                        stderr=subprocess.STDOUT, text=True,
                        timeout=900)
  try:
    with open(out_path) as f:
      sub = json.load(f)
    return (sub.get('corruption', {}),
            [e for e in sub.get('slo_violations', [])])
  except (OSError, ValueError):
    return ({'tail': proc.stdout[-800:] if proc.stdout else ''},
            [f'corruption subprocess produced no report '
             f'(exit {proc.returncode})'])


def main():
  which = os.environ.get('CHAOS_STORM', 'all')
  results = {}
  errors = []
  if which in ('all', 'fault'):
    with tempfile.TemporaryDirectory(prefix='chaos_') as logdir:
      storm_results, storm_errors = run_storm(logdir)
    results.update(storm_results)  # top-level keys: the r7 layout
    errors += storm_errors
  if which in ('all', 'overload'):
    with tempfile.TemporaryDirectory(prefix='chaos_ovl_') as logdir:
      results['overload'], overload_errors = run_overload_storm(logdir)
    errors += [f'overload: {e}' for e in overload_errors]
  if which in ('all', 'partition'):
    with tempfile.TemporaryDirectory(prefix='chaos_part_') as logdir:
      results['partition'], partition_errors = \
          run_partition_storm(logdir)
    errors += [f'partition: {e}' for e in partition_errors]
  if which in ('all', 'controller'):
    with tempfile.TemporaryDirectory(prefix='chaos_ctrl_') as logdir:
      results['controller'], controller_errors = \
          run_controller_storm(logdir)
    errors += [f'controller: {e}' for e in controller_errors]
  if which == 'elastic':
    # Dedicated invocation only (the ci.sh elastic lane): the drill's
    # wall clock is dominated by real host replacement — folding it
    # into CHAOS_STORM=all would double the default storm budget.
    with tempfile.TemporaryDirectory(prefix='chaos_elastic_') as logdir:
      results['elastic'], elastic_errors = run_elastic_storm(logdir)
    errors += [f'elastic: {e}' for e in elastic_errors]
  if which == 'routed':
    # Dedicated invocation only (the ci.sh serving lane): replica
    # startup is real-process jit compile — folding it into
    # CHAOS_STORM=all would stretch the default storm budget.
    with tempfile.TemporaryDirectory(prefix='chaos_routed_') as logdir:
      results['routed'], routed_errors = run_routed_storm(logdir)
    errors += [f'routed: {e}' for e in routed_errors]
  if which == 'corruption':
    with tempfile.TemporaryDirectory(prefix='chaos_corr_') as logdir:
      results['corruption'], corruption_errors = \
          run_corruption_storm(logdir)
    errors += [f'corruption: {e}' for e in corruption_errors]
  elif which == 'all':
    # Own process: the SDC leg needs XLA's device-count flag set
    # before jax imports, which this (already-imported) process and
    # the other storms' single-device shapes cannot absorb.
    results['corruption'], corruption_errors = \
        _run_corruption_subprocess()
    errors += [f'corruption: {e}' for e in corruption_errors]
  results['slo_violations'] = errors
  results['ok'] = not errors
  with open(OUT_PATH, 'w') as f:
    json.dump(results, f, indent=2, sort_keys=True)
  print(json.dumps({'chaos_ok': results['ok'],
                    'storms': which,
                    'wall_secs': results.get('wall_secs'),
                    'overload_wall_secs':
                        results.get('overload', {}).get('wall_secs'),
                    'partition_wall_secs':
                        results.get('partition', {}).get('wall_secs'),
                    'controller_wall_secs':
                        results.get('controller', {}).get('wall_secs'),
                    'elastic_wall_secs':
                        results.get('elastic', {}).get('wall_secs'),
                    'routed_wall_secs':
                        results.get('routed', {}).get('wall_secs'),
                    'corruption_wall_secs':
                        results.get('corruption', {}).get('wall_secs'),
                    'violations': errors,
                    'out': OUT_PATH}))
  if errors:
    sys.exit(1)


if __name__ == '__main__':
  main()
