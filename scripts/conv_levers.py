"""Conv-torso byte/time levers, measured (VERDICT r4 next-round #1).

The flagship step is HBM-bound and the conv torso's backward is ~87%
of all bytes (docs/PERF.md byte attribution); the section-1 pre-pool
activation ([3232, 72, 96, 16] bf16 = 715 MB) is the single biggest
tensor. This script measures each candidate lever in isolation at
flagship shapes — step time via async chains with one value-readback
barrier, bytes/FLOPs via XLA cost_analysis — so each can be taken or
rejected with numbers, remat-style:

  s1_baseline      conv3x3(3->16) + maxpool3x3/2 (the parity model)
  s1_strided       conv3x3/2 (the 'deep_fast' section form)
  s1_argmax_idx    custom-VJP conv+pool: backward rebuilds the sparse
                   pool gradient from stored uint8 argmax indices
                   instead of re-reading the 715 MB pre-pool tensor
  torso_baseline   full deep torso fwd+bwd
  torso_deep_fast  full strided-conv torso fwd+bwd
  torso_nchw       full deep torso computed in NCHW dimension numbers
                   (layout sweep: does XLA's TPU emitter prefer it?)

Usage: python scripts/conv_levers.py          # real chip
       SMOKE=1 python scripts/conv_levers.py  # CPU mechanics check

Prints one JSON line per variant + a summary table.
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SMOKE = os.environ.get('SMOKE') == '1'

import jax  # noqa: E402

if SMOKE:
  jax.config.update('jax_platforms', 'cpu')

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402


def _timed(fn, args, n=None):
  """(seconds/call, bytes, flops) for a jitted fn — one readback as
  the barrier (docs/PERF.md: block_until_ready can lie through the
  tunnel)."""
  n = n if n is not None else (2 if SMOKE else 20)
  jfn = jax.jit(fn)
  out = jfn(*args)
  float(jax.tree_util.tree_leaves(out)[0].ravel()[0])  # compile+sync
  t0 = time.perf_counter()
  for _ in range(n):
    out = jfn(*args)
  float(jax.tree_util.tree_leaves(out)[0].ravel()[0])
  dt = (time.perf_counter() - t0) / n
  cost = jfn.lower(*args).compile().cost_analysis()
  if isinstance(cost, list):  # older jax returns [dict]
    cost = cost[0]
  return dt, cost.get('bytes accessed', float('nan')), cost.get(
      'flops', float('nan'))


def _loss_grad(apply_fn, params, x):
  """Scalar-loss fwd+bwd through apply_fn, grads w.r.t. params — the
  shape of traffic the train step's backward produces."""

  def loss(p):
    y = apply_fn(p, x)
    return jnp.sum(y.astype(jnp.float32) ** 2)

  return jax.grad(loss)(params)


# --- Section-1 variants (conv 3->16 at 72x96 + 2x spatial reduction) --

def _conv(x, w, b, strides=(1, 1)):
  # Plain bf16 conv, exactly like flax nn.Conv(dtype=bf16) in the
  # torso (a preferred_element_type=f32 accumulate makes the conv's
  # transpose rule mix dtypes under grad).
  y = lax.conv_general_dilated(
      x, w, window_strides=strides, padding='SAME',
      dimension_numbers=('NHWC', 'HWIO', 'NHWC'))
  return y + b


def s1_baseline(params, frames):
  x = frames.astype(jnp.bfloat16) / 255.0
  y = _conv(x, params['w'], params['b'])
  return lax.reduce_window(y, -jnp.inf, lax.max, (1, 3, 3, 1),
                           (1, 2, 2, 1), 'SAME')


def s1_strided(params, frames):
  x = frames.astype(jnp.bfloat16) / 255.0
  return _conv(x, params['w'], params['b'], strides=(2, 2))


# Custom-VJP conv+pool: save (frames, w, argmax idx) — NOT the 715 MB
# pre-pool tensor. Backward scatters the pooled gradient through the
# stored indices and runs the conv wgrad against that sparse tensor.
@jax.custom_vjp
def s1_argmax(w, b, frames):
  pooled, _ = _s1_argmax_fwd_impl(w, b, frames)
  return pooled


def _pool_views(y):
  """The 9 shifted strided views of SAME-padded y: [9, N, Ho, Wo, C].

  XLA's SAME padding for (window 3, stride 2, even size) is
  ASYMMETRIC — pad_lo=0, pad_hi=1 (total pad = (Ho-1)*2+3-H = 1), so
  window i covers rows 2i..2i+2."""
  n, h, wd, c = y.shape
  ho, wo = h // 2, wd // 2
  yp = jnp.pad(y, ((0, 0), (0, 1), (0, 1), (0, 0)),
               constant_values=-jnp.inf)
  views = []
  for dy in range(3):
    for dx in range(3):
      views.append(lax.slice(yp, (0, dy, dx, 0),
                             (n, dy + 2 * (ho - 1) + 1,
                              dx + 2 * (wo - 1) + 1, c),
                             (1, 2, 2, 1)))
  return jnp.stack(views)


def _s1_argmax_fwd_impl(w, b, frames):
  x = frames.astype(jnp.bfloat16) / 255.0
  y = _conv(x, w, b)
  views = _pool_views(y)
  idx = jnp.argmax(views, axis=0).astype(jnp.uint8)
  pooled = jnp.max(views, axis=0)
  return pooled, idx


def _s1_argmax_fwd(w, b, frames):
  pooled, idx = _s1_argmax_fwd_impl(w, b, frames)
  return pooled, (w, b, frames, idx)


def _s1_argmax_bwd(res, g):
  w, b, frames, idx = res
  n, ho, wo, c = g.shape
  h, wd = 2 * ho, 2 * wo
  # Rebuild the sparse pre-pool gradient from the indices: for each of
  # the 9 window taps, the pooled grad lands at that tap's strided
  # position iff it was the argmax. Strided writes are expressed as
  # interior-dilated pads (stride-2 grid), offset by (dy, dx); the 9
  # planes sum into the conv-output gradient.
  planes = []
  for k in range(9):
    dy, dx = divmod(k, 3)
    contrib = jnp.where(idx == k, g, 0)
    # Tap (dy, dx) of window (i, j) sits at row 2i+dy, col 2j+dx in
    # the (0, 1)-padded frame (see _pool_views): interior-dilate by 2
    # and offset by (dy, dx) into the [h+1, w+1] padded grid.
    dilated = lax.pad(contrib, jnp.zeros((), g.dtype),
                      ((0, 0, 0),
                       (dy, (h + 1) - (dy + 2 * (ho - 1) + 1), 1),
                       (dx, (wd + 1) - (dx + 2 * (wo - 1) + 1), 1),
                       (0, 0, 0)))
    planes.append(dilated)
  dyp = functools.reduce(jnp.add, planes)
  dy_conv = dyp[:, :h, :wd, :]
  # Conv wgrad/bias-grad against the sparse gradient (frames are
  # integer — no dgrad exists for the input).
  x = frames.astype(jnp.bfloat16) / 255.0
  _, vjp = jax.vjp(lambda w_, b_: _conv(x, w_, b_), w, b)
  dw, db = vjp(dy_conv)
  return dw, db, None


s1_argmax.defvjp(_s1_argmax_fwd, _s1_argmax_bwd)


def s1_argmax_apply(params, frames):
  return s1_argmax(params['w'], params['b'], frames)


# --- Full-torso variants ---------------------------------------------

def _torso_apply(torso_name):
  from scalable_agent_tpu.models.torsos import TORSOS

  def apply_fn(params, frames):
    return TORSOS[torso_name](dtype=jnp.bfloat16).apply(params, frames)

  return apply_fn


def _torso_params(torso_name, frames):
  from scalable_agent_tpu.models.torsos import TORSOS
  return TORSOS[torso_name](dtype=jnp.bfloat16).init(
      jax.random.PRNGKey(0), frames)


def _nchw_full_apply(params, frames):
  """NCHW deep torso using the NHWC-initialized param tree (flax
  names: Conv_0..2 are the section convs, ResidualBlock_0..5 each hold
  Conv_0/Conv_1, Dense_0 is the projection)."""
  p = params['params']
  x = frames.astype(jnp.bfloat16) / 255.0
  x = jnp.transpose(x, (0, 3, 1, 2))

  def conv(x, cp, strides=(1, 1)):
    y = lax.conv_general_dilated(
        x, cp['kernel'].astype(x.dtype), window_strides=strides,
        padding='SAME',
        dimension_numbers=('NCHW', 'HWIO', 'NCHW'))
    return y + cp['bias'].astype(x.dtype)[None, :, None, None]

  rb = 0
  for section in range(3):
    x = conv(x, p[f'Conv_{section}'])
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 1, 3, 3),
                          (1, 1, 2, 2), 'SAME')
    for _ in range(2):
      y = jax.nn.relu(x)
      y = conv(y, p[f'ResidualBlock_{rb}']['Conv_0'])
      y = jax.nn.relu(y)
      y = conv(y, p[f'ResidualBlock_{rb}']['Conv_1'])
      x = x + y
      rb += 1
  x = jax.nn.relu(x)
  # Match NHWC flatten order so Dense_0 weights mean the same thing.
  x = jnp.transpose(x, (0, 2, 3, 1))
  x = x.reshape((x.shape[0], -1))
  d = p['Dense_0']
  x = (x @ d['kernel'] + d['bias']).astype(jnp.bfloat16)
  return jax.nn.relu(x)


def main():
  merged = 404 if SMOKE else 3232  # (T+1)*B at flagship = 101*32
  h, w = (24, 32) if SMOKE else (72, 96)
  rng = np.random.RandomState(0)
  frames = jnp.asarray(
      rng.randint(0, 255, (merged, h, w, 3)), jnp.uint8)

  key = jax.random.PRNGKey(0)
  s1_params = {
      'w': jax.random.normal(key, (3, 3, 3, 16), jnp.bfloat16) * 0.1,
      'b': jnp.zeros((16,), jnp.bfloat16),
  }

  results = {}

  def measure(name, apply_fn, params):
    dt, nbytes, flops = _timed(
        lambda p, x: _loss_grad(apply_fn, p, x), (params, frames))
    results[name] = {
        'ms': round(dt * 1e3, 2),
        'gb': round(nbytes / 1e9, 2),
        'tflop': round(flops / 1e12, 3),
    }
    print(json.dumps({'variant': name, **results[name]}), flush=True)

  # Parity check first (SMOKE and chip): the argmax-idx backward must
  # match autodiff through the baseline exactly (same max-tie policy:
  # argmax picks the first max, like reduce_window's select).
  g_base = _loss_grad(s1_baseline, s1_params, frames)
  g_idx = _loss_grad(s1_argmax_apply, s1_params, frames)
  dw_err = float(jnp.max(jnp.abs(
      g_base['w'].astype(jnp.float32) - g_idx['w'].astype(jnp.float32))))
  scale = float(jnp.max(jnp.abs(g_base['w'].astype(jnp.float32))))
  print(json.dumps({'check': 's1_argmax_vjp_parity',
                    'max_abs_err': dw_err, 'grad_scale': scale}),
        flush=True)
  # Gate, not just telemetry (ADVICE r5 — CI runs the SMOKE path and
  # previously only PRINTED this number): same tolerance discipline as
  # scripts/pallas_conv_pool.py — bit-exact in SMOKE (both paths share
  # the same max-tie policy and CPU lowering; measured 0.0), a few
  # bf16 ulps relative to the gradient's own scale on the chip.
  tol = 1e-6 if SMOKE else 0.02 * scale
  assert dw_err <= tol, (
      f's1_argmax VJP parity broke: max_abs_err {dw_err} > tol {tol} '
      f'(grad_scale {scale})')

  measure('s1_baseline', s1_baseline, s1_params)
  measure('s1_strided', s1_strided, s1_params)
  measure('s1_argmax_idx', s1_argmax_apply, s1_params)

  deep_params = _torso_params('deep', frames)
  measure('torso_baseline', _torso_apply('deep'), deep_params)
  measure('torso_nchw', _nchw_full_apply, deep_params)
  from scalable_agent_tpu.models.torsos import TORSOS
  if 'deep_fast' in TORSOS:
    fast_params = _torso_params('deep_fast', frames)
    measure('torso_deep_fast', _torso_apply('deep_fast'), fast_params)

  print(json.dumps({'summary': results}))


if __name__ == '__main__':
  main()
