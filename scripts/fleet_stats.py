"""Live learner telemetry from the v8 `stats` control-lane request.

    python scripts/fleet_stats.py HOST:PORT [--json] [--filter ingest]

Connects to a RUNNING learner's trajectory ingest port (the same one
actor hosts use), issues the round-13 `stats` request
(RemoteActorClient.fetch_stats), and pretty-prints the reply — the
unified metrics-registry snapshot plus the ingest server's stats
surface — for live operator debugging: no logdir access, no restart,
no summaries.jsonl dig. Histograms render as count/p50/p99/max rows;
`--filter` substring-matches names; `--json` dumps the raw reply.

The request rides a real handshake-free connection: `stats` is served
on the trajectory lane before any contract is offered, so this tool
never has to know the run's env/agent shapes.
"""

import argparse
import json
import math
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _fmt(v):
  if v is None:
    return '-'
  if isinstance(v, float):
    if math.isnan(v):
      return '-'
    return f'{v:.3f}'.rstrip('0').rstrip('.')
  return str(v)


def render(stats, name_filter=''):
  out = []
  w = out.append
  registry = stats.get('registry') or {}
  ingest = stats.get('ingest') or {}
  w('== metrics registry (%d names) ==' % len(registry))
  scalars = {}
  hists = {}
  for name, value in registry.items():
    if name_filter and name_filter not in name:
      continue
    (hists if isinstance(value, dict) else scalars)[name] = value
  for name in sorted(scalars):
    w(f'  {name:<44} {_fmt(scalars[name])}')
  if hists:
    w(f"  {'-- histograms --':<44} "
      f"{'count':>8} {'p50':>10} {'p99':>10} {'max':>10}")
    for name in sorted(hists):
      h = hists[name]
      w(f"  {name:<44} {_fmt(h.get('count')):>8} "
        f"{_fmt(h.get('p50')):>10} {_fmt(h.get('p99')):>10} "
        f"{_fmt(h.get('max')):>10}")
  w('')
  w('== ingest server ==')
  for key in sorted(ingest):
    value = ingest[key]
    if name_filter and name_filter not in key:
      continue
    if isinstance(value, dict):
      w(f'  {key}:')
      for sub in sorted(value):
        w(f'    {sub:<42} {_fmt(value[sub])}')
    else:
      w(f'  {key:<44} {_fmt(value)}')
  return '\n'.join(out)


def fetch(address, connect_timeout_secs=10.0):
  """One fetch_stats round trip against a live learner. Separated
  from main() so the smoke test can drive it against an in-process
  ingest server."""
  from scalable_agent_tpu.runtime import remote
  client = remote.RemoteActorClient(
      address, connect_timeout_secs=connect_timeout_secs)
  try:
    return client.fetch_stats()
  finally:
    client.close()


def main(argv=None):
  parser = argparse.ArgumentParser(
      description='pretty-print a live learner\'s v8 stats reply '
                  '(registry + ingest)')
  parser.add_argument('address', help='learner ingest HOST:PORT')
  parser.add_argument('--json', action='store_true',
                      help='dump the raw reply as JSON instead')
  parser.add_argument('--filter', default='',
                      help='substring filter on metric/stat names')
  parser.add_argument('--timeout', type=float, default=10.0,
                      help='connect timeout seconds')
  args = parser.parse_args(argv)
  try:
    stats = fetch(args.address, connect_timeout_secs=args.timeout)
  except Exception as e:
    print(f'could not fetch stats from {args.address!r}: {e}',
          file=sys.stderr)
    return 1
  if args.json:
    print(json.dumps(stats, indent=2, sort_keys=True, default=str))
  else:
    print(render(stats, name_filter=args.filter))
  return 0


if __name__ == '__main__':
  sys.exit(main())
