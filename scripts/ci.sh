#!/usr/bin/env bash
# CI entry: everything the repo can verify without real simulators.
# (The reference ships no CI at all — SURVEY §4 "no CI config"; this is
# the do-better path.) Exits nonzero on any failure.
set -euo pipefail
cd "$(dirname "$0")/.."

echo '== native batcher: build + stress test =='
make -C scalable_agent_tpu/ops/batcher clean all test

echo '== native batcher: ThreadSanitizer =='
make -C scalable_agent_tpu/ops/batcher tsan-test

echo '== unit + integration tests (CPU, 8 virtual devices) =='
python -m pytest tests/ -q

echo '== multi-chip sharding dry-run =='
python __graft_entry__.py

echo '== bench smoke (mechanics only, tiny shapes) =='
BENCH_SMOKE=1 python bench.py

echo '== soak smoke (mechanics only: popart/pc stack runs, tiny shapes;'
echo '   the real flagship soak is scripts/soak.py on the chip) =='
SOAK_SMOKE=1 python scripts/soak.py

echo '== churn-soak smoke (env kill + respawn + resource sampling'
echo '   mechanics; the real >=20 min churn soak runs on the chip) =='
SOAK_SMOKE=1 SOAK_CHURN=1 python scripts/soak.py

echo '== chaos smoke (deterministic fault storm: env hang/crash +'
echo '   socket garbage + NaN burst + interrupted save; asserts zero'
echo '   learner crashes, >=1 rollback, monotone frames — <60 s) =='
CHAOS_SMOKE=1 CHAOS_STORM=fault python scripts/chaos.py

echo '== overload-chaos smoke (fleet at 2x inference slots under shed'
echo '   admission + slow-learner backpressure + REAL mid-storm'
echo '   SIGTERM -> drain -> verified checkpoint + resume manifest ->'
echo '   resume parity; plus the drain/resume + admission selector'
echo '   and the tiny 1x/2x/4x shed-rate bench rows — <60 s CPU) =='
CHAOS_SMOKE=1 CHAOS_STORM=overload python scripts/chaos.py
JAX_PLATFORMS=cpu python -m pytest tests/test_overload.py -q \
  -k 'drain or admission or shed or waitlist or staleness' \
  -p no:cacheprovider
BENCH_SMOKE=1 BENCH_ONLY=overload python bench.py

echo '== partition-chaos smoke (remote feed under conn partition +'
echo '   delay faults, learner hard-killed (-9) mid-storm, restarted'
echo '   learner restores LAST_GOOD, fleet re-attaches within SLO,'
echo '   half-open peer reaped in budget, zero stale-epoch unrolls,'
echo '   zero wedged threads; plus the liveness/reattach selector'
echo '   — <90 s CPU) =='
CHAOS_SMOKE=1 CHAOS_STORM=partition python scripts/chaos.py
JAX_PLATFORMS=cpu python -m pytest tests/test_remote.py \
  tests/test_faults.py -q \
  -k 'reaped or heartbeat or busy or epoch or ping or partition or '\
'crash or unjoined or validate_transport' \
  -p no:cacheprovider

echo '== corruption-chaos smoke (the integrity plane end to end: a'
echo '   bit-flipped unroll refused before the buffer put + re-sent,'
echo '   a corrupt publish refused before install + refetched clean,'
echo '   an injected replica divergence detected + rolled back, and a'
echo '   bit-rotted committed checkpoint skipped via the digest'
echo '   ladder; plus the CRC/digest/SDC test selector — <90 s CPU) =='
CHAOS_SMOKE=1 CHAOS_STORM=corruption python scripts/chaos.py
JAX_PLATFORMS=cpu python -m pytest tests/test_remote.py \
  tests/test_checkpoint.py tests/test_health.py tests/test_faults.py \
  -q -k 'crc or digest or corrupt or bitflip or bitrot or sdc or '\
'fingerprint or discard or integrity' \
  -p no:cacheprovider

echo '== static-analysis lane (round 18: the invariant analyzer —'
echo '   the full contract-lint suite in scripts/lint.py replaces the'
echo '   old inline heredoc: metric names / SLO objectives /'
echo '   controller rules (the ported checks) + config-field flags,'
echo '   validate_* coverage, durable incident markers, protocol'
echo '   versions, summary scalars, the guarded_by lock-discipline'
echo '   AST pass, and the self-applied checker-inventory lint; then'
echo '   the seeded-violation self-tests (every checker proven able'
echo '   to fire) and the OrderedLock inversion-detector unit — the'
echo '   lint itself stays under ~20 s, docs/STATIC_ANALYSIS.md) =='
python scripts/lint.py
JAX_PLATFORMS=cpu python -m pytest tests/test_analysis.py -q \
  -p no:cacheprovider

echo '== slo lane (round 14: declarative objectives over the registry,'
echo '   burn-rate evaluation, triggered deep diagnostics, the'
echo '   SLO_VERDICT.json go/no-go artifact + slo_report regression'
echo '   gate; then a tiny driver run asserting the verdict lands with'
echo '   every default objective evaluated and zero captures on a'
echo '   clean run, and the tiny evaluator/capture bench rows — <90 s'
echo '   CPU) =='
JAX_PLATFORMS=cpu python -m pytest tests/test_slo.py -q \
  -p no:cacheprovider
JAX_PLATFORMS=cpu python - <<'SLO_EOF'
import json, logging, os, subprocess, sys, tempfile
logging.basicConfig(level=logging.WARNING)
sys.path.insert(0, os.getcwd())
from scalable_agent_tpu import driver, slo
from scalable_agent_tpu.config import Config
logdir = tempfile.mkdtemp(prefix='ci_slo_')
cfg = Config(logdir=logdir, env_backend='bandit', num_actors=2,
             batch_size=2, unroll_length=5, num_action_repeats=1,
             episode_length=4, height=24, width=32, torso='shallow',
             use_py_process=False, use_instruction=False,
             total_environment_frames=10**9, inference_timeout_ms=5,
             checkpoint_secs=0, summary_secs=0, seed=11)
driver.train(cfg, max_steps=6, stall_timeout_secs=60)
verdict = slo.read_verdict(logdir)
assert verdict is not None, 'no SLO_VERDICT.json from the clean run'
assert verdict['pass'], f"clean run verdict FAILED: {verdict['violations']}"
assert not verdict['captures'], 'clean run triggered captures'
expected = {o.name for o in slo.DEFAULT_OBJECTIVES}
got = set(verdict['objectives'])
assert got == expected, f'verdict objectives {got ^ expected} out of sync'
for name, e in verdict['objectives'].items():
    # info objectives are advisory leading indicators (round 15) — a
    # toy env-bound run may burn learner_plane_utilization without
    # failing anything.
    assert (e['state'] in ('ok', 'no_data', 'no_baseline')
            or e['severity'] == 'info'), (name, e)
# The go/no-go gate agrees: slo_report exits 0 on the passing verdict.
rc = subprocess.run([sys.executable, 'scripts/slo_report.py', logdir],
                    stdout=subprocess.DEVNULL).returncode
assert rc == 0, f'slo_report exited {rc} on a passing verdict'
print(f'slo lane OK: {len(got)} objectives evaluated, verdict PASS, '
      'zero captures, slo_report gate green')
SLO_EOF
BENCH_SMOKE=1 BENCH_ONLY=slo python bench.py

echo '== controller lane (round 15: the self-healing control plane —'
echo '   policy-table determinism, bounded escalate/revert with'
echo '   hysteresis, fleet elasticity + quarantine rehabilitation,'
echo '   then the load-surge storm: offered load doubles mid-run, the'
echo '   actuated run keeps SLO_VERDICT.json green with the'
echo '   escalation+revert in CONTROLLER_LOG.json while the observe'
echo '   run records the violation it avoided; plus the tiny'
echo '   tick-cost bench rows — <90 s CPU) =='
JAX_PLATFORMS=cpu python -m pytest tests/test_controller.py -q \
  -p no:cacheprovider
JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py \
  tests/test_replay.py tests/test_overload.py tests/test_slo.py \
  tests/test_remote.py -q \
  -k 'target_size or rehabilitat or probation or set_replay_k or '\
'set_admission or control_snapshot' \
  -p no:cacheprovider
CHAOS_SMOKE=1 CHAOS_STORM=controller python scripts/chaos.py
BENCH_SMOKE=1 BENCH_ONLY=controller python bench.py

echo '== anakin-runtime lane (round 16: the --runtime={fleet,anakin}'
echo '   axis — jittable env family semantics + mesh sharding, the'
echo '   hybrid filler (yield determinism, fresh-vs-filler frame'
echo '   accounting), then a tiny --runtime=anakin driver run'
echo '   asserting the full lifecycle artifacts land (SLO_VERDICT'
echo '   green, summaries/incidents JSONL, checkpoint restore), and'
echo '   the BENCH_ONLY=anakin smoke with the fed-reference + hybrid'
echo '   rows — <120 s CPU) =='
JAX_PLATFORMS=cpu python -m pytest tests/test_anakin.py \
  tests/test_filler.py -q -p no:cacheprovider
JAX_PLATFORMS=cpu python - <<'ANAKIN_EOF'
import json, logging, os, sys, tempfile
logging.basicConfig(level=logging.WARNING)
sys.path.insert(0, os.getcwd())
from scalable_agent_tpu import driver, slo
from scalable_agent_tpu.config import Config
logdir = tempfile.mkdtemp(prefix='ci_anakin_')
cfg = Config(logdir=logdir, runtime='anakin', env_backend='cue_memory',
             batch_size=4, unroll_length=5, num_action_repeats=1,
             height=24, width=32, torso='shallow', use_py_process=False,
             use_instruction=False, summary_secs=0, checkpoint_secs=0,
             total_environment_frames=6 * 4 * 5, seed=5)
run = driver.train(cfg)   # dispatches on --runtime
assert run.frames == 120, run.frames
verdict = slo.read_verdict(logdir)
assert verdict is not None, 'no SLO_VERDICT.json from the anakin run'
assert verdict['pass'], f"anakin verdict FAILED: {verdict['violations']}"
for stream in ('summaries.jsonl', 'incidents.jsonl', 'config.json'):
    assert os.path.exists(os.path.join(logdir, stream)), stream
# Checkpoint restore: a second run on the same logdir resumes at the
# already-met frame target instead of training from step 0.
run2 = driver.train(cfg)
assert run2.frames == 120, run2.frames
print('anakin lane OK: 6 fused steps, verdict PASS, restore green')
ANAKIN_EOF
XLA_FLAGS='--xla_force_host_platform_device_count=8' \
  BENCH_SMOKE=1 BENCH_ONLY=anakin python bench.py

echo '== multihost lane (round 17: the real multi-process runtime —'
echo '   2 OS processes join jax.distributed over gloo CPU collectives'
echo '   and run the FULL driver over one mesh: per-host fleets'
echo '   feeding process-local shards, the cross-process gradient'
echo '   psum, broadcast-gated collective checkpoints + the SIGKILL'
echo '   drill, the SDC all-gather rollback drill, cross-host trace'
echo '   joins, and the BENCH_ONLY=multihost scaling row; the'
echo '   validate_distributed/slot-placement unit half runs first.'
echo '   Round 19: the heavy drills (mixed topology, kill drills,'
echo '   cross-process TP) are slow-marked OUT of tier-1 and run HERE'
echo '   — the whole file, no -m filter — <600 s CPU) =='
JAX_PLATFORMS=cpu python -m pytest tests/test_multihost_unit.py -q \
  -p no:cacheprovider
# Children strip JAX_PLATFORMS/XLA_FLAGS themselves and force their
# own per-process virtual-device topology.
python -m pytest \
  tests/test_multihost.py \
  tests/test_multihost_extra.py \
  -q -p no:cacheprovider
BENCH_SMOKE=1 BENCH_ONLY=multihost python bench.py

echo '== elastic lane (round 20: elastic pod membership — the'
echo '   resharding edge-case unit tests + v9 membership-ledger units,'
echo '   the 2-proc -> 4-proc checkpoint-reshard parity drill, and the'
echo '   elastic storm smoke: SIGKILL an actor host mid-run, the'
echo '   controller raises POD_TARGET.json, the grow-only supervisor'
echo '   spawns the replacement, it JOINS the live learner, verdict'
echo '   green with zero knob-turning — <300 s CPU) =='
XLA_FLAGS='--xla_force_host_platform_device_count=8' \
  JAX_PLATFORMS=cpu python -m pytest tests/test_sharding.py -q \
  -k 'layout or reshard or topology' -p no:cacheprovider
JAX_PLATFORMS=cpu python -m pytest tests/test_remote.py -q \
  -k 'membership' -p no:cacheprovider
python -m pytest \
  "tests/test_multihost.py::test_reshard_checkpoint_2_to_4_processes" \
  -q -p no:cacheprovider
CHAOS_SMOKE=1 CHAOS_STORM=elastic python scripts/chaos.py

echo '== telemetry smoke (trace spans end to end: registry semantics,'
echo '   tracer pipeline, v8 negotiation + remote stamping,'
echo '   trace_report reconstruction; then the tiny tracing-on/off'
echo '   overhead rows via BENCH_ONLY=telemetry — <60 s CPU) =='
JAX_PLATFORMS=cpu python -m pytest tests/test_telemetry.py \
  tests/test_observability.py -q -p no:cacheprovider
BENCH_SMOKE=1 BENCH_ONLY=telemetry python bench.py

echo '== inference-plane smoke (state-cache golden parity + slot'
echo '   lifecycle selector, then the tiny cache×depth bench rows'
echo '   via BENCH_ONLY=inference_plane — <60 s CPU) =='
JAX_PLATFORMS=cpu python -m pytest tests/test_runtime.py \
  tests/test_parallel.py -q \
  -k 'state_cache or slot or inflight or version_gate or arena' \
  -p no:cacheprovider
BENCH_SMOKE=1 BENCH_ONLY=inference_plane python bench.py

echo '== learner-plane smoke (on-device assembly golden parity +'
echo '   failure paths + sharded Pallas V-trace parity selector, then'
echo '   the tiny {batch,unroll}×depth bench rows via'
echo '   BENCH_ONLY=learner_plane — <60 s CPU) =='
JAX_PLATFORMS=cpu python -m pytest tests/test_learner_plane.py \
  "tests/test_parallel.py::test_pallas_vtrace_sharded_step_matches_single_device" \
  -q -p no:cacheprovider
# 8 virtual devices: the vtrace_sharded row must exercise the
# multi-shard shard_map path here (the bench chip has 1 device).
XLA_FLAGS='--xla_force_host_platform_device_count=8' \
  BENCH_SMOKE=1 BENCH_ONLY=learner_plane python bench.py

echo '== sample-reuse smoke (circular replay tier + staged-arena'
echo '   re-serve lifecycle + IMPACT clipped-target parity selector,'
echo '   then the tiny replay_k x ratio rows + cue_memory curve run'
echo '   via BENCH_ONLY=replay — <60 s CPU) =='
JAX_PLATFORMS=cpu python -m pytest tests/test_replay.py \
  -q -k 'parity or tier or compos or validation or cadence' \
  -p no:cacheprovider
JAX_PLATFORMS=cpu python -m pytest tests/test_learner_plane.py \
  -q -k 'reserve or reuse' -p no:cacheprovider
BENCH_SMOKE=1 BENCH_ONLY=replay python bench.py

echo '== pixel-control fast-path parity (integer rewards + d2s head'
echo '   + bf16-Q levers vs the r5 reference forms — <60 s CPU) =='
JAX_PLATFORMS=cpu python -m pytest tests/test_unreal.py -q \
  -k 'parity or fast_path or bf16' -p no:cacheprovider

echo '== v5e-16 AOT memory-fit smoke (compiled per-device HBM check'
echo '   mechanics on 8 virtual devices; flagship check runs in the'
echo '   multi-chip dry-run artifact — <60 s CPU) =='
SMOKE=1 JAX_PLATFORMS=cpu python scripts/aot_fit.py

echo '== torso return-comparison smoke (deep vs deep_fast harness'
echo '   mechanics; the real head-to-head is scripts/compare_torsos.py'
echo '   on the chip) =='
SMOKE=1 JAX_PLATFORMS=cpu python scripts/compare_torsos.py

echo '== byte-attribution smoke (cost_analysis mechanics + the'
echo '   round-6 feature itemization rows) =='
SMOKE=1 python scripts/attribute_bytes.py

echo '== conv-lever smoke (variant mechanics + argmax-VJP parity) =='
SMOKE=1 python scripts/conv_levers.py

echo '== pallas fused conv+pool smoke (interpret-mode parity) =='
SMOKE=1 python scripts/pallas_conv_pool.py

echo '== sharding lane (round 19: the declarative registry as the one'
echo '   source of sharding truth — rule/guard/opt-clone semantics,'
echo '   the consumers-agree contract, the checkpoint manifest +'
echo '   cross-mesh resharded restore, and the 2D {data,model} deep-'
echo '   agent parity gate; then the DP vs DP+TP per-device bytes'
echo '   rows via BENCH_ONLY=mesh2d and the sharding-registry lint'
echo '   (no inline PartitionSpec outside parallel/sharding.py)'
echo '   — <2 min CPU) =='
JAX_PLATFORMS=cpu python -m pytest tests/test_sharding.py -q \
  -p no:cacheprovider
XLA_FLAGS='--xla_force_host_platform_device_count=8' \
  BENCH_SMOKE=1 BENCH_ONLY=mesh2d python bench.py
python scripts/lint.py --check sharding-registry

echo '== serving lane (round 21: the multi-tenant serving plane — the'
echo '   version-table/codec/AOT/routing/wire-v10 unit suite + the'
echo '   slow-marked 3-process routed drill, then the serving bench'
echo '   rows (int8 parity gate + wire bytes + publish/flip blackout'
echo '   + resident split) and the routed chaos storm: SIGKILL a'
echo '   serving replica under judged traffic, the router fails over'
echo '   with zero starvation and a green routed-latency verdict'
echo '   — <120 s CPU) =='
JAX_PLATFORMS=cpu python -m pytest tests/test_serving.py -q \
  -p no:cacheprovider
BENCH_SMOKE=1 BENCH_ONLY=serving python bench.py
CHAOS_SMOKE=1 CHAOS_STORM=routed python scripts/chaos.py

echo '== population lane (round 22: the population engine — in-graph'
echo '   curriculum sampler + mixed-fleet bucket-composition + PBT'
echo '   exploit/explore units, the slow learning-curve gate and the'
echo '   one-invocation two-suite population drills (no -m filter:'
echo '   the slow-marked curves run HERE), then a tiny real'
echo '   --runtime=anakin --curriculum=regret driver run asserting'
echo '   verdict PASS + per-level telemetry in summaries +'
echo '   CURRICULUM_LEVELS.json, and the BENCH_ONLY=population smoke'
echo '   (curriculum fps gate + padding-waste row) — <600 s CPU) =='
JAX_PLATFORMS=cpu python -m pytest tests/test_population.py -q \
  -p no:cacheprovider
JAX_PLATFORMS=cpu python - <<'POP_EOF'
import json, logging, os, sys, tempfile
logging.basicConfig(level=logging.WARNING)
sys.path.insert(0, os.getcwd())
from scalable_agent_tpu import driver, slo
from scalable_agent_tpu.config import Config
logdir = tempfile.mkdtemp(prefix='ci_pop_')
cfg = Config(logdir=logdir, runtime='anakin', env_backend='procgen',
             curriculum='regret', procgen_num_levels=4,
             batch_size=4, unroll_length=5, num_action_repeats=1,
             height=24, width=32, torso='shallow', use_py_process=False,
             use_instruction=False, summary_secs=0, checkpoint_secs=0,
             total_environment_frames=6 * 4 * 5, seed=7)
run = driver.train(cfg)
assert run.frames == 120, run.frames
verdict = slo.read_verdict(logdir)
assert verdict is not None and verdict['pass'], verdict
tags = set()
with open(os.path.join(logdir, 'summaries.jsonl')) as f:
    for line in f:
        tags.add(json.loads(line)['tag'])
for tag in ('curriculum_entropy', 'curriculum_levels_visited'):
    assert tag in tags, (tag, sorted(tags))
levels = json.load(open(os.path.join(logdir, 'CURRICULUM_LEVELS.json')))
assert levels['curriculum'] == 'regret'
assert len(levels['visits']) == 4 and sum(levels['visits']) > 0, levels
print('population lane OK: regret curriculum in-graph, verdict PASS, '
      'per-level telemetry landed')
POP_EOF
BENCH_SMOKE=1 BENCH_ONLY=population python bench.py

echo '== fused population + compile cache lane (round 23: vmapped PBT'
echo '   members in ONE Anakin program, on-device weight inheritance,'
echo '   persistent compilation cache — the compile-cache unit tests,'
echo '   a tiny N=2 fused driver run asserting PBT_LOG.json records'
echo '   vectorized=true + verdict PASS + per-member ladders, and a'
echo '   two-process cache smoke: process A compiles into a shared'
echo '   dir, process B proves a cache HIT via the jax monitoring'
echo '   events — <300 s CPU) =='
JAX_PLATFORMS=cpu python -m pytest tests/test_compile_cache.py -q \
  -p no:cacheprovider
JAX_PLATFORMS=cpu python - <<'FUSED_EOF'
import json, logging, os, sys, tempfile
logging.basicConfig(level=logging.WARNING)
sys.path.insert(0, os.getcwd())
from scalable_agent_tpu import driver, slo
from scalable_agent_tpu.config import Config
logdir = tempfile.mkdtemp(prefix='ci_fused_pop_')
cfg = Config(logdir=logdir, runtime='anakin', env_backend='gridworld',
             pbt_population=2, pbt_vectorized=True,
             pbt_suites='gridworld', pbt_round_frames=80,
             pbt_quantile=0.5, batch_size=4, unroll_length=4,
             num_action_repeats=1, height=24, width=32,
             torso='shallow', use_py_process=False,
             use_instruction=False, summary_secs=0, checkpoint_secs=0,
             total_environment_frames=160, seed=7)
run = driver.train(cfg)
log = json.load(open(os.path.join(logdir, 'PBT_LOG.json')))
assert log['vectorized'] is True, log
assert len(log['rounds']) == 2 and log['winner'] is not None, log
verdict = slo.read_verdict(logdir)
assert verdict is not None and verdict['pass'], verdict
for k in range(2):
    member = os.path.join(logdir, 'member_%02d' % k)
    assert os.listdir(os.path.join(member, 'checkpoints')), member
    assert os.path.exists(os.path.join(member, 'summaries.jsonl'))
print('fused population OK: one program, %d round(s), winner member '
      '%d, verdict PASS' % (len(log['rounds']),
                            log['winner']['member']))
FUSED_EOF
CACHE_DIR=$(mktemp -d)/ci_jax_cache
JAX_PLATFORMS=cpu CI_CACHE_DIR="$CACHE_DIR" CI_CACHE_PHASE=fill \
  python scripts/_compile_cache_smoke.py
JAX_PLATFORMS=cpu CI_CACHE_DIR="$CACHE_DIR" CI_CACHE_PHASE=hit \
  python scripts/_compile_cache_smoke.py

echo 'CI OK'
