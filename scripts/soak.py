"""Flagship stability soak (VERDICT r2 item 6; churn mode r3 W3).

The composition test proves the full extension stack RUNS; this proves
it is STABLE AND LEARNING over a sustained run: the real driver
pipeline (process-hosted envs → C++ batcher → buffer → prefetcher →
chip) with every flagship feature on at once — deep ResNet, 72×96
frames, bfloat16 compute, instruction encoder, PopArt, UNREAL pixel
control — on the contextual-bandit task, asserting over the whole run:

  - every logged total_loss is finite,
  - PopArt σ stays inside its clip bounds (a diverging value scale
    shows up there long before NaNs),
  - episode return IMPROVES (last-third mean > first-third mean) and
    beats the random baseline (~1/3 on 3-arm bandit).

SOAK_CHURN=1 additionally exercises the elasticity machinery under
sustained failure — the greenfield feature the reference never had
(its actors just die, SURVEY §5.3), so this is its proof of life
(VERDICT r3 W3):

  - every ~60 s one env process is SIGKILLed (fleet must respawn it
    and keep training),
  - a remote actor host (the production `--job_name=actor` CLI)
    feeds the learner over TCP; mid-run it is killed and a
    replacement spawned (ingest must accept the reconnect and remote
    unrolls must resume),
  - trimmed-RSS / thread-count / open-fd / python-allocated-block
    curves are sampled throughout; the Python-side curves must stay
    flat and per-step RSS growth must stay within 2× the measured
    ambient of the no-churn control (`_AMBIENT_RSS_MB_PER_STEP` —
    the plain train path grows natively on this host) — a slow leak
    in the respawn/reconnect paths would be invisible in short
    targeted tests.

Writes SOAK_r05.json at the repo root. Invocation (real chip):

    SOAK_CHURN=1 python scripts/soak.py        # ~20 min churn soak
    python scripts/soak.py                      # 10 min steady-state
    SOAK_SECONDS=1500 SOAK_CHURN=1 python scripts/soak.py
    SOAK_SMOKE=1 [SOAK_CHURN=1] python scripts/soak.py  # CPU mechanics

NOTE: a 600 s Bash timeout cannot fit the real runs (compiles eat
~2 min) — run detached and poll the artifact.

Learning hyperparameters: lr 5e-4 (≈ the paper's tuned 4.8e-4),
entropy 3e-3, γ=0 (the task is one-step). The smoke test's hotter
lr 2e-3 works for the SHALLOW torso but drives the deep ResNet into
a premature near-deterministic policy that solves only 2 of the 3
cues (measured: plateau at ~0.66 reward/step vs 1.0 at 5e-4) — the
flagship stack is what is under test, and at the paper-ish lr it
learns to optimal.
"""

import json
import multiprocessing
import os
import random
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _file_tail(path, n):
  """Last n bytes of a possibly large file, without slurping it."""
  if not os.path.exists(path):
    return ''
  with open(path, 'rb') as f:
    f.seek(0, os.SEEK_END)
    size = f.tell()
    f.seek(max(0, size - n))
    return f.read().decode('utf-8', errors='replace')


def _rss_mb():
  """Resident set AFTER malloc_trim: the churn pipeline allocates and
  frees multi-MB blocks (2.11 MB unrolls, 3.25 MB snapshot blobs, per
  publish/unroll) and glibc retains freed arena pages, so raw RSS
  creeps for minutes without any live-object growth. Trimming first
  makes the curve measure LIVE bytes — the thing a leak check is
  for — instead of allocator retention."""
  try:
    import ctypes
    ctypes.CDLL('libc.so.6').malloc_trim(0)
  except OSError:
    pass
  with open('/proc/self/status') as f:
    for line in f:
      if line.startswith('VmRSS:'):
        return int(line.split()[1]) / 1024.0
  return float('nan')


def _num_fds():
  return len(os.listdir('/proc/self/fd'))


def _spawn_remote_actor(cfg, port, log_path):
  """The production actor-host CLI (`--job_name=actor`), loopback.
  Flags cover every trajectory-contract field the soak config sets;
  both roles then derive identical contracts. Output goes to a FILE,
  not a PIPE: over a long soak the actor logs every param refresh and
  an undrained 64 KB pipe buffer would eventually block it inside a
  log write — a wedged feed misreported as an elasticity bug."""
  cmd = [
      sys.executable, os.path.join(REPO, 'experiment.py'),
      '--job_name=actor', '--task=0',
      f'--learner_address=127.0.0.1:{port}',
      f'--logdir={cfg.logdir}',
      '--env_backend=bandit', '--num_actors=2',
      f'--batch_size={cfg.batch_size}',
      f'--unroll_length={cfg.unroll_length}',
      '--num_action_repeats=1',
      f'--episode_length={cfg.episode_length}',
      f'--height={cfg.height}', f'--width={cfg.width}',
      f'--torso={cfg.torso}', f'--compute_dtype={cfg.compute_dtype}',
      '--use_instruction=true', '--use_popart=true',
      f'--pixel_control_cost={cfg.pixel_control_cost}',
      '--discounting=0.0',
      f'--inference_timeout_ms={cfg.inference_timeout_ms}',
      '--actor_reconnect_secs=120',
      f'--seed={cfg.seed + 50}',
  ]
  env = {k: v for k, v in os.environ.items()
         if k not in ('XLA_FLAGS', 'JAX_PLATFORMS')}
  existing = env.get('PYTHONPATH', '')
  env['PYTHONPATH'] = (REPO + os.pathsep + existing if existing
                       else REPO)
  log_file = open(log_path, 'a')
  try:
    return subprocess.Popen(cmd, cwd=REPO, env=env, stdout=log_file,
                            stderr=subprocess.STDOUT, text=True)
  finally:
    log_file.close()  # the child holds its own descriptor


def _wait_port(port, deadline, stop):
  """Block until the learner's ingest port accepts (it binds BEFORE
  the 20–40 s inference compile, so this resolves early). Bails out
  when `stop` is set — a learner that fails during setup must not
  leave this probing for the whole run duration."""
  while time.monotonic() < deadline and not stop.is_set():
    try:
      with socket.create_connection(('127.0.0.1', port), timeout=2):
        return True
    except OSError:
      stop.wait(1.0)
  return False


class Churn:
  """Background failure injector + resource sampler.

  Runs beside driver.train in the learner process: SIGKILLs one env
  child every `kill_every` seconds, drops and replaces the remote
  actor host once at ~55% of the run, samples trimmed-RSS/threads/
  fds/python-blocks every `sample_every` seconds. `stop()` ends it
  and reaps the child."""

  def __init__(self, cfg, port, seconds, smoke):
    self._cfg = cfg
    self._port = port
    self._seconds = seconds
    self._smoke = smoke
    self._stop = threading.Event()
    self.events = []
    self.samples = []  # (t, rss_mb, threads, fds, py_blocks)
    self.env_kills = 0
    self.port_probes = 0  # each probe counts in the server's conns
    self.actor_log = os.path.join(cfg.logdir, 'remote_actor.log')
    self._actor = None
    self._thread = threading.Thread(target=self._run,
                                    name='churn', daemon=True)

  def start(self):
    self._thread.start()

  def _event(self, what):
    self.events.append({'t': round(time.monotonic() - self._t0, 1),
                        'wall_time': round(time.time(), 3),
                        'event': what})

  def _kill_one_env(self):
    # Env processes are the mp (forkserver) children of THIS process;
    # the remote actor is a subprocess.Popen and so not in this list.
    children = multiprocessing.active_children()
    if not children:
      self._event('no env child to kill')
      return
    victim = random.choice(children)
    try:
      os.kill(victim.pid, signal.SIGKILL)
      self.env_kills += 1
      self._event(f'SIGKILL env pid {victim.pid}')
    except (OSError, AttributeError) as e:
      self._event(f'env kill failed: {e!r}')

  def _reap_actor(self):
    if self._actor is None:
      return
    try:
      self._actor.wait(timeout=10)
    except subprocess.TimeoutExpired:
      self._actor.kill()
      self._actor.wait()
    self._actor = None

  def _run(self):
    self._t0 = time.monotonic()
    grace = 20 if self._smoke else 120       # past compile/warmup
    kill_every = 8 if self._smoke else 60
    sample_every = 2 if self._smoke else 15
    use_remote = not self._smoke            # CLI child ~2 min to boot
    drop_at = self._seconds * 0.55
    next_kill = grace
    next_sample = 0.0
    dropped = False
    if use_remote:
      if _wait_port(self._port, self._t0 + self._seconds, self._stop):
        self.port_probes = 1
        self._actor = _spawn_remote_actor(self._cfg, self._port,
                                          self.actor_log)
        self._event('remote actor spawned')
      else:
        self._event('ingest port never opened')
    while not self._stop.wait(0.5):
      t = time.monotonic() - self._t0
      if t >= next_sample:
        self.samples.append((round(t, 1), round(_rss_mb(), 1),
                             threading.active_count(), _num_fds(),
                             sys.getallocatedblocks()))
        next_sample = t + sample_every
      if t >= next_kill:
        self._kill_one_env()
        next_kill = t + kill_every
      if use_remote and not dropped and t >= drop_at:
        dropped = True
        if self._actor is not None and self._actor.poll() is None:
          self._actor.kill()
          self._event('SIGKILL remote actor host')
        self._reap_actor()
        self._actor = _spawn_remote_actor(self._cfg, self._port,
                                          self.actor_log)
        self.drop_wall_time = time.time()
        self._event('replacement remote actor spawned')

  def stop(self):
    self._stop.set()
    self._thread.join(timeout=10)
    if self._actor is not None and self._actor.poll() is None:
      # Learner is down by now; the child's reconnect window would
      # just burn — end it.
      self._actor.kill()
    self._reap_actor()


# Measured ambient RSS growth of the PLAIN train path on this host —
# a 420 s no-churn/no-remote control run (same flagship config, RSS
# sampled after malloc_trim): 151 steps, ~840 MB post-warmup growth
# ≈ 5.6 MB/step, while sys.getallocatedblocks() stayed flat (+1%).
# The growth is NATIVE (TPU-tunnel/PJRT host buffers per step), not
# Python objects, and happens with the elasticity machinery entirely
# idle — so an absolute RSS-flatness gate can never pass here. The
# leak gate instead bounds per-step RSS growth at 2× this ambient
# constant (a churn-added leak of even a few MB/step trips it) and
# requires the PYTHON-side curves — allocated blocks, threads, fds —
# to stay genuinely flat.
_AMBIENT_RSS_MB_PER_STEP = 5.6


def _flatness_problems(samples, steps, smoke):
  """Fail on growth that looks like a leak in OUR machinery: flat
  Python blocks/threads/fds, and per-step RSS growth bounded by 2×
  the ambient (native, churn-independent) constant. On CPU (smoke —
  no tunnel, ambient ≈ 0) the RSS allowance drops to a small
  absolute bound so the CI smoke keeps real leak sensitivity."""
  problems = []
  if len(samples) < 8:
    problems.append(f'only {len(samples)} resource samples')
    return problems
  body = samples[len(samples) // 4:]          # drop warmup quarter
  ref = body[:max(len(body) // 2, 1)]
  tail = body[-3:]
  ref_thr = max(s[2] for s in ref)
  ref_fds = max(s[3] for s in ref)
  ref_blocks = max(s[4] for s in ref)
  for name, idx, bound in (('threads', 2, ref_thr + 4),
                           ('fds', 3, ref_fds + 16),
                           ('python blocks', 4, ref_blocks * 1.10)):
    worst = max(s[idx] for s in tail)
    if worst > bound:
      problems.append(
          f'{name} grew: tail max {worst} vs reference {bound:.1f} '
          f'(post-warmup ref max × tolerance)')
  rss_growth = max(s[1] for s in tail) - body[0][1]
  # Steps inside the sampled window, estimated time-proportionally.
  # Steps concentrate AFTER the excluded compile/warmup quarter, so
  # the time fraction UNDERcounts window steps — the computed
  # MB/step is an overestimate, i.e. the gate errs strict.
  span = samples[-1][0] - samples[0][0]
  window_frac = (tail[-1][0] - body[0][0]) / span if span > 0 else 1.0
  window_steps = max(steps * window_frac, 1.0)
  allowance = 0.5 if smoke else 2 * _AMBIENT_RSS_MB_PER_STEP
  if rss_growth / window_steps > allowance:
    problems.append(
        f'rss grew {rss_growth:.0f} MB over ~{window_steps:.0f} '
        f'post-warmup steps ({rss_growth / window_steps:.1f} '
        f'MB/step) — above the {allowance} MB/step allowance '
        f'({"CPU smoke" if smoke else "2x the measured ambient of the no-churn control"}); '
        'suspect a real leak')
  return problems


def _downsample(samples, n=40):
  if len(samples) <= n:
    return samples
  step = len(samples) / n
  return [samples[int(i * step)] for i in range(n)] + [samples[-1]]


def main():
  smoke = os.environ.get('SOAK_SMOKE') == '1'
  churn = os.environ.get('SOAK_CHURN') == '1'
  default_secs = ('40' if smoke else '1200' if churn else '600')
  seconds = float(os.environ.get('SOAK_SECONDS', default_secs))
  if smoke:
    import jax
    jax.config.update('jax_platforms', 'cpu')
  import numpy as np
  from scalable_agent_tpu import driver
  from scalable_agent_tpu import popart as popart_lib
  from scalable_agent_tpu.config import Config

  logdir = tempfile.mkdtemp(prefix='soak_')
  ingest_port = 0
  if churn:
    with socket.create_server(('127.0.0.1', 0)) as s:
      ingest_port = s.getsockname()[1]
  cfg = Config(
      logdir=logdir,
      env_backend='bandit',
      num_actors=8 if not smoke else 2,
      batch_size=4 if not smoke else 2,
      unroll_length=20 if not smoke else 5,
      num_action_repeats=1,
      episode_length=5,
      height=72 if not smoke else 24,
      width=96 if not smoke else 32,
      torso='deep' if not smoke else 'shallow',
      compute_dtype='bfloat16' if not smoke else 'float32',
      # Churn needs real processes to kill — also in smoke.
      use_py_process=(not smoke) or churn,
      use_instruction=True,
      use_popart=True,
      pixel_control_cost=0.01,
      learning_rate=0.0005,
      entropy_cost=0.003,
      discounting=0.0,
      reward_clipping='abs_one',
      total_environment_frames=int(1e9),
      inference_timeout_ms=20,
      checkpoint_secs=10**6,
      summary_secs=10 if not smoke else 2,
      remote_actor_port=ingest_port,
      # Churn runs the egress lever end-to-end: snapshots ship bf16
      # over the wire, the actor host upcasts, and the run still has
      # to learn to optimal (docs/PERF.md "Param-snapshot egress").
      remote_params_dtype='bfloat16' if churn else '',
      seed=7)

  churner = None
  if churn:
    churner = Churn(cfg, ingest_port, seconds, smoke)
    churner.start()
  try:
    run = driver.train(cfg, max_seconds=seconds,
                       stall_timeout_secs=180)
  finally:
    if churner is not None:
      churner.stop()

  losses, sigmas_min, sigmas_max, returns = [], [], [], []
  remote_unrolls = []  # (wall_time, cumulative unrolls over the wire)
  remote_conns = 0
  # Integrity counters over the soak window (round 12): final value
  # of each — all asserted ZERO below, so long-run rot shows up as a
  # red soak with a named counter instead of a mystery return dip.
  integrity_final = {'wire_crc_rejected': 0,
                     'publish_digest_rejected': 0,
                     'ckpt_digest_fallbacks': 0,
                     'sdc_replica_mismatches': 0}
  with open(os.path.join(logdir, 'summaries.jsonl')) as f:
    for line in f:
      e = json.loads(line)
      if 'value' not in e:
        continue
      if e['tag'] == 'total_loss':
        losses.append(e['value'])
      elif e['tag'] == 'popart_sigma_min':
        sigmas_min.append(e['value'])
      elif e['tag'] == 'popart_sigma_max':
        sigmas_max.append(e['value'])
      elif e['tag'] == 'remote_unrolls':
        remote_unrolls.append((e['wall_time'], e['value']))
      elif e['tag'] == 'remote_connections':
        remote_conns = max(remote_conns, int(e['value']))
      elif e['tag'] in integrity_final:
        integrity_final[e['tag']] = int(e['value'])
      elif e['tag'].endswith('/episode_return'):
        returns.append(e['value'])

  steps = int(run.state.update_steps)
  problems = []
  # --- Integrity SLO: ZERO violations over the soak window. Unlike
  # the chaos storm (which INJECTS corruption and asserts detection),
  # the soak runs clean hardware — any nonzero here is real rot on
  # this host, and a long soak is exactly where it accumulates. The
  # health counter covers local training too (no remote needed); the
  # wire counters only move in churn mode (remote feed on). ---
  if run.health is not None:
    integrity_final['sdc_replica_mismatches'] = max(
        integrity_final['sdc_replica_mismatches'],
        run.health.stats().get('sdc_mismatches', 0))
  integrity_final['ckpt_digest_fallbacks'] = max(
      integrity_final['ckpt_digest_fallbacks'],
      run.checkpointer.digest_fallbacks)
  if run.ingest is not None:
    ing = run.ingest.stats()
    integrity_final['wire_crc_rejected'] = max(
        integrity_final['wire_crc_rejected'],
        ing.get('wire_crc_rejected', 0))
    integrity_final['publish_digest_rejected'] = max(
        integrity_final['publish_digest_rejected'],
        ing.get('publish_digest_rejected', 0))
  # Round 13: the unified metrics registry is the SAME source of
  # truth the drain manifest / flight recorder / fleet 'stats' read —
  # cross-check the summaries-derived integrity counters against the
  # registrations that OUTLIVE the run (ingest Counters, the health
  # monitor's gauges; a disagreement means a reporting path rotted,
  # itself a soak finding). checkpoint/* gauges are deliberately
  # absent here: Checkpointer.close() unregisters them inside
  # driver.train's finally, and the direct
  # run.checkpointer.digest_fallbacks read above already covers that
  # counter.
  from scalable_agent_tpu import telemetry
  registry_snap = telemetry.registry().snapshot()
  registry_integrity = {
      'wire_crc_rejected': registry_snap.get('ingest/wire_crc_rejected'),
      'sdc_replica_mismatches': registry_snap.get(
          'health/sdc_mismatches'),
  }
  for name, reg_value in registry_integrity.items():
    if reg_value is None:
      continue
    integrity_final[name] = max(integrity_final[name], int(reg_value))
  for name, value in sorted(integrity_final.items()):
    if value:
      problems.append(
          f'integrity violation over the soak window: {name}={value} '
          '(expected 0 on clean hardware — suspect this host\'s '
          'NIC/RAM/disk; docs/RUNBOOK.md §9)')
  # Telemetry-plane liveness (round 13): with tracing on (default),
  # the soak window must have produced a parseable trace stream with
  # span coverage — a silent tracer over a long run is a telemetry
  # regression, not a shrug.
  telemetry_block = {'registry_names': len(registry_snap)}
  if cfg.telemetry_trace:
    sys.path.insert(0, REPO)
    from scripts import trace_report
    trace_summary = trace_report.summarize(
        trace_report.load_traces(logdir))
    telemetry_block.update({
        'trace_batches': trace_summary['batches'],
        'trace_unrolls': trace_summary['unrolls'],
        'policy_lag_p99': trace_summary['policy_lag']['p99'],
        'e2e_ms_p99': trace_summary['e2e_ms']['p99'],
    })
    if trace_summary['batches'] == 0:
      problems.append('telemetry_trace on but traces.jsonl carries '
                      'zero batch records over the soak window')
  # Round 14: the run judged itself continuously (slo.py default
  # objective set) — a soak whose SLO verdict fails is a red soak
  # naming the objective, and the soak artifact carries the verdict
  # so chip-run triage starts from margins, not raw counters.
  from scalable_agent_tpu import slo as slo_lib
  slo_verdict = slo_lib.read_verdict(logdir)
  slo_block = None
  if cfg.slo_engine:
    if slo_verdict is None:
      problems.append('slo_engine on but the run wrote no '
                      'SLO_VERDICT.json')
    else:
      slo_block = {
          'pass': slo_verdict.get('pass'),
          'violations': slo_verdict.get('violations') or [],
          'captures': sorted(slo_verdict.get('captures') or {}),
          'margins': {
              name: e.get('margin')
              for name, e in
              (slo_verdict.get('objectives') or {}).items()},
      }
      # Round 16 (ROADMAP item 3): the learner-plane utilization SLO
      # row, explicit in the soak artifact — the number the hybrid
      # filler exists to lift. With --anakin_filler the filler floor
      # objective must read ok (~1.0 by construction; burning means
      # the filler failed to fill); without it the plain row is the
      # env-bound capacity-headroom measurement. env stays alongside
      # as the dead-plane signal filler frames must never mask.
      objs = slo_verdict.get('objectives') or {}
      slo_block['plane_utilization'] = {
          'learner': (objs.get('learner_plane_utilization')
                      or {}).get('value'),
          'learner_filler_floor_state': (
              objs.get('learner_plane_utilization_filler')
              or {}).get('state'),
          'env': (objs.get('env_plane_utilization') or {}).get(
              'value'),
      }
      if not slo_verdict.get('pass'):
        problems.append(
            'SLO verdict FAILED over the soak window: '
            + ', '.join(slo_verdict.get('violations') or ['?']))
  # Round 15: the controller's action log rides the soak artifact —
  # a long run that moved its own knobs must say so (and in act mode
  # an apply error over the window is a soak finding).
  from scalable_agent_tpu import controller as controller_lib
  controller_log = controller_lib.read_log(logdir)
  controller_block = None
  if cfg.controller != 'off':
    if controller_log is None:
      problems.append('controller=%s but the run wrote no '
                      'CONTROLLER_LOG.json' % cfg.controller)
    else:
      counts = controller_log.get('counts') or {}
      controller_block = {
          'mode': controller_log.get('mode'),
          'counts': counts,
          'last_actions': [
              {k: a.get(k) for k in ('kind', 'objective', 'actuator',
                                     'from', 'to', 'applied')}
              for a in (controller_log.get('actions') or [])[-8:]],
      }
      if counts.get('apply_errors'):
        problems.append(
            'controller recorded %d actuator apply error(s) over the '
            'soak window' % counts['apply_errors'])
  if steps < (20 if not smoke else 2):
    problems.append(f'only {steps} learner steps in {seconds:.0f}s')
  if not losses or not np.all(np.isfinite(losses)):
    problems.append(f'non-finite or missing losses: {losses[-3:]}')
  # σ is clipped to [DEFAULT_SIGMA_MIN, DEFAULT_SIGMA_MAX] by design:
  # LANDING ON either bound means the value scale collapsed/diverged
  # (×1.01/÷1.01 so the check can actually fire at the clip).
  sigma_lo = float(popart_lib.DEFAULT_SIGMA_MIN)
  sigma_hi = float(popart_lib.DEFAULT_SIGMA_MAX)
  if not sigmas_max or not np.all(np.isfinite(sigmas_max)):
    problems.append('missing/non-finite popart sigma')
  elif (max(sigmas_max) >= sigma_hi / 1.01 or
        min(sigmas_min) <= sigma_lo * 1.01):
    problems.append(
        f'popart sigma hit its clip bounds: [{min(sigmas_min)}, '
        f'{max(sigmas_max)}]')
  third = max(len(returns) // 3, 1)
  early = float(np.mean(returns[:third])) if returns else float('nan')
  late = float(np.mean(returns[-third:])) if returns else float('nan')
  # Random play on the 3-arm bandit: 5-step episodes × 1/3 ≈ 1.67.
  random_baseline = cfg.episode_length / 3.0
  if not smoke:
    if len(returns) < 12:
      problems.append(f'only {len(returns)} episode returns logged')
    elif not (late > early):
      problems.append(f'return did not improve: early={early:.3f} '
                      f'late={late:.3f}')
    elif late <= 1.5 * random_baseline:
      problems.append(
          f'return does not clear the random baseline '
          f'({random_baseline:.2f}): late={late:.3f}')

  churn_artifact = None
  if churner is not None:
    respawns = run.fleet.stats()['respawns']
    if churner.env_kills == 0:
      problems.append('churn mode killed no env process')
    elif respawns == 0:
      problems.append(
          f'{churner.env_kills} env kills but fleet recorded 0 '
          'respawns')
    if not smoke:
      # The remote host was dropped and replaced: cumulative ingest
      # connections must show BOTH actors beyond the churn thread's
      # own port probe (which the server also counts), and unrolls
      # must keep landing AFTER the replacement connected.
      needed = 2 + churner.port_probes
      if remote_conns < needed:
        problems.append(
            f'expected >={needed} cumulative remote connections '
            f'({churner.port_probes} probe + original + replacement), '
            f'saw {remote_conns}')
      drop_wall = getattr(churner, 'drop_wall_time', None)
      if drop_wall is None:
        problems.append('remote actor was never dropped/replaced')
      else:
        before = max((v for w, v in remote_unrolls
                      if w <= drop_wall), default=0)
        after = max((v for w, v in remote_unrolls), default=0)
        if after <= before:
          problems.append(
              f'remote unrolls did not resume after the drop: '
              f'{before} before vs {after} final')
    problems.extend(_flatness_problems(churner.samples, steps, smoke))
    churn_artifact = {
        'env_kills': churner.env_kills,
        'fleet_respawns': respawns,
        'remote_connections': remote_conns,
        'remote_unrolls_final': (remote_unrolls[-1][1]
                                 if remote_unrolls else 0),
        'events': churner.events,
        'resource_curve': [
            {'t': t, 'rss_mb': r, 'threads': th, 'fds': fd,
             'py_blocks': bl}
            for t, r, th, fd, bl in _downsample(churner.samples)],
        'rss_note': (
            'RSS on this host grows ~5.6 MB/step in a NO-churn '
            'control (native tunnel/PJRT buffers; python blocks '
            'flat) — the leak gate bounds per-step growth at 2x '
            'that ambient constant plus flat blocks/threads/fds; '
            'see _AMBIENT_RSS_MB_PER_STEP'),
        'actor_tail': _file_tail(churner.actor_log, 400),
    }

  n_chunks = 8
  chunk = max(len(returns) // n_chunks, 1)
  curve = [round(float(np.mean(returns[i:i + chunk])), 3)
           for i in range(0, len(returns), chunk)]
  artifact = {
      'ok': not problems,
      'problems': problems,
      'seconds': seconds,
      'steps': steps,
      'frames': int(run.frames),
      'episodes_logged': len(returns),
      'return_early_third': round(early, 3),
      'return_late_third': round(late, 3),
      'return_curve': curve,
      'loss_first': round(float(losses[0]), 4) if losses else None,
      'loss_last': round(float(losses[-1]), 4) if losses else None,
      'popart_sigma_range': ([round(float(min(sigmas_min)), 5),
                              round(float(max(sigmas_max)), 5)]
                             if sigmas_max else None),
      'integrity': integrity_final,
      'telemetry': telemetry_block,
      'slo': slo_block,
      'controller': controller_block,
      'churn': churn_artifact,
      'stack': {
          'torso': cfg.torso, 'compute_dtype': cfg.compute_dtype,
          'frames': [cfg.height, cfg.width],
          'use_instruction': True, 'use_popart': True,
          'pixel_control_cost': cfg.pixel_control_cost,
          'unroll_length': cfg.unroll_length,
          'batch_size': cfg.batch_size, 'num_actors': cfg.num_actors,
          'use_py_process': cfg.use_py_process,
      },
      'smoke': smoke,
  }
  out_path = os.path.join(REPO, 'SOAK_r05.json')
  if smoke:
    out_path = os.path.join(logdir, 'SOAK_smoke.json')
  with open(out_path, 'w') as f:
    json.dump(artifact, f, indent=1)
  print(json.dumps(artifact))
  if problems:
    sys.exit(1)


if __name__ == '__main__':
  from scalable_agent_tpu.runtime.py_process import warm_forkserver
  warm_forkserver()
  main()
