"""Flagship stability soak (VERDICT r2 item 6).

The composition test proves the full extension stack RUNS; this proves
it is STABLE AND LEARNING over a sustained run: the real driver
pipeline (process-hosted envs → C++ batcher → buffer → prefetcher →
chip) with every flagship feature on at once — deep ResNet, 72×96
frames, bfloat16 compute, instruction encoder, PopArt, UNREAL pixel
control — on the contextual-bandit task, asserting over the whole run:

  - every logged total_loss is finite,
  - PopArt σ stays inside its clip bounds (a diverging value scale
    shows up there long before NaNs),
  - episode return IMPROVES (last-third mean > first-third mean) and
    beats the random baseline (~1/3 on 3-arm bandit).

Writes SOAK_r03.json at the repo root. Invocation (real chip, ~10 min):

    python scripts/soak.py                 # SOAK_SECONDS=600 default
    SOAK_SECONDS=120 python scripts/soak.py
    SOAK_SMOKE=1 python scripts/soak.py    # CPU mechanics check, ~40 s

Learning hyperparameters: lr 5e-4 (≈ the paper's tuned 4.8e-4),
entropy 3e-3, γ=0 (the task is one-step). The smoke test's hotter
lr 2e-3 works for the SHALLOW torso but drives the deep ResNet into
a premature near-deterministic policy that solves only 2 of the 3
cues (measured: plateau at ~0.66 reward/step vs 1.0 at 5e-4) — the
flagship stack is what is under test, and at the paper-ish lr it
learns to optimal.
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
  smoke = os.environ.get('SOAK_SMOKE') == '1'
  seconds = float(os.environ.get('SOAK_SECONDS', '600' if not smoke
                                 else '40'))
  if smoke:
    import jax
    jax.config.update('jax_platforms', 'cpu')
  import numpy as np
  from scalable_agent_tpu import driver
  from scalable_agent_tpu import popart as popart_lib
  from scalable_agent_tpu.config import Config

  logdir = tempfile.mkdtemp(prefix='soak_')
  cfg = Config(
      logdir=logdir,
      env_backend='bandit',
      num_actors=8 if not smoke else 2,
      batch_size=4 if not smoke else 2,
      unroll_length=20 if not smoke else 5,
      num_action_repeats=1,
      episode_length=5,
      height=72 if not smoke else 24,
      width=96 if not smoke else 32,
      torso='deep' if not smoke else 'shallow',
      compute_dtype='bfloat16' if not smoke else 'float32',
      use_py_process=not smoke,
      use_instruction=True,
      use_popart=True,
      pixel_control_cost=0.01,
      learning_rate=0.0005,
      entropy_cost=0.003,
      discounting=0.0,
      reward_clipping='abs_one',
      total_environment_frames=int(1e9),
      inference_timeout_ms=20,
      checkpoint_secs=10**6,
      summary_secs=10 if not smoke else 2,
      seed=7)
  run = driver.train(cfg, max_seconds=seconds, stall_timeout_secs=180)

  losses, sigmas_min, sigmas_max, returns = [], [], [], []
  with open(os.path.join(logdir, 'summaries.jsonl')) as f:
    for line in f:
      e = json.loads(line)
      if 'value' not in e:
        continue
      if e['tag'] == 'total_loss':
        losses.append(e['value'])
      elif e['tag'] == 'popart_sigma_min':
        sigmas_min.append(e['value'])
      elif e['tag'] == 'popart_sigma_max':
        sigmas_max.append(e['value'])
      elif e['tag'].endswith('/episode_return'):
        returns.append(e['value'])

  steps = int(run.state.update_steps)
  problems = []
  if steps < (20 if not smoke else 2):
    problems.append(f'only {steps} learner steps in {seconds:.0f}s')
  if not losses or not np.all(np.isfinite(losses)):
    problems.append(f'non-finite or missing losses: {losses[-3:]}')
  # σ is clipped to [DEFAULT_SIGMA_MIN, DEFAULT_SIGMA_MAX] by design:
  # LANDING ON either bound means the value scale collapsed/diverged
  # (×1.01/÷1.01 so the check can actually fire at the clip).
  sigma_lo = float(popart_lib.DEFAULT_SIGMA_MIN)
  sigma_hi = float(popart_lib.DEFAULT_SIGMA_MAX)
  if not sigmas_max or not np.all(np.isfinite(sigmas_max)):
    problems.append('missing/non-finite popart sigma')
  elif (max(sigmas_max) >= sigma_hi / 1.01 or
        min(sigmas_min) <= sigma_lo * 1.01):
    problems.append(
        f'popart sigma hit its clip bounds: [{min(sigmas_min)}, '
        f'{max(sigmas_max)}]')
  third = max(len(returns) // 3, 1)
  early = float(np.mean(returns[:third])) if returns else float('nan')
  late = float(np.mean(returns[-third:])) if returns else float('nan')
  # Random play on the 3-arm bandit: 5-step episodes × 1/3 ≈ 1.67.
  random_baseline = cfg.episode_length / 3.0
  if not smoke:
    if len(returns) < 12:
      problems.append(f'only {len(returns)} episode returns logged')
    elif not (late > early):
      problems.append(f'return did not improve: early={early:.3f} '
                      f'late={late:.3f}')
    elif late <= 1.5 * random_baseline:
      problems.append(
          f'return does not clear the random baseline '
          f'({random_baseline:.2f}): late={late:.3f}')

  n_chunks = 8
  chunk = max(len(returns) // n_chunks, 1)
  curve = [round(float(np.mean(returns[i:i + chunk])), 3)
           for i in range(0, len(returns), chunk)]
  artifact = {
      'ok': not problems,
      'problems': problems,
      'seconds': seconds,
      'steps': steps,
      'frames': int(run.frames),
      'episodes_logged': len(returns),
      'return_early_third': round(early, 3),
      'return_late_third': round(late, 3),
      'return_curve': curve,
      'loss_first': round(float(losses[0]), 4) if losses else None,
      'loss_last': round(float(losses[-1]), 4) if losses else None,
      'popart_sigma_range': ([round(float(min(sigmas_min)), 5),
                              round(float(max(sigmas_max)), 5)]
                             if sigmas_max else None),
      'stack': {
          'torso': cfg.torso, 'compute_dtype': cfg.compute_dtype,
          'frames': [cfg.height, cfg.width],
          'use_instruction': True, 'use_popart': True,
          'pixel_control_cost': cfg.pixel_control_cost,
          'unroll_length': cfg.unroll_length,
          'batch_size': cfg.batch_size, 'num_actors': cfg.num_actors,
          'use_py_process': cfg.use_py_process,
      },
      'smoke': smoke,
  }
  out_path = os.path.join(os.path.dirname(os.path.dirname(
      os.path.abspath(__file__))), 'SOAK_r03.json')
  if smoke:
    out_path = os.path.join(logdir, 'SOAK_smoke.json')
  with open(out_path, 'w') as f:
    json.dump(artifact, f, indent=1)
  print(json.dumps(artifact))
  if problems:
    sys.exit(1)


if __name__ == '__main__':
  from scalable_agent_tpu.runtime.py_process import warm_forkserver
  warm_forkserver()
  main()
