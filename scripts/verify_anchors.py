"""Diff the reconstructed anchor tables against their upstream sources.

This sandbox has no network and an empty reference mount, so the
human/random anchor tables in `envs/dmlab30.py` and `envs/atari57.py`
are reconstructions (each module's provenance caveat). This script is
the mechanical half of docs/RUNBOOK.md section 2: run it on a machine
that has the upstream sources, and it diffs every constant, prints any
drift, and on a clean diff prints the exact edits (provenance flip +
checksum) that mark the tables verified.

Usage:
  # DMLab-30: point at a checkout of the upstream module
  #   github.com/deepmind/scalable_agent/blob/master/dmlab30.py
  python scripts/verify_anchors.py dmlab30 /path/to/upstream/dmlab30.py

  # Atari-57: point at a JSON file {game: [random, human], ...}
  # transcribed from Wang et al. 2016 (arXiv:1511.06581) Table 4.
  python scripts/verify_anchors.py atari57 /path/to/wang2016_table4.json

Exit status: 0 = tables match upstream exactly; 1 = drift found (each
drifted symbol printed); 2 = usage/load error.

Not run in CI (upstream unavailable there) — tests/test_anchors.py
covers the checksum/warning machinery instead.
"""

import ast
import json
import os
import sys

# `python scripts/verify_anchors.py` puts scripts/ (not the repo root)
# on sys.path — same preamble as the sibling scripts.
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def extract_constant_tables(path, names):
  """{name: dict} for the requested module-level assignments in an
  UNTRUSTED python source, WITHOUT executing it (ADVICE r5: the
  upstream checkout this script points at is exactly the kind of file
  nobody audits before running; `runpy.run_path` executed it).

  Parses with `ast` and `ast.literal_eval`s each assigned value.
  Handles the two shapes upstream uses: plain dict literals and
  `collections.OrderedDict([...])` (the call's single literal
  argument is evaluated and dict()ed). A requested name bound to
  anything else (a computation, a function call with non-literal
  args) raises ValueError naming it — drift INTO executable table
  definitions should fail loudly, not get silently skipped."""
  with open(path) as f:
    tree = ast.parse(f.read(), filename=path)
  out = {}
  for node in tree.body:  # module level only, like the import would see
    if isinstance(node, ast.Assign):
      targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
      value = node.value
    elif (isinstance(node, ast.AnnAssign)
          and isinstance(node.target, ast.Name) and node.value):
      targets, value = [node.target.id], node.value
    else:
      continue
    wanted = [t for t in targets if t in names]
    if not wanted:
      continue
    if isinstance(value, ast.Call):
      func = value.func
      fname = (func.attr if isinstance(func, ast.Attribute)
               else getattr(func, 'id', None))
      if fname not in ('OrderedDict', 'dict') or len(value.args) != 1:
        raise ValueError(
            f'{wanted[0]} in {path} is built by a {fname!r} call this '
            'script cannot evaluate without executing the file')
      literal = dict(ast.literal_eval(value.args[0]))
    else:
      literal = ast.literal_eval(value)
    for name in wanted:
      out[name] = literal
  return out


def _fail(msg):
  print(f'verify_anchors: {msg}', file=sys.stderr)
  return 2


def _diff_tables(name, ours, upstream):
  """Print per-key drift between two {key: value} tables."""
  drift = 0
  for key in sorted(set(ours) | set(upstream)):
    if key not in ours:
      print(f'  {name}[{key!r}]: MISSING locally '
            f'(upstream {upstream[key]!r})')
      drift += 1
    elif key not in upstream:
      print(f'  {name}[{key!r}]: not in upstream '
            f'(local {ours[key]!r})')
      drift += 1
    elif ours[key] != upstream[key]:
      print(f'  {name}[{key!r}]: local {ours[key]!r} != '
            f'upstream {upstream[key]!r}')
      drift += 1
  return drift


def verify_dmlab30(upstream_path):
  """Returns (drift_count, module_path, our_tables)."""
  from scalable_agent_tpu.envs import dmlab30
  # The upstream checkout is UNTRUSTED input: extract its three
  # constant tables by ast-parsing the source instead of executing it
  # (ADVICE r5 — this used to be runpy.run_path).
  tables = {'LEVEL_MAPPING': dict(dmlab30.LEVEL_MAPPING),
            'HUMAN_SCORES': dmlab30.HUMAN_SCORES,
            'RANDOM_SCORES': dmlab30.RANDOM_SCORES}
  up = extract_constant_tables(upstream_path, set(tables))
  drift = 0
  for sym, ours in tables.items():
    if sym not in up:
      print(f'  upstream module has no {sym} — wrong file?')
      drift += 1
      continue
    drift += _diff_tables(sym, dict(ours), dict(up[sym]))
  return drift, 'scalable_agent_tpu/envs/dmlab30.py', tables


def verify_atari57(upstream_path):
  """Returns (drift_count, module_path, our_tables)."""
  from scalable_agent_tpu.envs import atari57
  with open(upstream_path) as f:
    table = json.load(f)
  tables = {'RANDOM_SCORES': atari57.RANDOM_SCORES,
            'HUMAN_SCORES': atari57.HUMAN_SCORES}
  upstream_random = {g: float(rh[0]) for g, rh in table.items()}
  upstream_human = {g: float(rh[1]) for g, rh in table.items()}
  drift = _diff_tables('RANDOM_SCORES', tables['RANDOM_SCORES'],
                       upstream_random)
  drift += _diff_tables('HUMAN_SCORES', tables['HUMAN_SCORES'],
                        upstream_human)
  return drift, 'scalable_agent_tpu/envs/atari57.py', tables


def main(argv):
  if len(argv) != 3 or argv[1] not in ('dmlab30', 'atari57'):
    return _fail(__doc__)
  which, upstream_path = argv[1], argv[2]
  try:
    drift, module_path, tables = (verify_dmlab30(upstream_path)
                                  if which == 'dmlab30'
                                  else verify_atari57(upstream_path))
  except (OSError, json.JSONDecodeError, SyntaxError,
          ValueError) as e:
    return _fail(f'could not load upstream source: {e!r}')
  if drift:
    print(f'{which}: {drift} drifted constant(s) — fix them in '
          f'{module_path}, rerun this script, then apply the '
          f'verified-edit it prints.')
    return 1
  from scalable_agent_tpu.envs import anchors
  print(f'{which}: all constants match upstream. Mark verified in '
        f'{module_path}:')
  print("  ANCHOR_PROVENANCE = 'verified'")
  print(f"  ANCHOR_SHA256 = ('{anchors.anchor_checksum(tables)}')")
  return 0


if __name__ == '__main__':
  sys.exit(main(sys.argv))
