"""Tests for DMLab-30 metadata + human-normalized scoring (SURVEY §2.13)."""

import numpy as np
import pytest

from scalable_agent_tpu.envs import dmlab30


def test_level_table_shape():
  assert len(dmlab30.LEVEL_MAPPING) == 30
  assert len(dmlab30.ALL_LEVELS) == 30
  # Only the two rooms_*_train levels map to distinct test variants.
  diffs = [k for k, v in dmlab30.LEVEL_MAPPING.items() if k != v]
  assert diffs == ['rooms_collect_good_objects_train',
                   'rooms_exploit_deferred_effects_train']
  # Every test level has both anchor scores.
  for test_level in dmlab30.LEVEL_MAPPING.values():
    assert test_level in dmlab30.HUMAN_SCORES
    assert test_level in dmlab30.RANDOM_SCORES
    assert (dmlab30.HUMAN_SCORES[test_level]
            > dmlab30.RANDOM_SCORES[test_level])


def test_table_values_are_sane():
  """Property bounds on the reconstructed tables (VERDICT r3 #7: the
  constants can't be re-verified offline — provenance is caveated in
  the module — but damage is bounded: finite floats, exactly the
  benchmark's key sets, well-formed level names)."""
  assert set(dmlab30.HUMAN_SCORES) == set(dmlab30.LEVEL_MAPPING.values())
  assert set(dmlab30.RANDOM_SCORES) == set(dmlab30.LEVEL_MAPPING.values())
  for table in (dmlab30.HUMAN_SCORES, dmlab30.RANDOM_SCORES):
    for level, score in table.items():
      assert np.isfinite(score), (level, score)
      assert isinstance(score, float), (level, score)
  for name in (*dmlab30.ALL_LEVELS, *dmlab30.LEVEL_MAPPING.values()):
    assert name == name.lower() and ' ' not in name, name


def test_score_at_anchors():
  # Returns exactly at the random anchor -> 0; at the human anchor -> 100.
  random_returns = {
      l: [dmlab30.RANDOM_SCORES[dmlab30.LEVEL_MAPPING[l]]]
      for l in dmlab30.ALL_LEVELS}
  human_returns = {
      l: [dmlab30.HUMAN_SCORES[dmlab30.LEVEL_MAPPING[l]]]
      for l in dmlab30.ALL_LEVELS}
  assert dmlab30.compute_human_normalized_score(random_returns) == (
      pytest.approx(0.0, abs=1e-9))
  assert dmlab30.compute_human_normalized_score(human_returns) == (
      pytest.approx(100.0, abs=1e-9))


def test_per_level_cap():
  # One superhuman level; cap=100 clips it, no-cap exceeds it.
  returns = {
      l: [dmlab30.HUMAN_SCORES[dmlab30.LEVEL_MAPPING[l]]]
      for l in dmlab30.ALL_LEVELS}
  lvl = dmlab30.ALL_LEVELS[0]
  test_lvl = dmlab30.LEVEL_MAPPING[lvl]
  human, random = dmlab30.HUMAN_SCORES[test_lvl], dmlab30.RANDOM_SCORES[test_lvl]
  returns[lvl] = [random + 2.0 * (human - random)]  # 200% on this level
  uncapped = dmlab30.compute_human_normalized_score(returns)
  capped = dmlab30.compute_human_normalized_score(returns, per_level_cap=100)
  assert uncapped == pytest.approx(100.0 + 100.0 / 30.0)
  assert capped == pytest.approx(100.0)


def test_mean_of_multiple_episodes():
  returns = {
      l: [dmlab30.RANDOM_SCORES[dmlab30.LEVEL_MAPPING[l]]]
      for l in dmlab30.ALL_LEVELS}
  lvl = dmlab30.ALL_LEVELS[3]
  test_lvl = dmlab30.LEVEL_MAPPING[lvl]
  human, random = dmlab30.HUMAN_SCORES[test_lvl], dmlab30.RANDOM_SCORES[test_lvl]
  # Two episodes averaging to the human anchor -> that level scores 100.
  returns[lvl] = [random, 2.0 * human - random]
  score = dmlab30.compute_human_normalized_score(returns)
  assert score == pytest.approx(100.0 / 30.0)


def test_missing_level_raises():
  returns = {
      l: [1.0] for l in dmlab30.ALL_LEVELS[:-1]}
  with pytest.raises(ValueError, match='Missing returns'):
    dmlab30.compute_human_normalized_score(returns)
  returns[dmlab30.ALL_LEVELS[-1]] = []
  with pytest.raises(ValueError, match='Missing returns'):
    dmlab30.compute_human_normalized_score(returns)
