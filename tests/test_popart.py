"""PopArt tests: statistics EMA, exact output preservation, and the
learner integration (normalized head + unnormalized V-trace).

PopArt is a TPU-build extension — the reference lists it as planned
but does not implement it (SURVEY §2.12). Ground truth here is the
PopArt definition itself (van Hasselt 2016; Hessel 2018): hand-computed
EMA updates and the preservation identity σ'·(w'x+b')+μ' == σ·(wx+b)+μ.
"""

import numpy as np

import jax
import jax.numpy as jnp

from scalable_agent_tpu import learner as learner_lib
from scalable_agent_tpu import popart
from scalable_agent_tpu.config import Config
from scalable_agent_tpu.models import ImpalaAgent, init_params
from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
from scalable_agent_tpu.testing import make_example_batch


def test_init_identity():
  state = popart.init(4)
  np.testing.assert_allclose(np.asarray(popart.sigma(state)),
                             np.ones(4))
  vals = jnp.array([[1.5, -2.0]])
  ids = jnp.array([0, 3])
  np.testing.assert_allclose(
      np.asarray(popart.unnormalize(state, vals, ids)),
      np.asarray(vals))


def test_update_stats_matches_hand_ema():
  state = popart.init(3)
  # Two trajectories of task 0 with targets {1,3} and {5,7}; task 2
  # with {10,10}; task 1 absent.
  targets = jnp.array([[1.0, 5.0, 10.0],
                       [3.0, 7.0, 10.0]])
  ids = jnp.array([0, 0, 2])
  beta = 0.1
  new = popart.update_stats(state, targets, ids, beta=beta)
  # Task 0: batch mean 4, second moment (1+9+25+49)/4=21.
  np.testing.assert_allclose(float(new.mu[0]), 0.9 * 0 + 0.1 * 4.0)
  np.testing.assert_allclose(float(new.nu[0]), 0.9 * 1 + 0.1 * 21.0)
  # Task 1 untouched (absent from batch).
  np.testing.assert_allclose(float(new.mu[1]), 0.0)
  np.testing.assert_allclose(float(new.nu[1]), 1.0)
  # Task 2: mean 10, second moment 100.
  np.testing.assert_allclose(float(new.mu[2]), 1.0)
  np.testing.assert_allclose(float(new.nu[2]), 0.9 + 10.0)


def test_normalize_unnormalize_roundtrip():
  state = popart.PopArtState(mu=jnp.array([2.0, -1.0]),
                             nu=jnp.array([13.0, 5.0]))
  ids = jnp.array([0, 1])
  vals = jnp.array([[4.0, -3.0], [0.0, 1.0]])
  n = popart.normalize(state, vals, ids)
  np.testing.assert_allclose(
      np.asarray(popart.unnormalize(state, n, ids)),
      np.asarray(vals), rtol=1e-6)


def test_preserve_outputs_exact():
  rng = np.random.RandomState(0)
  hidden, num_tasks = 16, 5
  kernel = jnp.asarray(rng.randn(hidden, num_tasks), jnp.float32)
  bias = jnp.asarray(rng.randn(num_tasks), jnp.float32)
  x = jnp.asarray(rng.randn(7, hidden), jnp.float32)
  old = popart.PopArtState(mu=jnp.zeros(num_tasks),
                           nu=jnp.ones(num_tasks))
  new = popart.PopArtState(
      mu=jnp.asarray(rng.randn(num_tasks), jnp.float32),
      nu=jnp.asarray(1.0 + rng.rand(num_tasks) * 10, jnp.float32))

  def unnorm_out(k, b, state):
    return (popart.sigma(state)[None, :] * (x @ k + b[None, :]) +
            state.mu[None, :])

  new_kernel, new_bias = popart.preserve_outputs(kernel, bias, old, new)
  np.testing.assert_allclose(
      np.asarray(unnorm_out(new_kernel, new_bias, new)),
      np.asarray(unnorm_out(kernel, bias, old)), rtol=1e-5, atol=1e-5)


def test_apply_preservation_flax_layout():
  agent = ImpalaAgent(num_actions=3, torso='shallow',
                      num_popart_tasks=4, use_instruction=False)
  obs = {'frame': (24, 32, 3), 'instr_len': MAX_INSTRUCTION_LEN}
  params = init_params(agent, jax.random.PRNGKey(0), obs)
  assert params['params']['baseline']['kernel'].shape[-1] == 4
  old = popart.init(4)
  new = popart.PopArtState(mu=jnp.full((4,), 2.0),
                           nu=jnp.full((4,), 8.0))
  rewritten = popart.apply_preservation(params, old, new)
  k0 = params['params']['baseline']['kernel']
  k1 = rewritten['params']['baseline']['kernel']
  np.testing.assert_allclose(np.asarray(k1),
                             np.asarray(k0) / 2.0, rtol=1e-6)
  # Everything else untouched.
  np.testing.assert_array_equal(
      np.asarray(rewritten['params']['policy_logits']['kernel']),
      np.asarray(params['params']['policy_logits']['kernel']))


def test_learner_with_popart_trains_and_preserves():
  num_tasks, a = 3, 4
  h, w = 24, 32
  obs = {'frame': (h, w, 3), 'instr_len': MAX_INSTRUCTION_LEN}
  cfg = Config(batch_size=3, unroll_length=4, num_action_repeats=1,
               total_environment_frames=10**6, use_popart=True,
               popart_beta=0.05, torso='shallow')
  agent = ImpalaAgent(num_actions=a, torso='shallow',
                      num_popart_tasks=num_tasks)
  params = init_params(agent, jax.random.PRNGKey(0), obs)
  state = learner_lib.make_train_state(params, cfg,
                                       num_popart_tasks=num_tasks)
  assert state.popart is not None
  batch = make_example_batch(5, 3, h, w, a, MAX_INSTRUCTION_LEN,
                             done_prob=0.1)
  batch = batch._replace(level_name=np.array([0, 1, 1], np.int32))
  step = learner_lib.make_train_step(agent, cfg)
  prev_mu = np.asarray(state.popart.mu).copy()
  for _ in range(3):
    state, metrics = step(state, batch)
  assert np.isfinite(float(metrics['total_loss']))
  new_mu = np.asarray(state.popart.mu)
  # Tasks 0 and 1 saw data; task 2 didn't.
  assert new_mu[0] != prev_mu[0]
  assert new_mu[1] != prev_mu[1]
  assert new_mu[2] == prev_mu[2]


def test_popart_unnormalized_values_continuous_across_update():
  """The preservation property end-to-end in the learner: after a
  train step changes the stats, the NEW params + NEW stats must give
  (nearly) the same unnormalized values as the same params would have
  before preservation — i.e. the rewrite exactly cancels the stats
  change on the head output (up to the SGD update itself, which we
  freeze with lr=0)."""
  num_tasks, a = 2, 3
  h, w = 24, 32
  obs = {'frame': (h, w, 3), 'instr_len': MAX_INSTRUCTION_LEN}
  cfg = Config(batch_size=2, unroll_length=4, num_action_repeats=1,
               total_environment_frames=10**6, use_popart=True,
               popart_beta=0.5, learning_rate=0.0, torso='shallow')
  agent = ImpalaAgent(num_actions=a, torso='shallow',
                      num_popart_tasks=num_tasks)
  params = init_params(agent, jax.random.PRNGKey(1), obs)
  state = learner_lib.make_train_state(params, cfg,
                                       num_popart_tasks=num_tasks)
  batch = make_example_batch(5, 2, h, w, a, MAX_INSTRUCTION_LEN,
                             done_prob=0.0)
  batch = batch._replace(level_name=np.array([0, 1], np.int32))
  ids = jnp.asarray(batch.level_name, jnp.int32)

  def unnorm_values(state):
    out, _ = agent.apply(state.params, batch.agent_outputs.action,
                         batch.env_outputs, batch.agent_state,
                         level_ids=ids)
    from scalable_agent_tpu import popart as popart_lib
    return np.asarray(
        popart_lib.unnormalize(state.popart, out.baseline, ids))

  before = unnorm_values(state)
  step = learner_lib.make_train_step(agent, cfg)
  state2, _ = step(state, batch)
  # Stats moved a lot (beta=0.5)…
  assert not np.allclose(np.asarray(state2.popart.mu), 0.0)
  # …but with lr=0 the unnormalized predictions are preserved.
  after = unnorm_values(state2)
  np.testing.assert_allclose(after, before, rtol=1e-4, atol=1e-4)


def test_sigma_no_nan_for_near_constant_targets():
  """Float rounding can push nu - mu² slightly negative for a
  near-constant-target task; sigma must clip the variance BEFORE the
  sqrt (a NaN here poisons the head permanently)."""
  state = popart.init(1)
  targets = jnp.full((4, 1), 1000.07, jnp.float32)
  ids = jnp.array([0])
  for _ in range(60):
    state = popart.update_stats(state, targets, ids, beta=0.1)
  s = np.asarray(popart.sigma(state))
  assert np.all(np.isfinite(s)), s
  assert s[0] >= float(state.sigma_min)
  n = popart.normalize(state, targets, ids)
  assert np.all(np.isfinite(np.asarray(n)))
