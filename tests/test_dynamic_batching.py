"""Contract tests for the C++ dynamic batcher.

Re-specifies the reference's dynamic_batching_test.py contract (SURVEY
§2.15: batch merging, max-batch split, timeout flush, error propagation
to the right caller, out-of-order completion, shutdown/cancellation,
shape validation) against the new C++ host batcher, with real Python
threads doing real blocking calls.
"""

import threading
import time

import numpy as np
import pytest

from scalable_agent_tpu.ops import dynamic_batching as db


def _run_threads(fns):
  """Run callables concurrently; re-raise the first exception."""
  results = [None] * len(fns)
  errors = []

  def runner(i, fn):
    try:
      results[i] = fn()
    except Exception as e:  # noqa: BLE001 — re-raised below
      errors.append(e)

  threads = [threading.Thread(target=runner, args=(i, fn))
             for i, fn in enumerate(fns)]
  for t in threads:
    t.start()
  for t in threads:
    t.join(timeout=30)
  if errors:
    raise errors[0]
  return results


class TestBasic:

  def test_single_call_passes_through(self):
    @db.batch_fn
    def f(a, b):
      return a + b

    try:
      out = f(np.array([1.0]), np.array([2.0]))
      np.testing.assert_array_equal(out, [3.0])
    finally:
      f.close()

  def test_multiple_sequential_calls(self):
    @db.batch_fn
    def f(a):
      return a * 2

    try:
      for i in range(5):
        np.testing.assert_array_equal(f(np.array([float(i)])),
                                      [2.0 * i])
    finally:
      f.close()

  def test_multiple_outputs_and_2d_rows(self):
    @db.batch_fn
    def f(a):
      return a + 1, (a * 2).astype(np.int32)

    try:
      x = np.arange(6, dtype=np.float32).reshape(2, 3)
      y, z = f(x)
      np.testing.assert_array_equal(y, x + 1)
      assert z.dtype == np.int32
    finally:
      f.close()


class TestMerging:

  def test_concurrent_calls_merge_into_one_batch(self):
    batch_sizes = []

    @db.batch_fn_with_options(minimum_batch_size=2,
                              maximum_batch_size=1024,
                              timeout_ms=5000)
    def f(a):
      batch_sizes.append(a.shape[0])
      return a * 10

    try:
      out1, out2 = _run_threads([
          lambda: f(np.array([1.0])),
          lambda: f(np.array([2.0])),
      ])
      np.testing.assert_array_equal(out1, [10.0])
      np.testing.assert_array_equal(out2, [20.0])
      # min=2 forces the two calls into ONE invocation of f.
      assert batch_sizes == [2], batch_sizes
    finally:
      f.close()

  def test_each_caller_gets_its_own_slice(self):
    @db.batch_fn_with_options(minimum_batch_size=3, timeout_ms=5000)
    def f(a):
      return a * 2

    try:
      outs = _run_threads(
          [lambda v=v: f(np.array([v, v], dtype=np.float64))
           for v in (1.0, 2.0, 3.0)])
      for v, out in zip((1.0, 2.0, 3.0), outs):
        np.testing.assert_array_equal(out, [2 * v, 2 * v])
    finally:
      f.close()

  def test_maximum_batch_size_splits(self):
    batch_sizes = []
    gate = threading.Semaphore(0)

    @db.batch_fn_with_options(minimum_batch_size=2,
                              maximum_batch_size=2, timeout_ms=200)
    def f(a):
      batch_sizes.append(a.shape[0])
      return a

    try:
      _run_threads([lambda v=v: f(np.array([float(v)]))
                    for v in range(4)])
      assert sum(batch_sizes) == 4
      assert all(s <= 2 for s in batch_sizes), batch_sizes
    finally:
      f.close()
      del gate

  def test_timeout_flushes_partial_batch(self):
    @db.batch_fn_with_options(minimum_batch_size=8, timeout_ms=100)
    def f(a):
      return a + 1

    try:
      t0 = time.monotonic()
      out = f(np.array([1.0]))  # never reaches min=8
      dt = time.monotonic() - t0
      np.testing.assert_array_equal(out, [2.0])
      assert dt < 10, dt  # flushed by timeout, not stuck
    finally:
      f.close()


class TestErrors:

  def test_error_propagates_to_caller(self):
    @db.batch_fn
    def f(a):
      raise ValueError('deliberate kaboom')

    try:
      with pytest.raises(db.BatcherError, match='deliberate kaboom'):
        f(np.array([1.0]))
    finally:
      f.close()

  def test_error_hits_only_the_affected_batch(self):
    calls = []

    @db.batch_fn_with_options(minimum_batch_size=1, timeout_ms=10)
    def f(a):
      calls.append(a.shape[0])
      if float(a[0]) < 0:
        raise ValueError('negative!')
      return a

    try:
      with pytest.raises(db.BatcherError, match='negative!'):
        f(np.array([-1.0]))
      np.testing.assert_array_equal(f(np.array([5.0])), [5.0])
    finally:
      f.close()

  def test_shape_validation_wrong_trailing_shape(self):
    @db.batch_fn_with_options(minimum_batch_size=1, timeout_ms=10)
    def f(a):
      return a

    try:
      f(np.zeros((1, 3), np.float32))
      with pytest.raises(ValueError, match='mismatch'):
        f(np.zeros((1, 4), np.float32))
      with pytest.raises(ValueError, match='mismatch'):
        f(np.zeros((1, 3), np.float64))
    finally:
      f.close()

  def test_scalar_input_rejected(self):
    @db.batch_fn
    def f(a):
      return a

    try:
      with pytest.raises(ValueError, match='leading batch dim'):
        f(np.float32(1.0))
    finally:
      f.close()

  def test_rows_over_maximum_rejected(self):
    @db.batch_fn_with_options(maximum_batch_size=2, timeout_ms=10)
    def f(a):
      return a

    try:
      with pytest.raises(ValueError, match='maximum_batch_size'):
        f(np.zeros((3,), np.float32))
    finally:
      f.close()


class TestShutdown:

  def test_close_cancels_pending_compute(self):
    release = threading.Event()

    @db.batch_fn_with_options(minimum_batch_size=4, timeout_ms=60000)
    def f(a):
      return a

    results = []

    def caller():
      try:
        f(np.array([1.0]))
        results.append('ok')
      except db.BatcherCancelled:
        results.append('cancelled')

    t = threading.Thread(target=caller)
    t.start()
    time.sleep(0.2)  # caller is parked waiting for min=4
    f.close()
    t.join(timeout=10)
    assert not t.is_alive()
    assert results == ['cancelled']
    del release

  def test_compute_after_close_raises(self):
    @db.batch_fn
    def f(a):
      return a

    f(np.array([1.0]))
    f.close()
    with pytest.raises(db.BatcherCancelled):
      f(np.array([1.0]))


class TestOutOfOrder:
  """Drive the low-level API directly: answers may land out of order
  across batches (the reference's out-of-order SetOutputs test)."""

  def test_out_of_order_set_outputs(self):
    b = db.Batcher(num_tensors=1, minimum_batch_size=1,
                   maximum_batch_size=1, timeout_ms=10)
    try:
      outs = {}

      def caller(v):
        def run():
          outs[v] = b.compute([np.array([v], np.float32)])[0]
        return run

      t1 = threading.Thread(target=caller(1.0))
      t1.start()
      time.sleep(0.05)
      t2 = threading.Thread(target=caller(2.0))
      t2.start()

      # max=1 ⇒ two separate batches, FIFO order.
      b1, arr1 = b.get_batch()
      b2, arr2 = b.get_batch()
      np.testing.assert_array_equal(arr1[0], [1.0])
      np.testing.assert_array_equal(arr2[0], [2.0])
      # Answer the SECOND batch first.
      b.set_outputs(b2, [arr2[0] * 100])
      t2.join(timeout=10)
      # The second caller is answered while the FIRST still waits.
      assert outs.get(2.0) is not None and t1.is_alive()
      b.set_outputs(b1, [arr1[0] * 100])
      t1.join(timeout=10)
      np.testing.assert_array_equal(outs[1.0], [100.0])
      np.testing.assert_array_equal(outs[2.0], [200.0])
    finally:
      b.close()

  def test_set_outputs_wrong_rows_raises(self):
    b = db.Batcher(num_tensors=1, minimum_batch_size=1, timeout_ms=10)
    try:
      t = threading.Thread(
          target=lambda: pytest.raises(
              db.BatcherCancelled,
              lambda: b.compute([np.array([1.0], np.float32)])))
      t.start()
      bid, arrs = b.get_batch()
      with pytest.raises(ValueError, match='rows'):
        b.set_outputs(bid, [np.zeros((5,), np.float32)])
    finally:
      b.close()
      t.join(timeout=5)


class TestConcurrencyStress:

  def test_many_threads_many_calls(self):
    """48 threads × 20 calls — the reference's actor-thread regime."""
    @db.batch_fn_with_options(minimum_batch_size=8,
                              maximum_batch_size=64, timeout_ms=5)
    def f(a):
      return a * 2 + 1

    try:
      def worker(tid):
        def run():
          for i in range(20):
            v = float(tid * 100 + i)
            out = f(np.array([v, v + 0.5]))
            np.testing.assert_array_equal(out, [2 * v + 1, 2 * v + 2])
          return True
        return run

      results = _run_threads([worker(t) for t in range(48)])
      assert all(results)
    finally:
      f.close()
