"""Round-17 multi-process drills beyond the seed suite: the two
deferred debts the ROADMAP parked on "real multi-host" land here —

- the SDC sentinel across processes (PR 9 gated it single-controller
  pending an in-graph all-gather of the per-replica fingerprints;
  train_parallel.make_sdc_fingerprint_fn now all-gathers, so a
  perturbed replica must still drive detection → incident → collective
  rollback when the mesh spans processes), and
- cross-host trace spans (PR 10 wall-clock-stamped spans exactly so
  hops could land on different hosts; a real 2-process run with a
  remote actor host must yield trace_report joins across the wire,
  skew-tolerant — a skewed hop renders None, never a fake latency).

Same harness discipline as tests/test_multihost.py: children are real
OS processes joining jax.distributed over gloo; every assert here
reads child stdout or on-disk artifacts.
"""

import pytest

import json
import os
import sys

import test_multihost as mh
import _multihost_child
import _remote_actor_child

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), 'scripts'))
import trace_report  # noqa: E402


@pytest.mark.slow  # tier-1 wall trim (round 20); ci.sh full-suite lane runs it
def test_sdc_mismatch_rolls_back_across_processes(tmp_path):
  """2 processes x 2 devices, pure-DP 4-way mesh: one replica's
  fingerprint lane is perturbed mid-run (the replica_divergence drill,
  installed identically in both children so the probe is lockstep).
  Both processes must see the mismatch through the all-gathered
  fingerprint vector, count it as an SDC incident, and complete the
  broadcast-coordinated rollback — then train on to the step budget."""
  logdir = str(tmp_path)
  procs = mh._spawn_children(logdir, mh._free_port(),
                             extra_args=('sdc',))
  outs = []
  try:
    for p in procs:
      out, _ = p.communicate(timeout=280)
      outs.append(out)
  finally:
    for p in procs:
      if p.poll() is None:
        p.kill()
        p.communicate()
  for i, (p, out) in enumerate(zip(procs, outs)):
    assert p.returncode == 0, f'child {i} failed:\n{out[-3000:]}'
    assert f'child {i}: sdc ok' in out, out[-2000:]

  # The incident is on disk on BOTH processes' streams: the mismatch
  # names the per-replica fingerprint vector, the rollback names the
  # restored step. (Detection is global — every host read the same
  # all-gathered vector.)
  for fname in ('incidents.jsonl', 'incidents_p1.jsonl'):
    with open(os.path.join(logdir, fname)) as f:
      kinds = [json.loads(line)['kind'] for line in f]
    assert 'sdc_replica_mismatch' in kinds, (fname, kinds)
    assert 'rollback' in kinds, (fname, kinds)


@pytest.mark.slow  # tier-1 wall trim (round 20); ci.sh full-suite lane runs it
def test_cross_host_trace_spans_join(tmp_path):
  """The mixed topology (remote actor host over TCP into process 0,
  local fleet on process 1) under default-ON tracing: spans whose hops
  were stamped on DIFFERENT hosts must reconstruct through
  trace_report.span_hop_deltas — wire hops present with real
  latencies, and any clock-skewed hop rendered None rather than a
  negative/zero fake (the PR 10 wall-clock design, verified on a real
  jax.distributed run)."""
  logdir = str(tmp_path)
  coord_port, ingest_port = mh._free_ports(2)
  procs = mh._spawn_children(logdir, coord_port,
                             extra_args=('mixed', str(ingest_port)))
  actor = _remote_actor_child.spawn(
      f'127.0.0.1:{ingest_port}', _multihost_child.CHILD_CONFIG)
  outs = []
  try:
    for p in procs:
      out, _ = p.communicate(timeout=280)
      outs.append(out)
    actor_out, _ = actor.communicate(timeout=120)
  finally:
    for p in procs + [actor]:
      if p.poll() is None:
        p.kill()
        p.communicate()
  for i, (p, out) in enumerate(zip(procs, outs)):
    assert p.returncode == 0, f'child {i} failed:\n{out[-3000:]}'
  assert actor.returncode == 0, actor_out[-2000:]

  # Process 0 trained on remote unrolls only: its trace stream carries
  # spans stamped on the actor host (send) AND on the learner host
  # (wire/commit/staged/serve/step) — the cross-host join.
  with open(os.path.join(logdir, 'traces.jsonl')) as f:
    batches = [json.loads(line) for line in f]
  assert batches, 'process 0 emitted no trace records'
  cross_host_spans = 0
  hop_pairs = set()
  for batch in batches:
    for span in batch.get('spans', []):
      deltas, e2e = trace_report.span_hop_deltas(span)
      names = {n for (pair, _) in deltas for n in pair}
      if 'send' in names and ('wire' in names or 'commit' in names):
        cross_host_spans += 1
        for pair, ms in deltas:
          hop_pairs.add(pair)
          # Skew tolerance: every delta is either a real non-negative
          # latency or None — span_hop_deltas must never emit a
          # negative number for consumers to launder into a
          # percentile.
          assert ms is None or ms >= 0, (pair, ms)
      if e2e is not None:
        assert e2e >= 0
  assert cross_host_spans > 0, 'no span crossed the wire'
  assert ('send', 'wire') in hop_pairs, hop_pairs
