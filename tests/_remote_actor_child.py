"""Subprocess body for the remote-actor tests: an actor-only host with
NO accelerator (jax platform forced to cpu before first use) builds an
env fleet + CPU inference and streams unrolls to the learner's ingest
server. Run: python _remote_actor_child.py <host:port> <config-json>.
"""

import json
import sys


def main():
  address = sys.argv[1]
  overrides = json.loads(sys.argv[2])
  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.runtime import remote
  cfg = Config(**overrides)
  sent = remote.run_remote_actor(cfg, address, task=0,
                                 stop_after_unrolls=500,
                                 platform='cpu')
  print(f'CHILD_OK {sent}', flush=True)


if __name__ == '__main__':
  main()
