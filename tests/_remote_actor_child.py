"""Subprocess body for the remote-actor tests: an actor-only host with
NO accelerator (jax platform forced to cpu before first use) builds an
env fleet + CPU inference and streams unrolls to the learner's ingest
server. Run: python _remote_actor_child.py <host:port> <config-json>,
or use `spawn()` (the one child-launch helper shared by the test
files).
"""

import json
import os
import subprocess
import sys


def spawn(address, overrides):
  """Popen this script as a no-accelerator actor child.

  The single place that knows how to launch it (script-run children
  resolve sys.path from the script dir, so the package root must be on
  PYTHONPATH; XLA_FLAGS/JAX_PLATFORMS are stripped — the child
  provisions itself)."""
  repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
  env = {k: v for k, v in os.environ.items()
         if k not in ('XLA_FLAGS', 'JAX_PLATFORMS')}
  existing = env.get('PYTHONPATH', '')
  env['PYTHONPATH'] = (repo + os.pathsep + existing if existing
                       else repo)
  return subprocess.Popen(
      [sys.executable, os.path.abspath(__file__), address,
       json.dumps(overrides)],
      cwd=repo, env=env, stdout=subprocess.PIPE,
      stderr=subprocess.STDOUT, text=True)


def main():
  address = sys.argv[1]
  overrides = json.loads(sys.argv[2])
  from scalable_agent_tpu.config import Config
  from scalable_agent_tpu.runtime import remote
  cfg = Config(**overrides)
  sent = remote.run_remote_actor(cfg, address, task=0,
                                 stop_after_unrolls=500,
                                 platform='cpu')
  print(f'CHILD_OK {sent}', flush=True)


if __name__ == '__main__':
  main()
