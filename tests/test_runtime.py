"""Runtime integration: InferenceServer + TrajectoryBuffer + learner.

The production topology on fake envs: N actor THREADS sharing one
batched-inference server (C++ batcher → one jitted call), unrolls
flowing through the bounded buffer with backpressure, prefetched
batches feeding the jitted train step. The reference never tests this
glue (SURVEY §4); we do.
"""

import threading
import time

import numpy as np
import pytest

import jax

from scalable_agent_tpu import learner as learner_lib
from scalable_agent_tpu.config import Config
from scalable_agent_tpu.envs.fake import ContextualBanditEnv, FakeEnv
from scalable_agent_tpu.models import ImpalaAgent, init_params
from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
from scalable_agent_tpu.runtime.actor import Actor, run_actor_loop
from scalable_agent_tpu.runtime.inference import InferenceServer
from scalable_agent_tpu.runtime.ring_buffer import (
    BatchPrefetcher, Closed, TrajectoryBuffer)

H, W, A = 24, 32, 3
OBS = {'frame': (H, W, 3), 'instr_len': MAX_INSTRUCTION_LEN}


def _mk(num_actions=A, **cfg_kw):
  agent = ImpalaAgent(num_actions=num_actions, torso='shallow',
                      use_instruction=False)
  params = init_params(agent, jax.random.PRNGKey(0), OBS)
  cfg = Config(**cfg_kw)
  return agent, params, cfg


class TestTrajectoryBuffer:

  def test_fifo_and_backpressure(self):
    buf = TrajectoryBuffer(capacity_unrolls=2)
    buf.put('a')
    buf.put('b')
    with pytest.raises(TimeoutError):
      buf.put('c', timeout=0.05)  # full → blocks
    assert buf.get() == 'a'
    buf.put('c')  # space again
    assert buf.get() == 'b'
    assert buf.get() == 'c'

  def test_close_wakes_blocked_producer(self):
    buf = TrajectoryBuffer(capacity_unrolls=1)
    buf.put('x')
    states = []

    def producer():
      try:
        buf.put('y')  # parks: buffer full
      except Closed:
        states.append('producer-closed')

    tp = threading.Thread(target=producer)
    tp.start()
    time.sleep(0.05)
    buf.close()
    tp.join(timeout=5)
    assert not tp.is_alive()
    assert states == ['producer-closed']
    # Queued items still drain after close, then Closed.
    assert buf.get() == 'x'
    with pytest.raises(Closed):
      buf.get()

  def test_get_batch_larger_than_capacity_streams(self):
    # The reference's capacity-1 FIFOQueue feeds dequeue_many(batch):
    # dequeues free producer slots incrementally, so batch > capacity
    # must work (no atomic-residency requirement).
    from scalable_agent_tpu.structs import ActorOutput
    buf = TrajectoryBuffer(capacity_unrolls=1)
    T, B = 4, 3

    def mk(i):
      return ActorOutput(
          level_name=np.int32(0),
          agent_state=np.full((1, 2), i, np.float32),
          env_outputs=np.full((T,), i, np.float32),
          agent_outputs=np.full((T,), i, np.float32))

    def producer():
      for i in range(B):
        buf.put(mk(i))

    tp = threading.Thread(target=producer)
    tp.start()
    batch = buf.get_batch(B, timeout=10)
    tp.join(timeout=5)
    assert batch.env_outputs.shape == (T, B)
    np.testing.assert_array_equal(batch.env_outputs[0], [0, 1, 2])
    assert batch.agent_state.shape == (B, 2)

  def test_get_batch_timeout_drops_nothing(self):
    from scalable_agent_tpu.structs import ActorOutput
    buf = TrajectoryBuffer(capacity_unrolls=4)
    item = ActorOutput(np.int32(7), np.zeros((1, 2), np.float32),
                       np.zeros((4,), np.float32),
                       np.zeros((4,), np.float32))
    buf.put(item)
    with pytest.raises(TimeoutError):
      buf.get_batch(2, timeout=0.05)  # partial: pushed back, not lost
    assert len(buf) == 1
    got = buf.get()
    assert got.level_name == 7

  def test_close_wakes_blocked_consumer(self):
    buf = TrajectoryBuffer(capacity_unrolls=1)
    states = []

    def consumer():
      try:
        buf.get()  # parks: buffer empty
      except Closed:
        states.append('consumer-closed')

    tc = threading.Thread(target=consumer)
    tc.start()
    time.sleep(0.05)
    buf.close()
    tc.join(timeout=5)
    assert not tc.is_alive()
    assert states == ['consumer-closed']


class TestBatchPrefetcher:

  @staticmethod
  def _item(i=0):
    from scalable_agent_tpu.structs import ActorOutput
    return ActorOutput(np.int32(0),
                       np.full((1, 2), i, np.float32),
                       np.full((4,), i, np.float32),
                       np.full((4,), i, np.float32))

  def test_double_buffering_hides_staging(self):
    """Acceptance (ISSUE 1): with staging depth >= 2 and producers
    keeping up, no step blocks on `place_fn` (the device_put stand-in)
    once the pipeline is primed — the overlap counters must show it."""
    buf = TrajectoryBuffer(capacity_unrolls=8)
    stop = threading.Event()

    def produce():
      while not stop.is_set():
        try:
          buf.put(self._item(), timeout=0.1)
        except (TimeoutError, Closed):
          continue

    producer = threading.Thread(target=produce, daemon=True)
    producer.start()

    def slow_place(batch):  # simulated H2D: 20 ms per staged batch
      time.sleep(0.02)
      return batch

    pf = BatchPrefetcher(buf, batch_size=2, place_fn=slow_place,
                         depth=2)
    try:
      pf.get(timeout=10)  # prime the pipeline (this one MAY block)
      for _ in range(10):
        time.sleep(0.03)  # simulated step: longer than one staging
        pf.get(timeout=10)
      stats = pf.stats()
      assert stats['depth'] == 2
      assert stats['gets'] == 11
      assert stats['staged_batches'] >= 11
      # Steady state never waited: at most the priming get blocked.
      assert stats['blocked_gets'] <= 1, stats
      assert stats['h2d_overlap_fraction'] >= 0.8, stats
    finally:
      stop.set()
      pf.close()
      producer.join(timeout=5)

  def test_depth_bounds_staged_batches(self):
    """depth bounds the staged-ahead pipeline (each slot extends the
    policy-lag bound by one batch, so the prefetcher must not run
    ahead of it): `depth` queued batches plus the one the thread has
    already dispatched and is parking — never more."""
    buf = TrajectoryBuffer(capacity_unrolls=8)
    for i in range(8):
      buf.put(self._item(i))
    staged = []
    pf = BatchPrefetcher(buf, batch_size=1,
                         place_fn=lambda b: staged.append(b) or b,
                         depth=3)
    try:
      deadline = time.monotonic() + 5
      while len(staged) < 4 and time.monotonic() < deadline:
        time.sleep(0.01)
      time.sleep(0.1)  # would overfill if depth were not enforced
      assert len(staged) == 4  # 3 queued + 1 parked at the full gate
      pf.get(timeout=5)
      deadline = time.monotonic() + 5
      while len(staged) < 5 and time.monotonic() < deadline:
        time.sleep(0.01)
      time.sleep(0.05)
      assert len(staged) == 5  # one slot freed -> exactly one more
    finally:
      pf.close()


class TestInferenceServer:

  def test_actors_share_batched_inference(self):
    agent, params, cfg = _mk(
        batch_size=4, unroll_length=8, num_action_repeats=1,
        inference_min_batch=1, inference_max_batch=8,
        inference_timeout_ms=20)
    server = InferenceServer(agent, params, cfg, seed=3)
    try:
      actors = [
          Actor(FakeEnv(height=H, width=W, num_actions=A, seed=i),
                server.policy, agent.initial_state(1), 8)
          for i in range(4)]
      unrolls = [[] for _ in actors]

      def run(i):
        for _ in range(2):
          unrolls[i].append(actors[i].unroll())

      threads = [threading.Thread(target=run, args=(i,))
                 for i in range(4)]
      for t in threads:
        t.start()
      for t in threads:
        t.join(timeout=60)
      for lst in unrolls:
        assert len(lst) == 2
        for u in lst:
          assert u.env_outputs.reward.shape == (9,)
          assert np.isfinite(
              np.asarray(u.agent_outputs.policy_logits)).all()
          assert (np.asarray(u.agent_outputs.action) >= 0).all()
          assert (np.asarray(u.agent_outputs.action) < A).all()
      # Merge telemetry: all requests accounted for, and with 4
      # concurrent actors against one computation thread some calls
      # MUST have merged (calls strictly < requests) — the
      # single-machine throughput lever the stats exist to expose.
      stats = server.stats()
      assert stats['requests'] >= 4 * 2 * 8
      assert stats['calls'] < stats['requests']
      assert stats['mean_batch'] > 1.0
    finally:
      server.close()

  def test_pad_batch_to_compiles_one_bucket(self):
    """VERDICT r3 W5: with pad_batch_to set (eval), every merged
    batch pads to ONE bucket — warmup executes exactly one padded
    shape and live traffic of any size reuses it (no tail compiles
    when levels finish)."""
    agent, params, cfg = _mk(
        batch_size=4, unroll_length=4, num_action_repeats=1,
        inference_min_batch=1, inference_max_batch=64,
        inference_timeout_ms=5)
    from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
    server = InferenceServer(agent, params, cfg, seed=3,
                             pad_batch_to=6)
    # Record FULL input shapes: "one compile" means one shape tuple —
    # a batch-rows-only probe would miss a second compile from any
    # other dimension (e.g. an instr-length mismatch between warmup
    # and live traffic).
    seen_shapes = set()
    real_step = server._step

    def recording_step(params_, rng, *batch_args):
      seen_shapes.add(tuple(a.shape for a in batch_args))
      return real_step(params_, rng, *batch_args)

    server._step = recording_step
    try:
      # Same call evaluate() makes: max_size = this host's level
      # count; with the pad floor every candidate size lands in ONE
      # bucket, so warmup executes exactly one padded shape.
      server.warmup({'frame': (H, W, 3),
                     'instr_len': MAX_INSTRUCTION_LEN}, max_size=6)
      assert len(seen_shapes) == 1, seen_shapes
      assert next(iter(seen_shapes))[0] == (8,)  # pow2(6) rows

      # Live batch-1 traffic pads to the same bucket — the SAME full
      # shape tuple, so no further compile.
      actor = Actor(FakeEnv(height=H, width=W, num_actions=A, seed=0),
                    server.policy, agent.initial_state(1), 4)
      actor.unroll()
      assert len(seen_shapes) == 1, seen_shapes
    finally:
      server.close()

  def test_concurrent_param_updates_under_load(self):
    """Publisher hammering update_params while actor threads infer:
    the params pointer swap, the PRNG key lock, and the batcher must
    hold up under churn (the production cadence is one publish per
    learner step against ~48 inferring actors)."""
    agent, params, cfg = _mk(
        batch_size=4, unroll_length=8, num_action_repeats=1,
        inference_min_batch=1, inference_max_batch=8,
        inference_timeout_ms=5)
    server = InferenceServer(agent, params, cfg, seed=5)
    stop = threading.Event()
    try:
      actors = [
          Actor(FakeEnv(height=H, width=W, num_actions=A, seed=i),
                server.policy, agent.initial_state(1), 8)
          for i in range(3)]

      def publisher():
        i = 0
        while not stop.is_set():
          scale = 1.0 + (i % 5) * 0.1
          server.update_params(jax.tree_util.tree_map(
              lambda x: x * scale, params))
          i += 1
          time.sleep(0.005)

      pub = threading.Thread(target=publisher, daemon=True)
      pub.start()
      unrolls = [[] for _ in actors]

      def run(i):
        for _ in range(3):
          unrolls[i].append(actors[i].unroll())

      threads = [threading.Thread(target=run, args=(i,))
                 for i in range(3)]
      for t in threads:
        t.start()
      for t in threads:
        t.join(timeout=120)
      # Let the publisher pass the count gate before stopping it: on a
      # loaded 1-core host the GIL can starve the publisher thread for
      # the actors' whole (warm-cache) run — the property under test
      # is swap-safety under churn, not a publish-rate SLO.
      deadline = time.monotonic() + 30
      while (server.stats()['params_version'] <= 3
             and time.monotonic() < deadline):
        time.sleep(0.01)
      stop.set()
      pub.join(timeout=10)
      for lst in unrolls:
        assert len(lst) == 3
        for u in lst:
          assert np.isfinite(
              np.asarray(u.agent_outputs.policy_logits)).all()
      assert server.stats()['params_version'] > 3
    finally:
      stop.set()
      server.close()

  def test_update_params_is_picked_up(self):
    agent, params, cfg = _mk(inference_timeout_ms=5)
    server = InferenceServer(agent, params, cfg)
    try:
      env = FakeEnv(height=H, width=W, num_actions=A)
      actor = Actor(env, server.policy, agent.initial_state(1), 4)
      u1 = actor.unroll()
      zeroed = jax.tree_util.tree_map(lambda x: x * 0, params)
      server.update_params(zeroed)
      u2 = actor.unroll()
      # With zero params, logits collapse to a constant vector.
      logits = np.asarray(u2.agent_outputs.policy_logits[1:])
      assert np.allclose(logits, logits[..., :1], atol=1e-6)
      del u1
    finally:
      server.close()



  def test_auto_min_batch_resolves_to_fleet_size(self, batcher_options_spy):
    """inference_min_batch=0 (auto) floors the merge at the fleet
    size, clamped to max_batch (docs/PERF.md round-5 batcher sweep)."""
    agent, params, cfg = _mk(
        batch_size=4, unroll_length=8, num_action_repeats=1,
        inference_min_batch=0, inference_max_batch=8,
        inference_timeout_ms=20)
    server = InferenceServer(agent, params, cfg, seed=3, fleet_size=6)
    server.close()
    assert batcher_options_spy[-1]['minimum_batch_size'] == 6
    # Clamped at max_batch when the fleet is bigger.
    server = InferenceServer(agent, params, cfg, seed=3, fleet_size=99)
    server.close()
    assert batcher_options_spy[-1]['minimum_batch_size'] == 8
    # Explicit min_batch is untouched by fleet_size.
    agent, params, cfg = _mk(
        batch_size=4, unroll_length=8, num_action_repeats=1,
        inference_min_batch=2, inference_max_batch=8,
        inference_timeout_ms=20)
    server = InferenceServer(agent, params, cfg, seed=3, fleet_size=6)
    server.close()
    assert batcher_options_spy[-1]['minimum_batch_size'] == 2

  def test_auto_min_batch_serves_a_fleet(self):
    """Auto merge floor end-to-end: 3 actors against min_batch=0 —
    every call should carry all 3 once the fleet is in steady state,
    and the timeout must keep a lone straggler from deadlocking."""
    agent, params, cfg = _mk(
        batch_size=3, unroll_length=6, num_action_repeats=1,
        inference_min_batch=0, inference_max_batch=8,
        inference_timeout_ms=50)
    server = InferenceServer(agent, params, cfg, seed=3, fleet_size=3)
    try:
      actors = [
          Actor(FakeEnv(height=H, width=W, num_actions=A, seed=i),
                server.policy, agent.initial_state(1), 6)
          for i in range(3)]
      results = [None] * 3

      def run(i):
        results[i] = actors[i].unroll()

      threads = [threading.Thread(target=run, args=(i,))
                 for i in range(3)]
      for t in threads:
        t.start()
      for t in threads:
        t.join(timeout=60)
      assert all(r is not None for r in results)
      stats = server.stats()
      assert stats['requests'] >= 3 * 6
      assert stats['calls'] >= 1
      # NOTE deliberately no merge-ratio assert: on a loaded 1-core CI
      # host thread skew can expire the 50 ms window with partial
      # batches — the floor-resolution contract is pinned by the
      # monkeypatch test above, and the steady-state merge (3.92/4)
      # was measured on the real pipeline (docs/PERF.md r5 sweep).
      # This test pins the no-deadlock property.
    finally:
      server.close()

def _cfg_variant(**kw):
  base = dict(batch_size=2, unroll_length=6, num_action_repeats=1,
              inference_min_batch=1, inference_max_batch=8,
              inference_timeout_ms=5)
  base.update(kw)
  return base


def _scripted_inputs(steps, seed=0):
  """Deterministic per-step (frame, reward, done) script with done
  edges (t % 7 == 0 past t=0) — both servers must see byte-identical
  inputs for the golden parity gate."""
  from scalable_agent_tpu.structs import StepOutput, StepOutputInfo
  rng = np.random.RandomState(seed)
  frames = rng.randint(0, 255, (steps, H, W, 3)).astype(np.uint8)
  from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
  instr = np.zeros((MAX_INSTRUCTION_LEN,), np.int32)

  def env_out(t):
    return StepOutput(
        reward=np.float32(0.1 * t),
        info=StepOutputInfo(np.float32(0), np.int32(0)),
        done=np.bool_(t > 0 and t % 7 == 0),
        observation=(frames[t], instr))

  return env_out


def _drive(server, env_out, steps, state=None, feedback=True):
  """Sequential policy() loop; returns the per-step (action, logits,
  baseline) plus the final carry snapshot and the state object (slot
  handle in cache mode). feedback=False pins prev_action to 0 so the
  trace depends only on (inputs, carry), not the sampling key stream
  (what the zeroed-slot-reuse parity needs)."""
  if state is None:
    state = server.initial_core_state()
  prev = np.int32(0)
  outs = []
  for t in range(steps):
    out, state = server.policy(prev, env_out(t), state)
    outs.append((int(out.action),
                 np.asarray(out.policy_logits).copy(),
                 float(out.baseline)))
    if feedback:
      prev = np.int32(out.action)
  snap = state.snapshot() if hasattr(state, 'snapshot') else state
  return outs, tuple(np.asarray(x) for x in snap), state


def _assert_traces_equal(a, b):
  assert len(a) == len(b)
  for t, (ra, rb) in enumerate(zip(a, b)):
    assert ra[0] == rb[0], f'step {t}: action {ra[0]} != {rb[0]}'
    np.testing.assert_array_equal(ra[1], rb[1], err_msg=f'step {t}')
    assert ra[2] == rb[2], f'step {t}: baseline'


class TestStateCache:
  """The round-7 tentpole's golden parity gate: the device-resident
  state arena must be numerics-IDENTICAL to the carry-passing path —
  same seeds → identical actions/logits/baselines across multiple
  unrolls, through done edges, respawn slot reuse, and the sharded
  eval mesh."""

  def _servers(self, mesh=None, **cfg_kw):
    agent, params, _ = _mk()
    carry_cfg = Config(**_cfg_variant(inference_state_cache=False,
                                      **cfg_kw))
    cache_cfg = Config(**_cfg_variant(inference_state_cache=True,
                                      **cfg_kw))
    carry = InferenceServer(agent, params, carry_cfg, seed=3, mesh=mesh)
    cache = InferenceServer(agent, params, cache_cfg, seed=3, mesh=mesh)
    return carry, cache

  def test_golden_parity_multi_unroll_with_done_edges(self):
    carry, cache = self._servers()
    try:
      env_out = _scripted_inputs(24)
      a, snap_a, _ = _drive(carry, env_out, 24)   # >= 2 unrolls of 8
      b, snap_b, _ = _drive(cache, env_out, 24)
      _assert_traces_equal(a, b)
      for x, y in zip(snap_a, snap_b):
        np.testing.assert_array_equal(x, y)
    finally:
      carry.close()
      cache.close()

  def test_slot_release_and_zeroed_reuse(self):
    """Respawn slot reuse: release → re-acquire returns the SAME slot
    ZEROED, so the replacement's trace matches the original's
    from-scratch trace — no stale carry served."""
    agent, params, _ = _mk()
    cfg = Config(**_cfg_variant(inference_state_cache=True,
                                inference_state_slots=2))
    server = InferenceServer(agent, params, cfg, seed=3)
    try:
      env_out = _scripted_inputs(6)
      # feedback=False: pin prev_action so the trace depends only on
      # (inputs, carry) — the key stream advances between the two
      # drives, so sampled actions may differ, exactly as a fresh
      # carry-passing actor's would.
      outs1, snap1, handle1 = _drive(server, env_out, 6,
                                     feedback=False)
      assert np.abs(snap1[0]).max() > 0  # carry actually advanced
      assert server.slots_free() == 1
      handle1.release()
      assert server.slots_free() == 2
      handle1.release()  # idempotent
      assert server.slots_free() == 2
      # LIFO reuse: the next acquire returns the SAME slot, zeroed —
      # logits/baseline (rng-free) must replay exactly.
      outs2, snap2, handle2 = _drive(server, env_out, 6,
                                     feedback=False)
      assert handle2.slot == handle1.slot
      for x, y in zip(outs1, outs2):
        np.testing.assert_array_equal(x[1], y[1])
        assert x[2] == y[2]
      for x, y in zip(snap1, snap2):
        np.testing.assert_array_equal(x, y)
      # A released handle must not be usable (a straggler thread must
      # fail loudly, not scatter into the new owner's slot).
      with pytest.raises(RuntimeError, match='released'):
        server.policy(np.int32(0), env_out(0), handle1)
    finally:
      server.close()

  def test_actor_death_mid_call_reclaims_slot(self):
    """Satellite: batcher-timeout/slot-leak — an actor whose policy
    call dies (server closed under it / env crash) unwinds through
    run_actor_loop's finally → actor.close() → the slot returns to
    the free list."""
    agent, params, cfg = _mk(**_cfg_variant(
        inference_state_cache=True, inference_timeout_ms=5))
    server = InferenceServer(agent, params, cfg, seed=3, fleet_size=2)
    from scalable_agent_tpu.runtime.ring_buffer import TrajectoryBuffer
    buf = TrajectoryBuffer(8)
    stop = threading.Event()
    total = server.slots_free()

    class DyingEnv(FakeEnv):

      def __init__(self, **kw):
        super().__init__(**kw)
        self._steps = 0

      def step(self, action):
        self._steps += 1
        if self._steps >= 3:
          raise RuntimeError('env crashed mid-unroll')
        return super().step(action)

    failures = []
    actor = Actor(DyingEnv(height=H, width=W, num_actions=A, seed=0),
                  server.policy, server.initial_core_state(), 8)
    try:
      assert server.slots_free() == total - 1
      run_actor_loop(actor, buf, stop, on_failure=failures.append)
      assert len(failures) == 1
      # The dying actor's slot came back; a fresh acquire is zeroed.
      assert server.slots_free() == total
      snap = server.initial_core_state().snapshot()
      assert np.abs(np.asarray(snap[0])).max() == 0
      assert np.abs(np.asarray(snap[1])).max() == 0
    finally:
      stop.set()
      server.close()
      buf.close()

  def test_mid_call_close_releases_slots_via_fleet_loop(self):
    """Actors parked IN policy() when the server closes: the
    BatcherCancelled unwind must still release every slot."""
    agent, params, cfg = _mk(**_cfg_variant(
        inference_state_cache=True,
        inference_min_batch=8,          # never satisfied: callers park
        inference_timeout_ms=60_000))
    server = InferenceServer(agent, params, cfg, seed=3, fleet_size=2)
    from scalable_agent_tpu.runtime.ring_buffer import TrajectoryBuffer
    buf = TrajectoryBuffer(8)
    stop = threading.Event()
    total = server.slots_free()
    actors = [Actor(FakeEnv(height=H, width=W, num_actions=A, seed=i),
                    server.policy, server.initial_core_state(), 8)
              for i in range(2)]
    threads = [threading.Thread(target=run_actor_loop,
                                args=(a, buf, stop), daemon=True)
               for a in actors]
    for t in threads:
      t.start()
    time.sleep(0.3)  # both park in the merge wait
    assert server.slots_free() == total - 2
    stop.set()        # stop FIRST: cancellation is then a clean exit
    server.close()
    for t in threads:
      t.join(timeout=10)
    assert not any(t.is_alive() for t in threads)
    assert server.slots_free() == total
    buf.close()

  def test_arena_exhaustion_degrades_not_raises(self):
    """Round 9: the old `RuntimeError('state arena exhausted')` is
    UNREACHABLE — under the default (block) admission policy an
    exhausted arena parks the caller, and only the deadline produces
    a clean, counted SlotUnavailable; a freed slot unparks a waiter
    or is acquirable again."""
    from scalable_agent_tpu.runtime.inference import SlotUnavailable
    agent, params, _ = _mk()
    cfg = Config(**_cfg_variant(inference_state_cache=True,
                                inference_state_slots=1,
                                inference_admission_timeout_secs=0.2))
    assert cfg.inference_admission == 'block'  # the default policy
    server = InferenceServer(agent, params, cfg, seed=3)
    try:
      h1 = server.initial_core_state()
      with pytest.raises(SlotUnavailable, match='admission timeout'):
        server.initial_core_state()
      stats = server.stats()
      assert stats['admission_timeouts'] == 1
      assert stats['admission_waits'] == 1
      assert stats['sheds'] == 0
      h1.release()
      server.initial_core_state()  # freed slot is acquirable again
    finally:
      server.close()

  def test_state_cache_through_actor_unroll_parity(self):
    """End-to-end through the REAL Actor loop (priming call included):
    identical unrolls from a carry-passing and a state-cache server —
    including agent_state (the learner's unroll-start carry) on the
    SECOND unroll, where the cache path's once-per-unroll snapshot
    must equal the carry path's host-held state."""
    agent, params, _ = _mk()
    results = {}
    for cache in (False, True):
      cfg = Config(**_cfg_variant(inference_state_cache=cache))
      server = InferenceServer(agent, params, cfg, seed=11)
      try:
        actor = Actor(FakeEnv(height=H, width=W, num_actions=A, seed=5),
                      server.policy, server.initial_core_state(), 6)
        u1 = actor.unroll()
        u2 = actor.unroll()
        actor.close()
        results[cache] = (u1, u2)
      finally:
        server.close()
    for (ua, ub) in zip(results[False], results[True]):
      np.testing.assert_array_equal(
          np.asarray(ua.agent_outputs.action),
          np.asarray(ub.agent_outputs.action))
      np.testing.assert_array_equal(
          np.asarray(ua.agent_outputs.policy_logits),
          np.asarray(ub.agent_outputs.policy_logits))
      for sa, sb in zip(ua.agent_state, ub.agent_state):
        np.testing.assert_array_equal(np.asarray(sa), np.asarray(sb))


class TestInferencePlaneStats:

  def test_stats_percentiles_and_echo(self):
    agent, params, cfg = _mk(**_cfg_variant(
        inference_state_cache=True, inference_pipeline_depth=2))
    server = InferenceServer(agent, params, cfg, seed=3)
    try:
      env_out = _scripted_inputs(8)
      _drive(server, env_out, 8)
      stats = server.stats()
      assert stats['pipeline_depth'] == 2
      assert stats['state_cache'] is True
      assert stats['latency_p50_ms'] > 0
      assert stats['latency_p99_ms'] >= stats['latency_p50_ms']
      assert stats['inflight_peak'] >= 1
      assert stats['slots_free'] is not None
    finally:
      server.close()
    # Carry-mode echo.
    server = InferenceServer(agent, params, Config(**_cfg_variant(
        inference_pipeline_depth=1)), seed=3)
    try:
      _drive(server, _scripted_inputs(4), 4)
      stats = server.stats()
      assert stats['pipeline_depth'] == 1
      assert stats['state_cache'] is False
      assert stats['slots_free'] is None
      assert stats['inflight_peak'] == 1  # depth 1: serial dispatch
    finally:
      server.close()

  def test_pipeline_depth_bounds_inflight(self):
    """The depth semaphore is the policy-lag bound of the inference
    plane: dispatched-but-uncompleted merged calls never exceed it."""
    agent, params, cfg = _mk(**_cfg_variant(
        inference_pipeline_depth=2, inference_timeout_ms=2))
    server = InferenceServer(agent, params, cfg, seed=3)
    stop = threading.Event()
    try:
      def hammer(i):
        env_out = _scripted_inputs(1, seed=i)
        state = server.initial_core_state()
        prev = np.int32(0)
        while not stop.is_set():
          out, state = server.policy(prev, env_out(0), state)
          prev = np.int32(out.action)

      threads = [threading.Thread(target=hammer, args=(i,),
                                  daemon=True) for i in range(4)]
      for t in threads:
        t.start()
      time.sleep(1.0)
      stop.set()
      for t in threads:
        t.join(timeout=10)
      stats = server.stats()
      assert stats['calls'] > 0
      assert 1 <= stats['inflight_peak'] <= 2
    finally:
      stop.set()
      server.close()

  def test_failed_execution_recovers_key_and_arena_chain(self):
    """One failed merged execution must fail THAT batch's callers and
    nothing else: the device key (and in cache mode the arena) are
    outputs of the failed step — the server re-anchors them instead of
    serving the poisoned chain to every later call forever."""
    from scalable_agent_tpu.ops.dynamic_batching import BatcherError

    class _Poisoned:
      """Stand-in for an array whose execution failed: any host
      materialization or readiness check raises (jax semantics for
      outputs of a failed computation)."""

      def block_until_ready(self):
        raise RuntimeError('computation failed (simulated)')

      def __array__(self, dtype=None):
        raise RuntimeError('computation failed (simulated)')

    for cache in (False, True):
      agent, params, cfg = _mk(**_cfg_variant(
          inference_state_cache=cache))
      server = InferenceServer(agent, params, cfg, seed=3)
      try:
        env_out = _scripted_inputs(4)
        _drive(server, env_out, 2)  # healthy warm path
        real_step = server._step
        n_outs = 6  # both modes: key + 5 / key + 2 arenas + 3
        state = {'poisoned': False}

        def failing_step(*args):
          if not state['poisoned']:
            state['poisoned'] = True
            return tuple(_Poisoned() for _ in range(n_outs))
          return real_step(*args)

        server._step = failing_step
        handle = server.initial_core_state()
        with pytest.raises(BatcherError, match='failed'):
          server.policy(np.int32(0), env_out(0), handle)
        # The very next call succeeds: the chain was re-anchored.
        out, handle = server.policy(np.int32(0), env_out(1), handle)
        assert np.isfinite(np.asarray(out.policy_logits)).all()
        stats = server.stats()
        assert stats['chain_recoveries'] >= 1
      finally:
        server.close()

  def test_staging_failure_answers_callers_and_survives(self):
    """A make_buffers failure after the batch was dequeued must answer
    the parked callers with the error (not strand them) and must not
    kill the dispatch thread."""
    from scalable_agent_tpu.ops.dynamic_batching import BatcherError
    agent, params, cfg = _mk(**_cfg_variant())
    server = InferenceServer(agent, params, cfg, seed=3)
    try:
      env_out = _scripted_inputs(4)
      real = server._staging_for
      state = {'failed': False}

      def flaky(total_rows):
        if not state['failed']:
          state['failed'] = True
          raise MemoryError('no staging memory (simulated)')
        return real(total_rows)

      server._staging_for = flaky
      core = server.initial_core_state()
      with pytest.raises(BatcherError, match='MemoryError'):
        server.policy(np.int32(0), env_out(0), core)
      out, core = server.policy(np.int32(0), env_out(1), core)
      assert np.isfinite(np.asarray(out.policy_logits)).all()
    finally:
      server.close()

  def test_update_params_version_gate(self):
    """Satellite: an unchanged-version publish must skip the
    whole-tree copy (counted), a new version must land."""
    agent, params, cfg = _mk()
    server = InferenceServer(agent, params, cfg)
    try:
      server.update_params(params, version=7)
      assert server.stats()['params_version'] == 1
      server.update_params(params, version=7)  # same version: skipped
      stats = server.stats()
      assert stats['params_version'] == 1
      assert stats['publishes_skipped'] == 1
      server.update_params(params, version=8)
      assert server.stats()['params_version'] == 2
      # Unversioned publishes never gate (the safe default).
      server.update_params(params)
      server.update_params(params)
      stats = server.stats()
      assert stats['params_version'] == 4
      assert stats['publishes_skipped'] == 1
    finally:
      server.close()


class TestFullPipeline:

  def test_actors_buffer_prefetcher_learner(self):
    agent, params, cfg = _mk(
        batch_size=2, unroll_length=6, num_action_repeats=1,
        total_environment_frames=10**6,
        inference_min_batch=1, inference_max_batch=8,
        inference_timeout_ms=10)
    server = InferenceServer(agent, params, cfg, seed=1)
    buf = TrajectoryBuffer(capacity_unrolls=cfg.batch_size *
                           cfg.queue_capacity_batches * 2)
    stop = threading.Event()

    def actor_loop(i):
      actor = Actor(
          ContextualBanditEnv(height=H, width=W, num_actions=A,
                              seed=10 + i),
          server.policy, agent.initial_state(1), cfg.unroll_length)
      run_actor_loop(actor, buf, stop)

    threads = [threading.Thread(target=actor_loop, args=(i,))
               for i in range(3)]
    for t in threads:
      t.start()

    prefetcher = BatchPrefetcher(buf, cfg.batch_size)
    state = learner_lib.make_train_state(params, cfg)
    train_step = learner_lib.make_train_step(agent, cfg)
    try:
      losses = []
      for _ in range(4):
        batch = prefetcher.get(timeout=60)
        state, metrics = train_step(state, batch)
        server.update_params(state.params)
        losses.append(float(metrics['total_loss']))
      assert all(np.isfinite(l) for l in losses), losses
      assert int(state.update_steps) == 4
    finally:
      stop.set()
      prefetcher.close()
      server.close()
      for t in threads:
        t.join(timeout=10)
      assert not any(t.is_alive() for t in threads)
