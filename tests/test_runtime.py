"""Runtime integration: InferenceServer + TrajectoryBuffer + learner.

The production topology on fake envs: N actor THREADS sharing one
batched-inference server (C++ batcher → one jitted call), unrolls
flowing through the bounded buffer with backpressure, prefetched
batches feeding the jitted train step. The reference never tests this
glue (SURVEY §4); we do.
"""

import threading
import time

import numpy as np
import pytest

import jax

from scalable_agent_tpu import learner as learner_lib
from scalable_agent_tpu.config import Config
from scalable_agent_tpu.envs.fake import ContextualBanditEnv, FakeEnv
from scalable_agent_tpu.models import ImpalaAgent, init_params
from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
from scalable_agent_tpu.runtime.actor import Actor, run_actor_loop
from scalable_agent_tpu.runtime.inference import InferenceServer
from scalable_agent_tpu.runtime.ring_buffer import (
    BatchPrefetcher, Closed, TrajectoryBuffer)

H, W, A = 24, 32, 3
OBS = {'frame': (H, W, 3), 'instr_len': MAX_INSTRUCTION_LEN}


def _mk(num_actions=A, **cfg_kw):
  agent = ImpalaAgent(num_actions=num_actions, torso='shallow',
                      use_instruction=False)
  params = init_params(agent, jax.random.PRNGKey(0), OBS)
  cfg = Config(**cfg_kw)
  return agent, params, cfg


class TestTrajectoryBuffer:

  def test_fifo_and_backpressure(self):
    buf = TrajectoryBuffer(capacity_unrolls=2)
    buf.put('a')
    buf.put('b')
    with pytest.raises(TimeoutError):
      buf.put('c', timeout=0.05)  # full → blocks
    assert buf.get() == 'a'
    buf.put('c')  # space again
    assert buf.get() == 'b'
    assert buf.get() == 'c'

  def test_close_wakes_blocked_producer(self):
    buf = TrajectoryBuffer(capacity_unrolls=1)
    buf.put('x')
    states = []

    def producer():
      try:
        buf.put('y')  # parks: buffer full
      except Closed:
        states.append('producer-closed')

    tp = threading.Thread(target=producer)
    tp.start()
    time.sleep(0.05)
    buf.close()
    tp.join(timeout=5)
    assert not tp.is_alive()
    assert states == ['producer-closed']
    # Queued items still drain after close, then Closed.
    assert buf.get() == 'x'
    with pytest.raises(Closed):
      buf.get()

  def test_get_batch_larger_than_capacity_streams(self):
    # The reference's capacity-1 FIFOQueue feeds dequeue_many(batch):
    # dequeues free producer slots incrementally, so batch > capacity
    # must work (no atomic-residency requirement).
    from scalable_agent_tpu.structs import ActorOutput
    buf = TrajectoryBuffer(capacity_unrolls=1)
    T, B = 4, 3

    def mk(i):
      return ActorOutput(
          level_name=np.int32(0),
          agent_state=np.full((1, 2), i, np.float32),
          env_outputs=np.full((T,), i, np.float32),
          agent_outputs=np.full((T,), i, np.float32))

    def producer():
      for i in range(B):
        buf.put(mk(i))

    tp = threading.Thread(target=producer)
    tp.start()
    batch = buf.get_batch(B, timeout=10)
    tp.join(timeout=5)
    assert batch.env_outputs.shape == (T, B)
    np.testing.assert_array_equal(batch.env_outputs[0], [0, 1, 2])
    assert batch.agent_state.shape == (B, 2)

  def test_get_batch_timeout_drops_nothing(self):
    from scalable_agent_tpu.structs import ActorOutput
    buf = TrajectoryBuffer(capacity_unrolls=4)
    item = ActorOutput(np.int32(7), np.zeros((1, 2), np.float32),
                       np.zeros((4,), np.float32),
                       np.zeros((4,), np.float32))
    buf.put(item)
    with pytest.raises(TimeoutError):
      buf.get_batch(2, timeout=0.05)  # partial: pushed back, not lost
    assert len(buf) == 1
    got = buf.get()
    assert got.level_name == 7

  def test_close_wakes_blocked_consumer(self):
    buf = TrajectoryBuffer(capacity_unrolls=1)
    states = []

    def consumer():
      try:
        buf.get()  # parks: buffer empty
      except Closed:
        states.append('consumer-closed')

    tc = threading.Thread(target=consumer)
    tc.start()
    time.sleep(0.05)
    buf.close()
    tc.join(timeout=5)
    assert not tc.is_alive()
    assert states == ['consumer-closed']


class TestBatchPrefetcher:

  @staticmethod
  def _item(i=0):
    from scalable_agent_tpu.structs import ActorOutput
    return ActorOutput(np.int32(0),
                       np.full((1, 2), i, np.float32),
                       np.full((4,), i, np.float32),
                       np.full((4,), i, np.float32))

  def test_double_buffering_hides_staging(self):
    """Acceptance (ISSUE 1): with staging depth >= 2 and producers
    keeping up, no step blocks on `place_fn` (the device_put stand-in)
    once the pipeline is primed — the overlap counters must show it."""
    buf = TrajectoryBuffer(capacity_unrolls=8)
    stop = threading.Event()

    def produce():
      while not stop.is_set():
        try:
          buf.put(self._item(), timeout=0.1)
        except (TimeoutError, Closed):
          continue

    producer = threading.Thread(target=produce, daemon=True)
    producer.start()

    def slow_place(batch):  # simulated H2D: 20 ms per staged batch
      time.sleep(0.02)
      return batch

    pf = BatchPrefetcher(buf, batch_size=2, place_fn=slow_place,
                         depth=2)
    try:
      pf.get(timeout=10)  # prime the pipeline (this one MAY block)
      for _ in range(10):
        time.sleep(0.03)  # simulated step: longer than one staging
        pf.get(timeout=10)
      stats = pf.stats()
      assert stats['depth'] == 2
      assert stats['gets'] == 11
      assert stats['staged_batches'] >= 11
      # Steady state never waited: at most the priming get blocked.
      assert stats['blocked_gets'] <= 1, stats
      assert stats['h2d_overlap_fraction'] >= 0.8, stats
    finally:
      stop.set()
      pf.close()
      producer.join(timeout=5)

  def test_depth_bounds_staged_batches(self):
    """depth bounds the staged-ahead pipeline (each slot extends the
    policy-lag bound by one batch, so the prefetcher must not run
    ahead of it): `depth` queued batches plus the one the thread has
    already dispatched and is parking — never more."""
    buf = TrajectoryBuffer(capacity_unrolls=8)
    for i in range(8):
      buf.put(self._item(i))
    staged = []
    pf = BatchPrefetcher(buf, batch_size=1,
                         place_fn=lambda b: staged.append(b) or b,
                         depth=3)
    try:
      deadline = time.monotonic() + 5
      while len(staged) < 4 and time.monotonic() < deadline:
        time.sleep(0.01)
      time.sleep(0.1)  # would overfill if depth were not enforced
      assert len(staged) == 4  # 3 queued + 1 parked at the full gate
      pf.get(timeout=5)
      deadline = time.monotonic() + 5
      while len(staged) < 5 and time.monotonic() < deadline:
        time.sleep(0.01)
      time.sleep(0.05)
      assert len(staged) == 5  # one slot freed -> exactly one more
    finally:
      pf.close()


class TestInferenceServer:

  def test_actors_share_batched_inference(self):
    agent, params, cfg = _mk(
        batch_size=4, unroll_length=8, num_action_repeats=1,
        inference_min_batch=1, inference_max_batch=8,
        inference_timeout_ms=20)
    server = InferenceServer(agent, params, cfg, seed=3)
    try:
      actors = [
          Actor(FakeEnv(height=H, width=W, num_actions=A, seed=i),
                server.policy, agent.initial_state(1), 8)
          for i in range(4)]
      unrolls = [[] for _ in actors]

      def run(i):
        for _ in range(2):
          unrolls[i].append(actors[i].unroll())

      threads = [threading.Thread(target=run, args=(i,))
                 for i in range(4)]
      for t in threads:
        t.start()
      for t in threads:
        t.join(timeout=60)
      for lst in unrolls:
        assert len(lst) == 2
        for u in lst:
          assert u.env_outputs.reward.shape == (9,)
          assert np.isfinite(
              np.asarray(u.agent_outputs.policy_logits)).all()
          assert (np.asarray(u.agent_outputs.action) >= 0).all()
          assert (np.asarray(u.agent_outputs.action) < A).all()
      # Merge telemetry: all requests accounted for, and with 4
      # concurrent actors against one computation thread some calls
      # MUST have merged (calls strictly < requests) — the
      # single-machine throughput lever the stats exist to expose.
      stats = server.stats()
      assert stats['requests'] >= 4 * 2 * 8
      assert stats['calls'] < stats['requests']
      assert stats['mean_batch'] > 1.0
    finally:
      server.close()

  def test_pad_batch_to_compiles_one_bucket(self):
    """VERDICT r3 W5: with pad_batch_to set (eval), every merged
    batch pads to ONE bucket — warmup executes exactly one padded
    shape and live traffic of any size reuses it (no tail compiles
    when levels finish)."""
    agent, params, cfg = _mk(
        batch_size=4, unroll_length=4, num_action_repeats=1,
        inference_min_batch=1, inference_max_batch=64,
        inference_timeout_ms=5)
    from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
    server = InferenceServer(agent, params, cfg, seed=3,
                             pad_batch_to=6)
    # Record FULL input shapes: "one compile" means one shape tuple —
    # a batch-rows-only probe would miss a second compile from any
    # other dimension (e.g. an instr-length mismatch between warmup
    # and live traffic).
    seen_shapes = set()
    real_step = server._step

    def recording_step(params_, rng, *batch_args):
      seen_shapes.add(tuple(a.shape for a in batch_args))
      return real_step(params_, rng, *batch_args)

    server._step = recording_step
    try:
      # Same call evaluate() makes: max_size = this host's level
      # count; with the pad floor every candidate size lands in ONE
      # bucket, so warmup executes exactly one padded shape.
      server.warmup({'frame': (H, W, 3),
                     'instr_len': MAX_INSTRUCTION_LEN}, max_size=6)
      assert len(seen_shapes) == 1, seen_shapes
      assert next(iter(seen_shapes))[0] == (8,)  # pow2(6) rows

      # Live batch-1 traffic pads to the same bucket — the SAME full
      # shape tuple, so no further compile.
      actor = Actor(FakeEnv(height=H, width=W, num_actions=A, seed=0),
                    server.policy, agent.initial_state(1), 4)
      actor.unroll()
      assert len(seen_shapes) == 1, seen_shapes
    finally:
      server.close()

  def test_concurrent_param_updates_under_load(self):
    """Publisher hammering update_params while actor threads infer:
    the params pointer swap, the PRNG key lock, and the batcher must
    hold up under churn (the production cadence is one publish per
    learner step against ~48 inferring actors)."""
    agent, params, cfg = _mk(
        batch_size=4, unroll_length=8, num_action_repeats=1,
        inference_min_batch=1, inference_max_batch=8,
        inference_timeout_ms=5)
    server = InferenceServer(agent, params, cfg, seed=5)
    stop = threading.Event()
    try:
      actors = [
          Actor(FakeEnv(height=H, width=W, num_actions=A, seed=i),
                server.policy, agent.initial_state(1), 8)
          for i in range(3)]

      def publisher():
        i = 0
        while not stop.is_set():
          scale = 1.0 + (i % 5) * 0.1
          server.update_params(jax.tree_util.tree_map(
              lambda x: x * scale, params))
          i += 1
          time.sleep(0.005)

      pub = threading.Thread(target=publisher, daemon=True)
      pub.start()
      unrolls = [[] for _ in actors]

      def run(i):
        for _ in range(3):
          unrolls[i].append(actors[i].unroll())

      threads = [threading.Thread(target=run, args=(i,))
                 for i in range(3)]
      for t in threads:
        t.start()
      for t in threads:
        t.join(timeout=120)
      stop.set()
      pub.join(timeout=10)
      for lst in unrolls:
        assert len(lst) == 3
        for u in lst:
          assert np.isfinite(
              np.asarray(u.agent_outputs.policy_logits)).all()
      assert server.stats()['params_version'] > 3
    finally:
      stop.set()
      server.close()

  def test_update_params_is_picked_up(self):
    agent, params, cfg = _mk(inference_timeout_ms=5)
    server = InferenceServer(agent, params, cfg)
    try:
      env = FakeEnv(height=H, width=W, num_actions=A)
      actor = Actor(env, server.policy, agent.initial_state(1), 4)
      u1 = actor.unroll()
      zeroed = jax.tree_util.tree_map(lambda x: x * 0, params)
      server.update_params(zeroed)
      u2 = actor.unroll()
      # With zero params, logits collapse to a constant vector.
      logits = np.asarray(u2.agent_outputs.policy_logits[1:])
      assert np.allclose(logits, logits[..., :1], atol=1e-6)
      del u1
    finally:
      server.close()



  def test_auto_min_batch_resolves_to_fleet_size(self, batcher_options_spy):
    """inference_min_batch=0 (auto) floors the merge at the fleet
    size, clamped to max_batch (docs/PERF.md round-5 batcher sweep)."""
    agent, params, cfg = _mk(
        batch_size=4, unroll_length=8, num_action_repeats=1,
        inference_min_batch=0, inference_max_batch=8,
        inference_timeout_ms=20)
    server = InferenceServer(agent, params, cfg, seed=3, fleet_size=6)
    server.close()
    assert batcher_options_spy[-1]['minimum_batch_size'] == 6
    # Clamped at max_batch when the fleet is bigger.
    server = InferenceServer(agent, params, cfg, seed=3, fleet_size=99)
    server.close()
    assert batcher_options_spy[-1]['minimum_batch_size'] == 8
    # Explicit min_batch is untouched by fleet_size.
    agent, params, cfg = _mk(
        batch_size=4, unroll_length=8, num_action_repeats=1,
        inference_min_batch=2, inference_max_batch=8,
        inference_timeout_ms=20)
    server = InferenceServer(agent, params, cfg, seed=3, fleet_size=6)
    server.close()
    assert batcher_options_spy[-1]['minimum_batch_size'] == 2

  def test_auto_min_batch_serves_a_fleet(self):
    """Auto merge floor end-to-end: 3 actors against min_batch=0 —
    every call should carry all 3 once the fleet is in steady state,
    and the timeout must keep a lone straggler from deadlocking."""
    agent, params, cfg = _mk(
        batch_size=3, unroll_length=6, num_action_repeats=1,
        inference_min_batch=0, inference_max_batch=8,
        inference_timeout_ms=50)
    server = InferenceServer(agent, params, cfg, seed=3, fleet_size=3)
    try:
      actors = [
          Actor(FakeEnv(height=H, width=W, num_actions=A, seed=i),
                server.policy, agent.initial_state(1), 6)
          for i in range(3)]
      results = [None] * 3

      def run(i):
        results[i] = actors[i].unroll()

      threads = [threading.Thread(target=run, args=(i,))
                 for i in range(3)]
      for t in threads:
        t.start()
      for t in threads:
        t.join(timeout=60)
      assert all(r is not None for r in results)
      stats = server.stats()
      assert stats['requests'] >= 3 * 6
      assert stats['calls'] >= 1
      # NOTE deliberately no merge-ratio assert: on a loaded 1-core CI
      # host thread skew can expire the 50 ms window with partial
      # batches — the floor-resolution contract is pinned by the
      # monkeypatch test above, and the steady-state merge (3.92/4)
      # was measured on the real pipeline (docs/PERF.md r5 sweep).
      # This test pins the no-deadlock property.
    finally:
      server.close()

class TestFullPipeline:

  def test_actors_buffer_prefetcher_learner(self):
    agent, params, cfg = _mk(
        batch_size=2, unroll_length=6, num_action_repeats=1,
        total_environment_frames=10**6,
        inference_min_batch=1, inference_max_batch=8,
        inference_timeout_ms=10)
    server = InferenceServer(agent, params, cfg, seed=1)
    buf = TrajectoryBuffer(capacity_unrolls=cfg.batch_size *
                           cfg.queue_capacity_batches * 2)
    stop = threading.Event()

    def actor_loop(i):
      actor = Actor(
          ContextualBanditEnv(height=H, width=W, num_actions=A,
                              seed=10 + i),
          server.policy, agent.initial_state(1), cfg.unroll_length)
      run_actor_loop(actor, buf, stop)

    threads = [threading.Thread(target=actor_loop, args=(i,))
               for i in range(3)]
    for t in threads:
      t.start()

    prefetcher = BatchPrefetcher(buf, cfg.batch_size)
    state = learner_lib.make_train_state(params, cfg)
    train_step = learner_lib.make_train_step(agent, cfg)
    try:
      losses = []
      for _ in range(4):
        batch = prefetcher.get(timeout=60)
        state, metrics = train_step(state, batch)
        server.update_params(state.params)
        losses.append(float(metrics['total_loss']))
      assert all(np.isfinite(l) for l in losses), losses
      assert int(state.update_steps) == 4
    finally:
      stop.set()
      prefetcher.close()
      server.close()
      for t in threads:
        t.join(timeout=10)
      assert not any(t.is_alive() for t in threads)
