"""The sharding registry (parallel/sharding.py, round 19).

Resolution semantics (rule order, the scalar guard, the hard
unmatched-path error, optimizer-spec cloning), mesh binding (the
divisibility guard), the consumers-agree contract (learner state,
checkpoint restore targets, inference arena, SDC probe — identical
placements from ONE authority), the checkpoint sharding manifest +
registry resharding targets (ROADMAP item 3's enabler), and the 2D
{data, model} flagship parity gate: the deep ResNet + LSTM agent
trained 3 steps on a (data=4, model=2) mesh matches the single-device
reference at the established sharded-parity tolerances.

NOTE on PartitionSpec literals: tests are exempt from the
`sharding-registry` lint — these specs are the EXPECTED values the
registry is asserted against, not sharding decisions.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from scalable_agent_tpu import checkpoint as checkpoint_lib
from scalable_agent_tpu import integrity
from scalable_agent_tpu import learner as learner_lib
from scalable_agent_tpu.config import Config
from scalable_agent_tpu.models import ImpalaAgent, init_params
from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
from scalable_agent_tpu.parallel import mesh as mesh_lib
from scalable_agent_tpu.parallel import sharding as sharding_lib
from scalable_agent_tpu.parallel import train_parallel
from scalable_agent_tpu.testing import make_example_batch

A = 4
OBS = {'frame': (24, 32, 3), 'instr_len': MAX_INSTRUCTION_LEN}


def _fake_batch(seed, t1, b):
  h, w, _ = OBS['frame']
  return make_example_batch(t1, b, h, w, A, OBS['instr_len'],
                            seed=seed, done_prob=0.1)


# --- resolution semantics ---------------------------------------------


def test_rule_order_first_match_wins():
  leaf = jnp.zeros((8, 16))
  specific_first = sharding_lib.ShardingRegistry((
      (r'special/kernel$', P(sharding_lib.MODEL_AXIS, None)),
      (r'.*kernel$', P(None, sharding_lib.MODEL_AXIS)),
      (r'.*', P()),
  ))
  assert (specific_first.spec_for('special/kernel', leaf) ==
          P(sharding_lib.MODEL_AXIS, None))
  assert (specific_first.spec_for('other/kernel', leaf) ==
          P(None, sharding_lib.MODEL_AXIS))
  assert specific_first.spec_for('other/bias', leaf) == P()
  # Same rules, generic first: the specific rule is now shadowed —
  # order IS the semantics (first re.search match wins).
  generic_first = sharding_lib.ShardingRegistry((
      (r'.*kernel$', P(None, sharding_lib.MODEL_AXIS)),
      (r'special/kernel$', P(sharding_lib.MODEL_AXIS, None)),
      (r'.*', P()),
  ))
  assert (generic_first.spec_for('special/kernel', leaf) ==
          P(None, sharding_lib.MODEL_AXIS))


def test_unmatched_path_is_a_hard_error():
  registry = sharding_lib.ShardingRegistry(((r'.*kernel$', P()),))
  # Matching path resolves; a path no rule matches names itself in the
  # error — silence is never a sharding decision.
  assert registry.spec_for('torso/kernel', jnp.zeros((4, 4))) == P()
  with pytest.raises(sharding_lib.ShardingRuleError,
                     match='torso/bias'):
    registry.spec_for('torso/bias', jnp.zeros((4, 4)))
  # And an empty rule set cannot even be constructed.
  with pytest.raises(ValueError, match='at least one rule'):
    sharding_lib.ShardingRegistry(())


def test_scalars_replicate_before_rules_run():
  registry = sharding_lib.ShardingRegistry(
      ((r'.*', P(sharding_lib.MODEL_AXIS)),))
  assert registry.spec_for('step', jnp.int32(3)) == P()
  assert registry.spec_for('one_elem', jnp.zeros((1,))) == P()
  # A real vector still takes the rule.
  assert (registry.spec_for('vec', jnp.zeros((8,))) ==
          P(sharding_lib.MODEL_AXIS))


def test_from_config_resolution():
  assert sharding_lib.from_config(
      Config(model_parallelism=1)).rule_set == 'replicated'
  assert sharding_lib.from_config(
      Config(batch_size=8, model_parallelism=2)).rule_set == 'megatron'
  # Explicit names win over the model_parallelism predicate.
  assert sharding_lib.from_config(
      Config(model_parallelism=1,
             sharding_rules='megatron')).rule_set == 'megatron'
  assert not sharding_lib.from_config(
      Config(batch_size=8, model_parallelism=2,
             sharding_rules='replicated')).model_sharded
  with pytest.raises(ValueError, match='bogus'):
    sharding_lib.from_config(Config(sharding_rules='bogus'))


def test_optimizer_specs_clone_param_specs():
  """SNIPPETS [1] semantics: moment buffers (param-shaped subtrees of
  the optax chain state) inherit the matched param specs leaf-for-leaf;
  every non-param leaf (the schedule count) is replicated."""
  agent = ImpalaAgent(num_actions=A, torso='shallow')
  params = init_params(agent, jax.random.PRNGKey(0), OBS)
  cfg = Config(batch_size=8, model_parallelism=2)
  state = learner_lib.make_train_state(params, cfg)
  registry = sharding_lib.from_config(cfg)

  pspecs = registry.param_specs(state.params)
  flat_p = jax.tree_util.tree_leaves(
      pspecs, is_leaf=lambda x: isinstance(x, P))
  assert any(sharding_lib.MODEL_AXIS in (s or ()) for s in flat_p)

  ospecs = registry.opt_specs(state.opt_state, pspecs)
  flat_o = jax.tree_util.tree_leaves(
      ospecs, is_leaf=lambda x: isinstance(x, P))
  # rmsprop-with-momentum chain: nu moments (param-shaped), the
  # schedule count (scalar), trace moments (param-shaped) — cloned
  # specs bracket exactly one replicated counter.
  assert flat_o == flat_p + [P()] + flat_p

  # The whole-state view: params and target_params by the rules,
  # opt_state as above, counters replicated.
  sspecs = registry.state_specs(state)
  assert jax.tree_util.tree_leaves(
      sspecs.params, is_leaf=lambda x: isinstance(x, P)) == flat_p
  assert sspecs.update_steps == P()


# --- mesh binding ------------------------------------------------------


def test_divisibility_guard_drops_odd_cuts():
  registry = sharding_lib.ShardingRegistry(
      sharding_lib.RULE_SETS['megatron'], rule_set='megatron')
  mesh = mesh_lib.make_mesh(model_parallelism=2)
  params = {'Dense_0': {'kernel': jnp.zeros((4, 8)),
                        'bias': jnp.zeros((8,))},
            'Dense_1': {'kernel': jnp.zeros((4, 7)),   # 7 % 2 != 0
                        'bias': jnp.zeros((7,))}}
  sh = registry.param_shardings(params, mesh)
  assert sh['Dense_0']['kernel'].spec == P(None, sharding_lib.MODEL_AXIS)
  assert sh['Dense_0']['bias'].spec == P(sharding_lib.MODEL_AXIS)
  # The guard is applied at BINDING, identically for every consumer —
  # including the describe() manifest the checkpointer records.
  assert sh['Dense_1']['kernel'].spec == P()
  assert sh['Dense_1']['bias'].spec == P()
  manifest = registry.describe(params, mesh)
  assert manifest['Dense_1/kernel'] == str(P())
  assert manifest['Dense_0/kernel'] == str(P(None,
                                             sharding_lib.MODEL_AXIS))


@pytest.mark.parametrize('model_parallelism', [1, 2])
def test_mesh_wrappers_delegate_to_registry(model_parallelism):
  """parallel/mesh.py's param_shardings/batch_shardings are thin
  delegations now — identical output to querying the registry."""
  agent = ImpalaAgent(num_actions=A, torso='shallow')
  params = init_params(agent, jax.random.PRNGKey(0), OBS)
  mesh = mesh_lib.make_mesh(model_parallelism=model_parallelism)
  tp = model_parallelism > 1
  registry = sharding_lib.from_config(
      Config(batch_size=8, model_parallelism=model_parallelism),
      enable_tp=tp)

  via_mesh = mesh_lib.param_shardings(params, mesh, enable_tp=tp)
  via_registry = registry.param_shardings(params, mesh)
  for a, b in zip(jax.tree_util.tree_leaves(via_mesh),
                  jax.tree_util.tree_leaves(via_registry)):
    assert a == b

  batch = _fake_batch(0, 5, 8)
  bm = jax.tree_util.tree_leaves(mesh_lib.batch_shardings(batch, mesh))
  br = jax.tree_util.tree_leaves(registry.batch_shardings(batch, mesh))
  assert bm == br
  # Cross-host TP layout: the batch dim spans BOTH axes.
  over = registry.batch_specs(batch, shard_over_model=True)
  assert over.env_outputs.reward == P(
      None, (sharding_lib.DATA_AXIS, sharding_lib.MODEL_AXIS))
  assert over.level_name == P(
      (sharding_lib.DATA_AXIS, sharding_lib.MODEL_AXIS))


def test_consumers_agree_on_placements():
  """The acceptance contract: every consumer's placements ARE the
  registry's — the learner's live TrainState, the checkpoint restore
  targets, the inference arena, the SDC probe, and the manifest all
  resolve to the same shardings for the same config + mesh."""
  agent = ImpalaAgent(num_actions=A, torso='shallow')
  params = init_params(agent, jax.random.PRNGKey(0), OBS)
  cfg = Config(batch_size=8, model_parallelism=2)
  mesh = mesh_lib.make_mesh(model_parallelism=2)
  registry = sharding_lib.from_config(cfg)

  # (1) learner: the live state's leaf shardings == state_shardings.
  state = train_parallel.make_sharded_train_state(params, cfg, mesh,
                                                  registry=registry)
  expected = registry.state_shardings(state, mesh)
  live = jax.tree_util.tree_map(lambda x: x.sharding, state)
  for a, b in zip(jax.tree_util.tree_leaves(live),
                  jax.tree_util.tree_leaves(expected)):
    assert a == b
  # TP actually engaged: at least one model-sharded param on the mesh.
  assert any(sharding_lib.MODEL_AXIS in str(s.spec)
             for s in jax.tree_util.tree_leaves(live))

  # (2) checkpoint: registry restore targets pin the SAME shardings —
  # a restore lands exactly where the learner would place (and, fed a
  # different mesh, exactly where the NEW topology's rules resolve:
  # the resharding primitive).
  abstract = jax.tree_util.tree_map(
      lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state)
  targets = checkpoint_lib.registry_restore_targets(abstract, registry,
                                                    mesh)
  for t, s in zip(jax.tree_util.tree_leaves(targets),
                  jax.tree_util.tree_leaves(expected)):
    assert t.sharding == s

  # (3) inference arena + (4) SDC probe placements are the registry's
  # primitive shardings, not private constructions.
  assert sharding_lib.replicated(mesh) == NamedSharding(mesh, P())
  assert sharding_lib.data_sharding(mesh) == NamedSharding(
      mesh, P(sharding_lib.DATA_AXIS))
  from scalable_agent_tpu.runtime.inference import InferenceServer
  server = InferenceServer(agent, params, Config(), seed=0, mesh=mesh)
  try:
    assert server._replicated == sharding_lib.replicated(mesh)
    assert server._batch_sharding == sharding_lib.data_sharding(mesh)
  finally:
    server.close()

  # (5) the manifest is the bound placements, stringified.
  manifest = registry.describe(state.params, mesh)
  flat = jax.tree_util.tree_flatten_with_path(
      registry.param_shardings(state.params, mesh))[0]
  for kp, sh in flat:
    path = '/'.join(str(getattr(k, 'key', k)) for k in kp)
    assert manifest[path] == str(sh.spec)

  # (6) the SDC gate consults the registry's model_sharded predicate:
  # TP params are legitimately different per device — nothing to
  # cross-compare.
  assert registry.model_sharded
  assert not train_parallel.supports_sdc_check(cfg, mesh)
  assert train_parallel.supports_sdc_check(
      Config(batch_size=8, model_parallelism=1),
      mesh_lib.make_mesh(model_parallelism=1))


def test_checkpoint_sharding_manifest_and_resharded_restore(tmp_path):
  """The save-side manifest (SHARDING_{step}.json: rule set, specs,
  digest) + the restore path onto registry-resolved placements for a
  DIFFERENT mesh — cross-topology resharding (ROADMAP item 3)."""
  agent = ImpalaAgent(num_actions=A, torso='shallow')
  params = init_params(agent, jax.random.PRNGKey(0), OBS)
  cfg = Config(batch_size=8, model_parallelism=2)
  mesh = mesh_lib.make_mesh(model_parallelism=2)
  registry = sharding_lib.from_config(cfg)
  state = train_parallel.make_sharded_train_state(params, cfg, mesh,
                                                  registry=registry)

  ckpt = checkpoint_lib.Checkpointer(str(tmp_path / 'ckpt'),
                                     save_interval_secs=0,
                                     registry=registry, mesh=mesh)
  assert ckpt.save(state, step=1)
  ckpt.wait_until_finished()

  manifest = ckpt.read_sharding_manifest(1)
  assert manifest is not None
  assert manifest['rule_set'] == 'megatron'
  assert manifest['mesh'] == {'data': 4, 'model': 2}
  assert manifest['specs'] == registry.describe(state.params, mesh)
  assert integrity.verify_record(
      manifest['digest'], integrity.spec_table_digest(manifest['specs']))
  # On disk next to the digest ledger.
  files = os.listdir(str(tmp_path / 'ckpt'))
  assert 'SHARDING_1.json' in files

  # Restore the TP-sharded checkpoint onto a PURE-DP mesh with the
  # pure-DP registry: every restored leaf lands replicated (the new
  # rules' resolution), values identical to the saved state.
  dp_cfg = Config(batch_size=8, model_parallelism=1)
  dp_mesh = mesh_lib.make_mesh(model_parallelism=1)
  dp_registry = sharding_lib.from_config(dp_cfg)
  abstract = jax.tree_util.tree_map(
      lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state)
  restored = ckpt.restore_resharded(abstract, dp_registry, dp_mesh)
  assert restored is not None
  for leaf in jax.tree_util.tree_leaves(restored.params):
    assert sharding_lib.MODEL_AXIS not in str(leaf.sharding.spec)
    assert leaf.sharding.mesh.shape == dp_mesh.shape
  for a, b in zip(jax.tree_util.tree_leaves(restored.params),
                  jax.tree_util.tree_leaves(state.params)):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
  ckpt.close()



# --- elastic resharding edge cases (round 20) --------------------------


def _abstract(state):
  return jax.tree_util.tree_map(
      lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state)


def test_layout_violations_name_the_structural_reason():
  """The three refusal stories, each named: a spec axis the mesh does
  not carry, a cut dim past the leaf's rank, and a dim that does not
  divide the axis width."""
  registry = sharding_lib.ShardingRegistry((
      (r'.*rank$', P(None, None, sharding_lib.MODEL_AXIS)),
      (r'.*odd$', P(None, sharding_lib.MODEL_AXIS)),
      (r'.*', P()),
  ), rule_set='layout-test')
  from jax.sharding import Mesh
  devs = np.array(jax.devices()[:2])
  data_only = Mesh(devs, ('data',))
  tp_mesh = Mesh(devs.reshape(1, 2), ('data', 'model'))
  tree = {'a_rank': jnp.zeros((4, 4)),   # spec cuts dim 2, rank 2
          'b_odd': jnp.zeros((4, 7)),    # 7 % 2 != 0
          'c_fine': jnp.zeros((4, 4))}

  on_tp = dict(registry.layout_violations(tree, tp_mesh))
  assert set(on_tp) == {'a_rank', 'b_odd'}
  assert 'rank' in on_tp['a_rank']
  assert 'does not divide' in on_tp['b_odd']

  # On a mesh with no model axis at all, every model cut is refused
  # with the missing-axis story (checked before rank/width).
  on_dp = dict(registry.layout_violations(tree, data_only))
  assert set(on_dp) == {'a_rank', 'b_odd'}
  assert "'model'" in on_dp['b_odd']


def test_check_layout_exempts_leaves_saved_replicated():
  """The manifest-aware exemption: a leaf the SAVE already degraded
  to replicated (odd dims under `_guard`) must not refuse a restore —
  the restore loses nothing the checkpoint still had."""
  registry = sharding_lib.ShardingRegistry((
      (r'.*odd$', P(None, sharding_lib.MODEL_AXIS)),
      (r'.*', P()),
  ), rule_set='layout-test')
  mesh = mesh_lib.make_mesh(model_parallelism=2)
  tree = {'w_odd': jnp.zeros((4, 7))}
  with pytest.raises(sharding_lib.ShardingLayoutError, match='w_odd'):
    registry.check_layout(tree, mesh, what='param')
  # Recorded replicated at save: exempt, no raise.
  registry.check_layout(tree, mesh, what='param',
                        saved_specs={'w_odd': str(P())})
  # Recorded SHARDED at save: the refusal stands.
  with pytest.raises(sharding_lib.ShardingLayoutError,
                     match='does not divide'):
    registry.check_layout(
        tree, mesh, what='param',
        saved_specs={'w_odd': str(P(None, sharding_lib.MODEL_AXIS))})


def test_restore_resharded_strict_refusal_and_escape(tmp_path):
  """Checkpoint-level strict gate: a leaf saved SHARDED whose cut the
  target topology cannot honor refuses with the structural error;
  strict=False accepts the documented replicated degradation."""
  registry = sharding_lib.ShardingRegistry((
      (r'.*kernel$', P(None, sharding_lib.MODEL_AXIS)),
      (r'.*', P()),
  ), rule_set='layout-test')
  params = {'Dense_0': {'kernel': jnp.ones((4, 6)),   # 6 % 2 == 0
                        'bias': jnp.zeros((6,))}}
  cfg = Config(batch_size=8)
  state = learner_lib.make_train_state(params, cfg)
  save_mesh = mesh_lib.make_mesh(model_parallelism=2)

  ckpt = checkpoint_lib.Checkpointer(str(tmp_path / 'ckpt'),
                                     save_interval_secs=0,
                                     registry=registry, mesh=save_mesh)
  assert ckpt.save(state, step=1)
  ckpt.wait_until_finished()
  manifest = ckpt.read_sharding_manifest(1)
  assert (manifest['specs']['Dense_0/kernel'] ==
          str(P(None, sharding_lib.MODEL_AXIS)))

  # model=4 cannot honor the 6-wide cut (6 % 4 != 0): strict refuses
  # with the leaf and the reason on the error.
  target_mesh = mesh_lib.make_mesh(model_parallelism=4)
  with pytest.raises(sharding_lib.ShardingLayoutError,
                     match='Dense_0/kernel'):
    ckpt.restore_resharded(_abstract(state), registry, target_mesh)

  # Non-strict: the `_guard` degradation (replicated) is accepted —
  # values intact, placement replicated on the NEW mesh.
  restored = ckpt.restore_resharded(_abstract(state), registry,
                                    target_mesh, strict=False)
  assert restored is not None
  kernel = restored.params['Dense_0']['kernel']
  assert kernel.sharding.spec == P()
  assert kernel.sharding.mesh.shape == target_mesh.shape
  np.testing.assert_array_equal(np.asarray(kernel),
                                np.asarray(params['Dense_0']['kernel']))
  ckpt.close()


def test_resharded_opt_state_follows_param_specs(tmp_path):
  """Across topologies the optimizer moments land EXACTLY where their
  params land (the round-19 cloning contract, now exercised by the
  2→4 analogue): restore a model=2 checkpoint onto a model=4 mesh and
  every param-shaped moment leaf carries the param's sharding."""
  registry = sharding_lib.ShardingRegistry((
      (r'.*kernel$', P(None, sharding_lib.MODEL_AXIS)),
      (r'.*', P()),
  ), rule_set='layout-test')
  params = {'Dense_0': {'kernel': jnp.ones((4, 8)),   # 8 % 4 == 0
                        'bias': jnp.zeros((8,))}}
  cfg = Config(batch_size=8)
  state = learner_lib.make_train_state(params, cfg)
  save_mesh = mesh_lib.make_mesh(model_parallelism=2)
  ckpt = checkpoint_lib.Checkpointer(str(tmp_path / 'ckpt'),
                                     save_interval_secs=0,
                                     registry=registry, mesh=save_mesh)
  assert ckpt.save(state, step=1)
  ckpt.wait_until_finished()

  target_mesh = mesh_lib.make_mesh(model_parallelism=4)
  restored = ckpt.restore_resharded(_abstract(state), registry,
                                    target_mesh)
  assert restored is not None
  kernel_sh = restored.params['Dense_0']['kernel'].sharding
  assert kernel_sh.spec == P(None, sharding_lib.MODEL_AXIS)
  assert dict(kernel_sh.mesh.shape) == dict(target_mesh.shape)
  # Every param-shaped moment subtree cloned the param placements.
  pdef = jax.tree_util.tree_structure(restored.params)
  expected = jax.tree_util.tree_map(lambda x: x.sharding,
                                    restored.params)
  moment_trees = [
      sub for sub in jax.tree_util.tree_leaves(
          restored.opt_state,
          is_leaf=lambda x: jax.tree_util.tree_structure(x) == pdef
          if not isinstance(x, jax.Array) else False)
      if jax.tree_util.tree_structure(sub) == pdef]
  assert moment_trees  # the rmsprop chain carries param-shaped moments
  for sub in moment_trees:
    got = jax.tree_util.tree_map(lambda x: x.sharding, sub)
    assert (jax.tree_util.tree_leaves(got) ==
            jax.tree_util.tree_leaves(expected))
  # Counters stay replicated.
  assert restored.update_steps.sharding.spec == P()
  ckpt.close()


def test_same_topology_restore_stays_byte_identical(tmp_path):
  """Regression guard for the elastic gate: when the live mesh equals
  the manifest's, the driver takes the UNCHANGED restore_latest path
  and the restored bytes equal the saved bytes exactly."""
  agent = ImpalaAgent(num_actions=A, torso='shallow')
  params = init_params(agent, jax.random.PRNGKey(0), OBS)
  cfg = Config(batch_size=8, model_parallelism=2)
  mesh = mesh_lib.make_mesh(model_parallelism=2)
  registry = sharding_lib.from_config(cfg)
  state = train_parallel.make_sharded_train_state(params, cfg, mesh,
                                                  registry=registry)
  ckpt = checkpoint_lib.Checkpointer(str(tmp_path / 'ckpt'),
                                     save_interval_secs=0,
                                     registry=registry, mesh=mesh)
  assert ckpt.save(state, step=3)
  ckpt.wait_until_finished()

  # The driver's gate reads the manifest's mesh: same topology →
  # topology_delta None → restore_latest (no resharding detour).
  from scalable_agent_tpu.parallel import distributed
  assert ckpt.saved_mesh_shape() == {'data': 4, 'model': 2}
  assert distributed.topology_delta(ckpt.saved_mesh_shape(),
                                    mesh) is None
  delta = distributed.topology_delta(
      ckpt.saved_mesh_shape(), mesh_lib.make_mesh(model_parallelism=1))
  assert delta is not None and delta['saved_mesh'] == {'data': 4,
                                                       'model': 2}

  restored = ckpt.restore_latest(state)
  assert restored is not None
  for a, b in zip(jax.tree_util.tree_leaves(restored),
                  jax.tree_util.tree_leaves(state)):
    assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
    assert a.sharding == b.sharding
  ckpt.close()


def test_spec_table_digest_is_content_addressed():
  specs = {'a/kernel': "PartitionSpec(None, 'model')",
           'b/bias': 'PartitionSpec()'}
  d1 = integrity.spec_table_digest(specs)
  # Order-independent (sorted paths), content-sensitive.
  d2 = integrity.spec_table_digest(dict(reversed(list(specs.items()))))
  assert d1 == d2
  changed = dict(specs, **{'a/kernel': 'PartitionSpec()'})
  assert integrity.spec_table_digest(changed) != d1


# --- the 2D {data, model} flagship parity gate -------------------------


@pytest.mark.slow  # tier-1 wall trim (round 20); ci.sh full-suite lane runs it
def test_2d_mesh_deep_agent_parity_gate():
  """The flagship on a real 2D mesh: the deep ResNet + LSTM agent
  (torso='deep', the reference architecture) trains 3 steps on a
  (data=4, model=2) mesh — rule set and mesh shape declared by the
  CONFIG (sharding_rules/model_parallelism), every placement resolved
  by the registry — and must match the single-device reference at the
  established sharded-parity tolerances (loss rtol 2e-4; post-update
  params rtol 5e-4 / atol 5e-6, compounding over the 3 steps). On CPU
  the tp_compute=auto gathered fallback keeps numerics exact while
  params stay model-sharded at rest (docs/PARALLELISM.md)."""
  agent = ImpalaAgent(num_actions=A, torso='deep')
  cfg = Config(batch_size=4, unroll_length=4, num_action_repeats=1,
               total_environment_frames=10**6,
               model_parallelism=2, sharding_rules='auto')
  batches = [_fake_batch(10 + i, 5, 4) for i in range(3)]

  params = init_params(agent, jax.random.PRNGKey(0), OBS)
  params2 = init_params(agent, jax.random.PRNGKey(0), OBS)

  state1 = learner_lib.make_train_state(params, cfg)
  step1 = learner_lib.make_train_step(agent, cfg)

  mesh = mesh_lib.make_mesh(model_parallelism=2)
  registry = sharding_lib.from_config(cfg)
  assert registry.rule_set == 'megatron'
  state2d = train_parallel.make_sharded_train_state(
      params2, cfg, mesh, registry=registry)
  # The 2D mesh genuinely engaged: model-sharded params at rest.
  assert any(sharding_lib.MODEL_AXIS in str(x.sharding.spec)
             for x in jax.tree_util.tree_leaves(state2d.params))
  step2d, place = train_parallel.make_sharded_train_step(
      agent, cfg, mesh, batches[0])

  losses1, losses2d = [], []
  for batch in batches:
    state1, m1 = step1(state1, batch)
    losses1.append(float(m1['total_loss']))
    state2d, m2d = step2d(state2d, place(batch))
    losses2d.append(float(m2d['total_loss']))

  np.testing.assert_allclose(losses1, losses2d, rtol=2e-4)
  for a, b in zip(jax.tree_util.tree_leaves(state1.params),
                  jax.tree_util.tree_leaves(state2d.params)):
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=5e-4, atol=5e-6)
  # Params are STILL model-sharded after 3 steps (the gathered path
  # re-scatters to the at-rest placements every step).
  assert any(sharding_lib.MODEL_AXIS in str(x.sharding.spec)
             for x in jax.tree_util.tree_leaves(state2d.params))
