"""Checkpoint round-trip: save → restore-latest reproduces the full
TrainState (params, optimizer slots, step counter) — SURVEY §5.4.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from scalable_agent_tpu import learner as learner_lib
from scalable_agent_tpu.checkpoint import Checkpointer
from scalable_agent_tpu.config import Config
from scalable_agent_tpu.models import ImpalaAgent, init_params
from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
from scalable_agent_tpu.testing import make_example_batch


@pytest.fixture(scope='module')
def setup():
  cfg = Config(batch_size=2, unroll_length=3, torso='shallow',
               total_environment_frames=10**6)
  agent = ImpalaAgent(num_actions=4, torso='shallow')
  params = init_params(agent, jax.random.PRNGKey(0),
                       {'frame': (24, 32, 3),
                        'instr_len': MAX_INSTRUCTION_LEN})
  batch = make_example_batch(cfg.unroll_length + 1, cfg.batch_size,
                             24, 32, 4, MAX_INSTRUCTION_LEN)
  return cfg, agent, params, batch


def _tree_equal(a, b):
  flat_a = jax.tree_util.tree_leaves(a)
  flat_b = jax.tree_util.tree_leaves(b)
  assert len(flat_a) == len(flat_b)
  for x, y in zip(flat_a, flat_b):
    np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_save_restore_roundtrip(setup, tmp_path):
  cfg, agent, params, batch = setup
  # Copy: the jitted step donates its state, which aliases the fixture's
  # params — other tests in this module still need them.
  params = jax.tree_util.tree_map(jnp.copy, params)
  train_step = learner_lib.make_train_step(agent, cfg)
  state = learner_lib.make_train_state(params, cfg)
  state, _ = train_step(state, batch)
  state, _ = train_step(state, batch)

  ckpt = Checkpointer(str(tmp_path / 'ckpt'), save_interval_secs=0)
  ckpt.save(state)
  ckpt.wait_until_finished()
  assert ckpt.latest_step() == 2

  # Fresh target state (different values) → restore must overwrite all.
  params2 = init_params(agent, jax.random.PRNGKey(1),
                        {'frame': (24, 32, 3),
                         'instr_len': MAX_INSTRUCTION_LEN})
  target = learner_lib.make_train_state(params2, cfg)
  restored = ckpt.restore_latest(target)
  assert restored is not None
  _tree_equal(restored, state)
  assert int(restored.update_steps) == 2
  ckpt.close()

  # Resume: training continues from the restored state identically.
  resumed, _ = train_step(restored, batch)
  again, _ = train_step(state, batch)
  _tree_equal(resumed.params, again.params)


def test_restore_latest_params_only(setup, tmp_path):
  """Eval-path restore: params + step counter come back equal, while
  the optimizer moments are never materialized (placeholder leaves) —
  VERDICT W5."""
  cfg, agent, params, batch = setup
  params = jax.tree_util.tree_map(jnp.copy, params)
  train_step = learner_lib.make_train_step(agent, cfg)
  state = learner_lib.make_train_state(params, cfg)
  state, _ = train_step(state, batch)

  ckpt = Checkpointer(str(tmp_path / 'ckpt'), save_interval_secs=0)
  ckpt.save(state)
  ckpt.wait_until_finished()

  restored = ckpt.restore_latest_params(
      state.params, lambda p: learner_lib.make_train_state(p, cfg))
  assert restored is not None
  got_params, got_steps = restored
  _tree_equal(got_params, state.params)
  assert got_steps == 1
  ckpt.close()


def test_restore_latest_params_only_none_when_empty(setup, tmp_path):
  cfg, agent, params, _ = setup
  ckpt = Checkpointer(str(tmp_path / 'empty'), save_interval_secs=0)
  assert ckpt.restore_latest_params(
      params, lambda p: learner_lib.make_train_state(p, cfg)) is None
  ckpt.close()


def test_restore_latest_none_when_empty(setup, tmp_path):
  cfg, agent, params, _ = setup
  state = learner_lib.make_train_state(params, cfg)
  ckpt = Checkpointer(str(tmp_path / 'empty'))
  assert ckpt.restore_latest(state) is None
  assert ckpt.latest_step() is None
  ckpt.close()


def test_maybe_save_throttles(setup, tmp_path):
  cfg, agent, params, _ = setup
  state = learner_lib.make_train_state(params, cfg)
  ckpt = Checkpointer(str(tmp_path / 'throttle'),
                      save_interval_secs=3600)
  # First call starts the clock, doesn't save.
  assert not ckpt.maybe_save(state)
  assert not ckpt.maybe_save(state)
  assert ckpt.latest_step() is None
  ckpt.close()

  fast = Checkpointer(str(tmp_path / 'fast'), save_interval_secs=0)
  assert not fast.maybe_save(state)   # starts clock
  assert fast.maybe_save(state)       # interval (0s) elapsed
  fast.wait_until_finished()
  assert fast.latest_step() == 0
  fast.close()


def test_max_to_keep_prunes(setup, tmp_path):
  cfg, agent, params, _ = setup
  state = learner_lib.make_train_state(params, cfg)
  ckpt = Checkpointer(str(tmp_path / 'keep'), max_to_keep=2)
  for step in (1, 2, 3):
    ckpt.save(state, step=step, force=True)
  ckpt.wait_until_finished()
  assert ckpt.latest_step() == 3
  restored = ckpt.restore_latest(state)
  assert restored is not None
  ckpt.close()


def test_save_same_step_twice_reports_skip(setup, tmp_path):
  cfg, agent, params, _ = setup
  state = learner_lib.make_train_state(params, cfg)
  ckpt = Checkpointer(str(tmp_path / 'dup'))
  assert ckpt.save(state, step=5)
  ckpt.wait_until_finished()
  assert not ckpt.save(state, step=5)  # existing step skipped → False
  ckpt.close()


def test_should_save_and_decision_override(setup, tmp_path):
  """Multi-host contract: a host whose local clock hasn't elapsed must
  still save when handed decision=True (process 0's broadcast), and
  must skip when handed False even if its own clock elapsed."""
  cfg, agent, params, _ = setup
  state = learner_lib.make_train_state(params, cfg)
  ckpt = Checkpointer(str(tmp_path / 'decision'),
                      save_interval_secs=10**6)
  try:
    assert not ckpt.should_save()  # first call starts the clock
    assert not ckpt.maybe_save(state)          # local clock: no
    assert ckpt.maybe_save(state, decision=True)   # broadcast: yes
    state2 = state._replace(update_steps=state.update_steps + 1)
    assert not ckpt.maybe_save(state2, decision=False)
    assert ckpt.latest_step() == 0
  finally:
    ckpt.close()


def test_structure_mismatch_names_the_flag(setup, tmp_path):
  """VERDICT r2 W7: restoring a with-instruction checkpoint into a
  without-instruction state must fail with a message that points at
  --use_instruction, not a raw Orbax tree error."""
  cfg = Config(batch_size=2, unroll_length=3, torso='shallow',
               total_environment_frames=10**6)
  obs_spec = {'frame': (24, 32, 3), 'instr_len': MAX_INSTRUCTION_LEN}
  with_instr = ImpalaAgent(num_actions=4, torso='shallow',
                           use_instruction=True)
  params = init_params(with_instr, jax.random.PRNGKey(0), obs_spec)
  state = learner_lib.make_train_state(params, cfg)
  ckpt = Checkpointer(str(tmp_path / 'mismatch'))
  ckpt.save(state, step=1, force=True)
  ckpt.wait_until_finished()

  without_instr = ImpalaAgent(num_actions=4, torso='shallow',
                              use_instruction=False)
  params2 = init_params(without_instr, jax.random.PRNGKey(0), obs_spec)
  target = learner_lib.make_train_state(params2, cfg)
  with pytest.raises(Exception, match='use_instruction'):
    ckpt.restore_latest(target)
  # The eval (params-only) path gets the same guidance.
  with pytest.raises(Exception, match='use_instruction'):
    ckpt.restore_latest_params(
        params2, lambda p: learner_lib.make_train_state(p, cfg))
  ckpt.close()


def test_wrap_error_sniffs_structure_vs_corruption():
  """ADVICE r3: flag guidance only on failures that look like tree-
  structure mismatches; corrupt/partial-file failures get the
  corruption wording instead of a misleading --use_instruction hunt."""
  from scalable_agent_tpu import checkpoint as ckpt_lib

  structural = [
      ValueError('User-provided restore item and on-disk value '
                 'metadata tree structures do not match.'),
      KeyError('params/instruction/embed/kernel'),  # bare key str
      TypeError('Custom PyTree node mismatch'),
      # Newer-Orbax spelling (jax tree_util raises it before any file
      # is read).
      ValueError("Dict key mismatch; expected keys: ['a']; dict: {}"),
  ]
  for e in structural:
    with pytest.raises(ckpt_lib.CheckpointStructureError,
                       match='use_instruction'):
      ckpt_lib._wrap_structure_error(e, '/ckpts', 7)

  corrupt_cases = [
      ValueError('zarr array data truncated at offset 18238'),
      # 'missing'/'key' alone must NOT count as structural — they
      # also appear in partial-save messages like this one.
      ValueError('checkpoint incomplete: missing commit file for key'),
  ]
  for e in corrupt_cases:
    with pytest.raises(ckpt_lib.CheckpointStructureError) as exc_info:
      ckpt_lib._wrap_structure_error(e, '/ckpts', 7)
    msg = str(exc_info.value)
    assert 'use_instruction' not in msg
    assert 'corrupt' in msg and 'previous retained step' in msg


def _save_steps(ckpt, state, steps):
  for step in steps:
    assert ckpt.save(state, step=step, force=True)


def test_restore_latest_falls_back_past_truncated_newest(setup,
                                                         tmp_path):
  """Integrity ladder: files of the newest step truncated (a save
  killed mid-write) → restore_latest logs, retries the previous
  retained step, and succeeds instead of dead-ending."""
  from scalable_agent_tpu.runtime import faults as faults_lib
  cfg, agent, params, _ = setup
  state = learner_lib.make_train_state(
      jax.tree_util.tree_map(jnp.copy, params), cfg)
  ckpt = Checkpointer(str(tmp_path / 'ladder'), save_interval_secs=0)
  try:
    _save_steps(ckpt, state, (1, 2))
    assert ckpt.last_good_step() == 2
    faults_lib.corrupt_checkpoint_step(str(tmp_path / 'ladder'), 2)
    restored = ckpt.restore_latest(state)
    assert restored is not None
    _tree_equal(restored.params, state.params)
    assert ckpt.restore_fallbacks >= 1
  finally:
    ckpt.close()


def test_restore_latest_falls_back_past_deleted_step_files(setup,
                                                           tmp_path):
  """Same ladder for wholesale-missing array files (partial rsync,
  eviction): the newest step still LISTS but cannot restore."""
  import os
  import shutil
  cfg, agent, params, _ = setup
  state = learner_lib.make_train_state(
      jax.tree_util.tree_map(jnp.copy, params), cfg)
  directory = str(tmp_path / 'deleted')
  ckpt = Checkpointer(directory, save_interval_secs=0)
  try:
    _save_steps(ckpt, state, (1, 2))
    step_dir = os.path.join(directory, '2')
    assert os.path.isdir(step_dir)
    # Delete the saved ARRAY payloads, keep the step dir listing.
    for root, dirs, files in os.walk(step_dir):
      for name in dirs:
        if name == 'default':
          shutil.rmtree(os.path.join(root, name))
    restored = ckpt.restore_latest(state)
    assert restored is not None
    _tree_equal(restored.params, state.params)
  finally:
    ckpt.close()


def test_restore_raises_corruption_guidance_when_all_steps_bad(
    setup, tmp_path):
  """Exhausting the ladder keeps the corruption (not flag-hunt)
  wording — the structure-vs-corruption message split stays intact."""
  from scalable_agent_tpu import checkpoint as ckpt_lib
  from scalable_agent_tpu.runtime import faults as faults_lib
  cfg, agent, params, _ = setup
  state = learner_lib.make_train_state(
      jax.tree_util.tree_map(jnp.copy, params), cfg)
  directory = str(tmp_path / 'allbad')
  ckpt = Checkpointer(directory, save_interval_secs=0)
  try:
    _save_steps(ckpt, state, (1, 2))
    for step in (1, 2):
      from scalable_agent_tpu.runtime.faults import (
          corrupt_checkpoint_step)
      corrupt_checkpoint_step(directory, step)
    with pytest.raises(ckpt_lib.CheckpointStructureError) as exc_info:
      ckpt.restore_latest(state)
    msg = str(exc_info.value)
    assert 'use_instruction' not in msg
    assert 'corrupt' in msg
  finally:
    ckpt.close()


def test_last_good_marker_roundtrip(setup, tmp_path):
  """LAST_GOOD distinguishes 'restorable' from merely 'newest':
  advanced only by verified saves, pruned entries invalidate it, and
  restore_last_good prefers it."""
  cfg, agent, params, _ = setup
  state = learner_lib.make_train_state(
      jax.tree_util.tree_map(jnp.copy, params), cfg)
  ckpt = Checkpointer(str(tmp_path / 'marker'), max_to_keep=2,
                      save_interval_secs=0)
  try:
    assert ckpt.last_good_step() is None
    _save_steps(ckpt, state, (1,))
    assert ckpt.last_good_step() == 1
    _save_steps(ckpt, state, (2, 3))   # step 1 pruned (max_to_keep=2)
    assert ckpt.last_good_step() == 3
    restored = ckpt.restore_last_good(state)
    assert restored is not None
    _tree_equal(restored.params, state.params)
  finally:
    ckpt.close()


def test_restore_last_good_none_when_empty(setup, tmp_path):
  cfg, agent, params, _ = setup
  state = learner_lib.make_train_state(params, cfg)
  ckpt = Checkpointer(str(tmp_path / 'emptygood'))
  try:
    assert ckpt.restore_last_good(state) is None
  finally:
    ckpt.close()


def test_sharded_state_roundtrip(setup, tmp_path):
  """The docstring's multi-chip claim: a DP-sharded TrainState saves
  and restores onto the same mesh placements (SURVEY §5.4 → Orbax)."""
  from scalable_agent_tpu.parallel import mesh as mesh_lib
  from scalable_agent_tpu.parallel import train_parallel
  import dataclasses
  cfg, agent, params, _ = setup
  cfg = dataclasses.replace(cfg, batch_size=8)  # 8-way data axis
  batch = make_example_batch(cfg.unroll_length + 1, cfg.batch_size,
                             24, 32, 4, MAX_INSTRUCTION_LEN)
  params = jax.tree_util.tree_map(jnp.copy, params)
  mesh = mesh_lib.make_mesh(model_parallelism=1)
  state = train_parallel.make_sharded_train_state(params, cfg, mesh)
  step, place = train_parallel.make_sharded_train_step(
      agent, cfg, mesh, batch)
  state, _ = step(state, place(batch))

  ckpt = Checkpointer(str(tmp_path / 'sharded'))
  ckpt.save(state, force=True)
  ckpt.wait_until_finished()

  params2 = init_params(agent, jax.random.PRNGKey(7),
                        {'frame': (24, 32, 3),
                         'instr_len': MAX_INSTRUCTION_LEN})
  target = train_parallel.make_sharded_train_state(params2, cfg, mesh)
  restored = ckpt.restore_latest(target)
  ckpt.close()
  assert restored is not None
  _tree_equal(restored.params, state.params)
  # Placements survive: restored leaves live on the mesh like the
  # original (and training continues from them without resharding).
  leaf = jax.tree_util.tree_leaves(restored.params)[0]
  orig = jax.tree_util.tree_leaves(state.params)[0]
  assert leaf.sharding.is_equivalent_to(orig.sharding, leaf.ndim)
  resumed, _ = step(restored, place(batch))
  assert int(resumed.update_steps) == 2


# --- Round 12: content-digest ladder (bit rot) -----------------------


def test_digest_ladder_refuses_bitrot_under_last_good(setup, tmp_path):
  """The round-12 gap: a byte flipped in a COMMITTED step — digests
  recorded, LAST_GOOD advanced — restores 'successfully' through
  orbax as garbage. The ladder must refuse it on content digests
  (counted separately as digest_fallbacks) and restore the previous
  verified step; restore_last_good must make the same call."""
  from scalable_agent_tpu.runtime import faults as faults_lib
  cfg, agent, params, _ = setup
  state = learner_lib.make_train_state(
      jax.tree_util.tree_map(jnp.copy, params), cfg)
  ckpt = Checkpointer(str(tmp_path / 'rot'), save_interval_secs=0)
  try:
    _save_steps(ckpt, state, (1, 2))
    assert ckpt.last_good_step() == 2
    assert ckpt.verify_step_digests(2) is True
    faults_lib.bitrot_checkpoint_step(str(tmp_path / 'rot'), 2, seed=3)
    with pytest.raises(Exception, match='digest'):
      ckpt.verify_step_digests(2)
    restored = ckpt.restore_latest(state)
    assert restored is not None
    _tree_equal(restored.params, state.params)
    assert ckpt.digest_fallbacks == 1
    assert ckpt.restore_fallbacks >= 1
    # restore_last_good: the marker NAMES the rotted step, but the
    # digests in its own manifest refuse it — the ladder lands on 1.
    rolled = ckpt.restore_last_good(state)
    assert rolled is not None
    assert ckpt.digest_fallbacks >= 2
  finally:
    ckpt.close()


def test_digest_mismatch_classified_corruption_not_structural():
  """CheckpointCorruption's message must route down the corruption
  arm of the ladder (fallback), never the structural arm (raise with
  config-flag guidance)."""
  from scalable_agent_tpu import checkpoint as checkpoint_lib
  e = checkpoint_lib.CheckpointCorruption(
      "checkpoint step 7: content digest verification failed for "
      "'default/d/abc' (crc 0000beef differs from the recorded "
      '0000dead) — bit rot after commit; this step cannot be trusted')
  assert not checkpoint_lib._looks_structural(e)


def test_ckpt_bitrot_fault_site_fires_after_commit(setup, tmp_path):
  """The 'ckpt_bitrot' site: save() verifies, records digests,
  advances LAST_GOOD — and THEN the scheduled fault rots the step, so
  every marker calls it good and only the digest ladder can tell."""
  from scalable_agent_tpu.runtime import faults as faults_lib
  cfg, agent, params, _ = setup
  state = learner_lib.make_train_state(
      jax.tree_util.tree_map(jnp.copy, params), cfg)
  ckpt = Checkpointer(str(tmp_path / 'site'), save_interval_secs=0)
  faults_lib.install(faults_lib.FaultPlan(
      [faults_lib.Fault('ckpt_bitrot', 0, 'flip')], seed=9))
  try:
    assert ckpt.save(state, step=1, force=True)
    assert ckpt.last_good_step() == 1  # the marker believed the save
    with pytest.raises(Exception, match='digest'):
      ckpt.verify_step_digests(1)
  finally:
    faults_lib.clear()
    ckpt.close()


def test_digests_disabled_skips_verification(setup, tmp_path):
  """--ckpt_digests=false: no ledger recorded, verification is a
  no-op (None), and a rotted step restores exactly as pre-round-12 —
  the knob is a real escape hatch, not a silent half-state."""
  cfg, agent, params, _ = setup
  state = learner_lib.make_train_state(
      jax.tree_util.tree_map(jnp.copy, params), cfg)
  ckpt = Checkpointer(str(tmp_path / 'off'), save_interval_secs=0,
                      verify_digests=False)
  try:
    _save_steps(ckpt, state, (1,))
    assert ckpt.verify_step_digests(1) is None
    import os
    assert not any(n.startswith('DIGEST_')
                   for n in os.listdir(str(tmp_path / 'off')))
  finally:
    ckpt.close()


def test_digest_ledgers_pruned_with_steps(setup, tmp_path):
  """DIGEST_<step>.json files of pruned steps are cleaned up (a long
  run must not accumulate one file per evicted checkpoint)."""
  import os
  cfg, agent, params, _ = setup
  state = learner_lib.make_train_state(
      jax.tree_util.tree_map(jnp.copy, params), cfg)
  ckpt = Checkpointer(str(tmp_path / 'prune'), max_to_keep=2,
                      save_interval_secs=0)
  try:
    _save_steps(ckpt, state, (1, 2, 3))
    names = {n for n in os.listdir(str(tmp_path / 'prune'))
             if n.startswith('DIGEST_')}
    assert names == {'DIGEST_2.json', 'DIGEST_3.json'}
  finally:
    ckpt.close()
