"""Persistent compilation cache (round 23): flag resolution,
arming order in `distributed.maybe_initialize` (before the backend
early-return so single-process runs get it too), warm-spin-up cache
hits observed through the JAX monitoring bus, and concurrent members
sharing one cache dir without tripping over each other.
"""

import os
import threading

import pytest

import jax
import jax.numpy as jnp
from jax._src import compilation_cache as jax_compilation_cache
from jax._src import monitoring as jax_monitoring

from scalable_agent_tpu.config import Config
from scalable_agent_tpu.parallel import distributed


def _base_config(logdir, **kw):
  return Config(env_backend='bandit', logdir=logdir, **kw)


def _current_cache_dir():
  # Contextmanager-backed flags are read via attribute access
  # (`jax.config.read` raises for them).
  return jax.config.jax_compilation_cache_dir


class _armed:
  """Arm a cache dir for the duration of a test, restoring the
  process-global jax.config value (and resetting the cache backend)
  on exit so unrelated tests never write into a deleted tmp dir."""

  def __init__(self, dirname):
    self.dirname = dirname

  def __enter__(self):
    self.prev = _current_cache_dir()
    return self

  def __exit__(self, *exc):
    jax.config.update('jax_compilation_cache_dir', self.prev)
    try:
      jax_compilation_cache.reset_cache()
    except Exception:
      pass


# --- Flag resolution. ---


def test_resolved_compile_cache_dir_auto_points_under_logdir(tmp_path):
  cfg = _base_config(str(tmp_path))
  assert cfg.compile_cache_dir == 'auto'
  assert cfg.resolved_compile_cache_dir == os.path.join(
      str(tmp_path), '.jax_cache')


def test_resolved_compile_cache_dir_empty_disables(tmp_path):
  cfg = _base_config(str(tmp_path), compile_cache_dir='')
  assert cfg.resolved_compile_cache_dir == ''


def test_resolved_compile_cache_dir_explicit_wins(tmp_path):
  d = os.path.join(str(tmp_path), 'shared_cache')
  cfg = _base_config(str(tmp_path), compile_cache_dir=d)
  assert cfg.resolved_compile_cache_dir == d


# --- Arming. ---


def test_arm_compile_cache_creates_dir_and_updates_jax_config(tmp_path):
  d = os.path.join(str(tmp_path), 'cache')
  cfg = _base_config(str(tmp_path), compile_cache_dir=d)
  with _armed(d):
    jax.config.update('jax_compilation_cache_dir', None)
    distributed._arm_compile_cache(cfg)
    assert os.path.isdir(d)
    assert _current_cache_dir() == d


def test_arm_compile_cache_empty_flag_is_a_no_op(tmp_path):
  cfg = _base_config(str(tmp_path), compile_cache_dir='')
  with _armed(None):
    jax.config.update('jax_compilation_cache_dir', None)
    distributed._arm_compile_cache(cfg)
    assert _current_cache_dir() is None
    assert not os.path.exists(os.path.join(str(tmp_path), '.jax_cache'))


def test_arm_compile_cache_first_writer_wins(tmp_path):
  # A population parent arms <parent_logdir>/.jax_cache; the member
  # configs that follow must NOT re-arm to per-member dirs (that would
  # shatter the shared cache into N cold ones).
  parent = os.path.join(str(tmp_path), 'parent_cache')
  member = os.path.join(str(tmp_path), 'member_cache')
  with _armed(parent):
    jax.config.update('jax_compilation_cache_dir', None)
    distributed._arm_compile_cache(
        _base_config(str(tmp_path), compile_cache_dir=parent))
    distributed._arm_compile_cache(
        _base_config(str(tmp_path), compile_cache_dir=member))
    assert _current_cache_dir() == parent
    assert not os.path.exists(member)


def test_auto_does_not_arm_on_cpu_pinned_process(tmp_path):
  # This test process IS cpu-pinned (tests/conftest.py), so this runs
  # the real gate: jaxlib's XLA:CPU executable reload can SIGSEGV at
  # driver scale, so 'auto' must never turn the cache on here — a
  # full tier-1 run used to die mid-suite (exit 134/139) the first
  # time a driver test re-hit an entry an earlier test had written.
  cfg = _base_config(str(tmp_path))  # compile_cache_dir='auto'
  with _armed(None):
    jax.config.update('jax_compilation_cache_dir', None)
    distributed._arm_compile_cache(cfg)
    assert _current_cache_dir() is None
    assert not os.path.exists(os.path.join(str(tmp_path), '.jax_cache'))


def test_auto_arms_under_logdir_when_not_cpu_pinned(tmp_path, monkeypatch):
  # On an accelerator host (sitecustomize pins a non-cpu platform)
  # 'auto' arms <logdir>/.jax_cache — the tentpole's default-on path.
  monkeypatch.setattr(distributed, '_cpu_pinned_platform', lambda: False)
  cfg = _base_config(str(tmp_path))
  d = os.path.join(str(tmp_path), '.jax_cache')
  with _armed(d):
    jax.config.update('jax_compilation_cache_dir', None)
    distributed._arm_compile_cache(cfg)
    assert _current_cache_dir() == d
    assert os.path.isdir(d)


def test_explicit_dir_arms_even_on_cpu_pinned_process(tmp_path):
  # Explicit opt-in overrides the CPU gate (the caller vouches their
  # programs reload safely — e.g. the small anakin/bandit programs).
  assert distributed._cpu_pinned_platform()  # conftest pins cpu
  d = os.path.join(str(tmp_path), 'cache')
  cfg = _base_config(str(tmp_path), compile_cache_dir=d)
  with _armed(d):
    jax.config.update('jax_compilation_cache_dir', None)
    distributed._arm_compile_cache(cfg)
    assert _current_cache_dir() == d


def test_maybe_initialize_arms_cache_before_backend_early_return(tmp_path):
  d = os.path.join(str(tmp_path), 'cache')
  cfg = _base_config(str(tmp_path), compile_cache_dir=d)
  with _armed(d):
    jax.config.update('jax_compilation_cache_dir', None)
    # No coordinator_address: multi-host init is skipped, but the
    # cache must already be armed by then.
    assert distributed.maybe_initialize(cfg) is False
    assert _current_cache_dir() == d
    assert os.path.isdir(d)


# --- Behavior: warm spin-ups actually hit the persistent cache. ---


def test_second_spinup_of_identical_program_hits_cache(tmp_path):
  d = os.path.join(str(tmp_path), 'cache')
  cfg = _base_config(str(tmp_path), compile_cache_dir=d)
  events = []

  def _listener(event, **kwargs):
    events.append(event)

  with _armed(d):
    jax.config.update('jax_compilation_cache_dir', None)
    distributed._arm_compile_cache(cfg)
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    jax.config.update('jax_persistent_cache_min_compile_time_secs', 0.0)
    jax_monitoring.register_event_listener(_listener)
    try:
      @jax.jit
      def f(x):
        return jnp.sin(x) * jnp.cos(x) + 23.0

      f(jnp.ones((8, 8))).block_until_ready()
      assert os.listdir(d), 'cold compile wrote no cache entries'
      # Drop the in-memory executable so the second "spin-up" must
      # go back through the compilation path.
      jax.clear_caches()
      events.clear()
      f(jnp.ones((8, 8))).block_until_ready()
      hits = [e for e in events if 'compilation_cache' in e and 'hit' in e]
      assert hits, f'no persistent-cache hit events in {sorted(set(events))}'
    finally:
      jax_monitoring._unregister_event_listener_by_callback(_listener)
      jax.config.update('jax_persistent_cache_min_compile_time_secs',
                        prev_min)


def test_concurrent_members_share_one_cache_dir_safely(tmp_path):
  # Two "members" compiling into the same armed dir at once: writes
  # are keyed and atomic on the JAX side; nothing may raise and the
  # dir must hold entries afterwards.
  d = os.path.join(str(tmp_path), 'cache')
  with _armed(d):
    jax.config.update('jax_compilation_cache_dir', None)
    distributed._arm_compile_cache(
        _base_config(str(tmp_path), compile_cache_dir=d))
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    jax.config.update('jax_persistent_cache_min_compile_time_secs', 0.0)
    errors = []

    def member(k):
      try:
        @jax.jit
        def g(x):
          return jnp.tanh(x) + float(k) * x

        g(jnp.ones((4, 4))).block_until_ready()
      except Exception as e:  # pragma: no cover - failure path
        errors.append(e)

    try:
      threads = [threading.Thread(target=member, args=(k,))
                 for k in range(2)]
      for t in threads:
        t.start()
      for t in threads:
        t.join()
    finally:
      jax.config.update('jax_persistent_cache_min_compile_time_secs',
                        prev_min)
    assert not errors
    assert os.listdir(d)
