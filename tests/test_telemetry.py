"""The round-13 telemetry plane: unified metrics registry, per-unroll
trace spans (v8 wire negotiation + learner-side completion), the
flight recorder, and trace_report reconstruction.

The e2e test is the acceptance bar: a 2-process fleet run (learner +
no-accelerator remote child) whose traces.jsonl reconstructs
per-unroll hop-by-hop latency and the per-batch policy-lag histogram
through scripts/trace_report.py.
"""

import json
import math
import os
import socket
import threading
import time

import numpy as np
import pytest

from scalable_agent_tpu import telemetry
from scalable_agent_tpu.runtime import remote, ring_buffer
from scalable_agent_tpu.structs import (ActorOutput, AgentOutput,
                                        StepOutput, StepOutputInfo)
from scripts import trace_report


def _tiny_unroll(seed=0, t1=3, num_actions=3):
  rng = np.random.RandomState(seed)
  return ActorOutput(
      level_name=np.int32(0),
      agent_state=(np.zeros((1, 4), np.float32),
                   np.ones((1, 4), np.float32)),
      env_outputs=StepOutput(
          reward=rng.randn(t1).astype(np.float32),
          info=StepOutputInfo(np.zeros(t1, np.float32),
                              np.zeros(t1, np.int32)),
          done=np.zeros(t1, bool),
          observation=(
              rng.randint(0, 255, (t1, 4, 6, 3)).astype(np.uint8),
              np.zeros((t1, 5), np.int32))),
      agent_outputs=AgentOutput(
          action=rng.randint(0, num_actions, t1).astype(np.int32),
          policy_logits=rng.randn(t1, num_actions).astype(np.float32),
          baseline=rng.randn(t1).astype(np.float32)))


# --------------------------------------------------------------------
# Metrics registry.
# --------------------------------------------------------------------


def test_registry_counter_gauge_histogram_snapshot():
  reg = telemetry.MetricsRegistry()
  c = reg.counter('t/c')
  c.inc()
  c.inc(4)
  g = reg.gauge('t/g')
  g.set(2.5)
  backing = {'n': 7}
  reg.gauge('t/lazy', fn=lambda: backing['n'])
  h = reg.histogram('t/h')
  for v in (1.0, 2.0, 3.0):
    h.observe(v)
  snap = reg.snapshot()
  assert snap['t/c'] == 5
  assert snap['t/g'] == 2.5
  assert snap['t/lazy'] == 7
  assert snap['t/h']['count'] == 3
  assert snap['t/h']['p50'] == 2.0
  backing['n'] = 9  # lazy gauges read live values
  assert reg.snapshot()['t/lazy'] == 9


def test_registry_replaces_by_name_latest_wins():
  reg = telemetry.MetricsRegistry()
  old = reg.counter('t/c')
  old.inc(10)
  new = reg.counter('t/c')  # a new component incarnation
  new.inc(1)
  assert reg.snapshot()['t/c'] == 1  # the live incarnation


def test_gauge_callback_failure_reads_nan():
  reg = telemetry.MetricsRegistry()
  reg.gauge('t/boom', fn=lambda: 1 / 0)
  assert math.isnan(reg.snapshot()['t/boom'])


def test_histogram_empty_percentiles_are_nan():
  h = telemetry.Histogram('t/h')
  p50, p99 = h.percentiles(0.5, 0.99)
  assert math.isnan(p50) and math.isnan(p99)
  assert math.isnan(h.snapshot_value()['p50'])


# --------------------------------------------------------------------
# Trace contexts + sidecar tag store.
# --------------------------------------------------------------------


def test_make_trace_and_stamp():
  tr = telemetry.make_trace('a-0', 3, epoch=7, behavior_version=2)
  telemetry.stamp(tr, telemetry.HOP_DONE, t=1.0)
  telemetry.stamp(tr, telemetry.HOP_SEND, t=2.0)
  assert tr['a'] == 'a-0' and tr['s'] == 3
  assert tr['e'] == 7 and tr['bv'] == 2
  assert tr['h'] == [['done', 1.0], ['send', 2.0]]
  assert telemetry.stamp(None, telemetry.HOP_WIRE) is None  # tolerant


def test_tag_store_identity_keyed_and_bounded():
  store = telemetry._TagStore(capacity=2)
  a, b, c = _tiny_unroll(1), _tiny_unroll(2), _tiny_unroll(3)
  store.tag(a, {'a': 'x'})
  store.tag(b, {'a': 'y'})
  store.tag(c, {'a': 'z'})  # evicts the oldest (a)
  assert store.pop(a) is None
  assert store.evicted == 1
  assert store.pop(b) == {'a': 'y'}
  assert store.pop(b) is None  # popped once


# --------------------------------------------------------------------
# PipelineTracer: staged/served FIFOs, lag clocks, traces.jsonl.
# --------------------------------------------------------------------


def _read_jsonl(path):
  with open(path) as f:
    return [json.loads(line) for line in f if line.strip()]


def test_tracer_completes_spans_and_batch_records(tmp_path):
  tracer = telemetry.PipelineTracer(str(tmp_path))
  try:
    tracer.on_publish(10)  # local publish clock -> 1
    u1, u2 = _tiny_unroll(1), _tiny_unroll(2)
    for i, u in enumerate((u1, u2)):
      tr = telemetry.make_trace('local-0', i, behavior_version=0)
      telemetry.stamp(tr, telemetry.HOP_DONE)
      tracer.tag(u, tr)
    tracer.on_batch([u1, u2], n_fresh=2)
    tracer.on_serve()
    tracer.on_step(5)
    records = _read_jsonl(tracer.path)
  finally:
    tracer.close()
  kinds = [r['k'] for r in records]
  assert kinds == ['publish', 'batch']
  batch = records[-1]
  assert batch['step'] == 5 and batch['n_fresh'] == 2
  # Local clock: publish count 1 - behaviour version 0 = lag 1.
  assert batch['lag'] == [1, 1]
  for span in batch['spans']:
    hops = [h[0] for h in span['h']]
    assert hops == ['done', 'staged', 'serve', 'step']
  assert tracer.stats()['batches'] == 1
  assert tracer.stats()['unrolls'] == 2


def test_tracer_remote_clock_uses_commit_version(tmp_path):
  tracer = telemetry.PipelineTracer(str(tmp_path))
  try:
    u = _tiny_unroll(1)
    tr = telemetry.make_trace('r0', 0, behavior_version=4)
    tr['cv'] = 9  # what the ingest worker stamps at commit
    tracer.tag(u, tr)
    tracer.on_batch([u], n_fresh=1)
    tracer.on_serve()
    tracer.on_step(1)
    records = _read_jsonl(tracer.path)
  finally:
    tracer.close()
  assert records[-1]['lag'] == [5]  # 9 - 4, ingest clock


def test_tracer_untagged_unrolls_counted(tmp_path):
  tracer = telemetry.PipelineTracer(str(tmp_path))
  try:
    u = _tiny_unroll(1)  # never tagged
    # The id-keyed sidecar documents one benign hazard: a freed
    # unroll from an earlier test can leave a stale tag at this
    # object's reused address. Drop any alias so 'never tagged' holds.
    telemetry.pop_unroll(u)
    tracer.on_batch([u], n_fresh=1)
    assert tracer.stats()['untagged_unrolls'] == 1
  finally:
    tracer.close()


def test_flight_recorder_ring_and_registry_snapshots():
  flight = telemetry.FlightRecorder(capacity=8, snapshots=2)
  for i in range(20):
    flight.record({'k': 'batch', 'step': i})
  flight.note_registry({'a': 1})
  flight.note_registry({'a': 2})
  flight.note_registry({'a': 3})
  dump = flight.dump()
  assert len(dump['records']) == 8
  assert dump['records'][-1]['step'] == 19
  assert [s['metrics']['a'] for s in dump['registry_snapshots']] == \
      [2, 3]


def test_flight_recorder_write_is_json(tmp_path):
  flight = telemetry.FlightRecorder()
  flight.record({'k': 'publish', 'v': 1})
  path = flight.write(str(tmp_path / 'flight.json'))
  with open(path) as f:
    dump = json.load(f)
  assert dump['records'][0]['v'] == 1


# --------------------------------------------------------------------
# v8 wire negotiation + remote stamping.
# --------------------------------------------------------------------


def test_v8_trace_negotiated_and_span_stamped_across_wire(tmp_path):
  buffer = ring_buffer.TrajectoryBuffer(4)
  server = remote.TrajectoryIngestServer(buffer, {'w': np.zeros(2)},
                                         host='127.0.0.1')
  tracer = telemetry.PipelineTracer(str(tmp_path))
  telemetry.set_tracer(tracer)
  client = remote.RemoteActorClient(f'127.0.0.1:{server.port}',
                                    connect_timeout_secs=10)
  try:
    client.handshake({'protocol': remote.PROTOCOL_VERSION})
    assert client.trace_ok
    client.note_install(1)
    unroll = _tiny_unroll(1)
    tr = telemetry.make_trace('child-0', 0, behavior_version=1)
    telemetry.stamp(tr, telemetry.HOP_DONE)
    client.send_unroll(unroll, params_version=1, trace=tr)
    landed = buffer.get(timeout=5)
    span = telemetry.pop_unroll(landed)
    assert span is not None
    hops = [h[0] for h in span['h']]
    assert hops == ['done', 'send', 'wire', 'commit']
    assert span['cv'] == 1  # ingest publish clock at commit
    assert 'pi' not in span  # install notice consumed server-side
    assert tracer.stats()['param_installs'] == 1
    records = _read_jsonl(tracer.path)
    installs = [r for r in records if r['k'] == 'install']
    assert installs and installs[0]['a'] == 'child-0'
    assert installs[0]['v'] == 1
  finally:
    telemetry.set_tracer(None)
    tracer.close()
    client.close()
    server.close()
    buffer.close()


def test_v8_v7_interop_trace_negotiated_off(tmp_path):
  """A forged v7 contract keeps the old wire exactly: trace_ok stays
  off and unroll frames carry no 5th element (the server parses them
  as v7)."""
  buffer = ring_buffer.TrajectoryBuffer(4)
  server = remote.TrajectoryIngestServer(buffer, {'w': np.zeros(2)},
                                         host='127.0.0.1')
  tracer = telemetry.PipelineTracer(str(tmp_path))
  telemetry.set_tracer(tracer)
  client = remote.RemoteActorClient(f'127.0.0.1:{server.port}',
                                    connect_timeout_secs=10)
  try:
    client.handshake({'protocol': 7})
    assert not client.trace_ok
    tr = telemetry.make_trace('old-0', 0)
    client.send_unroll(_tiny_unroll(1), params_version=1, trace=tr)
    landed = buffer.get(timeout=5)
    assert telemetry.pop_unroll(landed) is None
    assert tracer.stats()['untagged_unrolls'] == 0  # just no span
  finally:
    telemetry.set_tracer(None)
    tracer.close()
    client.close()
    server.close()
    buffer.close()


def test_trace_off_server_negotiates_off(tmp_path):
  """--telemetry_trace=false learner: server-info advertises no
  tracing, the client doesn't stamp."""
  buffer = ring_buffer.TrajectoryBuffer(4)
  server = remote.TrajectoryIngestServer(buffer, {'w': np.zeros(2)},
                                         host='127.0.0.1', trace=False)
  client = remote.RemoteActorClient(f'127.0.0.1:{server.port}',
                                    connect_timeout_secs=10)
  try:
    client.handshake({'protocol': remote.PROTOCOL_VERSION})
    assert not client.trace_ok
  finally:
    client.close()
    server.close()
    buffer.close()


def test_stats_request_serves_registry_snapshot():
  buffer = ring_buffer.TrajectoryBuffer(4)
  server = remote.TrajectoryIngestServer(buffer, {'w': np.zeros(2)},
                                         host='127.0.0.1')
  client = remote.RemoteActorClient(f'127.0.0.1:{server.port}',
                                    connect_timeout_secs=10)
  try:
    client.handshake({'protocol': remote.PROTOCOL_VERSION})
    client.send_unroll(_tiny_unroll(1))
    stats = client.fetch_stats()
    assert stats['ingest']['unrolls'] == 1
    # The registry view of the same counter — one source of truth.
    assert stats['registry']['ingest/unrolls'] == 1
    assert 'ingest/ack_ms' in stats['registry']
  finally:
    client.close()
    server.close()
    buffer.close()


# --------------------------------------------------------------------
# Prefetcher integration: spans complete through the real feed path.
# --------------------------------------------------------------------


def test_prefetcher_completes_spans_through_feed(tmp_path):
  tracer = telemetry.PipelineTracer(str(tmp_path))
  telemetry.set_tracer(tracer)
  buffer = ring_buffer.TrajectoryBuffer(8)
  try:
    for i in range(4):
      u = _tiny_unroll(i)
      tr = telemetry.begin_unroll_trace('local-0', i)
      assert tr is not None  # tracer installed -> tracing on
      telemetry.stamp(tr, telemetry.HOP_DONE)
      telemetry.tag_unroll(u, tr)
      buffer.put(u)
    prefetcher = ring_buffer.BatchPrefetcher(buffer, 4,
                                             place_fn=lambda b: b)
    prefetcher.get(timeout=10)
    tracer.on_step(1)
    records = _read_jsonl(tracer.path)
    batch = [r for r in records if r['k'] == 'batch'][-1]
    assert len(batch['spans']) == 4
    for span in batch['spans']:
      assert [h[0] for h in span['h']] == ['done', 'staged', 'serve',
                                           'step']
    # Behaviour version defaulted to the tracer's publish clock (0).
    assert batch['lag'] == [0, 0, 0, 0]
    prefetcher.close()
  finally:
    telemetry.set_tracer(None)
    tracer.close()
    buffer.close()


# --------------------------------------------------------------------
# trace_report reconstruction.
# --------------------------------------------------------------------


def test_trace_report_summarize_hops_and_lag(tmp_path):
  tracer = telemetry.PipelineTracer(str(tmp_path))
  # Backdated synthetic stamps: the staged/serve/step hops are
  # stamped at REAL now by the tracer, and the round-14 skew rule
  # drops negative deltas — future-dated done/send/wire stamps would
  # read as clock skew.
  t0 = time.time() - 30.0
  tracer.on_publish(1)
  for step in range(3):
    u = _tiny_unroll(step)
    tr = telemetry.make_trace('a0', step, behavior_version=0)
    telemetry.stamp(tr, telemetry.HOP_DONE, t0 + step)
    telemetry.stamp(tr, telemetry.HOP_SEND, t0 + step + 0.010)
    telemetry.stamp(tr, telemetry.HOP_WIRE, t0 + step + 0.030)
    tracer.tag(u, tr)
    tracer.on_batch([u], n_fresh=1)
    tracer.on_serve()
    tracer.on_step(step)
  # Install AFTER the publish in record order (summarize sorts by t).
  tracer.on_install('a0', 1, time.time() + 0.5)
  tracer.close()

  records = trace_report.load_traces(str(tmp_path))
  summary = trace_report.summarize(records)
  assert summary['batches'] == 3 and summary['unrolls'] == 3
  hops = {row['hop']: row for row in summary['hops']}
  assert hops['done->send']['count'] == 3
  assert abs(hops['done->send']['p50_ms'] - 10.0) < 2.0
  assert abs(hops['send->wire']['p50_ms'] - 20.0) < 2.0
  assert 'wire->staged' in hops and 'serve->step' in hops
  assert summary['policy_lag']['histogram'] == {1: 3}
  assert summary['publish_to_install_secs']['count'] == 1
  # The text renderer never crashes on the summary (NaN -> '-').
  text = trace_report.render(summary)
  assert 'policy lag' in text


def test_trace_report_render_handles_empty():
  summary = trace_report.summarize([])
  text = trace_report.render(summary)
  assert '-' in text  # NaN percentiles render as '-'


def test_span_hop_deltas_duplicate_resend_stamps():
  """A resend re-stamps send/wire; the FIRST stamp per hop is the
  latency story (round-14 satellite: pinned on a pathological
  stream, not just documented)."""
  span = {'h': [['done', 10.0], ['send', 10.5], ['wire', 11.0],
                ['send', 13.0], ['wire', 14.0], ['commit', 11.2]]}
  deltas, e2e = trace_report.span_hop_deltas(span)
  assert dict(((a, b), ms) for (a, b), ms in deltas) == {
      ('done', 'send'): pytest.approx(500.0),
      ('send', 'wire'): pytest.approx(500.0),
      ('wire', 'commit'): pytest.approx(200.0, abs=1e-6)}
  assert e2e == pytest.approx(1200.0)


def test_span_hop_deltas_clock_skew_renders_dash_never_zero():
  """Cross-host wall clocks can skew past each other (NTP): a
  negative hop delta must surface as '-' (None), never a laundered
  0 ms — and never a crash."""
  span = {'h': [['done', 100.0], ['send', 100.2], ['wire', 99.8],
                ['commit', 100.4]]}
  deltas, e2e = trace_report.span_hop_deltas(span)
  by_pair = dict(deltas)
  assert by_pair[('send', 'wire')] is None          # skewed: no number
  assert by_pair[('wire', 'commit')] == pytest.approx(600.0)
  assert e2e == pytest.approx(400.0)                # done <= commit
  # A span whose LAST hop skews before its first: no e2e either.
  skewed = {'h': [['done', 100.0], ['send', 99.0]]}
  deltas, e2e = trace_report.span_hop_deltas(skewed)
  assert deltas == [(('done', 'send'), None)] and e2e is None
  # summarize() skips the skewed hops instead of polluting p50 with
  # zeros, and the renderer stays crash-free.
  rec = {'k': 'batch', 'step': 1, 't': 100.0, 'lag': [],
         'spans': [span, skewed]}
  summary = trace_report.summarize([rec])
  hops = {row['hop']: row for row in summary['hops']}
  assert 'send->wire' not in hops  # only skewed observations existed
  assert hops['wire->commit']['count'] == 1
  assert trace_report.render(summary)


def test_span_hop_deltas_malformed_stamps_never_crash():
  for h in (None, 'junk', [['done']], [['done', 'not-a-time']],
            [[1, 2, 3]], [None]):
    deltas, e2e = trace_report.span_hop_deltas({'h': h})
    assert deltas == [] and e2e is None


def test_trace_report_main_empty_traces_file(tmp_path, capsys):
  """An empty traces.jsonl (a run that died before its first batch)
  exits 1 with the how-to hint, never a crash."""
  (tmp_path / 'traces.jsonl').write_text('')
  assert trace_report.main([str(tmp_path)]) == 1
  assert 'no traces' in capsys.readouterr().err


def test_to_tensorboard_skips_skewed_hop_points():
  """to_tensorboard consumes the same span_hop_deltas: a skewed hop
  contributes NO scalar point (round-14 satellite — the two views
  keep agreeing)."""
  from scripts import to_tensorboard
  event = {'k': 'batch', 'step': 3, 'lag': [1],
           'spans': [{'h': [['done', 100.0], ['send', 99.0],
                            ['wire', 100.5]]}]}
  rows = to_tensorboard._trace_events(event)
  tags = [t for t, _, _ in rows]
  assert 'trace/hop_done_send_ms' not in tags  # skewed: skipped
  assert 'trace/hop_send_wire_ms' in tags
  assert 'trace/policy_lag_mean' in tags


# --------------------------------------------------------------------
# Acceptance: 2-process fleet run -> trace_report reconstruction.
# --------------------------------------------------------------------


def test_e2e_remote_fleet_traces_and_report(tmp_path):
  """The acceptance bar: a learner + a no-accelerator remote actor
  child (2 OS processes) train with tracing on; traces.jsonl then
  reconstructs per-unroll hop-by-hop latency across the wire
  (done→send→wire→commit→staged→serve→step) and the per-batch
  policy-lag histogram, and the summary scalars carry the live
  policy-lag percentiles."""
  import _remote_actor_child
  from scalable_agent_tpu import driver
  from scalable_agent_tpu.config import Config

  base = dict(
      env_backend='bandit', batch_size=2, unroll_length=5,
      num_action_repeats=1, episode_length=4, height=24, width=32,
      torso='shallow', use_py_process=False, use_instruction=False,
      total_environment_frames=10**6, inference_timeout_ms=5,
      checkpoint_secs=0, summary_secs=0, seed=17,
      publish_params_every=1)
  with socket.create_server(('127.0.0.1', 0)) as s:
    port = s.getsockname()[1]
  learner_cfg = Config(logdir=str(tmp_path), num_actors=0,
                       remote_actor_port=port, **base)
  child = _remote_actor_child.spawn(f'127.0.0.1:{port}',
                                    dict(base, num_actors=2))
  try:
    run = driver.train(learner_cfg, max_steps=4,
                       stall_timeout_secs=120)
    assert int(run.state.update_steps) == 4
    out, _ = child.communicate(timeout=120)
    assert child.returncode == 0, out[-2000:]
  finally:
    if child.poll() is None:
      child.kill()
      child.communicate()

  records = trace_report.load_traces(str(tmp_path))
  summary = trace_report.summarize(
      records, trace_report.load_incidents(str(tmp_path)))
  assert summary['batches'] >= 3
  assert summary['unrolls'] >= 6
  hops = {row['hop'] for row in summary['hops']}
  # The full remote pipeline, hop by hop, across both processes.
  for hop in ('done->send', 'send->wire', 'wire->commit',
              'commit->staged', 'staged->serve', 'serve->step'):
    assert hop in hops, (hop, hops)
  assert summary['e2e_ms']['count'] >= 6
  assert not math.isnan(summary['e2e_ms']['p99'])
  # Policy lag: behaviour versions rode the wire; the histogram is
  # the publish-delta distribution (≥0, small on a healthy loopback).
  lag_hist = summary['policy_lag']['histogram']
  assert lag_hist and sum(lag_hist.values()) >= 6
  assert all(int(k) >= 0 for k in lag_hist)
  # Publish→install joins: the child reported at least its handshake
  # install, and versions joined against publish records.
  assert summary['publish_to_install_secs']['count'] >= 1
  # The report renders end to end.
  text = trace_report.render(summary)
  assert 'per-hop latency' in text
  # Live summary export: the lag percentiles reached summaries.jsonl.
  with open(os.path.join(str(tmp_path), 'summaries.jsonl')) as f:
    tags = {json.loads(line)['tag'] for line in f if line.strip()}
  for tag in ('policy_lag_p50', 'policy_lag_p99', 'unroll_e2e_p50_ms',
              'unroll_e2e_p99_ms', 'trace_untagged_unrolls',
              # Round-14 satellites: the flight-recorder ring and the
              # JSONL dropped-writes ledger reach summaries.jsonl end
              # to end (before, only the trace scalars were asserted).
              'trace_flight_records', 'dropped_writes'):
    assert tag in tags, tag


def test_halt_bundle_carries_flight_dump(tmp_path):
  from scalable_agent_tpu import health as health_lib
  monitor = health_lib.HealthMonitor()
  flight = telemetry.FlightRecorder()
  flight.record({'k': 'batch', 'step': 7, 'lag': [2]})
  flight.note_registry({'ingest/unrolls': 5})
  path = monitor.write_halt_bundle(str(tmp_path), None, step=7,
                                   reason='test', flight=flight.dump())
  with open(path) as f:
    bundle = json.load(f)
  assert bundle['flight']['records'][0]['step'] == 7
  assert bundle['flight']['registry_snapshots'][0]['metrics'] == \
      {'ingest/unrolls': 5}


def test_health_counters_reach_registry():
  from scalable_agent_tpu import health as health_lib
  monitor = health_lib.HealthMonitor()
  monitor.observe_values(1, {'step_ok': 0.0})
  snap = telemetry.registry().snapshot()
  assert snap['health/skipped_steps'] == 1
  assert snap['health/flagged_steps'] == 1


def test_flight_recorder_gauges_registered_and_unregistered(tmp_path):
  """Round-14 satellite: the tracer registers fn-gauges over its
  flight ring (trace/flight_records, trace/flight_snapshots) and
  unregisters them at close — identity-checked like every other
  per-run fn-gauge."""
  reg = telemetry.registry()
  tracer = telemetry.PipelineTracer(str(tmp_path))
  try:
    tracer.flight.record({'k': 'batch', 'step': 1})
    tracer.flight.note_registry({'a': 1})
    snap = reg.snapshot()
    assert snap['trace/flight_records'] == 1
    assert snap['trace/flight_snapshots'] == 1
    assert len(tracer.flight) == 1
  finally:
    tracer.close()
  assert reg.get('trace/flight_records') is None
  assert reg.get('trace/flight_snapshots') is None


def test_dropped_writes_total_counts_post_close_writes(tmp_path):
  before = telemetry.dropped_writes_total()
  writer = telemetry.JsonlAppender(str(tmp_path), 'x.jsonl')
  writer.close()
  writer.write({'late': True})
  assert telemetry.dropped_writes_total() == before + 1


def test_trace_report_hop_order_matches_telemetry():
  """trace_report keeps its own literal HOP_ORDER (operator machines
  run it without the package's dependency chain) — this is the pin
  that keeps the two in sync."""
  assert tuple(trace_report.HOP_ORDER) == telemetry.HOP_ORDER


def test_closed_components_unregister_their_gauges():
  """fn-gauges close over their owner: close() must drop the
  registry's hold (identity-checked — a newer incarnation's
  registration survives an older one's teardown)."""
  reg = telemetry.registry()
  buffer = ring_buffer.TrajectoryBuffer(4)
  assert reg.get('buffer/occupancy') is not None
  buffer2 = ring_buffer.TrajectoryBuffer(4)  # replaces the names
  buffer.close()  # older instance: must NOT evict buffer2's gauges
  assert reg.get('buffer/occupancy') is buffer2._gauges[0]
  buffer2.close()
  assert reg.get('buffer/occupancy') is None


def test_malformed_trace_context_does_not_kill_the_reader(tmp_path):
  """A buggy v8 peer shipping a trace dict without a stamp list must
  not crash the ingest reader outside the quarantine accounting —
  stamp() repairs the shape and the unroll still lands + acks."""
  assert telemetry.stamp({'a': 'x'}, telemetry.HOP_WIRE)['h']
  assert telemetry.stamp({'a': 'x', 'h': 'junk'},
                         telemetry.HOP_WIRE)['h']
  buffer = ring_buffer.TrajectoryBuffer(4)
  server = remote.TrajectoryIngestServer(buffer, {'w': np.zeros(2)},
                                         host='127.0.0.1')
  tracer = telemetry.PipelineTracer(str(tmp_path))
  telemetry.set_tracer(tracer)
  client = remote.RemoteActorClient(f'127.0.0.1:{server.port}',
                                    connect_timeout_secs=10)
  try:
    client.handshake({'protocol': remote.PROTOCOL_VERSION})
    # Bypass send_unroll's stamping: ship the malformed context raw.
    reply = client._rpc(('unroll', _tiny_unroll(1), None, None,
                         {'a': 'buggy-peer'}), oob=True)
    assert reply[0] == 'ack'
    landed = buffer.get(timeout=5)
    span = telemetry.pop_unroll(landed)
    assert [h[0] for h in span['h']] == ['wire', 'commit']
    stats = server.stats()
    assert stats['unrolls'] == 1 and stats['quarantined'] == 0
  finally:
    telemetry.set_tracer(None)
    tracer.close()
    client.close()
    server.close()
    buffer.close()


def test_publish_install_join_uses_ingest_lane_version(tmp_path):
  """Install notices carry the ingest lane's version sequence; the
  join must key on the publish record's 'rv', not the step-stamped
  label (which is a different clock at production cadences)."""
  tracer = telemetry.PipelineTracer(str(tmp_path))
  t0 = time.time()
  # Step-stamped label 100, ingest-lane version 2 (the sequences
  # diverge immediately at publish_params_every > 1).
  tracer.on_publish(100, remote_version=2)
  tracer.on_install('a0', 2, t0 + 0.25)
  tracer.on_publish(200)  # local-only publish: no 'rv', no join key
  tracer.close()
  summary = trace_report.summarize(
      trace_report.load_traces(str(tmp_path)))
  assert summary['publish_to_install_secs']['count'] == 1
