"""The round-15 self-healing controller: declarative policy table,
bounded escalate/revert moves with hysteresis, observe-mode dry runs,
the CONTROLLER_LOG.json / incident / external-ledger audit trail, and
the driver end-to-end (an acting controller's moves ride the drain
manifest like slo_violation incidents).

Determinism is a tested property, not an accident: the scripted-trace
test drives `Controller.tick(now=...)` with an injected clock through
a fixed snapshot sequence and pins the EXACT action list — escalate,
hold-under-cool-down, hysteresis no-flap, revert — twice, asserting
the two traces are identical (the controller uses no randomness and
no hidden wall-clock reads beyond `now`).
"""

import json
import threading
import time

import pytest

from scalable_agent_tpu import controller as ctl
from scalable_agent_tpu import health as health_lib
from scalable_agent_tpu import observability
from scalable_agent_tpu import slo


def _obj(state=slo.OK, margin=None, value=None, severity='page',
         target=1.0, burns=0):
  return {'state': state, 'margin': margin, 'value': value,
          'severity': severity, 'target': target, 'burns': burns}


class _StubEngine:
  """control_snapshot()-shaped stand-in the tests script directly."""

  def __init__(self, **objectives):
    self.snapshot = objectives

  def control_snapshot(self):
    return {n: dict(e) for n, e in self.snapshot.items()}


class _Knob:
  """A recording numeric/enum actuator target."""

  def __init__(self, value):
    self.value = value
    self.sets = []

  def get(self):
    return self.value

  def set(self, v):
    self.sets.append(v)
    self.value = v


def _controller(engine, rules, actuators, tmp_path, mode='act',
                **kw):
  return ctl.Controller(engine, rules, actuators, str(tmp_path),
                        mode=mode, interval_secs=60.0, **kw)


# --------------------------------------------------------------------
# Policy table.
# --------------------------------------------------------------------


def test_default_rules_reference_shipped_objectives_and_actuators():
  names = {o.name for o in slo.DEFAULT_OBJECTIVES}
  for rule in ctl.DEFAULT_RULES:
    rule.validate()
    assert rule.objective in names, rule
    assert rule.actuator in ctl.KNOWN_ACTUATORS, rule


def test_load_rules_json_roundtrip_and_failures(tmp_path):
  path = tmp_path / 'policy.json'
  path.write_text(json.dumps([
      {'objective': 'fleet_healthy_fraction', 'actuator': 'fleet_size',
       'direction': 'up', 'step': 1, 'trigger_margin': 0.1,
       'clear_margin': 0.4, 'cooldown_secs': 2.0}]))
  rules = ctl.load_rules(str(path))
  assert len(rules) == 1 and rules[0].clear_margin == 0.4
  # Defaults when no path.
  assert [r.objective for r in ctl.load_rules()] == \
      [r.objective for r in ctl.DEFAULT_RULES]
  # A typo'd actuator fails at load, not silently at runtime.
  path.write_text(json.dumps([
      {'objective': 'x', 'actuator': 'warp_drive'}]))
  with pytest.raises(ValueError, match='unknown actuator'):
    ctl.load_rules(str(path))
  # The hysteresis band must be a band: clear >= trigger.
  path.write_text(json.dumps([
      {'objective': 'x', 'actuator': 'replay_k',
       'trigger_margin': 0.5, 'clear_margin': 0.1}]))
  with pytest.raises(ValueError, match='hysteresis'):
    ctl.load_rules(str(path))
  path.write_text(json.dumps({'not': 'a list'}))
  with pytest.raises(ValueError, match='non-empty JSON list'):
    ctl.load_rules(str(path))


def test_rules_over_missing_actuator_or_objective_are_dropped(
    tmp_path):
  engine = _StubEngine(known=_obj())
  knob = _Knob(1)
  rules = [
      ctl.Rule(objective='known', actuator='replay_k'),
      ctl.Rule(objective='known', actuator='publish_secs'),  # no act.
      ctl.Rule(objective='unknown', actuator='replay_k'),    # no obj.
  ]
  c = _controller(engine, rules, [
      ctl.Actuator('replay_k', kind='int', get_fn=knob.get,
                   set_fn=knob.set, minimum=1, maximum=4)], tmp_path)
  assert len(c._rules) == 1
  c.stop()


# --------------------------------------------------------------------
# The scripted deterministic trace (the ISSUE's controller-determinism
# satellite): exact action sequence, zero jitter.
# --------------------------------------------------------------------


def _scripted_trace(tmp_path, subdir):
  engine = _StubEngine(lag=_obj())
  knob = _Knob(2)
  rule = ctl.Rule(objective='lag', actuator='replay_k', step=1,
                  direction='up', cooldown_secs=10.0,
                  clear_margin=0.5)
  out = tmp_path / subdir
  out.mkdir()
  c = _controller(engine, [rule], [
      ctl.Actuator('replay_k', kind='int', get_fn=knob.get,
                   set_fn=knob.set, minimum=1, maximum=4)], out)
  trace = []

  def step(now, **obj):
    engine.snapshot['lag'] = _obj(**obj)
    for a in c.tick(now=now):
      trace.append((round(now, 1), a['kind'], a['from'], a['to']))

  step(0.0, state=slo.BURNING, margin=-0.5)    # escalate 2 -> 3
  step(5.0, state=slo.BURNING, margin=-0.5)    # hold: cool-down
  step(12.0, state=slo.BURNING, margin=-0.5)   # escalate 3 -> 4
  step(24.0, state=slo.BURNING, margin=-0.5)   # hold: at the bound
  step(36.0, state=slo.OK, margin=0.2)         # hysteresis: no flap
  step(48.0, state=slo.OK, margin=0.6)         # revert 4 -> 3
  step(53.0, state=slo.OK, margin=0.6)         # hold: cool-down
  step(60.0, state=slo.OK, margin=0.6)         # revert 3 -> 2 (done)
  step(72.0, state=slo.OK, margin=0.6)         # disengaged: idle
  c.stop()
  return trace, knob.sets, c.counts()


def test_scripted_trace_exact_action_sequence(tmp_path):
  trace, sets, counts = _scripted_trace(tmp_path, 'a')
  assert trace == [
      (0.0, 'escalate', 2, 3),
      (12.0, 'escalate', 3, 4),
      (48.0, 'revert', 4, 3),
      (60.0, 'revert', 3, 2),
  ]
  assert sets == [3, 4, 3, 2]
  assert counts == {'actions': 4, 'escalations': 2, 'reverts': 2,
                    'applied': 4, 'apply_errors': 0}
  # Zero jitter: an identical re-run produces the identical trace.
  trace2, sets2, _ = _scripted_trace(tmp_path, 'b')
  assert trace2 == trace and sets2 == sets


def test_enum_actuator_escalates_to_target_and_reverts(tmp_path):
  engine = _StubEngine(overload=_obj(state=slo.BURNING, margin=-1.0))
  knob = _Knob('block')
  rule = ctl.Rule(objective='overload', actuator='admission',
                  to='shed', revert_to='block', cooldown_secs=1.0,
                  clear_margin=0.0)
  c = _controller(engine, [rule], [
      ctl.Actuator('admission', kind='enum', get_fn=knob.get,
                   set_fn=knob.set,
                   values=('block', 'shed', 'grow'))], tmp_path)
  assert [(a['kind'], a['to']) for a in c.tick(now=0.0)] == \
      [('escalate', 'shed')]
  # Already at the target: burning keeps holding, no action spam.
  assert c.tick(now=5.0) == []
  engine.snapshot['overload'] = _obj(state=slo.OK, margin=3.0)
  assert [(a['kind'], a['to']) for a in c.tick(now=10.0)] == \
      [('revert', 'block')]
  assert knob.sets == ['shed', 'block']
  assert c.engaged_rules() == 0
  c.stop()


def test_margin_pressure_triggers_before_the_burn(tmp_path):
  """The leading-edge trigger: a page objective whose margin thinned
  to the trigger band moves the knob while the state is still OK —
  the mechanism that lets an actuated run keep its verdict green."""
  engine = _StubEngine(quorum=_obj(state=slo.OK, margin=0.05))
  knob = _Knob(2)
  rule = ctl.Rule(objective='quorum', actuator='fleet_size', step=1,
                  trigger_margin=0.1, clear_margin=0.4,
                  cooldown_secs=1.0)
  c = _controller(engine, [rule], [
      ctl.Actuator('fleet_size', kind='int', get_fn=knob.get,
                   set_fn=knob.set, minimum=1, maximum=4)], tmp_path)
  assert [a['kind'] for a in c.tick(now=0.0)] == ['escalate']
  assert knob.value == 3
  c.stop()


def test_no_data_holds_every_knob(tmp_path):
  engine = _StubEngine(lag=_obj(state=slo.BURNING, margin=-1.0))
  knob = _Knob(1)
  rule = ctl.Rule(objective='lag', actuator='replay_k', step=1,
                  cooldown_secs=0.0, clear_margin=0.0)
  c = _controller(engine, [rule], [
      ctl.Actuator('replay_k', kind='int', get_fn=knob.get,
                   set_fn=knob.set, minimum=1, maximum=4)], tmp_path)
  c.tick(now=0.0)
  assert knob.value == 2
  # Blindness is not a reason to move a knob — in either direction.
  engine.snapshot['lag'] = _obj(state=slo.NO_DATA)
  assert c.tick(now=10.0) == []
  assert knob.value == 2
  c.stop()


# --------------------------------------------------------------------
# Observe mode: the faithful dry run.
# --------------------------------------------------------------------


def test_observe_mode_logs_whole_sequence_without_touching(tmp_path):
  engine = _StubEngine(lag=_obj(state=slo.BURNING, margin=-1.0))
  knob = _Knob(1)
  rule = ctl.Rule(objective='lag', actuator='replay_k', step=1,
                  cooldown_secs=1.0, clear_margin=0.5)
  c = _controller(engine, [rule], [
      ctl.Actuator('replay_k', kind='int', get_fn=knob.get,
                   set_fn=knob.set, minimum=1, maximum=3)], tmp_path,
                  mode='observe')
  moves = []
  for t in (0.0, 2.0, 4.0, 6.0):
    moves += [(a['from'], a['to'], a['applied'])
              for a in c.tick(now=t)]
  # The virtual value walks the same 1 -> 2 -> 3 -> bound sequence an
  # acting controller would; the real knob never moves.
  assert moves == [(1, 2, False), (2, 3, False)]
  assert knob.sets == [] and knob.value == 1
  engine.snapshot['lag'] = _obj(state=slo.OK, margin=0.9)
  reverts = [(a['from'], a['to']) for a in c.tick(now=8.0)]
  assert reverts == [(3, 2)]
  assert knob.sets == []
  c.stop()
  log = ctl.read_log(str(tmp_path))
  assert log['mode'] == 'observe'
  assert all(not a['applied'] for a in log['actions'])


# --------------------------------------------------------------------
# Audit trail: log file, incidents, external ledger, failure paths.
# --------------------------------------------------------------------


def test_actions_land_in_log_incidents_and_external_ledger(tmp_path):
  engine = _StubEngine(lag=_obj(state=slo.BURNING, margin=-1.0))
  knob = _Knob(1)
  incidents = observability.EventLog(str(tmp_path))
  monitor = health_lib.HealthMonitor()
  rule = ctl.Rule(objective='lag', actuator='replay_k', step=1,
                  cooldown_secs=0.0, clear_margin=0.0)
  c = _controller(engine, [rule], [
      ctl.Actuator('replay_k', kind='int', get_fn=knob.get,
                   set_fn=knob.set, minimum=1, maximum=4)], tmp_path,
                  incidents=incidents, health=monitor)
  c.tick(now=0.0)
  c.stop()
  c.finalize()
  incidents.close()
  log = ctl.read_log(str(tmp_path))
  assert log['counts']['applied'] == 1
  (row,) = log['actions']
  assert (row['kind'], row['actuator'], row['from'], row['to'],
          row['applied']) == ('escalate', 'replay_k', 1, 2, True)
  with open(tmp_path / 'incidents.jsonl') as f:
    events = [json.loads(l) for l in f if l.strip()]
  (ev,) = [e for e in events if e['kind'] == 'controller_action']
  assert ev['action'] == 'escalate' and ev['actuator'] == 'replay_k'
  # The external-incident ledger (rides drain manifests/halt bundles).
  assert monitor.external_incidents == {'controller_replay_k': 1}


def test_failing_actuator_set_is_counted_not_fatal(tmp_path):
  engine = _StubEngine(lag=_obj(state=slo.BURNING, margin=-1.0))

  def broken_set(v):
    raise RuntimeError('knob fell off')

  rule = ctl.Rule(objective='lag', actuator='replay_k', step=1,
                  cooldown_secs=0.0, clear_margin=0.0)
  c = _controller(engine, [rule], [
      ctl.Actuator('replay_k', kind='int', get_fn=lambda: 1,
                   set_fn=broken_set, minimum=1, maximum=4)], tmp_path)
  (action,) = c.tick(now=0.0)
  assert action['applied'] is False
  assert 'knob fell off' in action['error']
  assert c.counts()['apply_errors'] == 1
  c.stop()


def test_bounded_moves_never_leave_the_registered_range(tmp_path):
  engine = _StubEngine(p=_obj(state=slo.BURNING, margin=-1.0))
  knob = _Knob(28.0)
  rule = ctl.Rule(objective='p', actuator='publish_secs', step=5.0,
                  cooldown_secs=0.0, clear_margin=0.0)
  c = _controller(engine, [rule], [
      ctl.Actuator('publish_secs', kind='float', get_fn=knob.get,
                   set_fn=knob.set, minimum=2.0, maximum=30.0)],
                  tmp_path)
  c.tick(now=0.0)
  assert knob.value == 30.0   # clamped, not 33.0
  assert c.tick(now=1.0) == []  # at the bound: holding IS the action
  c.stop()


# --------------------------------------------------------------------
# Driver end-to-end: an acting controller's moves ride the drain
# manifest (the external-incident ledger), the log lands, and the
# actuator really moved.
# --------------------------------------------------------------------


def test_acting_controller_rides_drain_manifest(tmp_path):
  from scalable_agent_tpu import driver
  from scalable_agent_tpu.config import Config

  # Window sizing: the engine thread ticks at >= 0.25 s (SloEngine's
  # floor), and a value burn needs 3 fast-window samples — 1.5 s is
  # the narrowest fast window that can burn from the thread alone
  # (steps may be scarce around compile time on a slow CI host).
  spec = [dict(name='always_burning', metric='driver/update_steps',
               comparison='<=', target=-1.0, severity='info',
               fast_window_secs=1.5, slow_window_secs=4.0)]
  policy = [dict(objective='always_burning', actuator='replay_k',
                 direction='up', step=1, cooldown_secs=0.2,
                 clear_margin=0.0)]
  spec_path = tmp_path / 'spec.json'
  policy_path = tmp_path / 'policy.json'
  spec_path.write_text(json.dumps(spec))
  policy_path.write_text(json.dumps(policy))
  cfg = Config(
      logdir=str(tmp_path), env_backend='bandit', num_actors=2,
      batch_size=2, unroll_length=5, num_action_repeats=1,
      episode_length=4, height=24, width=32, torso='shallow',
      use_py_process=False, use_instruction=False,
      total_environment_frames=10**9, inference_timeout_ms=5,
      checkpoint_secs=0, summary_secs=0, seed=5,
      controller='act', controller_policy=str(policy_path),
      controller_interval_secs=0.1, controller_replay_k_max=2,
      slo_spec=str(spec_path), slo_capture=False)
  drain = threading.Event()
  threading.Timer(7.0, drain.set).start()
  run = driver.train(cfg, stall_timeout_secs=30, drain_event=drain)
  # The actuator really moved (bounded at controller_replay_k_max).
  assert run.prefetcher.replay_k == 2
  assert run.controller is not None
  assert run.controller.counts()['applied'] >= 1
  log = ctl.read_log(str(tmp_path))
  assert log['mode'] == 'act'
  assert any(a['actuator'] == 'replay_k' and a['applied']
             for a in log['actions'])
  # The drain manifest names the controller's writes in the external
  # ledger (like slo_<name> burns) and carries the counts block.
  manifest = driver.read_resume_manifest(str(tmp_path))
  assert manifest is not None
  external = manifest['health']['external_incidents']
  assert external.get('controller_replay_k', 0) >= 1
  assert manifest['controller']['applied'] >= 1
  assert manifest['controller']['mode'] == 'act'
  # Incident stream carries the fsync'd controller_action records.
  with open(tmp_path / 'incidents.jsonl') as f:
    kinds = {json.loads(l)['kind'] for l in f if l.strip()}
  assert 'controller_action' in kinds


def test_enum_rule_without_target_fails_at_spinup(tmp_path):
  """Review fix: an enum rule missing `to` (or with a typo'd state)
  must fail at construction, not silently never fire / burn an apply
  error per cool-down."""
  engine = _StubEngine(overload=_obj())
  knob = _Knob('block')
  actuators = [ctl.Actuator('admission', kind='enum', get_fn=knob.get,
                            set_fn=knob.set,
                            values=('block', 'shed', 'grow'))]
  with pytest.raises(ValueError, match='needs a `to` target'):
    _controller(engine, [ctl.Rule(objective='overload',
                                  actuator='admission')],
                actuators, tmp_path)
  with pytest.raises(ValueError, match='not a legal state'):
    _controller(engine, [ctl.Rule(objective='overload',
                                  actuator='admission', to='shedd')],
                actuators, tmp_path)
  with pytest.raises(ValueError, match='not a legal state'):
    _controller(engine, [ctl.Rule(objective='overload',
                                  actuator='admission', to='shed',
                                  revert_to='blok')],
                actuators, tmp_path)


def test_opposing_rules_do_not_seesaw_a_shared_actuator(tmp_path):
  """Review fix: at most one engaged rule owns an actuator (first
  engaged wins, table order); a conflicting rule holds until the
  owner disengages instead of fighting it."""
  engine = _StubEngine(
      quorum=_obj(state=slo.BURNING, margin=-1.0),
      parked=_obj(state=slo.BURNING, margin=-1.0))
  knob = _Knob(4)
  grow = ctl.Rule(objective='quorum', actuator='fleet_size',
                  direction='up', step=1, cooldown_secs=1.0,
                  clear_margin=0.5)
  shrink = ctl.Rule(objective='parked', actuator='fleet_size',
                    direction='down', step=1, cooldown_secs=1.0,
                    clear_margin=0.5)
  c = _controller(engine, [grow, shrink], [
      ctl.Actuator('fleet_size', kind='int', get_fn=knob.get,
                   set_fn=knob.set, minimum=1, maximum=8)], tmp_path)
  # Both burning: only the FIRST rule (grow) moves the knob; shrink
  # holds — the knob walks monotonically up, never see-saws.
  for t in (0.0, 2.0, 4.0):
    c.tick(now=t)
  assert knob.sets == [5, 6, 7]
  # Grow clears and reverts to its baseline; shrink holds while the
  # knob is owned and may engage only once grow fully disengages (the
  # final revert releases ownership within that same tick).
  engine.snapshot['quorum'] = _obj(state=slo.OK, margin=0.9)
  for t in (6.0, 8.0, 10.0):
    c.tick(now=t)
  # The whole history is two clean monotone phases, never interleaved:
  # grow up 4->7, grow back 7->4, then shrink's first own move 4->3.
  assert knob.sets == [5, 6, 7, 6, 5, 4, 3]
  assert c.engaged_rules() == 1  # shrink owns the knob now
  # Shrink's objective clears: it reverts to ITS baseline (4).
  engine.snapshot['parked'] = _obj(state=slo.OK, margin=0.9)
  c.tick(now=12.0)
  assert knob.value == 4 and c.engaged_rules() == 0
  c.stop()


def test_validate_controller_ranges_and_crosslinks():
  from scalable_agent_tpu.config import Config, validate_controller
  with pytest.raises(ValueError):
    validate_controller(Config(controller='auto'))
  with pytest.raises(ValueError):
    validate_controller(Config(controller_replay_k_max=0))
  assert validate_controller(Config()) == []
  warned = validate_controller(Config(controller='act',
                                      slo_engine=False))
  assert any('disabled' in w for w in warned)
  warned = validate_controller(Config(controller='act'))
  assert any('clipped-target anchor' in w for w in warned)
  # Review fix: a probation cool-down longer than the idle-reaping
  # window with heartbeats off would get the cooling client reaped
  # mid-probation.
  warned = validate_controller(Config(remote_heartbeat_secs=0,
                                      remote_conn_idle_timeout_secs=20,
                                      fleet_probation_secs=60))
  assert any('mid-probation' in w for w in warned)
