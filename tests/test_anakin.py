"""Anakin mode (parallel/anakin.py): jittable env cores match the host
CI envs' semantics, the fused step preserves the actor's T+1 overlap
contract, the whole on-device loop learns — and (round 16) the
`--runtime=anakin` axis runs it as a production run: checkpoint
restore, health/SLO lifecycle artifacts, and the anakin-vs-fleet
return parity gate on cue_memory.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalable_agent_tpu.config import Config
from scalable_agent_tpu.parallel import anakin


def _anakin_config(**kw):
  base = dict(env_backend='bandit', batch_size=4, unroll_length=5,
              num_action_repeats=1, episode_length=4, height=24,
              width=32, torso='shallow', use_instruction=False,
              use_py_process=False, learning_rate=2e-3,
              entropy_cost=3e-3, discounting=0.0,
              total_environment_frames=10**6, seed=0)
  base.update(kw)
  return Config(**base)


def test_bandit_core_matches_host_semantics():
  """Rewards/episode shape/stats mirror envs/fake.ContextualBanditEnv
  (reward iff action == dominant channel; episode_length steps per
  context; flow-style stats: emitted info carries the running totals,
  the carried state resets at done)."""
  core = anakin.BanditCore(height=8, width=8, episode_length=3,
                           num_action_repeats=2)
  state, out0 = core.init(jax.random.PRNGKey(0), batch=4)
  assert bool(out0.done.all())  # priming output starts an episode
  frame0 = np.asarray(out0.observation[0])
  assert frame0.shape == (4, 8, 8, 3) and frame0.dtype == np.uint8
  np.testing.assert_array_equal(frame0.max(axis=(1, 2)).argmax(-1),
                                np.asarray(state.context))

  returns = np.zeros(4, np.float32)
  for t in range(1, 7):
    target = np.asarray(state.context)
    action = jnp.asarray((target + (t % 2)) % 3)  # alternate hit/miss
    prev_state = state
    state, out = core.step(state, action)
    expected_reward = (np.asarray(action) == target).astype(np.float32)
    np.testing.assert_array_equal(np.asarray(out.reward),
                                  expected_reward)
    assert bool(np.all(np.asarray(out.done) == (t % 3 == 0)))
    returns += expected_reward
    # Emitted info carries the running totals (frames = steps x repeat).
    np.testing.assert_array_equal(np.asarray(out.info.episode_return),
                                  returns)
    assert np.all(np.asarray(out.info.episode_step) ==
                  (t - 1) % 3 * 2 + 2)
    if t % 3 == 0:
      returns[:] = 0.0  # carried stats reset at done
      assert np.all(np.asarray(state.episode_return) == 0.0)
    else:
      # Context holds within an episode.
      np.testing.assert_array_equal(np.asarray(state.context),
                                    np.asarray(prev_state.context))


def test_cue_memory_core_semantics():
  core = anakin.CueMemoryCore(height=8, width=8)
  state, out0 = core.init(jax.random.PRNGKey(1), batch=3)
  # Cue visible on the first frame only.
  frame0 = np.asarray(out0.observation[0])
  assert frame0.max() == 255
  cue = np.asarray(state.context)

  # First action: fixed-action-0 bonus, independent of the cue.
  state, out1 = core.step(state, jnp.array([0, 1, 2]))
  np.testing.assert_array_equal(
      np.asarray(out1.reward), [2.0, 0.0, 0.0])
  assert not np.asarray(out1.done).any()
  assert np.asarray(out1.observation[0]).max() == 0  # blank frame

  # Second action: reward iff it matches the ORIGINAL cue; episode ends.
  action = jnp.asarray(cue)
  state, out2 = core.step(state, action)
  np.testing.assert_array_equal(np.asarray(out2.reward),
                                [1.0, 1.0, 1.0])
  assert np.asarray(out2.done).all()


def test_overlap_contract_between_fused_steps():
  """Timestep 0 of each unroll == last timestep of the previous one
  (the reference's load-bearing T+1 overlap — experiment.py ≈L285),
  and the batch is [T+1, B] time-major."""
  cfg = _anakin_config(batch_size=2, unroll_length=3)
  core = anakin.BanditCore(cfg.height, cfg.width, cfg.episode_length)
  from scalable_agent_tpu import driver
  agent = driver.build_agent(cfg, core.num_actions)
  step = anakin.make_anakin_step(agent, core, cfg, return_batch=True)
  carry = anakin.init_carry(agent, core, cfg, jax.random.PRNGKey(0))
  carry, m1 = step(carry)
  carry, m2 = step(carry)
  b1, b2 = jax.device_get((m1['batch'], m2['batch']))
  t1 = cfg.unroll_length + 1
  assert b1.env_outputs.reward.shape == (t1, cfg.batch_size)
  assert b1.agent_outputs.policy_logits.shape == (
      t1, cfg.batch_size, core.num_actions)
  for leaf1, leaf2 in zip(
      jax.tree_util.tree_leaves((b1.env_outputs, b1.agent_outputs)),
      jax.tree_util.tree_leaves((b2.env_outputs, b2.agent_outputs))):
    np.testing.assert_array_equal(leaf1[-1], leaf2[0])


def test_anakin_learns_bandit():
  """The fully fused on-device loop drives the bandit to near-optimal
  mean reward (random = 1/3, optimal = 1.0)."""
  carry, history, _ = anakin.run(_anakin_config(batch_size=8), 150)
  rewards = [float(h['mean_reward']) for h in history]
  assert all(np.isfinite(h['total_loss']) for h in history)
  assert np.mean(rewards[-10:]) > 0.8, rewards[-10:]
  assert int(carry.train_state.update_steps) == 150


def test_anakin_shards_over_the_mesh():
  """Anakin scale-out (PARALLELISM.md): env batch sharded over the
  8-device data axis, params replicated, same fused step — the
  gradient psum is inserted by jit from the placements."""
  from scalable_agent_tpu import driver
  from scalable_agent_tpu.parallel import mesh as mesh_lib

  assert len(jax.devices()) == 8
  mesh = mesh_lib.make_mesh()
  cfg = _anakin_config(batch_size=16, unroll_length=3)
  core = anakin.BanditCore(cfg.height, cfg.width, cfg.episode_length)
  agent = driver.build_agent(cfg, core.num_actions)
  step = anakin.make_anakin_step(agent, core, cfg)
  carry = anakin.init_carry(agent, core, cfg, jax.random.PRNGKey(0),
                            mesh=mesh)
  # Env state genuinely spans the mesh's data axis.
  assert len(carry.env_state.context.sharding.device_set) == 8
  for _ in range(3):
    carry, metrics = step(carry)
  assert np.isfinite(float(metrics['total_loss']))
  assert int(carry.train_state.update_steps) == 3
  # The carry stays sharded across fused steps (no silent gather).
  assert len(carry.env_state.context.sharding.device_set) == 8

  import pytest
  with pytest.raises(ValueError, match='divisible'):
    anakin.init_carry(agent, core, _anakin_config(batch_size=6),
                      jax.random.PRNGKey(0), mesh=mesh)


def test_anakin_train_artifacts_and_resume(tmp_path):
  """The operator-facing loop (experiment.py --mode=anakin) produces
  the standard run artifacts: config dump, JSONL summaries, a
  checkpoint that a second invocation resumes from, and
  total_environment_frames termination."""
  import glob
  import json
  cfg = _anakin_config(
      logdir=str(tmp_path), summary_secs=0, checkpoint_secs=0,
      total_environment_frames=10 * 4 * 5)  # exactly 10 steps (B=4,T=5)
  carry = anakin.train(cfg)
  assert int(carry.train_state.update_steps) == 10

  events = [json.loads(line) for line in
            open(str(tmp_path / 'summaries.jsonl'))]
  tags = {e['tag'] for e in events}
  assert {'total_loss', 'mean_reward',
          'env_frames_per_sec'} <= tags
  assert json.load(open(str(tmp_path / 'config.json')))[
      'env_backend'] == 'bandit'

  # Resume: frames target already met -> restores and stops at 10.
  carry2 = anakin.train(cfg)
  assert int(carry2.train_state.update_steps) == 10
  # And a raised target continues from the checkpoint, not from 0.
  from scalable_agent_tpu.config import apply_overrides
  carry3 = anakin.train(
      apply_overrides(cfg, total_environment_frames=12 * 4 * 5))
  assert int(carry3.train_state.update_steps) == 12


def test_anakin_train_restore_mismatch_does_not_overwrite(tmp_path):
  """A structure-mismatch on resume must raise (with the flag
  guidance), not tail-save a fresh incompatible state into the logdir."""
  import glob
  import pytest
  from scalable_agent_tpu.checkpoint import CheckpointStructureError
  from scalable_agent_tpu.config import apply_overrides
  cfg = _anakin_config(logdir=str(tmp_path), checkpoint_secs=0,
                       total_environment_frames=2 * 4 * 5)
  anakin.train(cfg)
  before = sorted(glob.glob(str(tmp_path / 'checkpoints' / '*')))
  with pytest.raises(CheckpointStructureError):
    anakin.train(apply_overrides(cfg, use_instruction=True))
  assert sorted(glob.glob(str(tmp_path / 'checkpoints' / '*'))) == before


def test_run_rejects_host_only_backends_and_zero_steps():
  import pytest
  with pytest.raises(ValueError, match='jittable'):
    anakin.run(_anakin_config(env_backend='dmlab'), 1)
  with pytest.raises(ValueError, match='num_steps'):
    anakin.run(_anakin_config(), 0)
  # A core that cannot honor the requested head width raises (the
  # host CueMemoryEnv refuses the same way) ...
  with pytest.raises(ValueError, match='num_actions'):
    anakin.run(_anakin_config(env_backend='cue_memory',
                              num_actions=5), 1)
  # ... while bandit accepts wider heads exactly like its host env
  # (the hybrid filler runs it under the MAIN task's action space).
  core = anakin.make_env_core(_anakin_config(), num_actions=7)
  assert core.num_actions == 7
  state, out = core.init(jax.random.PRNGKey(0), batch=4)
  # The rewarded channel stays 0..2 regardless of head width (the
  # host env's randint(num_actions) % 3 draw, mirrored).
  assert int(np.asarray(state.context).max()) <= 2
  assert np.asarray(out.observation[0]).shape == (4, 24, 32, 3)


# --- Round 16: the pure-JAX env family (envs/jittable.py). ---


def test_jittable_registry_matches_config_backends():
  """config.JITTABLE_BACKENDS is the literal mirror of ENV_CORES
  (config.py cannot import jax-importing modules) — and every core is
  also host-registered, the dual registration the runtime-axis parity
  gate rides on."""
  from scalable_agent_tpu.config import JITTABLE_BACKENDS
  from scalable_agent_tpu.envs import jittable
  assert set(JITTABLE_BACKENDS) == set(anakin.ENV_CORES)
  assert set(jittable.HOST_ENVS) == set(jittable.JITTABLE_CORES)


def test_gridworld_core_semantics():
  """Movement clamps at borders, the goal pays +1 and ends the
  episode, the step cap ends it unpaid, flow-style stats reset at
  done, and the observation renders agent/goal cells on their own
  channels."""
  from scalable_agent_tpu.envs.jittable import GridworldCore
  core = GridworldCore(height=16, width=16, episode_length=3,
                       num_action_repeats=2, grid_size=3)
  state, out0 = core.init(jax.random.PRNGKey(0), batch=4)
  assert bool(out0.done.all())  # priming output starts an episode
  frame0 = np.asarray(out0.observation[0])
  assert frame0.shape == (4, 16, 16, 3) and frame0.dtype == np.uint8
  assert (frame0[..., 0] == 255).any()  # agent plane rendered
  assert (frame0[..., 1] == 255).any()  # goal plane rendered
  np.testing.assert_array_equal(np.asarray(state.agent_yx), 0)

  # Moving up/left from (0, 0) clamps in place.
  state1, out1 = core.step(state, jnp.array([0, 2, 0, 2]))
  at_goal = np.all(np.asarray(state.goal_yx) == 0, axis=-1)
  np.testing.assert_array_equal(np.asarray(out1.reward),
                                at_goal.astype(np.float32))
  # Non-terminal envs keep the clamped position.
  still = ~np.asarray(out1.done)
  if still.any():
    np.testing.assert_array_equal(
        np.asarray(state1.agent_yx)[still], 0)
  # Frames count action repeats; emitted stats carry running totals.
  np.testing.assert_array_equal(np.asarray(out1.info.episode_step), 2)

  # Walk right to the goal deterministically: batch=1, goal pinned by
  # re-sampling until it lands on row 0 (seeded draw is deterministic).
  core1 = GridworldCore(height=8, width=8, episode_length=8,
                        grid_size=3)
  s, _ = core1.init(jax.random.PRNGKey(3), batch=1)
  gy, gx = (int(np.asarray(s.goal_yx)[0, 0]),
            int(np.asarray(s.goal_yx)[0, 1]))
  total = 0.0
  for _ in range(gy):
    s, out = core1.step(s, jnp.array([1]))  # down
    total += float(np.asarray(out.reward)[0])
  for _ in range(gx):
    s, out = core1.step(s, jnp.array([3]))  # right
    total += float(np.asarray(out.reward)[0])
  assert total == 1.0
  assert bool(np.asarray(out.done)[0])
  # Auto-reset: agent back at origin, stats cleared in the carry.
  np.testing.assert_array_equal(np.asarray(s.agent_yx), 0)
  assert float(np.asarray(s.episode_return)[0]) == 0.0


def test_gridworld_episode_cap_ends_unpaid():
  from scalable_agent_tpu.envs.jittable import GridworldCore
  core = GridworldCore(height=8, width=8, episode_length=2,
                       grid_size=4)
  s, _ = core.init(jax.random.PRNGKey(1), batch=2)
  # Bounce up against the border twice: no goal, cap fires.
  s, out = core.step(s, jnp.array([0, 0]))
  s, out = core.step(s, jnp.array([0, 0]))
  assert bool(np.asarray(out.done).all())
  np.testing.assert_array_equal(np.asarray(out.reward), 0.0)


def test_procgen_levels_deterministic_and_walls_block():
  """The procgen-style generator: the wall layout is a pure function
  of the level id (same id -> identical walls across separate core
  instances), start/goal corners are always open, and a wall vetoes
  the move (agent stays)."""
  from scalable_agent_tpu.envs.jittable import ProcgenCore
  core_a = ProcgenCore(height=10, width=10, grid_size=4,
                       num_levels=6, wall_density=0.9)
  core_b = ProcgenCore(height=10, width=10, grid_size=4,
                       num_levels=6, wall_density=0.9)
  ids = jnp.arange(6)
  walls_a = np.asarray(core_a._walls(ids))
  walls_b = np.asarray(core_b._walls(ids))
  np.testing.assert_array_equal(walls_a, walls_b)
  assert not walls_a[:, 0, 0].any()      # start open
  assert not walls_a[:, -1, -1].any()    # goal open
  # At density 0.9 SOME interior wall must exist over 6 levels.
  assert walls_a.any()

  # A blocked move keeps the agent in place: find a level whose (0,1)
  # or (1,0) neighbor is a wall and step into it.
  state, _ = core_a.init(jax.random.PRNGKey(0), batch=6)
  walls = np.asarray(core_a._walls(state.level_id))
  right_blocked = walls[:, 0, 1]
  s1, _ = core_a.step(state, jnp.full((6,), 3))  # all step right
  moved = np.asarray(s1.agent_yx)[:, 1] == 1
  stayed = np.asarray(s1.agent_yx)[:, 1] == 0
  # done (goal/cap) resets to origin too, but with 4x4 grids and one
  # step neither can fire — so blocked <-> stayed exactly.
  np.testing.assert_array_equal(moved, ~right_blocked)
  np.testing.assert_array_equal(stayed, right_blocked)


def test_jittable_host_envs_run_the_same_cores():
  """The fleet-runtime half of the dual registration: the host
  wrappers speak the envs/base protocol (scalar reward/done, uint8
  frame, auto-reset inside step) over the SAME core classes."""
  from scalable_agent_tpu.envs import jittable
  for name, env_cls in jittable.HOST_ENVS.items():
    env = env_cls(height=12, width=12, num_actions=4,
                  episode_length=3, seed=7, level_name=name)
    frame, instr = env.initial()
    assert frame.shape == (12, 12, 3) and frame.dtype == np.uint8
    assert instr.shape[0] > 0 and instr.dtype == np.int32
    done_seen = False
    for i in range(8):
      reward, done, (frame, instr) = env.step(i % 4)
      assert isinstance(reward, np.float32)
      assert frame.shape == (12, 12, 3)
      done_seen = done_seen or bool(done)
    assert done_seen  # the 3-step cap must have fired at least once
    env.close()


def test_factory_builds_jittable_backends():
  from scalable_agent_tpu.envs import factory
  for backend in ('gridworld', 'procgen'):
    cfg = Config(env_backend=backend, height=16, width=16,
                 episode_length=4)
    spec = factory.make_env_spec(cfg, backend, seed=3)
    assert spec.num_actions == 4
    env, process = factory.build_environment(spec,
                                             use_py_process=False)
    assert process is None
    frame, _ = env.initial()
    assert frame.shape == (16, 16, 3)
    reward, done, _ = env.step(1)
    assert reward in (np.float32(0.0), np.float32(1.0))
    env.close()


@pytest.mark.slow
def test_anakin_learns_gridworld():
  """The fused loop learns the gridworld family too: mean reward over
  the last windows beats the first windows decisively (sparse +1 at
  the goal; random walk on a 3x3 grid with an 8-step cap collects
  some reward, a learned policy much more)."""
  cfg = _anakin_config(env_backend='gridworld', batch_size=16,
                       unroll_length=8, episode_length=8,
                       discounting=0.9, entropy_cost=0.01,
                       learning_rate=3e-3)
  _, history, _ = anakin.run(cfg, 250)
  rewards = [float(h['mean_reward']) for h in history]
  early = float(np.mean(rewards[:25]))
  late = float(np.mean(rewards[-25:]))
  assert late > early + 0.05, (early, late)


# --- Round 16: the --runtime=anakin production loop
# (driver.train_anakin). ---


def _runtime_config(tmp_path, **kw):
  base = dict(logdir=str(tmp_path), runtime='anakin',
              env_backend='cue_memory', batch_size=4, unroll_length=5,
              num_action_repeats=1, height=24, width=32,
              torso='shallow', use_instruction=False,
              use_py_process=False, learning_rate=2e-3,
              summary_secs=0, checkpoint_secs=0,
              total_environment_frames=8 * 4 * 5, seed=3)
  base.update(kw)
  return Config(**base)


@pytest.mark.slow
def test_runtime_anakin_full_lifecycle(tmp_path):
  """--runtime=anakin through driver.train: the fused loop runs as a
  PRODUCTION run — checkpoint restore, green SLO verdict, summaries +
  incidents JSONL, registry gauges unwound at exit.

  Slow-marked (the heaviest anakin drill, ~20 s): the ci.sh anakin
  lane runs the whole file unfiltered, so CI still exercises it."""
  from scalable_agent_tpu import driver, slo, telemetry
  cfg = _runtime_config(tmp_path)
  run = driver.train(cfg)  # dispatches on config.runtime
  assert run.frames == 8 * 4 * 5
  assert run.fleet is None and run.prefetcher is None

  # Lifecycle artifacts: the same contract the fleet runtime ships.
  verdict = slo.read_verdict(str(tmp_path))
  assert verdict is not None and verdict['pass'], verdict
  assert verdict['objectives']  # judged by the real default set
  assert os.path.exists(str(tmp_path / 'incidents.jsonl'))
  events = [json.loads(line)
            for line in open(str(tmp_path / 'summaries.jsonl'))]
  tags = {e['tag'] for e in events}
  assert {'total_loss', 'mean_reward', 'env_frames_per_sec',
          'learning_rate'} <= tags
  assert json.load(open(str(tmp_path / 'config.json')))[
      'runtime'] == 'anakin'
  # The loop gauges were unregistered at exit (a finished run must
  # not stay registry-pinned).
  snap = telemetry.registry().snapshot()
  assert 'driver/env_plane_utilization' not in snap

  # Restore: target already met -> resumes and stops immediately; a
  # raised target continues FROM the checkpoint.
  run2 = driver.train(cfg)
  assert run2.frames == 8 * 4 * 5
  from scalable_agent_tpu.config import apply_overrides
  run3 = driver.train(apply_overrides(
      cfg, total_environment_frames=10 * 4 * 5))
  assert run3.frames == 10 * 4 * 5


def test_runtime_anakin_rejects_bad_configs(tmp_path):
  from scalable_agent_tpu import driver
  with pytest.raises(ValueError, match='jittable'):
    driver.train(_runtime_config(tmp_path, env_backend='dmlab'))
  with pytest.raises(ValueError, match='data-parallel'):
    driver.train(_runtime_config(tmp_path, model_parallelism=2))
  with pytest.raises(ValueError, match='fleet_factory'):
    driver.train(_runtime_config(tmp_path), fleet_factory=object())
  with pytest.raises(ValueError, match='runtime'):
    driver.train(_runtime_config(tmp_path, runtime='nope'))


@pytest.mark.slow
def test_runtime_parity_cue_memory(tmp_path):
  """The runtime-axis parity gate: the SAME cue_memory task trained
  through BOTH runtimes reaches comparable final returns — both must
  clear the 2.6 memory bar (memory policy 3.0, best memoryless 2.33,
  relay 5/3; see CueMemoryEnv), so both runtimes demonstrably train
  the recurrent carry, not just the reactive head."""
  from scalable_agent_tpu import driver

  # Anakin side: fused loop; mean_reward is per STEP (2-step episodes
  # -> per-episode return = 2 * mean step reward).
  anakin_cfg = Config(
      logdir=str(tmp_path / 'anakin'), runtime='anakin',
      env_backend='cue_memory', batch_size=8, unroll_length=16,
      num_action_repeats=1, height=24, width=32, torso='shallow',
      use_instruction=False, use_py_process=False,
      learning_rate=3e-3, entropy_cost=0.01, discounting=0.9,
      summary_secs=0, checkpoint_secs=10**6,
      total_environment_frames=10**9, seed=5)
  run = driver.train(anakin_cfg, max_steps=220)
  events = [json.loads(line) for line in
            open(str(tmp_path / 'anakin' / 'summaries.jsonl'))]
  step_rewards = [e['value'] for e in events
                  if e['tag'] == 'mean_reward']
  anakin_return = 2.0 * float(np.mean(step_rewards[-20:]))
  assert anakin_return > 2.6, anakin_return

  # Fleet side: the full pipeline (actors -> inference -> buffer ->
  # learner) on the same task/hyperparameters.
  fleet_cfg = Config(
      logdir=str(tmp_path / 'fleet'), runtime='fleet',
      env_backend='cue_memory', level_name='cue_memory',
      num_actors=4, batch_size=4,
      unroll_length=16, num_action_repeats=1, height=24, width=32,
      torso='shallow', use_instruction=False, use_py_process=False,
      learning_rate=3e-3, entropy_cost=0.01, discounting=0.9,
      inference_timeout_ms=5, summary_secs=0, checkpoint_secs=10**6,
      total_environment_frames=10**9, seed=5)
  driver.train(fleet_cfg, max_steps=200, stall_timeout_secs=120)
  events = [json.loads(line) for line in
            open(str(tmp_path / 'fleet' / 'summaries.jsonl'))]
  returns = [e['value'] for e in events
             if e['tag'] == 'cue_memory/episode_return']
  assert len(returns) > 30, len(returns)
  fleet_return = float(np.mean(returns[-30:]))
  assert fleet_return > 2.6, fleet_return
  # Comparable: both runtimes land in the memory-policy band
  # [2.6, 3.0], so their gap is bounded by construction.
  assert abs(fleet_return - anakin_return) < 0.4, (
      fleet_return, anakin_return)
