"""Anakin mode (parallel/anakin.py): jittable env cores match the host
CI envs' semantics, the fused step preserves the actor's T+1 overlap
contract, and the whole on-device loop learns.
"""

import jax
import jax.numpy as jnp
import numpy as np

from scalable_agent_tpu.config import Config
from scalable_agent_tpu.parallel import anakin


def _anakin_config(**kw):
  base = dict(env_backend='bandit', batch_size=4, unroll_length=5,
              num_action_repeats=1, episode_length=4, height=24,
              width=32, torso='shallow', use_instruction=False,
              use_py_process=False, learning_rate=2e-3,
              entropy_cost=3e-3, discounting=0.0,
              total_environment_frames=10**6, seed=0)
  base.update(kw)
  return Config(**base)


def test_bandit_core_matches_host_semantics():
  """Rewards/episode shape/stats mirror envs/fake.ContextualBanditEnv
  (reward iff action == dominant channel; episode_length steps per
  context; flow-style stats: emitted info carries the running totals,
  the carried state resets at done)."""
  core = anakin.BanditCore(height=8, width=8, episode_length=3,
                           num_action_repeats=2)
  state, out0 = core.init(jax.random.PRNGKey(0), batch=4)
  assert bool(out0.done.all())  # priming output starts an episode
  frame0 = np.asarray(out0.observation[0])
  assert frame0.shape == (4, 8, 8, 3) and frame0.dtype == np.uint8
  np.testing.assert_array_equal(frame0.max(axis=(1, 2)).argmax(-1),
                                np.asarray(state.context))

  returns = np.zeros(4, np.float32)
  for t in range(1, 7):
    target = np.asarray(state.context)
    action = jnp.asarray((target + (t % 2)) % 3)  # alternate hit/miss
    prev_state = state
    state, out = core.step(state, action)
    expected_reward = (np.asarray(action) == target).astype(np.float32)
    np.testing.assert_array_equal(np.asarray(out.reward),
                                  expected_reward)
    assert bool(np.all(np.asarray(out.done) == (t % 3 == 0)))
    returns += expected_reward
    # Emitted info carries the running totals (frames = steps x repeat).
    np.testing.assert_array_equal(np.asarray(out.info.episode_return),
                                  returns)
    assert np.all(np.asarray(out.info.episode_step) ==
                  (t - 1) % 3 * 2 + 2)
    if t % 3 == 0:
      returns[:] = 0.0  # carried stats reset at done
      assert np.all(np.asarray(state.episode_return) == 0.0)
    else:
      # Context holds within an episode.
      np.testing.assert_array_equal(np.asarray(state.context),
                                    np.asarray(prev_state.context))


def test_cue_memory_core_semantics():
  core = anakin.CueMemoryCore(height=8, width=8)
  state, out0 = core.init(jax.random.PRNGKey(1), batch=3)
  # Cue visible on the first frame only.
  frame0 = np.asarray(out0.observation[0])
  assert frame0.max() == 255
  cue = np.asarray(state.context)

  # First action: fixed-action-0 bonus, independent of the cue.
  state, out1 = core.step(state, jnp.array([0, 1, 2]))
  np.testing.assert_array_equal(
      np.asarray(out1.reward), [2.0, 0.0, 0.0])
  assert not np.asarray(out1.done).any()
  assert np.asarray(out1.observation[0]).max() == 0  # blank frame

  # Second action: reward iff it matches the ORIGINAL cue; episode ends.
  action = jnp.asarray(cue)
  state, out2 = core.step(state, action)
  np.testing.assert_array_equal(np.asarray(out2.reward),
                                [1.0, 1.0, 1.0])
  assert np.asarray(out2.done).all()


def test_overlap_contract_between_fused_steps():
  """Timestep 0 of each unroll == last timestep of the previous one
  (the reference's load-bearing T+1 overlap — experiment.py ≈L285),
  and the batch is [T+1, B] time-major."""
  cfg = _anakin_config(batch_size=2, unroll_length=3)
  core = anakin.BanditCore(cfg.height, cfg.width, cfg.episode_length)
  from scalable_agent_tpu import driver
  agent = driver.build_agent(cfg, core.num_actions)
  step = anakin.make_anakin_step(agent, core, cfg, return_batch=True)
  carry = anakin.init_carry(agent, core, cfg, jax.random.PRNGKey(0))
  carry, m1 = step(carry)
  carry, m2 = step(carry)
  b1, b2 = jax.device_get((m1['batch'], m2['batch']))
  t1 = cfg.unroll_length + 1
  assert b1.env_outputs.reward.shape == (t1, cfg.batch_size)
  assert b1.agent_outputs.policy_logits.shape == (
      t1, cfg.batch_size, core.num_actions)
  for leaf1, leaf2 in zip(
      jax.tree_util.tree_leaves((b1.env_outputs, b1.agent_outputs)),
      jax.tree_util.tree_leaves((b2.env_outputs, b2.agent_outputs))):
    np.testing.assert_array_equal(leaf1[-1], leaf2[0])


def test_anakin_learns_bandit():
  """The fully fused on-device loop drives the bandit to near-optimal
  mean reward (random = 1/3, optimal = 1.0)."""
  carry, history, _ = anakin.run(_anakin_config(batch_size=8), 150)
  rewards = [float(h['mean_reward']) for h in history]
  assert all(np.isfinite(h['total_loss']) for h in history)
  assert np.mean(rewards[-10:]) > 0.8, rewards[-10:]
  assert int(carry.train_state.update_steps) == 150


def test_anakin_shards_over_the_mesh():
  """Anakin scale-out (PARALLELISM.md): env batch sharded over the
  8-device data axis, params replicated, same fused step — the
  gradient psum is inserted by jit from the placements."""
  from scalable_agent_tpu import driver
  from scalable_agent_tpu.parallel import mesh as mesh_lib

  assert len(jax.devices()) == 8
  mesh = mesh_lib.make_mesh()
  cfg = _anakin_config(batch_size=16, unroll_length=3)
  core = anakin.BanditCore(cfg.height, cfg.width, cfg.episode_length)
  agent = driver.build_agent(cfg, core.num_actions)
  step = anakin.make_anakin_step(agent, core, cfg)
  carry = anakin.init_carry(agent, core, cfg, jax.random.PRNGKey(0),
                            mesh=mesh)
  # Env state genuinely spans the mesh's data axis.
  assert len(carry.env_state.context.sharding.device_set) == 8
  for _ in range(3):
    carry, metrics = step(carry)
  assert np.isfinite(float(metrics['total_loss']))
  assert int(carry.train_state.update_steps) == 3
  # The carry stays sharded across fused steps (no silent gather).
  assert len(carry.env_state.context.sharding.device_set) == 8

  import pytest
  with pytest.raises(ValueError, match='divisible'):
    anakin.init_carry(agent, core, _anakin_config(batch_size=6),
                      jax.random.PRNGKey(0), mesh=mesh)


def test_anakin_train_artifacts_and_resume(tmp_path):
  """The operator-facing loop (experiment.py --mode=anakin) produces
  the standard run artifacts: config dump, JSONL summaries, a
  checkpoint that a second invocation resumes from, and
  total_environment_frames termination."""
  import glob
  import json
  cfg = _anakin_config(
      logdir=str(tmp_path), summary_secs=0, checkpoint_secs=0,
      total_environment_frames=10 * 4 * 5)  # exactly 10 steps (B=4,T=5)
  carry = anakin.train(cfg)
  assert int(carry.train_state.update_steps) == 10

  events = [json.loads(line) for line in
            open(str(tmp_path / 'summaries.jsonl'))]
  tags = {e['tag'] for e in events}
  assert {'total_loss', 'mean_reward',
          'env_frames_per_sec'} <= tags
  assert json.load(open(str(tmp_path / 'config.json')))[
      'env_backend'] == 'bandit'

  # Resume: frames target already met -> restores and stops at 10.
  carry2 = anakin.train(cfg)
  assert int(carry2.train_state.update_steps) == 10
  # And a raised target continues from the checkpoint, not from 0.
  from scalable_agent_tpu.config import apply_overrides
  carry3 = anakin.train(
      apply_overrides(cfg, total_environment_frames=12 * 4 * 5))
  assert int(carry3.train_state.update_steps) == 12


def test_anakin_train_restore_mismatch_does_not_overwrite(tmp_path):
  """A structure-mismatch on resume must raise (with the flag
  guidance), not tail-save a fresh incompatible state into the logdir."""
  import glob
  import pytest
  from scalable_agent_tpu.checkpoint import CheckpointStructureError
  from scalable_agent_tpu.config import apply_overrides
  cfg = _anakin_config(logdir=str(tmp_path), checkpoint_secs=0,
                       total_environment_frames=2 * 4 * 5)
  anakin.train(cfg)
  before = sorted(glob.glob(str(tmp_path / 'checkpoints' / '*')))
  with pytest.raises(CheckpointStructureError):
    anakin.train(apply_overrides(cfg, use_instruction=True))
  assert sorted(glob.glob(str(tmp_path / 'checkpoints' / '*'))) == before


def test_run_rejects_host_only_backends_and_zero_steps():
  import pytest
  with pytest.raises(ValueError, match='jittable'):
    anakin.run(_anakin_config(env_backend='dmlab'), 1)
  with pytest.raises(ValueError, match='num_steps'):
    anakin.run(_anakin_config(), 0)
  with pytest.raises(ValueError, match='num_actions'):
    anakin.run(_anakin_config(num_actions=5), 1)
