"""Hybrid filler fleets (round 16, anakin.HybridFiller + the driver's
ready-probe yield loop): idle learner slices run bounded Anakin
self-play, fresh/filler frame accounting stays split, and a staged
batch is never delayed by more than one filler step.
"""

import json
import os
import threading
import time

import jax
import numpy as np
import pytest

from scalable_agent_tpu.config import Config, validate_runtime
from scalable_agent_tpu.models.instruction import MAX_INSTRUCTION_LEN
from scalable_agent_tpu.parallel import anakin
from scalable_agent_tpu.runtime import ring_buffer
from scalable_agent_tpu.testing import make_example_unroll


def _filler_config(tmp_path, **kw):
  base = dict(logdir=str(tmp_path), env_backend='bandit',
              num_actors=0, batch_size=2, unroll_length=5,
              num_action_repeats=1, episode_length=4, height=24,
              width=32, torso='shallow', use_py_process=False,
              use_instruction=False, anakin_filler=True,
              filler_batch_size=2, filler_unroll_length=5,
              total_environment_frames=10**9,
              checkpoint_secs=10**6, summary_secs=0, seed=11)
  base.update(kw)
  return Config(**base)


class _ThrottledFleet:
  """Synthetic producer at a fixed trickle: the env-bound regime the
  filler exists for (BENCH r9: ~150 fps feed vs ~300k fps learner)."""

  def __init__(self, buffer, unroll, period=0.35):
    self._buffer, self._unroll, self._period = buffer, unroll, period
    self._stop = threading.Event()
    self._thread = threading.Thread(target=self._produce, daemon=True)

  def _produce(self):
    while not self._stop.is_set():
      time.sleep(self._period)
      try:
        self._buffer.put(self._unroll, timeout=0.2)
      except (TimeoutError, ring_buffer.Closed):
        continue

  def start(self):
    self._thread.start()

  def errors(self):
    return []

  def check_health(self, stall_timeout_secs=None):
    pass

  def stats(self, healthy_horizon_secs=60.0):
    return {'alive': 1, 'respawns': 0, 'healthy': 1,
            'healthy_fraction': 1.0, 'unrolls': 0}

  def stop(self, timeout=None):
    self._stop.set()


def _unroll(t1=6):
  return make_example_unroll(t1, 24, 32, 3, MAX_INSTRUCTION_LEN)


def _summary_tags(logdir):
  tags = {}
  for line in open(os.path.join(logdir, 'summaries.jsonl')):
    e = json.loads(line)
    if 'value' in e:
      tags[e['tag']] = e['value']
  return tags


# --- Unit layer. ---


def test_prefetcher_ready_probe():
  """ready() is a pure probe: False while nothing is staged, True
  once a batch is, True again after close (get() then raises — the
  caller's signal to stop filling), and it never consumes."""
  buffer = ring_buffer.TrajectoryBuffer(4)
  pf = ring_buffer.BatchPrefetcher(buffer, 2)
  try:
    assert not pf.ready()
    buffer.put(_unroll())
    buffer.put(_unroll())
    deadline = time.monotonic() + 5
    while not pf.ready() and time.monotonic() < deadline:
      time.sleep(0.01)
    assert pf.ready()
    assert pf.ready()  # probing twice consumed nothing
    pf.get(timeout=1)  # the staged batch is still there to dequeue
  finally:
    pf.close()
  assert pf.ready()
  with pytest.raises((ring_buffer.Closed, TimeoutError)):
    pf.get(timeout=0.1)


def test_validate_runtime_knob_group():
  ok = Config(anakin_filler=True, env_backend='bandit')
  warnings = validate_runtime(ok)
  assert any('vtrace' in w for w in warnings)  # IMPACT cross-link
  assert not any('vtrace' in w for w in validate_runtime(
      Config(anakin_filler=True, surrogate='impact')))
  # Filler + SLO engine off: the masking cross-link.
  assert any('env_plane' in w for w in validate_runtime(
      Config(anakin_filler=True, surrogate='impact',
             slo_engine=False)))
  with pytest.raises(ValueError, match='runtime'):
    validate_runtime(Config(runtime='bogus'))
  with pytest.raises(ValueError, match='jittable'):
    validate_runtime(Config(runtime='anakin', env_backend='dmlab'))
  with pytest.raises(ValueError, match='jittable'):
    validate_runtime(Config(anakin_filler=True,
                            filler_backend='dmlab'))
  # anakin runtime: the filler knob is a no-op worth a warning.
  assert any('no-op' in w for w in validate_runtime(
      Config(runtime='anakin', env_backend='bandit',
             anakin_filler=True)))
  # Auto backend: jittable runs self-play their OWN task; host-only
  # backends fall back to bandit.
  assert Config(env_backend='gridworld').resolved_filler_backend == \
      'gridworld'
  assert Config(env_backend='dmlab').resolved_filler_backend == \
      'bandit'


def test_hybrid_filler_freezes_the_fleet_clocks():
  """The clock contract (the PR 7 serve-time attribution, extended):
  a filler update mutates params but never advances update_steps — so
  the frame budget, LR schedule, and checkpoint numbering all stay on
  the fleet's fresh-frame count. Each fill_one is synchronous (the
  one-filler-step delay bound) and feeds the separate filler ledger."""
  from scalable_agent_tpu import driver, learner, telemetry
  cfg = _filler_config('/tmp/unused', env_backend='dmlab')
  agent = driver.build_agent(cfg, num_actions=9)
  from scalable_agent_tpu.models import init_params
  obs = {'frame': (24, 32, 3), 'instr_len': MAX_INSTRUCTION_LEN}
  params = init_params(agent, jax.random.PRNGKey(0), obs)
  state = learner.make_train_state(params, cfg)

  filler = anakin.HybridFiller(agent, cfg, num_actions=9)
  assert filler.backend == 'bandit'  # dmlab auto-falls back
  before = jax.device_get(state.params)
  for i in range(3):
    state = filler.fill_one(state)
    assert int(jax.device_get(state.update_steps)) == 0  # frozen
  after = jax.device_get(state.params)
  changed = any(
      not np.array_equal(a, b)
      for a, b in zip(jax.tree_util.tree_leaves(before),
                      jax.tree_util.tree_leaves(after)))
  assert changed  # the updates were real
  assert filler.updates == 3
  assert filler.frames == 3 * filler.frames_per_update
  assert filler.stats()['skipped'] == 0
  # The registry counter rode along (the name-lint contract) ...
  assert telemetry.registry().snapshot()[
      'driver/filler_updates'] >= 3
  # ... and close() unwinds it (the teardown contract: a later run in
  # the same process must not snapshot this run's tally).
  filler.close()
  assert 'driver/filler_updates' not in telemetry.registry().snapshot()


def test_filler_width_mismatch_fails_at_spinup(tmp_path):
  """An explicitly requested filler that cannot honor the main task's
  action-space width must FAIL the run at spin-up (like every
  validate_* error) — never be silently disabled behind a 'topology'
  warning. gridworld needs >= 4 actions; bandit is a 3-action task."""
  from scalable_agent_tpu import driver
  cfg = _filler_config(tmp_path, filler_backend='gridworld')
  with pytest.raises(ValueError, match='num_actions'):
    driver.train(cfg, max_steps=1, stall_timeout_secs=30)


def test_hybrid_filler_rejects_model_axis_mesh():
  from scalable_agent_tpu import driver
  from scalable_agent_tpu.parallel import mesh as mesh_lib
  cfg = _filler_config('/tmp/unused')
  agent = driver.build_agent(cfg, num_actions=3)
  mesh = mesh_lib.make_mesh(model_parallelism=2)
  with pytest.raises(ValueError, match='data-parallel'):
    anakin.HybridFiller(agent, cfg, num_actions=3, mesh=mesh)


# --- Driver integration. ---


def test_filler_yield_and_frame_accounting(tmp_path):
  """Under an env-throttled feed: every staged batch still trains
  (max_steps reached — the filler never starves the real stream), the
  fresh-frame budget matches the no-filler arithmetic exactly, filler
  work lands on its own summary curves, and learner-plane utilization
  is lifted ~1.0 by construction."""
  from scalable_agent_tpu import driver
  unroll = _unroll()
  cfg = _filler_config(tmp_path)

  def fleet_factory(config, agent, policy, buffer, levels):
    return _ThrottledFleet(buffer, unroll)

  run = driver.train(cfg, max_steps=4, stall_timeout_secs=60,
                     fleet_factory=fleet_factory)
  # Fresh-frame clock: 4 real batches x B=2 x T=5 x repeat=1 — the
  # filler added NOTHING here despite running throughout the stalls.
  assert run.frames == 4 * 2 * 5
  tags = _summary_tags(str(tmp_path))
  assert tags['filler_updates'] >= 1
  assert tags['filler_frames'] == tags['filler_updates'] * 2 * 5
  assert tags['filler_skipped_updates'] == 0
  assert tags['frames_fresh'] <= 4 * 2 * 5
  assert tags['learner_plane_utilization'] > 0.9
  # The run unregistered its filler counter at teardown.
  from scalable_agent_tpu import telemetry
  assert ('driver/filler_updates'
          not in telemetry.registry().snapshot())
  # env_plane_utilization stays the honest env-side signal (the
  # throttled producer is mostly idle-by-choice here, so it reads
  # high; the point is the filler did not overwrite it with 1.0-by-
  # construction semantics — it keeps its own formula).
  assert 'env_plane_utilization' in tags


def test_filler_off_parity(tmp_path):
  """Filler OFF under the same throttled feed: identical fresh-frame
  accounting (the budget/LR/fps clocks are invariant to the knob) and
  no filler curves in the summaries."""
  from scalable_agent_tpu import driver
  unroll = _unroll()
  cfg = _filler_config(tmp_path, anakin_filler=False)

  def fleet_factory(config, agent, policy, buffer, levels):
    return _ThrottledFleet(buffer, unroll)

  run = driver.train(cfg, max_steps=4, stall_timeout_secs=60,
                     fleet_factory=fleet_factory)
  assert run.frames == 4 * 2 * 5  # same fresh clock as filler ON
  tags = _summary_tags(str(tmp_path))
  assert 'filler_updates' not in tags
